#!/bin/bash
pkill -9 -f "python _[p]robe" 2>/dev/null; sleep 1; cd /root/repo; nohup python _probe.py > _probe.out 2>&1 &
echo launched
