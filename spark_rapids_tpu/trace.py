"""Pipeline-wide span tracer with Chrome-trace export (Dapper-style).

The reference explains *where a query's time went* with NVTX ranges fed
into Nsight plus the Qualification/Profiler tools; after whole-stage
fusion, mesh-parallel scan, and the async in-flight dispatch window the
hot path here is concurrent in three dimensions (reader pool threads,
``stageFusion.maxInFlight`` dispatches, per-chip mesh execution) and
wall-clock counters alone cannot attribute time.  This module is the
missing layer: a low-overhead, thread-safe span stream

    (query_id, batch_id, chip, thread, kind, t0, t1, attrs)

recorded at the engine's existing choke points and exported as
Chrome-trace-event JSON — one file per query under
``spark.rapids.sql.trace.dir`` — that loads directly in Perfetto /
chrome://tracing.  ``tools.py trace <file>`` analyzes the same stream
offline (critical path, exclusive self-time, per-chip occupancy).

Integration contract (docs/observability.md):

- ``MetricRegistry.timed``/``timed_wall`` mirror every metric timer
  into a span with the SAME interval, so the event log, the profiler,
  and the trace agree on one set of numbers by construction.
- Sites without a metric timer (fused/agg dispatch, semaphore waits,
  spills, JIT compiles) measure ONCE and feed both channels.
- Retry/backoff/split/chip-failure events are instant markers; the
  retry recovery block (spill + backoff) is a nested ``retryBlock``
  span so the offline analyzer's *exclusive* self-time report undoes
  the documented retryBlockTime-inside-opTime double count.

Overhead discipline: when no trace is active (``trace.enabled`` off,
or the query was not sampled per ``trace.sampleRate``) every hook is a
single module-global ``None`` check; span recording itself is a tuple
append under the GIL (no lock on the hot path).
"""

from __future__ import annotations

import contextlib
import json
import os
import random
import threading
import time
from typing import Any, Dict, Iterator, List, Optional, Tuple

from spark_rapids_tpu.conf import conf

TRACE_ENABLED = conf("spark.rapids.sql.trace.enabled").doc(
    "Record per-query span traces (reader IO/decode, host pack, upload, "
    "per-chip device dispatch, exchange, JIT compiles, semaphore waits, "
    "spills, retries) and write one Chrome-trace JSON file per query "
    "under spark.rapids.sql.trace.dir. Open the files in Perfetto "
    "(https://ui.perfetto.dev) or analyze offline with `python -m "
    "spark_rapids_tpu.tools trace <file>` (docs/observability.md)."
    ).boolean(False)

TRACE_DIR = conf("spark.rapids.sql.trace.dir").doc(
    "Directory for per-query Chrome-trace files "
    "(trace-<pid>-q<n>.json).").string("/tmp/srt_traces")

TRACE_SAMPLE_RATE = conf("spark.rapids.sql.trace.sampleRate").doc(
    "Fraction of queries to trace (1.0 = every query). Sampling is "
    "deterministic for a fixed spark.rapids.sql.trace.sampleSeed: the "
    "Nth traced-candidate query of the process is sampled iff the Nth "
    "draw of the seeded stream is below the rate — production use "
    "traces a stable subset at bounded overhead.").double(1.0)

TRACE_SAMPLE_SEED = conf("spark.rapids.sql.trace.sampleSeed").doc(
    "Seed of the deterministic query-sampling stream used by "
    "spark.rapids.sql.trace.sampleRate.").integer(0)

TRACE_MODE = conf("spark.rapids.sql.trace.mode").doc(
    "Trace sink: 'file' writes one Chrome-trace JSON per sampled query "
    "(the per-query exporter); 'ring' is the FLIGHT RECORDER — an "
    "always-on, fixed-size, lock-free per-thread ring buffer that "
    "survives across queries with bounded memory (the last "
    "spark.rapids.sql.trace.ringSpans records per thread) and dumps on "
    "demand — slow-query triggers (spark.rapids.sql.telemetry.*) or "
    "telemetry.dump_ring() — as the SAME Chrome-trace JSON, so `tools "
    "trace`/`tools hotspots` work unchanged on dumps. Query server "
    "sessions default to 'ring' (docs/observability.md 'Live "
    "telemetry').").string("file")

TRACE_RING_SPANS = conf("spark.rapids.sql.trace.ringSpans").doc(
    "Flight-recorder capacity in trace.mode=ring: spans (and instants "
    "/ counter samples) retained PER THREAD before the oldest are "
    "overwritten. Bounds recorder memory on a long-lived server; a "
    "dump reconstructs the most recent window of work."
    ).integer(4096)


# ---------------------------------------------------------------------------
# Span catalog (docs/observability.md; the tpu-lint `span-kind` rule
# checks every literal span/instant kind recorded in the package
# against these tables, so a dump's vocabulary can never drift from
# the documentation). Metric-mirror spans are the dynamic family
# `<Exec>.<metric>` — every member resolves via metrics.describe_metric
# and is covered by the `metric-key` rule instead.
# ---------------------------------------------------------------------------

SPAN_CATALOG: Dict[str, str] = {
    "scanPrefetch": "scan producer thread reading+packing one staged "
                    "batch (mirrors scanPrefetchTime)",
    "uploadAhead": "async raw-chunk device_put issued ahead of the "
                   "consuming stage (docs/scan.md)",
    "finishUpload": "host->device upload completion per staging mode "
                    "and chip",
    "TpuFusedStageExec.dispatch": "one fused-stage device program "
                                  "dispatch (chip, batch seq, compile "
                                  "flag)",
    "TpuHashAggregateExec.dispatch": "one aggregation device program "
                                     "dispatch (mode, kernel= attr)",
    "kernelDispatch": "one Pallas kernel dispatch (kernel= names it; "
                      "docs/kernels.md)",
    "exchangeMaterialize": "exchange input drain + partition "
                           "materialization",
    "meshStack": "per-device shard assembly into the globally-sharded "
                 "stack (ICI exchange)",
    "meshSizeExchange": "all-to-all partition-size exchange over the "
                        "mesh",
    "meshExchange": "HBM-resident all-to-all data exchange over the "
                    "mesh",
    "compile": "JIT build+compile on a cache miss (cache= names the "
               "LRU)",
    "semaphoreWait": "wall blocked on the device semaphore",
    "serveQueueWait": "admission-queue wait of a served query "
                      "(docs/serving.md)",
    "spillToHost": "device->host store demotion",
    "spillToDisk": "host->disk store demotion",
    "promoteFromDisk": "disk->host store promotion",
    "promoteToDevice": "host->device store promotion",
    "retryBlock": "spill+backoff recovery inside an OOM retry (the "
                  "retryBlockTime interval)",
    "aqeReplan": "an adaptive runtime replan over measured exchange "
                 "stats (action= broadcastDemotion/skewSplit; "
                 "docs/adaptive.md)",
    "resultCacheHit": "a query served verbatim from the result cache "
                      "— zero device work, zero queue wait, zero "
                      "admission slot (docs/caching.md)",
    "cacheEntryDrop": "the device pool dropped a cache-tier entry "
                      "under pressure instead of spilling a live "
                      "query's batch (docs/caching.md)",
}

INSTANT_CATALOG: Dict[str, str] = {
    "retryOOM": "an OOM retry re-attempted the operation",
    "splitRetry": "an input batch split in half after OOM exhaustion",
    "ioRetry": "a transient reader IO error was retried",
    "chipFailure": "a mesh chip was demoted after persistent failure",
    "compileCacheContention": "a thread blocked on another thread's "
                              "in-progress compile of the same key",
    "queryEnd": "a query finished while the ring recorder was active "
                "(wallSeconds/rows/error attrs)",
    "telemetryTrigger": "a telemetry trigger fired (trigger= names it; "
                        "docs/observability.md 'Live telemetry')",
    "queryCancelled": "a query's CancelToken was cancelled (reason= "
                      "cancel/deadline/disconnect/watchdog/shutdown/"
                      "injected; docs/serving.md 'Query lifecycle')",
    "oocJoinPlan": "the budget oracle partitioned a hash join into "
                   "spill-backed buckets (modulus=/depth=; depth > 0 "
                   "is a recursive escalation — docs/out_of_core.md)",
    "oocAggPlan": "the budget oracle bucketed an aggregation by "
                  "grouping-key hash (modulus=/depth=; "
                  "docs/out_of_core.md)",
}


# ---------------------------------------------------------------------------
# Active-trace state (process-wide, like the DeviceStore / FaultInjector)
# ---------------------------------------------------------------------------

class QueryTrace:
    """Span sink for one traced query. ``add``/``mark`` are called from
    task/pool threads concurrently; CPython ``list.append`` is atomic
    under the GIL, so the hot path takes no lock."""

    __slots__ = ("query_id", "t0", "wall_t0", "spans", "instants",
                 "counters", "_thread_names", "tenant")

    def __init__(self, query_id: int, tenant: Optional[str] = None):
        self.query_id = query_id
        # serving tenancy: the tenant of the session that OPENED the
        # trace (concurrent queries from other sessions fold their
        # spans into this file — the documented process-timeline
        # limitation — but the root attribution names its owner)
        self.tenant = tenant
        self.t0 = time.perf_counter_ns()
        self.wall_t0 = time.time()
        # span record: (kind, t0_ns, t1_ns, thread_ident, batch, chip,
        #               attrs-or-None)
        self.spans: List[Tuple] = []
        # instant record: (kind, t_ns, thread_ident, attrs-or-None)
        self.instants: List[Tuple] = []
        # counter sample: (series, t_ns, value) — Chrome "C" events;
        # the device/host pool occupancy timeline (docs/observability.md)
        self.counters: List[Tuple] = []
        self._thread_names: Dict[int, str] = {}

    def _thread(self) -> int:
        t = threading.current_thread()
        ident = t.ident or 0
        if ident not in self._thread_names:
            self._thread_names[ident] = t.name
        return ident

    def add(self, kind: str, t0: int, t1: int, batch=None, chip=None,
            **attrs) -> None:
        self.spans.append((kind, t0, t1, self._thread(), batch, chip,
                           _clean(attrs)))

    def mark(self, kind: str, **attrs) -> None:
        self.instants.append((kind, time.perf_counter_ns(),
                              self._thread(), _clean(attrs)))

    def count(self, series: str, value) -> None:
        self.counters.append((series, time.perf_counter_ns(), value))


def _clean(attrs: dict) -> Optional[dict]:
    if not attrs:
        return None
    out = {k: v for k, v in attrs.items() if v is not None}
    return out or None


# Hot-path flag: hooks read this module global directly (one attribute
# load when tracing is off). Guarded by _LOCK only for begin/end.
_ACTIVE: Optional[QueryTrace] = None
_LOCK = threading.Lock()
# an installed flight recorder parked while a file-mode root query
# owns _ACTIVE: the ring is process-lifetime state and a file trace
# must not destroy it (restored when the file trace closes)
_RING_STASH: Optional[QueryTrace] = None
_DEPTH = 0           # nested execute_plan calls (scalar subqueries)
_SEQ = 0             # traced-candidate query counter (sampling stream)
_RNG: Optional[random.Random] = None
_RNG_SEED: Optional[int] = None


def active() -> Optional[QueryTrace]:
    return _ACTIVE


def ring_active():
    """The installed flight recorder (telemetry.ring.RingTrace) when
    trace.mode=ring has been activated, else None."""
    qt = _ACTIVE
    return qt if getattr(qt, "is_ring", False) else None


def reset_tracing() -> None:
    """Drop the sampling stream + query counter so the next query sees
    a fresh deterministic schedule (tests call this between runs, like
    retry.reset_fault_injection). Uninstalls an active ring recorder
    too."""
    global _ACTIVE, _DEPTH, _SEQ, _RNG, _RNG_SEED, _RING_STASH
    with _LOCK:
        _ACTIVE = None
        _RING_STASH = None
        _DEPTH = 0
        _SEQ = 0
        _RNG = None
        _RNG_SEED = None


def begin_query(conf_obj) -> Optional[str]:
    """Start (or join) a query trace. Returns an opaque token for
    ``end_query`` — ``None`` when tracing is disabled, ``"root"`` when
    this call opened the trace, ``"ring"`` when the flight recorder is
    the sink (trace.mode=ring — installed on first use, shared by
    every query for the process life), ``"nested"``/``"unsampled"``
    otherwise. Nested queries (scalar subqueries executed during
    planning) fold their spans into the outer query's trace; so does a
    concurrent query from another session thread (documented
    limitation — span streams are a property of the process
    timeline)."""
    global _ACTIVE, _DEPTH, _SEQ, _RNG, _RNG_SEED, _RING_STASH
    if conf_obj is None or not bool(conf_obj.get(TRACE_ENABLED)):
        return None
    if str(conf_obj.get(TRACE_MODE)).lower() == "ring":
        # flight recorder: always on once installed, never sampled,
        # never cleared at query end — the interesting query is the
        # one you didn't pre-instrument. A query that begins while a
        # file-mode trace is open folds into that trace instead (the
        # nested-scope contract above).
        with _LOCK:
            if _ACTIVE is None:
                from spark_rapids_tpu.telemetry.ring import RingTrace
                from spark_rapids_tpu.conf import SERVE_TENANT_ID
                _ACTIVE = RingTrace(
                    int(conf_obj.get(TRACE_RING_SPANS)),
                    tenant=str(conf_obj.get(SERVE_TENANT_ID)) or None)
            elif not getattr(_ACTIVE, "is_ring", False):
                # a file-mode trace is open: fold into it WITHOUT
                # touching its depth bookkeeping (the "folded" token
                # is a no-op at end_query)
                return "folded"
            _ACTIVE.queries_begun += 1
            return "ring"
    with _LOCK:
        _DEPTH += 1
        if _DEPTH > 1:
            return "nested"
        _SEQ += 1
        rate = float(conf_obj.get(TRACE_SAMPLE_RATE))
        if rate < 1.0:
            seed = int(conf_obj.get(TRACE_SAMPLE_SEED))
            if _RNG is None or _RNG_SEED != seed:
                _RNG = random.Random(seed)
                _RNG_SEED = seed
            if _RNG.random() >= rate:
                return "unsampled"
        from spark_rapids_tpu.conf import SERVE_TENANT_ID
        if getattr(_ACTIVE, "is_ring", False):
            # park the process-lifetime flight recorder for the file
            # trace's duration — a file-mode query must not destroy
            # the ring's accumulated history (restored at end_query)
            _RING_STASH = _ACTIVE
        _ACTIVE = QueryTrace(
            _SEQ, tenant=str(conf_obj.get(SERVE_TENANT_ID)) or None)
        return "root"


def end_query(conf_obj, token: Optional[str], wall_s: float = 0.0,
              rows: int = 0, error: bool = False) -> Optional[str]:
    """Close a ``begin_query`` scope; on the outermost sampled close,
    write the Chrome-trace file and return its path. Failures never
    break the query (observability must not take down execution)."""
    global _ACTIVE, _DEPTH, _RING_STASH
    if token is None:
        return None
    if token == "folded":
        return None
    if token == "ring":
        # the recorder stays installed; the query leaves only a
        # boundary marker (the trigger engine receives wall/rows via
        # its own query-end hook, telemetry/triggers.py)
        qt = ring_active()
        if qt is not None:
            qt.mark("queryEnd", wallSeconds=round(wall_s, 6), rows=rows,
                    error=bool(error) or None)
        return None
    with _LOCK:
        _DEPTH = max(0, _DEPTH - 1)
        if token != "root":
            return None
        # reinstall a parked flight recorder, if any
        qt, _ACTIVE, _RING_STASH = _ACTIVE, _RING_STASH, None
    if qt is None:
        return None
    try:
        trace_dir = str(conf_obj.get(TRACE_DIR))
        os.makedirs(trace_dir, exist_ok=True)
        path = os.path.join(
            trace_dir, f"trace-{os.getpid()}-q{qt.query_id:05d}.json")
        write_chrome_trace(path, qt, wall_s=wall_s, rows=rows,
                           error=error)
        return path
    except Exception:
        return None


# ---------------------------------------------------------------------------
# Recording helpers (the instrumentation surface)
# ---------------------------------------------------------------------------

@contextlib.contextmanager
def span(kind: str, batch=None, chip=None, **attrs) -> Iterator[None]:
    """Trace-only span (sites whose duration already reaches a metric
    through another channel, e.g. store stats). One None check when
    tracing is off."""
    qt = _ACTIVE
    if qt is None:
        yield
        return
    t0 = time.perf_counter_ns()
    try:
        yield
    finally:
        qt.add(kind, t0, time.perf_counter_ns(), batch=batch, chip=chip,
               **attrs)


def instant(kind: str, **attrs) -> None:
    """Point-in-time marker (retry/backoff/split/chip-failure events)."""
    qt = _ACTIVE
    if qt is not None:
        qt.mark(kind, **attrs)


def counter(series: str, value) -> None:
    """Counter sample (Chrome "C" event): Perfetto renders each series
    as a stepped occupancy track next to the span lanes. Used by the
    DeviceStore so the HBM/host pool timeline sits beside the query's
    spans. One None check when tracing is off."""
    qt = _ACTIVE
    if qt is not None:
        qt.count(series, value)


def chip_of(batch) -> Optional[int]:
    """The chip a device batch is resident on, for span attribution —
    None (and no device query at all) when tracing is off."""
    if _ACTIVE is None:
        return None
    try:
        from spark_rapids_tpu.columnar.device import batch_device
        d = batch_device(batch)
        return d.id if d is not None else None
    except Exception:
        return None


# ---------------------------------------------------------------------------
# Chrome trace-event export
# ---------------------------------------------------------------------------
#
# Spans are emitted as matched B/E pairs (ph "B"/"E"), instants as ph
# "i". Within one recording thread, context-manager spans are properly
# nested (LIFO); a span that spans a generator yield can resume on a
# different consumer thread and partially overlap its lane's stack, so
# the writer assigns spans greedily to LANES: a span joins the first
# lane whose open spans all fully contain it, otherwise it opens an
# overflow lane (tid "<thread>!k"). Every lane's event stream is
# strictly nested and time-ordered, which is exactly what the Chrome
# B/E semantics (and the schema test) require.

def _us(t_ns: int, base_ns: int) -> float:
    return round((t_ns - base_ns) / 1000.0, 3)


def _lane_events(spans: List[Tuple], base: int, pid: int,
                 tid0: int) -> Tuple[List[dict], int]:
    """Per-source-thread span list -> correctly nested B/E streams over
    one or more lanes. Returns (events, lanes_used)."""
    events: List[dict] = []
    # lane state: list of stacks; each stack holds (t1, kind) of opens
    lanes: List[List[Tuple[int, str]]] = []
    lane_ev: List[List[dict]] = []
    for kind, t0, t1, _ident, batch, chip, attrs in sorted(
            spans, key=lambda s: (s[1], -s[2])):
        args: Dict[str, Any] = {}
        if batch is not None:
            args["batch"] = batch
        if chip is not None:
            args["chip"] = chip
        if attrs:
            args.update(attrs)
        placed = False
        for li in range(len(lanes)):
            stack, ev = lanes[li], lane_ev[li]
            while stack and stack[-1][0] <= t0:
                ct1, ckind = stack.pop()
                ev.append({"name": ckind, "ph": "E", "pid": pid,
                           "tid": tid0 + li, "ts": _us(ct1, base)})
            if not stack or stack[-1][0] >= t1:
                b = {"name": kind, "ph": "B", "pid": pid,
                     "tid": tid0 + li, "ts": _us(t0, base)}
                if args:
                    b["args"] = args
                ev.append(b)
                stack.append((t1, kind))
                placed = True
                break
        if not placed:
            li = len(lanes)
            b = {"name": kind, "ph": "B", "pid": pid, "tid": tid0 + li,
                 "ts": _us(t0, base)}
            if args:
                b["args"] = args
            lanes.append([(t1, kind)])
            lane_ev.append([b])
    for li, stack in enumerate(lanes):
        while stack:
            ct1, ckind = stack.pop()
            lane_ev[li].append({"name": ckind, "ph": "E", "pid": pid,
                                "tid": tid0 + li, "ts": _us(ct1, base)})
    for ev in lane_ev:
        events.extend(ev)
    return events, max(1, len(lanes))


def write_chrome_trace(path: str, qt: QueryTrace, wall_s: float = 0.0,
                       rows: int = 0, error: bool = False) -> None:
    base = qt.t0
    pid = os.getpid()
    events: List[dict] = [{
        "name": "process_name", "ph": "M", "pid": pid, "tid": 0,
        "args": {"name": f"spark-rapids-tpu q{qt.query_id}"}}]
    by_thread: Dict[int, List[Tuple]] = {}
    for s in qt.spans:
        by_thread.setdefault(s[3], []).append(s)
    tid = 1
    for ident in sorted(by_thread):
        ev, lanes = _lane_events(by_thread[ident], base, pid, tid)
        name = qt._thread_names.get(ident, str(ident))
        for li in range(lanes):
            events.append({"name": "thread_name", "ph": "M", "pid": pid,
                           "tid": tid + li,
                           "args": {"name": name if li == 0
                                    else f"{name}!{li}"}})
        events.extend(ev)
        tid += lanes
    # instants get a dedicated lane per source thread, time-sorted:
    # sharing the span lane would interleave timestamps out of order
    # (a ring dump always carries markers older than the lane's last
    # span end), breaking the per-tid monotonicity the schema test —
    # and Perfetto's track model — expect
    ins_by_thread: Dict[int, List[Tuple]] = {}
    for ins in qt.instants:
        ins_by_thread.setdefault(ins[2], []).append(ins)
    for ident in sorted(ins_by_thread):
        name = qt._thread_names.get(ident, str(ident))
        events.append({"name": "thread_name", "ph": "M", "pid": pid,
                       "tid": tid, "args": {"name": f"{name}!i"}})
        for kind, t_ns, _ident, attrs in sorted(
                ins_by_thread[ident], key=lambda i: i[1]):
            ev = {"name": kind, "ph": "i", "s": "t", "pid": pid,
                  "tid": tid, "ts": _us(t_ns, base)}
            if attrs:
                ev["args"] = attrs
            events.append(ev)
        tid += 1
    if qt.counters:
        # counter tracks get a lane of their own: samples from many
        # threads interleave in append order, so sort by time to keep
        # the per-tid stream monotone (the schema test's invariant)
        ctid = tid
        events.append({"name": "thread_name", "ph": "M", "pid": pid,
                       "tid": ctid, "args": {"name": "counters"}})
        for series, t_ns, value in sorted(qt.counters,
                                          key=lambda c: c[1]):
            events.append({"name": series, "ph": "C", "pid": pid,
                           "tid": ctid, "ts": _us(t_ns, base),
                           "args": {"value": value}})
    doc = {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "otherData": {
            "version": 1,
            "queryId": qt.query_id,
            "pid": pid,
            "wallSeconds": round(wall_s, 6),
            "outputRows": rows,
            "error": bool(error),
            "startUnixTime": qt.wall_t0,
            "spanCount": len(qt.spans),
            "instantCount": len(qt.instants),
            "counterCount": len(qt.counters),
        },
    }
    if qt.tenant:
        doc["otherData"]["tenant"] = qt.tenant
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        # default=str: attr values are normally JSON scalars, but an
        # exotic attr must degrade to its repr, never kill the write
        json.dump(doc, f, default=str)
    os.replace(tmp, path)


# ---------------------------------------------------------------------------
# Loader (tools.py's data source)
# ---------------------------------------------------------------------------

def load_trace(path: str) -> Dict[str, Any]:
    """Parse a written trace back into spans/instants (timestamps in
    microseconds from trace start). B/E pairs are matched per tid with
    a stack, exactly the Chrome semantics."""
    with open(path) as f:
        doc = json.load(f)
    spans: List[dict] = []
    instants: List[dict] = []
    counters: List[dict] = []
    tid_names: Dict[int, str] = {}
    stacks: Dict[int, List[dict]] = {}
    for ev in doc.get("traceEvents", []):
        ph = ev.get("ph")
        tid = ev.get("tid", 0)
        if ph == "M":
            if ev.get("name") == "thread_name":
                tid_names[tid] = ev.get("args", {}).get("name", str(tid))
        elif ph == "B":
            stacks.setdefault(tid, []).append(ev)
        elif ph == "E":
            st = stacks.get(tid)
            if not st:
                raise ValueError(f"unmatched E event at ts={ev.get('ts')}")
            b = st.pop()
            if b.get("name") != ev.get("name"):
                raise ValueError(
                    f"B/E name mismatch: {b.get('name')} vs "
                    f"{ev.get('name')}")
            spans.append({"name": b["name"], "t0": float(b["ts"]),
                          "t1": float(ev["ts"]), "tid": tid,
                          "args": b.get("args", {})})
        elif ph in ("i", "I"):
            instants.append({"name": ev.get("name"),
                             "ts": float(ev.get("ts", 0)), "tid": tid,
                             "args": ev.get("args", {})})
        elif ph == "C":
            counters.append({"name": ev.get("name"),
                             "ts": float(ev.get("ts", 0)),
                             "value": ev.get("args", {}).get("value")})
    leftover = {t: st for t, st in stacks.items() if st}
    if leftover:
        raise ValueError(f"unmatched B events on tids {sorted(leftover)}")
    return {"spans": spans, "instants": instants, "counters": counters,
            "meta": doc.get("otherData", {}), "tidNames": tid_names}
