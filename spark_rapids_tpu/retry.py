"""Task-level OOM retry & split-and-retry framework + fault injection.

The reference survives memory pressure with two cooperating pieces:
``DeviceMemoryEventHandler.onAllocFailure`` spills the device store and
retries the allocation, and the retry framework (RmmRapidsRetryIterator
.scala:243 withRetry / withRetryNoSplit) wraps every operator-held
allocation so a ``GpuRetryOOM`` re-attempts after the store drains and a
``GpuSplitAndRetryOOM`` splits the operator's input in half and
processes the pieces independently.  This module is the TPU twin:

- ``with_retry(fn, conf, metrics)`` — run one device operation under
  the retry protocol: on :class:`TpuRetryOOM` spill the DeviceStore
  down, sleep a bounded exponential backoff, and re-attempt up to
  ``spark.rapids.sql.retry.maxRetries`` times, then re-raise.
- ``with_split_retry(batch, fn, conf, metrics)`` — the split-and-retry
  combinator: when retries exhaust (or the failure explicitly asks for
  a split), the input batch splits in half BY ROWS and each half runs
  independently; results concat downstream to a bit-identical whole.
- ``io_with_retry(fn, conf, metrics)`` — bounded-backoff retry for
  transient reader IO errors, re-raising the original after
  ``spark.rapids.sql.reader.maxRetries``.

Fault injection (SURVEY.md:377-385 names the missing piece): a
deterministic, seeded :class:`FaultInjector` driven by the
``spark.rapids.sql.test.injectOOM`` / ``injectIOError`` /
``injectChipFailure`` confs throws synthetic OOMs at the Nth wrapped
allocation, IO errors at the Nth reader access, and dispatch failures
on named mesh chips.  Chip failures degrade the mesh (parallel/mesh.py
``mark_chip_failed``) instead of failing the query; see
docs/robustness.md for the full state machine.
"""

from __future__ import annotations

import contextlib
import random
import threading
import time
from typing import Any, Callable, List, Optional, TypeVar

from spark_rapids_tpu import metrics as M

T = TypeVar("T")


# ---------------------------------------------------------------------------
# Exceptions (GpuRetryOOM / GpuSplitAndRetryOOM / shuffle-fetch-failure twins)
# ---------------------------------------------------------------------------

class TpuRetryOOM(MemoryError):
    """Retryable device allocation failure: the caller should make its
    held batches spillable, spill the store down, and re-attempt."""


class TpuSplitAndRetryOOM(TpuRetryOOM):
    """Retrying at the same size will not help: split the input batch
    in half by rows and process the halves independently."""


class TpuChipFailure(RuntimeError):
    """A device program could not be dispatched on a mesh chip. Handled
    by degrading the mesh to the surviving chips (the Spark analogue is
    a fetch-failure driving stage re-execution on healthy executors)."""

    def __init__(self, chip_id: int, msg: str = ""):
        super().__init__(msg or f"dispatch failure on mesh chip {chip_id}")
        self.chip_id = chip_id


_OOM_MARKERS = ("RESOURCE_EXHAUSTED", "Resource exhausted",
                "Out of memory", "out of memory",
                "Failed to allocate", "OOM")


def is_oom_error(e: BaseException) -> bool:
    """Heuristic: does a raw backend error look like an HBM allocation
    failure (XLA surfaces RESOURCE_EXHAUSTED through generic
    RuntimeError/XlaRuntimeError types)?"""
    if isinstance(e, TpuRetryOOM):
        return True
    s = str(e)
    return any(m in s for m in _OOM_MARKERS)


# ---------------------------------------------------------------------------
# Recovery-path injection suppression (the retry machinery's own spill /
# split / fallback work must never recurse into another injected fault)
# ---------------------------------------------------------------------------

_tls = threading.local()


def _suppressed() -> bool:
    return getattr(_tls, "suppress", 0) > 0


@contextlib.contextmanager
def suppress_injection():
    _tls.suppress = getattr(_tls, "suppress", 0) + 1
    try:
        yield
    finally:
        _tls.suppress -= 1


# ---------------------------------------------------------------------------
# Deterministic fault injector
# ---------------------------------------------------------------------------

class _Schedule:
    """Parsed injection spec. Grammar (docs/robustness.md):

    - ``"N"``        fire once at every Nth event
    - ``"N:K"``      at every Nth event, fail K CONSECUTIVE attempts
                     (K > retry.maxRetries forces split-and-retry)
    - ``"split:N"``  throw TpuSplitAndRetryOOM at every Nth event
    - ``"seed:S:P"`` seeded random: each event fails with probability P
    - ``"site:NAME:SPEC"`` scope any of the above to events tagged
      with site NAME (e.g. ``site:upload:2`` fails every 2nd scan
      upload-ahead; untagged sites never count against the schedule).
      ``site:cancel:SPEC`` is special: it counts LIFECYCLE
      cancellation checkpoints and injects a cooperative cancel of
      the live query's token instead of an OOM (docs/robustness.md
      site catalog). ``site:budget:SPEC`` is the planning leg: it
      counts budget-ORACLE queries and makes the firing query report
      half the real headroom (docs/out_of_core.md) — never an error
    """

    __slots__ = ("every_n", "streak", "split", "seed", "prob", "rng",
                 "site")

    def __init__(self, every_n=0, streak=1, split=False, seed=0,
                 prob=0.0, site=""):
        self.every_n = every_n
        self.streak = max(1, streak)
        self.split = split
        self.seed = seed
        self.prob = prob
        self.site = site
        # per-schedule RNG: a seeded OOM schedule and a seeded IO
        # schedule must each follow their OWN deterministic stream
        self.rng = random.Random(seed) if prob > 0.0 else None


def _parse_schedule(spec: str) -> Optional[_Schedule]:
    s = str(spec or "").strip().lower()
    if not s or s in ("0", "false", "off", "none"):
        return None
    if s.startswith("site:"):
        _, name, rest = s.split(":", 2)
        sched = _parse_schedule(rest)
        if sched is not None:
            sched.site = name
        return sched
    if s.startswith("split:"):
        return _Schedule(every_n=int(s[len("split:"):]), split=True)
    if s.startswith("seed:"):
        _, seed, prob = s.split(":")
        return _Schedule(seed=int(seed), prob=float(prob))
    if ":" in s:
        n, k = s.split(":")
        return _Schedule(every_n=int(n), streak=int(k))
    return _Schedule(every_n=int(s))


class FaultInjector:
    """Deterministic synthetic-fault source. One instance per distinct
    injection conf (process-wide, like the DeviceStore); counters are
    shared across sessions so a schedule is a property of the process
    timeline, exactly like the reference's RMM inject-OOM hook."""

    def __init__(self, oom_spec: str = "", io_spec: str = "",
                 chip_spec: str = ""):
        self._oom = _parse_schedule(oom_spec)
        # `site:cancel:N` is the LIFECYCLE leg of the grammar
        # (docs/robustness.md): the schedule counts cancellation
        # CHECKPOINTS (lifecycle.checkpoint) instead of allocations,
        # and the injected fault is a cooperative cancel of the live
        # query's token — never an OOM
        self._cancel = None
        if self._oom is not None and self._oom.site == "cancel":
            self._cancel, self._oom = self._oom, None
        # `site:budget:N` is the PLANNING leg (docs/robustness.md,
        # docs/out_of_core.md): the schedule counts budget-ORACLE
        # queries instead of allocations, and the injected fault is a
        # halved headroom report — never a raised error — so the
        # planned out-of-core tier's escalation path (more partitions,
        # zero retries) is deterministically testable
        self._budget = None
        if self._oom is not None and self._oom.site == "budget":
            self._budget, self._oom = self._oom, None
        # `site:tuning:N` is the FEEDBACK-CONTROL leg (docs/tuning.md):
        # the schedule counts TuningController scan ticks, and the
        # injected fault is a deliberately harmful synthetic action —
        # never an error — so the guardrail's auto-revert path is
        # deterministically testable end to end
        self._tuning = None
        if self._oom is not None and self._oom.site == "tuning":
            self._tuning, self._oom = self._oom, None
        self._io = _parse_schedule(io_spec)
        self._chips = set()
        for part in str(chip_spec or "").split(","):
            part = part.strip()
            if part:
                self._chips.add(int(part))
        self._lock = threading.Lock()
        self._alloc_count = 0
        self._oom_streak = 0
        self._io_count = 0
        self._io_streak = 0
        self._cancel_count = 0
        self._budget_count = 0
        self._tuning_count = 0
        # observability (bench detail.robustness, tests)
        self.oom_injected = 0
        self.io_injected = 0
        self.chip_failures_injected = 0
        self.cancels_injected = 0
        self.budget_faults_injected = 0
        self.tuning_faults_injected = 0

    def _fire(self, sched: _Schedule, count: int) -> bool:
        if sched.prob > 0.0:
            return sched.rng.random() < sched.prob
        return sched.every_n > 0 and count % sched.every_n == 0

    def on_alloc(self, site: str = "") -> None:
        """Checkpoint at one wrapped device allocation attempt. ``site``
        tags named allocation classes (``upload`` = the scan pipeline's
        prefetched raw-chunk upload) so a ``site:NAME:...`` schedule
        can target exactly one of them."""
        if self._oom is None or _suppressed():
            return
        if self._oom.site and self._oom.site != site:
            return
        with self._lock:
            if self._oom_streak > 0:
                self._oom_streak -= 1
                self.oom_injected += 1
                raise TpuRetryOOM("injected OOM (consecutive-failure "
                                  "streak, spark.rapids.sql.test.injectOOM)")
            self._alloc_count += 1
            if not self._fire(self._oom, self._alloc_count):
                return
            self.oom_injected += 1
            if self._oom.split:
                raise TpuSplitAndRetryOOM(
                    f"injected split-OOM at allocation {self._alloc_count} "
                    "(spark.rapids.sql.test.injectOOM)")
            self._oom_streak = self._oom.streak - 1
            raise TpuRetryOOM(
                f"injected OOM at allocation {self._alloc_count} "
                "(spark.rapids.sql.test.injectOOM)")

    def on_io(self, path: str = "") -> None:
        """Checkpoint at one reader IO attempt."""
        if self._io is None or _suppressed():
            return
        with self._lock:
            if self._io_streak > 0:
                self._io_streak -= 1
                self.io_injected += 1
                raise IOError(f"injected IO error reading {path!r} "
                              "(spark.rapids.sql.test.injectIOError)")
            self._io_count += 1
            if not self._fire(self._io, self._io_count):
                return
            self.io_injected += 1
            self._io_streak = self._io.streak - 1
            raise IOError(f"injected IO error reading {path!r} "
                          "(spark.rapids.sql.test.injectIOError)")

    def on_chip(self, chip_id: int) -> None:
        """Checkpoint before dispatching device work onto a mesh chip.
        Injected failures are PERSISTENT per chip — the degrade loop
        stops consulting a chip once it is marked failed, which is what
        ends the failure stream (a real dead chip behaves the same)."""
        if chip_id in self._chips:
            with self._lock:
                self.chip_failures_injected += 1
            raise TpuChipFailure(chip_id)

    def on_cancel_point(self, token, site: str = "") -> None:
        """Checkpoint at one lifecycle cancellation checkpoint
        (lifecycle.checkpoint). A ``site:cancel:N`` schedule cancels
        the live query's token at the Nth checkpoint — the fault it
        injects IS a cancellation, so the query unwinds through the
        cooperative-cancel protocol, not the retry protocol. Recovery
        paths are exempt like every other injection site."""
        if self._cancel is None or token is None or _suppressed():
            return
        with self._lock:
            self._cancel_count += 1
            if not self._fire(self._cancel, self._cancel_count):
                return
            self.cancels_injected += 1
        from spark_rapids_tpu.lifecycle import REASON_INJECTED
        token.cancel(REASON_INJECTED)

    def on_budget_query(self) -> bool:
        """Checkpoint at one budget-oracle headroom query. A
        ``site:budget:N`` schedule returns True at the Nth query — the
        oracle then reports HALF the real headroom, so planning sees
        synthetic memory pressure and escalates its partition count
        (never an error: the fault exercises the planned path, not the
        retry backstop). Recovery paths are exempt like every other
        injection site."""
        if self._budget is None or _suppressed():
            return False
        with self._lock:
            self._budget_count += 1
            if not self._fire(self._budget, self._budget_count):
                return False
            self.budget_faults_injected += 1
            return True

    def on_tuning_tick(self) -> bool:
        """Checkpoint at one TuningController scan tick. A
        ``site:tuning:N`` schedule returns True at the Nth tick — the
        controller then applies a deliberately HARMFUL synthetic action
        (docs/tuning.md) so the guardrail's observe-and-revert loop is
        exercised without waiting for a real bad decision (never an
        error: the fault is a bad action, and reverting it IS the
        behavior under test)."""
        if self._tuning is None or _suppressed():
            return False
        with self._lock:
            self._tuning_count += 1
            if not self._fire(self._tuning, self._tuning_count):
                return False
            self.tuning_faults_injected += 1
            return True

    def stats(self) -> dict:
        with self._lock:
            return {"allocations": self._alloc_count,
                    "oomInjected": self.oom_injected,
                    "ioInjected": self.io_injected,
                    "chipFailuresInjected": self.chip_failures_injected,
                    "cancelsInjected": self.cancels_injected,
                    "budgetFaultsInjected": self.budget_faults_injected,
                    "tuningFaultsInjected": self.tuning_faults_injected}


_INJECTOR: Optional[FaultInjector] = None
_INJECTOR_KEY: Optional[tuple] = None
_INJECTOR_LOCK = threading.Lock()


def get_fault_injector(conf) -> Optional[FaultInjector]:
    """Process-wide injector for the session's injection confs; None
    (zero overhead) when injection is off. Rebuilt — with fresh,
    deterministic counters — whenever the injection confs change."""
    if conf is None:
        return None
    from spark_rapids_tpu.conf import (INJECT_CHIP_FAILURE, INJECT_IO_ERROR,
                                       INJECT_OOM)
    key = (str(conf.get(INJECT_OOM) or ""),
           str(conf.get(INJECT_IO_ERROR) or ""),
           str(conf.get(INJECT_CHIP_FAILURE) or ""))
    if key == ("", "", ""):
        return None
    global _INJECTOR, _INJECTOR_KEY
    with _INJECTOR_LOCK:
        if _INJECTOR is None or _INJECTOR_KEY != key:
            _INJECTOR = FaultInjector(*key)
            _INJECTOR_KEY = key
        return _INJECTOR


def reset_fault_injection() -> None:
    """Drop the injector singleton so the next query sees a fresh,
    deterministic schedule (tests call this between runs)."""
    global _INJECTOR, _INJECTOR_KEY
    with _INJECTOR_LOCK:
        _INJECTOR = None
        _INJECTOR_KEY = None


def degrade_on_chip_failure(attempt: Callable[[], T],
                            metrics=None) -> T:
    """The chip-failure degrade loop (docs/robustness.md ladder), shared
    by the exchange materializer and the driver-level collect so the
    retry-vs-reraise protocol lives in ONE place. Snapshot the failed
    set BEFORE each attempt: a failure on a chip that was already
    demoted when the attempt began means the failure is elsewhere and
    re-raises (bounding the loop by the chip count); a chip another
    thread demoted mid-attempt still retries on the survivors."""
    from spark_rapids_tpu.parallel.mesh import (failed_chips,
                                                mark_chip_failed)
    while True:
        already = failed_chips()
        try:
            return attempt()
        except TpuChipFailure as e:
            if e.chip_id in already:
                raise
            from spark_rapids_tpu import trace as TR
            TR.instant("chipFailure", chip=e.chip_id)
            if mark_chip_failed(e.chip_id) and metrics is not None:
                metrics.create(M.DEGRADED_CHIPS, M.ESSENTIAL).add(1)


def chip_checkpoint(conf, device) -> None:
    """Raise TpuChipFailure when dispatch onto ``device`` is injected
    to fail (called at mesh upload / mesh exchange dispatch points)."""
    inj = get_fault_injector(conf)
    if inj is not None:
        inj.on_chip(device.id if hasattr(device, "id") else int(device))


# ---------------------------------------------------------------------------
# Retry combinators
# ---------------------------------------------------------------------------

def _retry_limits(conf) -> tuple:
    if conf is None:
        return 3, 1, 100
    from spark_rapids_tpu.conf import (RETRY_BACKOFF_MS, RETRY_MAX_BACKOFF_MS,
                                       RETRY_MAX_RETRIES)
    return (int(conf.get(RETRY_MAX_RETRIES)),
            int(conf.get(RETRY_BACKOFF_MS)),
            int(conf.get(RETRY_MAX_BACKOFF_MS)))


def _recover(conf, metrics, attempt: int, backoff_ms: int,
             max_backoff_ms: int) -> None:
    """One OOM recovery step: spill the device store down (the
    DeviceMemoryEventHandler.onAllocFailure role), then block for a
    bounded exponential backoff so concurrent tasks' frees land. Traced
    as an instant ``retryOOM`` marker plus a nested ``retryBlock`` span
    over the SAME interval the retryBlockTime metric reads — the
    offline analyzer subtracts the nested span from enclosing operator
    spans, undoing the documented retryBlockTime-inside-opTime double
    count at the reporting layer (docs/observability.md)."""
    from spark_rapids_tpu import trace as TR
    from spark_rapids_tpu.telemetry import triggers as TEL
    TR.instant("retryOOM", attempt=attempt)
    # retry-STORM telemetry is evaluated here, at retry time, so a
    # storm surfaces while it is happening (one boolean check when the
    # engine is unarmed; docs/observability.md "Live telemetry")
    TEL.on_retry()
    t0 = time.perf_counter_ns()
    freed = 0
    with suppress_injection():
        if conf is not None:
            from spark_rapids_tpu.memory import get_device_store
            store = get_device_store(conf)
        else:
            # conf-less wrap sites (columnar helpers without a plan
            # context): best-effort spill of the live process store —
            # backoff alone rarely frees HBM
            from spark_rapids_tpu import memory
            store = memory._STORE
        if store is not None:
            # escalate: first retry frees half the device tier (handles
            # the operation touches next stay resident instead of
            # thrashing a full device->host->device round trip), later
            # retries drain it completely
            target = store.device_bytes // 2 if attempt == 1 else 0
            freed = store.spill_device_down(target)
        delay = min(backoff_ms * (1 << (attempt - 1)), max_backoff_ms)
        if delay > 0:
            # cancellation-aware backoff (docs/serving.md "Query
            # lifecycle"): a cancelled/timed-out query must not sleep
            # through its deadline inside the retry protocol
            from spark_rapids_tpu.lifecycle import cancellable_sleep
            cancellable_sleep(delay / 1000.0, site="retryBackoff")
    t1 = time.perf_counter_ns()
    qt = TR._ACTIVE
    if qt is not None:
        qt.add("retryBlock", t0, t1, attempt=attempt, freedBytes=freed)
    if metrics is not None:
        metrics.create(M.RETRY_COUNT, M.ESSENTIAL).add(1)
        if freed:
            metrics.create(M.SPILL_BYTES_ON_RETRY, M.ESSENTIAL).add(freed)
        metrics.create(M.RETRY_BLOCK_TIME).add(t1 - t0)


def with_retry(fn: Callable[[], T], conf=None, metrics=None, *,
               splittable: bool = False,
               translate_real: bool = True, site: str = "") -> T:
    """Run ``fn`` under the OOM-retry protocol (withRetryNoSplit role).

    On :class:`TpuRetryOOM` — injected, or a real backend
    RESOURCE_EXHAUSTED when ``translate_real`` — spill the DeviceStore
    down, back off (bounded exponential), and re-attempt up to
    ``spark.rapids.sql.retry.maxRetries`` times before re-raising.
    ``fn`` must be safe to re-execute (callers with donated input
    buffers pass ``translate_real=False``: a real OOM may have consumed
    the inputs mid-program, so only pre-dispatch injected faults — which
    leave inputs intact — are retried there).

    ``splittable=True`` (set by :func:`with_split_retry`) propagates
    :class:`TpuSplitAndRetryOOM` to the caller instead of degrading it
    to a plain retry.
    """
    inj = get_fault_injector(conf)
    max_retries, backoff_ms, max_backoff_ms = _retry_limits(conf)
    attempt = 0
    while True:
        try:
            if inj is not None:
                inj.on_alloc(site)
            return fn()
        except TpuSplitAndRetryOOM:
            if splittable:
                raise
            # no split support at this site: degrade to a plain retry
            attempt += 1
            if attempt > max_retries:
                raise
        except TpuRetryOOM:
            attempt += 1
            if attempt > max_retries:
                raise
        except TpuChipFailure:
            raise  # handled by the mesh degrade loop, never retried here
        except Exception as e:
            from spark_rapids_tpu.lifecycle import TpuQueryCancelled
            if isinstance(e, TpuQueryCancelled):
                raise  # cooperative cancel unwinds, never retried
            if not translate_real or not is_oom_error(e):
                raise
            attempt += 1
            if attempt > max_retries:
                raise TpuRetryOOM(f"device OOM after {max_retries} "
                                  f"retries: {e}") from e
        _recover(conf, metrics, attempt, backoff_ms, max_backoff_ms)


def with_split_retry(batch, fn: Callable[[Any], T], conf=None,
                     metrics=None, *, split=None,
                     translate_real: bool = True,
                     split_first: bool = False) -> List[T]:
    """Split-and-retry combinator (RmmRapidsRetryIterator.withRetry with
    the splitSpillableInHalfByRows policy): process ``batch`` with
    ``fn``; when the per-piece retry protocol exhausts — or the failure
    explicitly demands a split — the piece splits in half by rows and
    the halves are processed independently, recursively. Returns the
    per-piece results IN ROW ORDER, so concatenating them downstream is
    bit-identical to the unsplit whole (for the row-wise operators this
    wraps). Raises when a piece of <= 1 row still cannot complete.
    """
    if split is None:
        split = split_device_batch
    stack = [batch]
    out: List[T] = []
    first = True
    while stack:
        b = stack.pop()
        if first and split_first:
            first = False
            halves = _split_piece(b, split, metrics)
            if halves is None:
                stack.append(b)  # cannot split: one plain attempt
            else:
                stack.extend(reversed(halves))
            continue
        first = False
        try:
            out.append(with_retry(lambda: fn(b), conf, metrics,
                                  splittable=True,
                                  translate_real=translate_real))
        except TpuRetryOOM:
            halves = _split_piece(b, split, metrics)
            if halves is None:
                # unsplittable piece (single row, array/map columns):
                # last resort is the plain retry protocol — spilling
                # the store down may still free enough HBM for the
                # piece to fit; re-raises after maxRetries
                out.append(with_retry(lambda: fn(b), conf, metrics,
                                      splittable=False,
                                      translate_real=translate_real))
                continue
            stack.extend(reversed(halves))
    return out


def _split_piece(b, split, metrics) -> Optional[list]:
    with suppress_injection():
        halves = split(b)
    if not halves or len(halves) < 2:
        return None
    if metrics is not None:
        metrics.create(M.SPLIT_RETRY_COUNT, M.ESSENTIAL).add(1)
    from spark_rapids_tpu import trace as TR
    TR.instant("splitRetry", pieces=len(halves))
    return halves


def io_with_retry(fn: Callable[[], T], conf=None, metrics=None,
                  path: str = "") -> T:
    """Bounded-exponential-backoff retry for transient reader IO
    errors; the ORIGINAL error re-raises after
    ``spark.rapids.sql.reader.maxRetries`` attempts."""
    inj = get_fault_injector(conf)
    if conf is not None:
        from spark_rapids_tpu.conf import (READER_MAX_RETRIES,
                                           READER_RETRY_BACKOFF_MS)
        max_retries = int(conf.get(READER_MAX_RETRIES))
        backoff_ms = int(conf.get(READER_RETRY_BACKOFF_MS))
    else:
        max_retries, backoff_ms = 3, 1
    attempt = 0
    first_err: Optional[OSError] = None
    while True:
        try:
            if inj is not None:
                inj.on_io(path)
            return fn()
        except OSError as e:
            if first_err is None:
                first_err = e  # the root cause, not the last retry's
            attempt += 1
            if attempt > max_retries:
                raise first_err
            from spark_rapids_tpu import trace as TR
            TR.instant("ioRetry", path=path, attempt=attempt)
            if metrics is not None:
                metrics.create(M.IO_RETRY_COUNT, M.ESSENTIAL).add(1)
            t0 = time.perf_counter_ns()
            from spark_rapids_tpu.lifecycle import cancellable_sleep
            cancellable_sleep(
                min(backoff_ms * (1 << (attempt - 1)), 1000) / 1000.0,
                site="retryBackoff")
            if metrics is not None:
                metrics.create(M.RETRY_BLOCK_TIME).add(
                    time.perf_counter_ns() - t0)


# ---------------------------------------------------------------------------
# Split policies
# ---------------------------------------------------------------------------

def split_host_batch(hb) -> Optional[list]:
    """HostBatch -> two halves by rows (the R2C upload split policy)."""
    n = hb.num_rows
    if n <= 1:
        return None
    return [hb.slice(0, n // 2), hb.slice(n // 2, n)]


def split_device_batch(b) -> Optional[list]:
    """DeviceBatch -> halves with ~equal ACTIVE rows, original order
    preserved (the splitSpillableInHalfByRows policy). Reuses the
    exchange's one-program sort-split (split_by_pid), so each half
    compacts to its own smaller capacity bucket — the memory actually
    shrinks. Nested array/map columns carry element pools the row-sort
    cannot ride; those batches report unsplittable (None)."""
    from spark_rapids_tpu.sql import types as T
    for f in b.schema.fields:
        if isinstance(f.data_type, (T.ArrayType, T.MapType)):
            return None
    n = b.row_count()  # recovery path: a blocking count sync is fine
    if n <= 1:
        return None
    from spark_rapids_tpu.exec.exchange import split_by_pid
    parts = split_by_pid(b, _half_pids()(b.active), 2)
    return [p for p in parts if p is not None]


_HALF_PIDS = None


def _half_pids():
    """Jitted half-point pid assignment (compiled once per capacity
    bucket by jax's own cache; the builder itself is built once)."""
    global _HALF_PIDS
    if _HALF_PIDS is None:
        import jax
        import jax.numpy as jnp

        def _fn(active):
            rank = jnp.cumsum(active.astype(jnp.int64)) - 1
            total = jnp.sum(active.astype(jnp.int64))
            return jnp.where(rank * 2 < total, 0, 1).astype(jnp.int32)
        # tpu-lint: disable=jit-direct(one lazily-built fixed split program — bounded by construction)
        _HALF_PIDS = jax.jit(_fn)
    return _HALF_PIDS
