"""Adaptive query execution over measured exchange statistics
(docs/adaptive.md; GpuQueryStagePrepOverrides / AQE ShuffleQueryStage
roles from GpuOverrides.scala:3550 and SURVEY §2.5).

The engine holds EXACT per-partition byte/row counts the moment any
exchange materializes and previously threw them away. This module is
the decision layer over those numbers:

- ``ExchangeStats`` — the record every ``TpuShuffleExchangeExec``
  captures at ``_materialize`` (single-chip and mesh paths both);
- broadcast demotion / partition coalescing / skew splitting policy
  helpers consumed by ``exec/join.py`` and ``exec/exchange.py``;
- the literal-normalization key the server's batch fusion uses to
  recognize same-shape queries (``fusion_key``).

Every decision here only changes HOW a result is computed, never WHAT
it is: the adaptive-off plan and the CPU engine are both oracles for
the adaptive plan (tests/test_adaptive.py asserts bit-identity).

Gating: ``adaptive_enabled`` requires BOTH ``spark.sql.adaptive.
enabled`` (the v0 switch) and ``spark.rapids.sql.adaptive.enabled``,
so either knob disables every runtime replan. The adaptive.* conf
family is excluded from the plan-cache signature (plan_cache.py):
adaptive and unadaptive runs of one query shape share baselines,
quarantine streaks, and doctor history.
"""

from __future__ import annotations

import re
from dataclasses import dataclass
from typing import Dict, List, Sequence, Tuple

from spark_rapids_tpu.conf import (ADAPTIVE_AUTO_BROADCAST_BYTES,
                                   ADAPTIVE_ENABLED, ADAPTIVE_SKEW_FACTOR,
                                   ADAPTIVE_TARGET_PARTITION_BYTES,
                                   AQE_ADVISORY_PARTITION_BYTES,
                                   AQE_ENABLED,
                                   AUTO_BROADCAST_JOIN_THRESHOLD, TpuConf)

# ---------------------------------------------------------------------------
# Exchange statistics
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class ExchangeStats:
    """Realized per-partition sizes of one materialized exchange.

    Bytes are ACTIVE-row refined where the handle can say (a filter
    only flips the active mask, so capacity-based sizes over-count);
    spilled handles keep their full size — off-device data is costed
    conservatively rather than re-promoted for a statistic. Rows are
    whatever the producer attached; a partition whose counts were
    never synced contributes 0 rows (bytes still count)."""

    partition_bytes: Tuple[int, ...]
    partition_rows: Tuple[int, ...]

    @property
    def num_partitions(self) -> int:
        return len(self.partition_bytes)

    @property
    def total_bytes(self) -> int:
        return sum(self.partition_bytes)

    @property
    def max_bytes(self) -> int:
        return max(self.partition_bytes, default=0)

    @property
    def median_bytes(self) -> int:
        """Median over NON-EMPTY partitions: empty partitions are the
        normal hash-shuffle tail and would drag the median toward zero,
        making every real partition look skewed."""
        live = sorted(b for b in self.partition_bytes if b > 0)
        if not live:
            return 0
        mid = len(live) // 2
        if len(live) % 2:
            return live[mid]
        return (live[mid - 1] + live[mid]) // 2

    @property
    def skew_ratio(self) -> float:
        med = self.median_bytes
        return (self.max_bytes / med) if med > 0 else 0.0


def _item_stats(item) -> Tuple[int, int]:
    """(bytes, rows) of one retained partition item — a SpillableBatch
    handle on the in-process paths, a raw per-chip DeviceBatch on the
    mesh path. Never forces a device sync: unknown row counts read 0."""
    from spark_rapids_tpu.memory import SpillableBatch
    if isinstance(item, SpillableBatch):
        size = item.sizeof()
        cap = item.capacity_hint
        st = item._state
        rows = st.rows if st.rows is not None else 0
        if cap and st.rows is not None:
            size = int(size * (st.rows / cap))
        return size, int(rows)
    size = int(item.sizeof()) if hasattr(item, "sizeof") else 0
    rows = getattr(item, "_num_rows", None)
    return size, int(rows) if rows is not None else 0


def capture_stats(cache: Sequence[Sequence]) -> ExchangeStats:
    """Build the ExchangeStats record from a materialized exchange
    cache (list of partitions, each a list of retained items)."""
    pbytes: List[int] = []
    prows: List[int] = []
    for part in cache:
        b = r = 0
        for item in part:
            ib, ir = _item_stats(item)
            b += ib
            r += ir
        pbytes.append(b)
        prows.append(r)
    return ExchangeStats(tuple(pbytes), tuple(prows))


# ---------------------------------------------------------------------------
# Conf resolution (the -1/0 "inherit the v0 knob" sentinels)
# ---------------------------------------------------------------------------


def adaptive_enabled(conf: TpuConf) -> bool:
    """BOTH adaptive switches on — the gate every runtime replan
    (broadcast demotion, coalescing, skew split, re-fusion) checks."""
    return bool(conf.get(AQE_ENABLED)) and bool(conf.get(ADAPTIVE_ENABLED))


def auto_broadcast_bytes(conf: TpuConf) -> int:
    """Runtime broadcast-demotion threshold; -1 (the default) inherits
    the static autoBroadcastJoinThreshold. Negative result disables."""
    v = int(conf.get(ADAPTIVE_AUTO_BROADCAST_BYTES))
    if v >= 0:
        return v
    return int(conf.get(AUTO_BROADCAST_JOIN_THRESHOLD))


def target_partition_bytes(conf: TpuConf) -> int:
    """Coalescing target; 0 (the default) inherits the v0 advisory
    partition size."""
    v = int(conf.get(ADAPTIVE_TARGET_PARTITION_BYTES))
    if v > 0:
        return v
    return int(conf.get(AQE_ADVISORY_PARTITION_BYTES))


def skew_factor(conf: TpuConf) -> float:
    return float(conf.get(ADAPTIVE_SKEW_FACTOR))


# ---------------------------------------------------------------------------
# Decision helpers
# ---------------------------------------------------------------------------


def coalesce_groups(sizes: Sequence[int], target: int) -> List[List[int]]:
    """Merge ADJACENT partitions up to ``target`` bytes
    (GpuCustomShuffleReaderExec / coalesced-partition-spec role;
    adjacency preserves range-partition ordering). Returns the list of
    partition-index groups, in order."""
    groups: List[List[int]] = []
    cur: List[int] = []
    cur_bytes = 0
    for i, sz in enumerate(sizes):
        if cur and cur_bytes + sz > target:
            groups.append(cur)
            cur, cur_bytes = [], 0
        cur.append(i)
        cur_bytes += sz
    if cur:
        groups.append(cur)
    return groups


# one pathological partition must not explode the probe-thunk count
MAX_SKEW_SPLITS = 16


def skew_splits(stats: ExchangeStats, factor: float) -> Dict[int, int]:
    """Skew plan: partition index -> sub-partition count (>= 2) for
    every partition whose realized bytes exceed ``factor`` x the median
    non-empty partition. The split count aims each sub-partition back
    at the median, capped at MAX_SKEW_SPLITS. Empty/None-factor plans
    return {} (no replan)."""
    if factor <= 0:
        return {}
    med = stats.median_bytes
    if med <= 0:
        return {}
    out: Dict[int, int] = {}
    for i, b in enumerate(stats.partition_bytes):
        if b > factor * med:
            out[i] = min(MAX_SKEW_SPLITS, max(2, (b + med - 1) // med))
    return out


def slice_groups(weights: Sequence[int], k: int) -> List[List[int]]:
    """Greedy contiguous slicing of ``len(weights)`` items into at most
    ``k`` groups of roughly equal total weight (the skew split over a
    partition's retained handle list — contiguity keeps batch order,
    so the joined output concatenation stays deterministic)."""
    n = len(weights)
    k = max(1, min(k, n))
    total = sum(weights)
    if k == 1 or total <= 0:
        return [list(range(n))]
    goal = total / k
    groups: List[List[int]] = []
    cur: List[int] = []
    cur_w = 0
    remaining = k
    for i, w in enumerate(weights):
        if cur and cur_w + w > goal and len(groups) < remaining - 1:
            groups.append(cur)
            cur, cur_w = [], 0
        cur.append(i)
        cur_w += w
    if cur:
        groups.append(cur)
    return groups


# ---------------------------------------------------------------------------
# Batch-fusion key (the serving layer's same-shape recognizer)
# ---------------------------------------------------------------------------

# SQL string literals first ('' is the embedded quote), then bare
# numerics not embedded in an identifier/qualified name
_SQL_STRING = re.compile(r"'(?:[^']|'')*'")
_SQL_NUMBER = re.compile(
    r"(?<![A-Za-z0-9_.'\"])\d+(?:\.\d+)?(?![A-Za-z0-9_])")


def fusion_key(sql: str) -> Tuple[str, Tuple[str, ...]]:
    """(normalized text, literal vector) for one SQL string: string and
    numeric literals become ``?`` placeholders and whitespace collapses,
    so queries differing only in literal bindings share a key. The
    literal vector is the binding that distinguishes members inside one
    fused batch (identical SQL => identical vector => one execution).

    This is the serving-layer proxy for "same plan-cache signature
    modulo literals": numeric literals are runtime arguments to the
    compiled device programs (ops/exprs.py ``expr_key``), so every
    member of a fused batch rides the same XLA executables."""
    literals: List[str] = []

    def keep(m: "re.Match[str]") -> str:
        literals.append(m.group(0))
        return "?"

    s = _SQL_STRING.sub(keep, sql)
    s = _SQL_NUMBER.sub(keep, s)
    return " ".join(s.split()), tuple(literals)
