"""Worker-process entry point.

Runs in a SEPARATE process with no JAX/engine imports: the loop
receives (mode, pickled-functions, Arrow-IPC bytes) frames, applies the
UDFs with pandas, and returns Arrow-IPC bytes — the same
stream-of-record-batches contract the reference's GpuArrowPythonRunner
speaks over its socket (GpuArrowEvalPythonExec.scala:353). Errors
travel back as formatted tracebacks and re-raise engine-side.
"""

from __future__ import annotations

import io
import traceback


def _read_table(ipc_bytes: bytes):
    import pyarrow as pa
    with pa.ipc.open_stream(io.BytesIO(ipc_bytes)) as rd:
        return rd.read_all()


def _write_table(tbl) -> bytes:
    import pyarrow as pa
    sink = io.BytesIO()
    with pa.ipc.new_stream(sink, tbl.schema) as wr:
        wr.write_table(tbl)
    return sink.getvalue()


def _apply_scalar(fns, arg_idxs, tbl, out_schema):
    """SQL_SCALAR_PANDAS_UDF: fns[i] gets its input columns (by index
    into ``tbl``) as pandas Series and returns a Series/scalar of
    len(tbl); outputs conform to out_schema's field types."""
    import pandas as pd
    import pyarrow as pa
    cols = []
    for i, (fn, idxs) in enumerate(zip(fns, arg_idxs)):
        args = [tbl.column(j).to_pandas() for j in idxs]
        out = fn(*args)
        if not isinstance(out, pd.Series):
            out = pd.Series([out] * tbl.num_rows)
        arr = pa.Array.from_pandas(out, type=out_schema.field(i).type)
        if len(arr) != tbl.num_rows:
            raise ValueError(
                f"pandas_udf returned {len(arr)} rows for a "
                f"{tbl.num_rows}-row batch")
        cols.append(arr)
    return pa.Table.from_arrays(cols, schema=out_schema)


def _apply_map(fn, tbl, out_schema):
    """mapInPandas: fn(iterator of DataFrames) -> iterator of DataFrames."""
    import pandas as pd
    import pyarrow as pa
    outs = []
    for df in fn(iter([tbl.to_pandas()])):
        if not isinstance(df, pd.DataFrame):
            raise TypeError("mapInPandas function must yield DataFrames")
        outs.append(pa.Table.from_pandas(df, schema=out_schema,
                                         preserve_index=False))
    if outs:
        return pa.concat_tables(outs)
    return out_schema.empty_table()


def _read_frame(stream) -> bytes:
    hdr = stream.read(4)
    if len(hdr) < 4:
        raise EOFError
    n = int.from_bytes(hdr, "big")
    buf = stream.read(n)
    if len(buf) < n:
        raise EOFError
    return buf


def _write_frame(stream, payload: bytes) -> None:
    stream.write(len(payload).to_bytes(4, "big"))
    stream.write(payload)
    stream.flush()


def main() -> None:
    """Serve length-prefixed frames over stdin/stdout until EOF (the
    reference's worker speaks the same framed-stream shape over its
    socket, GpuArrowPythonRunner:353). Frame (engine->worker): pickle of
    (mode, payload, ipc_bytes); reply: pickle of ('ok', ipc_bytes) or
    ('err', traceback_string). ``payload`` carries cloudpickled
    functions plus an Arrow-IPC-encoded OUTPUT schema (an empty table —
    the IPC stream is the one type encoding both sides already speak)."""
    import pickle
    import sys

    import cloudpickle

    rd = sys.stdin.buffer
    # claim fd 1: anything the UDF prints must not corrupt the frame
    # stream (Spark's worker redirects the same way)
    wr = sys.stdout.buffer
    sys.stdout = sys.stderr
    while True:
        try:
            msg = _read_frame(rd)
        except EOFError:
            return
        try:
            mode, payload, ipc = pickle.loads(msg)
            tbl = _read_table(ipc)
            if mode == "scalar":
                fn_blobs, arg_idxs, schema_ipc = payload
                fns = [cloudpickle.loads(b) for b in fn_blobs]
                out_schema = _read_table(schema_ipc).schema
                out = _apply_scalar(fns, arg_idxs, tbl, out_schema)
            elif mode == "map":
                fn_blob, schema_ipc = payload
                fn = cloudpickle.loads(fn_blob)
                out_schema = _read_table(schema_ipc).schema
                out = _apply_map(fn, tbl, out_schema)
            else:
                raise ValueError(f"unknown mode {mode!r}")
            _write_frame(wr, pickle.dumps(("ok", _write_table(out))))
        except Exception:
            _write_frame(wr, pickle.dumps(("err", traceback.format_exc())))


if __name__ == "__main__":
    main()
