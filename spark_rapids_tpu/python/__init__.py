"""Python-worker side module (the reference's python/ dir +
sql-plugin execution/python package): vectorized pandas UDFs evaluated
in a pool of WORKER PROCESSES that speak Arrow IPC with the engine
(GpuArrowEvalPythonExec.scala:487, GpuArrowPythonRunner:353 roles)."""
