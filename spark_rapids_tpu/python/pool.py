"""Python worker-process pool + the worker-concurrency throttle.

The reference bounds concurrent python workers with its own semaphore
distinct from the GPU one (python/PythonWorkerSemaphore.scala,
spark.rapids.python.concurrentPythonWorkers in PythonConfEntries.scala
:32); here the pool IS the throttle: at most ``concurrentPythonWorkers``
processes exist, and a task borrowing a worker blocks until one frees.
Workers are plain subprocesses (no fork of the engine process, so the
initialized TPU client never duplicates into a child) and are reused
across batches and queries until shutdown.
"""

from __future__ import annotations

import atexit
import pickle
import queue
import threading
from typing import Any, List, Optional, Tuple


class PythonWorkerError(RuntimeError):
    """A UDF raised in the worker; carries the remote traceback."""


class _Worker:
    """One worker subprocess; frames ride its stdin/stdout (the
    reference uses a socket — same framed-stream shape). A plain
    subprocess (not multiprocessing) so no engine/JAX state leaks into
    the child and no __main__ re-import happens."""

    def __init__(self):
        import os
        import subprocess
        import sys
        repo_root = os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))))
        env = dict(os.environ)
        env["PYTHONPATH"] = repo_root + os.pathsep + env.get(
            "PYTHONPATH", "")
        env["JAX_PLATFORMS"] = "cpu"  # the worker never touches devices
        self.proc = subprocess.Popen(
            [sys.executable, "-m", "spark_rapids_tpu.python.worker"],
            stdin=subprocess.PIPE, stdout=subprocess.PIPE, env=env)

    def request(self, mode: str, payload: Tuple, ipc: bytes) -> bytes:
        from spark_rapids_tpu.python.worker import (_read_frame,
                                                    _write_frame)
        _write_frame(self.proc.stdin, pickle.dumps((mode, payload, ipc)))
        status, body = pickle.loads(_read_frame(self.proc.stdout))
        if status != "ok":
            raise PythonWorkerError(
                f"pandas UDF failed in python worker:\n{body}")
        return body

    def close(self) -> None:
        try:
            self.proc.stdin.close()
        except Exception:
            pass
        if self.proc.poll() is None:
            self.proc.terminate()
        try:
            self.proc.wait(timeout=5)
        except Exception:
            self.proc.kill()


class PythonWorkerPool:
    """Lazy pool of at most ``size`` worker processes."""

    def __init__(self, size: int):
        self.size = max(1, int(size))
        self._idle: "queue.Queue[_Worker]" = queue.Queue()
        self._created = 0
        self._lock = threading.Lock()
        self._closed = False

    def run(self, mode: str, payload: Tuple, ipc: bytes) -> bytes:
        w = self._borrow()
        try:
            out = w.request(mode, payload, ipc)
        except PythonWorkerError:
            self._return(w)  # UDF error: worker loop is still healthy
            raise
        except Exception:
            # transport/process failure: replace the worker
            with self._lock:
                self._created -= 1
            w.close()
            raise
        self._return(w)
        return out

    def _return(self, w: "_Worker") -> None:
        """Idle-queue the worker, unless the pool was shut down while it
        was borrowed (resize/stop mid-query) — then it must die here or
        the subprocess leaks until interpreter exit."""
        with self._lock:
            closed = self._closed
            if closed:
                self._created = max(0, self._created - 1)
        if closed:
            w.close()
        else:
            self._idle.put(w)

    def _borrow(self) -> _Worker:
        while True:
            try:
                return self._idle.get_nowait()
            except queue.Empty:
                pass
            with self._lock:
                if self._closed:
                    raise RuntimeError("python worker pool is shut down")
                if self._created < self.size:
                    self._created += 1
                    try:
                        return _Worker()
                    except Exception:
                        self._created -= 1
                        raise
            try:
                # at capacity: wait for a free worker, but re-check
                # periodically (a crashed worker decrements _created and
                # never returns to the queue)
                return self._idle.get(timeout=5)
            except queue.Empty:
                continue

    def shutdown(self) -> None:
        with self._lock:
            self._closed = True
            n = self._created
            self._created = 0
        for _ in range(n):
            try:
                w = self._idle.get_nowait()
            except queue.Empty:
                break
            w.close()


_POOL: Optional[PythonWorkerPool] = None
_POOL_LOCK = threading.Lock()


def get_worker_pool(conf) -> PythonWorkerPool:
    from spark_rapids_tpu.conf import CONCURRENT_PYTHON_WORKERS
    # clamp BEFORE the staleness compare: an unclamped 0 would mismatch
    # the pool's clamped size forever and churn pools mid-query
    size = max(1, int(conf.get(CONCURRENT_PYTHON_WORKERS)))
    global _POOL
    with _POOL_LOCK:
        if _POOL is None or _POOL.size != size:
            if _POOL is not None:
                _POOL.shutdown()
            _POOL = PythonWorkerPool(size)
        return _POOL


def shutdown_worker_pool() -> None:
    global _POOL
    with _POOL_LOCK:
        if _POOL is not None:
            _POOL.shutdown()
            _POOL = None


atexit.register(shutdown_worker_pool)
