"""Typed configuration registry.

Equivalent of the reference's RapidsConf (sql-plugin RapidsConf.scala:301-1400):
a DSL of typed config entries under ``spark.rapids.*`` with docs, defaults,
startup-vs-runtime distinction, and markdown doc generation
(RapidsConf.scala's `help`/docs generation for docs/configs.md).

Per-operator enable keys (``spark.rapids.sql.exec.<Op>``,
``spark.rapids.sql.expression.<Expr>``) are auto-derived by the rule registry
in overrides.py, mirroring ReplacementRule.confKey (GpuOverrides.scala:147).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional


@dataclass
class ConfEntry:
    """One typed config entry. Mirrors RapidsConf's ConfEntry builders."""

    key: str
    doc: str
    default: Any
    converter: Callable[[str], Any]
    is_startup: bool = False
    is_internal: bool = False

    def get(self, conf: Dict[str, str]) -> Any:
        raw = conf.get(self.key)
        if raw is None:
            return self.default
        if isinstance(raw, str):
            return self.converter(raw)
        return raw


_REGISTRY: Dict[str, ConfEntry] = {}


def _to_bool(s: str) -> bool:
    return s.strip().lower() in ("true", "1", "yes")


class _Builder:
    """conf("key").doc(...).booleanConf.createWithDefault(x) style DSL
    (RapidsConf.scala:103-240)."""

    def __init__(self, key: str):
        self._key = key
        self._doc = ""
        self._startup = False
        self._internal = False

    def doc(self, text: str) -> "_Builder":
        self._doc = text
        return self

    def startup_only(self) -> "_Builder":
        self._startup = True
        return self

    def internal(self) -> "_Builder":
        self._internal = True
        return self

    def _create(self, default: Any, conv: Callable[[str], Any]) -> ConfEntry:
        e = ConfEntry(self._key, self._doc, default, conv, self._startup,
                      self._internal)
        if self._key in _REGISTRY:
            raise ValueError(f"duplicate conf key {self._key}")
        _REGISTRY[self._key] = e
        return e

    def boolean(self, default: bool) -> ConfEntry:
        return self._create(default, _to_bool)

    def integer(self, default: int) -> ConfEntry:
        return self._create(default, int)

    def long(self, default: int) -> ConfEntry:
        return self._create(default, int)

    def double(self, default: float) -> ConfEntry:
        return self._create(default, float)

    def string(self, default: Optional[str]) -> ConfEntry:
        return self._create(default, str)

    def bytes(self, default: int) -> ConfEntry:
        return self._create(default, parse_bytes)


def conf(key: str) -> _Builder:
    return _Builder(key)


def parse_bytes(s: str) -> int:
    """Parse '512m', '16g' style byte sizes (ConfHelper byteFromString)."""
    s = s.strip().lower()
    mult = 1
    for suffix, m in (("k", 1 << 10), ("m", 1 << 20), ("g", 1 << 30),
                      ("t", 1 << 40), ("b", 1)):
        if s.endswith(suffix):
            mult = m
            s = s[: -len(suffix)]
            break
    return int(float(s) * mult)


# ---------------------------------------------------------------------------
# Core entries (subset of the reference's 122 spark.rapids.* keys;
# RapidsConf.scala:301 onward). Grown as features land.
# ---------------------------------------------------------------------------

SQL_ENABLED = conf("spark.rapids.sql.enabled").doc(
    "Enable (true) or disable (false) TPU acceleration of SQL plans. "
    "(RapidsConf.scala SQL_ENABLED)").boolean(True)

EXPLAIN = conf("spark.rapids.sql.explain").doc(
    "Explain why parts of a query were or were not placed on the TPU: "
    "NONE (silent), NOT_ON_TPU (print one line per operator/expression "
    "fallback with the reason and the offending expression subtree), or "
    "ALL (also list every operator that WILL run on TPU). NOT_ON_GPU is "
    "accepted as an alias of NOT_ON_TPU. The same report is aggregated "
    "per query into the profile artifact (spark.rapids.sql.profile.*) "
    "and the event log (GpuOverrides.scala:3609-3616).").string("NONE")

CONCURRENT_TPU_TASKS = conf("spark.rapids.sql.concurrentGpuTasks").doc(
    "Number of tasks that may use the TPU concurrently; bounds HBM pressure "
    "(GpuSemaphore.scala:27).").integer(2)

TASK_PARALLELISM = conf("spark.rapids.sql.taskParallelism").doc(
    "Driver-side partition-execution threads (the executor-cores "
    "analogue): partitions run concurrently so host syncs of one task "
    "overlap device compute of another; concurrentGpuTasks still bounds "
    "simultaneous device use. Default 1 (sequential); raise on real "
    "TPU backends where per-task host round trips dominate.").integer(1)

EVENT_LOG_DIR = conf("spark.rapids.sql.eventLog.dir").doc(
    "Directory for per-query JSON event logs (empty = disabled); the "
    "offline qualification/profiling tools read these "
    "(Qualification.scala:34 / Profiler.scala:31 data source).").string("")

SHUFFLE_MODE = conf("spark.rapids.shuffle.mode").doc(
    "Exchange transport: 'inprocess' (materialized partition lists, the "
    "JVM sort-shuffle analogue), 'ici' (HBM-resident all-to-all over "
    "the active jax device mesh — the RapidsShuffleManager/UCX "
    "replacement, GpuShuffleEnv.scala:26 role; activates a mesh over "
    "all visible devices at session start), or 'external' (SRTB-"
    "serialized partitions over a shared directory — the cross-process "
    "host-staged/DCN transport skeleton).").string("inprocess")

SHUFFLE_ICI_DEVICES = conf("spark.rapids.shuffle.ici.devices").doc(
    "Number of devices in the ICI shuffle mesh (0 = all visible "
    "devices).").integer(0)

AQE_ENABLED = conf("spark.sql.adaptive.enabled").doc(
    "Adaptive query execution v0: replan at exchange materialization "
    "using MEASURED output sizes - a shuffled hash join whose build "
    "side lands under the broadcast threshold flips to a broadcast-"
    "style join at runtime, and tiny exchange partitions coalesce "
    "toward the advisory size (GpuOverrides.scala:3550 "
    "GpuQueryStagePrepOverrides / GpuCustomShuffleReaderExec "
    "roles).").boolean(True)

AQE_ADVISORY_PARTITION_BYTES = conf(
    "spark.sql.adaptive.advisoryPartitionSizeInBytes").doc(
    "Target post-shuffle partition size for AQE partition coalescing "
    "(Spark's advisoryPartitionSizeInBytes).").bytes(64 << 20)

AUTO_BROADCAST_JOIN_THRESHOLD = conf(
    "spark.rapids.sql.autoBroadcastJoinThreshold").doc(
    "Maximum estimated build-side size in bytes for a join to use a "
    "broadcast exchange instead of a shuffled hash join; -1 disables "
    "broadcast selection (spark.sql.autoBroadcastJoinThreshold "
    "semantics; the reference consumes Spark's decision via "
    "GpuBroadcastHashJoinExec).").bytes(10 << 20)

ADAPTIVE_ENABLED = conf("spark.rapids.sql.adaptive.enabled").doc(
    "Adaptive query execution over MEASURED exchange statistics "
    "(docs/adaptive.md): every exchange materialization records exact "
    "per-partition byte/row counts, and before the probe side compiles "
    "the AQE pass may demote a shuffled hash join to broadcast "
    "(adaptive.autoBroadcastBytes), coalesce undersized partitions "
    "toward adaptive.targetPartitionBytes, or split skewed stream "
    "partitions above adaptive.skewFactor x the median. Results are "
    "bit-identical to the unadaptive plan. Composes with "
    "spark.sql.adaptive.enabled: BOTH must be on (turning either off "
    "disables every runtime replan).").boolean(True)

ADAPTIVE_AUTO_BROADCAST_BYTES = conf(
    "spark.rapids.sql.adaptive.autoBroadcastBytes").doc(
    "Runtime broadcast-demotion threshold: a shuffled hash join whose "
    "REALIZED build-side bytes (exchange stats, active-row refined) "
    "land at or under this flips to a broadcast-style join, bypassing "
    "the stream side's co-partitioning exchange. -1 inherits "
    "spark.rapids.sql.autoBroadcastJoinThreshold (docs/adaptive.md)."
    ).bytes(-1)

ADAPTIVE_TARGET_PARTITION_BYTES = conf(
    "spark.rapids.sql.adaptive.targetPartitionBytes").doc(
    "Target size AQE coalesces undersized exchange output partitions "
    "toward (fewer, fuller device programs). 0 inherits "
    "spark.sql.adaptive.advisoryPartitionSizeInBytes "
    "(docs/adaptive.md).").bytes(0)

ADAPTIVE_SKEW_FACTOR = conf("spark.rapids.sql.adaptive.skewFactor").doc(
    "Skewed-partition detection: a realized stream-side join partition "
    "larger than this factor times the median non-empty partition is "
    "split into sub-partitions (each re-joined against the same build "
    "partition) so one hot key stops serializing the probe stage and "
    "stops triggering OOM-retry. 0 disables skew splitting "
    "(docs/adaptive.md).").double(4.0)

BATCH_SIZE_BYTES = conf("spark.rapids.sql.batchSizeBytes").doc(
    "Target size in bytes of columnar batches fed to TPU operators "
    "(RapidsConf.scala GPU_BATCH_SIZE_BYTES).").bytes(128 << 20)

BATCH_SIZE_ROWS = conf("spark.rapids.sql.batchSizeRows").doc(
    "Target row capacity of a device columnar batch. Static XLA shapes are "
    "derived by bucketing row counts up to this ceiling.").integer(1 << 20)

MAX_READER_BATCH_SIZE_ROWS = conf(
    "spark.rapids.sql.reader.batchSizeRows").doc(
    "Soft cap on rows per batch produced by file readers "
    "(RapidsConf.scala MAX_READER_BATCH_SIZE_ROWS).").integer(1 << 20)

HAS_NANS = conf("spark.rapids.sql.hasNans").doc(
    "Assume floating point data may contain NaN; affects agg/join support "
    "(RapidsConf.scala HAS_NANS).").boolean(True)

ENABLE_FLOAT_AGG = conf("spark.rapids.sql.variableFloatAgg.enabled").doc(
    "Allow float aggregations whose result can differ from CPU due to "
    "ordering (RapidsConf.scala:557 defaults this off; opt-in only)."
    ).boolean(False)

INCOMPATIBLE_OPS = conf("spark.rapids.sql.incompatibleOps.enabled").doc(
    "Enable ops that are not 100%% compatible with Spark semantics "
    "(RapidsConf.scala INCOMPATIBLE_OPS).").boolean(False)

ANSI_ENABLED = conf("spark.sql.ansi.enabled").doc(
    "ANSI SQL mode: overflow/invalid-cast raise instead of null/wrap "
    "(Spark conf honored by the rewrite like GpuOverrides does).").boolean(False)

CASE_SENSITIVE = conf("spark.sql.caseSensitive").doc(
    "Case sensitivity of column resolution (Spark SQLConf).").boolean(False)

SESSION_TIMEZONE = conf("spark.sql.session.timeZone").doc(
    "Session timezone for timestamp/date expressions.").string("UTC")

SHUFFLE_PARTITIONS = conf("spark.sql.shuffle.partitions").doc(
    "Default partition count for exchanges (Spark SQLConf).").integer(8)

DEVICE_SHUFFLE_PARTITIONS = conf(
    "spark.rapids.sql.shuffle.devicePartitions").doc(
    "Partition count for DEVICE hash/range exchanges; 0 = auto (the "
    "active ICI mesh size, or 1 in-process). One chip executes all "
    "partitions' programs serially anyway, so extra in-process "
    "partitions only add split programs and count syncs — the AQE "
    "coalesce-shuffle-partitions decision made statically for the TPU "
    "(GpuShuffleExchangeExecBase partitioning role).").integer(0)

METRICS_LEVEL = conf("spark.rapids.sql.metrics.level").doc(
    "ESSENTIAL, MODERATE or DEBUG op metric verbosity "
    "(RapidsConf.scala:491, GpuExec.scala:17-103).").string("MODERATE")

CPU_RANGE_PARTITIONING = conf(
    "spark.rapids.sql.rangePartitioning.sampleOnCpu").internal().doc(
    "Sample range-partition bounds on CPU (GpuRangePartitioner).").boolean(True)

DEVICE_MEMORY_LIMIT = conf("spark.rapids.memory.tpu.poolSize").doc(
    "HBM budget (bytes) managed by the device store; 0 = 80%% of the "
    "device's reported memory (GpuDeviceManager.initializeRmm, "
    "GpuDeviceManager.scala:216).").startup_only().bytes(0)

HOST_SPILL_STORAGE_SIZE = conf("spark.rapids.memory.host.spillStorageSize").doc(
    "Bytes of host memory used to spill device batches before disk "
    "(RapidsConf.scala HOST_SPILL_STORAGE_SIZE).").startup_only().bytes(1 << 30)

SPILL_DIR = conf("spark.rapids.memory.spillDirectory").doc(
    "Directory for the disk spill tier (RapidsDiskStore).").string("/tmp/srt_spill")

MEMORY_DEBUG = conf("spark.rapids.memory.tpu.debug").doc(
    "Log device allocation/free events (RapidsConf.scala:307).").boolean(False)

DEVICE_BUDGET_BYTES = conf("spark.rapids.sql.memory.deviceBudgetBytes").doc(
    "Planned out-of-core budget in bytes: the working-set ceiling the "
    "memory oracle hands operators BEFORE they materialize, so a join "
    "build side or aggregation estimated over its budget share "
    "partitions/spills up front instead of riding the reactive "
    "OOM-retry protocol. 0 probes the device (80%% of reported HBM, "
    "the pool default); set low on CPU for deterministic out-of-core "
    "tests (docs/out_of_core.md).").bytes(0)

OUT_OF_CORE_ENABLED = conf("spark.rapids.sql.outOfCore.enabled").doc(
    "Planned out-of-core execution (docs/out_of_core.md): operators "
    "consult the memory budget oracle before materializing and choose "
    "a spill-friendly shape up front — partitioned hash join, "
    "bucketed aggregation, budget-capped exchange coalesce — keeping "
    "the OOM-retry protocol as a last-resort backstop instead of the "
    "steady-state execution mode. Results are bit-identical to the "
    "in-memory paths.").boolean(True)

OUT_OF_CORE_BUDGET_SHARE = conf("spark.rapids.sql.outOfCore.budgetShare").doc(
    "Fraction of the device budget one operator's working set may "
    "claim before the planned out-of-core tier engages (several "
    "operators hold batches concurrently under taskParallelism, so "
    "one operator never plans for the whole budget).").double(0.5)

OUT_OF_CORE_MAX_PARTITIONS = conf(
    "spark.rapids.sql.outOfCore.maxPartitions").doc(
    "Ceiling on the spill-backed partition count the budget oracle "
    "plans UP FRONT (pow2-rounded estimate/share). A partition that "
    "still overflows past the ceiling re-partitions recursively "
    "(bounded by outOfCore.maxRecursion) instead of planning "
    "thousands of tiny splits from a bad estimate.").integer(64)

OUT_OF_CORE_MAX_RECURSION = conf(
    "spark.rapids.sql.outOfCore.maxRecursion").doc(
    "Bound on recursive re-partitioning depth when a planned "
    "partition still overflows its budget share (each level doubles "
    "the partition modulus; pmod(hash, 2N) refines pmod(hash, N)). "
    "Past the bound the partition falls back to the OOM-retry "
    "backstop.").integer(3)

SHUFFLE_COMPRESSION_CODEC = conf("spark.rapids.shuffle.compression.codec").doc(
    "Codec for serialized batch payloads (disk spill tier and any "
    "host-staged shuffle leg): none, zlib or zstd "
    "(TableCompressionCodec framework analogue).").string("none")

ALLOW_DISABLE_ENTIRE_PLAN = conf(
    "spark.rapids.allowDisableEntirePlan").internal().doc(
    "Allow the rewrite to bail out entirely when the whole plan would fall "
    "back (GpuOverrides).").boolean(True)

CBO_ENABLED = conf("spark.rapids.sql.optimizer.enabled").doc(
    "Cost-based optimizer: revert subtrees to CPU when transition costs "
    "outweigh speedup (CostBasedOptimizer.scala:52). Off by default, as in "
    "the reference.").boolean(False)

TEST_FORCE_DEVICE = conf("spark.rapids.sql.test.forceDevice").internal().doc(
    "Testing: fail instead of falling back to CPU when an op is "
    "unsupported (integration test TEST_CONF analogue).").boolean(False)

UDF_COMPILER_ENABLED = conf("spark.rapids.sql.udfCompiler.enabled").doc(
    "Compile Python lambda UDFs to Catalyst-style expressions "
    "(udf-compiler/ Plugin.scala:27-37).").boolean(False)

PARQUET_READER_TYPE = conf("spark.rapids.sql.format.parquet.reader.type").doc(
    "PERFILE, MULTITHREADED or COALESCING parquet reader strategy "
    "(RapidsConf.scala:719-733).").string("MULTITHREADED")

CONCURRENT_PYTHON_WORKERS = conf(
    "spark.rapids.python.concurrentPythonWorkers").doc(
    "Max concurrent python worker processes for pandas UDFs "
    "(PythonConfEntries.scala:32 twin; the pool is the throttle the "
    "reference implements as PythonWorkerSemaphore).").integer(2)

MULTITHREADED_READ_NUM_THREADS = conf(
    "spark.rapids.sql.format.parquet.multiThreadedRead.numThreads").doc(
    "Thread pool size for the multithreaded reader "
    "(GpuMultiFileReader.scala:300).").integer(8)

STAGE_FUSION_ENABLED = conf("spark.rapids.sql.stageFusion.enabled").doc(
    "Fuse maximal linear chains of per-batch device operators "
    "(filter -> project -> partial hash-aggregate update) into ONE "
    "jitted XLA program per batch (TpuFusedStageExec) — the whole-"
    "stage-codegen / GpuTieredProject analogue. Cuts per-operator "
    "dispatch and intermediate HBM materialization; results are "
    "bit-identical to the unfused plan. Per-operator metrics still "
    "report: fused nodes fan updates back to their constituent "
    "execs (see docs/fusion.md).").boolean(True)

STAGE_FUSION_MAX_IN_FLIGHT = conf(
    "spark.rapids.sql.stageFusion.maxInFlight").doc(
    "Async pipeline window of a fused stage: how many batches may be "
    "in flight (dispatched to the device but not yet yielded "
    "downstream) at once. Batch k+1's dispatch overlaps batch k's "
    "device compute; the value bounds HBM held by outstanding "
    "batches. 1 = sequential per-batch draining.").integer(2)

MULTICHIP_SCAN_ENABLED = conf(
    "spark.rapids.sql.multichip.scan.enabled").doc(
    "Shard the SCAN itself across the active shuffle mesh: partition "
    "units (parquet row groups / orc stripes / files) are assigned "
    "round-robin-by-bytes to one reader stream per chip, and each "
    "stream's batches (encoded pages or decoded rows) upload directly "
    "to that chip's HBM — no gather to chip 0. Downstream per-batch "
    "stages (filter/project/partial aggregate, fused stages) then run "
    "data-parallel on each chip's resident batches, and the ICI "
    "exchange consumes them without a host-side stacking round trip "
    "(docs/multichip.md). Effective only while a multi-device mesh is "
    "active (spark.rapids.shuffle.mode=ici); single-device behavior "
    "and the CPU engine are unchanged and results are bit-identical."
    ).boolean(True)

MULTICHIP_SERIALIZE_SERVED = conf(
    "spark.rapids.sql.multichip.serializeServedQueries").doc(
    "Serialize ICI-mesh collective sections across concurrently served "
    "queries behind a per-process mesh mutex. Two concurrent XLA CPU "
    "collectives over one device set deadlock at rendezvous (the PR 13 "
    "soak-documented limit), so served sessions take the mutex around "
    "each mesh exchange by default — other queries keep executing "
    "their non-collective stages, and waiting queries remain "
    "cancellable. Non-served (single-user) sessions never contend and "
    "skip the mutex entirely. Disable only on runtimes with per-query "
    "collective isolation.").boolean(True)

RETRY_MAX_RETRIES = conf("spark.rapids.sql.retry.maxRetries").doc(
    "Maximum OOM retries of one device allocation/operation before the "
    "failure escalates (split-and-retry where the operator supports "
    "splitting its input, abort otherwise). Each retry spills the "
    "device store down and backs off exponentially "
    "(RmmRapidsRetryIterator.scala:243 withRetry role).").integer(3)

RETRY_BACKOFF_MS = conf("spark.rapids.sql.retry.backoffMs").doc(
    "Base backoff in milliseconds between OOM retries; doubles per "
    "attempt up to spark.rapids.sql.retry.maxBackoffMs. The block time "
    "is reported as the retryBlockTime metric.").integer(1)

RETRY_MAX_BACKOFF_MS = conf("spark.rapids.sql.retry.maxBackoffMs").doc(
    "Upper bound in milliseconds on the exponential OOM-retry "
    "backoff.").integer(100)

READER_MAX_RETRIES = conf("spark.rapids.sql.reader.maxRetries").doc(
    "Maximum retries of a transient IO error in the file readers "
    "(PERFILE / MULTITHREADED / COALESCING and the mesh-sharded "
    "streams); the original error re-raises after exhaustion.").integer(3)

READER_RETRY_BACKOFF_MS = conf("spark.rapids.sql.reader.retryBackoffMs").doc(
    "Base backoff in milliseconds between reader IO retries; doubles "
    "per attempt (bounded at 1s).").integer(5)

INJECT_OOM = conf("spark.rapids.sql.test.injectOOM").internal().doc(
    "Testing: deterministic synthetic-OOM schedule for the retry "
    "framework. 'N' = every Nth wrapped allocation throws TpuRetryOOM; "
    "'N:K' = K consecutive failures at every Nth allocation; "
    "'split:N' = TpuSplitAndRetryOOM every Nth; 'seed:S:P' = seeded "
    "random with probability P; 'site:NAME:SPEC' scopes any form to "
    "the named site — site:cancel counts lifecycle checkpoints and "
    "injects cooperative cancels, site:budget makes every Nth "
    "budget-oracle query report half the real headroom "
    "(docs/robustness.md site catalog).").string("")

INJECT_IO_ERROR = conf("spark.rapids.sql.test.injectIOError").internal().doc(
    "Testing: deterministic synthetic IO-error schedule for the file "
    "readers; same 'N' / 'N:K' grammar as injectOOM.").string("")

INJECT_CHIP_FAILURE = conf(
    "spark.rapids.sql.test.injectChipFailure").internal().doc(
    "Testing: comma-separated mesh chip ids whose dispatches "
    "persistently fail; the mesh degrades to the surviving chips "
    "(docs/robustness.md degradation ladder).").string("")

PLAN_CACHE_ENABLED = conf("spark.rapids.sql.planCache.enabled").doc(
    "Cross-query plan-rewrite cache: the finished physical plan "
    "(Planner + TpuOverrides rewrite + CBO + whole-stage fusion) is "
    "cached per normalized logical-plan signature, and repeated query "
    "shapes clone the cached template instead of re-running the "
    "rewrite pipeline. Results are bit-identical (each execution gets "
    "fresh operator instances and metric registries); the cache is the "
    "bounded LRU 'planRewrite' in the jit-cache registry. Off by "
    "default; the query server enables it for its sessions "
    "(docs/serving.md).").boolean(False)

RESULT_CACHE_ENABLED = conf("spark.rapids.sql.resultCache.enabled").doc(
    "Serve-tier result cache (docs/caching.md): the final Arrow IPC "
    "payload of a finished query is kept in a bounded LRU keyed on "
    "(plan-signature digest, extracted literal bindings, input-file "
    "fingerprint set). A hit is detected BEFORE admission and served "
    "straight from memory — zero device work, zero queue wait, zero "
    "admission slot — and any input-file fingerprint mismatch "
    "(path/size/mtime) invalidates the entry and falls through to "
    "normal execution, so served bytes are always bit-identical to a "
    "fresh run. Off by default.").boolean(False)

RESULT_CACHE_MAX_ENTRIES = conf(
    "spark.rapids.sql.resultCache.maxEntries").doc(
    "Bound on distinct cached results; least-recently-served entries "
    "are evicted past it (docs/caching.md).").integer(256)

RESULT_CACHE_MAX_BYTES = conf(
    "spark.rapids.sql.resultCache.maxBytes").doc(
    "Bound on total cached Arrow IPC payload bytes held by the result "
    "cache; LRU eviction keeps the sum under it (docs/caching.md)."
    ).integer(256 << 20)

SUBPLAN_CACHE_ENABLED = conf(
    "spark.rapids.sql.subplanCache.enabled").doc(
    "Cross-query broadcast build-table cache (docs/caching.md): the "
    "device-resident build side of a broadcast hash join is kept keyed "
    "on the build subtree's structural signature + its input-file "
    "fingerprint set and reused across queries and tenants, lifting "
    "the reference's within-plan GpuBroadcastExchangeExec reuse across "
    "query boundaries. Entries register in the device store as "
    "evict-FIRST: pool pressure drops cached build tables before any "
    "live query's batches spill. Fingerprints are re-checked on every "
    "reuse; a mismatch drops the entry and rebuilds. Off by default."
    ).boolean(False)

SUBPLAN_CACHE_MAX_ENTRIES = conf(
    "spark.rapids.sql.subplanCache.maxEntries").doc(
    "Bound on distinct cached build tables; least-recently-reused "
    "entries are dropped past it (docs/caching.md).").integer(32)

SUBPLAN_CACHE_MAX_BYTES = conf(
    "spark.rapids.sql.subplanCache.maxBytes").doc(
    "Bound on total device bytes the subplan cache may pin; LRU drops "
    "keep the sum under it. The device store may additionally drop "
    "entries at any moment under pool pressure (docs/caching.md)."
    ).integer(64 << 20)

SERVE_MAX_CONCURRENT = conf(
    "spark.rapids.sql.serve.maxConcurrentQueries").doc(
    "Queries the server executes simultaneously across all tenants; "
    "admitted queries still contend on concurrentGpuTasks for actual "
    "device access — this bounds whole-query concurrency the way "
    "GpuSemaphore bounds task concurrency (docs/serving.md)."
    ).integer(4)

SERVE_MAX_QUEUED = conf("spark.rapids.sql.serve.maxQueued").doc(
    "Bound on queries waiting for admission; a request arriving with "
    "the queue full is REJECTED immediately (backpressure — the client "
    "sees status=rejected and retries with its own policy) instead of "
    "growing an unbounded queue (docs/serving.md).").integer(32)

SERVE_MAX_PER_TENANT = conf(
    "spark.rapids.sql.serve.maxConcurrentPerTenant").doc(
    "Per-tenant in-flight query limit: one tenant cannot occupy every "
    "execution slot no matter how fast it submits (docs/serving.md)."
    ).integer(2)

SERVE_FAIR_SHARE_FACTOR = conf(
    "spark.rapids.sql.serve.fairShareFactor").doc(
    "Fair-share HBM arbitration: a tenant whose live device-store "
    "bytes exceed factor * (pool budget / live tenants) is over share "
    "— its batches spill FIRST under pool pressure (billing the spill "
    "to the offender, not an LRU victim) and its queued queries are "
    "passed over while other tenants wait (docs/serving.md)."
    ).double(1.5)

SERVE_BATCH_FUSION_ENABLED = conf(
    "spark.rapids.sql.serve.batchFusion.enabled").doc(
    "Same-signature batch fusion (docs/adaptive.md): concurrent "
    "queries whose SQL differs only in literal bindings are collected "
    "within batchFusion.windowMs and executed under ONE admission "
    "slot; identical texts share a single execution, distinct "
    "bindings ride the same cached plan template and compiled device "
    "programs back-to-back. Per-tenant results stay bit-identical and "
    "each member bills its own tenant ledger and queue wait; the "
    "window engages only while the server is saturated, so an idle "
    "server adds no latency.").boolean(True)

SERVE_BATCH_FUSION_WINDOW_MS = conf(
    "spark.rapids.sql.serve.batchFusion.windowMs").doc(
    "Collection window for batch fusion: the first query of a shape "
    "holds its batch open this long (only while the server is "
    "saturated) so same-shape peers can join before execution "
    "(docs/adaptive.md).").integer(10)

SERVE_BATCH_FUSION_MAX_BATCH = conf(
    "spark.rapids.sql.serve.batchFusion.maxBatch").doc(
    "Maximum member queries one fused batch accepts; the next arrival "
    "opens a fresh batch (docs/adaptive.md).").integer(16)

SERVE_HOST = conf("spark.rapids.sql.serve.host").doc(
    "Interface the query server binds (local serving; the cross-host "
    "tier is ROADMAP item 5).").string("127.0.0.1")

SERVE_PORT = conf("spark.rapids.sql.serve.port").doc(
    "Port the query server binds (0 = ephemeral; the bound port is "
    "printed/returned for clients).").integer(0)

SERVE_QUERY_TIMEOUT_MS = conf(
    "spark.rapids.sql.serve.queryTimeoutMs").doc(
    "Per-query deadline in milliseconds, enforced from request "
    "admission (queue wait counts against the budget): a query that "
    "exceeds it is cooperatively cancelled at the engine's lifecycle "
    "checkpoints and returns status=cancelled (reason=deadline) on "
    "the wire. 0 disables. Per-tenant override: set "
    "spark.rapids.sql.serve.queryTimeoutMs.<tenant>; a client may "
    "TIGHTEN the deadline (or set one where the operator set none) "
    "per request via the sql header's timeoutMs field — it can never "
    "loosen or disable an operator-enforced bound "
    "(docs/serving.md 'Query lifecycle').").integer(0)

SERVE_WATCHDOG_FACTOR = conf(
    "spark.rapids.sql.serve.watchdogFactor").doc(
    "Stuck-query watchdog: a running query whose elapsed wall exceeds "
    "this factor times its plan-cache signature's observed p99 wall "
    "fires a stuckQuery slow-query bundle through the telemetry "
    "trigger engine (and, with serve.watchdogCancel, a cooperative "
    "cancel). Signatures with fewer than 5 observed walls are never "
    "flagged. 0 disables (docs/serving.md 'Query lifecycle')."
    ).double(0.0)

SERVE_WATCHDOG_CANCEL = conf(
    "spark.rapids.sql.serve.watchdogCancel").doc(
    "When the stuck-query watchdog flags a query, also CANCEL it "
    "(reason=watchdog) instead of only emitting the stuckQuery "
    "bundle. Off by default — observation first, enforcement opt-in "
    "(docs/serving.md 'Query lifecycle').").boolean(False)

SERVE_QUARANTINE_THRESHOLD = conf(
    "spark.rapids.sql.serve.quarantineThreshold").doc(
    "Poison-query quarantine: a plan-cache signature that fails this "
    "many CONSECUTIVE times with a runtime-fatal error (cancellations "
    "and deadline timeouts never count) is blacklisted — further "
    "submissions fail fast with status=quarantined before touching "
    "the device, instead of re-wedging the runtime. One success "
    "clears the streak; a restart clears the blacklist. 0 disables "
    "(docs/serving.md 'Query lifecycle').").integer(0)

SERVE_DRAIN_TIMEOUT_MS = conf(
    "spark.rapids.sql.serve.drainTimeoutMs").doc(
    "Graceful-drain deadline for `tools serve` shutdown (SIGTERM or "
    "the shutdown verb): admission stops immediately, in-flight "
    "queries get this long to finish, then stragglers are "
    "cooperatively cancelled (reason=shutdown) so the process exits "
    "with the store empty and all permits restored "
    "(docs/serving.md 'Query lifecycle').").integer(60000)

SERVE_TENANT_ID = conf("spark.rapids.sql.serve.tenantId").internal().doc(
    "Session-scoped tenant id the server sets on each tenant's "
    "session; threads through trace files, event-log lines, profile "
    "artifacts, and the store's per-tenant HBM ledger.").string("")

TELEMETRY_DIR = conf("spark.rapids.sql.telemetry.dir").doc(
    "Directory for slow-query bundles emitted by the telemetry trigger "
    "engine (bundle-<pid>-<n>-<trigger>.json + the flight-recorder "
    "dump trace-ring-<pid>-<n>.json it references; "
    "docs/observability.md 'Live telemetry').").string("/tmp/srt_telemetry")

TELEMETRY_SLOW_QUERY_MS = conf("spark.rapids.sql.telemetry.slowQueryMs").doc(
    "Slow-query trigger: a query whose wall exceeds this many "
    "milliseconds emits a slow-query bundle (flight-recorder dump + "
    "profile artifact path + server stats + the condition) into "
    "spark.rapids.sql.telemetry.dir. 0 disables the trigger."
    ).integer(0)

TELEMETRY_RETRY_COUNT_THRESHOLD = conf(
    "spark.rapids.sql.telemetry.retryCountThreshold").doc(
    "Per-query retry trigger: a query whose plan accumulates MORE than "
    "this many retryCount (OOM retries) emits a slow-query bundle. "
    "0 disables the trigger.").integer(0)

TELEMETRY_KERNEL_FALLBACK_THRESHOLD = conf(
    "spark.rapids.sql.telemetry.kernelFallbackThreshold").doc(
    "Per-query kernel-fallback trigger: a query whose plan accumulates "
    "MORE than this many kernelFallbacks.* (Pallas kernel calls that "
    "fell back to the XLA-op oracle) emits a slow-query bundle. "
    "0 disables the trigger.").integer(0)

TELEMETRY_RETRY_STORM_THRESHOLD = conf(
    "spark.rapids.sql.telemetry.retryStormThreshold").doc(
    "Process-wide retry-storm trigger: MORE than this many OOM retries "
    "inside one 60-second window emits a retryStorm bundle (evaluated "
    "at retry time, not query end — a storm is visible while the "
    "storm is happening). 0 disables the trigger.").integer(0)

TELEMETRY_HBM_WATERMARK = conf(
    "spark.rapids.sql.telemetry.hbmWatermark").doc(
    "HBM-occupancy trigger: a device-store sample whose live bytes "
    "exceed this fraction of the pool budget emits an hbmWatermark "
    "bundle (evaluated at every store transition). 0 disables the "
    "trigger. Arm it via any session that sets a telemetry conf "
    "(triggers.configure).").double(0.0)

TELEMETRY_QUEUE_WATERMARK = conf(
    "spark.rapids.sql.telemetry.queueWatermark").doc(
    "Admission-saturation trigger: an admission queue whose depth "
    "exceeds this fraction of serve.maxQueued emits a queueSaturation "
    "bundle (evaluated at every enqueue). 0 disables the trigger."
    ).double(0.0)

TELEMETRY_MIN_INTERVAL_S = conf(
    "spark.rapids.sql.telemetry.triggerMinIntervalS").doc(
    "Per-trigger rate limit: after a trigger fires, further firings of "
    "the SAME trigger inside this many seconds are counted "
    "(rateLimited in the engine stats, srt_telemetry_triggers_rate_"
    "limited_total on the endpoint) but emit no bundle — a storm "
    "cannot flood the disk.").double(60.0)

TELEMETRY_MAX_BUNDLES = conf(
    "spark.rapids.sql.telemetry.maxBundles").doc(
    "Retention bound on telemetry artifacts in "
    "spark.rapids.sql.telemetry.dir: trigger bundles "
    "(bundle-*.json) and flight-recorder dumps (trace-ring-*.json) "
    "beyond this count are pruned OLDEST-FIRST by the bundle-worker "
    "thread after each write (never under a hot-path lock). Pruned "
    "counts show in the engine stats, the server stats telemetry "
    "section, and srt_telemetry_bundles_pruned_total. 0 disables "
    "count-based retention.").integer(256)

TELEMETRY_MAX_BUNDLE_BYTES = conf(
    "spark.rapids.sql.telemetry.maxBundleBytes").doc(
    "Retention bound on the TOTAL bytes of telemetry artifacts "
    "(bundles + ring dumps) in spark.rapids.sql.telemetry.dir, pruned "
    "oldest-first alongside spark.rapids.sql.telemetry.maxBundles. "
    "0 disables byte-based retention.").bytes(0)

TELEMETRY_HISTORY_DIR = conf(
    "spark.rapids.sql.telemetry.history.dir").doc(
    "Directory of the persistent query-history store: one compact "
    "JSONL record per finished query (signature, tenant, terminal "
    "status/reason, wall/queue-wait, retry/spill/kernel/jit counters, "
    "fallback coverage, peak HBM, artifact paths), appended at query "
    "close by session.execute_plan and the query server, rotated into "
    "bounded segments and compacted by telemetry.history.maxBytes / "
    "maxAgeDays. The store is the cross-run performance memory behind "
    "server warm-start, SLO tracking, `tools history`, and `tools "
    "doctor` (docs/observability.md 'Query history'). Empty = "
    "disabled.").string("")

TELEMETRY_HISTORY_MAX_BYTES = conf(
    "spark.rapids.sql.telemetry.history.maxBytes").doc(
    "Size bound on the query-history store: segments are rotated at a "
    "fraction of this and the OLDEST whole segments are deleted once "
    "the store's total size exceeds it (each record is one JSON line, "
    "so compaction never truncates a record mid-line)."
    ).bytes(64 << 20)

TELEMETRY_HISTORY_MAX_AGE_DAYS = conf(
    "spark.rapids.sql.telemetry.history.maxAgeDays").doc(
    "Age bound on the query-history store: a rotated segment whose "
    "newest record is older than this many days is deleted at "
    "compaction. 0 disables age-based compaction.").double(14.0)

TELEMETRY_HISTORY_WARM_START = conf(
    "spark.rapids.sql.telemetry.history.warmStart").doc(
    "Seed the serving tier's lifecycle state from the query-history "
    "store at server start: per-signature wall reservoirs (so the "
    "stuck-query watchdog has a p99 from query one after a restart) "
    "and consecutive-failure streaks / quarantine blacklisting (so a "
    "poison signature stays fail-fast across restarts). Effective "
    "only when spark.rapids.sql.telemetry.history.dir is set "
    "(docs/observability.md 'Query history').").boolean(True)

SERVE_SLO_P99_MS = conf("spark.rapids.sql.serve.slo.p99Ms").doc(
    "Per-tenant latency objective: the tenant's observed p99 wall over "
    "the spark.rapids.sql.serve.slo.window seconds of query history "
    "must stay under this many milliseconds. Evaluated over the "
    "persistent history store (telemetry.history.dir must be set), "
    "exported as the srt_slo_* Prometheus families, and — when the "
    "observed p99 exceeds the objective — fires a rate-limited "
    "sloBurn bundle through the telemetry trigger engine. Per-tenant "
    "override: spark.rapids.sql.serve.slo.p99Ms.<tenant>. 0 disables "
    "(docs/observability.md 'SLO tracking').").integer(0)

SERVE_SLO_WINDOW = conf("spark.rapids.sql.serve.slo.window").doc(
    "SLO evaluation window in seconds: objectives under "
    "spark.rapids.sql.serve.slo.p99Ms are checked against the query "
    "history's finished records newer than this."
    ).double(3600.0)

SERVE_TUNING_ENABLED = conf("spark.rapids.sql.serve.tuning.enabled").doc(
    "History-driven feedback control (docs/tuning.md): the server "
    "embeds a TuningController that scores the query history through "
    "the signature-aggregate + doctor verdict pipeline at start and "
    "on a periodic tick, and applies bounded, logged, reversible "
    "per-signature actions from the declared ACTION_CATALOG — cache "
    "pre-warm for compile storms, admission narrowing / out-of-core "
    "seeding for retry-spill shapes, culprit-kernel fallback flips, "
    "and per-tenant admission weight shifts for SLO burn. Every "
    "action lands in the history store as a tuning record, exports "
    "as srt_tuning_* Prometheus families, and auto-reverts when the "
    "post-action baseline regresses (tools tuning inspects/pins/"
    "reverts). Requires telemetry.history.dir; off by default."
    ).boolean(False)

SERVE_TUNING_INTERVAL_S = conf(
    "spark.rapids.sql.serve.tuning.intervalS").doc(
    "Seconds between TuningController scan ticks (history scoring + "
    "action application + guardrail evaluation). The start-of-server "
    "scan always runs regardless (docs/tuning.md).").double(30.0)

SERVE_TUNING_MAX_ACTIONS = conf(
    "spark.rapids.sql.serve.tuning.maxActionsPerTick").doc(
    "Ceiling on NEW tuning actions one scan tick may apply — the "
    "controller converges knob by knob instead of rewriting the whole "
    "server's posture from one noisy window (docs/tuning.md)."
    ).integer(4)

SERVE_TUNING_GUARD_WINDOW = conf(
    "spark.rapids.sql.serve.tuning.guardWindowQueries").doc(
    "Guardrail sample window: an applied action is judged once this "
    "many post-action finished records exist for its scope — p50/p99 "
    "over the window diffed against the pre-action baseline captured "
    "in the action's evidence; a regression past "
    "serve.tuning.revertThreshold auto-reverts the action "
    "(docs/tuning.md).").integer(5)

SERVE_TUNING_REVERT_THRESHOLD = conf(
    "spark.rapids.sql.serve.tuning.revertThreshold").doc(
    "Relative p50/p99 regression past which the guardrail reverts an "
    "applied action — the same relative-change discipline tools "
    "bench-diff gates on ((baseline - candidate) / baseline for "
    "lower-is-better metrics; docs/tuning.md).").double(0.25)

SERVE_TUNING_MAX_PREWARM = conf(
    "spark.rapids.sql.serve.tuning.maxPrewarm").doc(
    "Ceiling on the signatures the compile-storm pre-warm action may "
    "hold in its replay ledger (and therefore on the planning replays "
    "a server start performs) — startup cost stays bounded no matter "
    "how storm-prone the history looks (docs/tuning.md).").integer(8)

PARQUET_DEVICE_DECODE = conf(
    "spark.rapids.sql.format.parquet.deviceDecode.enabled").doc(
    "Decode Parquet pages ON DEVICE (the default scan path, the "
    "cuDF-decode role of GpuParquetScanBase.scala:82): host threads "
    "read raw column-chunk bytes, decompress pages and parse headers "
    "only; bit-unpacking of RLE/bit-packed runs, dictionary gather, "
    "PLAIN fixed-width reinterpret, string offset+bytes assembly "
    "(segmented prefix-sum over the lengths + bytes gather), "
    "DELTA_BINARY_PACKED reconstruction, BYTE_STREAM_SPLIT "
    "reinterleave and definition-level expansion run as XLA kernels. "
    "Columns with genuinely unsupported shapes (nested, INT96, "
    "DELTA_BYTE_ARRAY) fall back per column to the pyarrow host "
    "decode; results are bit-identical either way. See "
    "docs/supported_ops.md for the encoding matrix and docs/scan.md "
    "for the async scan pipeline.").boolean(True)

PARQUET_DEVICE_DECODE_BYTE_ARRAY = conf(
    "spark.rapids.sql.format.parquet.deviceDecode.byteArray.enabled"
    ).doc(
    "Device-decode PLAIN / DELTA_LENGTH byte-array (string/binary) "
    "pages: the host extracts only the per-value byte lengths; the "
    "offsets column is built ON DEVICE by a per-page segmented "
    "prefix-sum and the bytes column is gathered into the padded char "
    "matrix (SURVEY.md §7 hard part (c)). Off = those columns fall "
    "back to the pyarrow host decode (dictionary-encoded strings "
    "still device-decode).").boolean(True)

PARQUET_DEVICE_DECODE_DELTA = conf(
    "spark.rapids.sql.format.parquet.deviceDecode.delta.enabled").doc(
    "Device-decode DELTA_BINARY_PACKED (and the length half of "
    "DELTA_LENGTH_BYTE_ARRAY): the host parses block/miniblock "
    "headers only; bit-unpacking of the packed deltas and the "
    "prefix-sum reconstruction run on device. Off = DELTA_* columns "
    "fall back to the pyarrow host decode.").boolean(True)

PARQUET_DEVICE_DECODE_BSS = conf(
    "spark.rapids.sql.format.parquet.deviceDecode.byteStreamSplit."
    "enabled").doc(
    "Device-decode BYTE_STREAM_SPLIT pages (float/double/int32/int64): "
    "the byte-plane reinterleave is a strided device gather. Off = "
    "those columns fall back to the pyarrow host decode.").boolean(True)

KERNEL_ENABLED = conf("spark.rapids.sql.kernel.enabled").doc(
    "Master switch for the hand-written Pallas kernel tier "
    "(spark_rapids_tpu/kernels/): ops whose shape a kernel supports "
    "swap their stock XLA-op composition for the kernel behind the "
    "same JitCache keys, with automatic per-call fallback to the "
    "composition (the bit-identity oracle) on lowering/compile "
    "failure or hash-table overflow — counted as kernelFallbacks.* "
    "metrics. On backends without native Pallas lowering (CPU) the "
    "kernels run in interpreter mode so every kernel path stays "
    "exercised (docs/kernels.md).").boolean(True)

KERNEL_GROUPBY_HASH = conf(
    "spark.rapids.sql.kernel.groupbyHash.enabled").doc(
    "Single-pass open-addressed hash-table group-by kernel for the "
    "PARTIAL aggregation update (SUM/COUNT/MIN/MAX over fixed-width "
    "keys and values): replaces the lexsort + segmented-scan pipeline "
    "with one insert/combine pass over the batch. Batches with more "
    "distinct groups than kernel.groupbyHash.tableSlots overflow and "
    "re-run on the oracle composition (docs/kernels.md).").boolean(True)

KERNEL_GROUPBY_TABLE_SLOTS = conf(
    "spark.rapids.sql.kernel.groupbyHash.tableSlots").doc(
    "Hash-table capacity (slots, rounded up to a power of two) of the "
    "group-by kernel. Bounds the distinct groups one batch may "
    "produce through the kernel; beyond it the batch overflows to the "
    "oracle composition (kernelFallbacks.groupbyHash). Sized for "
    "low-cardinality aggregations (the q1 shape); raise it for "
    "wider group counts at the cost of on-chip table state."
    ).integer(1024)

KERNEL_JOIN_PROBE = conf(
    "spark.rapids.sql.kernel.joinProbe.enabled").doc(
    "Hash-table build/probe kernel for the join gather map: the build "
    "side inserts into an open-addressed table (first-occurrence row "
    "per key), the stream side probes it — replacing the sort-based "
    "key plan for semi/anti joins and the certified-unique-build-key "
    "(FK) fast path. Applies when the build side fits "
    "kernel.joinProbe.maxBuildRows (docs/kernels.md).").boolean(True)

KERNEL_JOIN_MAX_BUILD_ROWS = conf(
    "spark.rapids.sql.kernel.joinProbe.maxBuildRows").doc(
    "Largest build-side row capacity the join probe kernel accepts; "
    "the table is sized at twice the capacity (load factor <= 0.5, so "
    "probe chains always terminate and overflow is impossible). "
    "Bigger build sides keep the sort-based oracle plan.").integer(8192)

KERNEL_MURMUR3 = conf("spark.rapids.sql.kernel.murmur3.enabled").doc(
    "Fused Murmur3 partition-hashing kernel: the per-column "
    "rotl/fmix chains of Spark's Murmur3_x86_32 fold in one pass over "
    "the row block instead of a chain of stock XLA ops. Bit-identical "
    "to ops/hashing.py (the same arithmetic runs inside the kernel); "
    "used by the in-process hash exchange (docs/kernels.md)."
    ).boolean(True)

KERNEL_DECODE_FUSED = conf(
    "spark.rapids.sql.kernel.decodeFused.enabled").doc(
    "Fused Parquet decode kernel: collapse the per-batch encoded-scan "
    "decode chain (RLE/bit-unpack, dictionary gather, definition-level "
    "validity expansion, byte-array offsets-from-lengths + char "
    "gather) into ONE Pallas kernel per (layout, capacity bucket), "
    "behind the same uploadDecode cache keys. The stock XLA "
    "composition stays the bit-identity oracle and the per-call "
    "fallback on any lowering/compile/dispatch failure "
    "(kernelFallbacks.decodeFused); host-decoded columns pass through "
    "outside the kernel untouched (docs/kernels.md).").boolean(True)

KERNEL_AUTOTUNE_ENABLED = conf(
    "spark.rapids.sql.kernel.autotune.enabled").doc(
    "Per-kernel parameter autotuner (docs/kernels.md): the first "
    "dispatch of a kernel at a new (kernel, shape bucket, device kind) "
    "sweeps a small bounded parameter grid (block shapes, tableSlots "
    "multiplier, char-gather chunking), validates every candidate "
    "against the kernel's oracle, and persists the winner in the "
    "crash-safe table under kernel.autotune.dir. Off (the default) = "
    "read-only: previously recorded winners still apply, but no sweep "
    "ever runs — production servers against a warmed table never "
    "re-tune.").boolean(False)

KERNEL_AUTOTUNE_DIR = conf("spark.rapids.sql.kernel.autotune.dir").doc(
    "Directory of the autotuner's persistent winner table "
    "(kernel-autotune.jsonl, append-only JSON lines next to the "
    "JitCache artifacts): loaded once per process at first use, so a "
    "second session against the same directory performs zero sweeps. "
    "Torn or garbage lines are skipped on load; an unreadable table "
    "falls back to default parameters. Empty = autotuning fully off "
    "(defaults everywhere).").string("")

KERNEL_AUTOTUNE_BUDGET_MS = conf(
    "spark.rapids.sql.kernel.autotune.budgetMs").doc(
    "Wall budget in milliseconds for ONE autotune sweep (one kernel at "
    "one shape bucket): candidate timing stops once the budget is "
    "spent and the best validated candidate so far wins. Bounds the "
    "cold-start cost a sweep can add to the first query at a new "
    "shape.").integer(2000)

PARQUET_DEVICE_DECODE_MAX_IN_FLIGHT = conf(
    "spark.rapids.sql.format.parquet.deviceDecode.maxInFlight").doc(
    "Scan upload pipeline depth: how many staged scan batches may have "
    "their raw-chunk upload in flight (device_put issued, decode "
    "program not yet dispatched) ahead of the consuming stage, per "
    "reader stream and per chip. A producer thread prefetches + packs "
    "batch k+1 while batch k's bytes move and batch k-1 computes, so "
    "the scan never idles a chip (docs/scan.md). 1 = upload-ahead off "
    "(still prefetch-threaded); 0 = fully synchronous scan uploads "
    "(the A/B baseline bench.py measures).").integer(2)


class TpuConf:
    """Bound view over a conf dict; the RapidsConf class equivalent.

    Usage: ``TpuConf({"spark.rapids.sql.enabled": "true"}).get(SQL_ENABLED)``
    or attribute-style helpers below.
    """

    def __init__(self, settings: Optional[Dict[str, Any]] = None):
        self.settings: Dict[str, Any] = dict(settings or {})

    def get(self, entry: ConfEntry) -> Any:
        return entry.get(self.settings)

    def get_key(self, key: str, default: Any = None) -> Any:
        e = _REGISTRY.get(key)
        if e is not None:
            return e.get(self.settings)
        return self.settings.get(key, default)

    def set(self, key: str, value: Any) -> None:
        self.settings[key] = value

    def is_op_enabled(self, conf_key: str, default: bool = True) -> bool:
        raw = self.settings.get(conf_key)
        if raw is None:
            return default
        return raw if isinstance(raw, bool) else _to_bool(str(raw))

    # Frequently used helpers
    @property
    def sql_enabled(self) -> bool:
        return self.get(SQL_ENABLED)

    @property
    def batch_size_rows(self) -> int:
        return self.get(BATCH_SIZE_ROWS)

    @property
    def batch_size_bytes(self) -> int:
        return self.get(BATCH_SIZE_BYTES)

    @property
    def ansi_enabled(self) -> bool:
        return self.get(ANSI_ENABLED)

    @property
    def shuffle_partitions(self) -> int:
        return int(self.get(SHUFFLE_PARTITIONS))

    @property
    def explain(self) -> str:
        return str(self.get(EXPLAIN)).upper()


def registered_entries() -> List[ConfEntry]:
    return list(_REGISTRY.values())


def generate_docs() -> str:
    """Markdown config table; the docs/configs.md generator equivalent
    (RapidsConf.scala `help`)."""
    lines = ["# spark-rapids-tpu configuration", "",
             "| Key | Default | Startup | Description |",
             "|---|---|---|---|"]
    for e in sorted(_REGISTRY.values(), key=lambda e: e.key):
        if e.is_internal:
            continue
        lines.append(
            f"| {e.key} | {e.default} | {e.is_startup} | {e.doc} |")
    return "\n".join(lines) + "\n"
