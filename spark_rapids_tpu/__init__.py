"""spark-rapids-tpu: a TPU-native accelerator for columnar SQL execution.

Built from scratch with the capabilities of NVIDIA's RAPIDS Accelerator for
Apache Spark (reference: /root/reference, spark-rapids 21.10): a physical-plan
rewrite engine that replaces supported operators/expressions with Tpu*Exec
nodes whose columnar batches are HBM-resident JAX arrays, with the kernel
library (the cuDF equivalent) implemented as XLA/Pallas programs, a tiered
HBM->host->disk spill framework in place of RMM, and an ICI/DCN all-to-all
shuffle in place of the UCX RapidsShuffleManager.

Because no JVM Spark is present in this environment, the package also ships
the host engine the plugin accelerates: a Catalyst-like DataFrame/SQL layer
(`spark_rapids_tpu.sql`) whose CPU physical operators implement Spark
semantics and serve both as the bit-identical comparison baseline and as the
per-operator fallback target (the reference's contract, README.md:15-16).

Layering mirrors SURVEY.md section 1:
  L7 plugin bootstrap      spark_rapids_tpu.plugin
  L6 plan rewrite          spark_rapids_tpu.{meta,typesig,overrides,transitions,cbo}
  L5 columnar operators    spark_rapids_tpu.exec
  L4 batch/row interchange spark_rapids_tpu.exec.transitions_exec
  L3 memory/spill          spark_rapids_tpu.memory
  L2 shuffle/communication spark_rapids_tpu.shuffle
  L1 kernel library        spark_rapids_tpu.columnar  (cuDF equivalent)
  L0 device runtime        JAX / XLA / Pallas
"""

__version__ = "0.1.0"

# SQL semantics require 64-bit longs/doubles; JAX defaults to 32-bit.
# Must run before any jax array is created anywhere in the package.
import os as _os

import jax as _jax

_jax.config.update("jax_enable_x64", True)

# Persistent XLA compilation cache: on tunneled TPU backends a single
# program compile costs ~30-40s (measured round 3); cached reloads cost
# ~0.1s, across processes. CPU backends are excluded — XLA:CPU AOT cache
# entries pin machine features and reloads warn of possible SIGILL.
_cache_dir = _os.environ.get(
    "SRT_XLA_CACHE_DIR",
    _os.path.expanduser("~/.cache/spark_rapids_tpu/xla"))


def _enable_compile_cache() -> None:
    """Called once a backend is live (session start / first device use);
    cheap and idempotent."""
    if not _cache_dir:
        return
    try:
        if _jax.default_backend() == "cpu":
            return
    except Exception:
        return
    _jax.config.update("jax_compilation_cache_dir", _cache_dir)
    _jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)

from spark_rapids_tpu.conf import TpuConf  # noqa: F401,E402
