"""spark-rapids-tpu: a TPU-native accelerator for columnar SQL execution.

Built from scratch with the capabilities of NVIDIA's RAPIDS Accelerator for
Apache Spark (reference: /root/reference, spark-rapids 21.10): a physical-plan
rewrite engine that replaces supported operators/expressions with Tpu*Exec
nodes whose columnar batches are HBM-resident JAX arrays, with the kernel
library (the cuDF equivalent) implemented as XLA/Pallas programs, a tiered
HBM->host->disk spill framework in place of RMM, and an ICI/DCN all-to-all
shuffle in place of the UCX RapidsShuffleManager.

Because no JVM Spark is present in this environment, the package also ships
the host engine the plugin accelerates: a Catalyst-like DataFrame/SQL layer
(`spark_rapids_tpu.sql`) whose CPU physical operators implement Spark
semantics and serve both as the bit-identical comparison baseline and as the
per-operator fallback target (the reference's contract, README.md:15-16).

Layering mirrors SURVEY.md section 1:
  L7 plugin bootstrap      spark_rapids_tpu.plugin
  L6 plan rewrite          spark_rapids_tpu.{meta,typesig,overrides,transitions,cbo}
  L5 columnar operators    spark_rapids_tpu.exec
  L4 batch/row interchange spark_rapids_tpu.exec.transitions_exec
  L3 memory/spill          spark_rapids_tpu.memory
  L2 shuffle/communication spark_rapids_tpu.shuffle
  L1 kernel library        spark_rapids_tpu.columnar  (cuDF equivalent)
  L0 device runtime        JAX / XLA / Pallas
"""

__version__ = "0.1.0"

# SQL semantics require 64-bit longs/doubles; JAX defaults to 32-bit.
# Must run before any jax array is created anywhere in the package.
import os as _os

import jax as _jax

_jax.config.update("jax_enable_x64", True)

# Persistent XLA compilation cache: on tunneled TPU backends a single
# program compile costs ~30-240s (measured rounds 3-4); cached reloads
# cost ~0.1s, across processes. Policy (off switch, per-config
# directory fingerprint) lives in device_manager.initialize.


def _enable_compile_cache() -> None:
    """Called once a backend is live (session start / first device use);
    cheap and idempotent. Delegates to device_manager.initialize, the
    single owner of the persistent-cache policy (off switch + the
    config-fingerprinted directory — mixing configs in one directory
    deserializes foreign XLA:CPU AOT entries into SIGSEGV)."""
    from spark_rapids_tpu import device_manager
    device_manager.initialize()

from spark_rapids_tpu.conf import TpuConf  # noqa: F401,E402
