"""TpuSparkSession: the SparkSession-shaped entry point.

Mirrors the role Spark's session + the plugin's ColumnarOverrideRules hook
play in the reference (Plugin.scala:44-50): after CPU physical planning,
`spark.rapids.sql.enabled` routes the plan through the TpuOverrides rewrite
before execution.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, Iterable, List, Optional, Sequence, Union

import numpy as np

from spark_rapids_tpu.conf import TpuConf
from spark_rapids_tpu.columnar.host import HostBatch, HostColumn
from spark_rapids_tpu.sql import expressions as E
from spark_rapids_tpu.sql import logical as L
from spark_rapids_tpu.sql import types as T
from spark_rapids_tpu.sql.dataframe import DataFrame
from spark_rapids_tpu.sql.planner import Planner


class RuntimeConfApi:
    """spark.conf facade."""

    def __init__(self, conf: TpuConf):
        self._conf = conf

    def set(self, key: str, value: Any) -> None:
        self._conf.set(key, value)

    def get(self, key: str, default: Any = None) -> Any:
        return self._conf.get_key(key, default)

    def unset(self, key: str) -> None:
        self._conf.settings.pop(key, None)


class TpuSparkSession:
    _active: Optional["TpuSparkSession"] = None
    _lock = threading.Lock()

    def __init__(self, conf: Optional[Dict[str, Any]] = None):
        self.conf_obj = TpuConf(conf)
        self._owns_mesh = False
        if self.conf_obj.sql_enabled:
            import spark_rapids_tpu
            from spark_rapids_tpu import device_manager
            device_manager.initialize(self.conf_obj)
            spark_rapids_tpu._enable_compile_cache()
            from spark_rapids_tpu.conf import (HAS_NANS,
                                               SHUFFLE_ICI_DEVICES,
                                               SHUFFLE_MODE)
            from spark_rapids_tpu.ops import groupby as _G
            _G.set_has_nans(bool(self.conf_obj.get(HAS_NANS)))
            if str(self.conf_obj.get(SHUFFLE_MODE)).lower() == "ici":
                # executor-plugin-init analogue: activate the shuffle
                # mesh once per session (GpuShuffleEnv.initShuffleManager
                # role; jax already knows the topology). Check-then-act
                # under the class lock: concurrent server threads
                # constructing tenant sessions must not both build (and
                # later both tear down) the process mesh
                from spark_rapids_tpu.parallel import mesh as PM
                with TpuSparkSession._lock:
                    if PM.get_active_mesh() is None:
                        n = int(self.conf_obj.get(
                            SHUFFLE_ICI_DEVICES)) or None
                        PM.set_active_mesh(PM.build_mesh(n))
                        self._owns_mesh = True
        # live telemetry (docs/observability.md): a session that sets
        # any spark.rapids.sql.telemetry.* conf arms the process
        # trigger engine's conf-less hooks (HBM watermark, admission
        # saturation, retry storm); default sessions never disarm it
        from spark_rapids_tpu.telemetry import triggers as _telemetry
        _telemetry.configure(self.conf_obj)
        self.conf = RuntimeConfApi(self.conf_obj)
        self.catalog_views: Dict[str, L.LogicalPlan] = {}
        self._plan_capture: List = []  # ExecutionPlanCaptureCallback twin
        self._capture_enabled = False
        self.last_rewrite_report = None
        self.last_profile_path: Optional[str] = None
        # per-thread mirrors of last_rewrite_report/last_profile_path:
        # concurrent queries on ONE session (the server shares a
        # session per tenant) race the session-level attributes; each
        # worker thread plans AND executes on its own thread, so the
        # profile/event-log sinks read the thread's own report and the
        # server reads thread_profile_path
        self._tls = threading.local()
        # serving tenant id (docs/serving.md): threads through the
        # store's per-tenant HBM ledger, trace files, event-log lines,
        # and profile artifacts; "" = untenanted
        from spark_rapids_tpu.conf import SERVE_TENANT_ID
        self.tenant: Optional[str] = \
            str(self.conf_obj.get(SERVE_TENANT_ID)) or None
        # the previously-active session is REMEMBERED, not clobbered:
        # stop() restores it, so interleaved session lifetimes (the
        # server keeps one live session per tenant) leave active()
        # pointing at a live session instead of None/stale
        self._stopped = False
        with TpuSparkSession._lock:
            self._prev_active = TpuSparkSession._active
            TpuSparkSession._active = self

    # -- builder-compatible constructor
    class Builder:
        def __init__(self):
            self._conf: Dict[str, Any] = {}

        def config(self, key: str, value: Any) -> "TpuSparkSession.Builder":
            self._conf[key] = value
            return self

        def appName(self, name: str) -> "TpuSparkSession.Builder":
            return self

        def master(self, m: str) -> "TpuSparkSession.Builder":
            return self

        def getOrCreate(self) -> "TpuSparkSession":
            return TpuSparkSession(self._conf)

    builder = None  # set below

    @staticmethod
    def active() -> "TpuSparkSession":
        if TpuSparkSession._active is None:
            TpuSparkSession._active = TpuSparkSession()
        return TpuSparkSession._active

    # -- data sources ------------------------------------------------------
    def createDataFrame(self, data, schema=None,
                        num_partitions: int = 2) -> DataFrame:
        batch = _infer_batch(data, schema)
        # split into partitions for realistic multi-partition plans
        np_ = max(1, min(num_partitions, max(1, batch.num_rows)))
        if np_ == 1 or batch.num_rows == 0:
            batches = [batch]
        else:
            per = (batch.num_rows + np_ - 1) // np_
            batches = [batch.slice(i * per, (i + 1) * per)
                       for i in range(np_)
                       if batch.slice(i * per, (i + 1) * per).num_rows > 0]
        rel = L.LocalRelation(batch.schema, batches, len(batches))
        return DataFrame(rel, self)

    def range(self, start: int, end: Optional[int] = None, step: int = 1,
              numPartitions: int = 2) -> DataFrame:
        if end is None:
            start, end = 0, start
        return DataFrame(L.Range(start, end, step, numPartitions), self)

    @property
    def read(self):
        from spark_rapids_tpu.io.readers import DataFrameReader
        return DataFrameReader(self)

    def table(self, name: str) -> DataFrame:
        # qualify outputs by the table name (Spark does the same), so
        # `SELECT t.col FROM t JOIN u ...` resolves unambiguously
        return DataFrame(
            L.SubqueryAlias(name, self.catalog_views[name.lower()]), self)

    def sql(self, query: str) -> DataFrame:
        from spark_rapids_tpu.sql.parser import parse_sql
        return parse_sql(query, self)

    # -- execution ---------------------------------------------------------
    def plan_physical(self, plan: L.LogicalPlan,
                      execute_subqueries: bool = True):
        """CPU physical plan, then the plugin rewrite when enabled.
        ``execute_subqueries=False`` (the explain path) substitutes
        scalar subqueries with unevaluated placeholders — rendering a
        plan must never run the query's subqueries (Spark's explain
        does not either)."""
        from spark_rapids_tpu import udf_compiler
        from spark_rapids_tpu.sql.expressions import \
            materialize_scalar_subqueries
        plan = materialize_scalar_subqueries(
            plan, self if execute_subqueries else None)
        plan = udf_compiler.rewrite_plan(plan, self.conf_obj)
        # cross-query plan-rewrite cache (docs/serving.md): AFTER
        # subquery materialization (their results must be fresh per
        # submission) a repeated query shape skips the whole
        # Planner + apply_overrides + CBO + fusion pipeline and clones
        # the cached template. Scoped to the execute path — the explain
        # path plans with unevaluated placeholders and must not pollute
        # (or hit) the executable cache.
        from spark_rapids_tpu.conf import PLAN_CACHE_ENABLED
        use_cache = (execute_subqueries
                     and bool(self.conf_obj.get(PLAN_CACHE_ENABLED)))
        if use_cache:
            from spark_rapids_tpu import plan_cache as PC
            sig = PC.plan_signature(plan, self.conf_obj)
            # lifecycle keying (docs/serving.md "Query lifecycle"): the
            # signature DIGEST identifies this query shape for the
            # watchdog's p99 history, the poison-query quarantine, and
            # the persistent query history (compact enough to persist
            # per record); threaded per-thread (concurrent queries
            # share this session) and onto the live CancelToken for
            # the watchdog's scan. The plan cache keys on the full
            # string — a digest collision must never alias two plans.
            sig_key = PC.signature_digest(sig)
            self._tls.plan_signature = sig_key
            from spark_rapids_tpu import lifecycle as LC
            ltok = LC.current_token()
            if ltok is not None:
                ltok.signature = sig_key
            # single-flight build: concurrent cold misses of one shape
            # (a burst of identical queries on a fresh server) run the
            # rewrite once; everyone executes a clone of the template
            physical, report, was_miss = PC.get_or_clone(
                sig, lambda: self._rewrite_fresh(plan),
                conf_obj=self.conf_obj)
            self.last_rewrite_report = report
            self._tls.rewrite_report = report
            if not was_miss and report is not None:
                # sql.explain output replays from the cached report
                # (the building thread printed inside apply_overrides)
                report.print_explain(self.conf_obj)
        else:
            self._tls.plan_signature = None
            template, report = self._rewrite_fresh(plan)
            physical = template
            self.last_rewrite_report = report
            self._tls.rewrite_report = report
        if self._capture_enabled:
            self._plan_capture.append(physical)
        return physical

    def _rewrite_fresh(self, plan):
        """Run the full rewrite pipeline (CPU planning, TpuOverrides,
        CBO, fusion, broadcast reuse); returns ``(physical, report)``.
        The plan-cache build callback — must not touch session state
        (it may run under the cache's single-flight on behalf of
        another thread's identical query)."""
        physical = Planner(self.conf_obj, session=self).plan(plan)
        report = None
        if self.conf_obj.sql_enabled:
            from spark_rapids_tpu.overrides import (RewriteReport,
                                                    apply_overrides)
            report = RewriteReport()
            physical = apply_overrides(physical, self.conf_obj, report)
        physical = _reuse_broadcast_exchanges(physical)
        return physical, report

    def execute_plan(self, plan: L.LogicalPlan) -> HostBatch:
        import time as _time

        from spark_rapids_tpu import trace as TR
        from spark_rapids_tpu.conf import EVENT_LOG_DIR, TASK_PARALLELISM
        if self.conf_obj.sql_enabled:
            # re-assert THIS session's kernel flags before executing:
            # another session constructed since __init__ may have set a
            # different hasNans (kernel_salt keys the program caches, so
            # flips only change which cached trace is used)
            from spark_rapids_tpu.conf import HAS_NANS
            from spark_rapids_tpu.ops import groupby as _G
            _G.set_has_nans(bool(self.conf_obj.get(HAS_NANS)))
        # profiling: re-base the process store's pool + per-owner peak
        # watermarks at query START so each artifact's memory section
        # covers THIS query, not a high-watermark inherited from
        # earlier queries (concurrent queries still share the process
        # store — same documented limitation as the span stream)
        from spark_rapids_tpu import profile as PROF
        if bool(self.conf_obj.get(PROF.PROFILE_ENABLED)):
            from spark_rapids_tpu import memory as _memory
            _memory.reset_store_peaks()
        # span tracing (docs/observability.md): the trace scope opens
        # BEFORE planning so compile spans and scalar-subquery execution
        # (nested execute_plan calls fold into this query's trace) are
        # attributed; one Chrome-trace file per sampled query
        # lifecycle (docs/serving.md "Query lifecycle"): materialize
        # the process fault injector up front so checkpoint-level
        # site:cancel schedules fire even before the first wrapped
        # allocation, and read the quarantine threshold once
        from spark_rapids_tpu import lifecycle as LC
        from spark_rapids_tpu import retry as _retry
        from spark_rapids_tpu.conf import SERVE_QUARANTINE_THRESHOLD
        _retry.get_fault_injector(self.conf_obj)
        quar_thr = int(self.conf_obj.get(SERVE_QUARANTINE_THRESHOLD))
        sig = None
        physical = None
        t_begin = _time.perf_counter()
        tok = TR.begin_query(self.conf_obj)
        try:
            physical = self.plan_physical(plan)
            sig = getattr(self._tls, "plan_signature", None)
            if quar_thr > 0 and sig is not None \
                    and LC.is_quarantined(sig):
                # poison-query quarantine: fail fast BEFORE touching
                # the device — the signature already wedged the
                # runtime quar_thr consecutive times
                raise LC.TpuQueryQuarantined(
                    sig, LC.quarantined_failures(sig))
            # THIS thread's rewrite report: a concurrent query on the
            # same session may overwrite last_rewrite_report before the
            # profile/event-log writes below run
            report = getattr(self._tls, "rewrite_report",
                             self.last_rewrite_report)
            # serving tenancy (docs/serving.md): stamp every registry of
            # THIS execution's plan with the session tenant so store
            # registrations from any pool thread bill the right ledger
            from spark_rapids_tpu import memory as _mem
            _mem.stamp_plan_tenant(physical, self.tenant)
            # serve-tier caching (docs/caching.md): fingerprint every
            # file-scan input BEFORE execution reads it — a file
            # mutated mid-query then mismatches at lookup time instead
            # of going stale. Captured on this thread for the server's
            # result-cache population and the join build-reuse hooks;
            # skipped (and cleared) when neither cache is on.
            from spark_rapids_tpu.conf import (RESULT_CACHE_ENABLED,
                                               SUBPLAN_CACHE_ENABLED)
            from spark_rapids_tpu.serve import result_cache as _RC
            if (bool(self.conf_obj.get(RESULT_CACHE_ENABLED))
                    or bool(self.conf_obj.get(SUBPLAN_CACHE_ENABLED))):
                _RC.set_execution_fingerprints(
                    _RC.capture_fingerprints(physical))
            else:
                _RC.set_execution_fingerprints(None)
            t0 = _time.perf_counter()
            with _mem.tenant_scope(self.tenant):
                result = physical.execute_collect(
                    int(self.conf_obj.get(TASK_PARALLELISM)))
            wall_s = _time.perf_counter() - t0
        except LC.TpuQueryCancelled as e:
            TR.end_query(self.conf_obj, tok, error=True)
            # a cancelled/timed-out query's HBM frees NOW: close the
            # dead plan's spillable handles deterministically instead
            # of waiting for plan GC (cancellation never counts toward
            # quarantine — it is not a runtime-fatal failure)
            from spark_rapids_tpu import memory as _mem
            _mem.release_plan_handles(physical)
            self._record_terminal(
                ("timed-out" if e.reason == LC.REASON_DEADLINE
                 else "cancelled"), e.reason, physical, sig,
                _time.perf_counter() - t_begin)
            raise
        except LC.TpuQueryQuarantined:
            TR.end_query(self.conf_obj, tok, error=True)
            self._record_terminal(
                "quarantined", None, physical, sig,
                _time.perf_counter() - t_begin)
            raise  # never ran: neither a failure nor a success
        except BaseException:
            TR.end_query(self.conf_obj, tok, error=True)
            if quar_thr > 0 and sig is not None:
                LC.record_runtime_failure(sig, quar_thr)
            self._record_terminal(
                "failed", None, physical, sig,
                _time.perf_counter() - t_begin)
            raise
        trace_path = TR.end_query(self.conf_obj, tok, wall_s=wall_s,
                                  rows=result.num_rows)
        if sig is not None:
            # the watchdog's per-signature p99 history; one success
            # also clears the signature's quarantine streak
            LC.record_wall(sig, wall_s)
            if quar_thr > 0:
                LC.record_success(sig)
        # profile artifact (docs/observability.md "Reading a query
        # profile"): the executed plan's registries + the store's
        # owner-attributed HBM ledger + the rewrite explain, one JSON
        # per query; the path is kept for tests/tools. ONE query id is
        # allocated for both sinks so the artifact and the event-log
        # line for this query correlate by queryId
        from spark_rapids_tpu import event_log
        from spark_rapids_tpu.conf import TELEMETRY_HISTORY_DIR
        log_dir = str(self.conf_obj.get(EVENT_LOG_DIR))
        profiling = bool(self.conf_obj.get(PROF.PROFILE_ENABLED))
        history_on = bool(str(
            self.conf_obj.get(TELEMETRY_HISTORY_DIR) or ""))
        qid = event_log.next_query_id() \
            if (log_dir or profiling or history_on) else None
        self.last_profile_path = PROF.write_profile(
            self.conf_obj, physical, report,
            wall_s, result.num_rows, query_id=qid)
        self._tls.profile_path = self.last_profile_path
        if log_dir:
            from spark_rapids_tpu import memory
            store = memory._STORE
            event_log.write_event(
                log_dir, id(self) & 0xFFFF, physical, report,
                wall_s, result.num_rows,
                store.stats() if store is not None else None,
                conf=self.conf_obj,
                memory_by_op=(store.owner_stats()
                              if store is not None else None),
                query_id=qid, tenant=self.tenant)
        # telemetry query-close triggers (slow query, per-query retry /
        # kernel-fallback deltas): evaluated AFTER the profile write so
        # a fired bundle can reference this query's artifact
        from spark_rapids_tpu.telemetry import triggers as _telemetry
        _telemetry.on_query_end(
            self.conf_obj, wall_s, plan=physical, tenant=self.tenant,
            query_id=qid,
            # THIS thread's artifact: a concurrent query on the shared
            # tenant session may overwrite last_profile_path before
            # the hook runs — the bundle must reference its own query
            profile_path=self.thread_profile_path())
        # persistent query history (docs/observability.md "Query
        # history"): one compact record per finished query, the
        # cross-run memory behind warm-start / SLO burn / tools
        # history / tools doctor. Appended AFTER the profile/trace
        # writes so the record can reference both artifacts.
        from spark_rapids_tpu.telemetry import history as _history
        # the WIRE queryId wins when the server supplied one (same
        # rule as the cancelled/failed paths): the id the client saw
        # in its response must resolve in `tools doctor`
        wire_qid = self._wire_query_id()
        _history.record_query_close(
            self.conf_obj, status=_history.STATUS_FINISHED,
            signature=sig, tenant=self.tenant,
            query_id=(wire_qid if wire_qid is not None else qid),
            wall_s=wall_s, queue_wait_s=self._queue_wait(),
            rows=result.num_rows, physical=physical, report=report,
            profile_path=self.thread_profile_path(),
            trace_path=trace_path)
        return result

    @staticmethod
    def _queue_wait() -> float:
        """The calling thread's admission-queue wait (0 outside a
        served query) — the lifecycle token records admission time."""
        from spark_rapids_tpu import lifecycle as LC
        tok = LC.current_token()
        if tok is None or tok.admitted is None:
            return 0.0
        return max(0.0, tok.admitted - tok.started)

    @staticmethod
    def _wire_query_id():
        from spark_rapids_tpu import lifecycle as LC
        tok = LC.current_token()
        return tok.query_id if tok is not None else None

    def _record_terminal(self, status: str, reason, physical, sig,
                         wall_s: float) -> None:
        """Event-log + history sinks for a NON-finished terminal
        outcome (cancelled / timed-out / quarantined / failed), so the
        two surfaces agree on query outcomes. Never raises — the
        original exception is already propagating."""
        try:
            from spark_rapids_tpu import event_log
            from spark_rapids_tpu import memory
            from spark_rapids_tpu.conf import (EVENT_LOG_DIR,
                                               TELEMETRY_HISTORY_DIR)
            from spark_rapids_tpu.telemetry import history as _history
            log_dir = str(self.conf_obj.get(EVENT_LOG_DIR))
            history_on = bool(str(
                self.conf_obj.get(TELEMETRY_HISTORY_DIR) or ""))
            # ONE id for both sinks, so the failure's event line and
            # history record correlate (same contract as success);
            # the wire queryId wins when the server supplied one
            qid = self._wire_query_id()
            if qid is None and (log_dir or history_on):
                qid = event_log.next_query_id()
            if log_dir:
                store = memory._STORE
                event_log.write_event(
                    log_dir, id(self) & 0xFFFF, physical, None,
                    wall_s, 0,
                    store.stats() if store is not None else None,
                    conf=self.conf_obj, tenant=self.tenant,
                    query_id=qid, status=status, reason=reason)
            _history.record_query_close(
                self.conf_obj, status=status, reason=reason,
                signature=sig, tenant=self.tenant,
                query_id=qid, wall_s=wall_s,
                queue_wait_s=self._queue_wait(), rows=0,
                physical=physical)
        except Exception:
            pass  # observability must not mask the real failure

    def explain_string(self, plan: L.LogicalPlan, physical=None) -> str:
        if physical is None:
            physical = self.plan_physical(plan, execute_subqueries=False)
        return f"== Logical ==\n{plan!r}\n== Physical ==\n{physical!r}"

    def thread_profile_path(self) -> Optional[str]:
        """The profile artifact written by the CALLING thread's last
        query on this session (None when none) — race-free under the
        server's shared-session-per-tenant concurrency."""
        return getattr(self._tls, "profile_path", None)

    def thread_plan_signature(self) -> Optional[str]:
        """The plan-signature digest of the CALLING thread's last
        planned query on this session (None when planning ran without
        the plan cache) — the server's result-cache population reads
        this after _execute() on the same thread (docs/caching.md)."""
        return getattr(self._tls, "plan_signature", None)

    # -- plan capture (ExecutionPlanCaptureCallback, Plugin.scala:268-390)
    def start_capture(self) -> None:
        self._plan_capture.clear()
        self._capture_enabled = True

    def get_captured_plans(self) -> List:
        self._capture_enabled = False
        return list(self._plan_capture)

    def stop(self) -> None:
        if self._owns_mesh:
            from spark_rapids_tpu.parallel import mesh as PM
            PM.set_active_mesh(None)
            self._owns_mesh = False
        with TpuSparkSession._lock:
            if TpuSparkSession._active is self:
                # restore the session that was active before this one
                # (global-singleton satellite: concurrent server
                # sessions must not clobber each other's active slot) —
                # skipping any already-stopped ancestor in the chain
                prev = self._prev_active
                while prev is not None and getattr(prev, "_stopped",
                                                   False):
                    prev = prev._prev_active
                TpuSparkSession._active = prev
            self._stopped = True


class _BuilderFactory:
    def __get__(self, obj, objtype=None):
        return TpuSparkSession.Builder()


TpuSparkSession.builder = _BuilderFactory()


def _infer_batch(data, schema) -> HostBatch:
    if isinstance(data, HostBatch):
        return data
    if isinstance(schema, str):
        schema = _parse_ddl_schema(schema)
    if isinstance(data, dict):
        if schema is None:
            schema = T.StructType([
                T.StructField(k, _infer_type_from_values(v))
                for k, v in data.items()])
        return HostBatch.from_pydict(data, schema)
    rows = list(data)
    if schema is None:
        if not rows:
            raise ValueError("cannot infer schema from empty data")
        first = rows[0]
        if isinstance(first, dict):
            names = list(first.keys())
            cols = {n: [r.get(n) for r in rows] for n in names}
            schema = T.StructType([
                T.StructField(n, _infer_type_from_values(cols[n]))
                for n in names])
            return HostBatch.from_pydict(cols, schema)
        names = [f"_{i + 1}" for i in range(len(first))]
        cols = {n: [r[i] for r in rows] for i, n in enumerate(names)}
        schema = T.StructType([
            T.StructField(n, _infer_type_from_values(cols[n]))
            for n in names])
        return HostBatch.from_pydict(cols, schema)
    if isinstance(schema, (list, tuple)):
        names = list(schema)
        if not rows:
            raise ValueError("cannot infer schema from empty data")
        cols = {n: [r[i] for r in rows] for i, n in enumerate(names)}
        schema = T.StructType([
            T.StructField(n, _infer_type_from_values(cols[n]))
            for n in names])
        return HostBatch.from_pydict(cols, schema)
    cols = {f.name: [r[i] for r in rows]
            for i, f in enumerate(schema.fields)}
    return HostBatch.from_pydict(cols, schema)


def _infer_type_from_values(values: Iterable[Any]) -> T.DataType:
    import datetime
    for v in values:
        if v is None:
            continue
        if isinstance(v, bool):
            return T.BooleanT
        if isinstance(v, int):
            return T.LongT
        if isinstance(v, float):
            return T.DoubleT
        if isinstance(v, str):
            return T.StringT
        if isinstance(v, datetime.datetime):
            return T.TimestampT
        if isinstance(v, datetime.date):
            return T.DateT
        if isinstance(v, bytes):
            return T.BinaryT
    return T.StringT


def _parse_ddl_schema(ddl: str) -> T.StructType:
    from spark_rapids_tpu.sql.functions import _parse_type, split_top_level
    # split on commas not inside parens (decimal(10,2) etc.)
    fields = []
    for part in split_top_level(ddl):
        name, _, tp = part.strip().partition(" ")
        fields.append(T.StructField(name.strip(), _parse_type(tp.strip())))
    return T.StructType(fields)


def _reuse_broadcast_exchanges(plan):
    """ReuseExchange (GpuBroadcastExchangeExec.scala:280 reuse
    semantics): structurally equal broadcast subtrees in one query plan
    collapse to ONE shared node instance, so the build side
    materializes once no matter how many joins consume it."""
    from spark_rapids_tpu.exec.exchange import TpuBroadcastExchangeExec
    from spark_rapids_tpu.sql import physical as P

    seen = {}

    def params(p):
        # node parameters beyond simple_string: limits, ranges, expr
        # lists (exprs repr with their ids). Unknown object-valued
        # attrs key by IDENTITY — conservative: equal-content-but-
        # distinct objects just skip reuse, never alias wrongly.
        out = []
        for k in sorted(vars(p)):
            if k in ("children", "conf", "metrics") or k.startswith("_"):
                continue
            v = vars(p)[k]
            if isinstance(v, (int, str, bool, float, type(None))):
                out.append((k, v))
            elif isinstance(v, (list, tuple)) and all(
                    isinstance(x, (int, str, bool, float)) for x in v):
                out.append((k, tuple(v)))
            elif isinstance(v, E.Expression) or (
                    isinstance(v, (list, tuple)) and v and all(
                        isinstance(x, E.Expression) for x in v)):
                out.append((k, repr(v)))
            else:
                out.append((k, id(v)))
        return tuple(out)

    def sig(p):
        # simple_string alone is NOT identity (two equal-shaped
        # LocalScans or Limits print identically); output attr EXPR IDS
        # plus the node's own parameters are
        return (type(p).__name__, p.simple_string(), params(p),
                tuple((a.name, a.expr_id, repr(a.data_type))
                      for a in p.output),
                tuple(sig(c) for c in p.children))

    def walk(p):
        p.children = [walk(c) for c in p.children]
        if isinstance(p, (P.CpuBroadcastExchangeExec,
                          TpuBroadcastExchangeExec)):
            key = (type(p).__name__, sig(p.child))
            hit = seen.get(key)
            if hit is not None:
                return hit
            seen[key] = p
        return p

    return walk(plan)
