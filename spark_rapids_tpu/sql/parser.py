"""SQL text -> logical plan (the role Spark's Catalyst parser plays for
the reference, which inherits it for free; this build supplies its own).

Hand-written tokenizer + recursive-descent parser covering the dialect
the engine executes: SELECT [DISTINCT] ... FROM (tables, subqueries,
joins) WHERE / GROUP BY / HAVING / ORDER BY / LIMIT, UNION ALL, CASE,
CAST, IN/LIKE/BETWEEN/IS NULL, window functions with OVER, and the
engine's function library. Expressions are built through the Column API
(spark_rapids_tpu.sql.functions) so SQL gets exactly the same coercion
rules as the DataFrame surface.

Aggregation follows Spark's analyzer shape: aggregate subtrees in the
select/having lists are extracted into an Aggregate node and the select
list becomes a Project over its output.
"""

from __future__ import annotations

import re
from typing import List, Optional, Tuple

from spark_rapids_tpu.sql import expressions as E
from spark_rapids_tpu.sql import functions as F
from spark_rapids_tpu.sql import logical as L
from spark_rapids_tpu.sql.functions import Column, WindowSpec, _parse_type

# ---------------------------------------------------------------------------
# Tokenizer
# ---------------------------------------------------------------------------

_TOKEN_RE = re.compile(r"""
    (?P<ws>\s+|--[^\n]*)
  | (?P<num>\d+\.\d*(?:[eE][-+]?\d+)?|\.\d+(?:[eE][-+]?\d+)?|\d+(?:[eE][-+]?\d+)?)
  | (?P<str>'(?:[^']|'')*')
  | (?P<qid>`[^`]+`|"[^"]+")
  | (?P<id>[A-Za-z_][A-Za-z_0-9]*)
  | (?P<op><=|>=|<>|!=|==|\|\||[-+*/%=<>(),.])
""", re.VERBOSE)


def _tokenize(text: str) -> List[Tuple[str, str]]:
    out: List[Tuple[str, str]] = []
    pos = 0
    while pos < len(text):
        m = _TOKEN_RE.match(text, pos)
        if m is None:
            raise ValueError(f"SQL syntax error near: {text[pos:pos+30]!r}")
        pos = m.end()
        kind = m.lastgroup
        if kind == "ws":
            continue
        val = m.group()
        if kind == "id":
            out.append(("id", val))
        elif kind == "qid":
            out.append(("id", val[1:-1]))
        else:
            out.append((kind, val))
    out.append(("eof", ""))
    return out


_JOIN_TYPES = {
    ("inner",): "inner", ("cross",): "cross",
    ("left",): "left", ("left", "outer"): "left",
    ("right",): "right", ("right", "outer"): "right",
    ("full",): "full", ("full", "outer"): "full",
    ("left", "semi"): "leftsemi", ("left", "anti"): "leftanti",
    ("semi",): "leftsemi", ("anti",): "leftanti",
}

_RESERVED_AFTER_RELATION = {
    "where", "group", "having", "order", "limit", "union", "on", "join",
    "inner", "left", "right", "full", "cross", "semi", "anti", "outer",
}


class _Parser:
    def __init__(self, text: str, session=None):
        self.toks = _tokenize(text)
        self.i = 0
        self.session = session

    # -- token helpers -----------------------------------------------------

    def peek(self, k: int = 0) -> Tuple[str, str]:
        return self.toks[min(self.i + k, len(self.toks) - 1)]

    def next(self) -> Tuple[str, str]:
        t = self.toks[self.i]
        self.i += 1
        return t

    def kw(self, *words: str) -> bool:
        """Consume the keyword sequence if present (case-insensitive)."""
        for k, w in enumerate(words):
            kind, val = self.peek(k)
            if kind != "id" or val.lower() != w:
                return False
        self.i += len(words)
        return True

    def at_kw(self, word: str) -> bool:
        kind, val = self.peek()
        return kind == "id" and val.lower() == word

    def expect(self, tok: str) -> str:
        kind, val = self.next()
        if val.lower() != tok and kind != tok:
            raise ValueError(f"expected {tok!r}, got {val!r}")
        return val

    # -- query -------------------------------------------------------------

    def query(self):
        """select_core (UNION [ALL] select_core)* [ORDER BY] [LIMIT] —
        a trailing ORDER BY/LIMIT binds to the WHOLE union (SQL spec),
        not the last branch."""
        df = self.select_stmt()
        while self.kw("union"):
            all_ = self.kw("all")
            right = self.select_stmt()
            df = df.union(right)
            if not all_:
                df = df.distinct()
        if self.kw("order", "by"):
            df = df.orderBy(*self._order_list())
        if self.kw("limit"):
            kind, val = self.next()
            assert kind == "num", f"LIMIT expects a number, got {val!r}"
            df = df.limit(int(val))
        return df

    def select_stmt(self):
        self.expect("select")
        distinct = self.kw("distinct")
        items: List[Tuple[Optional[Column], Optional[str]]] = []
        while True:
            if self.peek()[1] == "*":
                self.next()
                items.append((None, None))  # star
            else:
                c = self.expr()
                name = self._opt_alias()
                items.append((c, name))
            if self.peek()[1] == ",":
                self.next()
                continue
            break
        self.expect("from")
        df = self.from_clause()
        if self.kw("where"):
            df = df.filter(self.expr())
        group: Optional[List[Column]] = None
        if self.kw("group", "by"):
            group = [self.expr()]
            while self.peek()[1] == ",":
                self.next()
                group.append(self.expr())
        having = self.expr() if self.kw("having") else None
        df = self._project(df, items, group, having)
        # DISTINCT applies to the projected rows (ORDER BY/LIMIT are
        # parsed by query(), after any UNION branches)
        if distinct:
            df = df.distinct()
        return df

    def _opt_alias(self) -> Optional[str]:
        if self.kw("as"):
            return self.next()[1]
        kind, val = self.peek()
        if kind == "id" and val.lower() not in _RESERVED_AFTER_RELATION \
                and val.lower() not in ("from", "as"):
            # bare alias only valid in select list before , or FROM
            nk = self.peek(1)[1]
            if nk in (",",) or self.peek(1)[0] == "eof" \
                    or (self.peek(1)[0] == "id"
                        and self.peek(1)[1].lower() == "from") \
                    or nk == ")":
                self.next()
                return val
        return None

    def _order_list(self) -> List[Column]:
        out: List[Column] = []
        while True:
            c = self.expr()
            asc = True
            if self.kw("asc"):
                asc = True
            elif self.kw("desc"):
                asc = False
            nulls_first = None
            if self.kw("nulls", "first"):
                nulls_first = True
            elif self.kw("nulls", "last"):
                nulls_first = False
            out.append(Column(E.SortOrder(c.expr, asc, nulls_first)))
            if self.peek()[1] == ",":
                self.next()
                continue
            return out

    # -- FROM / joins ------------------------------------------------------

    def from_clause(self):
        df = self.relation()
        while True:
            jt = None
            for words, how in _JOIN_TYPES.items():
                if self.kw(*words, "join"):
                    jt = how
                    break
            if jt is None:
                if self.kw("join"):
                    jt = "inner"
                else:
                    break
            right = self.relation()
            cond = self.expr() if self.kw("on") else None
            df = df.join(right, on=cond, how=jt)
        return df

    def relation(self):
        if self.peek()[1] == "(":
            self.next()
            df = self.query()
            self.expect(")")
            alias = self._relation_alias()
            return df.alias(alias) if alias else df
        kind, name = self.next()
        assert kind == "id", f"expected table name, got {name!r}"
        df = self.session.table(name)
        alias = self._relation_alias()
        return df.alias(alias) if alias else df

    def _relation_alias(self) -> Optional[str]:
        if self.kw("as"):
            return self.next()[1]
        kind, val = self.peek()
        if kind == "id" and val.lower() not in _RESERVED_AFTER_RELATION:
            self.next()
            return val
        return None

    # -- aggregation shaping ----------------------------------------------

    def _project(self, df, items, group: Optional[List[Column]],
                 having: Optional[Column]):
        from spark_rapids_tpu.sql.dataframe import DataFrame

        def has_group_agg(e: E.Expression) -> bool:
            """Aggregate NOT under an OVER clause (window aggs project)."""
            if isinstance(e, E.WindowExpression):
                return False
            if isinstance(e, E.AggregateExpression):
                return True
            return any(has_group_agg(c) for c in e.children)

        resolved: List[Tuple[Optional[E.Expression], Optional[str]]] = []
        has_agg = False
        for c, name in items:
            if c is None:
                resolved.append((None, None))
                continue
            e = df._resolve(c)
            if has_group_agg(e):
                has_agg = True
            resolved.append((e, name))
        having_e = df._resolve(having) if having is not None else None
        if having_e is not None and has_group_agg(having_e):
            has_agg = True

        if group is None and not has_agg:
            cols = []
            for e, name in resolved:
                if e is None:
                    cols.extend(Column(a) for a in df.plan.output)
                else:
                    cols.append(Column(e).alias(name) if name
                                else Column(e))
            return df.select(*cols)

        # Aggregate + Project (Spark analyzer shape)
        group_exprs = [df._resolve(g) for g in (group or [])]
        grouping: List[E.Expression] = []
        group_attr_by_repr = {}
        for g in group_exprs:
            if isinstance(g, E.AttributeReference):
                grouping.append(g)
                group_attr_by_repr[repr(g)] = g
            else:
                alias = E.Alias(g, f"_g{len(grouping)}")
                grouping.append(alias)
                group_attr_by_repr[repr(g)] = alias.to_attribute()
        agg_aliases: List[E.Expression] = []

        def extract(e: E.Expression) -> E.Expression:
            """Replace agg subtrees (and grouping-expr matches) with
            attribute refs into the Aggregate's output."""
            rg = group_attr_by_repr.get(repr(e))
            if rg is not None:
                return rg

            def rule(x):
                if isinstance(x, E.AggregateExpression):
                    alias = E.Alias(x, f"_a{len(agg_aliases)}")
                    agg_aliases.append(alias)
                    return alias.to_attribute()
                return None
            return e.transform(rule)

        out_items: List[E.Expression] = []
        for e, name in resolved:
            assert e is not None, "SELECT * is not valid with GROUP BY"
            r = extract(e)
            if name:
                r = E.Alias(r, name)
            elif not isinstance(r, (E.AttributeReference, E.Alias)):
                r = E.Alias(r, _sql_name(e))
            out_items.append(r)
        having_r = extract(having_e) if having_e is not None else None

        plan = L.Aggregate(list(grouping),
                           list(grouping) + agg_aliases, df.plan)
        out = DataFrame(plan, df.session)
        if having_r is not None:
            out = DataFrame(L.Filter(having_r, out.plan), out.session)
        return out.select(*[Column(e) for e in out_items])

    # -- expressions -------------------------------------------------------

    def expr(self) -> Column:
        return self.or_expr()

    def or_expr(self) -> Column:
        left = self.and_expr()
        while self.kw("or"):
            left = left | self.and_expr()
        return left

    def and_expr(self) -> Column:
        left = self.not_expr()
        while self.kw("and"):
            left = left & self.not_expr()
        return left

    def not_expr(self) -> Column:
        if self.kw("not"):
            return ~self.not_expr()
        return self.comparison()

    def comparison(self) -> Column:
        left = self.add_expr()
        while True:
            kind, val = self.peek()
            if val in ("=", "=="):
                self.next()
                left = left == self.add_expr()
            elif val in ("!=", "<>"):
                self.next()
                left = left != self.add_expr()
            elif val == "<":
                self.next()
                left = left < self.add_expr()
            elif val == "<=":
                self.next()
                left = left <= self.add_expr()
            elif val == ">":
                self.next()
                left = left > self.add_expr()
            elif val == ">=":
                self.next()
                left = left >= self.add_expr()
            elif self.kw("is", "not", "null"):
                left = left.isNotNull()
            elif self.kw("is", "null"):
                left = left.isNull()
            elif self.kw("not", "in"):
                left = ~self._in_list(left)
            elif self.at_kw("in"):
                self.kw("in")
                left = self._in_list(left)
            elif self.kw("not", "like"):
                left = ~left.like(self._string_lit())
            elif self.kw("like"):
                left = left.like(self._string_lit())
            elif self.kw("not", "rlike"):
                left = ~left.rlike(self._string_lit())
            elif self.kw("rlike") or self.kw("regexp"):
                left = left.rlike(self._string_lit())
            elif self.kw("not", "between"):
                lo = self.add_expr()
                self.expect("and")
                left = ~left.between(lo, self.add_expr())
            elif self.kw("between"):
                lo = self.add_expr()
                self.expect("and")
                left = left.between(lo, self.add_expr())
            else:
                return left

    def _in_list(self, left: Column) -> Column:
        self.expect("(")
        vals = [self._literal_value()]
        while self.peek()[1] == ",":
            self.next()
            vals.append(self._literal_value())
        self.expect(")")
        return left.isin(*vals)

    def _literal_value(self):
        kind, val = self.next()
        if kind == "op" and val in ("-", "+"):
            sign = -1 if val == "-" else 1
            kind, val = self.next()
            assert kind == "num", f"expected number after {val!r}"
            return sign * (float(val) if any(c in val for c in ".eE")
                           else int(val))
        if kind == "num":
            return float(val) if any(c in val for c in ".eE") else int(val)
        if kind == "str":
            return val[1:-1].replace("''", "'")
        if kind == "id" and val.lower() in ("true", "false"):
            return val.lower() == "true"
        raise ValueError(f"expected literal in IN list, got {val!r}")

    def _string_lit(self) -> str:
        kind, val = self.next()
        assert kind == "str", f"expected string literal, got {val!r}"
        return val[1:-1].replace("''", "'")

    def add_expr(self) -> Column:
        left = self.mul_expr()
        while True:
            kind, val = self.peek()
            if val == "+":
                self.next()
                left = left + self.mul_expr()
            elif val == "-":
                self.next()
                left = left - self.mul_expr()
            elif val == "||":
                self.next()
                left = F.concat(left, self.mul_expr())
            else:
                return left

    def mul_expr(self) -> Column:
        left = self.unary()
        while True:
            kind, val = self.peek()
            if val == "*":
                self.next()
                left = left * self.unary()
            elif val == "/":
                self.next()
                left = left / self.unary()
            elif val == "%":
                self.next()
                left = left % self.unary()
            else:
                return left

    def unary(self) -> Column:
        kind, val = self.peek()
        if val == "-":
            self.next()
            return -self.unary()
        if val == "+":
            self.next()
            return self.unary()
        return self.primary()

    def primary(self) -> Column:
        kind, val = self.peek()
        if val == "(":
            if self.peek(1)[1].lower() == "select":
                # uncorrelated scalar subquery (Catalyst ScalarSubquery;
                # materialized to a Literal before physical planning)
                self.next()
                sub = self.query()
                self.expect(")")
                out = sub.plan.output
                if len(out) != 1:
                    raise ValueError(
                        "scalar subquery must return one column, got "
                        f"{len(out)}")
                return Column(E.ScalarSubquery(sub.plan,
                                               out[0].data_type))
            self.next()
            c = self.expr()
            self.expect(")")
            return c
        if kind == "num":
            self.next()
            v = float(val) if any(ch in val for ch in ".eE") else int(val)
            return F.lit(v)
        if kind == "str":
            self.next()
            return F.lit(val[1:-1].replace("''", "'"))
        if kind != "id":
            raise ValueError(f"unexpected token {val!r}")
        low = val.lower()
        if low == "null":
            self.next()
            return Column(E.Literal(None))
        if low in ("true", "false"):
            self.next()
            return F.lit(low == "true")
        if low == "case":
            return self._case()
        if low in ("date", "timestamp") and self.peek(1)[0] == "str":
            # ANSI typed literals: DATE '1998-09-02' (Spark AstBuilder
            # visitTypeConstructor semantics = cast of the string)
            self.next()
            s = self._string_lit()
            from spark_rapids_tpu.sql import types as T
            return Column(E.Cast(
                E.Literal(s),
                T.DateT if low == "date" else T.TimestampT))
        if low == "cast":
            self.next()
            self.expect("(")
            c = self.expr()
            self.expect("as")
            tp = self._type_name()
            self.expect(")")
            return Column(E.Cast(c.expr, _parse_type(tp)))
        if self.peek(1)[1] == "(":
            return self._function_call()
        # column reference; qualified names keep every dotted part — the
        # resolver matches relation aliases then walks struct fields
        # (Catalyst's resolution order)
        self.next()
        parts = [val]
        while self.peek()[1] == "." and self.peek(1)[0] == "id":
            self.next()
            parts.append(self.next()[1])
        return F.col(".".join(parts))

    def _type_name(self) -> str:
        parts = [self.next()[1]]
        if self.peek()[1] == "(":
            while True:
                _, v = self.next()
                parts.append(v)
                if v == ")":
                    break
        return "".join(parts)

    def _case(self) -> Column:
        self.kw("case")
        simple = None
        if not self.at_kw("when"):
            simple = self.expr()
        branches = []
        while self.kw("when"):
            cond = self.expr()
            if simple is not None:
                cond = simple == cond
            self.expect("then")
            branches.append((cond.expr, self.expr().expr))
        default = self.expr().expr if self.kw("else") else None
        self.expect("end")
        return Column(E.CaseWhen(branches, default))

    def _function_call(self) -> Column:
        _, name = self.next()
        low = name.lower()
        self.expect("(")
        distinct = self.kw("distinct")
        args: List[Column] = []
        star = False
        if self.peek()[1] == "*":
            self.next()
            star = True
        elif self.peek()[1] != ")":
            args.append(self.expr())
            while self.peek()[1] == ",":
                self.next()
                args.append(self.expr())
        self.expect(")")
        c = self._build_function(low, args, star, distinct)
        if self.kw("over"):
            c = c.over(self._window_spec())
        return c

    def _window_spec(self) -> WindowSpec:
        self.expect("(")
        spec = WindowSpec()
        if self.kw("partition", "by"):
            parts = [self.expr()]
            while self.peek()[1] == ",":
                self.next()
                parts.append(self.expr())
            spec = spec.partitionBy(*parts)
        if self.kw("order", "by"):
            spec = spec.orderBy(*self._order_list())
        if self.kw("rows", "between"):
            lo = self._frame_bound()
            self.expect("and")
            hi = self._frame_bound()
            spec = spec.rowsBetween(lo, hi)
        self.expect(")")
        return spec

    def _frame_bound(self) -> int:
        if self.kw("unbounded", "preceding"):
            return F.Window.unboundedPreceding
        if self.kw("unbounded", "following"):
            return F.Window.unboundedFollowing
        if self.kw("current", "row"):
            return 0
        kind, val = self.next()
        assert kind == "num", f"bad frame bound {val!r}"
        n = int(val)
        if self.kw("preceding"):
            return -n
        self.expect("following")
        return n

    def _build_function(self, low: str, args: List[Column], star: bool,
                        distinct: bool) -> Column:
        if low == "count":
            if star or not args:
                return F.count("*")
            # multi-arg count: rows where ALL args are non-null
            c = Column(E.AggregateExpression(
                E.Count([a.expr for a in args]), is_distinct=distinct))
            return c
        if low == "if":
            return F.when(args[0], args[1]).otherwise(args[2])
        if low in ("nvl", "ifnull"):
            return F.coalesce(*args)
        if low in ("substr", "substring"):
            return F.substring(args[0],
                               int(_lit_value(args[1])),
                               int(_lit_value(args[2])))
        if low in ("power",):
            low = "pow"
        if low in ("mean",):
            low = "avg"
        if low in ("day",):
            low = "dayofmonth"
        if low in ("ucase",):
            low = "upper"
        if low in ("lcase",):
            low = "lower"
        fn = _FUNCTIONS.get(low)
        if fn is None:
            raise ValueError(f"unknown SQL function {low!r}")
        c = fn(*args)
        if distinct:
            # sum(DISTINCT x) etc. — flag the AggregateExpression; the
            # planner's dedup-then-aggregate rewrite executes it
            if not isinstance(c.expr, E.AggregateExpression):
                raise ValueError(
                    f"DISTINCT is not valid for function {low!r}")
            c = Column(E.AggregateExpression(c.expr.func, is_distinct=True))
        return c


def _lit_value(c: Column):
    assert isinstance(c.expr, E.Literal), \
        f"expected a literal argument, got {c.expr!r}"
    return c.expr.value


def _sql_name(e: E.Expression) -> str:
    return repr(e)[:60]


_FUNCTIONS = {
    "sum": F.sum, "avg": F.avg, "min": F.min, "max": F.max,
    "first": F.first, "last": F.last,
    "collect_list": F.collect_list, "collect_set": F.collect_set,
    "monotonically_increasing_id": F.monotonically_increasing_id,
    "window": lambda c, *a: F.window(c, *[_lit_value(x) for x in a]),
    "spark_partition_id": F.spark_partition_id,
    "input_file_name": F.input_file_name,
    "stddev": F.stddev_samp, "stddev_samp": F.stddev_samp,
    "std": F.stddev_samp, "stddev_pop": F.stddev_pop,
    "variance": F.var_samp, "var_samp": F.var_samp,
    "var_pop": F.var_pop,
    "abs": F.abs, "sqrt": F.sqrt, "exp": F.exp, "log": F.log,
    "ln": F.log, "log10": F.log10, "floor": F.floor, "ceil": F.ceil,
    "ceiling": F.ceil, "pow": F.pow, "round": F.round,
    "signum": F.signum, "sign": F.signum, "sin": F.sin, "cos": F.cos,
    "tan": F.tan, "upper": F.upper, "lower": F.lower,
    "length": F.length, "char_length": F.length, "trim": F.trim,
    "concat": F.concat, "coalesce": F.coalesce, "isnull": F.isnull,
    "isnan": F.isnan, "year": F.year, "month": F.month,
    "dayofmonth": F.dayofmonth, "hour": F.hour, "minute": F.minute,
    "second": F.second, "date_add": F.date_add, "date_sub": F.date_sub,
    "datediff": F.datediff, "hash": F.hash, "xxhash64": F.xxhash64,
    "array": F.array, "size": F.size, "element_at": F.element_at,
    "array_contains": F.array_contains, "explode": F.explode,
    "explode_outer": F.explode_outer, "posexplode": F.posexplode,
    "posexplode_outer": F.posexplode_outer,
    "shiftleft": F.shiftleft, "shiftright": F.shiftright,
    "shiftrightunsigned": F.shiftrightunsigned,
    "log2": F.log2, "log1p": F.log1p, "expm1": F.expm1, "cbrt": F.cbrt,
    "rint": F.rint, "degrees": F.degrees, "radians": F.radians,
    "atan2": F.atan2, "hypot": F.hypot,
    "greatest": F.greatest, "least": F.least,
    "concat_ws": lambda sep, *cols: F.concat_ws(_lit_value(sep), *cols),
    "repeat": lambda c, n: F.repeat(c, int(_lit_value(n))),
    "lpad": lambda c, n, p: F.lpad(c, int(_lit_value(n)), _lit_value(p)),
    "rpad": lambda c, n, p: F.rpad(c, int(_lit_value(n)), _lit_value(p)),
    "translate": lambda c, m, r: F.translate(c, _lit_value(m),
                                             _lit_value(r)),
    "replace": F.replace, "instr": lambda c, s: F.instr(c, _lit_value(s)),
    "locate": lambda s, c, *p: F.locate(
        _lit_value(s), c, *[int(_lit_value(x)) for x in p]),
    "initcap": F.initcap, "reverse": F.reverse,
    "split": lambda c, p, *l: F.split(c, _lit_value(p),
                                      *[int(_lit_value(x)) for x in l]),
    "regexp_replace": lambda c, p, r: F.regexp_replace(
        c, _lit_value(p), _lit_value(r)),
    "regexp_extract": lambda c, p, i: F.regexp_extract(
        c, _lit_value(p), int(_lit_value(i))),
    "ltrim": F.ltrim, "rtrim": F.rtrim,
    "ascii": F.ascii, "char": F.chr, "chr": F.chr,
    "quarter": F.quarter, "dayofweek": F.dayofweek,
    "weekday": F.weekday, "dayofyear": F.dayofyear,
    "weekofyear": F.weekofyear, "last_day": F.last_day,
    "add_months": F.add_months, "months_between": F.months_between,
    "trunc": lambda c, f: F.trunc(c, _lit_value(f)),
    "date_format": lambda c, f: F.date_format(c, _lit_value(f)),
    "unix_timestamp": lambda c, *f: F.unix_timestamp(
        c, *[_lit_value(x) for x in f]),
    "from_unixtime": lambda c, *f: F.from_unixtime(
        c, *[_lit_value(x) for x in f]),
    "to_date": lambda c, *f: F.to_date(c, *[_lit_value(x) for x in f]),
    "to_timestamp": lambda c, *f: F.to_timestamp(
        c, *[_lit_value(x) for x in f]),
    "row_number": F.row_number, "rank": F.rank,
    "dense_rank": F.dense_rank, "ntile": lambda n: F.ntile(
        int(_lit_value(n))),
    "lag": lambda c, *a: F.lag(c, *[int(_lit_value(x)) if i == 0
                                    else _lit_value(x)
                                    for i, x in enumerate(a)]),
    "lead": lambda c, *a: F.lead(c, *[int(_lit_value(x)) if i == 0
                                      else _lit_value(x)
                                      for i, x in enumerate(a)]),
}


# ---------------------------------------------------------------------------
# Entry points
# ---------------------------------------------------------------------------

def parse_expression(text: str) -> E.Expression:
    """One expression (selectExpr / string filter)."""
    p = _Parser(text)
    c = p.expr()
    name = p._opt_alias()
    kind, _ = p.peek()
    if kind != "eof":
        raise ValueError(f"trailing tokens in expression: {text!r}")
    e = c.expr
    if name:
        e = E.Alias(e, name)
    return e


def parse_sql(query: str, session):
    """Full SELECT statement -> DataFrame."""
    p = _Parser(query, session)
    df = p.query()
    kind, val = p.peek()
    if kind != "eof":
        raise ValueError(f"trailing tokens near {val!r}")
    return df
