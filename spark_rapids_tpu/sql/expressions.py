"""Catalyst-like expression trees with vectorized CPU evaluation.

In the reference, Spark provides Catalyst expressions and the plugin mirrors
231 of them as Gpu* case classes (SURVEY.md 2.2 'Expressions'). Here the
expression tree itself is part of the framework; each node carries a
vectorized CPU `eval` over HostBatch implementing *Spark* semantics
(null propagation, two's-complement overflow wrap in non-ANSI mode,
NaN-equals-NaN ordering, 3-valued logic), and the plugin layer
(overrides.py) maps nodes to device implementations.

CPU eval requires bound references (`bind_references`), exactly like Spark's
BoundReference binding before codegen.
"""

from __future__ import annotations

import itertools
import math
from typing import Any, Callable, List, Optional, Sequence

import numpy as np

from spark_rapids_tpu.columnar import murmur3
from spark_rapids_tpu.columnar.host import HostBatch, HostColumn
from spark_rapids_tpu.sql import types as T

_expr_id = itertools.count(1)


def next_expr_id() -> int:
    return next(_expr_id)


class Expression:
    """Base expression node."""

    children: List["Expression"]

    @property
    def data_type(self) -> T.DataType:
        raise NotImplementedError(type(self).__name__)

    @property
    def nullable(self) -> bool:
        return True

    def eval(self, batch: HostBatch) -> HostColumn:
        raise NotImplementedError(
            f"CPU eval not implemented for {type(self).__name__}")

    @property
    def pretty_name(self) -> str:
        return type(self).__name__.lower()

    def __repr__(self) -> str:
        cs = ", ".join(repr(c) for c in self.children)
        return f"{type(self).__name__}({cs})"

    def transform(self, fn: Callable[["Expression"], Optional["Expression"]]
                  ) -> "Expression":
        """Bottom-up transform; fn returns replacement or None to keep."""
        new_children = [c.transform(fn) for c in self.children]
        node = self
        if new_children != self.children:
            node = node.with_children(new_children)
        replaced = fn(node)
        return replaced if replaced is not None else node

    def with_children(self, children: List["Expression"]) -> "Expression":
        import copy
        node = copy.copy(self)
        node.children = children
        return node

    def collect(self, pred: Callable[["Expression"], bool]
                ) -> List["Expression"]:
        out = []
        if pred(self):
            out.append(self)
        for c in self.children:
            out.extend(c.collect(pred))
        return out

    def references(self) -> List["AttributeReference"]:
        return self.collect(lambda e: isinstance(e, AttributeReference))


# ---------------------------------------------------------------------------
# Leaves
# ---------------------------------------------------------------------------

class Literal(Expression):
    def __init__(self, value: Any, dtype: Optional[T.DataType] = None):
        self.children = []
        if dtype is None:
            dtype = _infer_literal_type(value)
        self.value = value
        self._dtype = dtype

    @property
    def data_type(self) -> T.DataType:
        return self._dtype

    @property
    def nullable(self) -> bool:
        return self.value is None

    def eval(self, batch: HostBatch) -> HostColumn:
        from spark_rapids_tpu.columnar.host import _to_storage
        n = batch.num_rows
        if self.value is None:
            return HostColumn.nulls(n, self._dtype)
        if T.is_limb_decimal(self._dtype):
            from spark_rapids_tpu.ops import int128 as I
            u = _to_storage(self.value, self._dtype)
            hi, lo = I.from_pyints([u])
            data = np.empty((n, 2), dtype=np.int64)
            data[:, 0] = hi[0]
            data[:, 1] = lo[0]
            return HostColumn.all_valid(data, self._dtype)
        np_dt = T.numpy_dtype(self._dtype)
        if np_dt == np.dtype(object):
            data = np.full(n, self.value, dtype=object)
        else:
            data = np.full(n, _to_storage(self.value, self._dtype),
                           dtype=np_dt)
        return HostColumn.all_valid(data, self._dtype)

    def __repr__(self) -> str:
        return f"lit({self.value!r})"


def _infer_literal_type(v: Any) -> T.DataType:
    import datetime
    if v is None:
        return T.NullT
    if isinstance(v, bool):
        return T.BooleanT
    if isinstance(v, int):
        return T.IntegerT if -(2**31) <= v < 2**31 else T.LongT
    if isinstance(v, float):
        return T.DoubleT
    if isinstance(v, str):
        return T.StringT
    if isinstance(v, bytes):
        return T.BinaryT
    if isinstance(v, datetime.datetime):
        return T.TimestampT
    if isinstance(v, datetime.date):
        return T.DateT
    import decimal
    if isinstance(v, decimal.Decimal):
        sign, digits, exp = v.as_tuple()
        scale = -exp if exp < 0 else 0
        return T.DecimalType(max(len(digits), scale), scale)
    raise TypeError(f"cannot infer literal type for {v!r}")


class AttributeReference(Expression):
    """A resolved column with a unique id (Catalyst AttributeReference).
    ``qualifier`` carries the relation alias/table name so ``t.col``
    references resolve against the right side of a join (Catalyst keeps
    a qualifier seq on every attribute the same way)."""

    def __init__(self, name: str, dtype: T.DataType, nullable: bool = True,
                 expr_id: Optional[int] = None,
                 qualifier: Optional[str] = None):
        self.children = []
        self.name = name
        self._dtype = dtype
        self._nullable = nullable
        self.expr_id = expr_id if expr_id is not None else next_expr_id()
        self.qualifier = qualifier

    def with_qualifier(self, qualifier: str) -> "AttributeReference":
        return AttributeReference(self.name, self._dtype, self._nullable,
                                  self.expr_id, qualifier)

    @property
    def data_type(self) -> T.DataType:
        return self._dtype

    @property
    def nullable(self) -> bool:
        return self._nullable

    def __repr__(self) -> str:
        return f"{self.name}#{self.expr_id}"

    def __eq__(self, other) -> bool:
        return (isinstance(other, AttributeReference)
                and other.expr_id == self.expr_id)

    def __hash__(self) -> int:
        return hash(("attr", self.expr_id))

    def renamed(self, name: str) -> "AttributeReference":
        return AttributeReference(name, self._dtype, self._nullable,
                                  self.expr_id)


class UnresolvedAttribute(Expression):
    def __init__(self, name: str):
        self.children = []
        self.name = name

    @property
    def data_type(self) -> T.DataType:
        raise RuntimeError(f"unresolved attribute {self.name}")

    def __repr__(self) -> str:
        return f"'{self.name}"


class BoundReference(Expression):
    """Column by ordinal after binding (Catalyst BoundReference)."""

    def __init__(self, ordinal: int, dtype: T.DataType, nullable: bool):
        self.children = []
        self.ordinal = ordinal
        self._dtype = dtype
        self._nullable = nullable

    @property
    def data_type(self) -> T.DataType:
        return self._dtype

    @property
    def nullable(self) -> bool:
        return self._nullable

    def eval(self, batch: HostBatch) -> HostColumn:
        return batch.columns[self.ordinal]

    def __repr__(self) -> str:
        return f"input[{self.ordinal}]"


class Alias(Expression):
    def __init__(self, child: Expression, name: str,
                 expr_id: Optional[int] = None,
                 qualifier: Optional[str] = None):
        self.children = [child]
        self.name = name
        self.expr_id = expr_id if expr_id is not None else next_expr_id()
        self.qualifier = qualifier  # kept by self-join dedup re-aliasing

    @property
    def child(self) -> Expression:
        return self.children[0]

    @property
    def data_type(self) -> T.DataType:
        return self.child.data_type

    @property
    def nullable(self) -> bool:
        return self.child.nullable

    def eval(self, batch: HostBatch) -> HostColumn:
        return self.child.eval(batch)

    def to_attribute(self) -> AttributeReference:
        return AttributeReference(self.name, self.data_type, self.nullable,
                                  self.expr_id, self.qualifier)

    def __repr__(self) -> str:
        return f"{self.child!r} AS {self.name}#{self.expr_id}"


def named_output(expr: Expression) -> AttributeReference:
    """Output attribute for a projection item (Catalyst NamedExpression)."""
    if isinstance(expr, Alias):
        return expr.to_attribute()
    if isinstance(expr, AttributeReference):
        return expr
    raise TypeError(f"not a named expression: {expr!r}")


def bind_references(expr: Expression, input_attrs: Sequence[AttributeReference]
                    ) -> Expression:
    ids = {a.expr_id: i for i, a in enumerate(input_attrs)}

    def rule(e: Expression) -> Optional[Expression]:
        if isinstance(e, AttributeReference):
            if e.expr_id not in ids:
                raise KeyError(f"couldn't bind {e!r} against {input_attrs}")
            return BoundReference(ids[e.expr_id], e.data_type, e.nullable)
        return None

    return expr.transform(rule)


# ---------------------------------------------------------------------------
# Eval helpers
# ---------------------------------------------------------------------------

def _combined_validity(cols: Sequence[HostColumn]) -> np.ndarray:
    v = cols[0].validity
    for c in cols[1:]:
        v = v & c.validity
    return v.copy()


class UnaryExpression(Expression):
    @property
    def child(self) -> Expression:
        return self.children[0]


class BinaryExpression(Expression):
    @property
    def left(self) -> Expression:
        return self.children[0]

    @property
    def right(self) -> Expression:
        return self.children[1]


# ---------------------------------------------------------------------------
# Arithmetic (Spark semantics: null-propagating; non-ANSI ints wrap like
# Java two's complement — numpy matches; see GpuAdd etc. in the reference's
# arithmetic.scala)
# ---------------------------------------------------------------------------

class BinaryArithmetic(BinaryExpression):
    symbol = "?"

    def __init__(self, left: Expression, right: Expression):
        self.children = [left, right]

    @property
    def data_type(self) -> T.DataType:
        lt = self.left.data_type
        if self.symbol in ("+", "-", "*", "/") \
                and isinstance(lt, T.DecimalType) \
                and isinstance(self.right.data_type, T.DecimalType):
            return T.decimal_binary_result(self.symbol, lt,
                                           self.right.data_type)
        return lt

    def op(self, a: np.ndarray, b: np.ndarray) -> np.ndarray:
        raise NotImplementedError

    def eval(self, batch: HostBatch) -> HostColumn:
        lc = self.left.eval(batch)
        rc = self.right.eval(batch)
        validity = _combined_validity([lc, rc])
        if self.symbol in ("+", "-", "*") and \
                isinstance(self.data_type, T.DecimalType):
            return _decimal_arith(self.symbol, lc, rc, validity,
                                  self.data_type)
        with np.errstate(all="ignore"):
            data = self.op(lc.data, rc.data)
        np_dt = T.numpy_dtype(self.data_type)
        if data.dtype != np_dt:
            data = data.astype(np_dt)
        return HostColumn(self.data_type, data, validity).normalized()


class Add(BinaryArithmetic):
    symbol = "+"

    def op(self, a, b):
        return a + b


class Subtract(BinaryArithmetic):
    symbol = "-"

    def op(self, a, b):
        return a - b


class Multiply(BinaryArithmetic):
    symbol = "*"

    def op(self, a, b):
        return a * b


class Divide(BinaryArithmetic):
    """Fractional division (Spark analyzer casts ints to double first).
    Spark non-ANSI returns NULL for a zero divisor on every numeric type
    (unlike IEEE); ANSI raises."""
    symbol = "/"

    def op(self, a, b):
        return np.divide(a, b)

    def eval(self, batch: HostBatch) -> HostColumn:
        if isinstance(self.data_type, T.DecimalType):
            return _decimal_divide(self, batch)
        lc = self.left.eval(batch)
        rc = self.right.eval(batch)
        validity = _combined_validity([lc, rc]) & (rc.data != 0)
        with np.errstate(all="ignore"):
            data = np.divide(lc.data, np.where(rc.data != 0, rc.data, 1))
        np_dt = T.numpy_dtype(self.data_type)
        if data.dtype != np_dt:
            data = data.astype(np_dt)
        return HostColumn(self.data_type, data, validity).normalized()


class IntegralDivide(BinaryExpression):
    """`div`: long division, null on divide-by-zero (Spark IntegralDivide)."""

    def __init__(self, left: Expression, right: Expression):
        self.children = [left, right]

    @property
    def data_type(self) -> T.DataType:
        return T.LongT

    def eval(self, batch: HostBatch) -> HostColumn:
        lc, rc = self.left.eval(batch), self.right.eval(batch)
        a = lc.data.astype(np.int64)
        b = rc.data.astype(np.int64)
        validity = _combined_validity([lc, rc]) & (b != 0)
        with np.errstate(all="ignore"):
            safe_b = np.where(b == 0, 1, b)
            # Java integer division truncates toward zero; numpy floors.
            q = np.abs(a) // np.abs(safe_b)
            data = np.where((a < 0) != (safe_b < 0), -q, q).astype(np.int64)
        return HostColumn(T.LongT, data, validity).normalized()


class Remainder(BinaryArithmetic):
    """% with Java sign semantics (follows dividend); x % 0 -> null for
    all numeric types in Spark non-ANSI mode."""
    symbol = "%"

    def eval(self, batch: HostBatch) -> HostColumn:
        lc, rc = self.left.eval(batch), self.right.eval(batch)
        a, b = lc.data, rc.data
        validity = _combined_validity([lc, rc]) & (b != 0)
        with np.errstate(all="ignore"):
            safe_b = np.where(b == 0, 1, b)
            data = np.fmod(a, safe_b)
        np_dt = T.numpy_dtype(self.data_type)
        return HostColumn(self.data_type, data.astype(np_dt),
                          validity).normalized()


class Pmod(BinaryArithmetic):
    symbol = "pmod"

    def eval(self, batch: HostBatch) -> HostColumn:
        lc, rc = self.left.eval(batch), self.right.eval(batch)
        a, b = lc.data, rc.data
        # Spark DivModLike: divisor 0 -> null for ALL numeric types
        validity = _combined_validity([lc, rc]) & (b != 0)
        with np.errstate(all="ignore"):
            b = np.where(b == 0, 1, b).astype(b.dtype)
            r = np.fmod(a, b)
            data = np.where((r != 0) & ((r < 0) != (b < 0)), r + b, r)
        np_dt = T.numpy_dtype(self.data_type)
        return HostColumn(self.data_type, data.astype(np_dt),
                          validity).normalized()


class BitwiseAnd(BinaryArithmetic):
    """& over integral types (GpuBitwiseAnd, arithmetic.scala role)."""
    symbol = "&"

    def op(self, a, b):
        return a & b


class BitwiseOr(BinaryArithmetic):
    symbol = "|"

    def op(self, a, b):
        return a | b


class BitwiseXor(BinaryArithmetic):
    symbol = "^"

    def op(self, a, b):
        return a ^ b


class BitwiseNot(UnaryExpression):
    def __init__(self, child: Expression):
        self.children = [child]

    @property
    def data_type(self) -> T.DataType:
        return self.child.data_type

    def eval(self, batch: HostBatch) -> HostColumn:
        c = self.child.eval(batch)
        return HostColumn(self.data_type, ~c.data,
                          c.validity.copy()).normalized()


class _Shift(BinaryExpression):
    """Java shift semantics: the amount is masked to the value width
    (x << 65 == x << 1 for long), like the JVM bytecodes Spark compiles
    to (GpuShiftLeft/Right/RightUnsigned twins)."""

    def __init__(self, left: Expression, right: Expression):
        self.children = [left, right]

    @property
    def data_type(self) -> T.DataType:
        return self.left.data_type

    def _mask(self) -> int:
        return 63 if isinstance(self.data_type, T.LongType) else 31

    def eval(self, batch: HostBatch) -> HostColumn:
        lc, rc = self.left.eval(batch), self.right.eval(batch)
        validity = _combined_validity([lc, rc])
        n = (rc.data.astype(np.int64) & self._mask()).astype(np.int64)
        data = self.shift(lc.data, n)
        np_dt = T.numpy_dtype(self.data_type)
        return HostColumn(self.data_type, data.astype(np_dt),
                          validity).normalized()

    def shift(self, a: np.ndarray, n: np.ndarray) -> np.ndarray:
        raise NotImplementedError


class ShiftLeft(_Shift):
    def shift(self, a, n):
        return a << n


class ShiftRight(_Shift):
    def shift(self, a, n):
        return a >> n  # numpy >> on signed ints is arithmetic, like Java


class ShiftRightUnsigned(_Shift):
    def shift(self, a, n):
        if a.dtype == np.dtype(np.int64):
            return (a.view(np.uint64) >> n.astype(np.uint64)).view(np.int64)
        return (a.astype(np.int32).view(np.uint32)
                >> n.astype(np.uint32)).view(np.int32)


class Greatest(Expression):
    """Row-wise max skipping nulls; null only when every input is null
    (Spark Greatest; NaN is greatest among floats)."""
    is_min = False

    def __init__(self, children: List[Expression]):
        self.children = list(children)

    @property
    def data_type(self) -> T.DataType:
        return self.children[0].data_type

    def eval(self, batch: HostBatch) -> HostColumn:
        cols = [c.eval(batch) for c in self.children]
        np_dt = T.numpy_dtype(self.data_type)
        validity = np.zeros(batch.num_rows, dtype=bool)
        for c in cols:
            validity |= c.validity
        is_float = np.issubdtype(np_dt, np.floating)
        data = None
        for c in cols:
            d = c.data.astype(np_dt)
            if data is None:
                data, have = d.copy(), c.validity.copy()
                continue
            if is_float:
                # NaN ranks greatest (Spark total order)
                better = (np.isnan(d) | (d > data)) if not self.is_min \
                    else ((~np.isnan(d)) & ((d < data) | np.isnan(data)))
            else:
                better = (d > data) if not self.is_min else (d < data)
            take = c.validity & (~have | better)
            data = np.where(take, d, data)
            have |= c.validity
        return HostColumn(self.data_type, data, validity).normalized()


class Least(Greatest):
    """Row-wise min skipping nulls (NaN still sorts greatest)."""
    is_min = True


class UnaryMinus(UnaryExpression):
    def __init__(self, child: Expression):
        self.children = [child]

    @property
    def data_type(self) -> T.DataType:
        return self.child.data_type

    def eval(self, batch: HostBatch) -> HostColumn:
        c = self.child.eval(batch)
        if T.is_limb_decimal(self.data_type):
            from spark_rapids_tpu.ops import int128 as I
            hi, lo = I.neg(np, *_dec_limbs(c))
            return _limbs_to_col(hi, lo, c.validity.copy(), self.data_type)
        with np.errstate(all="ignore"):
            return HostColumn(self.data_type, -c.data, c.validity.copy())


class Abs(UnaryExpression):
    def __init__(self, child: Expression):
        self.children = [child]

    @property
    def data_type(self) -> T.DataType:
        return self.child.data_type

    def eval(self, batch: HostBatch) -> HostColumn:
        c = self.child.eval(batch)
        if T.is_limb_decimal(self.data_type):
            from spark_rapids_tpu.ops import int128 as I
            hi, lo = I.abs_(np, *_dec_limbs(c))
            return _limbs_to_col(hi, lo, c.validity.copy(), self.data_type)
        with np.errstate(all="ignore"):
            return HostColumn(self.data_type, np.abs(c.data),
                              c.validity.copy())


def _dec_limbs(col: HostColumn):
    """HostColumn (decimal storage) -> (hi, lo) int64 limb arrays."""
    from spark_rapids_tpu.ops import int128 as I
    if T.is_limb_decimal(col.dtype):
        return np.ascontiguousarray(col.data[:, 0]), \
            np.ascontiguousarray(col.data[:, 1])
    return I.from_i64(np, col.data.astype(np.int64))


def _limbs_to_col(hi, lo, validity, dt: T.DecimalType) -> HostColumn:
    from spark_rapids_tpu.ops import decimal_ops as D
    if T.is_limb_decimal(dt):
        hi = np.where(validity, hi, 0)
        lo = np.where(validity, lo, 0)
        return HostColumn(dt, np.stack([hi, lo], axis=1), validity)
    v = D.to_i64_unscaled(np, hi, lo)
    return HostColumn(dt, np.where(validity, v, 0), validity)


def _decimal_arith(sym: str, lc: HostColumn, rc: HostColumn,
                   validity: np.ndarray, res: T.DecimalType) -> HostColumn:
    """Host +,-,* on decimals: vectorized limb math when the shapes are
    in the supported envelope, exact Python-int fallback otherwise
    (CheckOverflow -> NULL, non-ANSI)."""
    from spark_rapids_tpu.ops import decimal_ops as D
    lt, rt = lc.dtype, rc.dtype
    if sym in ("+", "-"):
        if not D.add_sub_supported(lt, rt):
            return _decimal_slow(sym, lc, rc, validity, res)
        ahi, alo = _dec_limbs(lc)
        bhi, blo = _dec_limbs(rc)
        hi, lo, ok = D.add_sub(np, sym, ahi, alo, bhi, blo, lt, rt, res)
    elif D.mul_supported(lt, rt):
        ahi, alo = _dec_limbs(lc)
        bhi, blo = _dec_limbs(rc)
        hi, lo, ok = D.mul(np, ahi, alo, bhi, blo, lt, rt, res)
    else:  # exact slow path (both operands wide, or deep rescale)
        return _decimal_slow(sym, lc, rc, validity, res)
    return _limbs_to_col(hi, lo, validity & ok, res)


def _decimal_slow(sym: str, lc: HostColumn, rc: HostColumn,
                  validity: np.ndarray, res: T.DecimalType) -> HostColumn:
    from spark_rapids_tpu.ops import int128 as I
    a = I.to_pyints(*_dec_limbs(lc))
    b = I.to_pyints(*_dec_limbs(rc))
    s1, s2 = lc.dtype.scale, rc.dtype.scale
    out = []
    bound = 10 ** res.precision

    def _to_scale(v: int, s_from: int) -> int:
        # per-operand cast to the result scale (HALF_UP on reduction),
        # matching Spark's PromotePrecision(Cast(operand, resultType))
        d = res.scale - s_from
        if d >= 0:
            return v * 10 ** d
        q, r = divmod(abs(v), 10 ** -d)
        if 2 * r >= 10 ** -d:
            q += 1
        return q if v >= 0 else -q

    for x, y, ok in zip(a, b, validity):
        if not ok:
            out.append(None)
            continue
        if sym == "+":
            v = _to_scale(x, s1) + _to_scale(y, s2)
        elif sym == "-":
            v = _to_scale(x, s1) - _to_scale(y, s2)
        elif sym == "*":
            v = x * y
            down = (s1 + s2) - res.scale
            if down > 0:
                d = 10 ** down
                q, r = divmod(abs(v), d)
                if 2 * r >= d:
                    q += 1
                v = q if v >= 0 else -q
        else:  # "/"
            if y == 0:
                out.append(None)
                continue
            num = x * 10 ** (res.scale - s1 + s2)
            q, r = divmod(abs(num), abs(y))
            if 2 * r >= abs(y):
                q += 1
            v = q if (num >= 0) == (y >= 0) else -q
        out.append(None if abs(v) >= bound else v)
    from decimal import Decimal
    return HostColumn.from_pylist(
        [None if v is None else Decimal(v).scaleb(-res.scale)
         for v in out], res)


def _decimal_divide(node: Divide, batch: HostBatch) -> HostColumn:
    """Spark decimal division: HALF_UP at the DecimalPrecision result
    scale, NULL on zero divisor (non-ANSI) or overflow."""
    from spark_rapids_tpu.ops import decimal_ops as D
    lc = node.left.eval(batch)
    rc = node.right.eval(batch)
    res = node.data_type
    lt, rt = lc.dtype, rc.dtype
    if T.is_limb_decimal(rt):
        bhi, blo = _dec_limbs(rc)
        nonzero = (bhi != 0) | (blo != 0)
    else:
        nonzero = rc.data.astype(np.int64) != 0
    validity = _combined_validity([lc, rc]) & nonzero
    if not D.div_supported(lt, rt):
        return _decimal_slow("/", lc, rc, validity, res)
    ahi, alo = _dec_limbs(lc)
    # div_supported caps the divisor at 18 digits -> plain int64 storage
    assert not T.is_limb_decimal(rt), rt
    d_safe = np.where(nonzero, rc.data.astype(np.int64), 1)
    hi, lo, ok = D.div(np, ahi, alo, d_safe, lt, rt, res)
    return _limbs_to_col(hi, lo, validity & ok, res)


# ---------------------------------------------------------------------------
# Comparisons. Spark orders NaN greater than any other value and
# NaN == NaN is true (unlike IEEE); see the reference's hasNans handling.
# ---------------------------------------------------------------------------

class BinaryComparison(BinaryExpression):
    symbol = "?"

    def __init__(self, left: Expression, right: Expression):
        self.children = [left, right]

    @property
    def data_type(self) -> T.DataType:
        return T.BooleanT

    def cmp(self, a: np.ndarray, b: np.ndarray) -> np.ndarray:
        raise NotImplementedError

    def eval(self, batch: HostBatch) -> HostColumn:
        lc, rc = self.left.eval(batch), self.right.eval(batch)
        validity = _combined_validity([lc, rc])
        data = self._compare(lc, rc)
        return HostColumn(T.BooleanT, data, validity).normalized()

    def _compare(self, lc: HostColumn, rc: HostColumn) -> np.ndarray:
        a, b = lc.data, rc.data
        if T.is_limb_decimal(lc.dtype) or T.is_limb_decimal(rc.dtype):
            # coercion aligned both sides to one (wide) decimal type:
            # reduce the limb comparison to a sign surrogate so every
            # operator reuses its scalar cmp
            from spark_rapids_tpu.ops import int128 as I
            ahi, alo = _dec_limbs(lc)
            bhi, blo = _dec_limbs(rc)
            lt = I.cmp_lt(np, ahi, alo, bhi, blo)
            eqm = I.eq(np, ahi, alo, bhi, blo)
            sign = np.where(lt, -1, np.where(eqm, 0, 1)).astype(np.int8)
            return self.cmp(sign, np.zeros_like(sign))
        if a.dtype == np.dtype(object):
            n = len(a)
            out = np.zeros(n, dtype=bool)
            for i in range(n):
                out[i] = self.cmp_scalar(a[i], b[i])
            return out
        if np.issubdtype(a.dtype, np.floating):
            # Total order with NaN largest: compare via ordered keys.
            ka, kb = _float_total_order(a), _float_total_order(b)
            return self.cmp(ka, kb)
        return self.cmp(a, b)

    def cmp_scalar(self, a, b) -> bool:
        return bool(self.cmp(np.array([a], dtype=object),
                             np.array([b], dtype=object))[0])


def _float_total_order(a: np.ndarray) -> np.ndarray:
    """Map floats to unsigned keys preserving Spark's total order
    (-inf < ... < -0.0 = 0.0 < ... < inf < NaN; all NaNs equal).

    Classic radix trick on the IEEE bit pattern: flip all bits for
    negatives, set the sign bit for non-negatives; NaNs and -0.0 are
    canonicalized first so every NaN maps to one (maximal) key.
    """
    v = (a.astype(np.float32) if a.dtype == np.float32
         else a.astype(np.float64)).copy()
    v[np.isnan(v)] = np.nan  # canonical positive NaN
    v[v == 0.0] = 0.0        # fold -0.0 into +0.0
    if v.dtype == np.float32:
        u = v.view(np.uint32)
        return np.where((u >> np.uint32(31)) == 1, ~u,
                        u | np.uint32(0x80000000))
    u = v.view(np.uint64)
    return np.where((u >> np.uint64(63)) == 1, ~u,
                    u | np.uint64(0x8000000000000000))


class EqualTo(BinaryComparison):
    symbol = "="

    def cmp(self, a, b):
        return a == b


class LessThan(BinaryComparison):
    symbol = "<"

    def cmp(self, a, b):
        return a < b


class LessThanOrEqual(BinaryComparison):
    symbol = "<="

    def cmp(self, a, b):
        return a <= b


class GreaterThan(BinaryComparison):
    symbol = ">"

    def cmp(self, a, b):
        return a > b


class GreaterThanOrEqual(BinaryComparison):
    symbol = ">="

    def cmp(self, a, b):
        return a >= b


class EqualNullSafe(BinaryComparison):
    """<=>: never null; null <=> null is true."""
    symbol = "<=>"

    def cmp(self, a, b):
        return a == b

    @property
    def nullable(self) -> bool:
        return False

    def eval(self, batch: HostBatch) -> HostColumn:
        lc, rc = self.left.eval(batch), self.right.eval(batch)
        both_valid = lc.validity & rc.validity
        both_null = (~lc.validity) & (~rc.validity)
        eq = self._compare(lc, rc)
        data = np.where(both_valid, eq, both_null)
        return HostColumn.all_valid(data.astype(bool), T.BooleanT)


# ---------------------------------------------------------------------------
# Logic (3-valued)
# ---------------------------------------------------------------------------

class And(BinaryExpression):
    def __init__(self, left: Expression, right: Expression):
        self.children = [left, right]

    @property
    def data_type(self) -> T.DataType:
        return T.BooleanT

    def eval(self, batch: HostBatch) -> HostColumn:
        lc, rc = self.left.eval(batch), self.right.eval(batch)
        lt = lc.validity & lc.data.astype(bool)
        lf = lc.validity & ~lc.data.astype(bool)
        rt = rc.validity & rc.data.astype(bool)
        rf = rc.validity & ~rc.data.astype(bool)
        data = lt & rt
        validity = lf | rf | (lt & rt)
        return HostColumn(T.BooleanT, data, validity).normalized()


class Or(BinaryExpression):
    def __init__(self, left: Expression, right: Expression):
        self.children = [left, right]

    @property
    def data_type(self) -> T.DataType:
        return T.BooleanT

    def eval(self, batch: HostBatch) -> HostColumn:
        lc, rc = self.left.eval(batch), self.right.eval(batch)
        lt = lc.validity & lc.data.astype(bool)
        rt = rc.validity & rc.data.astype(bool)
        lf = lc.validity & ~lc.data.astype(bool)
        rf = rc.validity & ~rc.data.astype(bool)
        data = lt | rt
        validity = lt | rt | (lf & rf)
        return HostColumn(T.BooleanT, data, validity).normalized()


class Not(UnaryExpression):
    def __init__(self, child: Expression):
        self.children = [child]

    @property
    def data_type(self) -> T.DataType:
        return T.BooleanT

    def eval(self, batch: HostBatch) -> HostColumn:
        c = self.child.eval(batch)
        return HostColumn(T.BooleanT, ~c.data.astype(bool),
                          c.validity.copy()).normalized()


class In(Expression):
    def __init__(self, value: Expression, items: List[Expression]):
        self.children = [value] + items

    @property
    def data_type(self) -> T.DataType:
        return T.BooleanT

    def eval(self, batch: HostBatch) -> HostColumn:
        vc = self.children[0].eval(batch)
        any_true = np.zeros(batch.num_rows, dtype=bool)
        any_null = np.zeros(batch.num_rows, dtype=bool)
        for item in self.children[1:]:
            ic = item.eval(batch)
            eq = EqualTo(self.children[0], item)._compare(vc, ic)
            valid = vc.validity & ic.validity
            any_true |= valid & eq
            any_null |= ~ic.validity
        validity = vc.validity & (any_true | ~any_null)
        return HostColumn(T.BooleanT, any_true, validity).normalized()


# ---------------------------------------------------------------------------
# Null handling / conditionals
# ---------------------------------------------------------------------------

class IsNull(UnaryExpression):
    def __init__(self, child: Expression):
        self.children = [child]

    @property
    def data_type(self) -> T.DataType:
        return T.BooleanT

    @property
    def nullable(self) -> bool:
        return False

    def eval(self, batch: HostBatch) -> HostColumn:
        c = self.child.eval(batch)
        return HostColumn.all_valid(~c.validity, T.BooleanT)


class IsNotNull(UnaryExpression):
    def __init__(self, child: Expression):
        self.children = [child]

    @property
    def data_type(self) -> T.DataType:
        return T.BooleanT

    @property
    def nullable(self) -> bool:
        return False

    def eval(self, batch: HostBatch) -> HostColumn:
        c = self.child.eval(batch)
        return HostColumn.all_valid(c.validity.copy(), T.BooleanT)


class IsNan(UnaryExpression):
    def __init__(self, child: Expression):
        self.children = [child]

    @property
    def data_type(self) -> T.DataType:
        return T.BooleanT

    @property
    def nullable(self) -> bool:
        return False

    def eval(self, batch: HostBatch) -> HostColumn:
        c = self.child.eval(batch)
        data = np.isnan(c.data) & c.validity
        return HostColumn.all_valid(data, T.BooleanT)


class Coalesce(Expression):
    def __init__(self, children: List[Expression]):
        self.children = list(children)

    @property
    def data_type(self) -> T.DataType:
        return self.children[0].data_type

    def eval(self, batch: HostBatch) -> HostColumn:
        """Later arguments evaluate only where every earlier one was null
        (short-circuit; matches the device handler's ANSI scoping)."""
        first = self.children[0].eval(batch)
        data = first.data.copy()
        validity = first.validity.copy()
        for child in self.children[1:]:
            idx = np.nonzero(~validity)[0]
            if not len(idx):
                break
            c = child.eval(batch.take(idx))
            data[idx] = np.where(c.validity, c.data, data[idx])
            validity[idx] = c.validity
        return HostColumn(self.data_type, data, validity).normalized()


class If(Expression):
    def __init__(self, predicate: Expression, true_value: Expression,
                 false_value: Expression):
        self.children = [predicate, true_value, false_value]

    @property
    def data_type(self) -> T.DataType:
        return self.children[1].data_type

    def eval(self, batch: HostBatch) -> HostColumn:
        """Arms evaluate only on their taken rows (Spark's lazy
        branches), so ANSI errors in the untaken arm never fire."""
        p = self.children[0].eval(batch)
        cond = p.validity & p.data.astype(bool)  # null predicate -> false
        n = batch.num_rows
        np_dt = T.numpy_dtype(self.data_type)
        data = (np.full(n, "", dtype=object)
                if np_dt == np.dtype(object) else np.zeros(n, dtype=np_dt))
        validity = np.zeros(n, dtype=bool)
        for mask, child in ((cond, self.children[1]),
                            (~cond, self.children[2])):
            idx = np.nonzero(mask)[0]
            if len(idx):
                v = child.eval(batch.take(idx))
                data[idx] = v.data
                validity[idx] = v.validity
        return HostColumn(self.data_type, data,
                          validity.astype(bool)).normalized()


class CaseWhen(Expression):
    """CASE WHEN p1 THEN v1 ... ELSE e END. children =
    [p1, v1, p2, v2, ..., (else)]."""

    def __init__(self, branches: List, else_value: Optional[Expression]):
        self.children = []
        for p, v in branches:
            self.children.extend([p, v])
        self.has_else = else_value is not None
        if else_value is not None:
            self.children.append(else_value)

    @property
    def data_type(self) -> T.DataType:
        return self.children[1].data_type

    def eval(self, batch: HostBatch) -> HostColumn:
        """Branches evaluate only on the rows that REACH them (Spark's
        first-match short-circuit), so ANSI errors inside an untaken
        branch never fire."""
        n = batch.num_rows
        np_dt = T.numpy_dtype(self.data_type)
        data = (np.full(n, "", dtype=object)
                if np_dt == np.dtype(object) else np.zeros(n, dtype=np_dt))
        validity = np.zeros(n, dtype=bool)
        decided = np.zeros(n, dtype=bool)
        pairs = (self.children[:-1] if self.has_else else self.children)
        for i in range(0, len(pairs), 2):
            und = np.nonzero(~decided)[0]
            if not len(und):
                break
            sub = batch.take(und)
            p = pairs[i].eval(sub)
            hit_idx = und[p.validity & p.data.astype(bool)]
            if len(hit_idx):
                v = pairs[i + 1].eval(batch.take(hit_idx))
                data[hit_idx] = v.data
                validity[hit_idx] = v.validity
                decided[hit_idx] = True
        if self.has_else:
            rest = np.nonzero(~decided)[0]
            if len(rest):
                e = self.children[-1].eval(batch.take(rest))
                data[rest] = e.data
                validity[rest] = e.validity
        return HostColumn(self.data_type, data, validity).normalized()


# ---------------------------------------------------------------------------
# Math functions
# ---------------------------------------------------------------------------

class UnaryMath(UnaryExpression):
    np_fn: Callable = None

    def __init__(self, child: Expression):
        self.children = [child]

    @property
    def data_type(self) -> T.DataType:
        return T.DoubleT

    def eval(self, batch: HostBatch) -> HostColumn:
        c = self.child.eval(batch)
        with np.errstate(all="ignore"):
            data = type(self).np_fn(c.data.astype(np.float64))
        return HostColumn(T.DoubleT, data, c.validity.copy()).normalized()


class Sqrt(UnaryMath):
    np_fn = np.sqrt


class Exp(UnaryMath):
    np_fn = np.exp


class Log(UnaryMath):
    """Natural log; Spark non-ANSI returns null for x <= 0."""

    def eval(self, batch: HostBatch) -> HostColumn:
        c = self.child.eval(batch)
        x = c.data.astype(np.float64)
        validity = c.validity & (x > 0)
        with np.errstate(all="ignore"):
            data = np.log(np.where(x > 0, x, 1.0))
        return HostColumn(T.DoubleT, data, validity).normalized()


class Log10(UnaryMath):
    def eval(self, batch: HostBatch) -> HostColumn:
        c = self.child.eval(batch)
        x = c.data.astype(np.float64)
        validity = c.validity & (x > 0)
        with np.errstate(all="ignore"):
            data = np.log10(np.where(x > 0, x, 1.0))
        return HostColumn(T.DoubleT, data, validity).normalized()


class Sin(UnaryMath):
    np_fn = np.sin


class Cos(UnaryMath):
    np_fn = np.cos


class Tan(UnaryMath):
    np_fn = np.tan


class Asin(UnaryMath):
    np_fn = np.arcsin


class Acos(UnaryMath):
    np_fn = np.arccos


class Atan(UnaryMath):
    np_fn = np.arctan


class Sinh(UnaryMath):
    np_fn = np.sinh


class Cosh(UnaryMath):
    np_fn = np.cosh


class Tanh(UnaryMath):
    np_fn = np.tanh


class Signum(UnaryMath):
    """Java Math.signum: preserves ±0.0 and NaN (np.sign folds -0.0)."""

    @staticmethod
    def np_fn(x):
        return np.where(x == 0.0, x, np.sign(x))


class Log2(UnaryMath):
    def eval(self, batch: HostBatch) -> HostColumn:
        c = self.child.eval(batch)
        x = c.data.astype(np.float64)
        validity = c.validity & (x > 0)
        with np.errstate(all="ignore"):
            data = np.log2(np.where(x > 0, x, 1.0))
        return HostColumn(T.DoubleT, data, validity).normalized()


class Log1p(UnaryMath):
    def eval(self, batch: HostBatch) -> HostColumn:
        c = self.child.eval(batch)
        x = c.data.astype(np.float64)
        validity = c.validity & (x > -1.0)
        with np.errstate(all="ignore"):
            data = np.log1p(np.where(x > -1.0, x, 0.0))
        return HostColumn(T.DoubleT, data, validity).normalized()


class Expm1(UnaryMath):
    np_fn = np.expm1


class Cbrt(UnaryMath):
    np_fn = np.cbrt


class Rint(UnaryMath):
    np_fn = np.rint  # Math.rint = round-half-even, same as IEEE rint


class ToDegrees(UnaryMath):
    np_fn = np.degrees


class ToRadians(UnaryMath):
    np_fn = np.radians


class BinaryMath(BinaryExpression):
    np_fn: Callable = None

    def __init__(self, left: Expression, right: Expression):
        self.children = [left, right]

    @property
    def data_type(self) -> T.DataType:
        return T.DoubleT

    def eval(self, batch: HostBatch) -> HostColumn:
        lc, rc = self.left.eval(batch), self.right.eval(batch)
        validity = _combined_validity([lc, rc])
        with np.errstate(all="ignore"):
            data = type(self).np_fn(lc.data.astype(np.float64),
                                    rc.data.astype(np.float64))
        return HostColumn(T.DoubleT, data, validity).normalized()


class Atan2(BinaryMath):
    np_fn = np.arctan2


class Hypot(BinaryMath):
    np_fn = np.hypot


class Floor(UnaryExpression):
    def __init__(self, child: Expression):
        self.children = [child]

    @property
    def data_type(self) -> T.DataType:
        return T.LongT

    def eval(self, batch: HostBatch) -> HostColumn:
        c = self.child.eval(batch)
        with np.errstate(all="ignore"):
            data = _java_double_to_long(np.floor(c.data.astype(np.float64)))
        return HostColumn(T.LongT, data, c.validity.copy()).normalized()


class Ceil(UnaryExpression):
    def __init__(self, child: Expression):
        self.children = [child]

    @property
    def data_type(self) -> T.DataType:
        return T.LongT

    def eval(self, batch: HostBatch) -> HostColumn:
        c = self.child.eval(batch)
        with np.errstate(all="ignore"):
            data = _java_double_to_long(np.ceil(c.data.astype(np.float64)))
        return HostColumn(T.LongT, data, c.validity.copy()).normalized()


def _java_double_to_long(x: np.ndarray) -> np.ndarray:
    """Java (long) cast: NaN -> 0, saturate at Long.MIN/MAX, trunc.

    Saturation needs threshold compares: float(Long.MAX) rounds up to
    2**63, so clip-then-astype would wrap positive overflow to MIN."""
    info = np.iinfo(np.int64)
    with np.errstate(all="ignore"):
        y = np.nan_to_num(x, nan=0.0, posinf=0.0, neginf=0.0)
        hi = x >= 2.0 ** 63          # covers +inf
        lo = x <= -(2.0 ** 63) - 1.0  # -2^63 itself is representable
        y = np.where(hi | lo, 0.0, y)
        out = y.astype(np.int64)
        out = np.where(hi, info.max, out)
        out = np.where(lo | (x == -np.inf), info.min, out)
        return np.where(np.isnan(x), 0, out)


class Pow(BinaryExpression):
    def __init__(self, left: Expression, right: Expression):
        self.children = [left, right]

    @property
    def data_type(self) -> T.DataType:
        return T.DoubleT

    def eval(self, batch: HostBatch) -> HostColumn:
        lc, rc = self.left.eval(batch), self.right.eval(batch)
        validity = _combined_validity([lc, rc])
        with np.errstate(all="ignore"):
            data = np.power(lc.data.astype(np.float64),
                            rc.data.astype(np.float64))
        return HostColumn(T.DoubleT, data, validity).normalized()


class Round(Expression):
    """HALF_UP rounding (Spark Round)."""

    def __init__(self, child: Expression, scale: Expression):
        self.children = [child, scale]

    @property
    def data_type(self) -> T.DataType:
        return self.children[0].data_type

    def eval(self, batch: HostBatch) -> HostColumn:
        c = self.children[0].eval(batch)
        scale = self.children[1]
        assert isinstance(scale, Literal), "round scale must be literal"
        s = int(scale.value)
        x = c.data
        if np.issubdtype(x.dtype, np.integer):
            if s >= 0:
                data = x.copy()
            else:
                p = 10 ** (-s)
                half = p // 2
                data = ((np.abs(x) + half) // p * p) * np.sign(x)
                data = data.astype(x.dtype)
        else:
            with np.errstate(all="ignore"):
                p = 10.0 ** s
                scaled = x.astype(np.float64) * p
                # HALF_UP: away from zero on ties (np.round is HALF_EVEN)
                data = (np.sign(scaled)
                        * np.floor(np.abs(scaled) + 0.5)) / p
                data = data.astype(x.dtype)
        return HostColumn(self.data_type, data, c.validity.copy()).normalized()


# ---------------------------------------------------------------------------
# Strings (host: object arrays; per-row loops are acceptable on the CPU
# baseline path). Mirrors the reference's stringFunctions.scala surface.
# ---------------------------------------------------------------------------

class StringUnary(UnaryExpression):
    def __init__(self, child: Expression):
        self.children = [child]

    @property
    def data_type(self) -> T.DataType:
        return T.StringT

    def fn(self, s: str) -> Any:
        raise NotImplementedError

    def eval(self, batch: HostBatch) -> HostColumn:
        c = self.child.eval(batch)
        out = np.empty(len(c.data), dtype=T.numpy_dtype(self.data_type))
        if out.dtype == np.dtype(object):
            out[:] = ""
        for i in range(len(c.data)):
            if c.validity[i]:
                out[i] = self.fn(c.data[i])
        return HostColumn(self.data_type, out, c.validity.copy())


class Upper(StringUnary):
    def fn(self, s: str) -> str:
        return s.upper()


class Lower(StringUnary):
    def fn(self, s: str) -> str:
        return s.lower()


class Length(StringUnary):
    @property
    def data_type(self) -> T.DataType:
        return T.IntegerT

    def eval(self, batch: HostBatch) -> HostColumn:
        c = self.child.eval(batch)
        data = np.array([len(s) if v else 0
                         for s, v in zip(c.data, c.validity)], dtype=np.int32)
        return HostColumn(T.IntegerT, data, c.validity.copy())


class StringTrim(StringUnary):
    def fn(self, s: str) -> str:
        return s.strip(" ")


class Substring(Expression):
    """1-based substring with Spark's negative-position semantics."""

    def __init__(self, child: Expression, pos: Expression, length: Expression):
        self.children = [child, pos, length]

    @property
    def data_type(self) -> T.DataType:
        return T.StringT

    def eval(self, batch: HostBatch) -> HostColumn:
        c = self.children[0].eval(batch)
        p = self.children[1].eval(batch)
        ln = self.children[2].eval(batch)
        validity = _combined_validity([c, p, ln])
        out = np.full(len(c.data), "", dtype=object)
        for i in range(len(c.data)):
            if not validity[i]:
                continue
            s = c.data[i]
            pos, length = int(p.data[i]), int(ln.data[i])
            if length <= 0:
                out[i] = ""
                continue
            if pos > 0:
                start = pos - 1
            elif pos == 0:
                start = 0
            else:
                start = max(len(s) + pos, 0)
                if len(s) + pos < 0:
                    length = length + (len(s) + pos)
                    if length <= 0:
                        out[i] = ""
                        continue
            out[i] = s[start:start + length]
        return HostColumn(T.StringT, out, validity)


class ConcatStr(Expression):
    def __init__(self, children: List[Expression]):
        self.children = list(children)

    @property
    def pretty_name(self) -> str:
        return "concat"

    @property
    def data_type(self) -> T.DataType:
        return T.StringT

    def eval(self, batch: HostBatch) -> HostColumn:
        cols = [c.eval(batch) for c in self.children]
        validity = _combined_validity(cols)
        out = np.full(batch.num_rows, "", dtype=object)
        for i in range(batch.num_rows):
            if validity[i]:
                out[i] = "".join(c.data[i] for c in cols)
        return HostColumn(T.StringT, out, validity)


class StartsWith(BinaryExpression):
    def __init__(self, left: Expression, right: Expression):
        self.children = [left, right]

    @property
    def data_type(self) -> T.DataType:
        return T.BooleanT

    def scalar(self, s: str, p: str) -> bool:
        return s.startswith(p)

    def eval(self, batch: HostBatch) -> HostColumn:
        lc, rc = self.left.eval(batch), self.right.eval(batch)
        validity = _combined_validity([lc, rc])
        out = np.zeros(batch.num_rows, dtype=bool)
        for i in range(batch.num_rows):
            if validity[i]:
                out[i] = self.scalar(lc.data[i], rc.data[i])
        return HostColumn(T.BooleanT, out, validity)


class EndsWith(StartsWith):
    def scalar(self, s: str, p: str) -> bool:
        return s.endswith(p)


class Contains(StartsWith):
    def scalar(self, s: str, p: str) -> bool:
        return p in s


class Like(StartsWith):
    """SQL LIKE with %% and _ wildcards, escape '\\'."""

    def scalar(self, s: str, p: str) -> bool:
        import re
        regex = _like_to_regex(p)
        return re.fullmatch(regex, s, flags=re.DOTALL) is not None


def _like_to_regex(pattern: str) -> str:
    import re
    out = []
    i = 0
    while i < len(pattern):
        ch = pattern[i]
        if ch == "\\" and i + 1 < len(pattern):
            out.append(re.escape(pattern[i + 1]))
            i += 2
            continue
        if ch == "%":
            out.append(".*")
        elif ch == "_":
            out.append(".")
        else:
            out.append(re.escape(ch))
        i += 1
    return "".join(out)


import threading as _threading

# Per-thread partition context for partition-aware expressions; set by
# the Project execs (pid, row_start) and the file scan (input_file)
# right before each batch evaluation.
_PART_CTX = _threading.local()


class SparkPartitionID(Expression):
    """spark_partition_id() (GpuSparkPartitionID role)."""

    children: List[Expression] = []

    def __init__(self):
        self.children = []

    @property
    def pretty_name(self) -> str:
        return "spark_partition_id"

    @property
    def data_type(self) -> T.DataType:
        return T.IntegerT

    @property
    def nullable(self) -> bool:
        return False

    def eval(self, batch: HostBatch) -> HostColumn:
        pid = getattr(_PART_CTX, "pid", 0)
        return HostColumn.all_valid(
            np.full(batch.num_rows, pid, dtype=np.int32), T.IntegerT)


class MonotonicallyIncreasingID(Expression):
    """monotonically_increasing_id(): partition id << 33 | row position
    within the partition (GpuMonotonicallyIncreasingID.scala)."""

    def __init__(self):
        self.children = []

    @property
    def pretty_name(self) -> str:
        return "monotonically_increasing_id"

    @property
    def data_type(self) -> T.DataType:
        return T.LongT

    @property
    def nullable(self) -> bool:
        return False

    def eval(self, batch: HostBatch) -> HostColumn:
        pid = getattr(_PART_CTX, "pid", 0)
        start = getattr(_PART_CTX, "row_start", 0)
        base = (pid << 33) + start
        return HostColumn.all_valid(
            base + np.arange(batch.num_rows, dtype=np.int64), T.LongT)


class InputFileName(Expression):
    """input_file_name(): path of the file the current rows came from;
    empty string outside a file scan (Spark semantics; the reference's
    InputFileBlockRule likewise confines it to scan-adjacent projects)."""

    def __init__(self):
        self.children = []

    @property
    def pretty_name(self) -> str:
        return "input_file_name"

    @property
    def data_type(self) -> T.DataType:
        return T.StringT

    @property
    def nullable(self) -> bool:
        return False

    def eval(self, batch: HostBatch) -> HostColumn:
        f = getattr(_PART_CTX, "input_file", "")
        return HostColumn.all_valid(
            np.full(batch.num_rows, f, dtype=object), T.StringT)


class RLike(StartsWith):
    """RLIKE / regexp: Java-regex search semantics (unanchored), CPU
    only — the device rewrite tags regexp to CPU (the reference gates
    GpuRLike behind cudf regex support the same way)."""

    def scalar(self, s: str, p: str) -> bool:
        import re
        return re.search(p, s) is not None


class RegExpReplace(Expression):
    """regexp_replace(str, pattern, replacement); CPU only."""

    def __init__(self, child: Expression, pattern: Expression,
                 replacement: Expression):
        self.children = [child, pattern, replacement]

    @property
    def pretty_name(self) -> str:
        return "regexp_replace"

    @property
    def data_type(self) -> T.DataType:
        return T.StringT

    def eval(self, batch: HostBatch) -> HostColumn:
        import re
        cols = [c.eval(batch) for c in self.children]
        validity = _combined_validity(cols)
        out = np.full(batch.num_rows, "", dtype=object)
        for i in range(batch.num_rows):
            if validity[i]:
                # Java $1 group references map to python \1
                rep = re.sub(r"\$(\d+)", r"\\\1", cols[2].data[i])
                out[i] = re.sub(cols[1].data[i], rep, cols[0].data[i])
        return HostColumn(T.StringT, out, validity)


class RegExpExtract(Expression):
    """regexp_extract(str, pattern, idx): group idx of the FIRST match,
    empty string when no match (Spark semantics); CPU only."""

    def __init__(self, child: Expression, pattern: Expression,
                 idx: Expression):
        self.children = [child, pattern, idx]

    @property
    def pretty_name(self) -> str:
        return "regexp_extract"

    @property
    def data_type(self) -> T.DataType:
        return T.StringT

    def eval(self, batch: HostBatch) -> HostColumn:
        import re
        cols = [c.eval(batch) for c in self.children]
        validity = _combined_validity(cols)
        out = np.full(batch.num_rows, "", dtype=object)
        for i in range(batch.num_rows):
            if validity[i]:
                m = re.search(cols[1].data[i], cols[0].data[i])
                g = int(cols[2].data[i])
                out[i] = (m.group(g) or "") if m and g <= len(
                    m.groups()) else ""
        return HostColumn(T.StringT, out, validity)


class StringSplit(Expression):
    """split(str, regex[, limit]) -> array<string> (GpuStringSplit,
    stringFunctions.scala:1014). Java split semantics: limit > 0 caps
    the parts; limit <= 0 keeps trailing empty strings."""

    def __init__(self, child: Expression, pattern: Expression,
                 limit: Expression):
        self.children = [child, pattern, limit]

    @property
    def pretty_name(self) -> str:
        return "split"

    @property
    def data_type(self) -> T.DataType:
        return T.ArrayType(T.StringT)

    def eval(self, batch: HostBatch) -> HostColumn:
        import re
        cols = [c.eval(batch) for c in self.children]
        validity = _combined_validity(cols)
        out = np.empty(batch.num_rows, dtype=object)
        for i in range(batch.num_rows):
            if not validity[i]:
                out[i] = ()
                continue
            lim = int(cols[2].data[i])
            parts = re.split(cols[1].data[i], cols[0].data[i],
                             maxsplit=lim - 1 if lim > 0 else 0)
            if lim == 0 and len(parts) > 1:
                # Java Pattern.split(limit=0) strips trailing empties;
                # the no-match case returns [input] untouched (so
                # "".split(",") stays [""])
                while parts and parts[-1] == "":
                    parts.pop()
            out[i] = tuple(parts)
        return HostColumn(self.data_type, out, validity)


class ConcatWs(Expression):
    """concat_ws(sep, ...): null arguments are SKIPPED; null only when
    the separator itself is null (stringFunctions.scala GpuConcatWs)."""

    def __init__(self, children: List[Expression]):
        self.children = list(children)  # [sep, arg0, arg1, ...]

    @property
    def pretty_name(self) -> str:
        return "concat_ws"

    @property
    def data_type(self) -> T.DataType:
        return T.StringT

    def eval(self, batch: HostBatch) -> HostColumn:
        cols = [c.eval(batch) for c in self.children]
        sep, args = cols[0], cols[1:]
        validity = sep.validity.copy()
        out = np.full(batch.num_rows, "", dtype=object)
        for i in range(batch.num_rows):
            if validity[i]:
                out[i] = sep.data[i].join(
                    c.data[i] for c in args if c.validity[i])
        return HostColumn(T.StringT, out, validity)


class StringRepeat(BinaryExpression):
    def __init__(self, left: Expression, right: Expression):
        self.children = [left, right]

    @property
    def data_type(self) -> T.DataType:
        return T.StringT

    def eval(self, batch: HostBatch) -> HostColumn:
        sc, nc = self.left.eval(batch), self.right.eval(batch)
        validity = _combined_validity([sc, nc])
        out = np.full(batch.num_rows, "", dtype=object)
        for i in range(batch.num_rows):
            if validity[i]:
                out[i] = sc.data[i] * max(0, int(nc.data[i]))
        return HostColumn(T.StringT, out, validity)


class StringLPad(Expression):
    """lpad/rpad with Spark semantics: result is exactly `len` chars
    (truncating when longer); an empty pad leaves the string as-is."""
    left_side = True

    def __init__(self, child: Expression, length: Expression,
                 pad: Expression):
        self.children = [child, length, pad]

    @property
    def pretty_name(self) -> str:
        return "lpad" if self.left_side else "rpad"

    @property
    def data_type(self) -> T.DataType:
        return T.StringT

    def eval(self, batch: HostBatch) -> HostColumn:
        cols = [c.eval(batch) for c in self.children]
        validity = _combined_validity(cols)
        out = np.full(batch.num_rows, "", dtype=object)
        for i in range(batch.num_rows):
            if not validity[i]:
                continue
            s, n, p = cols[0].data[i], int(cols[1].data[i]), cols[2].data[i]
            if n <= 0:
                out[i] = ""
            elif len(s) >= n:
                out[i] = s[:n]
            elif not p:
                out[i] = s
            else:
                fill = (p * ((n - len(s)) // len(p) + 1))[:n - len(s)]
                out[i] = fill + s if self.left_side else s + fill
        return HostColumn(T.StringT, out, validity)


class StringRPad(StringLPad):
    left_side = False


class StringTranslate(Expression):
    """translate(src, match, replace): per-char mapping; match chars
    beyond len(replace) are deleted."""

    def __init__(self, child: Expression, match: Expression,
                 replace: Expression):
        self.children = [child, match, replace]

    @property
    def data_type(self) -> T.DataType:
        return T.StringT

    def eval(self, batch: HostBatch) -> HostColumn:
        cols = [c.eval(batch) for c in self.children]
        validity = _combined_validity(cols)
        out = np.full(batch.num_rows, "", dtype=object)
        for i in range(batch.num_rows):
            if not validity[i]:
                continue
            m, r = cols[1].data[i], cols[2].data[i]
            # first occurrence of a duplicated matching char wins
            # (Spark/Hive semantics; mirrors the device kernel)
            table = {}
            for j, ch in enumerate(m):
                table.setdefault(ord(ch), r[j] if j < len(r) else None)
            out[i] = cols[0].data[i].translate(table)
        return HostColumn(T.StringT, out, validity)


class StringReplace(Expression):
    """replace(str, search, replace): empty search returns the input."""

    def __init__(self, child: Expression, search: Expression,
                 replace: Expression):
        self.children = [child, search, replace]

    @property
    def data_type(self) -> T.DataType:
        return T.StringT

    def eval(self, batch: HostBatch) -> HostColumn:
        cols = [c.eval(batch) for c in self.children]
        validity = _combined_validity(cols)
        out = np.full(batch.num_rows, "", dtype=object)
        for i in range(batch.num_rows):
            if validity[i]:
                s, f, r = (cols[0].data[i], cols[1].data[i],
                           cols[2].data[i])
                out[i] = s.replace(f, r) if f else s
        return HostColumn(T.StringT, out, validity)


class StringInstr(BinaryExpression):
    """instr(str, substr): 1-based position of first occurrence, 0 when
    absent, 1 for the empty substring."""

    def __init__(self, left: Expression, right: Expression):
        self.children = [left, right]

    @property
    def data_type(self) -> T.DataType:
        return T.IntegerT

    def eval(self, batch: HostBatch) -> HostColumn:
        sc, pc = self.left.eval(batch), self.right.eval(batch)
        validity = _combined_validity([sc, pc])
        out = np.zeros(batch.num_rows, dtype=np.int32)
        for i in range(batch.num_rows):
            if validity[i]:
                out[i] = sc.data[i].find(pc.data[i]) + 1
        return HostColumn(T.IntegerT, out, validity).normalized()


class StringLocate(Expression):
    """locate(substr, str, pos): search from 1-based `pos`; pos < 1
    yields 0 (Spark StringLocate)."""

    def __init__(self, substr: Expression, child: Expression,
                 pos: Expression):
        self.children = [substr, child, pos]

    @property
    def data_type(self) -> T.DataType:
        return T.IntegerT

    def eval(self, batch: HostBatch) -> HostColumn:
        cols = [c.eval(batch) for c in self.children]
        validity = _combined_validity(cols)
        out = np.zeros(batch.num_rows, dtype=np.int32)
        for i in range(batch.num_rows):
            if not validity[i]:
                continue
            sub, s, pos = cols[0].data[i], cols[1].data[i], int(
                cols[2].data[i])
            if pos < 1:
                out[i] = 0
            else:
                out[i] = s.find(sub, pos - 1) + 1
        return HostColumn(T.IntegerT, out, validity).normalized()


class InitCap(StringUnary):
    """First character of each space-separated word uppercased, the rest
    lowercased (UTF8String.toTitleCase semantics)."""

    def fn(self, s: str) -> str:
        out = []
        prev_space = True
        for ch in s:
            out.append(ch.upper() if prev_space else ch.lower())
            prev_space = ch == " "
        return "".join(out)


class StringReverse(StringUnary):
    def fn(self, s: str) -> str:
        return s[::-1]


class StringTrimLeft(StringUnary):
    def fn(self, s: str) -> str:
        return s.lstrip(" ")


class StringTrimRight(StringUnary):
    def fn(self, s: str) -> str:
        return s.rstrip(" ")


class Ascii(UnaryExpression):
    """Codepoint of the first character (0 for the empty string)."""

    def __init__(self, child: Expression):
        self.children = [child]

    @property
    def data_type(self) -> T.DataType:
        return T.IntegerT

    def eval(self, batch: HostBatch) -> HostColumn:
        c = self.child.eval(batch)
        out = np.zeros(len(c.data), dtype=np.int32)
        for i in range(len(c.data)):
            if c.validity[i] and c.data[i]:
                out[i] = ord(c.data[i][0])
        return HostColumn(T.IntegerT, out, c.validity.copy()).normalized()


class Chr(UnaryExpression):
    """chr(n): the character of codepoint n % 256 (empty for n < 0)."""

    def __init__(self, child: Expression):
        self.children = [child]

    @property
    def data_type(self) -> T.DataType:
        return T.StringT

    def eval(self, batch: HostBatch) -> HostColumn:
        c = self.child.eval(batch)
        out = np.full(len(c.data), "", dtype=object)
        for i in range(len(c.data)):
            if c.validity[i]:
                n = int(c.data[i])
                out[i] = "" if n < 0 else chr(n % 256)
        return HostColumn(T.StringT, out, c.validity.copy())


# ---------------------------------------------------------------------------
# Date/time (DateType = days since epoch; TimestampType = micros UTC;
# mirrors datetimeExpressions.scala)
# ---------------------------------------------------------------------------

_EPOCH_ORD = 719163  # datetime.date(1970,1,1).toordinal()


def _days_to_ymd(days: np.ndarray):
    # Proleptic Gregorian, vectorized civil-from-days (Howard Hinnant's algo)
    z = days.astype(np.int64) + 719468
    era = np.where(z >= 0, z, z - 146096) // 146097
    doe = z - era * 146097
    yoe = (doe - doe // 1460 + doe // 36524 - doe // 146096) // 365
    y = yoe + era * 400
    doy = doe - (365 * yoe + yoe // 4 - yoe // 100)
    mp = (5 * doy + 2) // 153
    d = doy - (153 * mp + 2) // 5 + 1
    m = np.where(mp < 10, mp + 3, mp - 9)
    y = np.where(m <= 2, y + 1, y)
    return y.astype(np.int64), m.astype(np.int64), d.astype(np.int64)


class DateTimeField(UnaryExpression):
    field = "year"

    def __init__(self, child: Expression):
        self.children = [child]

    @property
    def data_type(self) -> T.DataType:
        return T.IntegerT

    def _days(self, c: HostColumn) -> np.ndarray:
        if isinstance(self.child.data_type, T.TimestampType):
            micros = c.data.astype(np.int64)
            return np.floor_divide(micros, 86_400_000_000)
        return c.data.astype(np.int64)

    def eval(self, batch: HostBatch) -> HostColumn:
        c = self.child.eval(batch)
        y, m, d = _days_to_ymd(self._days(c))
        data = {"year": y, "month": m, "dayofmonth": d}[self.field]
        return HostColumn(T.IntegerT, data.astype(np.int32),
                          c.validity.copy()).normalized()


class Year(DateTimeField):
    field = "year"


class Month(DateTimeField):
    field = "month"


class DayOfMonth(DateTimeField):
    field = "dayofmonth"


class TimeField(UnaryExpression):
    divisor = 1
    modulus = 1

    def __init__(self, child: Expression):
        self.children = [child]

    @property
    def data_type(self) -> T.DataType:
        return T.IntegerT

    def eval(self, batch: HostBatch) -> HostColumn:
        c = self.child.eval(batch)
        micros = c.data.astype(np.int64)
        sec_of_day = np.mod(np.floor_divide(micros, 1_000_000), 86400)
        data = np.mod(np.floor_divide(sec_of_day, self.divisor), self.modulus)
        return HostColumn(T.IntegerT, data.astype(np.int32),
                          c.validity.copy()).normalized()


class Hour(TimeField):
    divisor, modulus = 3600, 24


class Minute(TimeField):
    divisor, modulus = 60, 60


class Second(TimeField):
    divisor, modulus = 1, 60


class DateAdd(BinaryExpression):
    def __init__(self, start: Expression, days: Expression):
        self.children = [start, days]

    @property
    def data_type(self) -> T.DataType:
        return T.DateT

    def eval(self, batch: HostBatch) -> HostColumn:
        sc, dc = self.left.eval(batch), self.right.eval(batch)
        validity = _combined_validity([sc, dc])
        data = (sc.data.astype(np.int64)
                + dc.data.astype(np.int64)).astype(np.int32)
        return HostColumn(T.DateT, data, validity).normalized()


class DateSub(DateAdd):
    def eval(self, batch: HostBatch) -> HostColumn:
        sc, dc = self.left.eval(batch), self.right.eval(batch)
        validity = _combined_validity([sc, dc])
        data = (sc.data.astype(np.int64)
                - dc.data.astype(np.int64)).astype(np.int32)
        return HostColumn(T.DateT, data, validity).normalized()


class DateDiff(BinaryExpression):
    def __init__(self, end: Expression, start: Expression):
        self.children = [end, start]

    @property
    def data_type(self) -> T.DataType:
        return T.IntegerT

    def eval(self, batch: HostBatch) -> HostColumn:
        ec, sc = self.left.eval(batch), self.right.eval(batch)
        validity = _combined_validity([ec, sc])
        data = (ec.data.astype(np.int64)
                - sc.data.astype(np.int64)).astype(np.int32)
        return HostColumn(T.IntegerT, data, validity).normalized()


def _ymd_to_days(y: np.ndarray, m: np.ndarray, d: np.ndarray) -> np.ndarray:
    """Inverse of _days_to_ymd (Hinnant's days-from-civil), vectorized."""
    y = y.astype(np.int64) - (m <= 2)
    era = np.where(y >= 0, y, y - 399) // 400
    yoe = y - era * 400
    mp = np.where(m > 2, m - 3, m + 9)
    doy = (153 * mp + 2) // 5 + d - 1
    doe = yoe * 365 + yoe // 4 - yoe // 100 + doy
    return era * 146097 + doe - 719468


def _days_in_month(y: np.ndarray, m: np.ndarray) -> np.ndarray:
    lengths = np.array([31, 28, 31, 30, 31, 30, 31, 31, 30, 31, 30, 31],
                       dtype=np.int64)
    leap = ((y % 4 == 0) & (y % 100 != 0)) | (y % 400 == 0)
    return lengths[m - 1] + ((m == 2) & leap)


class Quarter(DateTimeField):
    field = "quarter"

    def eval(self, batch: HostBatch) -> HostColumn:
        c = self.child.eval(batch)
        _y, m, _d = _days_to_ymd(self._days(c))
        data = (m - 1) // 3 + 1
        return HostColumn(T.IntegerT, data.astype(np.int32),
                          c.validity.copy()).normalized()


class DayOfWeek(DateTimeField):
    """1 = Sunday .. 7 = Saturday (Spark DayOfWeek)."""
    field = "dayofweek"

    def eval(self, batch: HostBatch) -> HostColumn:
        c = self.child.eval(batch)
        days = self._days(c)
        data = np.mod(days + 4, 7) + 1  # epoch day 0 was a Thursday
        return HostColumn(T.IntegerT, data.astype(np.int32),
                          c.validity.copy()).normalized()


class WeekDay(DateTimeField):
    """0 = Monday .. 6 = Sunday (Spark WeekDay)."""
    field = "weekday"

    def eval(self, batch: HostBatch) -> HostColumn:
        c = self.child.eval(batch)
        days = self._days(c)
        data = np.mod(days + 3, 7)
        return HostColumn(T.IntegerT, data.astype(np.int32),
                          c.validity.copy()).normalized()


class DayOfYear(DateTimeField):
    field = "dayofyear"

    def eval(self, batch: HostBatch) -> HostColumn:
        c = self.child.eval(batch)
        days = self._days(c)
        y, _m, _d = _days_to_ymd(days)
        jan1 = _ymd_to_days(y, np.ones_like(y), np.ones_like(y))
        data = days - jan1 + 1
        return HostColumn(T.IntegerT, data.astype(np.int32),
                          c.validity.copy()).normalized()


class WeekOfYear(DateTimeField):
    """ISO-8601 week number (Spark WeekOfYear)."""
    field = "weekofyear"

    def eval(self, batch: HostBatch) -> HostColumn:
        c = self.child.eval(batch)
        days = self._days(c)
        # the Thursday of this date's ISO week decides the week-year
        thursday = days + 3 - np.mod(days + 3, 7)
        ty, _m, _d = _days_to_ymd(thursday)
        jan1 = _ymd_to_days(ty, np.ones_like(ty), np.ones_like(ty))
        data = (thursday - jan1) // 7 + 1
        return HostColumn(T.IntegerT, data.astype(np.int32),
                          c.validity.copy()).normalized()


class LastDay(UnaryExpression):
    def __init__(self, child: Expression):
        self.children = [child]

    @property
    def data_type(self) -> T.DataType:
        return T.DateT

    def eval(self, batch: HostBatch) -> HostColumn:
        c = self.child.eval(batch)
        days = c.data.astype(np.int64)
        y, m, _d = _days_to_ymd(days)
        data = _ymd_to_days(y, m, _days_in_month(y, m)).astype(np.int32)
        return HostColumn(T.DateT, data, c.validity.copy()).normalized()


class AddMonths(BinaryExpression):
    """add_months: day-of-month clamps to the target month's last day."""

    def __init__(self, start: Expression, months: Expression):
        self.children = [start, months]

    @property
    def data_type(self) -> T.DataType:
        return T.DateT

    def eval(self, batch: HostBatch) -> HostColumn:
        sc, mc = self.left.eval(batch), self.right.eval(batch)
        validity = _combined_validity([sc, mc])
        y, m, d = _days_to_ymd(sc.data.astype(np.int64))
        total = (y * 12 + (m - 1)) + mc.data.astype(np.int64)
        ny = total // 12  # numpy // already floors for negatives
        nm = total - ny * 12 + 1
        nd = np.minimum(d, _days_in_month(ny, nm))
        data = _ymd_to_days(ny, nm, nd).astype(np.int32)
        return HostColumn(T.DateT, data, validity).normalized()


class MonthsBetween(BinaryExpression):
    """months_between(end, start): whole months when both fall on the
    same day-of-month or both on month-ends, else 31-day fractional
    months; result rounded to 8 places (Spark roundOff default)."""

    def __init__(self, end: Expression, start: Expression):
        self.children = [end, start]

    @property
    def data_type(self) -> T.DataType:
        return T.DoubleT

    @staticmethod
    def _parts(col: HostColumn, dtype: T.DataType):
        if isinstance(dtype, T.TimestampType):
            micros = col.data.astype(np.int64)
            days = np.floor_divide(micros, 86_400_000_000)
            sec = (micros - days * 86_400_000_000) / 1e6
        else:
            days = col.data.astype(np.int64)
            sec = np.zeros(len(col.data))
        y, m, d = _days_to_ymd(days)
        return y, m, d, sec

    def eval(self, batch: HostBatch) -> HostColumn:
        ec, sc = self.left.eval(batch), self.right.eval(batch)
        validity = _combined_validity([ec, sc])
        y1, m1, d1, s1 = self._parts(ec, self.left.data_type)
        y2, m2, d2, s2 = self._parts(sc, self.right.data_type)
        month_diff = (y1 - y2) * 12.0 + (m1 - m2)
        both_last = (d1 == _days_in_month(y1, m1)) & \
                    (d2 == _days_in_month(y2, m2))
        aligned = (d1 == d2) | both_last
        frac = ((d1 - d2) * 86400.0 + (s1 - s2)) / (31.0 * 86400.0)
        data = np.where(aligned, month_diff, month_diff + frac)
        data = np.round(data, 8)
        return HostColumn(T.DoubleT, data, validity).normalized()


class TruncDate(BinaryExpression):
    """trunc(date, fmt): fmt in year/yyyy/yy, quarter, month/mon/mm,
    week; unknown fmt -> null (Spark TruncDate)."""

    def __init__(self, child: Expression, fmt: Expression):
        self.children = [child, fmt]

    @property
    def data_type(self) -> T.DataType:
        return T.DateT

    def eval(self, batch: HostBatch) -> HostColumn:
        c, fc = self.left.eval(batch), self.right.eval(batch)
        days = c.data.astype(np.int64)
        y, m, _d = _days_to_ymd(days)
        out = np.zeros(len(days), dtype=np.int64)
        validity = _combined_validity([c, fc])
        ones = np.ones_like(y)
        year_start = _ymd_to_days(y, ones, ones)
        month_start = _ymd_to_days(y, m, ones)
        q_month = ((m - 1) // 3) * 3 + 1
        quarter_start = _ymd_to_days(y, q_month, ones)
        week_start = days - np.mod(days + 3, 7)  # Monday
        for i in range(len(days)):
            if not validity[i]:
                continue
            f = fc.data[i].lower()
            if f in ("year", "yyyy", "yy"):
                out[i] = year_start[i]
            elif f in ("month", "mon", "mm"):
                out[i] = month_start[i]
            elif f == "quarter":
                out[i] = quarter_start[i]
            elif f == "week":
                out[i] = week_start[i]
            else:
                validity[i] = False
        return HostColumn(T.DateT, out.astype(np.int32),
                          validity).normalized()


# Restricted datetime pattern support shared by CPU and device paths:
# literal text plus the unambiguous numeric tokens. Anything else falls
# back (device tags to CPU; CPU raises).
_DT_TOKENS = ("yyyy", "MM", "dd", "HH", "mm", "ss")


def parse_dt_pattern(fmt: str) -> Optional[List[Tuple[str, str]]]:
    """[(kind, text)] where kind is 'lit' or a token; None when the
    pattern uses anything outside the supported subset."""
    out: List[Tuple[str, str]] = []
    i = 0
    while i < len(fmt):
        for tok in _DT_TOKENS:
            if fmt.startswith(tok, i):
                out.append((tok, tok))
                i += len(tok)
                break
        else:
            ch = fmt[i]
            if ch.isalpha():
                return None  # unsupported pattern letter
            out.append(("lit", ch))
            i += 1
    return out


DEFAULT_TS_FMT = "yyyy-MM-dd HH:mm:ss"


def _format_micros(micros: np.ndarray, validity: np.ndarray,
                   parts: List[Tuple[str, str]]) -> np.ndarray:
    days = np.floor_divide(micros, 86_400_000_000)
    sec_of_day = np.floor_divide(micros - days * 86_400_000_000, 1_000_000)
    y, m, d = _days_to_ymd(days)
    # fixed-width digit formatting only represents years 0-9999; rows
    # outside become null on BOTH engines so CPU and device agree
    # (documented deviation from Spark's signed 5+-digit year output)
    validity = validity & (y >= 0) & (y <= 9999)
    fields = {
        "yyyy": (y, 4), "MM": (m, 2), "dd": (d, 2),
        "HH": (sec_of_day // 3600, 2), "mm": (sec_of_day // 60 % 60, 2),
        "ss": (sec_of_day % 60, 2),
    }
    n = len(micros)
    out = np.full(n, "", dtype=object)
    pieces = []
    for kind, text in parts:
        if kind == "lit":
            pieces.append(np.full(n, text, dtype=object))
        else:
            vals, width = fields[kind]
            pieces.append(np.char.zfill(
                vals.astype(np.int64).astype("U16"), width).astype(object))
    for i in range(n):
        if validity[i]:
            out[i] = "".join(p[i] for p in pieces)
    return out


def _parse_with_pattern(strings: np.ndarray, validity: np.ndarray,
                        parts: List[Tuple[str, str]]):
    """Parse per the token list; returns (micros, ok). Lenient like
    Spark's legacy parser about trailing text only when the pattern
    consumed everything."""
    n = len(strings)
    micros = np.zeros(n, dtype=np.int64)
    ok = validity.copy()
    for i in range(n):
        if not ok[i]:
            continue
        s = str(strings[i])
        pos = 0
        vals = {"yyyy": 1970, "MM": 1, "dd": 1, "HH": 0, "mm": 0, "ss": 0}
        good = True
        for kind, text in parts:
            if kind == "lit":
                if pos < len(s) and s[pos] == text:
                    pos += 1
                else:
                    good = False
                    break
            else:
                width = 4 if kind == "yyyy" else 2
                chunk = s[pos:pos + width]
                if len(chunk) == width and chunk.isdigit():
                    vals[kind] = int(chunk)
                    pos += width
                else:
                    good = False
                    break
        if not good or pos != len(s):
            ok[i] = False
            continue
        if not (1 <= vals["MM"] <= 12 and 1 <= vals["dd"] <= 31
                and vals["HH"] < 24 and vals["mm"] < 60
                and vals["ss"] < 60):
            ok[i] = False
            continue
        day = _ymd_to_days(np.array([vals["yyyy"]]), np.array([vals["MM"]]),
                           np.array([vals["dd"]]))[0]
        micros[i] = ((day * 86400 + vals["HH"] * 3600 + vals["mm"] * 60
                      + vals["ss"]) * 1_000_000)
    return micros, ok


class DateFormatClass(BinaryExpression):
    """date_format(ts, fmt) over the supported token subset."""

    def __init__(self, child: Expression, fmt: Expression):
        self.children = [child, fmt]

    @property
    def data_type(self) -> T.DataType:
        return T.StringT

    def _micros(self, c: HostColumn) -> np.ndarray:
        if isinstance(self.left.data_type, T.DateType):
            return c.data.astype(np.int64) * 86_400_000_000
        return c.data.astype(np.int64)

    def eval(self, batch: HostBatch) -> HostColumn:
        c, fc = self.left.eval(batch), self.right.eval(batch)
        assert isinstance(self.right, Literal), \
            "date_format pattern must be a literal"
        parts = parse_dt_pattern(self.right.value)
        if parts is None:
            raise NotImplementedError(
                f"unsupported datetime pattern {fc.data[0]!r}")
        validity = _combined_validity([c, fc])
        out = _format_micros(self._micros(c), validity, parts)
        return HostColumn(T.StringT, out, validity)


class UnixTimestamp(BinaryExpression):
    """unix_timestamp(col, fmt) -> long seconds; strings parse with the
    pattern (null on failure), dates/timestamps convert directly."""
    pretty = "unix_timestamp"

    def __init__(self, child: Expression, fmt: Expression):
        self.children = [child, fmt]

    @property
    def data_type(self) -> T.DataType:
        return T.LongT

    def eval(self, batch: HostBatch) -> HostColumn:
        c, fc = self.left.eval(batch), self.right.eval(batch)
        src = self.left.data_type
        if isinstance(src, T.DateType):
            data = c.data.astype(np.int64) * 86400
            return HostColumn(T.LongT, data, c.validity.copy()).normalized()
        if isinstance(src, T.TimestampType):
            data = np.floor_divide(c.data.astype(np.int64), 1_000_000)
            return HostColumn(T.LongT, data, c.validity.copy()).normalized()
        assert isinstance(self.right, Literal), \
            "unix_timestamp pattern must be a literal"
        parts = parse_dt_pattern(self.right.value)
        if parts is None:
            raise NotImplementedError(
                f"unsupported datetime pattern {fc.data[0]!r}")
        validity = _combined_validity([c, fc])
        micros, ok = _parse_with_pattern(c.data, validity, parts)
        return HostColumn(T.LongT, np.floor_divide(micros, 1_000_000),
                          ok).normalized()


class FromUnixTime(BinaryExpression):
    """from_unixtime(seconds, fmt) -> formatted string (UTC session)."""

    def __init__(self, child: Expression, fmt: Expression):
        self.children = [child, fmt]

    @property
    def data_type(self) -> T.DataType:
        return T.StringT

    def eval(self, batch: HostBatch) -> HostColumn:
        c, fc = self.left.eval(batch), self.right.eval(batch)
        assert isinstance(self.right, Literal), \
            "from_unixtime pattern must be a literal"
        parts = parse_dt_pattern(self.right.value)
        if parts is None:
            raise NotImplementedError(
                f"unsupported datetime pattern {fc.data[0]!r}")
        validity = _combined_validity([c, fc])
        out = _format_micros(c.data.astype(np.int64) * 1_000_000,
                             validity, parts)
        return HostColumn(T.StringT, out, validity)


class GetTimestamp(BinaryExpression):
    """to_date/to_timestamp(col, fmt): pattern-parse to TimestampType
    (to_date wraps this in a Cast to date, like Spark's ParseToDate)."""

    def __init__(self, child: Expression, fmt: Expression):
        self.children = [child, fmt]

    @property
    def data_type(self) -> T.DataType:
        return T.TimestampT

    def eval(self, batch: HostBatch) -> HostColumn:
        c, fc = self.left.eval(batch), self.right.eval(batch)
        assert isinstance(self.right, Literal), \
            "to_date/to_timestamp pattern must be a literal"
        parts = parse_dt_pattern(self.right.value)
        if parts is None:
            raise NotImplementedError(
                f"unsupported datetime pattern {fc.data[0]!r}")
        validity = _combined_validity([c, fc])
        micros, ok = _parse_with_pattern(c.data, validity, parts)
        return HostColumn(T.TimestampT, micros, ok).normalized()


# ---------------------------------------------------------------------------
# Hash
# ---------------------------------------------------------------------------

class Murmur3Hash(Expression):
    """Spark Murmur3Hash(seed=42) over columns left-to-right; the rewrite
    maps this to the device twin in kernels/hashing.py
    (reference: GpuMurmur3Hash, HashFunctions.scala)."""

    def __init__(self, children: List[Expression], seed: int = 42):
        self.children = list(children)
        self.seed = seed

    @property
    def data_type(self) -> T.DataType:
        return T.IntegerT

    @property
    def nullable(self) -> bool:
        return False

    def eval(self, batch: HostBatch) -> HostColumn:
        n = batch.num_rows
        h = np.full(n, self.seed, dtype=np.int32)
        for child in self.children:
            c = child.eval(batch)
            h = _hash_column(c, h)
        return HostColumn.all_valid(h, T.IntegerT)


def _hash_column(c: HostColumn, seed: np.ndarray) -> np.ndarray:
    dt = c.dtype
    if isinstance(dt, (T.StringType, T.BinaryType)):
        out = seed.copy()
        for i in range(len(c.data)):
            if c.validity[i]:
                raw = (c.data[i].encode("utf-8")
                       if isinstance(c.data[i], str) else bytes(c.data[i]))
                out[i] = murmur3.hash_bytes_one(raw, int(seed[i]))
        return out
    if isinstance(dt, T.BooleanType):
        h = murmur3.hash_int(c.data.astype(np.int32), seed)
    elif isinstance(dt, (T.ByteType, T.ShortType, T.IntegerType, T.DateType)):
        h = murmur3.hash_int(c.data.astype(np.int32), seed)
    elif isinstance(dt, (T.LongType, T.TimestampType)):
        h = murmur3.hash_long(c.data.astype(np.int64), seed)
    elif isinstance(dt, T.FloatType):
        h = murmur3.hash_float(c.data, seed)
    elif isinstance(dt, T.DoubleType):
        h = murmur3.hash_double(c.data, seed)
    elif isinstance(dt, T.DecimalType) and dt.precision <= 18:
        h = murmur3.hash_long(c.data.astype(np.int64), seed)
    elif isinstance(dt, T.DecimalType):
        # Spark hashes a big decimal as the minimal big-endian
        # two's-complement bytes of its unscaled value
        # (Murmur3Hash.computeHash on Decimal, hash.scala)
        from spark_rapids_tpu.ops import int128 as I
        ints = I.to_pyints(np.ascontiguousarray(c.data[:, 0]),
                           np.ascontiguousarray(c.data[:, 1]))
        out = seed.copy()
        for i in range(len(ints)):
            if c.validity[i]:
                v = int(ints[i])
                # BigInteger.toByteArray length: bitLength/8 + 1, where
                # bitLength excludes the sign bit (negatives count the
                # bits of minimal two's complement)
                bl = v.bit_length() if v >= 0 else (-v - 1).bit_length()
                raw = v.to_bytes(bl // 8 + 1, "big", signed=True)
                out[i] = murmur3.hash_bytes_one(raw, int(seed[i]))
        return out
    elif isinstance(dt, T.StructType):
        # Spark hashes a struct by folding murmur3 over its fields with
        # the running hash as each field's seed; null fields keep the
        # seed (HashExpression.computeHash on struct)
        out = seed.copy()
        from spark_rapids_tpu.columnar.host import struct_field_values
        from spark_rapids_tpu.columnar.transfer import \
            _col_from_storage_values
        for fi, f in enumerate(dt.fields):
            fc = _col_from_storage_values(
                struct_field_values(c, fi), f.data_type)
            # only valid STRUCT rows advance their hash
            nh = _hash_column(fc, out)
            out = np.where(c.validity, nh, out)
        return out
    else:
        raise TypeError(f"cannot hash {dt}")
    return np.where(c.validity, h, seed)


# ---------------------------------------------------------------------------
# Collections (collectionOperations.scala, complexTypeCreator/Extractor
# twins) + generators (GpuGenerateExec.scala:440)
# ---------------------------------------------------------------------------

class CreateArray(Expression):
    """array(e1, e2, ...): never null; null inputs become null elements."""

    def __init__(self, children: List[Expression]):
        self.children = list(children)

    @property
    def data_type(self) -> T.DataType:
        et = self.children[0].data_type if self.children else T.NullT
        return T.ArrayType(et)

    @property
    def nullable(self) -> bool:
        return False

    def eval(self, batch: HostBatch) -> HostColumn:
        cols = [c.eval(batch) for c in self.children]
        out = np.empty(batch.num_rows, dtype=object)
        for i in range(batch.num_rows):
            out[i] = tuple(
                (c.data[i].item() if isinstance(c.data[i], np.generic)
                 else c.data[i]) if c.validity[i] else None
                for c in cols)
        return HostColumn(self.data_type, out,
                          np.ones(batch.num_rows, dtype=bool))


class CreateNamedStruct(Expression):
    """struct(c1, c2, ...) / named_struct: never-null struct whose
    fields keep the children's names and null-ness
    (complexTypeCreator.scala GpuCreateNamedStruct role)."""

    def __init__(self, names: List[str], children: List[Expression]):
        self.names = list(names)
        self.children = list(children)

    @property
    def pretty_name(self) -> str:
        return "named_struct"

    @property
    def data_type(self) -> T.DataType:
        return T.StructType([
            T.StructField(n, c.data_type, True)
            for n, c in zip(self.names, self.children)])

    @property
    def nullable(self) -> bool:
        return False

    def eval(self, batch: HostBatch) -> HostColumn:
        from spark_rapids_tpu.columnar.host import struct_storage_rows
        cols = [c.eval(batch) for c in self.children]
        n = batch.num_rows
        validity = np.ones(n, dtype=bool)
        return HostColumn(self.data_type,
                          struct_storage_rows(cols, validity), validity)


class GetStructField(UnaryExpression):
    """struct.field extraction (complexTypeExtractors.scala
    GpuGetStructField role). The ordinal resolves lazily from the field
    name so the expression can be built over an unresolved column."""

    def __init__(self, child: Expression, ordinal: Optional[int] = None,
                 name: Optional[str] = None):
        assert ordinal is not None or name is not None
        self.children = [child]
        self._ordinal = ordinal
        self.field_name = name

    @property
    def ordinal(self) -> int:
        if self._ordinal is None:
            dt = self.children[0].data_type
            self._ordinal = next(
                i for i, f in enumerate(dt.fields)
                if f.name == self.field_name)
        return self._ordinal

    @property
    def pretty_name(self) -> str:
        if self.field_name is not None:
            return self.field_name
        return self.children[0].data_type.fields[self.ordinal].name

    @property
    def data_type(self) -> T.DataType:
        return self.children[0].data_type.fields[self.ordinal].data_type

    def eval(self, batch: HostBatch) -> HostColumn:
        from spark_rapids_tpu.columnar.host import struct_field_values
        from spark_rapids_tpu.columnar.transfer import \
            _col_from_storage_values
        c = self.children[0].eval(batch)
        return _col_from_storage_values(
            struct_field_values(c, self.ordinal),
            self.data_type).normalized()


class TimeWindow(UnaryExpression):
    """window(ts, duration[, slide, start]) for TUMBLING windows
    (slide == duration): struct<start:timestamp, end:timestamp> with
    start = ts - floorMod(ts - startTime, duration) in microseconds
    (Spark TimeWindow / GpuOverrides TimeWindow rule role). Sliding
    windows (slide < duration) emit multiple rows per input and are not
    supported."""

    def __init__(self, child: Expression, window_us: int,
                 start_us: int = 0):
        self.children = [child]
        self.window_us = int(window_us)
        self.start_us = int(start_us)

    @property
    def pretty_name(self) -> str:
        return "window"

    @property
    def data_type(self) -> T.DataType:
        return T.StructType([T.StructField("start", T.TimestampT, True),
                             T.StructField("end", T.TimestampT, True)])

    def eval(self, batch: HostBatch) -> HostColumn:
        c = self.children[0].eval(batch)
        ts = c.data.astype(np.int64)
        w = np.int64(self.window_us)
        # numpy % already floor-mods like Spark's Math.floorMod
        start = ts - np.mod(ts - np.int64(self.start_us), w)
        end = start + w
        out = np.empty(batch.num_rows, dtype=object)
        for i in range(batch.num_rows):
            out[i] = ((int(start[i]), int(end[i]))
                      if c.validity[i] else ())
        return HostColumn(self.data_type, out, c.validity.copy())


class Size(UnaryExpression):
    """size(array): element count; null input -> -1 (legacy Spark
    default spark.sql.legacy.sizeOfNull=true semantics)."""

    LEGACY_NULL = -1

    def __init__(self, child: Expression):
        self.children = [child]

    @property
    def data_type(self) -> T.DataType:
        return T.IntegerT

    @property
    def nullable(self) -> bool:
        return False

    def eval(self, batch: HostBatch) -> HostColumn:
        c = self.child.eval(batch)
        out = np.full(len(c.data), self.LEGACY_NULL, dtype=np.int32)
        for i in range(len(c.data)):
            if c.validity[i]:
                out[i] = len(c.data[i])
        return HostColumn.all_valid(out, T.IntegerT)


class ElementAt(BinaryExpression):
    """element_at(array, i): 1-based, negative from the end; null when
    out of range (non-ANSI)."""

    def __init__(self, left: Expression, right: Expression):
        self.children = [left, right]

    @property
    def data_type(self) -> T.DataType:
        return self.left.data_type.element_type

    def eval(self, batch: HostBatch) -> HostColumn:
        ac, ic = self.left.eval(batch), self.right.eval(batch)
        n = len(ac.data)
        np_dt = T.numpy_dtype(self.data_type)
        validity = np.zeros(n, dtype=bool)
        fill = "" if np_dt == np.dtype(object) else _zero_for_np(np_dt)
        data = np.full(n, fill, dtype=np_dt)
        for i in range(n):
            if not (ac.validity[i] and ic.validity[i]):
                continue
            arr, idx = ac.data[i], int(ic.data[i])
            if idx == 0 or abs(idx) > len(arr):
                continue
            v = arr[idx - 1] if idx > 0 else arr[idx]
            if v is not None:
                validity[i] = True
                data[i] = v
        return HostColumn(self.data_type, data, validity).normalized()


class GetArrayItem(ElementAt):
    """array[i]: 0-based ordinal access (null when out of range)."""

    def eval(self, batch: HostBatch) -> HostColumn:
        ac, ic = self.left.eval(batch), self.right.eval(batch)
        n = len(ac.data)
        np_dt = T.numpy_dtype(self.data_type)
        validity = np.zeros(n, dtype=bool)
        fill = "" if np_dt == np.dtype(object) else _zero_for_np(np_dt)
        data = np.full(n, fill, dtype=np_dt)
        for i in range(n):
            if not (ac.validity[i] and ic.validity[i]):
                continue
            arr, idx = ac.data[i], int(ic.data[i])
            if idx < 0 or idx >= len(arr):
                continue
            v = arr[idx]
            if v is not None:
                validity[i] = True
                data[i] = v
        return HostColumn(self.data_type, data, validity).normalized()


class ArrayContains(BinaryExpression):
    """array_contains(array, value): 3-valued like IN (null when absent
    but null elements exist)."""

    def __init__(self, left: Expression, right: Expression):
        self.children = [left, right]

    @property
    def data_type(self) -> T.DataType:
        return T.BooleanT

    def eval(self, batch: HostBatch) -> HostColumn:
        ac, vc = self.left.eval(batch), self.right.eval(batch)
        n = len(ac.data)
        validity = np.zeros(n, dtype=bool)
        data = np.zeros(n, dtype=bool)
        for i in range(n):
            if not (ac.validity[i] and vc.validity[i]):
                continue
            arr = ac.data[i]
            target = vc.data[i]
            if isinstance(target, np.generic):
                target = target.item()
            found = any(x is not None and x == target for x in arr)
            has_null = any(x is None for x in arr)
            if found:
                validity[i], data[i] = True, True
            elif not has_null:
                validity[i] = True
        return HostColumn(T.BooleanT, data, validity).normalized()


def _zero_for_np(np_dt) -> Any:
    if np_dt == np.dtype(bool):
        return False
    if np.issubdtype(np_dt, np.floating):
        return 0.0
    return 0


class Explode(UnaryExpression):
    """Generator: one output row per array element (GpuGenerateExec
    role). ``position`` adds the pos column (posexplode); ``outer``
    keeps empty/null arrays as one null row."""

    is_generator = True

    def __init__(self, child: Expression, position: bool = False,
                 outer: bool = False):
        self.children = [child]
        self.position = position
        self.outer = outer

    @property
    def data_type(self) -> T.DataType:
        return self.child.data_type.element_type

    def generator_output(self, col_name: str = "col"
                         ) -> List["AttributeReference"]:
        out = []
        if self.position:
            out.append(AttributeReference("pos", T.IntegerT,
                                          nullable=False))
        out.append(AttributeReference(col_name, self.data_type))
        return out


class XxHash64(Expression):
    """Spark XxHash64(seed=42L) over columns left-to-right (reference:
    GpuXxHash64, HashFunctions.scala); device twin in ops/hashing.py."""

    def __init__(self, children: List[Expression], seed: int = 42):
        self.children = list(children)
        self.seed = seed

    @property
    def data_type(self) -> T.DataType:
        return T.LongT

    @property
    def nullable(self) -> bool:
        return False

    def eval(self, batch: HostBatch) -> HostColumn:
        from spark_rapids_tpu.columnar import xxhash64
        n = batch.num_rows
        h = np.full(n, self.seed, dtype=np.int64)
        for child in self.children:
            c = child.eval(batch)
            h = _xx_hash_column(c, h, xxhash64)
        return HostColumn.all_valid(h, T.LongT)


def _xx_hash_column(c: HostColumn, seed: np.ndarray, xx) -> np.ndarray:
    dt = c.dtype
    if isinstance(dt, (T.StringType, T.BinaryType)):
        out = seed.copy()
        for i in range(len(c.data)):
            if c.validity[i]:
                raw = (c.data[i].encode("utf-8")
                       if isinstance(c.data[i], str) else bytes(c.data[i]))
                out[i] = xx.hash_bytes_one(raw, int(seed[i]))
        return out
    if isinstance(dt, (T.BooleanType, T.ByteType, T.ShortType,
                       T.IntegerType, T.DateType)):
        h = xx.hash_int(c.data.astype(np.int32), seed)
    elif isinstance(dt, (T.LongType, T.TimestampType)):
        h = xx.hash_long(c.data.astype(np.int64), seed)
    elif isinstance(dt, T.FloatType):
        h = xx.hash_float(c.data, seed)
    elif isinstance(dt, T.DoubleType):
        h = xx.hash_double(c.data, seed)
    elif isinstance(dt, T.DecimalType) and dt.precision <= 18:
        h = xx.hash_long(c.data.astype(np.int64), seed)
    else:
        raise TypeError(f"cannot xxhash {dt}")
    return np.where(c.validity, h, seed)


# ---------------------------------------------------------------------------
# Cast (GpuCast.scala:1338 equivalent; the CastChecks matrix in typesig.py
# gates which directions the device may take)
# ---------------------------------------------------------------------------

class Cast(UnaryExpression):
    def __init__(self, child: Expression, dtype: T.DataType,
                 ansi: bool = False):
        self.children = [child]
        self._dtype = dtype
        self.ansi = ansi

    @property
    def data_type(self) -> T.DataType:
        return self._dtype

    def eval(self, batch: HostBatch) -> HostColumn:
        c = self.child.eval(batch)
        return cast_host_column(c, self._dtype, self.ansi)

    def __repr__(self) -> str:
        return f"cast({self.child!r} as {self._dtype.simple_string})"


def cast_host_column(c: HostColumn, to: T.DataType, ansi: bool = False
                     ) -> HostColumn:
    frm = c.dtype
    if frm == to:
        return c
    if isinstance(frm, T.NullType):
        return HostColumn.nulls(len(c), to)

    # numeric -> numeric
    if T.is_numeric(frm) and T.is_numeric(to) and not isinstance(
            to, T.DecimalType) and not isinstance(frm, T.DecimalType):
        return _cast_numeric(c, to, ansi)
    # bool -> numeric
    if isinstance(frm, T.BooleanType) and T.is_numeric(to):
        data = c.data.astype(T.numpy_dtype(to))
        return HostColumn(to, data, c.validity.copy())
    # numeric -> bool
    if T.is_numeric(frm) and isinstance(to, T.BooleanType):
        return HostColumn(to, c.data != 0, c.validity.copy())
    # anything -> string
    if isinstance(to, T.StringType):
        return _cast_to_string(c)
    # string -> *
    if isinstance(frm, T.StringType):
        return _cast_from_string(c, to, ansi)
    # date/timestamp conversions
    if isinstance(frm, T.DateType) and isinstance(to, T.TimestampType):
        data = c.data.astype(np.int64) * 86_400_000_000
        return HostColumn(to, data, c.validity.copy())
    if isinstance(frm, T.TimestampType) and isinstance(to, T.DateType):
        data = np.floor_divide(c.data.astype(np.int64),
                               86_400_000_000).astype(np.int32)
        return HostColumn(to, data, c.validity.copy())
    # decimal <-> numeric (decimal64 path)
    if isinstance(to, T.DecimalType):
        return _cast_to_decimal(c, to, ansi)
    if isinstance(frm, T.DecimalType):
        return _cast_from_decimal(c, to, ansi)
    raise TypeError(f"unsupported cast {frm} -> {to}")


def _cast_numeric(c: HostColumn, to: T.DataType, ansi: bool) -> HostColumn:
    np_to = T.numpy_dtype(to)
    src = c.data
    validity = c.validity.copy()
    if np.issubdtype(src.dtype, np.floating) and not T.is_floating(to):
        # Java double->int semantics: NaN -> 0, saturate at bounds,
        # truncate toward zero (Spark non-ANSI Cast). Long.MAX is not
        # representable as double, so saturate via threshold compares.
        info = np.iinfo(np_to)
        as_long = _java_double_to_long(np.trunc(src))
        data = np.clip(as_long, info.min, info.max).astype(np_to)
        if ansi:
            # bound compares (exact 2^k floats) — round-trip compares
            # miss values that round back onto the clipped result (2^63)
            with np.errstate(all="ignore"):
                t = np.trunc(src)
                bad = (np.isnan(src) | (t >= np.float64(info.max) + 1.0)
                       | (t < np.float64(info.min)))
            if (bad & validity).any():
                raise ArithmeticError("Cast overflow in ANSI mode")
    else:
        # int narrowing wraps (two's complement), widening exact;
        # int->float may round — all match Java/Spark non-ANSI.
        with np.errstate(all="ignore"):
            data = src.astype(np_to)
        if ansi and np.issubdtype(src.dtype, np.integer) \
                and np.issubdtype(np_to, np.integer) \
                and np_to.itemsize < src.dtype.itemsize:
            bad = data.astype(src.dtype) != src
            if (bad & validity).any():
                raise ArithmeticError("Cast overflow in ANSI mode")
    return HostColumn(to, data, validity)


def _format_double_java(v: float) -> str:
    """Approximate Java Double.toString (Spark cast double->string).
    Gated behind castFloatToString like the reference."""
    if math.isnan(v):
        return "NaN"
    if math.isinf(v):
        return "Infinity" if v > 0 else "-Infinity"
    if v == int(v) and abs(v) < 1e7:
        return f"{int(v)}.0"
    r = repr(float(v))
    if "e" in r:
        mant, exp = r.split("e")
        e = int(exp)
        if "." not in mant:
            mant += ".0"
        return f"{mant}E{e}"
    return r


def _cast_to_string(c: HostColumn) -> HostColumn:
    frm = c.dtype
    out = np.full(len(c), "", dtype=object)
    if isinstance(frm, T.BooleanType):
        for i in range(len(c)):
            if c.validity[i]:
                out[i] = "true" if c.data[i] else "false"
    elif isinstance(frm, T.DateType):
        y, m, d = _days_to_ymd(c.data.astype(np.int64))
        for i in range(len(c)):
            if c.validity[i]:
                out[i] = f"{y[i]:04d}-{m[i]:02d}-{d[i]:02d}"
    elif isinstance(frm, T.TimestampType):
        micros = c.data.astype(np.int64)
        days = np.floor_divide(micros, 86_400_000_000)
        y, m, d = _days_to_ymd(days)
        rem = micros - days * 86_400_000_000
        for i in range(len(c)):
            if c.validity[i]:
                s = int(rem[i] // 1_000_000)
                us = int(rem[i] % 1_000_000)
                base = (f"{y[i]:04d}-{m[i]:02d}-{d[i]:02d} "
                        f"{s // 3600:02d}:{(s // 60) % 60:02d}:{s % 60:02d}")
                if us:
                    base += ("." + f"{us:06d}".rstrip("0"))
                out[i] = base
    elif T.is_floating(frm):
        for i in range(len(c)):
            if c.validity[i]:
                out[i] = _format_double_java(float(c.data[i]))
    elif isinstance(frm, T.DecimalType):
        scale = frm.scale
        for i in range(len(c)):
            if c.validity[i]:
                u = int(c.data[i])
                out[i] = _format_decimal(u, scale)
    elif isinstance(frm, T.StringType):
        return c
    else:
        for i in range(len(c)):
            if c.validity[i]:
                out[i] = str(int(c.data[i]))
    return HostColumn(T.StringT, out, c.validity.copy())


def _format_decimal(unscaled: int, scale: int) -> str:
    sign = "-" if unscaled < 0 else ""
    u = abs(unscaled)
    if scale == 0:
        return f"{sign}{u}"
    s = str(u).rjust(scale + 1, "0")
    return f"{sign}{s[:-scale]}.{s[-scale:]}"


def _cast_from_string(c: HostColumn, to: T.DataType, ansi: bool
                      ) -> HostColumn:
    n = len(c)
    validity = c.validity.copy()
    np_dt = T.numpy_dtype(to)
    if isinstance(to, T.BooleanType):
        data = np.zeros(n, dtype=bool)
        for i in range(n):
            if not validity[i]:
                continue
            s = c.data[i].strip().lower()
            if s in ("t", "true", "y", "yes", "1"):
                data[i] = True
            elif s in ("f", "false", "n", "no", "0"):
                data[i] = False
            else:
                validity[i] = False
        return HostColumn(to, data, validity)
    if T.is_floating(to):
        data = np.zeros(n, dtype=np_dt)
        for i in range(n):
            if not validity[i]:
                continue
            try:
                data[i] = float(c.data[i].strip())
            except ValueError:
                validity[i] = False
        return HostColumn(to, data, validity)
    if T.is_integral(to):
        data = np.zeros(n, dtype=np_dt)
        info = np.iinfo(np_dt)
        for i in range(n):
            if not validity[i]:
                continue
            s = c.data[i].strip()
            try:
                v = int(s)
            except ValueError:
                # Spark accepts "123.45" -> 123 for cast to int? It does
                # truncate decimals in strings (UTF8String.toInt rejects;
                # Cast uses toLongExact on trimmed decimal strings). Keep
                # the common behavior: reject non-integer strings.
                validity[i] = False
                continue
            if v < info.min or v > info.max:
                validity[i] = False
                continue
            data[i] = v
        return HostColumn(to, data, validity)
    if isinstance(to, T.DateType):
        data = np.zeros(n, dtype=np.int32)
        import datetime
        import re as _re
        # ASCII digits only (\d matches Unicode digits, which the device
        # byte-matrix parser rightly rejects)
        pat = _re.compile(r"[+]?([0-9]{1,7})-([0-9]{1,2})-([0-9]{1,2})\Z")
        for i in range(n):
            if not validity[i]:
                continue
            m = pat.match(c.data[i].strip())
            if m is None:
                validity[i] = False
                continue
            try:
                d = datetime.date(int(m.group(1)), int(m.group(2)),
                                  int(m.group(3)))
                data[i] = d.toordinal() - _EPOCH_ORD
            except ValueError:
                validity[i] = False
        return HostColumn(to, data, validity)
    if isinstance(to, T.TimestampType):
        data = np.zeros(n, dtype=np.int64)
        import datetime
        for i in range(n):
            if not validity[i]:
                continue
            s = c.data[i].strip().replace("T", " ")
            try:
                if " " in s:
                    dt = datetime.datetime.fromisoformat(s)
                else:
                    dt = datetime.datetime.fromisoformat(s + " 00:00:00")
                dt = dt.replace(tzinfo=datetime.timezone.utc)
                data[i] = int(dt.timestamp() * 1_000_000)
            except ValueError:
                validity[i] = False
        return HostColumn(to, data, validity)
    if isinstance(to, T.DecimalType):
        data = np.zeros(n, dtype=np.int64)
        import decimal as pydec
        q = pydec.Decimal(1).scaleb(-to.scale)
        for i in range(n):
            if not validity[i]:
                continue
            try:
                d = pydec.Decimal(c.data[i].strip()).quantize(
                    q, rounding=pydec.ROUND_HALF_UP)
                u = int(d.scaleb(to.scale))
                if abs(u) >= 10 ** to.precision:
                    validity[i] = False
                else:
                    data[i] = u
            except pydec.InvalidOperation:
                validity[i] = False
        return HostColumn(to, data, validity)
    raise TypeError(f"unsupported cast string -> {to}")


def _cast_to_decimal(c: HostColumn, to: T.DecimalType, ansi: bool
                     ) -> HostColumn:
    from spark_rapids_tpu.ops import decimal_ops as D
    validity = c.validity.copy()
    frm = c.dtype
    if isinstance(frm, T.DecimalType):
        if D.cast_supported(frm, to):
            hi, lo = _dec_limbs(c)
            hi, lo, ok = D.cast_decimal(np, hi, lo, frm, to)
            if ansi and (~ok & validity).any():
                raise ArithmeticError("Decimal overflow in ANSI mode")
            return _limbs_to_col(hi, lo, validity & ok, to)
        # deep down-rescale: exact Python ints (rare)
        from spark_rapids_tpu.ops import int128 as I
        vals = I.to_pyints(*_dec_limbs(c))
        d = 10 ** (frm.scale - to.scale)
        bound_i = 10 ** to.precision
        out = []
        for v, okv in zip(vals, validity):
            if not okv:
                out.append(None)
                continue
            q, r = divmod(abs(v), d)
            if 2 * r >= d:
                q += 1
            q = q if v >= 0 else -q
            out.append(None if abs(q) >= bound_i else q)
        if ansi and any(v is None for v, okv in zip(out, validity) if okv):
            raise ArithmeticError("Decimal overflow in ANSI mode")
        from decimal import Decimal
        return HostColumn.from_pylist(
            [None if v is None else Decimal(v).scaleb(-to.scale)
             for v in out], to)
    if T.is_integral(frm) or isinstance(frm, T.BooleanType):
        from spark_rapids_tpu.ops import int128 as I
        hi, lo = I.from_i64(np, c.data.astype(np.int64))
        hi, lo, over = D.rescale_up(np, hi, lo, to.scale)
        ok = ~over & I.fits_precision(np, hi, lo, to.precision)
        if ansi and (~ok & validity).any():
            raise ArithmeticError("Decimal overflow in ANSI mode")
        return _limbs_to_col(np.where(ok, hi, 0), np.where(ok, lo, 0),
                             validity & ok, to)
    if T.is_floating(frm):
        bound = 10 ** to.precision
        with np.errstate(all="ignore"):
            scaled = c.data.astype(np.float64) * (10.0 ** to.scale)
            data = (np.sign(scaled) * np.floor(np.abs(scaled) + 0.5))
            over = (np.isnan(scaled) | np.isinf(scaled)
                    | (np.abs(data) >= float(bound)))
            data = np.nan_to_num(data, nan=0.0, posinf=0.0,
                                 neginf=0.0)
            data = np.where(over, 0.0, data)
        if ansi and (over & validity).any():
            raise ArithmeticError("Decimal overflow in ANSI mode")
        validity &= ~over
        # exact limb extraction from the (integral-valued) float: the
        # split v = hi*2^64 + lo is exact float arithmetic, so values
        # beyond 2^63 but within the precision survive (Spark keeps
        # e.g. 1e20 in a decimal(38,0))
        with np.errstate(all="ignore"):
            hi_f = np.floor(data * 2.0 ** -64)
            lo_f = data - hi_f * 2.0 ** 64
        hi = hi_f.astype(np.int64)
        lo = lo_f.astype(np.uint64).astype(np.int64)
        if T.is_limb_decimal(to):
            return _limbs_to_col(hi, lo, validity, to)
        return HostColumn(to, np.where(validity, lo, 0), validity
                          ).normalized()
    raise TypeError(f"cast {frm} -> {to}")


def _cast_from_decimal(c: HostColumn, to: T.DataType, ansi: bool
                       ) -> HostColumn:
    frm = c.dtype
    assert isinstance(frm, T.DecimalType)
    if T.is_limb_decimal(frm):
        from spark_rapids_tpu.ops import int128 as I
        hi, lo = _dec_limbs(c)
        if T.is_floating(to):
            # exact int64 path when the value fits; the 2-term wide sum
            # (within ~1 ulp of correctly rounded) only beyond 64 bits.
            # Multiply by the reciprocal rather than divide: XLA folds a
            # constant-divisor division into exactly this multiply, so
            # doing the same here keeps CPU == device bit-identical
            v64, small = I.to_i64(np, hi, lo)
            ulo = np.asarray(lo).astype(np.uint64).astype(np.float64)
            wide = hi.astype(np.float64) * 2.0 ** 64 + ulo
            data = np.where(small, v64.astype(np.float64), wide) \
                * (1.0 / 10.0 ** frm.scale)
            return HostColumn(to, data.astype(T.numpy_dtype(to)),
                              c.validity.copy())
        if T.is_integral(to):
            d = np.int64(10 ** min(frm.scale, 18))
            mhi, mlo = I.abs_(np, hi, lo)
            qh, ql, _r = I.divmod_u128_by_u64(np, mhi, mlo, d)
            if frm.scale > 18:
                qh, ql, _r2 = I.divmod_u128_by_u64(
                    np, qh, ql, np.int64(10 ** (frm.scale - 18)))
            neg = I.is_neg(np, hi, lo)
            nh, nl = I.neg(np, qh, ql)
            qh = np.where(neg, nh, qh)
            ql = np.where(neg, nl, ql)
            v, fits = I.to_i64(np, qh, ql)
            info = np.iinfo(T.numpy_dtype(to))
            validity = c.validity & fits & (v >= info.min) & (v <= info.max)
            if ansi and (~validity & c.validity).any():
                raise ArithmeticError("Cast overflow in ANSI mode")
            return HostColumn(to, v.astype(T.numpy_dtype(to)),
                              validity).normalized()
        raise TypeError(f"cast {frm} -> {to}")
    scale_div = 10 ** frm.scale
    if T.is_floating(to):
        # reciprocal multiply, matching XLA's constant-divisor folding
        # on the device leg (see the limb branch above)
        data = (c.data.astype(np.float64) * (1.0 / scale_div)).astype(
            T.numpy_dtype(to))
        return HostColumn(to, data, c.validity.copy())
    if T.is_integral(to):
        q = c.data.astype(np.int64)
        trunc = np.where(q < 0, -((-q) // scale_div), q // scale_div)
        info = np.iinfo(T.numpy_dtype(to))
        validity = c.validity & (trunc >= info.min) & (trunc <= info.max)
        if ansi and (~validity & c.validity).any():
            raise ArithmeticError("Cast overflow in ANSI mode")
        return HostColumn(to, trunc.astype(T.numpy_dtype(to)),
                          validity).normalized()
    raise TypeError(f"cast {frm} -> {to}")


# ---------------------------------------------------------------------------
# Aggregate functions. Modeled as (buffer slots + primitive segment ops)
# so CPU (numpy) and TPU (jax.ops.segment_*) share one contract; mirrors
# the update/merge split the reference binds separately per mode
# (aggregate.scala:247 strategy doc).
# ---------------------------------------------------------------------------

# primitive segment ops understood by both engines
PRIM_SUM = "sum"
PRIM_COUNT = "count"   # counts valid slots
PRIM_MIN = "min"
PRIM_MAX = "max"
PRIM_FIRST = "first"   # first valid value in segment (ignoreNulls=true)
PRIM_LAST = "last"
PRIM_FIRST_ANY = "first_any"  # first row incl. nulls (ignoreNulls=false);
PRIM_LAST_ANY = "last_any"    # sound at merge: partial rows exist only for
                              # non-empty groups, so a null buffer slot means
                              # "first value was null", never "no rows"
PRIM_SUM_NONNULL = "sum_nonnull"  # null-skipping sum that yields 0, not null
PRIM_COLLECT = "collect"          # gather valid values per group into a tuple
PRIM_COLLECT_MERGE = "collect_merge"  # concatenate gathered tuples


class AggregateFunction(Expression):
    """Declarative aggregate: buffer slots with update/merge primitives.

    buffer_slots(): [(slot_name, DataType, update_prim, update_child_expr,
                      merge_prim)]
    evaluate(buffers): final result column from merged buffer columns.
    """

    def buffer_slots(self) -> List:
        raise NotImplementedError

    def evaluate(self, buffers: List[HostColumn]) -> HostColumn:
        raise NotImplementedError


def _sum_result_type(dt: T.DataType) -> T.DataType:
    if isinstance(dt, T.DecimalType):
        return T.DecimalType(min(dt.precision + 10, 38), dt.scale)
    if T.is_integral(dt) or isinstance(dt, T.BooleanType):
        return T.LongT
    return T.DoubleT


class Sum(AggregateFunction):
    def __init__(self, child: Expression):
        self.children = [child]

    @property
    def data_type(self) -> T.DataType:
        return _sum_result_type(self.children[0].data_type)

    def buffer_slots(self):
        return [("sum", self.data_type, PRIM_SUM, self.children[0], PRIM_SUM)]

    def evaluate(self, buffers):
        return buffers[0]


class Count(AggregateFunction):
    def __init__(self, children: List[Expression]):
        self.children = list(children)  # empty = COUNT(*)

    @property
    def data_type(self) -> T.DataType:
        return T.LongT

    @property
    def nullable(self) -> bool:
        return False

    def buffer_slots(self):
        child = self.children[0] if self.children else Literal(1)
        return [("count", T.LongT, PRIM_COUNT, child, PRIM_SUM_NONNULL)]

    def evaluate(self, buffers):
        b = buffers[0]
        data = np.where(b.validity, b.data, 0).astype(np.int64)
        return HostColumn.all_valid(data, T.LongT)


class Min(AggregateFunction):
    def __init__(self, child: Expression):
        self.children = [child]

    @property
    def data_type(self) -> T.DataType:
        return self.children[0].data_type

    def buffer_slots(self):
        return [("min", self.data_type, PRIM_MIN, self.children[0], PRIM_MIN)]

    def evaluate(self, buffers):
        return buffers[0]


class Max(AggregateFunction):
    def __init__(self, child: Expression):
        self.children = [child]

    @property
    def data_type(self) -> T.DataType:
        return self.children[0].data_type

    def buffer_slots(self):
        return [("max", self.data_type, PRIM_MAX, self.children[0], PRIM_MAX)]

    def evaluate(self, buffers):
        return buffers[0]


class Average(AggregateFunction):
    def __init__(self, child: Expression):
        self.children = [child]

    def _child_decimal(self) -> Optional[T.DecimalType]:
        dt = self.children[0].data_type
        return dt if isinstance(dt, T.DecimalType) else None

    @property
    def data_type(self) -> T.DataType:
        dec = self._child_decimal()
        if dec is not None:
            # Spark Average for decimal: adjusted (p+4, s+4)
            return T.adjust_precision_scale(dec.precision + 4,
                                            dec.scale + 4)
        return T.DoubleT

    @property
    def nullable(self) -> bool:
        return True

    def buffer_slots(self):
        child = self.children[0]
        dec = self._child_decimal()
        if dec is not None:
            sum_t = T.DecimalType(min(dec.precision + 10, 38), dec.scale)
            return [("sum", sum_t, PRIM_SUM, child, PRIM_SUM),
                    ("count", T.LongT, PRIM_COUNT, child, PRIM_SUM_NONNULL)]
        if not isinstance(child.data_type, T.DoubleType):
            child_d = Cast(child, T.DoubleT)
        else:
            child_d = child
        return [("sum", T.DoubleT, PRIM_SUM, child_d, PRIM_SUM),
                ("count", T.LongT, PRIM_COUNT, child, PRIM_SUM_NONNULL)]

    def evaluate(self, buffers):
        s, cnt = buffers[0], buffers[1]
        count = np.where(cnt.validity, cnt.data, 0)
        dec = self._child_decimal()
        if dec is not None:
            # HALF_UP(sum * 10^4 / count) at the adjusted result scale
            from spark_rapids_tpu.ops import decimal_ops as D
            from spark_rapids_tpu.ops import int128 as I
            res = self.data_type
            hi, lo = _dec_limbs(s)
            up = res.scale - dec.scale
            hi, lo, over = D.rescale_up(np, hi, lo, max(up, 0))
            nz = count.astype(np.int64) > 0
            qh, ql = I.div_halfup(np, hi, lo,
                                  np.where(nz, count, 1).astype(np.int64))
            validity = s.validity & nz & ~over & I.fits_precision(
                np, qh, ql, res.precision)
            return _limbs_to_col(qh, ql, validity, res)
        count = count.astype(np.float64)
        validity = count > 0
        with np.errstate(all="ignore"):
            data = s.data.astype(np.float64) / np.where(count > 0, count, 1)
        return HostColumn(T.DoubleT, data, validity).normalized()


class First(AggregateFunction):
    def __init__(self, child: Expression, ignore_nulls: bool = False):
        self.children = [child]
        self.ignore_nulls = ignore_nulls

    @property
    def data_type(self) -> T.DataType:
        return self.children[0].data_type

    def buffer_slots(self):
        prim = PRIM_FIRST if self.ignore_nulls else PRIM_FIRST_ANY
        return [("first", self.data_type, prim, self.children[0], prim)]

    def evaluate(self, buffers):
        return buffers[0]


class Last(AggregateFunction):
    def __init__(self, child: Expression, ignore_nulls: bool = False):
        self.children = [child]
        self.ignore_nulls = ignore_nulls

    @property
    def data_type(self) -> T.DataType:
        return self.children[0].data_type

    def buffer_slots(self):
        prim = PRIM_LAST if self.ignore_nulls else PRIM_LAST_ANY
        return [("last", self.data_type, prim, self.children[0], prim)]

    def evaluate(self, buffers):
        return buffers[0]


class CollectList(AggregateFunction):
    """collect_list: per-group array of the non-null values, in row
    order (GpuCollectList, AggregateFunctions.scala:953). Empty groups
    yield an empty array, never null (Spark TypedImperativeAggregate
    createAggregationBuffer semantics)."""

    def __init__(self, child: Expression):
        self.children = [child]

    @property
    def data_type(self) -> T.DataType:
        return T.ArrayType(self.children[0].data_type)

    @property
    def nullable(self) -> bool:
        return False

    def buffer_slots(self):
        return [("collect", self.data_type, PRIM_COLLECT,
                 self.children[0], PRIM_COLLECT_MERGE)]

    def evaluate(self, buffers):
        b = buffers[0]
        data = np.empty(len(b.data), dtype=object)
        for i in range(len(b.data)):
            data[i] = tuple(b.data[i]) if b.validity[i] else ()
        return HostColumn.all_valid(data, self.data_type)


class CollectSet(CollectList):
    """collect_set: collect_list deduplicated at evaluation, first
    occurrence kept (GpuCollectSet role); NaNs deduplicate as one
    value and 0.0/-0.0 stay distinct (JVM Double.equals semantics of
    Spark's OpenHashSet buffer)."""

    def evaluate(self, buffers):
        b = buffers[0]
        data = np.empty(len(b.data), dtype=object)
        for i in range(len(b.data)):
            if not b.validity[i]:
                data[i] = ()
                continue
            seen = set()
            out = []
            for v in b.data[i]:
                k = ("<nan>",) if isinstance(v, float) and v != v else \
                    (v, math.copysign(1.0, v)) if isinstance(v, float) \
                    else v
                if k in seen:
                    continue
                seen.add(k)
                out.append(v)
            data[i] = tuple(out)
        return HostColumn.all_valid(data, self.data_type)


class CentralMomentAgg(AggregateFunction):
    """stddev/variance family over (count, sum, sum-of-squares) buffers.

    Spark's CentralMomentAgg (AggregateFunctions twin) keeps a Welford
    (n, avg, M2) buffer; this engine uses the algebraically equal
    moment sums so the update/merge primitives stay the shared
    sum/count vocabulary: M2 = sumsq - sum^2/n, clamped at 0 against
    float cancellation (a constant column must give stddev 0, not
    sqrt(-1e-18)). Both engines evaluate the SAME formula, so
    CPU == device holds bit-for-bit wherever their sums do."""

    is_sample = False   # /(n-1) vs /n
    is_stddev = False   # sqrt at the end

    def __init__(self, child: Expression):
        self.children = [child]

    @property
    def data_type(self) -> T.DataType:
        return T.DoubleT

    @property
    def nullable(self) -> bool:
        return True

    def buffer_slots(self):
        child = self.children[0]
        child_d = child if isinstance(child.data_type, T.DoubleType) \
            else Cast(child, T.DoubleT)
        sq = Multiply(child_d, child_d)
        return [("n", T.LongT, PRIM_COUNT, child, PRIM_SUM_NONNULL),
                ("sum", T.DoubleT, PRIM_SUM, child_d, PRIM_SUM),
                ("sumsq", T.DoubleT, PRIM_SUM, sq, PRIM_SUM)]

    def _finish(self, n, s, sq):
        """Shared (numpy) finisher; the device twin mirrors it in
        exec/agg.dev_evaluate."""
        nf = n.astype(np.float64)
        with np.errstate(all="ignore"):
            m2 = np.maximum(sq - (s * s) / np.where(n > 0, nf, 1.0), 0.0)
            div = nf - 1.0 if self.is_sample else nf
            out = m2 / div  # n==1 sample: 0/0 -> NaN (Spark semantics)
            if self.is_stddev:
                out = np.sqrt(out)
        return out

    def evaluate(self, buffers):
        n = np.where(buffers[0].validity, buffers[0].data, 0)
        s = buffers[1].data.astype(np.float64)
        sq = buffers[2].data.astype(np.float64)
        validity = n > 0
        out = self._finish(n, s, sq)
        return HostColumn(T.DoubleT, np.where(validity, out, 0.0),
                          validity).normalized()


class VariancePop(CentralMomentAgg):
    pass


class VarianceSamp(CentralMomentAgg):
    is_sample = True


class StddevPop(CentralMomentAgg):
    is_stddev = True


class StddevSamp(CentralMomentAgg):
    is_sample = True
    is_stddev = True


class AggregateExpression(Expression):
    """Wraps an AggregateFunction with mode + distinct flag (Catalyst
    AggregateExpression)."""

    def __init__(self, func: AggregateFunction, is_distinct: bool = False):
        self.children = [func]
        self.is_distinct = is_distinct

    @property
    def func(self) -> AggregateFunction:
        return self.children[0]

    @property
    def data_type(self) -> T.DataType:
        return self.func.data_type

    def __repr__(self) -> str:
        d = "distinct " if self.is_distinct else ""
        return f"{self.func.pretty_name}({d}{self.func.children})"


# ---------------------------------------------------------------------------
# Sort order
# ---------------------------------------------------------------------------

class SortOrder(Expression):
    def __init__(self, child: Expression, ascending: bool = True,
                 nulls_first: Optional[bool] = None):
        self.children = [child]
        self.ascending = ascending
        # Spark default: NULLS FIRST for asc, NULLS LAST for desc
        self.nulls_first = (ascending if nulls_first is None else nulls_first)

    @property
    def child(self) -> Expression:
        return self.children[0]

    @property
    def data_type(self) -> T.DataType:
        return self.child.data_type

    def __repr__(self) -> str:
        dirn = "ASC" if self.ascending else "DESC"
        nf = "NULLS FIRST" if self.nulls_first else "NULLS LAST"
        return f"{self.child!r} {dirn} {nf}"


# ---------------------------------------------------------------------------
# Window expressions (Catalyst windowExpressions.scala shape; reference
# device impl: GpuWindowExec.scala:187, GpuWindowExpression.scala)
# ---------------------------------------------------------------------------

# Frame boundary sentinels: None = unbounded in that direction, 0 = the
# current row, +/-k = k rows after/before (rows frames only).
class WindowFrame:
    """Rows/range frame. Spark defaults: with an order spec -> RANGE
    BETWEEN UNBOUNDED PRECEDING AND CURRENT ROW; without -> ROWS BETWEEN
    UNBOUNDED PRECEDING AND UNBOUNDED FOLLOWING."""

    def __init__(self, frame_type: str, lower: Optional[int],
                 upper: Optional[int]):
        assert frame_type in ("rows", "range")
        self.frame_type = frame_type
        self.lower = lower
        self.upper = upper

    @property
    def is_unbounded_whole(self) -> bool:
        return self.lower is None and self.upper is None

    @property
    def is_running(self) -> bool:
        """UNBOUNDED PRECEDING .. CURRENT ROW."""
        return self.lower is None and self.upper == 0

    def key(self) -> tuple:
        return (self.frame_type, self.lower, self.upper)

    def __repr__(self) -> str:
        def b(v, side):
            if v is None:
                return f"UNBOUNDED {side}"
            if v == 0:
                return "CURRENT ROW"
            return f"{abs(v)} {'PRECEDING' if v < 0 else 'FOLLOWING'}"
        return (f"{self.frame_type.upper()} BETWEEN "
                f"{b(self.lower, 'PRECEDING')} AND "
                f"{b(self.upper, 'FOLLOWING')}")


def default_frame(has_order: bool) -> WindowFrame:
    if has_order:
        return WindowFrame("range", None, 0)
    return WindowFrame("rows", None, None)


class WindowFunction(Expression):
    """Base of ranking/offset window functions (non-aggregate)."""


class RowNumber(WindowFunction):
    def __init__(self):
        self.children = []

    @property
    def data_type(self) -> T.DataType:
        return T.IntegerT

    @property
    def nullable(self) -> bool:
        return False


class Rank(WindowFunction):
    def __init__(self):
        self.children = []

    @property
    def data_type(self) -> T.DataType:
        return T.IntegerT

    @property
    def nullable(self) -> bool:
        return False


class DenseRank(WindowFunction):
    def __init__(self):
        self.children = []

    @property
    def data_type(self) -> T.DataType:
        return T.IntegerT

    @property
    def nullable(self) -> bool:
        return False


class NTile(WindowFunction):
    def __init__(self, n: int):
        self.children = []
        self.n = n

    @property
    def data_type(self) -> T.DataType:
        return T.IntegerT


class Lag(WindowFunction):
    """children = [input, default?]; offset is static."""

    def __init__(self, child: Expression, offset: int = 1,
                 default: Optional[Expression] = None):
        self.children = [child] + ([default] if default is not None else [])
        self.offset = offset

    @property
    def input(self) -> Expression:
        return self.children[0]

    @property
    def default(self) -> Optional[Expression]:
        return self.children[1] if len(self.children) > 1 else None

    @property
    def data_type(self) -> T.DataType:
        return self.input.data_type


class Lead(Lag):
    pass


class WindowExpression(Expression):
    """function OVER (spec). children = [func] + partition exprs + order
    SortOrders so resolution/transforms reach every subtree; the frame
    rides alongside."""

    def __init__(self, func: Expression, partition_spec: List[Expression],
                 order_spec: List[SortOrder],
                 frame: Optional[WindowFrame] = None):
        self.children = [func] + list(partition_spec) + list(order_spec)
        self.n_partition = len(partition_spec)
        self.n_order = len(order_spec)
        self.frame = frame or default_frame(bool(order_spec))

    @property
    def func(self) -> Expression:
        return self.children[0]

    @property
    def partition_spec(self) -> List[Expression]:
        return self.children[1:1 + self.n_partition]

    @property
    def order_spec(self) -> List["SortOrder"]:
        return self.children[1 + self.n_partition:]

    @property
    def data_type(self) -> T.DataType:
        return self.func.data_type

    def __repr__(self) -> str:
        return (f"{self.func!r} OVER (PARTITION BY {self.partition_spec} "
                f"ORDER BY {self.order_spec} {self.frame!r})")


# ---------------------------------------------------------------------------
# Python UDFs (sql/core PythonUDF; the reference routes these to its
# python worker pool — here they evaluate on the host row loop and the
# rewrite engine tags them NOT_ON_GPU, same placement the reference
# reports for un-compiled UDFs)
# ---------------------------------------------------------------------------

class ScalarSubquery(Expression):
    """Uncorrelated scalar subquery `(SELECT ... )` in expression
    position (Catalyst ScalarSubquery; the reference keeps the plan on
    device via GpuScalarSubquery over a materialized value). The session
    materializes it to a Literal before physical planning
    (session.plan_physical) — this node never reaches execution."""

    def __init__(self, plan, dtype: T.DataType):
        self.children = []
        self.plan = plan
        self._dtype = dtype

    @property
    def data_type(self) -> T.DataType:
        return self._dtype

    def __repr__(self) -> str:
        return "scalar-subquery"


def materialize_scalar_subqueries(plan, session):
    """Replace every ScalarSubquery with the Literal it evaluates to
    (executing each subquery ONCE per query, like Spark's subquery
    reuse). Enforces the at-most-one-row contract. With ``session``
    None (the explain path) subqueries substitute to unevaluated NULL
    placeholders instead — rendering a plan must never execute it."""
    cache: dict = {}

    def subst(e: Expression):
        if not isinstance(e, ScalarSubquery):
            return None
        if session is None:
            return Literal(None, e.data_type)
        key = id(e.plan)
        if key not in cache:
            batch = session.execute_plan(e.plan)
            if batch.num_rows > 1:
                raise ValueError(
                    "scalar subquery returned more than one row")
            if batch.num_rows == 0 or not batch.columns[0].validity[0]:
                val = None
            else:
                val = batch.columns[0].to_pylist()[0]
            cache[key] = Literal(val, e.data_type)
        return cache[key]

    _EXPR_ATTRS = ("project_list", "condition", "aggregates",
                   "grouping", "order", "window_exprs",
                   "partition_spec", "order_spec", "generator",
                   "expressions")

    def walk(p):
        """Copy-on-write: the input plan keeps its ScalarSubquery nodes
        so a later collect() re-evaluates against fresh data."""
        import copy as _copy
        new_children = [walk(c) for c in p.children]
        repl = {}
        for attr in _EXPR_ATTRS:
            v = getattr(p, attr, None)
            if isinstance(v, list) and any(isinstance(x, Expression)
                                           for x in v):
                repl[attr] = [x.transform(subst)
                              if isinstance(x, Expression) else x
                              for x in v]
            elif isinstance(v, Expression):
                repl[attr] = v.transform(subst)
        if new_children == p.children and not repl:
            return p
        q = _copy.copy(p)
        q.children = new_children
        for k, v in repl.items():
            setattr(q, k, v)
        return q

    def has_subquery(p) -> bool:
        for attr in _EXPR_ATTRS:
            v = getattr(p, attr, None)
            vs = v if isinstance(v, list) else [v] if v is not None else []
            for x in vs:
                if isinstance(x, Expression) and x.collect(
                        lambda n: isinstance(n, ScalarSubquery)):
                    return True
        return any(has_subquery(c) for c in p.children)

    if has_subquery(plan):
        return walk(plan)
    return plan


class PandasUDF(Expression):
    """Vectorized (scalar) pandas UDF (sql/core PythonUDF with
    SQL_SCALAR_PANDAS_UDF evalType; GpuPythonUDF.scala role). The
    planner EXTRACTS these out of projections into an
    ArrowEvalPythonExec (Spark's ExtractPythonUDFs rule) — eval() here
    is the in-process fallback used when one appears in an expression
    position the extractor doesn't cover (filters, sort keys)."""

    def __init__(self, fn, name: str, dtype: T.DataType,
                 children: List[Expression]):
        self.children = list(children)
        self.fn = fn
        self.name = name
        self._dtype = dtype

    @property
    def data_type(self) -> T.DataType:
        return self._dtype

    def eval(self, batch: HostBatch) -> HostColumn:
        import pandas as pd

        from spark_rapids_tpu.io.arrow_convert import (arrow_column_to_host,
                                                       host_column_to_arrow,
                                                       sql_type_to_arrow)
        args = []
        for c in self.children:
            args.append(host_column_to_arrow(c.eval(batch)).to_pandas())
        out = self.fn(*args)
        if not isinstance(out, pd.Series):
            out = pd.Series([out] * batch.num_rows)
        import pyarrow as pa
        arr = pa.Array.from_pandas(out,
                                   type=sql_type_to_arrow(self._dtype))
        if len(arr) != batch.num_rows:
            raise ValueError(
                f"pandas_udf {self.name} returned {len(arr)} rows for a "
                f"{batch.num_rows}-row batch")
        return arrow_column_to_host(arr, self._dtype)

    def __repr__(self) -> str:
        return f"{self.name}({self.children})"


class PythonUDF(Expression):
    def __init__(self, fn, name: str, dtype: T.DataType,
                 children: List[Expression]):
        self.children = list(children)
        self.fn = fn
        self.name = name
        self._dtype = dtype

    @property
    def data_type(self) -> T.DataType:
        return self._dtype

    def eval(self, batch: HostBatch) -> HostColumn:
        cols = [c.eval(batch) for c in self.children]
        n = batch.num_rows
        np_dt = T.numpy_dtype(self._dtype)
        data = (np.full(n, "", dtype=object)
                if np_dt == np.dtype(object) else np.zeros(n, dtype=np_dt))
        validity = np.zeros(n, dtype=bool)
        for i in range(n):
            args = [None if not c.validity[i]
                    else (c.data[i].item() if isinstance(c.data[i],
                                                         np.generic)
                          else c.data[i]) for c in cols]
            out = self.fn(*args)
            if out is not None:
                data[i] = out
                validity[i] = True
        return HostColumn(self._dtype, data, validity).normalized()

    def __repr__(self) -> str:
        return f"{self.name}({self.children})"
