"""CpuWindowExec: reference-semantics window evaluation on the host
(Spark WindowExec twin; the device twin is exec/window.py). Used by the
CPU session as the bit-exactness oracle for TpuWindowExec.

Per partition-group: rows are ordered by the window order spec; each
window expression computes a result array in ORIGINAL row order so the
operator appends columns without permuting its input (order-insensitive
output, same contract as the device exec).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

from spark_rapids_tpu.columnar.host import HostBatch, HostColumn
from spark_rapids_tpu.sql import expressions as E
from spark_rapids_tpu.sql import physical as P
from spark_rapids_tpu.sql import types as T


class CpuWindowExec(P.PhysicalPlan):
    def __init__(self, window_exprs: List[E.Expression],
                 partition_spec: List[E.Expression],
                 order_spec: List[E.SortOrder], child: P.PhysicalPlan):
        self.children = [child]
        self.window_exprs = window_exprs  # Alias(WindowExpression)
        self.partition_spec = partition_spec
        self.order_spec = order_spec

    @property
    def child(self):
        return self.children[0]

    @property
    def output(self):
        return list(self.child.output) + [E.named_output(e)
                                          for e in self.window_exprs]

    def partitions(self) -> List[P.PartitionThunk]:
        schema = self.schema

        def make(thunk: P.PartitionThunk) -> P.PartitionThunk:
            def run():
                batches = [b for b in thunk() if b.num_rows]
                if not batches:
                    return
                whole = (batches[0] if len(batches) == 1
                         else HostBatch.concat(batches))
                yield self._evaluate(whole, schema)
            return run
        return [make(t) for t in self.child.partitions()]

    # -- evaluation --------------------------------------------------------

    def _evaluate(self, batch: HostBatch, schema: T.StructType) -> HostBatch:
        child_out = self.child.output
        n = batch.num_rows
        # partition groups
        if self.partition_spec:
            key_cols = [E.bind_references(e, child_out).eval(batch)
                        for e in self.partition_spec]
            gids, n_groups, _rep = P.group_ids(key_cols, n)
        else:
            gids, n_groups = np.zeros(n, dtype=np.int64), 1
        # order composite keys (whole batch, sliced per group)
        composites = [P._composite_key(
            E.bind_references(o.child, child_out).eval(batch), o)
            for o in self.order_spec]

        out_cols = list(batch.columns)
        for alias in self.window_exprs:
            wx = alias.child
            assert isinstance(wx, E.WindowExpression)
            out_cols.append(self._eval_window(wx, batch, child_out, gids,
                                              n_groups, composites))
        return HostBatch(schema, out_cols, n)

    def _eval_window(self, wx: E.WindowExpression, batch: HostBatch,
                     child_out, gids: np.ndarray, n_groups: int,
                     composites: List[np.ndarray]) -> HostColumn:
        n = batch.num_rows
        dt = wx.data_type
        func = wx.func
        frame = wx.frame
        # order VALUES for value-bounded range frames (Spark RangeFrame:
        # exactly one numeric/date/timestamp order expression)
        order_vals: Optional[HostColumn] = None
        asc = True
        if frame.frame_type == "range" and not frame.is_unbounded_whole \
                and not frame.is_running:
            if len(self.order_spec) != 1:
                raise ValueError(
                    "RANGE frame with value offsets requires exactly "
                    "one ORDER BY expression")
            o = self.order_spec[0]
            odt = o.child.data_type
            # decimals rejected outright: int offsets against unscaled
            # storage would silently land at the wrong scale
            if not (T.is_integral(odt) or T.is_floating(odt)
                    or isinstance(odt, (T.DateType, T.TimestampType))):
                raise ValueError(
                    "RANGE frame offsets require a numeric/date/"
                    "timestamp ORDER BY expression, got "
                    f"{odt.simple_string}")
            order_vals = E.bind_references(o.child, child_out).eval(batch)
            asc = o.ascending
        # input values for aggregate/offset functions
        vals: Optional[HostColumn] = None
        if isinstance(func, E.AggregateExpression):
            agg = func.func
            if isinstance(agg, E.Count) and not agg.children:
                vals = HostColumn(
                    T.LongT, np.ones(n, dtype=np.int64),
                    np.ones(n, dtype=bool))
            else:
                src = agg.children[0]
                if isinstance(agg, E.Average):
                    src = E.Cast(src, T.DoubleT)
                vals = E.bind_references(src, child_out).eval(batch)
        elif isinstance(func, E.Lag):
            vals = E.bind_references(func.input, child_out).eval(batch)

        # storage_zeros: decimal128 outputs need the (n, 2) limb layout
        out_data = T.storage_zeros(dt, n)
        out_valid = np.zeros(n, dtype=bool)

        for g in range(n_groups):
            rows = np.nonzero(gids == g)[0]
            if not len(rows):
                continue
            if composites:
                order_local = np.lexsort(
                    [c[rows] for c in composites][::-1])
            else:
                order_local = np.arange(len(rows))
            sorted_rows = rows[order_local]
            m = len(sorted_rows)
            # peer boundaries (for rank/dense_rank/range frames)
            new_peer = np.ones(m, dtype=bool)
            if composites:
                eq = np.ones(m - 1, dtype=bool) if m > 1 else \
                    np.zeros(0, dtype=bool)
                for c in composites:
                    cv = c[sorted_rows]
                    eq &= cv[1:] == cv[:-1]
                new_peer[1:] = ~eq
            d, v = self._func_over_group(func, frame, vals, sorted_rows,
                                         new_peer, dt, order_vals, asc)
            out_data[sorted_rows] = d
            out_valid[sorted_rows] = v
        return HostColumn(dt, out_data, out_valid).normalized()

    def _func_over_group(self, func, frame: E.WindowFrame,
                         vals: Optional[HostColumn],
                         sorted_rows: np.ndarray, new_peer: np.ndarray,
                         dt: T.DataType,
                         order_vals: Optional[HostColumn] = None,
                         asc: bool = True) -> Tuple[np.ndarray, np.ndarray]:
        """Result (data, validity) in SORTED group order."""
        m = len(sorted_rows)
        if isinstance(func, E.RowNumber):
            return np.arange(1, m + 1, dtype=np.int32), np.ones(m, bool)
        if isinstance(func, E.DenseRank):
            return np.cumsum(new_peer).astype(np.int32), np.ones(m, bool)
        if isinstance(func, E.Rank):
            pos = np.arange(m)
            peer_start = np.maximum.accumulate(np.where(new_peer, pos, 0))
            return (peer_start + 1).astype(np.int32), np.ones(m, bool)
        if isinstance(func, E.NTile):
            k = func.n
            pos = np.arange(m)
            base, rem = divmod(m, k)
            # first `rem` buckets get base+1 rows
            big = rem * (base + 1)
            tile = np.where(pos < big, pos // max(base + 1, 1),
                            rem + (pos - big) // max(base, 1))
            return (tile + 1).astype(np.int32), np.ones(m, bool)
        if isinstance(func, E.Lag):
            off = func.offset if isinstance(func, E.Lag) and \
                not isinstance(func, E.Lead) else -func.offset
            src_pos = np.arange(m) - off
            ok = (src_pos >= 0) & (src_pos < m)
            safe = np.clip(src_pos, 0, m - 1)
            gd = vals.data[sorted_rows][safe]
            gv = vals.validity[sorted_rows][safe] & ok
            if func.default is not None:
                # the analyzer-level cast Spark inserts: one rounding
                # implementation (Cast's HALF_UP decimal rescale, limb
                # split included) shared with the device exec
                dflt = func.default
                if dflt.data_type != dt:
                    dflt = E.Cast(dflt, dt)
                dcol = dflt.eval(HostBatch(T.StructType([]), [], 1))
                if dcol.validity[0]:
                    # decimal128 data is (m, 2) limbs: lift the row mask
                    okb = ok[:, None] if gd.ndim == 2 else ok
                    gd = np.where(okb, gd, dcol.data[0])
                    gv = gv | ~ok
            if T.is_limb_decimal(dt):
                return gd.astype(np.int64), gv
            return gd.astype(T.numpy_dtype(dt)), gv
        if isinstance(func, E.AggregateExpression):
            return self._agg_over_group(func.func, frame, vals,
                                        sorted_rows, new_peer, dt,
                                        order_vals, asc)
        raise NotImplementedError(type(func).__name__)

    def _agg_over_group(self, agg: E.AggregateFunction,
                        frame: E.WindowFrame, vals: HostColumn,
                        sorted_rows: np.ndarray, new_peer: np.ndarray,
                        dt: T.DataType,
                        order_vals: Optional[HostColumn] = None,
                        asc: bool = True) -> Tuple[np.ndarray, np.ndarray]:
        m = len(sorted_rows)
        v = vals.data[sorted_rows]
        ok = vals.validity[sorted_rows].astype(bool)
        # frame [lo_i, hi_i] inclusive bounds per sorted position
        pos = np.arange(m)
        if frame.is_unbounded_whole:
            lo = np.zeros(m, dtype=np.int64)
            hi = np.full(m, m - 1, dtype=np.int64)
        elif frame.frame_type == "range" and frame.is_running:
            # running with peers: frame end = last row of the peer group
            peer_id = np.cumsum(new_peer) - 1
            last_of_peer = np.zeros(peer_id.max() + 1, dtype=np.int64)
            np.maximum.at(last_of_peer, peer_id, pos)
            lo = np.zeros(m, dtype=np.int64)
            hi = last_of_peer[peer_id]
        elif frame.frame_type == "range":
            # VALUE-bounded range: [ov + lower, ov + upper] resolved by
            # binary search over the (partition-sorted) order values;
            # null-ordered rows frame their null peer block (Spark
            # RangeFrame semantics)
            ov = order_vals.data[sorted_rows].astype(np.float64) \
                if np.issubdtype(order_vals.data.dtype, np.floating) \
                else order_vals.data[sorted_rows].astype(np.int64)
            ook = order_vals.validity[sorted_rows].astype(bool)
            sgn = ov if asc else -ov
            # NaN order values: all NaNs are ordering-peers (Spark total
            # order), so NaN rows frame their peer block like nulls do
            # (a SEPARATE block — nulls and NaNs sort apart), and finite
            # rows' searches exclude them (NaN never falls in a finite
            # value interval; inside the search array it would break
            # searchsorted's sorted contract).
            orig_ook = ook
            if np.issubdtype(ov.dtype, np.floating):
                is_nan_row = orig_ook & np.isnan(ov)
                ook = ook & ~np.isnan(ov)
            else:
                is_nan_row = np.zeros(m, dtype=bool)
            nn = np.nonzero(ook)[0]  # contiguous block by sort order
            nn_start = int(nn[0]) if len(nn) else 0
            nn_vals = sgn[nn]  # ascending within the block
            low_off = frame.lower
            up_off = frame.upper
            lo = np.zeros(m, dtype=np.int64)
            hi = np.full(m, -1, dtype=np.int64)
            if len(nn):
                # offsets apply UNNEGATED in sign-normalized space: for
                # DESC, sgn = -ov ascends with sort position, and
                # [sgn+lower, sgn+upper] is exactly Spark's value frame
                if low_off is None:
                    lo_nn = np.full(len(nn), nn_start, dtype=np.int64)
                else:
                    lo_nn = nn_start + np.searchsorted(
                        nn_vals, nn_vals + low_off, "left")
                if up_off is None:
                    hi_nn = np.full(len(nn), nn_start + len(nn) - 1,
                                    dtype=np.int64)
                else:
                    hi_nn = nn_start + np.searchsorted(
                        nn_vals, nn_vals + up_off, "right") - 1
                lo[nn] = lo_nn
                hi[nn] = hi_nn
            nulls = np.nonzero(~orig_ook)[0]
            if len(nulls):  # null rows frame the whole null block
                lo[nulls] = nulls[0]
                hi[nulls] = nulls[-1]
            nans = np.nonzero(is_nan_row)[0]
            if len(nans):  # NaN rows frame the whole NaN block
                lo[nans] = nans[0]
                hi[nans] = nans[-1]
        else:  # rows frame
            lo = pos + (-(1 << 62) if frame.lower is None else frame.lower)
            hi = pos + ((1 << 62) if frame.upper is None else frame.upper)
            lo = np.clip(lo, 0, m)
            hi = np.clip(hi, -1, m - 1)
        out = np.zeros(m, dtype=T.numpy_dtype(dt))
        valid = np.zeros(m, dtype=bool)
        for i in range(m):
            l, h = int(lo[i]), int(hi[i])
            if h < l:
                if isinstance(agg, E.Count):
                    out[i], valid[i] = 0, True
                continue
            sl_ok = ok[l:h + 1]
            sl = v[l:h + 1][sl_ok]
            if isinstance(agg, E.Count):
                out[i], valid[i] = len(sl), True
                continue
            if isinstance(agg, (E.First, E.Last)) and not agg.ignore_nulls:
                j = l if isinstance(agg, E.First) else h
                out[i], valid[i] = v[j], ok[j]
                continue
            if len(sl) == 0:
                continue
            if isinstance(agg, E.Sum):
                out[i], valid[i] = sl.sum(), True
            elif isinstance(agg, E.Min):
                # Spark total order: NaN is greatest, so min skips NaNs
                if np.issubdtype(sl.dtype, np.floating):
                    nn = sl[~np.isnan(sl)]
                    out[i] = nn.min() if len(nn) else np.nan
                else:
                    out[i] = sl.min()
                valid[i] = True
            elif isinstance(agg, E.Max):
                # np.max already yields NaN when present (NaN greatest)
                if np.issubdtype(sl.dtype, np.floating) and \
                        np.isnan(sl).any():
                    out[i] = np.nan
                else:
                    out[i] = sl.max()
                valid[i] = True
            elif isinstance(agg, E.Average):
                out[i], valid[i] = sl.astype(np.float64).mean(), True
            elif isinstance(agg, E.First):
                out[i], valid[i] = sl[0], True
            elif isinstance(agg, E.Last):
                out[i], valid[i] = sl[-1], True
            else:
                raise NotImplementedError(type(agg).__name__)
        return out, valid

    def simple_string(self):
        return (f"Window {self.window_exprs} part={self.partition_spec} "
                f"order={self.order_spec}")
