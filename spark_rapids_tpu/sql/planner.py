"""Logical -> physical planning (the role Spark's SparkPlanner +
EnsureRequirements plays in the reference). Produces the CPU physical plan
that the plugin's TpuOverrides then rewrites (Plugin.scala:48 hook point).

Planning decisions mirrored from Spark:
- Aggregate splits into partial -> hash exchange on keys -> final.
- Equi-joins become exchange(left) + exchange(right) + shuffled hash join,
  or broadcast hash join when the build side is a small LocalRelation
  (autoBroadcastJoinThreshold analogue).
- Global sort inserts a range-partitioning exchange; the reference replaces
  SortMergeJoin with shuffled hash join (GpuSortMergeJoinExec.scala:72-92),
  so we never plan SMJ at all.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

from spark_rapids_tpu.conf import (AUTO_BROADCAST_JOIN_THRESHOLD, TpuConf,
                                   SHUFFLE_PARTITIONS)
from spark_rapids_tpu.sql import expressions as E
from spark_rapids_tpu.sql import logical as L
from spark_rapids_tpu.sql import physical as P
from spark_rapids_tpu.sql import types as T


def estimate_plan_bytes(p: L.LogicalPlan) -> Optional[int]:
    """Best-effort size estimate of a logical subtree's output, for
    broadcast selection (the sizeInBytes statistic Spark's JoinSelection
    consults). LocalRelations measure their host batches, FileScans their
    on-disk footprint; row-preserving/reducing unary nodes pass the child
    estimate through (an upper bound). None = unknown (never broadcast).
    """
    if isinstance(p, L.LocalRelation):
        from spark_rapids_tpu.memory import _host_sizeof
        return sum(_host_sizeof(b) for b in p.batches)
    if isinstance(p, L.FileScan):
        import os
        total = 0
        for path in p.paths:
            if os.path.isdir(path):
                for root, _dirs, files in os.walk(path):
                    total += sum(os.path.getsize(os.path.join(root, f))
                                 for f in files)
            elif os.path.exists(path):
                total += os.path.getsize(path)
        return total
    if isinstance(p, (L.Project, L.Filter, L.Limit, L.Sort,
                      L.SubqueryAlias)):
        return estimate_plan_bytes(p.child)
    return None


class Planner:
    def __init__(self, conf: TpuConf, session=None):
        self.conf = conf
        self.session = session
        self.shuffle_partitions = conf.shuffle_partitions

    def plan(self, plan: L.LogicalPlan) -> P.PhysicalPlan:
        m = getattr(self, f"_plan_{type(plan).__name__.lower()}", None)
        if m is None:
            raise NotImplementedError(
                f"no physical planning for {type(plan).__name__}")
        return m(plan)

    def _plan_subqueryalias(self, p) -> P.PhysicalPlan:
        # physically transparent: the alias only re-qualifies attributes
        # (same expr_ids), so the child's plan IS the plan
        return self.plan(p.child)

    # -- sources -----------------------------------------------------------
    def _plan_localrelation(self, p: L.LocalRelation) -> P.PhysicalPlan:
        return P.CpuLocalScanExec(p.output, p.batches, p.num_partitions)

    def _plan_filescan(self, p: L.FileScan) -> P.PhysicalPlan:
        from spark_rapids_tpu.io.readers import CpuFileScanExec
        return CpuFileScanExec(p.output, p.fmt, p.paths, p.options,
                               self.conf)

    def _plan_cachedrelation(self, p) -> P.PhysicalPlan:
        from spark_rapids_tpu.io.cache import CpuCachedScanExec
        return CpuCachedScanExec(p)

    def _plan_range(self, p: L.Range) -> P.PhysicalPlan:
        return P.CpuRangeExec(p.output, p.start, p.end, p.step,
                              p.num_partitions)

    def _plan_mapinpandas(self, p) -> P.PhysicalPlan:
        from spark_rapids_tpu.exec.python_exec import CpuMapInPandasExec
        # the logical node's output attrs pass through (downstream
        # operators already resolved against those expr_ids)
        return CpuMapInPandasExec(p.fn, p._schema, self.plan(p.child),
                                  self.conf, output=p.output)

    def _extract_pandas_udfs(self, project_list, child):
        """ExtractPythonUDFs rule (sql/core python rules; the reference
        converts the result to GpuArrowEvalPythonExec): pull every
        PandasUDF subtree into an ArrowEvalPython node below the
        projection and substitute attribute references. UDF arguments
        that are not plain attributes are pre-projected. PURE: the
        logical expressions are never mutated (a DataFrame plans once
        per execution; explain + collect must both see the UDFs)."""
        extra: List[E.Alias] = []
        udfs: dict = {}  # semantic key -> Alias(PandasUDF-copy)

        def sub(e):
            if not isinstance(e, E.PandasUDF):
                return None
            # the whole arg subtree must be free of already-extracted
            # UDF outputs (bottom-up transform replaced inner UDFs with
            # their _pudfN attrs): one eval node cannot feed itself
            udf_ids = {al.expr_id for al in udfs.values()}
            for a in e.children:
                if a.collect(lambda x: isinstance(
                        x, E.AttributeReference)
                        and x.expr_id in udf_ids):
                    raise NotImplementedError(
                        "nested pandas UDF calls are not supported")
            # dedup on the ORIGINAL arg subtrees so identical calls with
            # expression args also evaluate once
            key = (id(e.fn), repr(e.children), repr(e.data_type))
            al = udfs.get(key)
            if al is None:
                new_args = []
                for a in e.children:
                    if isinstance(a, E.AttributeReference):
                        new_args.append(a)
                    else:
                        arg_al = E.Alias(a, f"_pudf_arg{len(extra)}")
                        extra.append(arg_al)
                        new_args.append(arg_al.to_attribute())
                al = E.Alias(
                    E.PandasUDF(e.fn, e.name, e.data_type, new_args),
                    f"_pudf{len(udfs)}")
                udfs[key] = al
            return al.to_attribute()

        new_list = [e.transform(sub) for e in project_list]
        if not udfs:
            return project_list, child
        from spark_rapids_tpu.exec.python_exec import CpuArrowEvalPythonExec
        if extra:
            child = P.CpuProjectExec(list(child.output) + extra, child)
        return new_list, CpuArrowEvalPythonExec(
            list(udfs.values()), child, self.conf)

    # -- simple unary ------------------------------------------------------
    def _plan_project(self, p: L.Project) -> P.PhysicalPlan:
        child = self.plan(p.child)
        plist, child = self._extract_pandas_udfs(p.project_list, child)
        p = L.Project(plist, p.child)
        # input_file_name() needs per-file batches: downgrade a
        # COALESCING scan under this project to PERFILE (the reference's
        # InputFileBlockRule forces the same, GpuOverrides.scala)
        def has_iff(e):
            return isinstance(e, E.InputFileName) \
                or any(has_iff(c) for c in e.children)
        if any(has_iff(e) for e in p.project_list):
            from spark_rapids_tpu.io.readers import CpuFileScanExec
            node = child
            while node is not None:
                if isinstance(node, CpuFileScanExec):
                    node.force_perfile = True
                    break
                node = node.children[0] if len(node.children) == 1 \
                    else None
        return P.CpuProjectExec(p.project_list, child)

    def _plan_filter(self, p: L.Filter) -> P.PhysicalPlan:
        child = self.plan(p.child)
        # predicate pushdown: attribute-vs-literal conjuncts reach the
        # parquet scan for footer-stats row-group pruning (the planner
        # half of GpuParquetScanBase's filterBlocks; the Filter node
        # stays, so pruning may be conservative)
        from spark_rapids_tpu.io.readers import CpuFileScanExec
        if isinstance(child, CpuFileScanExec):
            preds = _pushable_predicates(p.condition)
            if preds:
                child.set_pushdown(preds)
        return P.CpuFilterExec(p.condition, child)

    def _plan_union(self, p: L.Union) -> P.PhysicalPlan:
        return P.CpuUnionExec([self.plan(c) for c in p.children], p.output)

    def _plan_limit(self, p: L.Limit) -> P.PhysicalPlan:
        child = self.plan(p.child)
        local = P.CpuLocalLimitExec(p.n, child)
        single = P.CpuShuffleExchangeExec(P.SinglePartitioning(), local)
        return P.CpuGlobalLimitExec(p.n, single)

    def _plan_sort(self, p: L.Sort) -> P.PhysicalPlan:
        child = self.plan(p.child)
        if p.is_global:
            npart = min(self.shuffle_partitions,
                        max(1, self.shuffle_partitions))
            child = P.CpuShuffleExchangeExec(
                P.RangePartitioning(p.order, npart), child)
        return P.CpuSortExec(p.order, p.is_global, child)

    def _plan_repartition(self, p: L.Repartition) -> P.PhysicalPlan:
        child = self.plan(p.child)
        if p.by is not None:
            part = P.HashPartitioning(p.by, p.num_partitions)
        else:
            part = P.RoundRobinPartitioning(p.num_partitions)
        # df.repartition(n, ...) is an explicit user ask: the device
        # rewrite must not coalesce it like a planner-inserted exchange
        part.user_specified = True
        return P.CpuShuffleExchangeExec(part, child)

    def _plan_expand(self, p: L.Expand) -> P.PhysicalPlan:
        return P.CpuExpandExec(p.projections, p.output, self.plan(p.child))

    def _plan_generate(self, p: L.Generate) -> P.PhysicalPlan:
        return P.CpuGenerateExec(p.generator, p.gen_output,
                                 self.plan(p.child))

    def _plan_window(self, p: L.Window) -> P.PhysicalPlan:
        from spark_rapids_tpu.sql.window_exec import CpuWindowExec
        child = self.plan(p.child)
        if p.partition_spec:
            child = P.CpuShuffleExchangeExec(
                P.HashPartitioning(p.partition_spec,
                                   self.shuffle_partitions), child)
        else:
            child = P.CpuShuffleExchangeExec(P.SinglePartitioning(), child)
        return CpuWindowExec(p.window_exprs, p.partition_spec, p.order_spec,
                             child)

    # -- aggregate ---------------------------------------------------------
    def _plan_aggregate(self, p: L.Aggregate) -> P.PhysicalPlan:
        rewritten = self._rewrite_distinct(p)
        if rewritten is not None:
            return self.plan(rewritten)
        child = self.plan(p.child)
        # grouping must be attributes; project aliased keys first, reusing
        # the Alias' own id so result expressions bind to the same attr
        grouping_attrs: List[E.AttributeReference] = []
        pre_proj: List[E.Expression] = list(child.output)
        need_proj = False
        aggregates = list(p.aggregates)
        for g in p.grouping:
            if isinstance(g, E.AttributeReference):
                grouping_attrs.append(g)
            elif isinstance(g, E.Alias):
                pre_proj.append(g)
                grouping_attrs.append(g.to_attribute())
                need_proj = True
            else:
                alias = E.Alias(g, f"_groupingexpr_{len(grouping_attrs)}")
                pre_proj.append(alias)
                grouping_attrs.append(alias.to_attribute())
                need_proj = True
        if need_proj:
            child = P.CpuProjectExec(pre_proj, child)

        slots = P.plan_agg_slots(aggregates)
        partial = P.CpuHashAggregateExec(grouping_attrs, aggregates,
                                         "partial", child, slots)
        if grouping_attrs:
            exchange = P.CpuShuffleExchangeExec(
                P.HashPartitioning(list(grouping_attrs),
                                   self.shuffle_partitions), partial)
        else:
            exchange = P.CpuShuffleExchangeExec(P.SinglePartitioning(),
                                                partial)
        return P.CpuHashAggregateExec(grouping_attrs, aggregates, "final",
                                      exchange, slots)

    def _rewrite_distinct(self, p: L.Aggregate) -> Optional[L.Aggregate]:
        """DISTINCT aggregates -> dedup-then-aggregate (Spark's
        RewriteDistinctAggregates single-distinct-group shape): an inner
        Aggregate on (grouping, distinct children) deduplicates, the
        outer runs the same functions non-distinct. Mixed distinct +
        non-distinct aggregates would need Expand; unsupported."""
        aliases = [e for e in p.aggregates
                   if isinstance(e, E.Alias)
                   and isinstance(e.child, E.AggregateExpression)]
        distinct = [a for a in aliases if a.child.is_distinct]
        if not distinct:
            return None
        if len(distinct) != len(aliases):
            return self._rewrite_mixed_distinct(p, aliases, distinct)
        child_sets = {tuple(sorted(repr(c) for c in a.child.func.children))
                      for a in distinct}
        if len(child_sets) > 1:
            raise NotImplementedError(
                "multiple DISTINCT aggregates over different columns need "
                "the Expand rewrite; split the query instead")
        inner_items: List[E.Expression] = list(p.grouping)
        child_attr: dict = {}
        for a in distinct:
            for c in a.child.func.children:
                key = repr(c)
                if key in child_attr:
                    continue
                if isinstance(c, E.AttributeReference):
                    child_attr[key] = c
                    inner_items.append(c)
                else:
                    al = E.Alias(c, f"_d{len(child_attr)}")
                    child_attr[key] = al.to_attribute()
                    inner_items.append(al)
        # aggregates list carries attribute refs (aliases stay in the
        # grouping for the pre-projection), mirroring GroupedData.agg
        inner_aggs = [g if isinstance(g, E.AttributeReference)
                      else g.to_attribute() for g in inner_items]
        inner = L.Aggregate(list(inner_items), inner_aggs, p.child)
        # the outer aggregate sees the inner's OUTPUT attributes: aliased
        # grouping expressions become their attribute references
        grouping_attr = {id(g): (g if isinstance(g, E.AttributeReference)
                                 else g.to_attribute())
                         for g in p.grouping}
        outer_grouping = [grouping_attr[id(g)] for g in p.grouping]
        grouping_ids = {a.expr_id for a in outer_grouping}
        outer_aggs: List[E.Expression] = []
        for e in p.aggregates:
            if e in distinct:
                func = e.child.func
                new_children = [child_attr[repr(c)]
                                for c in func.children]
                new_func = func.with_children(new_children)
                outer_aggs.append(E.Alias(
                    E.AggregateExpression(new_func, is_distinct=False),
                    e.name, expr_id=e.expr_id))
            elif isinstance(e, E.Alias) and e.expr_id in grouping_ids:
                outer_aggs.append(e.to_attribute())
            else:
                outer_aggs.append(e)
        return L.Aggregate(outer_grouping, outer_aggs, inner)

    def _rewrite_mixed_distinct(self, p: L.Aggregate, aliases,
                                distinct) -> L.Aggregate:
        """Mixed DISTINCT + plain aggregates (``count(DISTINCT a),
        sum(b)``): split into a distinct-only aggregate and a plain
        aggregate over the same child, then join them on null-safe key
        equality — both sides have exactly one row per group (incl. the
        null-key groups, hence ``<=>``), so the join is 1:1. This is the
        role Spark's RewriteDistinctAggregates Expand plays
        (aggregate.scala:1059); the two-aggregate join form reuses the
        engine's existing exact aggregate + join machinery end-to-end on
        device. Before round 5 this shape raised NotImplementedError.

        The shared child is wrapped in a CachedRelation so the two
        aggregates read it ONCE (Spark's Expand shape also reads once;
        without the cache the whole upstream pipeline, scans included,
        would execute twice)."""
        distinct_ids = {id(a) for a in distinct}
        plain = [a for a in aliases if id(a) not in distinct_ids]
        grouping_attr = {id(g): (g if isinstance(g, E.AttributeReference)
                                 else g.to_attribute())
                         for g in p.grouping}
        g_attrs = [grouping_attr[id(g)] for g in p.grouping]
        g_ids = {a.expr_id for a in g_attrs}
        child = p.child
        if self.session is not None:
            from spark_rapids_tpu.io.cache import CachedRelation
            child = CachedRelation(child, self.session)
        # left: grouping + distinct aggs (recursion hits the pure-distinct
        # rewrite); right: grouping re-aliased to fresh ids + plain aggs
        left = L.Aggregate(
            list(p.grouping),
            list(g_attrs) + [a for a in p.aggregates
                             if id(a) in distinct_ids],
            child)
        rk_aliases = [E.Alias(a, f"_mdk{i}")
                      for i, a in enumerate(g_attrs)]
        right = L.Aggregate(list(p.grouping),
                            rk_aliases + plain, child)
        cond = None
        for la, ra in zip(g_attrs, rk_aliases):
            eq = E.EqualNullSafe(la, ra.to_attribute())
            cond = eq if cond is None else E.And(cond, eq)
        if cond is None:
            # global aggregates: two single-row sides, cross join
            joined = L.Join(left, right, "cross", None)
        else:
            joined = L.Join(left, right, "inner", cond)
        # final projection restores the requested output order
        plain_attr = {id(a): a.to_attribute() for a in plain}
        out: List[E.Expression] = []
        for e in p.aggregates:
            if isinstance(e, E.Alias) and isinstance(
                    e.child, E.AggregateExpression) \
                    and id(e) in {id(x) for x in plain}:
                out.append(plain_attr[id(e)])
            elif isinstance(e, E.Alias) and e.expr_id in g_ids:
                out.append(e.to_attribute())
            elif isinstance(e, E.Alias) and isinstance(
                    e.child, E.AggregateExpression):
                out.append(e.to_attribute())
            else:
                out.append(e)
        return L.Project(out, joined)

    # -- join --------------------------------------------------------------
    def _plan_join(self, p: L.Join) -> P.PhysicalPlan:
        left = self.plan(p.left)
        right = self.plan(p.right)
        left_keys, right_keys, null_safe, residual = split_equi_join(
            p.condition, p.left.output, p.right.output)
        if not left_keys:
            if p.join_type in ("inner", "cross"):
                return self._nested_loop(p, left, right)
            raise NotImplementedError(
                f"non-equi {p.join_type} join not supported yet")

        threshold = int(self.conf.get(AUTO_BROADCAST_JOIN_THRESHOLD))
        est = estimate_plan_bytes(p.right)
        small_right = (threshold >= 0 and est is not None
                       and est <= threshold)
        if small_right and p.join_type in ("inner", "left", "leftouter",
                                           "leftsemi", "leftanti", "cross"):
            return P.CpuBroadcastHashJoinExec(
                left_keys, right_keys, p.join_type, residual, left,
                P.CpuBroadcastExchangeExec(right),
                p.output, null_safe=null_safe)
        n = self.shuffle_partitions
        lex = P.CpuShuffleExchangeExec(P.HashPartitioning(left_keys, n),
                                       left)
        rex = P.CpuShuffleExchangeExec(P.HashPartitioning(right_keys, n),
                                       right)
        return P.CpuShuffledHashJoinExec(left_keys, right_keys, p.join_type,
                                         residual, lex, rex, p.output,
                                         null_safe=null_safe)

    def _nested_loop(self, p: L.Join, left: P.PhysicalPlan,
                     right: P.PhysicalPlan) -> P.PhysicalPlan:
        from spark_rapids_tpu.sql.nested_loop import (
            CpuBroadcastNestedLoopJoinExec)
        return CpuBroadcastNestedLoopJoinExec(p.join_type, p.condition,
                                              left, right, p.output)


def split_equi_join(condition: Optional[E.Expression],
                    left_out, right_out
                    ) -> Tuple[List[E.Expression], List[E.Expression],
                               List[bool], Optional[E.Expression]]:
    """Split a join condition into equi-key pairs (+ per-pair null-safe
    flags for ``<=>``) and residual conjuncts (Spark
    ExtractEquiJoinKeys)."""
    if condition is None:
        return [], [], [], None
    left_ids = {a.expr_id for a in left_out}
    right_ids = {a.expr_id for a in right_out}

    def side(e: E.Expression) -> Optional[str]:
        ids = {a.expr_id for a in e.references()}
        if not ids:
            return "none"
        if ids <= left_ids:
            return "left"
        if ids <= right_ids:
            return "right"
        return None

    conjuncts = split_conjuncts(condition)
    lk: List[E.Expression] = []
    rk: List[E.Expression] = []
    ns: List[bool] = []
    residual: List[E.Expression] = []
    for c in conjuncts:
        if isinstance(c, (E.EqualTo, E.EqualNullSafe)):
            sl, sr = side(c.left), side(c.right)
            if sl == "left" and sr == "right":
                lk.append(c.left)
                rk.append(c.right)
                ns.append(isinstance(c, E.EqualNullSafe))
                continue
            if sl == "right" and sr == "left":
                lk.append(c.right)
                rk.append(c.left)
                ns.append(isinstance(c, E.EqualNullSafe))
                continue
        residual.append(c)
    res = None
    for r in residual:
        res = r if res is None else E.And(res, r)
    return lk, rk, ns, res


def split_conjuncts(e: E.Expression) -> List[E.Expression]:
    if isinstance(e, E.And):
        return split_conjuncts(e.left) + split_conjuncts(e.right)
    return [e]


_PUSH_OPS = {E.EqualTo: "eq", E.LessThan: "lt", E.LessThanOrEqual: "le",
             E.GreaterThan: "gt", E.GreaterThanOrEqual: "ge"}
_PUSH_SWAP = {"lt": "gt", "le": "ge", "gt": "lt", "ge": "le", "eq": "eq"}


def _fold_literal(e: E.Expression):
    """Storage value of a literal-only subtree (e.g. Cast('1998-09-02'
    as date)), or None when it references columns or fails to fold."""
    def has_attr(x) -> bool:
        if isinstance(x, (E.AttributeReference, E.BoundReference)):
            return True
        return any(has_attr(c) for c in x.children)
    if has_attr(e):
        return None
    try:
        from spark_rapids_tpu.columnar.host import HostBatch
        col = e.eval(HostBatch(T.StructType([]), [], 1))
        if not col.validity[0]:
            return None
        v = col.data[0]
        if hasattr(v, "item"):
            v = v.item()
        return v if isinstance(v, (int, float, str)) else None
    except Exception:
        return None


def _pushable_predicates(condition: E.Expression) -> List[tuple]:
    """(column, op, storage-value) conjuncts a parquet footer can rule
    on: plain attribute vs foldable literal comparisons, IsNull and
    IsNotNull (ParquetFilters.createFilter's pushable subset)."""
    out: List[tuple] = []
    for conj in split_conjuncts(condition):
        if isinstance(conj, E.IsNotNull) and isinstance(
                conj.child, E.AttributeReference):
            out.append((conj.child.name, "notnull", None))
            continue
        if isinstance(conj, E.IsNull) and isinstance(
                conj.child, E.AttributeReference):
            out.append((conj.child.name, "isnull", None))
            continue
        op = _PUSH_OPS.get(type(conj))
        if op is None:
            continue
        left, right = conj.left, conj.right
        if isinstance(left, E.AttributeReference):
            v = _fold_literal(right)
            if v is not None:
                out.append((left.name, op, v))
        elif isinstance(right, E.AttributeReference):
            v = _fold_literal(left)
            if v is not None:
                out.append((right.name, _PUSH_SWAP[op], v))
    return out
