"""Logical plans (the Catalyst layer Spark provides in the reference).

Name resolution happens eagerly in the DataFrame API (resolve() below)
rather than in a separate analyzer phase; after construction every
expression in a plan refers to AttributeReferences with unique ids.
"""

from __future__ import annotations

from typing import Any, List, Optional, Sequence

from spark_rapids_tpu.sql import types as T
from spark_rapids_tpu.sql.expressions import (
    AggregateExpression, Alias, AttributeReference, Cast, Expression,
    Literal, SortOrder, UnresolvedAttribute, named_output)


class LogicalPlan:
    children: List["LogicalPlan"]

    @property
    def output(self) -> List[AttributeReference]:
        raise NotImplementedError

    @property
    def schema(self) -> T.StructType:
        return T.StructType([
            T.StructField(a.name, a.data_type, a.nullable)
            for a in self.output])

    def __repr__(self) -> str:
        return self._tree_string(0)

    def _tree_string(self, indent: int) -> str:
        s = " " * indent + self.simple_string()
        for c in self.children:
            s += "\n" + c._tree_string(indent + 2)
        return s

    def simple_string(self) -> str:
        return type(self).__name__


def resolve(expr: Expression, inputs: Sequence[AttributeReference],
            case_sensitive: bool = False) -> Expression:
    """Replace UnresolvedAttribute with matching AttributeReference."""

    def norm(s: Optional[str]) -> Optional[str]:
        return s if case_sensitive or s is None else s.lower()

    def base_matches(parts: List[str], k: int) -> List[AttributeReference]:
        """Attributes matching the first k name parts: as a bare (dotted)
        column name, or as qualifier + column (Catalyst's order)."""
        nm = norm(".".join(parts[:k]))
        ms = [a for a in inputs if norm(a.name) == nm]
        if not ms and k >= 2:
            qual, col = norm(parts[0]), norm(".".join(parts[1:k]))
            ms = [a for a in inputs
                  if norm(a.name) == col and norm(a.qualifier) == qual]
        return ms

    def rule(e: Expression) -> Optional[Expression]:
        if isinstance(e, UnresolvedAttribute):
            parts = e.name.split(".")
            # longest base first: `a.s.y` prefers column a.s (or
            # qualifier a + column s) before treating y as a field
            for k in range(len(parts), 0, -1):
                ms = base_matches(parts, k)
                if len(ms) > 1:
                    raise KeyError(f"ambiguous column '{e.name}'")
                if not ms:
                    continue
                out: Expression = ms[0]
                ok = True
                for p in parts[k:]:  # remaining parts walk struct fields
                    dt = out.data_type
                    fld = next(
                        (f.name for f in dt.fields
                         if norm(f.name) == norm(p)), None) \
                        if isinstance(dt, T.StructType) else None
                    if fld is None:
                        ok = False
                        break
                    from spark_rapids_tpu.sql.expressions import \
                        GetStructField
                    out = GetStructField(out, name=fld)
                if ok:
                    return out
            raise KeyError(
                f"cannot resolve '{e.name}' among "
                f"{[a.name for a in inputs]}")
        return None

    return expr.transform(rule)


class MapInPandas(LogicalPlan):
    """DataFrame.mapInPandas(func, schema) (sql/core MapInPandas)."""

    def __init__(self, fn, schema: T.StructType, child: LogicalPlan):
        self.children = [child]
        self.fn = fn
        self._schema = schema
        self._output = [AttributeReference(f.name, f.data_type, f.nullable)
                        for f in schema.fields]

    @property
    def child(self) -> LogicalPlan:
        return self.children[0]

    @property
    def output(self) -> List[AttributeReference]:
        return self._output

    def simple_string(self) -> str:
        return f"MapInPandas {getattr(self.fn, '__name__', '<fn>')}"


class SubqueryAlias(LogicalPlan):
    """Relation alias (Catalyst SubqueryAlias): same expr_ids, outputs
    re-qualified so ``alias.col`` references resolve. Physically
    transparent — the planner plans straight through it."""

    def __init__(self, alias: str, child: LogicalPlan):
        self.children = [child]
        self.alias = alias

    @property
    def child(self) -> LogicalPlan:
        return self.children[0]

    @property
    def output(self) -> List[AttributeReference]:
        return [a.with_qualifier(self.alias) for a in self.child.output]

    def simple_string(self) -> str:
        return f"SubqueryAlias {self.alias}"


class LocalRelation(LogicalPlan):
    """In-memory data; plays LocalTableScan / the test-side gen_df source."""

    def __init__(self, schema: T.StructType, batches: List,
                 num_partitions: int = 1):
        from spark_rapids_tpu.columnar.host import HostBatch
        self.children = []
        self._output = [AttributeReference(f.name, f.data_type, f.nullable)
                        for f in schema.fields]
        self._schema = schema
        self.batches: List[HostBatch] = batches
        self.num_partitions = num_partitions

    @property
    def output(self) -> List[AttributeReference]:
        return self._output

    def simple_string(self) -> str:
        n = sum(b.num_rows for b in self.batches)
        return f"LocalRelation [{n} rows, {len(self._output)} cols]"


class FileScan(LogicalPlan):
    """Parquet/CSV/ORC scan (GpuFileSourceScanExec's logical ancestor)."""

    def __init__(self, fmt: str, paths: List[str], schema: T.StructType,
                 options: Optional[dict] = None):
        self.children = []
        self.fmt = fmt
        self.paths = paths
        self._schema = schema
        self.options = options or {}
        self._output = [AttributeReference(f.name, f.data_type, f.nullable)
                        for f in schema.fields]

    @property
    def output(self) -> List[AttributeReference]:
        return self._output

    def simple_string(self) -> str:
        return f"FileScan {self.fmt} {self.paths}"


class Range(LogicalPlan):
    """spark.range(); GpuRangeExec analogue upstream."""

    def __init__(self, start: int, end: int, step: int = 1,
                 num_partitions: int = 1):
        self.children = []
        self.start, self.end, self.step = start, end, step
        self.num_partitions = num_partitions
        self._output = [AttributeReference("id", T.LongT, nullable=False)]

    @property
    def output(self) -> List[AttributeReference]:
        return self._output


class Project(LogicalPlan):
    def __init__(self, project_list: List[Expression], child: LogicalPlan):
        self.children = [child]
        self.project_list = project_list

    @property
    def child(self) -> LogicalPlan:
        return self.children[0]

    @property
    def output(self) -> List[AttributeReference]:
        return [named_output(e) for e in self.project_list]

    def simple_string(self) -> str:
        return f"Project {self.project_list}"


class Filter(LogicalPlan):
    def __init__(self, condition: Expression, child: LogicalPlan):
        self.children = [child]
        self.condition = condition

    @property
    def child(self) -> LogicalPlan:
        return self.children[0]

    @property
    def output(self) -> List[AttributeReference]:
        return self.child.output

    def simple_string(self) -> str:
        return f"Filter {self.condition!r}"


class Aggregate(LogicalPlan):
    """grouping expressions + result expressions (group attrs and
    Alias(AggregateExpression) items)."""

    def __init__(self, grouping: List[Expression],
                 aggregates: List[Expression], child: LogicalPlan):
        self.children = [child]
        self.grouping = grouping
        self.aggregates = aggregates

    @property
    def child(self) -> LogicalPlan:
        return self.children[0]

    @property
    def output(self) -> List[AttributeReference]:
        return [named_output(e) for e in self.aggregates]

    def simple_string(self) -> str:
        return f"Aggregate {self.grouping} {self.aggregates}"


class Join(LogicalPlan):
    def __init__(self, left: LogicalPlan, right: LogicalPlan,
                 join_type: str, condition: Optional[Expression]):
        self.children = [left, right]
        self.join_type = join_type  # inner/left/right/full/leftsemi/leftanti/cross
        self.condition = condition

    @property
    def left(self) -> LogicalPlan:
        return self.children[0]

    @property
    def right(self) -> LogicalPlan:
        return self.children[1]

    @property
    def output(self) -> List[AttributeReference]:
        jt = self.join_type
        if jt in ("leftsemi", "leftanti"):
            return self.left.output
        left_out = list(self.left.output)
        right_out = list(self.right.output)
        if jt in ("left", "full", "leftouter", "fullouter"):
            right_out = [AttributeReference(a.name, a.data_type, True,
                                            a.expr_id, a.qualifier)
                         for a in right_out]
        if jt in ("right", "full", "rightouter", "fullouter"):
            left_out = [AttributeReference(a.name, a.data_type, True,
                                           a.expr_id, a.qualifier)
                        for a in left_out]
        return left_out + right_out

    def simple_string(self) -> str:
        return f"Join {self.join_type} {self.condition!r}"


class Sort(LogicalPlan):
    def __init__(self, order: List[SortOrder], is_global: bool,
                 child: LogicalPlan):
        self.children = [child]
        self.order = order
        self.is_global = is_global

    @property
    def child(self) -> LogicalPlan:
        return self.children[0]

    @property
    def output(self) -> List[AttributeReference]:
        return self.child.output

    def simple_string(self) -> str:
        return f"Sort {self.order} global={self.is_global}"


class Limit(LogicalPlan):
    def __init__(self, n: int, child: LogicalPlan):
        self.children = [child]
        self.n = n

    @property
    def child(self) -> LogicalPlan:
        return self.children[0]

    @property
    def output(self) -> List[AttributeReference]:
        return self.child.output


class Union(LogicalPlan):
    def __init__(self, plans: List[LogicalPlan]):
        self.children = list(plans)
        first = plans[0].output
        self._output = [AttributeReference(a.name, a.data_type,
                                           any(p.output[i].nullable
                                               for p in plans))
                        for i, a in enumerate(first)]

    @property
    def output(self) -> List[AttributeReference]:
        return self._output


class Repartition(LogicalPlan):
    def __init__(self, num_partitions: int, shuffle: bool,
                 child: LogicalPlan, by: Optional[List[Expression]] = None):
        self.children = [child]
        self.num_partitions = num_partitions
        self.shuffle = shuffle
        self.by = by  # None = round robin

    @property
    def child(self) -> LogicalPlan:
        return self.children[0]

    @property
    def output(self) -> List[AttributeReference]:
        return self.child.output


class Generate(LogicalPlan):
    """Generator application: child rows x generator output
    (Spark Generate / GpuGenerateExec.scala:440 logical twin). Output =
    child output + the generator's attributes (pre-allocated so
    downstream references bind by expr_id)."""

    def __init__(self, generator: Expression,
                 gen_output: List[AttributeReference], child: LogicalPlan):
        self.children = [child]
        self.generator = generator
        self.gen_output = gen_output

    @property
    def child(self) -> LogicalPlan:
        return self.children[0]

    @property
    def output(self) -> List[AttributeReference]:
        return list(self.child.output) + list(self.gen_output)

    def simple_string(self) -> str:
        return f"Generate {self.generator!r}"


class Expand(LogicalPlan):
    """Grouping-sets expansion (GpuExpandExec's logical twin)."""

    def __init__(self, projections: List[List[Expression]],
                 output: List[AttributeReference], child: LogicalPlan):
        self.children = [child]
        self.projections = projections
        self._output = output

    @property
    def child(self) -> LogicalPlan:
        return self.children[0]

    @property
    def output(self) -> List[AttributeReference]:
        return self._output


class Window(LogicalPlan):
    def __init__(self, window_exprs: List[Expression],
                 partition_spec: List[Expression],
                 order_spec: List[SortOrder], child: LogicalPlan):
        self.children = [child]
        self.window_exprs = window_exprs  # Alias(WindowExpression) items
        self.partition_spec = partition_spec
        self.order_spec = order_spec

    @property
    def child(self) -> LogicalPlan:
        return self.children[0]

    @property
    def output(self) -> List[AttributeReference]:
        return self.child.output + [named_output(e)
                                    for e in self.window_exprs]
