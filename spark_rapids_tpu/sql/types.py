"""Spark SQL data types.

Mirrors org.apache.spark.sql.types, which the reference's TypeSig algebra
(sql-plugin TypeChecks.scala:171) enumerates: BOOLEAN, BYTE, SHORT, INT,
LONG, FLOAT, DOUBLE, DATE, TIMESTAMP, STRING, DECIMAL, NULL, BINARY,
CALENDAR, ARRAY, MAP, STRUCT, UDT.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional

import numpy as np


class DataType:
    """Base of the SQL type lattice."""

    @property
    def simple_string(self) -> str:
        return type(self).__name__.replace("Type", "").lower()

    def __repr__(self) -> str:
        return self.simple_string

    def __eq__(self, other) -> bool:
        return type(self) is type(other)

    def __hash__(self) -> int:
        return hash(type(self).__name__)

    @property
    def default_size(self) -> int:
        return 8


class NumericType(DataType):
    pass


class IntegralType(NumericType):
    pass


class FractionalType(NumericType):
    pass


class AtomicType(DataType):
    pass


class NullType(DataType):
    default_size = 1


class BooleanType(AtomicType):
    np_dtype = np.bool_
    default_size = 1


class ByteType(IntegralType):
    np_dtype = np.int8
    default_size = 1
    simple_string = "tinyint"


class ShortType(IntegralType):
    np_dtype = np.int16
    default_size = 2
    simple_string = "smallint"


class IntegerType(IntegralType):
    np_dtype = np.int32
    default_size = 4
    simple_string = "int"


class LongType(IntegralType):
    np_dtype = np.int64
    default_size = 8
    simple_string = "bigint"


class FloatType(FractionalType):
    np_dtype = np.float32
    default_size = 4


class DoubleType(FractionalType):
    np_dtype = np.float64
    default_size = 8


class StringType(AtomicType):
    default_size = 20


class BinaryType(AtomicType):
    default_size = 100


class DateType(AtomicType):
    """Days since epoch, int32 (Spark internal representation)."""
    np_dtype = np.int32
    default_size = 4


class TimestampType(AtomicType):
    """Microseconds since epoch UTC, int64 (Spark internal representation)."""
    np_dtype = np.int64
    default_size = 8


class CalendarIntervalType(DataType):
    default_size = 16
    simple_string = "interval"


@dataclass(frozen=True)
class DecimalType(FractionalType):
    """Fixed decimal(precision, scale); unscaled int64 storage up to
    precision 18 (DECIMAL64), two-limb beyond (the reference gates most ops
    at DECIMAL64, TypeChecks.scala gpuNumeric)."""

    precision: int = 10
    scale: int = 0

    MAX_PRECISION = 38
    MAX_LONG_DIGITS = 18

    @property
    def simple_string(self) -> str:
        return f"decimal({self.precision},{self.scale})"

    @property
    def default_size(self) -> int:
        return 8 if self.precision <= 18 else 16

    def __eq__(self, other) -> bool:
        return (isinstance(other, DecimalType)
                and other.precision == self.precision
                and other.scale == self.scale)

    def __hash__(self) -> int:
        return hash(("decimal", self.precision, self.scale))


@dataclass(frozen=True)
class ArrayType(DataType):
    element_type: DataType = field(default_factory=NullType)
    contains_null: bool = True

    @property
    def simple_string(self) -> str:
        return f"array<{self.element_type.simple_string}>"

    def __eq__(self, other) -> bool:
        return (isinstance(other, ArrayType)
                and other.element_type == self.element_type)

    def __hash__(self) -> int:
        return hash(("array", self.element_type))


@dataclass(frozen=True)
class MapType(DataType):
    key_type: DataType = field(default_factory=NullType)
    value_type: DataType = field(default_factory=NullType)
    value_contains_null: bool = True

    @property
    def simple_string(self) -> str:
        return (f"map<{self.key_type.simple_string},"
                f"{self.value_type.simple_string}>")

    def __eq__(self, other) -> bool:
        return (isinstance(other, MapType) and other.key_type == self.key_type
                and other.value_type == self.value_type)

    def __hash__(self) -> int:
        return hash(("map", self.key_type, self.value_type))


@dataclass(frozen=True)
class StructField:
    name: str
    data_type: DataType
    nullable: bool = True


@dataclass(frozen=True)
class StructType(DataType):
    fields: tuple = ()

    def __init__(self, fields=()):
        object.__setattr__(self, "fields", tuple(fields))

    @property
    def names(self) -> List[str]:
        return [f.name for f in self.fields]

    @property
    def simple_string(self) -> str:
        inner = ",".join(
            f"{f.name}:{f.data_type.simple_string}" for f in self.fields)
        return f"struct<{inner}>"

    def add(self, name: str, dt: DataType, nullable: bool = True
            ) -> "StructType":
        return StructType(self.fields + (StructField(name, dt, nullable),))

    def field_index(self, name: str) -> int:
        for i, f in enumerate(self.fields):
            if f.name == name:
                return i
        raise KeyError(name)

    def __eq__(self, other) -> bool:
        return isinstance(other, StructType) and other.fields == self.fields

    def __hash__(self) -> int:
        return hash(("struct", self.fields))

    def __len__(self) -> int:
        return len(self.fields)

    def __iter__(self):
        return iter(self.fields)


# Singletons, Spark style
NullT = NullType()
BooleanT = BooleanType()
ByteT = ByteType()
ShortT = ShortType()
IntegerT = IntegerType()
LongT = LongType()
FloatT = FloatType()
DoubleT = DoubleType()
StringT = StringType()
BinaryT = BinaryType()
DateT = DateType()
TimestampT = TimestampType()


def require_x64() -> None:
    """Row counters and SQL LONG/DOUBLE need 64-bit jax types. The
    package __init__ enables x64 before any array exists, but an
    embedder that imported jax first (or flipped the flag) would make
    ``jnp.int64(v)`` silently produce int32 — row counts would wrap at
    2^31 rows with no error. Fail loudly instead."""
    import jax
    if not jax.config.jax_enable_x64:
        raise RuntimeError(
            "jax_enable_x64 is disabled: spark-rapids-tpu requires "
            "64-bit jax types (int64 row counters, SQL bigint/double). "
            "Import spark_rapids_tpu before creating jax arrays, or "
            "set JAX_ENABLE_X64=1.")


def device_long(v) -> "object":
    """int64 DEVICE scalar (row counters, batch offsets, partition
    ids). All device row-counter scalars must come through here: a bare
    ``jnp.int64(v)`` downcasts to int32 without x64 — silently."""
    require_x64()
    import jax.numpy as jnp
    a = jnp.asarray(v, dtype=jnp.int64)
    assert a.dtype == jnp.int64, a.dtype
    return a


def is_integral(dt: DataType) -> bool:
    return isinstance(dt, IntegralType)


def is_numeric(dt: DataType) -> bool:
    return isinstance(dt, NumericType)


def is_floating(dt: DataType) -> bool:
    return isinstance(dt, (FloatType, DoubleType))


def storage_zeros(dt: DataType, n: int) -> np.ndarray:
    """Zeroed host buffer in the engine's storage layout for ``dt``.
    DECIMAL128 is the one type whose storage is not a flat numpy dtype:
    its unscaled value lives in an (n, 2) int64 [hi, lo] limb pair (the
    layout transfer.py ships and ops/int128.py computes over), so
    buffer allocation must go through here, not numpy_dtype."""
    if is_limb_decimal(dt):
        return np.zeros((n, 2), dtype=np.int64)
    return np.zeros(n, dtype=numpy_dtype(dt))


def numpy_dtype(dt: DataType) -> np.dtype:
    """numpy storage dtype for the fixed-width physical representation."""
    if isinstance(dt, DecimalType):
        if dt.precision <= DecimalType.MAX_LONG_DIGITS:
            return np.dtype(np.int64)
        raise TypeError(f"decimal > 18 digits not fixed-width-64: {dt}")
    if isinstance(dt, (StringType, BinaryType)):
        return np.dtype(object)
    if isinstance(dt, (ArrayType, MapType, StructType)):
        # host representation: object array of python lists/dicts/tuples
        return np.dtype(object)
    if isinstance(dt, NullType):
        return np.dtype(np.int8)
    nd = getattr(dt, "np_dtype", None)
    if nd is None:
        raise TypeError(f"no numpy dtype for {dt}")
    return np.dtype(nd)


# Numeric widening lattice for binary op type coercion
# (Spark TypeCoercion.findTightestCommonType).
_NUMERIC_ORDER = [ByteType(), ShortType(), IntegerType(), LongType(),
                  FloatType(), DoubleType()]


def is_limb_decimal(dt: DataType) -> bool:
    """True for DECIMAL128 storage: unscaled value kept as two int64
    limbs (precision beyond DecimalType.MAX_LONG_DIGITS)."""
    return (isinstance(dt, DecimalType)
            and dt.precision > DecimalType.MAX_LONG_DIGITS)


def decimal_for_integral(dt: DataType) -> DecimalType:
    """Spark DecimalType.forType: the exact decimal an integral fits."""
    if isinstance(dt, ByteType):
        return DecimalType(3, 0)
    if isinstance(dt, ShortType):
        return DecimalType(5, 0)
    if isinstance(dt, IntegerType):
        return DecimalType(10, 0)
    return DecimalType(20, 0)  # long / boolean-as-int never reaches here


def adjust_precision_scale(p: int, s: int) -> DecimalType:
    """Spark DecimalPrecision.adjustPrecisionScale with
    spark.sql.decimalOperations.allowPrecisionLoss=true (the default):
    cap at 38 digits, sacrificing scale but keeping at least 6
    fractional digits when possible."""
    if p <= DecimalType.MAX_PRECISION:
        return DecimalType(max(p, 1), s)
    int_digits = p - s
    min_scale = min(s, 6)
    adjusted = max(DecimalType.MAX_PRECISION - int_digits, min_scale)
    return DecimalType(DecimalType.MAX_PRECISION, adjusted)


def decimal_binary_result(op: str, lt: DecimalType, rt: DecimalType
                          ) -> DecimalType:
    """Spark DecimalPrecision result types for +,-,*,/ (arithmetic.scala
    / DecimalPrecision.scala; the reference re-checks these in
    GpuDecimalMultiply etc., decimalExpressions.scala)."""
    p1, s1, p2, s2 = lt.precision, lt.scale, rt.precision, rt.scale
    if op in ("+", "-"):
        s = max(s1, s2)
        p = max(p1 - s1, p2 - s2) + s + 1
    elif op == "*":
        p = p1 + p2 + 1
        s = s1 + s2
    elif op == "/":
        s = max(6, s1 + p2 + 1)
        p = p1 - s1 + s2 + s
    else:
        raise ValueError(op)
    return adjust_precision_scale(p, s)


def wider_decimal(a: DecimalType, b: DecimalType) -> DecimalType:
    """Loss-free common type for comparisons/set ops (Spark
    DecimalPrecision.widerDecimalType), 38-capped."""
    s = max(a.scale, b.scale)
    rng = max(a.precision - a.scale, b.precision - b.scale)
    return DecimalType(min(rng + s, DecimalType.MAX_PRECISION), s)


def tightest_common_type(a: DataType, b: DataType) -> Optional[DataType]:
    if a == b:
        return a
    if isinstance(a, NullType):
        return b
    if isinstance(b, NullType):
        return a
    if a in _NUMERIC_ORDER and b in _NUMERIC_ORDER:
        return _NUMERIC_ORDER[max(_NUMERIC_ORDER.index(a),
                                  _NUMERIC_ORDER.index(b))]
    if isinstance(a, DecimalType) or isinstance(b, DecimalType):
        # fractional side wins entirely (Spark: decimal + float/double
        # -> double); integral side is lifted to its exact decimal and
        # widened loss-free
        if isinstance(a, (FloatType, DoubleType)) or \
                isinstance(b, (FloatType, DoubleType)):
            return DoubleT
        if isinstance(a, DecimalType) and isinstance(b, DecimalType):
            return wider_decimal(a, b)
        other = b if isinstance(a, DecimalType) else a
        dec = a if isinstance(a, DecimalType) else b
        if other in _NUMERIC_ORDER[:4]:
            return wider_decimal(dec, decimal_for_integral(other))
    return None
