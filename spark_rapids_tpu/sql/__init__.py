"""The host SQL engine the plugin accelerates.

In the reference, Apache Spark provides this layer unmodified (SURVEY.md L7+
'Spark SQL (unmodified)'); here it is part of the framework: Catalyst-like
expressions, logical plans, a DataFrame API, and CPU physical operators that
implement Spark semantics and serve as the bit-identical baseline and the
per-op fallback target.

TpuSparkSession is exposed lazily to keep the package import-order free
(columnar <-> sql would otherwise cycle through session.py).
"""


def __getattr__(name):
    if name == "TpuSparkSession":
        from spark_rapids_tpu.sql.session import TpuSparkSession
        return TpuSparkSession
    raise AttributeError(name)
