"""The host SQL engine the plugin accelerates.

In the reference, Apache Spark provides this layer unmodified (SURVEY.md L7+
'Spark SQL (unmodified)'); here it is part of the framework: Catalyst-like
expressions, logical plans, a DataFrame API, and CPU physical operators that
implement Spark semantics and serve as the bit-identical baseline and the
per-op fallback target.
"""

from spark_rapids_tpu.sql.session import TpuSparkSession  # noqa: F401
