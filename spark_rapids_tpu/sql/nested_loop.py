"""Broadcast nested-loop join (GpuBroadcastNestedLoopJoinExecBase twin,
590 LoC in the reference; SURVEY.md 2.2 Joins row). CPU baseline
implementation; device version arrives with the join kernel family.
"""

from __future__ import annotations

from typing import Iterator, List, Optional

import numpy as np

from spark_rapids_tpu.columnar.host import HostBatch
from spark_rapids_tpu.sql import expressions as E
from spark_rapids_tpu.sql import physical as P
from spark_rapids_tpu.sql import types as T


class CpuBroadcastNestedLoopJoinExec(P.PhysicalPlan):
    def __init__(self, join_type: str, condition: Optional[E.Expression],
                 left: P.PhysicalPlan, right: P.PhysicalPlan,
                 output: List[E.AttributeReference]):
        self.children = [left, right]
        self.join_type = join_type
        self.condition = condition
        self._output = output

    @property
    def output(self):
        return self._output

    def partitions(self) -> List[P.PartitionThunk]:
        left, right = self.children
        rschema = T.StructType([
            T.StructField(a.name, a.data_type, a.nullable)
            for a in right.output])
        rb: List[HostBatch] = []
        for t in right.partitions():
            rb.extend(b for b in t() if b.num_rows)
        rwhole = HostBatch.concat(rb) if rb else HostBatch.empty(rschema)

        cond = None
        if self.condition is not None:
            cond = E.bind_references(
                self.condition, list(left.output) + list(right.output))

        def make(lt: P.PartitionThunk) -> P.PartitionThunk:
            def run() -> Iterator[HostBatch]:
                for b in lt():
                    if not b.num_rows:
                        continue
                    nl, nr = b.num_rows, rwhole.num_rows
                    li = np.repeat(np.arange(nl, dtype=np.int64), nr)
                    ri = np.tile(np.arange(nr, dtype=np.int64), nl)
                    pairs = P._gather_pair(b, rwhole, li, ri, self.schema)
                    if cond is not None and len(li):
                        pr = cond.eval(pairs)
                        keep = pr.validity & pr.data.astype(bool)
                        pairs = pairs.take(np.nonzero(keep)[0])
                    if self.join_type in ("inner", "cross"):
                        yield pairs
                    else:
                        raise NotImplementedError(
                            f"nested loop {self.join_type}")
            return run
        return [make(t) for t in left.partitions()]
