"""DataFrame API (pyspark.sql.DataFrame shape) over logical plans."""

from __future__ import annotations

from typing import Any, List, Optional, Sequence, Union

import numpy as np

from spark_rapids_tpu.columnar.host import HostBatch
from spark_rapids_tpu.sql import expressions as E
from spark_rapids_tpu.sql import logical as L
from spark_rapids_tpu.sql import types as T
from spark_rapids_tpu.sql.functions import Column, _to_expr


class Row(tuple):
    """Lightweight named row."""

    def __new__(cls, values, names):
        r = super().__new__(cls, values)
        r._names = list(names)
        return r

    def __getattr__(self, name):
        try:
            return self[self._names.index(name)]
        except ValueError:
            raise AttributeError(name)

    def asDict(self):
        return dict(zip(self._names, self))

    def __repr__(self):
        inner = ", ".join(f"{n}={v!r}" for n, v in zip(self._names, self))
        return f"Row({inner})"


class DataFrame:
    def __init__(self, plan: L.LogicalPlan, session):
        self.plan = plan
        self.session = session

    # -- schema ------------------------------------------------------------
    @property
    def schema(self) -> T.StructType:
        return self.plan.schema

    @property
    def columns(self) -> List[str]:
        return [a.name for a in self.plan.output]

    def _resolve(self, c: Union[Column, str, E.Expression]) -> E.Expression:
        if isinstance(c, str):
            if c == "*":
                raise ValueError("* only valid inside select()")
            expr: E.Expression = E.UnresolvedAttribute(c)
        else:
            expr = _to_expr(c)
        case_sensitive = self.session.conf_obj.get_key(
            "spark.sql.caseSensitive", False)
        resolved = L.resolve(expr, self.plan.output,
                             bool(case_sensitive))
        return _coerce_resolved(resolved)

    # -- transformations ---------------------------------------------------
    def alias(self, name: str) -> "DataFrame":
        """pyspark DataFrame.alias: re-qualify this relation's columns so
        ``name.col`` references resolve (SubqueryAlias node)."""
        return DataFrame(L.SubqueryAlias(name, self.plan), self.session)

    def mapInPandas(self, func, schema) -> "DataFrame":
        """pyspark DataFrame.mapInPandas: ``func(iter_of_pdf) ->
        iter_of_pdf`` runs in the python worker pool over Arrow IPC
        (GpuMapInPandasExec role)."""
        if isinstance(schema, str):
            from spark_rapids_tpu.sql.session import _parse_ddl_schema
            schema = _parse_ddl_schema(schema)
        return DataFrame(L.MapInPandas(func, schema, self.plan),
                         self.session)

    def select(self, *cols) -> "DataFrame":
        items: List[E.Expression] = []
        for c in cols:
            if isinstance(c, str) and c == "*":
                items.extend(self.plan.output)
                continue
            e = self._resolve(c)
            if not isinstance(e, (E.AttributeReference, E.Alias)) and \
                    not getattr(e, "is_generator", False):
                e = E.Alias(e, _auto_name(e))
            items.append(e)
        return DataFrame(self._project_plan(items), self.session)

    def _project_plan(self, items: List[E.Expression]) -> L.LogicalPlan:
        """Project, extracting window expressions into L.Window nodes
        grouped by (partition, order) spec — the analyzer's
        ExtractWindowExpressions role — and generators (explode/
        posexplode) into L.Generate (ExtractGenerator role)."""
        gens = [e for e in items
                if e.collect(lambda x: getattr(x, "is_generator", False))]
        if gens:
            assert len(gens) == 1, \
                "only one generator per select clause is allowed"
            item = gens[0]
            gen = (item.child if isinstance(item, E.Alias) else item)
            assert getattr(gen, "is_generator", False), \
                "generators must be top-level select items"
            col_name = item.name if isinstance(item, E.Alias) else "col"
            gen_out = gen.generator_output(col_name)
            child = L.Generate(gen, gen_out, self.plan)
            new_items: List[E.Expression] = []
            for e in items:
                if e is item:
                    new_items.extend(gen_out)
                else:
                    new_items.append(e)
            return L.Project(new_items, child)
        if not any(e.collect(lambda x: isinstance(x, E.WindowExpression))
                   for e in items):
            return L.Project(items, self.plan)
        groups: dict = {}
        counter = [0]

        def extract(item: E.Expression) -> E.Expression:
            def rule(x):
                if isinstance(x, E.WindowExpression):
                    name = (item.name if isinstance(item, E.Alias)
                            and item.child is x
                            else f"_we{counter[0]}")
                    counter[0] += 1
                    alias = E.Alias(x, name)
                    key = (tuple(map(repr, x.partition_spec)),
                           tuple(map(repr, x.order_spec)))
                    groups.setdefault(
                        key, (x.partition_spec, x.order_spec, []))[2] \
                        .append(alias)
                    return alias.to_attribute()
                return None
            return item.transform(rule)

        new_items = [extract(e) for e in items]
        child = self.plan
        for part, order, aliases in groups.values():
            child = L.Window(aliases, list(part), list(order), child)
        return L.Project(new_items, child)

    def selectExpr(self, *exprs: str) -> "DataFrame":
        from spark_rapids_tpu.sql.parser import parse_expression
        cols = [parse_expression(s) for s in exprs]
        return self.select(*[Column(c) for c in cols])

    def withColumn(self, name: str, col: Column) -> "DataFrame":
        e = self._resolve(col)
        items: List[E.Expression] = []
        replaced = False
        for a in self.plan.output:
            if a.name == name:
                items.append(E.Alias(e, name))
                replaced = True
            else:
                items.append(a)
        if not replaced:
            items.append(E.Alias(e, name))
        return DataFrame(self._project_plan(items), self.session)

    def withColumnRenamed(self, old: str, new: str) -> "DataFrame":
        items = [a.renamed(new) if a.name == old else a
                 for a in self.plan.output]
        return DataFrame(L.Project(items, self.plan), self.session)

    def drop(self, *names: str) -> "DataFrame":
        keep = [a for a in self.plan.output if a.name not in names]
        return DataFrame(L.Project(keep, self.plan), self.session)

    def filter(self, condition: Union[Column, str]) -> "DataFrame":
        if isinstance(condition, str):
            from spark_rapids_tpu.sql.parser import parse_expression
            condition = Column(parse_expression(condition))
        cond = self._resolve(condition)
        return DataFrame(L.Filter(cond, self.plan), self.session)

    where = filter

    def groupBy(self, *cols) -> "GroupedData":
        grouping = [self._resolve(c) for c in cols]
        return GroupedData(self, grouping)

    def rollup(self, *cols) -> "GroupedData":
        """Hierarchical grouping sets: (a,b,c), (a,b), (a), () — the
        Aggregate-over-Expand shape Spark's analyzer produces."""
        grouping = [self._resolve(c) for c in cols]
        return GroupedData(self, grouping, sets_mode="rollup")

    def cube(self, *cols) -> "GroupedData":
        """All 2^n grouping-set combinations."""
        grouping = [self._resolve(c) for c in cols]
        return GroupedData(self, grouping, sets_mode="cube")

    def agg(self, *cols) -> "DataFrame":
        return self.groupBy().agg(*cols)

    def join(self, other: "DataFrame", on=None, how: str = "inner"
             ) -> "DataFrame":
        how = {"left_outer": "leftouter", "right_outer": "rightouter",
               "full_outer": "fullouter", "semi": "leftsemi",
               "anti": "leftanti", "left_semi": "leftsemi",
               "left_anti": "leftanti", "outer": "fullouter"}.get(how, how)
        # Self-join disambiguation (Spark's dedupRight): re-alias the right
        # side with fresh expr_ids when the two sides share attribute ids.
        left_ids = {a.expr_id for a in self.plan.output}
        if any(a.expr_id in left_ids for a in other.plan.output):
            # fresh expr_ids, same names AND same qualifiers — `b.col`
            # still resolves after a self-join re-alias, however deep
            # the alias sits under filters/projections
            other = DataFrame(
                L.Project([E.Alias(a, a.name, qualifier=a.qualifier)
                           for a in other.plan.output], other.plan),
                other.session)
        cond: Optional[E.Expression] = None
        using: List[str] = []
        if on is not None:
            if isinstance(on, str):
                using = [on]
            elif isinstance(on, (list, tuple)) and on and isinstance(
                    on[0], str):
                using = list(on)
            elif isinstance(on, Column):
                combined = list(self.plan.output) + list(other.plan.output)
                cond = L.resolve(on.expr, combined)
                cond = _coerce_resolved(cond)
        if using:
            conds = []
            for name in using:
                lc = L.resolve(E.UnresolvedAttribute(name),
                               self.plan.output)
                rc = L.resolve(E.UnresolvedAttribute(name),
                               other.plan.output)
                conds.append(E.EqualTo(lc, rc))
            for c in conds:
                cond = c if cond is None else E.And(cond, c)
        joined = L.Join(self.plan, other.plan, how, cond)
        df = DataFrame(joined, self.session)
        if using and how not in ("leftsemi", "leftanti"):
            # USING join: single key column, drop duplicate right-side keys
            keep: List[E.Expression] = []
            right_ids = set()
            for name in using:
                r = L.resolve(E.UnresolvedAttribute(name),
                              other.plan.output)
                right_ids.add(r.expr_id)
            for a in joined.output:
                if a.expr_id not in right_ids:
                    keep.append(a)
            df = DataFrame(L.Project(keep, joined), self.session)
        return df

    def crossJoin(self, other: "DataFrame") -> "DataFrame":
        left_ids = {a.expr_id for a in self.plan.output}
        if any(a.expr_id in left_ids for a in other.plan.output):
            # fresh expr_ids, same names AND same qualifiers — `b.col`
            # still resolves after a self-join re-alias, however deep
            # the alias sits under filters/projections
            other = DataFrame(
                L.Project([E.Alias(a, a.name, qualifier=a.qualifier)
                           for a in other.plan.output], other.plan),
                other.session)
        return DataFrame(L.Join(self.plan, other.plan, "cross", None),
                         self.session)

    def union(self, other: "DataFrame") -> "DataFrame":
        return DataFrame(L.Union([self.plan, other.plan]), self.session)

    unionAll = union

    def distinct(self) -> "DataFrame":
        return DataFrame(
            L.Aggregate(list(self.plan.output), list(self.plan.output),
                        self.plan), self.session)

    def dropDuplicates(self, subset: Optional[List[str]] = None
                       ) -> "DataFrame":
        if subset is None:
            return self.distinct()
        keys = [self._resolve(s) for s in subset]
        aggs: List[E.Expression] = []
        key_ids = {k.expr_id for k in keys
                   if isinstance(k, E.AttributeReference)}
        for a in self.plan.output:
            if a.expr_id in key_ids:
                aggs.append(a)
            else:
                aggs.append(E.Alias(
                    E.AggregateExpression(E.First(a)), a.name))
        return DataFrame(L.Aggregate(keys, aggs, self.plan), self.session)

    def orderBy(self, *cols) -> "DataFrame":
        order = self._sort_orders(cols)
        return DataFrame(L.Sort(order, True, self.plan), self.session)

    sort = orderBy

    def sortWithinPartitions(self, *cols) -> "DataFrame":
        order = self._sort_orders(cols)
        return DataFrame(L.Sort(order, False, self.plan), self.session)

    def _sort_orders(self, cols) -> List[E.SortOrder]:
        order: List[E.SortOrder] = []
        for c in cols:
            e = self._resolve(c)
            if isinstance(e, E.SortOrder):
                order.append(e)
            else:
                order.append(E.SortOrder(e, ascending=True))
        return order

    def limit(self, n: int) -> "DataFrame":
        return DataFrame(L.Limit(n, self.plan), self.session)

    def repartition(self, num: int, *cols) -> "DataFrame":
        by = [self._resolve(c) for c in cols] if cols else None
        return DataFrame(L.Repartition(num, True, self.plan, by),
                         self.session)

    def coalesce(self, num: int) -> "DataFrame":
        return DataFrame(L.Repartition(num, False, self.plan), self.session)

    # -- actions -----------------------------------------------------------
    def _execute(self) -> HostBatch:
        return self.session.execute_plan(self.plan)

    def collect(self) -> List[Row]:
        batch = self._execute()
        names = [f.name for f in batch.schema.fields]
        return [Row(r, names) for r in batch.rows()]

    def count(self) -> int:
        return int(self._execute().num_rows)

    def toPandas(self):
        import pandas as pd
        return pd.DataFrame(self._execute().to_pydict())

    def show(self, n: int = 20) -> None:
        rows = self.limit(n).collect()
        names = self.columns
        widths = [max(len(str(x)) for x in [nm] + [r[i] for r in rows])
                  for i, nm in enumerate(names)]
        line = "+" + "+".join("-" * (w + 2) for w in widths) + "+"
        print(line)
        print("|" + "|".join(f" {nm:<{w}} "
                             for nm, w in zip(names, widths)) + "|")
        print(line)
        for r in rows:
            print("|" + "|".join(f" {str(v):<{w}} "
                                 for v, w in zip(r, widths)) + "|")
        print(line)

    def explain(self, extended: bool = False) -> None:
        print(self.session.explain_string(self.plan))

    def createOrReplaceTempView(self, name: str) -> None:
        self.session.catalog_views[name.lower()] = self.plan

    @property
    def write(self):
        from spark_rapids_tpu.io.writers import DataFrameWriter
        return DataFrameWriter(self)

    def cache(self) -> "DataFrame":
        from spark_rapids_tpu.io.cache import cache_plan
        return DataFrame(cache_plan(self), self.session)

    def __getitem__(self, name: str) -> Column:
        return Column(self._resolve(name))

    def __getattr__(self, name: str) -> Column:
        if name.startswith("_"):
            raise AttributeError(name)
        if name in self.columns:
            return Column(self._resolve(name))
        raise AttributeError(name)


class GroupedData:
    def __init__(self, df: DataFrame, grouping: List[E.Expression],
                 sets_mode: Optional[str] = None):
        self.df = df
        self.grouping = grouping
        self.sets_mode = sets_mode  # None | "rollup" | "cube"

    def _expand_sets(self, agg_cols) -> DataFrame:
        """rollup/cube -> Aggregate over Expand with a grouping-id column
        (Spark's ResolveGroupingAnalytics shape; device twin:
        GpuExpandExec). The gid keeps 'key absent from this set' groups
        apart from genuine null-key groups."""
        df = self.df
        # 1. make every key an attribute (pre-project aliased exprs)
        base_items = list(df.plan.output)
        key_attrs: List[E.AttributeReference] = []
        need_proj = False
        for g in self.grouping:
            if isinstance(g, E.AttributeReference):
                key_attrs.append(g)
            else:
                alias = g if isinstance(g, E.Alias) else \
                    E.Alias(g, _auto_name(g))
                base_items.append(alias)
                key_attrs.append(alias.to_attribute())
                need_proj = True
        plan = (L.Project(base_items, df.plan) if need_proj else df.plan)
        child_out = list(plan.output)
        # 2. grouping sets
        n = len(key_attrs)
        if self.sets_mode == "rollup":
            sets = [frozenset(range(k)) for k in range(n, -1, -1)]
        else:  # cube
            sets = [frozenset(i for i in range(n) if mask & (1 << i))
                    for mask in range((1 << n) - 1, -1, -1)]
        # 3. expanded output: child cols + one fresh attr per key + gid
        out_keys = [E.AttributeReference(a.name, a.data_type, True)
                    for a in key_attrs]
        gid = E.AttributeReference("spark_grouping_id", T.LongT, False)
        expand_out = child_out + out_keys + [gid]
        projections: List[List[E.Expression]] = []
        for si, s in enumerate(sets):
            proj: List[E.Expression] = list(child_out)
            for i, a in enumerate(key_attrs):
                proj.append(a if i in s
                            else E.Literal(None, a.data_type))
            proj.append(E.Literal(si, T.LongT))
            projections.append(proj)
        expanded = DataFrame(
            L.Expand(projections, expand_out, plan), df.session)
        # 4. aggregate over (expanded keys, gid); gid stays internal.
        # Aggregates referencing a grouping column resolve to the
        # EXPANDED (nulled) key, like Spark — so resolve against the
        # non-key child columns + the fresh key attrs only.
        key_ids = {a.expr_id for a in key_attrs}
        resolve_attrs = [a for a in child_out
                         if a.expr_id not in key_ids] + out_keys
        case_sensitive = df.session.conf.get(
            "spark.sql.caseSensitive", False)
        aggs: List[E.Expression] = list(out_keys)
        for c in agg_cols:
            e = _coerce_resolved(L.resolve(
                c.expr if isinstance(c, Column) else c,
                resolve_attrs, bool(case_sensitive)))
            if not isinstance(e, (E.Alias, E.AttributeReference)):
                e = E.Alias(e, _auto_name(e))
            aggs.append(e)
        return DataFrame(
            L.Aggregate(out_keys + [gid], aggs, expanded.plan),
            df.session)

    def agg(self, *cols) -> DataFrame:
        if self.sets_mode is not None:
            return self._expand_sets(cols)
        # Non-attribute grouping keys get a single shared Alias so the
        # planner's pre-projection and the result column refer to the same
        # attribute id (Spark aliases grouping expressions the same way).
        grouping: List[E.Expression] = []
        aggs: List[E.Expression] = []
        for g in self.grouping:
            if isinstance(g, E.AttributeReference):
                grouping.append(g)
                aggs.append(g)
            else:
                alias = g if isinstance(g, E.Alias) else \
                    E.Alias(g, _auto_name(g))
                grouping.append(alias)
                aggs.append(alias.to_attribute())
        for c in cols:
            e = self.df._resolve(c)
            if not isinstance(e, (E.Alias, E.AttributeReference)):
                e = E.Alias(e, _auto_name(e))
            aggs.append(e)
        return DataFrame(L.Aggregate(grouping, aggs, self.df.plan),
                         self.df.session)

    def count(self) -> DataFrame:
        from spark_rapids_tpu.sql import functions as F
        return self.agg(F.count("*").alias("count"))

    def pivot(self, col: str, values: Optional[list] = None
              ) -> "PivotedData":
        """groupBy(...).pivot(c, [v...]).agg(f): rewritten to one
        conditional aggregate per pivot value — sum(when(c = v, x)) —
        so the whole pivot rides the existing device aggregation path
        (Spark's PivotFirst lowered to its CASE WHEN equivalent; the
        reference device-codegens the same shape via GpuPivotFirst,
        aggregate.scala:1059). Without explicit values the distinct
        values are collected first (Spark does the same extra job)."""
        from spark_rapids_tpu.sql import functions as F
        if values is None:
            rows = (self.df.select(F.col(col)).distinct()
                    .orderBy(F.col(col)).collect())
            values = [r[0] for r in rows if r[0] is not None]
        return PivotedData(self, col, list(values))

    def _simple(self, fn, *cols) -> DataFrame:
        from spark_rapids_tpu.sql import functions as F
        targets = cols or [a.name for a in self.df.plan.output
                           if T.is_numeric(a.data_type)]
        return self.agg(*[fn(F.col(c)).alias(f"{fn.__name__}({c})")
                          for c in targets])


class PivotedData:
    """groupBy().pivot() staging: agg() fans each aggregate out across
    the pivot values as conditional aggregates."""

    def __init__(self, grouped: GroupedData, col: str, values: list):
        self._grouped = grouped
        self._col = col
        self._values = values

    def agg(self, *cols) -> DataFrame:
        from spark_rapids_tpu.sql import functions as F
        out = []
        for c in cols:
            e = self._grouped.df._resolve(c)
            base_name = e.name if isinstance(e, E.Alias) else None
            agg_expr = e.child if isinstance(e, E.Alias) else e
            assert isinstance(agg_expr, E.AggregateExpression), (
                "pivot agg expects aggregate expressions")
            func = agg_expr.func
            for v in self._values:
                # sum(x) FILTER (WHERE p = v) == sum(when(p = v, x))
                src = func.children[0] if func.children else E.Literal(1)
                gated = E.CaseWhen(
                    [(E.EqualTo(E.UnresolvedAttribute(self._col),
                                E.Literal(v)), src)], None)
                if isinstance(func, E.Count):
                    fn2: E.AggregateFunction = E.Count([gated])
                elif isinstance(func, (E.First, E.Last)):
                    fn2 = type(func)(gated, func.ignore_nulls)
                else:
                    fn2 = type(func)(gated)
                if len(cols) == 1:
                    name = str(v)
                else:
                    suffix = base_name or _auto_name(agg_expr)
                    name = f"{v}_{suffix}"
                out.append(Column(E.Alias(
                    E.AggregateExpression(fn2, agg_expr.is_distinct),
                    name)))
        return self._grouped.agg(*out)

    def sum(self, *cols) -> DataFrame:
        from spark_rapids_tpu.sql import functions as F
        return self._simple(F.sum, *cols)

    def avg(self, *cols) -> DataFrame:
        from spark_rapids_tpu.sql import functions as F
        return self._simple(F.avg, *cols)

    def min(self, *cols) -> DataFrame:
        from spark_rapids_tpu.sql import functions as F
        return self._simple(F.min, *cols)

    def max(self, *cols) -> DataFrame:
        from spark_rapids_tpu.sql import functions as F
        return self._simple(F.max, *cols)


def _auto_name(e: E.Expression) -> str:
    if isinstance(e, E.AggregateExpression):
        inner = ", ".join(_auto_name(c) for c in e.func.children)
        return f"{e.func.pretty_name}({inner})"
    if isinstance(e, E.AttributeReference):
        return e.name
    if isinstance(e, E.Literal):
        return str(e.value)
    if isinstance(e, E.Cast):
        return _auto_name(e.child)
    if isinstance(e, E.GetStructField):
        return e.pretty_name  # `SELECT s.x` names the output column x
    return repr(e)


def _coerce_resolved(e: E.Expression) -> E.Expression:
    """Post-resolution type coercion: insert casts on mismatched binary
    ops (the TypeCoercion role)."""
    from spark_rapids_tpu.sql.functions import _coerce_pair

    def rule(node: E.Expression) -> Optional[E.Expression]:
        if isinstance(node, (E.BinaryArithmetic, E.BinaryComparison)) and \
                not isinstance(node, E.Divide):
            try:
                lt, rt = node.left.data_type, node.right.data_type
            except Exception:
                return None
            if lt != rt:
                # +,-,* take DecimalPrecision's no-widen rule; %/pmod
                # and comparisons widen to a common decimal
                a, b = _coerce_pair(
                    node.left, node.right,
                    arith=isinstance(node, (E.Add, E.Subtract,
                                            E.Multiply)))
                return type(node)(a, b)
        if isinstance(node, E.Divide):
            try:
                lt, rt = node.left.data_type, node.right.data_type
            except Exception:
                return None
            if isinstance(lt, T.DecimalType) or \
                    isinstance(rt, T.DecimalType):
                # decimal division unless a fractional side forces double
                if isinstance(lt, (T.FloatType, T.DoubleType)) or \
                        isinstance(rt, (T.FloatType, T.DoubleType)):
                    return E.Divide(
                        node.left if isinstance(lt, T.DoubleType)
                        else E.Cast(node.left, T.DoubleT),
                        node.right if isinstance(rt, T.DoubleType)
                        else E.Cast(node.right, T.DoubleT))
                a, b = _coerce_pair(node.left, node.right, arith=True)
                if a is not node.left or b is not node.right:
                    return E.Divide(a, b)
                return None
            if not isinstance(lt, T.DoubleType) or \
                    not isinstance(rt, T.DoubleType):
                a = node.left if isinstance(lt, T.DoubleType) \
                    else E.Cast(node.left, T.DoubleT)
                b = node.right if isinstance(rt, T.DoubleType) \
                    else E.Cast(node.right, T.DoubleT)
                return E.Divide(a, b)
        return None

    return e.transform(rule)
