"""Physical plans and CPU operators.

The physical tree is what the plugin rewrites (GpuOverrides.apply in the
reference wraps SparkPlan nodes; SURVEY.md 3.2). CPU operators here play
the role of Spark's own execs: they are the fallback target and the
bit-identical baseline. Execution model mirrors RDD[ColumnarBatch]:
each operator exposes `partitions()` -> list of thunks yielding HostBatch.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Dict, Iterator, List, Optional, Sequence, Tuple

import numpy as np

from spark_rapids_tpu.columnar.host import HostBatch, HostColumn
from spark_rapids_tpu.columnar import murmur3
from spark_rapids_tpu.sql import types as T
from spark_rapids_tpu.sql import expressions as E

PartitionThunk = Callable[[], Iterator[HostBatch]]


class Partitioning:
    num_partitions: int


class SinglePartitioning(Partitioning):
    num_partitions = 1

    def __repr__(self):
        return "SinglePartition"


class HashPartitioning(Partitioning):
    """Spark HashPartitioning: pmod(murmur3(keys, 42), n)."""

    def __init__(self, exprs: List[E.Expression], num_partitions: int):
        self.exprs = exprs
        self.num_partitions = num_partitions

    def partition_ids(self, batch: HostBatch,
                      bound_exprs: List[E.Expression]) -> np.ndarray:
        h = E.Murmur3Hash(bound_exprs).eval(batch).data.astype(np.int64)
        return np.mod(h, self.num_partitions).astype(np.int32)

    def __repr__(self):
        return f"HashPartitioning({self.exprs}, {self.num_partitions})"


class RoundRobinPartitioning(Partitioning):
    def __init__(self, num_partitions: int):
        self.num_partitions = num_partitions

    def __repr__(self):
        return f"RoundRobinPartitioning({self.num_partitions})"


class RangePartitioning(Partitioning):
    def __init__(self, order: List[E.SortOrder], num_partitions: int):
        self.order = order
        self.num_partitions = num_partitions

    def __repr__(self):
        return f"RangePartitioning({self.order}, {self.num_partitions})"


class PhysicalPlan:
    children: List["PhysicalPlan"]

    @property
    def output(self) -> List[E.AttributeReference]:
        raise NotImplementedError

    @property
    def schema(self) -> T.StructType:
        return T.StructType([T.StructField(a.name, a.data_type, a.nullable)
                             for a in self.output])

    def partitions(self) -> List[PartitionThunk]:
        raise NotImplementedError

    def execute_collect(self, parallelism: int = 1) -> HostBatch:
        """Drain all partitions (optionally with a task thread pool — the
        executor-cores analogue; the TpuSemaphore bounds how many tasks
        touch the device at once). Partition ORDER is preserved.

        A ``TpuChipFailure`` that escapes the operators' own recovery
        (queries without an exchange between the mesh point and the
        sink) is handled HERE like Spark's driver handles a fetch
        failure: the chip is demoted and the whole collect re-executes
        on the surviving mesh (retry.degrade_on_chip_failure — shared
        with the exchange materializer so the retry-vs-reraise protocol
        lives in one place). CPU-only roots without a metric registry
        just skip the degradedChips update."""
        from spark_rapids_tpu.retry import degrade_on_chip_failure
        return degrade_on_chip_failure(
            lambda: self._collect_once(parallelism),
            getattr(self, "metrics", None))

    def _collect_once(self, parallelism: int) -> HostBatch:
        from spark_rapids_tpu import lifecycle as LC
        from spark_rapids_tpu.resource import release_current_thread
        # the query's CancelToken follows the work onto pool threads
        # explicitly (a thread-local cannot cross the task pool);
        # checkpointing per drained batch is the cooperative batch-loop
        # cancellation point (docs/serving.md "Query lifecycle")
        token = LC.current_token()

        def drain(t) -> list:
            # per-task try/finally: an injected/real fault mid-drain
            # must return the task thread's device permit — pool
            # threads are discarded with the pool, so a leaked permit
            # would shrink the semaphore for the process lifetime
            try:
                with LC.token_scope(token):
                    out = []
                    for b in t():
                        LC.checkpoint("batch")
                        out.append(b)
                    return out
            finally:
                release_current_thread()

        try:
            thunks = self.partitions()
            if parallelism > 1 and len(thunks) > 1:
                from concurrent.futures import ThreadPoolExecutor
                # partitions() may have eagerly drained device subtrees
                # on this thread (broadcast build sides), leaving a
                # permit held; release it before blocking on the pool or
                # the task threads can starve of permits and hang
                release_current_thread()
                with ThreadPoolExecutor(
                        min(parallelism, len(thunks)),
                        thread_name_prefix="srt-task") as pool:
                    per_part = list(pool.map(drain, thunks))
                batches = [b for part in per_part for b in part]
            else:
                batches = []
                for thunk in thunks:
                    batches.extend(drain(thunk))
        finally:
            # the planning/drain path itself may hold this thread's
            # permit when an exception unwinds (e.g. an AQE broadcast
            # materialization during partitions() wiring)
            release_current_thread()
        if not batches:
            return HostBatch.empty(self.schema)
        return HostBatch.concat(batches)

    def with_new_children(self, children: List["PhysicalPlan"]
                          ) -> "PhysicalPlan":
        import copy
        node = copy.copy(self)
        node.children = list(children)
        return node

    def simple_string(self) -> str:
        return type(self).__name__

    def tree_string(self, indent: int = 0) -> str:
        s = " " * indent + self.simple_string()
        for c in self.children:
            s += "\n" + c.tree_string(indent + 2)
        return s

    def __repr__(self) -> str:
        return self.tree_string()


def bind_list(exprs: Sequence[E.Expression],
              inputs: Sequence[E.AttributeReference]) -> List[E.Expression]:
    return [E.bind_references(e, inputs) for e in exprs]


# ---------------------------------------------------------------------------
# Sources
# ---------------------------------------------------------------------------

class CpuLocalScanExec(PhysicalPlan):
    def __init__(self, output: List[E.AttributeReference],
                 batches: List[HostBatch], num_partitions: int = 1):
        self.children = []
        self._output = output
        self.batches = batches
        self.num_partitions = max(1, num_partitions)

    @property
    def output(self):
        return self._output

    def partitions(self) -> List[PartitionThunk]:
        parts: List[List[HostBatch]] = [[] for _ in
                                        range(self.num_partitions)]
        for i, b in enumerate(self.batches):
            parts[i % self.num_partitions].append(b)
        return [(lambda bs=bs: iter(bs)) for bs in parts]

    def simple_string(self):
        n = sum(b.num_rows for b in self.batches)
        return f"LocalScan [{n} rows x {len(self._output)} cols]"


class CpuRangeExec(PhysicalPlan):
    def __init__(self, output, start: int, end: int, step: int,
                 num_partitions: int):
        self.children = []
        self._output = output
        self.start, self.end, self.step = start, end, step
        self.num_partitions = max(1, num_partitions)

    @property
    def output(self):
        return self._output

    def partitions(self) -> List[PartitionThunk]:
        total = max(0, (self.end - self.start + self.step
                        - (1 if self.step > 0 else -1)) // self.step)
        per = (total + self.num_partitions - 1) // self.num_partitions \
            if total else 0

        def make(pidx: int) -> PartitionThunk:
            def run() -> Iterator[HostBatch]:
                lo = pidx * per
                hi = min(total, lo + per)
                if hi <= lo:
                    return
                vals = (self.start
                        + np.arange(lo, hi, dtype=np.int64) * self.step)
                col = HostColumn.all_valid(vals, T.LongT)
                yield HostBatch(self.schema, [col], len(vals))
            return run
        return [make(i) for i in range(self.num_partitions)]


# ---------------------------------------------------------------------------
# Row-level operators
# ---------------------------------------------------------------------------

class CpuProjectExec(PhysicalPlan):
    def __init__(self, project_list: List[E.Expression], child: PhysicalPlan):
        self.children = [child]
        self.project_list = project_list

    @property
    def child(self):
        return self.children[0]

    @property
    def output(self):
        return [E.named_output(e) for e in self.project_list]

    def partitions(self) -> List[PartitionThunk]:
        bound = bind_list(self.project_list, self.child.output)
        schema = self.schema

        def make(pid: int, thunk: PartitionThunk) -> PartitionThunk:
            def run() -> Iterator[HostBatch]:
                rows_seen = 0
                it = iter(thunk())
                while True:
                    # input_file resets BEFORE each pull: a scan feeding
                    # this batch re-sets it while yielding; any other
                    # producer (exchange, cache) leaves it "" — Spark's
                    # input_file_name() post-shuffle semantics
                    E._PART_CTX.input_file = ""
                    b = next(it, None)
                    if b is None:
                        break
                    # (re)set pid/row_start right before EACH eval:
                    # interleaved generators on one thread must not see
                    # each other's context (GpuMonotonicallyIncreasingID
                    # role)
                    E._PART_CTX.pid = pid
                    E._PART_CTX.row_start = rows_seen
                    cols = [e.eval(b) for e in bound]
                    rows_seen += b.num_rows
                    yield HostBatch(schema, cols, b.num_rows)
            return run
        return [make(i, t)
                for i, t in enumerate(self.child.partitions())]

    def simple_string(self):
        return f"Project {self.project_list}"


class CpuGenerateExec(PhysicalPlan):
    """Explode/posexplode (+outer): child rows repeated per array
    element, with pos/col generated columns (GpuGenerateExec.scala:440
    CPU oracle)."""

    def __init__(self, generator: E.Expression,
                 gen_output: List[E.AttributeReference],
                 child: PhysicalPlan):
        self.children = [child]
        self.generator = generator
        self.gen_output = gen_output

    @property
    def child(self):
        return self.children[0]

    @property
    def output(self):
        return list(self.child.output) + list(self.gen_output)

    def partitions(self) -> List[PartitionThunk]:
        import numpy as np
        gen = self.generator
        bound = E.bind_references(gen.children[0], self.child.output)
        schema = self.schema
        elem_t = gen.data_type
        np_elem = T.numpy_dtype(elem_t)

        def explode_batch(b: HostBatch) -> HostBatch:
            arr_col = bound.eval(b)
            counts = np.zeros(b.num_rows, dtype=np.int64)
            for i in range(b.num_rows):
                if arr_col.validity[i]:
                    counts[i] = len(arr_col.data[i])
            if gen.outer:
                counts = np.maximum(counts, 1)
            parent = np.repeat(np.arange(b.num_rows), counts)
            total = int(counts.sum())
            pos = np.zeros(total, dtype=np.int32)
            # outer's pad rows carry NULL in every generated column,
            # pos included (Spark Generate outer join semantics)
            is_real = np.zeros(total, dtype=bool)
            if np_elem == np.dtype(object):
                elems = np.full(total, "", dtype=object)
            else:
                elems = np.zeros(total, dtype=np_elem)
            evalid = np.zeros(total, dtype=bool)
            o = 0
            for i in range(b.num_rows):
                n = int(counts[i])
                if n == 0:
                    continue
                row = (arr_col.data[i] if arr_col.validity[i] else ())
                for j in range(len(row)):
                    pos[o + j] = j
                    is_real[o + j] = True
                    if row[j] is not None:
                        elems[o + j] = row[j]
                        evalid[o + j] = True
                o += n
            from spark_rapids_tpu.columnar.host import HostColumn
            cols = [c.take(parent) for c in b.columns]
            if gen.position:
                cols.append(HostColumn(T.IntegerT, pos, is_real.copy()))
            cols.append(HostColumn(elem_t, elems, evalid).normalized())
            return HostBatch(schema, cols, total)

        def make(thunk: PartitionThunk) -> PartitionThunk:
            def run() -> Iterator[HostBatch]:
                for b in thunk():
                    yield explode_batch(b)
            return run
        return [make(t) for t in self.child.partitions()]

    def simple_string(self):
        return f"Generate {self.generator!r}"


class CpuFilterExec(PhysicalPlan):
    def __init__(self, condition: E.Expression, child: PhysicalPlan):
        self.children = [child]
        self.condition = condition

    @property
    def child(self):
        return self.children[0]

    @property
    def output(self):
        return self.child.output

    def partitions(self) -> List[PartitionThunk]:
        bound = E.bind_references(self.condition, self.child.output)

        def make(pid: int, thunk: PartitionThunk) -> PartitionThunk:
            def run() -> Iterator[HostBatch]:
                rows_seen = 0
                it = iter(thunk())
                while True:
                    E._PART_CTX.input_file = ""
                    b = next(it, None)
                    if b is None:
                        break
                    E._PART_CTX.pid = pid
                    E._PART_CTX.row_start = rows_seen
                    rows_seen += b.num_rows
                    p = bound.eval(b)
                    keep = p.validity & p.data.astype(bool)
                    yield b.take(np.nonzero(keep)[0])
            return run
        return [make(i, t)
                for i, t in enumerate(self.child.partitions())]

    def simple_string(self):
        return f"Filter {self.condition!r}"


class CpuUnionExec(PhysicalPlan):
    def __init__(self, children: List[PhysicalPlan],
                 output: List[E.AttributeReference]):
        self.children = list(children)
        self._output = output

    @property
    def output(self):
        return self._output

    def partitions(self) -> List[PartitionThunk]:
        out: List[PartitionThunk] = []
        schema = self.schema

        def retag(thunk: PartitionThunk) -> PartitionThunk:
            def run():
                for b in thunk():
                    yield HostBatch(schema, b.columns, b.num_rows)
            return run
        for c in self.children:
            out.extend(retag(t) for t in c.partitions())
        return out


class CpuLocalLimitExec(PhysicalPlan):
    def __init__(self, n: int, child: PhysicalPlan):
        self.children = [child]
        self.n = n

    @property
    def child(self):
        return self.children[0]

    @property
    def output(self):
        return self.child.output

    def partitions(self) -> List[PartitionThunk]:
        n = self.n

        def make(thunk: PartitionThunk) -> PartitionThunk:
            def run() -> Iterator[HostBatch]:
                remaining = n
                for b in thunk():
                    if remaining <= 0:
                        break
                    if b.num_rows > remaining:
                        yield b.slice(0, remaining)
                        remaining = 0
                    else:
                        yield b
                        remaining -= b.num_rows
            return run
        return [make(t) for t in self.child.partitions()]


class CpuGlobalLimitExec(CpuLocalLimitExec):
    """Requires single-partition input (planner inserts exchange)."""


# ---------------------------------------------------------------------------
# Exchange
# ---------------------------------------------------------------------------

class CpuShuffleExchangeExec(PhysicalPlan):
    """Materializes the child and redistributes rows; the Spark
    ShuffleExchangeExec the plugin wraps (GpuShuffleExchangeExecBase)."""

    def __init__(self, partitioning: Partitioning, child: PhysicalPlan):
        self.children = [child]
        self.partitioning = partitioning
        self._cache: Optional[List[List[HostBatch]]] = None
        self._lock = threading.Lock()

    @property
    def child(self):
        return self.children[0]

    @property
    def output(self):
        return self.child.output

    def _materialize(self) -> List[List[HostBatch]]:
        # same hazard as the TPU exchange: parking on the lock while
        # holding a device-semaphore permit can starve the materializer
        from spark_rapids_tpu.resource import release_current_thread
        release_current_thread()
        with self._lock:  # consumers race under taskParallelism
            if self._cache is not None:
                return self._cache
            self._cache = out = self._materialize_inner()
            return out

    def _materialize_inner(self) -> List[List[HostBatch]]:
        p = self.partitioning
        n = p.num_partitions
        out: List[List[HostBatch]] = [[] for _ in range(n)]
        if isinstance(p, HashPartitioning):
            bound = bind_list(p.exprs, self.child.output)
            for thunk in self.child.partitions():
                for b in thunk():
                    if b.num_rows == 0:
                        continue
                    pids = p.partition_ids(b, bound)
                    for pid in range(n):
                        idx = np.nonzero(pids == pid)[0]
                        if len(idx):
                            out[pid].append(b.take(idx))
        elif isinstance(p, SinglePartitioning):
            for thunk in self.child.partitions():
                out[0].extend(list(thunk()))
        elif isinstance(p, RoundRobinPartitioning):
            i = 0
            for thunk in self.child.partitions():
                for b in thunk():
                    for pid in range(n):
                        idx = np.arange(pid, b.num_rows, n)
                        if len(idx):
                            out[(i + pid) % n].append(b.take(idx))
                    i += 1
        elif isinstance(p, RangePartitioning):
            out = self._range_partition(p, n)
        else:
            raise NotImplementedError(repr(p))
        return out

    def _range_partition(self, p: RangePartitioning, n: int
                         ) -> List[List[HostBatch]]:
        # Sample bounds on CPU like GpuRangePartitioner, then bucket rows.
        all_batches: List[HostBatch] = []
        for thunk in self.child.partitions():
            all_batches.extend(b for b in thunk() if b.num_rows)
        out: List[List[HostBatch]] = [[] for _ in range(n)]
        if not all_batches:
            return out
        whole = HostBatch.concat(all_batches)
        order_idx = sort_indices(
            whole, bind_list([o.child for o in p.order], self.child.output),
            p.order)
        ranks = np.empty(len(order_idx), dtype=np.int64)
        ranks[order_idx] = np.arange(len(order_idx))
        # equal-depth bounds over the sorted rank space
        bucket = np.minimum((ranks * n) // max(1, whole.num_rows), n - 1)
        for pid in range(n):
            idx = np.nonzero(bucket == pid)[0]
            if len(idx):
                out[pid].append(whole.take(idx))
        return out

    def partitions(self) -> List[PartitionThunk]:
        nparts = self.partitioning.num_partitions

        def make(pid: int) -> PartitionThunk:
            def run() -> Iterator[HostBatch]:
                return iter(self._materialize()[pid])
            return run
        return [make(i) for i in range(nparts)]

    def simple_string(self):
        return f"Exchange {self.partitioning!r}"


# ---------------------------------------------------------------------------
# Sort
# ---------------------------------------------------------------------------

def _composite_key(c: HostColumn, o: E.SortOrder) -> np.ndarray:
    """Single int64/float64 pair encoded as structured key columns is
    overkill here; produce a float64 key with nulls mapped to +/-inf and
    direction applied. Exact for int53; object/large-int fall back to
    rank-based keys."""
    if T.is_limb_decimal(c.dtype):
        from spark_rapids_tpu.ops import int128 as I
        ints = I.to_pyints(*E._dec_limbs(c))
        uniq = np.sort(np.unique(ints[c.validity])) if c.validity.any() \
            else np.array([], dtype=object)
        r = np.searchsorted(uniq, ints).astype(np.float64)
        base = np.where(c.validity, r, np.nan)
    elif c.data.dtype == np.dtype(object):
        vals = c.to_pylist()
        uniq = sorted({v for v in vals if v is not None})
        ranks = {v: i + 1 for i, v in enumerate(uniq)}
        base = np.array([np.nan if v is None else float(ranks[v])
                         for v in vals], dtype=np.float64)
    elif np.issubdtype(c.data.dtype, np.floating) \
            or c.data.dtype == np.int64:
        # rank-based keys: exact beyond float64's 53-bit mantissa for
        # int64/timestamp, and for the uint64 float total-order keys
        raw = (E._float_total_order(c.data)
               if np.issubdtype(c.data.dtype, np.floating) else c.data)
        su = np.unique(raw)
        r = np.searchsorted(su, raw).astype(np.float64)
        base = np.where(c.validity, r, np.nan)
    else:
        base = np.where(c.validity, c.data.astype(np.float64), np.nan)
    if not o.ascending:
        base = -base
    null_key = -np.inf if o.nulls_first else np.inf
    return np.where(np.isnan(base), null_key, base)


def sort_indices(batch: HostBatch, bound_children: List[E.Expression],
                 order: List[E.SortOrder]) -> np.ndarray:
    keys = [_composite_key(e.eval(batch), o)
            for e, o in zip(bound_children, order)]
    return np.lexsort(keys[::-1])


class CpuSortExec(PhysicalPlan):
    def __init__(self, order: List[E.SortOrder], is_global: bool,
                 child: PhysicalPlan):
        self.children = [child]
        self.order = order
        self.is_global = is_global

    @property
    def child(self):
        return self.children[0]

    @property
    def output(self):
        return self.child.output

    def partitions(self) -> List[PartitionThunk]:
        bound = bind_list([o.child for o in self.order], self.child.output)

        def make(thunk: PartitionThunk) -> PartitionThunk:
            def run() -> Iterator[HostBatch]:
                batches = [b for b in thunk() if b.num_rows]
                if not batches:
                    return
                whole = HostBatch.concat(batches)
                idx = sort_indices(whole, bound, self.order)
                yield whole.take(idx)
            return run
        return [make(t) for t in self.child.partitions()]

    def simple_string(self):
        return f"Sort {self.order} global={self.is_global}"


# ---------------------------------------------------------------------------
# Hash aggregate (partial/final split mirroring Spark;
# aggregate.scala:247 in the reference)
# ---------------------------------------------------------------------------

def group_ids(key_cols: List[HostColumn], n: int
              ) -> Tuple[np.ndarray, int, np.ndarray]:
    """(group_id per row, num_groups, representative row per group).
    Nulls form groups; NaN normalized; -0.0 == 0.0."""
    gids = np.empty(n, dtype=np.int64)
    table: Dict[Tuple, int] = {}
    reps: List[int] = []
    key_lists = []
    for c in key_cols:
        if np.issubdtype(c.data.dtype, np.floating):
            key_lists.append([None if not c.validity[i]
                              else ("NaN" if np.isnan(c.data[i])
                                    else float(c.data[i]) + 0.0)
                              for i in range(n)])
        elif c.data.dtype == np.dtype(object):
            key_lists.append([c.data[i] if c.validity[i] else None
                              for i in range(n)])
        else:
            key_lists.append([c.data[i].item() if c.validity[i] else None
                              for i in range(n)])
    for i in range(n):
        k = tuple(kl[i] for kl in key_lists)
        gid = table.get(k)
        if gid is None:
            gid = len(table)
            table[k] = gid
            reps.append(i)
        gids[i] = gid
    return gids, len(table), np.array(reps, dtype=np.int64)


def _limb_update_prim(prim: str, col: HostColumn, gids: np.ndarray,
                      ngroups: int, out_type: T.DataType) -> HostColumn:
    """Group primitives over DECIMAL128 limb columns. Sums accumulate
    four 32-bit parts with np.add.at (each part sum fits int64 for
    < 2^31 rows) and recombine exactly per group."""
    from spark_rapids_tpu.ops import int128 as I
    valid = col.validity
    hi, lo = E._dec_limbs(col)
    if prim in (E.PRIM_SUM, E.PRIM_SUM_NONNULL):
        ulo = lo.astype(np.uint64)
        parts = [
            (ulo & np.uint64(0xFFFFFFFF)).astype(np.int64),
            (ulo >> np.uint64(32)).astype(np.int64),
            (hi.astype(np.uint64) & np.uint64(0xFFFFFFFF)).astype(np.int64),
            hi >> np.int64(32),  # signed top part
        ]
        accs = [np.zeros(ngroups, dtype=np.int64) for _ in parts]
        for acc, part in zip(accs, parts):
            np.add.at(acc, gids[valid], part[valid])
        has = np.zeros(ngroups, dtype=bool)
        has[gids[valid]] = True
        bound = 10 ** out_type.precision
        totals = []
        for g in range(ngroups):
            t = (((int(accs[3][g]) << 32) + int(accs[2][g])) << 64) \
                + (int(accs[1][g]) << 32) + int(accs[0][g])
            totals.append(0 if abs(t) >= bound else t)
            if abs(t) >= bound:
                has[g] = False  # overflow -> null (non-ANSI Sum)
        rhi, rlo = I.from_pyints(totals)
        data = np.stack([rhi, rlo], axis=1)
        if prim == E.PRIM_SUM_NONNULL:
            return HostColumn.all_valid(data, out_type)
        return HostColumn(out_type, data, has).normalized()
    # first/last/min/max: exact ints, per-row walk (host engine style)
    ints = I.to_pyints(hi, lo)
    best = [None] * ngroups
    has = np.zeros(ngroups, dtype=bool)
    touched = np.zeros(ngroups, dtype=bool)
    for i in range(len(ints)):
        g = gids[i]
        if prim in (E.PRIM_FIRST_ANY, E.PRIM_LAST_ANY):
            if prim == E.PRIM_FIRST_ANY and touched[g]:
                continue
            touched[g] = True
            has[g] = valid[i]
            best[g] = int(ints[i]) if valid[i] else None
            continue
        if not valid[i]:
            continue
        v = int(ints[i])
        if not has[g]:
            has[g], best[g] = True, v
        elif prim == E.PRIM_LAST:
            best[g] = v
        elif prim == E.PRIM_MIN and v < best[g]:
            best[g] = v
        elif prim == E.PRIM_MAX and v > best[g]:
            best[g] = v
    rhi, rlo = I.from_pyints([0 if b is None else b for b in best])
    return HostColumn(out_type, np.stack([rhi, rlo], axis=1), has
                      ).normalized()


def apply_update_prim(prim: str, col: HostColumn, gids: np.ndarray,
                      ngroups: int, out_type: T.DataType) -> HostColumn:
    if T.is_limb_decimal(out_type) and prim != E.PRIM_COUNT:
        return _limb_update_prim(prim, col, gids, ngroups, out_type)
    np_dt = T.numpy_dtype(out_type)
    valid = col.validity
    if prim == E.PRIM_COUNT:
        counts = np.zeros(ngroups, dtype=np.int64)
        np.add.at(counts, gids[valid], 1)
        return HostColumn.all_valid(counts, T.LongT)
    if prim in (E.PRIM_SUM, E.PRIM_SUM_NONNULL):
        if np_dt == np.dtype(object):
            raise TypeError("sum of non-numeric")
        acc = np.zeros(ngroups, dtype=np_dt)
        with np.errstate(all="ignore"):
            np.add.at(acc, gids[valid], col.data[valid].astype(np_dt))
        has = np.zeros(ngroups, dtype=bool)
        has[gids[valid]] = True
        if prim == E.PRIM_SUM_NONNULL:
            return HostColumn.all_valid(acc, out_type)
        return HostColumn(out_type, acc, has).normalized()
    if prim in (E.PRIM_FIRST_ANY, E.PRIM_LAST_ANY):
        # first/last row per group INCLUDING nulls (Spark ignoreNulls=false)
        if np_dt == np.dtype(object):
            data = np.full(ngroups, "", dtype=object)
        else:
            data = np.zeros(ngroups, dtype=np_dt)
        validity = np.zeros(ngroups, dtype=bool)
        touched = np.zeros(ngroups, dtype=bool)
        for i in range(len(col.data)):
            g = gids[i]
            if prim == E.PRIM_FIRST_ANY and touched[g]:
                continue
            touched[g] = True
            validity[g] = valid[i]
            if valid[i]:
                data[g] = col.data[i]
        return HostColumn(out_type, data, validity).normalized()
    if prim in (E.PRIM_COLLECT, E.PRIM_COLLECT_MERGE):
        # gather valid values (or concatenate gathered tuples) per group;
        # buffer rows are ALWAYS valid — an empty group holds ()
        limb_ints = None
        if prim == E.PRIM_COLLECT and T.is_limb_decimal(col.dtype):
            from spark_rapids_tpu.ops import int128 as I
            # array-element storage form is the unscaled python int
            limb_ints = I.to_pyints(col.data[:, 0], col.data[:, 1])
        lists: List[list] = [[] for _ in range(ngroups)]
        for i in range(len(col.data)):
            if not valid[i]:
                continue
            g = gids[i]
            if prim == E.PRIM_COLLECT:
                v = int(limb_ints[i]) if limb_ints is not None \
                    else col.data[i]
                if isinstance(v, np.generic):
                    v = v.item()
                lists[g].append(v)
            else:
                lists[g].extend(col.data[i])
        data = np.empty(ngroups, dtype=object)
        for g in range(ngroups):
            data[g] = tuple(lists[g])
        return HostColumn.all_valid(data, out_type)
    if prim in (E.PRIM_MIN, E.PRIM_MAX, E.PRIM_FIRST, E.PRIM_LAST):
        if np_dt == np.dtype(object):
            data = np.full(ngroups, "", dtype=object)
        else:
            data = np.zeros(ngroups, dtype=np_dt)
        has = np.zeros(ngroups, dtype=bool)
        is_float = np.issubdtype(col.data.dtype, np.floating) \
            and np_dt != np.dtype(object)
        fk = E._float_total_order(col.data) if is_float else None
        best_key = {}
        for i in range(len(col.data)):
            if not valid[i]:
                continue
            g = gids[i]
            v = col.data[i]
            if not has[g]:
                has[g] = True
                data[g] = v
                if is_float:
                    best_key[g] = fk[i]
                continue
            if prim == E.PRIM_FIRST:
                continue
            if prim == E.PRIM_LAST:
                data[g] = v
            elif is_float:
                if (prim == E.PRIM_MIN and fk[i] < best_key[g]) or \
                        (prim == E.PRIM_MAX and fk[i] > best_key[g]):
                    best_key[g] = fk[i]
                    data[g] = v
            else:
                if (prim == E.PRIM_MIN and v < data[g]) or \
                        (prim == E.PRIM_MAX and v > data[g]):
                    data[g] = v
        return HostColumn(out_type, data, has).normalized()
    raise NotImplementedError(prim)


class AggSlot:
    """One buffer slot of one aggregate function, with its attribute."""

    def __init__(self, name: str, dtype: T.DataType, update_prim: str,
                 update_expr: E.Expression, merge_prim: str):
        self.name = name
        self.dtype = dtype
        self.update_prim = update_prim
        self.update_expr = update_expr
        self.merge_prim = merge_prim
        self.attr = E.AttributeReference(name, dtype, True)


def plan_agg_slots(aggregates: List[E.Expression]) -> Dict[int, List[AggSlot]]:
    """aggregate Alias expr_id -> its slots."""
    out: Dict[int, List[AggSlot]] = {}
    for e in aggregates:
        if isinstance(e, E.Alias) and isinstance(e.child,
                                                 E.AggregateExpression):
            if e.child.is_distinct:
                raise NotImplementedError(
                    "DISTINCT aggregates are not supported yet; rewrite "
                    "with dropDuplicates + aggregate")
            func = e.child.func
            slots = [AggSlot(f"{e.name}_{s[0]}", s[1], s[2], s[3], s[4])
                     for s in func.buffer_slots()]
            out[e.expr_id] = slots
    return out


class CpuHashAggregateExec(PhysicalPlan):
    """mode: 'partial' emits keys+buffers; 'final' merges buffers and
    projects results; 'complete' does both in one node."""

    def __init__(self, grouping: List[E.AttributeReference],
                 aggregates: List[E.Expression], mode: str,
                 child: PhysicalPlan,
                 slots: Optional[Dict[int, List[AggSlot]]] = None):
        self.children = [child]
        self.grouping = grouping
        self.aggregates = aggregates
        self.mode = mode
        self.slots = slots if slots is not None else \
            plan_agg_slots(aggregates)

    @property
    def child(self):
        return self.children[0]

    @property
    def output(self):
        if self.mode == "partial":
            out = list(self.grouping)
            for e in self.aggregates:
                if isinstance(e, E.Alias) and isinstance(
                        e.child, E.AggregateExpression):
                    out.extend(s.attr for s in self.slots[e.expr_id])
            return out
        return [E.named_output(e) for e in self.aggregates]

    def partitions(self) -> List[PartitionThunk]:
        return [self._make(t) for t in self.child.partitions()]

    def _make(self, thunk: PartitionThunk) -> PartitionThunk:
        def run() -> Iterator[HostBatch]:
            batches = [b for b in thunk() if b.num_rows]
            grouped = len(self.grouping) > 0
            if not batches:
                if not grouped and self.mode in ("final", "complete"):
                    yield self._empty_global_result()
                return
            whole = HostBatch.concat(batches)
            yield self._aggregate(whole)
        return run

    def _aggregate(self, whole: HostBatch) -> HostBatch:
        child_out = self.child.output
        key_bound = bind_list(list(self.grouping), child_out)
        key_cols = [e.eval(whole) for e in key_bound]
        if self.grouping:
            gids, ngroups, reps = group_ids(key_cols, whole.num_rows)
        else:
            gids = np.zeros(whole.num_rows, dtype=np.int64)
            ngroups, reps = 1, np.array([0], dtype=np.int64)

        out_cols: List[HostColumn] = []
        if self.mode == "partial":
            for kc in key_cols:
                out_cols.append(kc.take(reps))
            for e in self.aggregates:
                if isinstance(e, E.Alias) and isinstance(
                        e.child, E.AggregateExpression):
                    for s in self.slots[e.expr_id]:
                        prim = s.update_prim
                        bound = E.bind_references(s.update_expr, child_out)
                        col = bound.eval(whole)
                        out_cols.append(apply_update_prim(
                            prim, col, gids, ngroups, s.dtype))
            return HostBatch(self.schema, out_cols, ngroups)

        # final / complete: compute merged buffers per group
        merged: Dict[int, List[HostColumn]] = {}
        for e in self.aggregates:
            if isinstance(e, E.Alias) and isinstance(e.child,
                                                     E.AggregateExpression):
                cols = []
                for s in self.slots[e.expr_id]:
                    if self.mode == "complete":
                        prim, src = s.update_prim, s.update_expr
                    else:
                        prim, src = s.merge_prim, s.attr
                    bound = E.bind_references(src, child_out)
                    col = bound.eval(whole)
                    cols.append(apply_update_prim(
                        prim, col, gids, ngroups, s.dtype))
                merged[e.expr_id] = cols

        key_by_attr = {a.expr_id: kc.take(reps)
                       for a, kc in zip(self.grouping, key_cols)}
        for e in self.aggregates:
            if isinstance(e, E.Alias) and isinstance(e.child,
                                                     E.AggregateExpression):
                out_cols.append(e.child.func.evaluate(merged[e.expr_id]))
            elif isinstance(e, E.AttributeReference):
                out_cols.append(key_by_attr[e.expr_id])
            elif isinstance(e, E.Alias) and isinstance(e.child,
                                                       E.AttributeReference):
                out_cols.append(key_by_attr[e.child.expr_id])
            else:
                raise NotImplementedError(f"agg result expr {e!r}")
        return HostBatch(self.schema, out_cols, ngroups)

    def _empty_global_result(self) -> HostBatch:
        """Global agg over empty input yields one row (sum=null, count=0)."""
        cols = []
        for e in self.aggregates:
            assert isinstance(e, E.Alias)
            func = e.child.func
            buffers = [HostColumn.nulls(1, s.dtype)
                       for s in self.slots[e.expr_id]]
            cols.append(func.evaluate(buffers))
        return HostBatch(self.schema, cols, 1)

    def simple_string(self):
        return (f"HashAggregate mode={self.mode} keys={self.grouping} "
                f"aggs={self.aggregates}")


# ---------------------------------------------------------------------------
# Joins (CPU shuffled hash join; GpuShuffledHashJoinBase twin)
# ---------------------------------------------------------------------------

class CpuShuffledHashJoinExec(PhysicalPlan):
    def __init__(self, left_keys: List[E.Expression],
                 right_keys: List[E.Expression], join_type: str,
                 condition: Optional[E.Expression],
                 left: PhysicalPlan, right: PhysicalPlan,
                 output: List[E.AttributeReference],
                 null_safe: Optional[List[bool]] = None):
        self.children = [left, right]
        self.left_keys = left_keys
        self.right_keys = right_keys
        self.join_type = join_type
        self.condition = condition
        self._output = output
        # per-key <=> flags: a null-safe key matches null to null
        # instead of excluding the row (Spark EqualNullSafe join keys)
        self.null_safe = list(null_safe or [False] * len(left_keys))

    @property
    def left(self):
        return self.children[0]

    @property
    def right(self):
        return self.children[1]

    @property
    def output(self):
        return self._output

    def partitions(self) -> List[PartitionThunk]:
        lp = self.left.partitions()
        rp = self.right.partitions()
        assert len(lp) == len(rp), "join children must be co-partitioned"
        return [self._make(lt, rt) for lt, rt in zip(lp, rp)]

    _NULL_KEY = "\x00<null-safe-null>\x00"  # sentinel for <=> null keys

    def _key_tuples(self, batch: HostBatch, keys: List[E.Expression],
                    inputs) -> List[Optional[Tuple]]:
        cols = [E.bind_references(k, inputs).eval(batch) for k in keys]
        ns = self.null_safe
        out: List[Optional[Tuple]] = []
        for i in range(batch.num_rows):
            parts = []
            null = False
            for ki, c in enumerate(cols):
                if not c.validity[i]:
                    if ns[ki]:  # <=>: null groups with null
                        parts.append(self._NULL_KEY)
                        continue
                    null = True
                    break
                v = c.data[i]
                if isinstance(v, np.generic):
                    v = v.item()
                if isinstance(v, float):
                    v = "NaN" if v != v else v + 0.0
                parts.append(v)
            out.append(None if null else tuple(parts))
        return out

    def _make(self, lt: PartitionThunk, rt: PartitionThunk) -> PartitionThunk:
        def run() -> Iterator[HostBatch]:
            lb = [b for b in lt() if b.num_rows]
            rb = [b for b in rt() if b.num_rows]
            jt = self.join_type
            lschema = T.StructType([
                T.StructField(a.name, a.data_type, a.nullable)
                for a in self.left.output])
            rschema = T.StructType([
                T.StructField(a.name, a.data_type, a.nullable)
                for a in self.right.output])
            lwhole = HostBatch.concat(lb) if lb else HostBatch.empty(lschema)
            rwhole = HostBatch.concat(rb) if rb else HostBatch.empty(rschema)
            yield self._join(lwhole, rwhole)
        return run

    def _join(self, lwhole: HostBatch, rwhole: HostBatch) -> HostBatch:
        jt = self.join_type
        # build on right
        build_map: Dict[Tuple, List[int]] = {}
        rkeys = self._key_tuples(rwhole, self.right_keys, self.right.output)
        for i, k in enumerate(rkeys):
            if k is not None:
                build_map.setdefault(k, []).append(i)
        lkeys = self._key_tuples(lwhole, self.left_keys, self.left.output)

        cond = None
        if self.condition is not None:
            cond = E.bind_references(
                self.condition, list(self.left.output)
                + list(self.right.output))

        li: List[int] = []
        ri: List[int] = []
        lmatched = np.zeros(lwhole.num_rows, dtype=bool)
        rmatched = np.zeros(rwhole.num_rows, dtype=bool)
        for i, k in enumerate(lkeys):
            if k is None:
                continue
            for j in build_map.get(k, ()):
                li.append(i)
                ri.append(j)
        li_a = np.array(li, dtype=np.int64)
        ri_a = np.array(ri, dtype=np.int64)
        if cond is not None and len(li_a):
            pairs = _gather_pair(lwhole, rwhole, li_a, ri_a,
                                 self._pair_schema())
            p = cond.eval(pairs)
            keep = p.validity & p.data.astype(bool)
            li_a, ri_a = li_a[keep], ri_a[keep]
        lmatched[li_a] = True
        rmatched[ri_a] = True

        if jt == "inner" or jt == "cross":
            return _gather_pair(lwhole, rwhole, li_a, ri_a, self.schema)
        if jt in ("left", "leftouter"):
            extra = np.nonzero(~lmatched)[0]
            li_a = np.concatenate([li_a, extra])
            ri_a = np.concatenate([ri_a, np.full(len(extra), -1,
                                                 dtype=np.int64)])
            return _gather_pair(lwhole, rwhole, li_a, ri_a, self.schema)
        if jt in ("right", "rightouter"):
            extra = np.nonzero(~rmatched)[0]
            li_a = np.concatenate([li_a, np.full(len(extra), -1,
                                                 dtype=np.int64)])
            ri_a = np.concatenate([ri_a, extra])
            return _gather_pair(lwhole, rwhole, li_a, ri_a, self.schema)
        if jt in ("full", "fullouter"):
            lex = np.nonzero(~lmatched)[0]
            rex = np.nonzero(~rmatched)[0]
            li_a = np.concatenate([li_a, lex,
                                   np.full(len(rex), -1, dtype=np.int64)])
            ri_a = np.concatenate([ri_a,
                                   np.full(len(lex), -1, dtype=np.int64),
                                   rex])
            return _gather_pair(lwhole, rwhole, li_a, ri_a, self.schema)
        if jt == "leftsemi":
            idx = np.nonzero(lmatched)[0]
            return lwhole.take(idx)
        if jt == "leftanti":
            # anti keeps rows with no match; null-keyed rows never match
            idx = np.nonzero(~lmatched)[0]
            return lwhole.take(idx)
        raise NotImplementedError(jt)

    def _pair_schema(self) -> T.StructType:
        attrs = list(self.left.output) + list(self.right.output)
        return T.StructType([T.StructField(a.name, a.data_type, a.nullable)
                             for a in attrs])

    def simple_string(self):
        return (f"ShuffledHashJoin {self.join_type} "
                f"l={self.left_keys} r={self.right_keys} "
                f"cond={self.condition!r}")


def _gather_pair(lwhole: HostBatch, rwhole: HostBatch, li: np.ndarray,
                 ri: np.ndarray, schema: T.StructType) -> HostBatch:
    """Gather rows from both sides; index -1 = null row (outer joins)."""
    cols: List[HostColumn] = []
    nl = lwhole.num_cols
    fields = list(schema.fields)
    for c_idx in range(nl):
        cols.append(_gather_nullable(lwhole.columns[c_idx], li))
    for c_idx in range(rwhole.num_cols):
        cols.append(_gather_nullable(rwhole.columns[c_idx], ri))
    return HostBatch(schema, cols, len(li))


def _gather_nullable(c: HostColumn, idx: np.ndarray) -> HostColumn:
    if len(c.data) == 0:
        # empty side of an outer join: every gathered row is a null row
        return HostColumn.nulls(len(idx), c.dtype)
    safe = np.where(idx >= 0, idx, 0)
    data = c.data[safe]
    validity = np.where(idx >= 0, c.validity[safe], False)
    out = HostColumn(c.dtype, data.copy(), validity.astype(bool))
    return out.normalized()


class CpuBroadcastExchangeExec(PhysicalPlan):
    """Reusable broadcast exchange (GpuBroadcastExchangeExec.scala:71,
    280 role): the build side materializes ONCE behind a lock and is
    shared by every consumer — all stream partitions of one join, and
    SEVERAL joins when the reuse pass deduplicates structurally equal
    broadcast subtrees (Spark's ReuseExchange)."""

    def __init__(self, child: PhysicalPlan):
        self.children = [child]
        self._lock = threading.Lock()
        self._built: Optional[HostBatch] = None
        self.build_count = 0  # observability: reuse tests pin this

    @property
    def child(self):
        return self.children[0]

    @property
    def output(self):
        return self.child.output

    def materialize(self) -> HostBatch:
        with self._lock:
            if self._built is None:
                self.build_count += 1
                batches = [b for t in self.child.partitions()
                           for b in t() if b.num_rows]
                self._built = (HostBatch.concat(batches) if batches
                               else HostBatch.empty(self.schema))
            return self._built

    def partitions(self) -> List[PartitionThunk]:
        return [lambda: iter([self.materialize()])]

    def simple_string(self):
        return "BroadcastExchange"


class CpuBroadcastHashJoinExec(CpuShuffledHashJoinExec):
    """Build side fully materialized and shared across stream partitions
    (GpuBroadcastHashJoinExec twin; build side = right)."""

    def partitions(self) -> List[PartitionThunk]:
        rschema = T.StructType([
            T.StructField(a.name, a.data_type, a.nullable)
            for a in self.right.output])
        if isinstance(self.right, CpuBroadcastExchangeExec):
            rwhole = self.right.materialize()
        else:
            rbatches: List[HostBatch] = []
            for t in self.right.partitions():
                rbatches.extend(b for b in t() if b.num_rows)
            rwhole = (HostBatch.concat(rbatches) if rbatches
                      else HostBatch.empty(rschema))

        def make(lt: PartitionThunk) -> PartitionThunk:
            def run() -> Iterator[HostBatch]:
                lb = [b for b in lt() if b.num_rows]
                lschema = T.StructType([
                    T.StructField(a.name, a.data_type, a.nullable)
                    for a in self.left.output])
                lwhole = (HostBatch.concat(lb) if lb
                          else HostBatch.empty(lschema))
                yield self._join(lwhole, rwhole)
            return run
        return [make(t) for t in self.left.partitions()]


class CpuExpandExec(PhysicalPlan):
    def __init__(self, projections: List[List[E.Expression]],
                 output: List[E.AttributeReference], child: PhysicalPlan):
        self.children = [child]
        self.projections = projections
        self._output = output

    @property
    def child(self):
        return self.children[0]

    @property
    def output(self):
        return self._output

    def partitions(self) -> List[PartitionThunk]:
        bound = [bind_list(p, self.child.output) for p in self.projections]
        schema = self.schema

        def make(thunk: PartitionThunk) -> PartitionThunk:
            def run() -> Iterator[HostBatch]:
                for b in thunk():
                    outs = []
                    for proj in bound:
                        cols = [e.eval(b) for e in proj]
                        outs.append(HostBatch(schema, cols, b.num_rows))
                    if outs:
                        yield HostBatch.concat(outs)
            return run
        return [make(t) for t in self.child.partitions()]
