"""User-facing Column API and function constructors (pyspark.sql.functions
shape). Handles binary-op type coercion by inserting Casts, like Spark's
TypeCoercion rules, so expression trees are fully typed at construction.
"""

from __future__ import annotations

from typing import Any, List, Optional, Union

from spark_rapids_tpu.sql import types as T
from spark_rapids_tpu.sql import expressions as E


class Column:
    def __init__(self, expr: E.Expression):
        self.expr = expr

    # -- naming
    def alias(self, name: str) -> "Column":
        return Column(E.Alias(self.expr, name))

    name = alias

    # -- arithmetic with coercion
    def _bin(self, other: Any, cls, swap: bool = False) -> "Column":
        o = _to_expr(other)
        a, b = (o, self.expr) if swap else (self.expr, o)
        # ONLY +,-,*,/ use DecimalPrecision's no-widen operand rule;
        # %/pmod (and comparisons) coerce to a common wider decimal
        a, b = _coerce_pair(a, b, arith=issubclass(
            cls, (E.Add, E.Subtract, E.Multiply, E.Divide)))
        return Column(cls(a, b))

    def __add__(self, other):
        return self._bin(other, E.Add)

    def __radd__(self, other):
        return self._bin(other, E.Add, swap=True)

    def __sub__(self, other):
        return self._bin(other, E.Subtract)

    def __rsub__(self, other):
        return self._bin(other, E.Subtract, swap=True)

    def __mul__(self, other):
        return self._bin(other, E.Multiply)

    def __rmul__(self, other):
        return self._bin(other, E.Multiply, swap=True)

    def __truediv__(self, other):
        return _divide(self.expr, _to_expr(other))

    def __rtruediv__(self, other):
        return _divide(_to_expr(other), self.expr)

    def __mod__(self, other):
        return self._bin(other, E.Remainder)

    def __neg__(self):
        return Column(E.UnaryMinus(self.expr))

    # -- comparisons
    def __eq__(self, other):  # type: ignore[override]
        return self._bin(other, E.EqualTo)

    def __ne__(self, other):  # type: ignore[override]
        return Column(E.Not(self._bin(other, E.EqualTo).expr))

    def __lt__(self, other):
        return self._bin(other, E.LessThan)

    def __le__(self, other):
        return self._bin(other, E.LessThanOrEqual)

    def __gt__(self, other):
        return self._bin(other, E.GreaterThan)

    def __ge__(self, other):
        return self._bin(other, E.GreaterThanOrEqual)

    def eqNullSafe(self, other):
        return self._bin(other, E.EqualNullSafe)

    # -- logic
    def __and__(self, other):
        return Column(E.And(self.expr, _to_expr(other)))

    def __or__(self, other):
        return Column(E.Or(self.expr, _to_expr(other)))

    def __invert__(self):
        return Column(E.Not(self.expr))

    # -- null / membership
    def isNull(self):
        return Column(E.IsNull(self.expr))

    def isNotNull(self):
        return Column(E.IsNotNull(self.expr))

    def isin(self, *values):
        items = [_to_expr(v) for v in
                 (values[0] if len(values) == 1
                  and isinstance(values[0], (list, tuple)) else values)]
        return Column(E.In(self.expr, items))

    def getItem(self, key) -> "Column":
        return Column(E.GetArrayItem(self.expr, _to_expr(key)))

    def __getitem__(self, key) -> "Column":
        return self.getItem(key)

    def bitwiseAND(self, other) -> "Column":
        return Column(E.BitwiseAnd(self.expr, _to_expr(other)))

    def bitwiseOR(self, other) -> "Column":
        return Column(E.BitwiseOr(self.expr, _to_expr(other)))

    def bitwiseXOR(self, other) -> "Column":
        return Column(E.BitwiseXor(self.expr, _to_expr(other)))

    # -- casts & misc
    def cast(self, dtype: Union[T.DataType, str]) -> "Column":
        return Column(E.Cast(self.expr, _parse_type(dtype)))

    astype = cast

    def substr(self, pos, length):
        return Column(E.Substring(self.expr, _to_expr(pos),
                                  _to_expr(length)))

    def startswith(self, other):
        return Column(E.StartsWith(self.expr, _to_expr(other)))

    def endswith(self, other):
        return Column(E.EndsWith(self.expr, _to_expr(other)))

    def contains(self, other):
        return Column(E.Contains(self.expr, _to_expr(other)))

    def like(self, pattern: str):
        return Column(E.Like(self.expr, E.Literal(pattern)))

    def rlike(self, pattern: str):
        return Column(E.RLike(self.expr, E.Literal(pattern)))

    def getField(self, name: str):
        return Column(E.GetStructField(self.expr, name=name))

    def between(self, low, high):
        return (self >= low) & (self <= high)

    # -- window
    def over(self, spec: "WindowSpec") -> "Column":
        return Column(E.WindowExpression(
            self.expr, spec._partition, spec._order, spec._frame))

    # -- sort orders
    def asc(self):
        return Column(E.SortOrder(self.expr, ascending=True))

    def desc(self):
        return Column(E.SortOrder(self.expr, ascending=False))

    def asc_nulls_first(self):
        return Column(E.SortOrder(self.expr, True, nulls_first=True))

    def asc_nulls_last(self):
        return Column(E.SortOrder(self.expr, True, nulls_first=False))

    def desc_nulls_first(self):
        return Column(E.SortOrder(self.expr, False, nulls_first=True))

    def desc_nulls_last(self):
        return Column(E.SortOrder(self.expr, False, nulls_first=False))

    def when(self, condition: "Column", value) -> "Column":
        raise TypeError("use functions.when(...) to start a CASE expression")

    def otherwise(self, value) -> "Column":
        expr = self.expr
        if not isinstance(expr, E.CaseWhen) or expr.has_else:
            raise TypeError("otherwise() follows when()")
        branches = [(expr.children[i], expr.children[i + 1])
                    for i in range(0, len(expr.children), 2)]
        return Column(E.CaseWhen(branches, _to_expr(value)))

    def __repr__(self):
        return f"Column<{self.expr!r}>"


def _to_expr(v: Any) -> E.Expression:
    if isinstance(v, Column):
        return v.expr
    if isinstance(v, E.Expression):
        return v
    return E.Literal(v)


def _expr_type(e: E.Expression) -> Optional[T.DataType]:
    try:
        return e.data_type
    except Exception:
        return None  # unresolved; coercion re-checked at plan build


def _coerce_pair(a: E.Expression, b: E.Expression, arith: bool = False):
    ta, tb = _expr_type(a), _expr_type(b)
    if ta is None or tb is None or ta == tb:
        return a, b
    if arith and (isinstance(ta, T.DecimalType)
                  or isinstance(tb, T.DecimalType)):
        # Spark DecimalPrecision: arithmetic operands are NOT widened to
        # a common decimal (that would change mul/div result types);
        # integrals lift to their exact decimal, fractionals win whole
        if isinstance(ta, (T.FloatType, T.DoubleType)) or \
                isinstance(tb, (T.FloatType, T.DoubleType)):
            return (a if isinstance(ta, T.DoubleType)
                    else E.Cast(a, T.DoubleT),
                    b if isinstance(tb, T.DoubleType)
                    else E.Cast(b, T.DoubleT))
        if not isinstance(ta, T.DecimalType) and T.is_integral(ta):
            a = E.Cast(a, T.decimal_for_integral(ta))
        if not isinstance(tb, T.DecimalType) and T.is_integral(tb):
            b = E.Cast(b, T.decimal_for_integral(tb))
        return a, b
    common = T.tightest_common_type(ta, tb)
    if common is None:
        return a, b
    if ta != common:
        a = E.Cast(a, common)
    if tb != common:
        b = E.Cast(b, common)
    return a, b


def _divide(a: E.Expression, b: E.Expression) -> Column:
    """Spark: `/` on non-decimal operands is double division."""
    ta, tb = _expr_type(a), _expr_type(b)
    if ta is None or tb is None:
        # unresolved: the post-resolution coercion pass (dataframe
        # _coerce_resolved) applies the double-vs-decimal rule
        return Column(E.Divide(a, b))
    if isinstance(ta, T.DecimalType) or isinstance(tb, T.DecimalType):
        a2, b2 = _coerce_pair(a, b, arith=True)
        return Column(E.Divide(a2, b2))
    if not isinstance(ta, T.DoubleType):
        a = E.Cast(a, T.DoubleT)
    if not isinstance(tb, T.DoubleType):
        b = E.Cast(b, T.DoubleT)
    return Column(E.Divide(a, b))


_TYPE_NAMES = {
    "boolean": T.BooleanT, "bool": T.BooleanT,
    "tinyint": T.ByteT, "byte": T.ByteT,
    "smallint": T.ShortT, "short": T.ShortT,
    "int": T.IntegerT, "integer": T.IntegerT,
    "bigint": T.LongT, "long": T.LongT,
    "float": T.FloatT, "double": T.DoubleT,
    "string": T.StringT, "binary": T.BinaryT,
    "date": T.DateT, "timestamp": T.TimestampT,
}


def split_top_level(s: str, sep: str = ",") -> List[str]:
    """Split on ``sep`` at nesting depth 0 (ignoring separators inside
    <> and ()); shared by the DDL schema parser and struct/map type
    strings."""
    parts: List[str] = []
    depth = 0
    cur = ""
    for ch in s:
        if ch == sep and depth == 0:
            parts.append(cur)
            cur = ""
            continue
        if ch in "(<":
            depth += 1
        elif ch in ")>":
            depth -= 1
        cur += ch
    if cur.strip():
        parts.append(cur)
    return parts


def _parse_type(dt: Union[T.DataType, str]) -> T.DataType:
    if isinstance(dt, T.DataType):
        return dt
    orig = dt.strip()
    s = orig.lower()
    if s in _TYPE_NAMES:
        return _TYPE_NAMES[s]
    if s.startswith("decimal"):
        if "(" in s:
            inner = s[s.index("(") + 1: s.index(")")]
            p, sc = inner.split(",")
            return T.DecimalType(int(p), int(sc))
        return T.DecimalType(10, 0)
    # nested types parse from the ORIGINAL string: field names keep case
    if s.startswith("array<") and s.endswith(">"):
        return T.ArrayType(_parse_type(orig[6:-1]))
    if s.startswith("struct<") and s.endswith(">"):
        out = []
        for f in split_top_level(orig[7:-1]):
            name, _, tp = f.strip().partition(":")
            out.append(T.StructField(name.strip(), _parse_type(tp.strip())))
        return T.StructType(out)
    if s.startswith("map<") and s.endswith(">"):
        kv = split_top_level(orig[4:-1])
        if len(kv) == 2:
            return T.MapType(_parse_type(kv[0]), _parse_type(kv[1]))
    raise ValueError(f"unknown type string {dt!r}")




def _to_col_expr(c: Any) -> E.Expression:
    """In function position, a bare string names a column (pyspark
    convention); elsewhere strings are literals."""
    if isinstance(c, str):
        return E.UnresolvedAttribute(c)
    return _to_expr(c)

# ---------------------------------------------------------------------------
# functions
# ---------------------------------------------------------------------------

def col(name: str) -> Column:
    return Column(E.UnresolvedAttribute(name))


column = col


def lit(v: Any) -> Column:
    return Column(E.Literal(v))


def expr_col(e: E.Expression) -> Column:
    return Column(e)


def when(condition: Column, value) -> Column:
    return Column(E.CaseWhen([(_to_expr(condition), _to_expr(value))], None))


def coalesce(*cols) -> Column:
    return Column(E.Coalesce([_to_col_expr(c) for c in cols]))


def isnull(c) -> Column:
    return Column(E.IsNull(_to_col_expr(c)))


def isnan(c) -> Column:
    return Column(E.IsNan(_to_col_expr(c)))


# aggregates
def _agg(fn: E.AggregateFunction) -> Column:
    return Column(E.AggregateExpression(fn))


def sum(c) -> Column:  # noqa: A001 - mirrors pyspark.sql.functions
    return _agg(E.Sum(_to_col_expr(c)))


def count(c="*") -> Column:
    if isinstance(c, str) and c == "*":
        return _agg(E.Count([]))
    return _agg(E.Count([_to_col_expr(c)]))


def avg(c) -> Column:
    return _agg(E.Average(_to_col_expr(c)))


mean = avg


def _parse_duration_us(s: str) -> int:
    import re as _re
    m = _re.fullmatch(
        r"\s*(\d+)\s*(microsecond|millisecond|second|minute|hour|day|"
        r"week)s?\s*", s)
    if not m:
        raise ValueError(f"cannot parse interval {s!r}")
    n = int(m.group(1))
    mult = {"microsecond": 1, "millisecond": 1000, "second": 10**6,
            "minute": 60 * 10**6, "hour": 3600 * 10**6,
            "day": 86400 * 10**6, "week": 7 * 86400 * 10**6}[m.group(2)]
    return n * mult


def window(c, windowDuration: str, slideDuration=None,
           startTime=None) -> Column:
    """Tumbling time window: struct<start, end> (Spark TimeWindow;
    sliding windows are unsupported)."""
    w = _parse_duration_us(windowDuration)
    if w <= 0:
        raise ValueError("window duration must be positive")
    if slideDuration is not None and \
            _parse_duration_us(slideDuration) != w:
        raise NotImplementedError(
            "sliding time windows (slide != duration) are not supported")
    start = _parse_duration_us(startTime) if startTime else 0
    return Column(E.TimeWindow(_to_col_expr(c), w, start))


def struct(*cols) -> Column:
    exprs = [_to_col_expr(c) for c in cols]
    names = [getattr(e, "name", None) or f"col{i + 1}"
             for i, e in enumerate(exprs)]
    return Column(E.CreateNamedStruct(names, exprs))


def named_struct(*name_col_pairs) -> Column:
    names = [str(x) for x in name_col_pairs[0::2]]
    exprs = [_to_col_expr(c) for c in name_col_pairs[1::2]]
    return Column(E.CreateNamedStruct(names, exprs))


def monotonically_increasing_id() -> Column:
    return Column(E.MonotonicallyIncreasingID())


def spark_partition_id() -> Column:
    return Column(E.SparkPartitionID())


def input_file_name() -> Column:
    return Column(E.InputFileName())


def collect_list(c) -> Column:
    return _agg(E.CollectList(_to_col_expr(c)))


def collect_set(c) -> Column:
    return _agg(E.CollectSet(_to_col_expr(c)))


def stddev_samp(c) -> Column:
    return _agg(E.StddevSamp(_to_col_expr(c)))


stddev = stddev_samp


def stddev_pop(c) -> Column:
    return _agg(E.StddevPop(_to_col_expr(c)))


def var_samp(c) -> Column:
    return _agg(E.VarianceSamp(_to_col_expr(c)))


variance = var_samp


def var_pop(c) -> Column:
    return _agg(E.VariancePop(_to_col_expr(c)))


def min(c) -> Column:  # noqa: A001
    return _agg(E.Min(_to_col_expr(c)))


def max(c) -> Column:  # noqa: A001
    return _agg(E.Max(_to_col_expr(c)))


def first(c, ignorenulls: bool = False) -> Column:
    return _agg(E.First(_to_col_expr(c), ignorenulls))


def last(c, ignorenulls: bool = False) -> Column:
    return _agg(E.Last(_to_col_expr(c), ignorenulls))


def countDistinct(c) -> Column:
    return Column(E.AggregateExpression(E.Count([_to_col_expr(c)]),
                                        is_distinct=True))


# math
def sqrt(c) -> Column:
    return Column(E.Sqrt(_to_col_expr(c)))


def exp(c) -> Column:
    return Column(E.Exp(_to_col_expr(c)))


def log(c) -> Column:
    return Column(E.Log(_to_col_expr(c)))


def log10(c) -> Column:
    return Column(E.Log10(_to_col_expr(c)))


def abs(c) -> Column:  # noqa: A001
    return Column(E.Abs(_to_col_expr(c)))


def floor(c) -> Column:
    return Column(E.Floor(_to_col_expr(c)))


def ceil(c) -> Column:
    return Column(E.Ceil(_to_col_expr(c)))


def pow(a, b) -> Column:  # noqa: A001
    return Column(E.Pow(E.Cast(_to_col_expr(a), T.DoubleT),
                        E.Cast(_to_col_expr(b), T.DoubleT)))


def round(c, scale: int = 0) -> Column:  # noqa: A001
    return Column(E.Round(_to_col_expr(c), E.Literal(scale)))


def signum(c) -> Column:
    return Column(E.Signum(_to_col_expr(c)))


def sin(c) -> Column:
    return Column(E.Sin(_to_col_expr(c)))


def cos(c) -> Column:
    return Column(E.Cos(_to_col_expr(c)))


def tan(c) -> Column:
    return Column(E.Tan(_to_col_expr(c)))


# strings
def upper(c) -> Column:
    return Column(E.Upper(_to_col_expr(c)))


def lower(c) -> Column:
    return Column(E.Lower(_to_col_expr(c)))


def length(c) -> Column:
    return Column(E.Length(_to_col_expr(c)))


def trim(c) -> Column:
    return Column(E.StringTrim(_to_col_expr(c)))


def substring(c, pos: int, length_: int) -> Column:
    return Column(E.Substring(_to_col_expr(c), E.Literal(pos),
                              E.Literal(length_)))


def concat(*cols) -> Column:
    return Column(E.ConcatStr([_to_col_expr(c) for c in cols]))


# datetime
def year(c) -> Column:
    return Column(E.Year(_to_col_expr(c)))


def month(c) -> Column:
    return Column(E.Month(_to_col_expr(c)))


def dayofmonth(c) -> Column:
    return Column(E.DayOfMonth(_to_col_expr(c)))


def hour(c) -> Column:
    return Column(E.Hour(_to_col_expr(c)))


def minute(c) -> Column:
    return Column(E.Minute(_to_col_expr(c)))


def second(c) -> Column:
    return Column(E.Second(_to_col_expr(c)))


def date_add(c, days) -> Column:
    return Column(E.DateAdd(_to_col_expr(c), _to_col_expr(days)))


def date_sub(c, days) -> Column:
    return Column(E.DateSub(_to_col_expr(c), _to_col_expr(days)))


def datediff(end, start) -> Column:
    return Column(E.DateDiff(_to_col_expr(end), _to_col_expr(start)))


def hash(*cols) -> Column:  # noqa: A001
    return Column(E.Murmur3Hash([_to_col_expr(c) for c in cols]))


def xxhash64(*cols) -> Column:
    return Column(E.XxHash64([_to_col_expr(c) for c in cols]))


# collections / generators
def array(*cols) -> Column:
    return Column(E.CreateArray([_to_col_expr(c) for c in cols]))


def size(c) -> Column:
    return Column(E.Size(_to_col_expr(c)))


def element_at(c, idx) -> Column:
    return Column(E.ElementAt(_to_col_expr(c), _to_expr(idx)))


def array_contains(c, value) -> Column:
    return Column(E.ArrayContains(_to_col_expr(c), _to_expr(value)))


def explode(c) -> Column:
    return Column(E.Explode(_to_col_expr(c)))


def explode_outer(c) -> Column:
    return Column(E.Explode(_to_col_expr(c), outer=True))


def posexplode(c) -> Column:
    return Column(E.Explode(_to_col_expr(c), position=True))


def posexplode_outer(c) -> Column:
    return Column(E.Explode(_to_col_expr(c), position=True, outer=True))


# bitwise
def shiftleft(c, n) -> Column:
    return Column(E.ShiftLeft(_to_col_expr(c), _to_expr(n)))


def shiftright(c, n) -> Column:
    return Column(E.ShiftRight(_to_col_expr(c), _to_expr(n)))


def shiftrightunsigned(c, n) -> Column:
    return Column(E.ShiftRightUnsigned(_to_col_expr(c), _to_expr(n)))


def bitwise_not(c) -> Column:
    return Column(E.BitwiseNot(_to_col_expr(c)))


# more math
def log2(c) -> Column:
    return Column(E.Log2(_to_col_expr(c)))


def log1p(c) -> Column:
    return Column(E.Log1p(_to_col_expr(c)))


def expm1(c) -> Column:
    return Column(E.Expm1(_to_col_expr(c)))


def cbrt(c) -> Column:
    return Column(E.Cbrt(_to_col_expr(c)))


def rint(c) -> Column:
    return Column(E.Rint(_to_col_expr(c)))


def degrees(c) -> Column:
    return Column(E.ToDegrees(_to_col_expr(c)))


def radians(c) -> Column:
    return Column(E.ToRadians(_to_col_expr(c)))


def atan2(a, b) -> Column:
    return Column(E.Atan2(E.Cast(_to_col_expr(a), T.DoubleT),
                          E.Cast(_to_col_expr(b), T.DoubleT)))


def hypot(a, b) -> Column:
    return Column(E.Hypot(E.Cast(_to_col_expr(a), T.DoubleT),
                          E.Cast(_to_col_expr(b), T.DoubleT)))


def greatest(*cols) -> Column:
    return Column(E.Greatest([_to_col_expr(c) for c in cols]))


def least(*cols) -> Column:
    return Column(E.Least([_to_col_expr(c) for c in cols]))


# more strings
def concat_ws(sep: str, *cols) -> Column:
    return Column(E.ConcatWs([E.Literal(sep)]
                             + [_to_col_expr(c) for c in cols]))


def repeat(c, n: int) -> Column:
    return Column(E.StringRepeat(_to_col_expr(c), E.Literal(n)))


def lpad(c, length_: int, pad: str) -> Column:
    return Column(E.StringLPad(_to_col_expr(c), E.Literal(length_),
                               E.Literal(pad)))


def rpad(c, length_: int, pad: str) -> Column:
    return Column(E.StringRPad(_to_col_expr(c), E.Literal(length_),
                               E.Literal(pad)))


def translate(c, matching: str, replace: str) -> Column:
    return Column(E.StringTranslate(_to_col_expr(c), E.Literal(matching),
                                    E.Literal(replace)))


def regexp_replace(c, pattern: str, replacement: str) -> Column:
    # literal (non-regex) patterns only would be StringReplace; the
    # regex engine is not implemented yet
    raise NotImplementedError("regexp_replace is not implemented")


def replace(c, search, replacement="") -> Column:
    return Column(E.StringReplace(_to_col_expr(c), _to_expr(search),
                                  _to_expr(replacement)))


def instr(c, substr: str) -> Column:
    return Column(E.StringInstr(_to_col_expr(c), E.Literal(substr)))


def locate(substr: str, c, pos: int = 1) -> Column:
    return Column(E.StringLocate(E.Literal(substr), _to_col_expr(c),
                                 E.Literal(pos)))


def split(c, pattern: str, limit: int = -1) -> Column:
    return Column(E.StringSplit(_to_col_expr(c), E.Literal(pattern),
                                E.Literal(limit)))


def regexp_replace(c, pattern: str, replacement: str) -> Column:
    return Column(E.RegExpReplace(_to_col_expr(c), E.Literal(pattern),
                                  E.Literal(replacement)))


def regexp_extract(c, pattern: str, idx: int) -> Column:
    return Column(E.RegExpExtract(_to_col_expr(c), E.Literal(pattern),
                                  E.Literal(idx)))


def initcap(c) -> Column:
    return Column(E.InitCap(_to_col_expr(c)))


def reverse(c) -> Column:
    return Column(E.StringReverse(_to_col_expr(c)))


def ltrim(c) -> Column:
    return Column(E.StringTrimLeft(_to_col_expr(c)))


def rtrim(c) -> Column:
    return Column(E.StringTrimRight(_to_col_expr(c)))


def ascii(c) -> Column:
    return Column(E.Ascii(_to_col_expr(c)))


def chr(c) -> Column:  # noqa: A001
    return Column(E.Chr(_to_col_expr(c)))


# more datetime
def quarter(c) -> Column:
    return Column(E.Quarter(_to_col_expr(c)))


def dayofweek(c) -> Column:
    return Column(E.DayOfWeek(_to_col_expr(c)))


def weekday(c) -> Column:
    return Column(E.WeekDay(_to_col_expr(c)))


def dayofyear(c) -> Column:
    return Column(E.DayOfYear(_to_col_expr(c)))


def weekofyear(c) -> Column:
    return Column(E.WeekOfYear(_to_col_expr(c)))


def last_day(c) -> Column:
    return Column(E.LastDay(_to_col_expr(c)))


def add_months(c, months) -> Column:
    return Column(E.AddMonths(_to_col_expr(c), _to_expr(months)))


def months_between(end, start) -> Column:
    return Column(E.MonthsBetween(_to_col_expr(end), _to_col_expr(start)))


def trunc(c, fmt: str) -> Column:
    return Column(E.TruncDate(_to_col_expr(c), E.Literal(fmt)))


def date_format(c, fmt: str) -> Column:
    return Column(E.DateFormatClass(_to_col_expr(c), E.Literal(fmt)))


def unix_timestamp(c, fmt: str = "yyyy-MM-dd HH:mm:ss") -> Column:
    return Column(E.UnixTimestamp(_to_col_expr(c), E.Literal(fmt)))


def from_unixtime(c, fmt: str = "yyyy-MM-dd HH:mm:ss") -> Column:
    return Column(E.FromUnixTime(_to_col_expr(c), E.Literal(fmt)))


def to_date(c, fmt: Optional[str] = None) -> Column:
    if fmt is None:
        return Column(E.Cast(_to_col_expr(c), T.DateT))
    return Column(E.Cast(E.GetTimestamp(_to_col_expr(c), E.Literal(fmt)),
                         T.DateT))


def to_timestamp(c, fmt: Optional[str] = None) -> Column:
    if fmt is None:
        return Column(E.Cast(_to_col_expr(c), T.TimestampT))
    return Column(E.GetTimestamp(_to_col_expr(c), E.Literal(fmt)))


# ---------------------------------------------------------------------------
# Window API (pyspark.sql.window.Window / WindowSpec shape)
# ---------------------------------------------------------------------------

class WindowSpec:
    def __init__(self, partition_spec=None, order_spec=None, frame=None):
        self._partition = list(partition_spec or [])
        self._order = list(order_spec or [])
        self._frame = frame

    def partitionBy(self, *cols) -> "WindowSpec":
        exprs = [_to_expr(c if not isinstance(c, str) else col(c))
                 for c in cols]
        return WindowSpec(exprs, self._order, self._frame)

    def orderBy(self, *cols) -> "WindowSpec":
        order = []
        for c in cols:
            e = _to_expr(c if not isinstance(c, str) else col(c))
            order.append(e if isinstance(e, E.SortOrder)
                         else E.SortOrder(e, ascending=True))
        return WindowSpec(self._partition, order, self._frame)

    def rowsBetween(self, start: int, end: int) -> "WindowSpec":
        lo = None if start <= Window.unboundedPreceding else int(start)
        hi = None if end >= Window.unboundedFollowing else int(end)
        return WindowSpec(self._partition, self._order,
                          E.WindowFrame("rows", lo, hi))

    def rangeBetween(self, start: int, end: int) -> "WindowSpec":
        lo = None if start <= Window.unboundedPreceding else int(start)
        hi = None if end >= Window.unboundedFollowing else int(end)
        # (None, 0) is the running-with-peers frame; any finite offset
        # makes a VALUE-bounded range frame (requires a single numeric
        # order expression, checked at evaluation like Spark's
        # RangeFrame resolution)
        return WindowSpec(self._partition, self._order,
                          E.WindowFrame("range", lo, hi))


class Window:
    """pyspark.sql.Window twin (static constructors)."""

    unboundedPreceding = -(1 << 63)
    unboundedFollowing = (1 << 63)
    currentRow = 0

    @staticmethod
    def partitionBy(*cols) -> WindowSpec:
        return WindowSpec().partitionBy(*cols)

    @staticmethod
    def orderBy(*cols) -> WindowSpec:
        return WindowSpec().orderBy(*cols)

    @staticmethod
    def rowsBetween(start: int, end: int) -> WindowSpec:
        return WindowSpec().rowsBetween(start, end)

    @staticmethod
    def rangeBetween(start: int, end: int) -> WindowSpec:
        return WindowSpec().rangeBetween(start, end)


def row_number() -> Column:
    return Column(E.RowNumber())


def rank() -> Column:
    return Column(E.Rank())


def dense_rank() -> Column:
    return Column(E.DenseRank())


def ntile(n: int) -> Column:
    return Column(E.NTile(int(n)))


def lag(c, offset: int = 1, default=None) -> Column:
    e = _to_expr(col(c) if isinstance(c, str) else c)
    d = None if default is None else _to_expr(lit(default))
    return Column(E.Lag(e, int(offset), d))


def lead(c, offset: int = 1, default=None) -> Column:
    e = _to_expr(col(c) if isinstance(c, str) else c)
    d = None if default is None else _to_expr(lit(default))
    return Column(E.Lead(e, int(offset), d))


def pandas_udf(f=None, returnType=None):
    """pyspark.sql.functions.pandas_udf twin (SCALAR evalType): the
    function receives pandas Series and returns a Series. Evaluated
    vectorized through the python worker pool (Arrow IPC) by
    ArrowEvalPythonExec — on the TPU session the surrounding plan stays
    on device (GpuArrowEvalPythonExec.scala:487 role)."""
    if f is not None and not callable(f):
        f, returnType = None, f
    if returnType is None:
        # pyspark requires a return type for SCALAR pandas UDFs too —
        # silently defaulting would coerce results to the wrong type
        raise ValueError("pandas_udf requires a returnType, e.g. "
                         "@pandas_udf('long')")
    rt = _parse_type(returnType)

    def wrap(fn):
        def call(*cols) -> Column:
            exprs = [_to_expr(col(c) if isinstance(c, str) else c)
                     for c in cols]
            return Column(E.PandasUDF(
                fn, getattr(fn, "__name__", "pandas_udf"), rt, exprs))
        return call
    if f is not None:
        return wrap(f)
    return wrap


def udf(f=None, returnType=None):
    """pyspark.sql.functions.udf twin: a host-evaluated Python UDF. The
    plan rewrite reports it NOT_ON_GPU (same placement the reference
    gives un-compiled UDFs; its udf-compiler translates a Scala subset —
    arbitrary Python bodies stay on the CPU here too)."""
    # pyspark form @udf("int"): a non-callable first positional arg is
    # the return type
    if f is not None and not callable(f):
        f, returnType = None, f
    rt = _parse_type(returnType) if returnType is not None else T.StringT

    def wrap(fn):
        def call(*cols) -> Column:
            exprs = [_to_expr(col(c) if isinstance(c, str) else c)
                     for c in cols]
            return Column(E.PythonUDF(fn, getattr(fn, "__name__", "udf"),
                                      rt, exprs))
        return call
    if f is not None:
        return wrap(f)
    return wrap
