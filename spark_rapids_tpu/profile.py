"""Per-query profile artifacts: EXPLAIN-ANALYZE plan reports with
per-operator HBM accounting and fallback attribution.

The reference exposes two flagship observability surfaces — the
plan-rewrite explain (``spark.rapids.sql.explain``, every
willNotWorkOnGpu reason surfaced) and per-operator GPU metrics in the
SQL UI. This module unifies their equivalents into ONE structured
artifact per executed query, written as ``profile-<pid>-q<n>.json``
under ``spark.rapids.sql.profile.dir``:

- **plan**: the final physical tree, each node annotated with its full
  metric registry (zero values included — the event-log v2 contract),
  device placement, fused-stage constituents, jit-cache hit/miss and
  retry/spill counters;
- **memory**: the DeviceStore pool watermarks plus the owner-attributed
  per-operator HBM ledger (live/peak bytes per registering exec —
  memory.py threads the owner tag through ``TpuExec.register_spillable``);
- **explain**: the finished RewriteReport — device ops, fallbacks with
  expression-level reasons, operator coverage, reason histogram;
- **conf**: the session's explicit settings (enough to re-run the
  query's configuration offline).

``python -m spark_rapids_tpu.tools profile <file-or-dir>`` renders the
artifact as an annotated plan tree plus top-memory-consumers and
fallback-summary tables (docs/observability.md "Reading a query
profile"). Profile writing never raises — observability must not take
down execution — and costs nothing when disabled (one conf check after
the query completes; the metrics it serializes are maintained anyway).
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Dict, Iterator, List, Optional

from spark_rapids_tpu.conf import conf

PROFILE_ENABLED = conf("spark.rapids.sql.profile.enabled").doc(
    "Write one structured profile artifact per executed query "
    "(profile-<pid>-q<n>.json under spark.rapids.sql.profile.dir): the "
    "annotated physical plan with every operator's metrics, the "
    "owner-attributed HBM accounting (per-operator live/peak bytes "
    "against the device-store pool watermarks), and the plan-rewrite "
    "explain (fallbacks with reasons, operator coverage). Render with "
    "`python -m spark_rapids_tpu.tools profile <file-or-dir>` "
    "(docs/observability.md).").boolean(False)

PROFILE_DIR = conf("spark.rapids.sql.profile.dir").doc(
    "Directory for per-query profile artifacts "
    "(profile-<pid>-q<n>.json).").string("/tmp/srt_profiles")

PROFILE_VERSION = 1


# ---------------------------------------------------------------------------
# Artifact construction
# ---------------------------------------------------------------------------

def _node_entry(p) -> Dict[str, Any]:
    """One plan node as a JSON-ready dict; recursive over children,
    fused-stage constituents listed SHALLOW under their stage (their
    child links point back into the chain)."""
    from spark_rapids_tpu.exec.base import TpuExec
    entry: Dict[str, Any] = {
        "op": type(p).__name__,
        "simpleString": p.simple_string(),
        "device": isinstance(p, TpuExec),
    }
    m = getattr(p, "metrics", None)
    if m is not None:
        # ALL created metrics, zero-valued included: 0 output rows is
        # distinguishable from a metric that never existed
        entry["metrics"] = {k: v.value for k, v in m.metrics.items()}
    fused = []
    for op in getattr(p, "fused_ops", []):
        fe: Dict[str, Any] = {"op": type(op).__name__,
                              "simpleString": op.simple_string(),
                              "device": True}
        fm = getattr(op, "metrics", None)
        if fm is not None:
            fe["metrics"] = {k: v.value for k, v in fm.metrics.items()}
        fused.append(fe)
    if fused:
        entry["fused"] = fused
    entry["children"] = [_node_entry(c)
                         for c in getattr(p, "children", [])]
    return entry


def _kernel_summary(physical) -> Dict[str, Dict[str, int]]:
    """Top-level kernel-tier attribution (docs/kernels.md): per-kernel
    dispatch and fallback counts summed across the executed plan, so a
    query that silently rode the XLA-op oracle path (fallbacks > 0, or
    zero dispatches with the tier enabled) is visible in the artifact
    header without grepping per-node metrics."""
    out: Dict[str, Dict[str, int]] = {"dispatches": {}, "fallbacks": {}}

    def add(p) -> None:
        m = getattr(p, "metrics", None)
        if m is None:
            return
        for k, metric in m.metrics.items():
            if not metric.value:
                continue
            for prefix, bucket in (("kernelDispatchCount.",
                                    "dispatches"),
                                   ("kernelFallbacks.", "fallbacks")):
                if k.startswith(prefix):
                    name = k[len(prefix):]
                    out[bucket][name] = \
                        out[bucket].get(name, 0) + metric.value

    def walk(p) -> None:
        add(p)
        for op in getattr(p, "fused_ops", []):
            add(op)
        for c in getattr(p, "children", []):
            walk(c)

    walk(physical)
    return out


def build_profile(physical, report, conf_obj, wall_s: float, rows: int,
                  query_id: int) -> Dict[str, Any]:
    """Assemble the artifact dict from an EXECUTED plan (its registries
    carry the run's metrics), the rewrite report, and the process
    store's ledgers."""
    from spark_rapids_tpu import memory
    from spark_rapids_tpu.jit_cache import cache_stats
    store = memory._STORE
    prof: Dict[str, Any] = {
        "version": PROFILE_VERSION,
        "queryId": query_id,
        "ts": time.time(),
        "wallSeconds": round(wall_s, 6),
        "outputRows": rows,
        "plan": _node_entry(physical),
        "memory": {
            "pool": store.stats() if store is not None else {},
            "operators": (store.owner_stats()
                          if store is not None else {}),
            "tenants": (store.tenant_stats()
                        if store is not None else {}),
        },
        "kernels": _kernel_summary(physical),
        "jitCaches": cache_stats(),
    }
    if conf_obj is not None:
        from spark_rapids_tpu.conf import SERVE_TENANT_ID
        tenant = str(conf_obj.get(SERVE_TENANT_ID))
        if tenant:
            # serving tenancy: the artifact names the tenant the query
            # executed for (matches the event-log line's field)
            prof["tenant"] = tenant
    if report is not None:
        prof["explain"] = report.summary()
    if conf_obj is not None:
        prof["conf"] = {k: str(v) for k, v
                        in sorted(conf_obj.settings.items())}
    return prof


def write_profile(conf_obj, physical, report, wall_s: float,
                  rows: int, query_id: Optional[int] = None
                  ) -> Optional[str]:
    """Write one profile artifact when profiling is enabled; returns
    the path (None when disabled or on failure — a profile write must
    never break the query). ``query_id`` is the caller-allocated
    process query sequence (event_log.next_query_id), so the artifact
    and the event-log line for one query carry the SAME id."""
    try:
        if conf_obj is None or not bool(conf_obj.get(PROFILE_ENABLED)):
            return None
        from spark_rapids_tpu.event_log import next_query_id
        qid = query_id if query_id is not None else next_query_id()
        prof = build_profile(physical, report, conf_obj, wall_s, rows,
                             qid)
        prof_dir = str(conf_obj.get(PROFILE_DIR))
        os.makedirs(prof_dir, exist_ok=True)
        path = os.path.join(
            prof_dir, f"profile-{os.getpid()}-q{qid:05d}.json")
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(prof, f, default=str)
        os.replace(tmp, path)
        return path
    except Exception:
        return None


def read_profiles(path: str) -> Iterator[Dict[str, Any]]:
    """Load one profile-*.json file, or every one in a directory."""
    if os.path.isdir(path):
        files = sorted(
            os.path.join(path, f) for f in os.listdir(path)
            if f.startswith("profile-") and f.endswith(".json"))
    else:
        files = [path]
    for fp in files:
        with open(fp) as f:
            prof = json.load(f)
        prof["_file"] = fp
        yield prof


# ---------------------------------------------------------------------------
# Text rendering (the `tools profile` CLI)
# ---------------------------------------------------------------------------

# metrics shown inline on the tree (in this order) — the ones that
# answer "where did the time/memory go" at a glance; everything else
# prints in the per-node detail only when nonzero
_TREE_METRICS = (
    "numOutputRows", "opTime", "computeAggTime", "sortTime", "joinTime",
    "partitionTime", "copyToDeviceTime", "copyFromDeviceTime",
    "pipelineDrainTime", "peakDeviceMemory", "spillBytes", "retryCount",
    "splitRetryCount", "compileCacheHits", "compileCacheMisses",
    "dispatchCount",
)


def _fmt_bytes(n: int) -> str:
    for unit, div in (("GiB", 1 << 30), ("MiB", 1 << 20), ("KiB", 1 << 10)):
        if n >= div:
            return f"{n / div:.1f}{unit}"
    return f"{n}B"


def _fmt_metric(name: str, v: int) -> str:
    if name.endswith(("Time", "time")):
        return f"{name}={v / 1e9:.3f}s"
    if "Memory" in name or name.endswith(("Bytes", "bytes")):
        return f"{name}={_fmt_bytes(int(v))}"
    return f"{name}={v}"


def _render_node(entry: Dict[str, Any], lines: List[str],
                 indent: int) -> None:
    pad = " " * indent
    mark = "*" if entry.get("device") else " "
    lines.append(f"{pad}{mark} {entry.get('simpleString', entry['op'])}")
    ms = entry.get("metrics") or {}
    shown = [_fmt_metric(k, ms[k]) for k in _TREE_METRICS
             if ms.get(k)]
    # kernel-tier attribution rides in the headline list: a node whose
    # work went through (or fell back from) a Pallas kernel says so at
    # a glance (docs/kernels.md)
    shown += [_fmt_metric(k, v) for k, v in sorted(ms.items())
              if v and k.startswith(("kernelDispatchCount.",
                                     "kernelFallbacks."))]
    extra = [_fmt_metric(k, v) for k, v in sorted(ms.items())
             if v and k not in _TREE_METRICS
             and not k.startswith(("kernelDispatchCount.",
                                   "kernelFallbacks."))]
    for chunk in (shown, extra):
        if chunk:
            lines.append(pad + "    [" + ", ".join(chunk) + "]")
    for fe in entry.get("fused", []):
        lines.append(f"{pad}    : {fe.get('simpleString', fe['op'])}")
        fms = fe.get("metrics") or {}
        fshown = [_fmt_metric(k, fms[k]) for k in _TREE_METRICS
                  if fms.get(k)]
        fshown += [_fmt_metric(k, v) for k, v in sorted(fms.items())
                   if v and k.startswith(("kernelDispatchCount.",
                                          "kernelFallbacks."))]
        if fshown:
            lines.append(pad + "        [" + ", ".join(fshown) + "]")
    for c in entry.get("children", []):
        _render_node(c, lines, indent + 2)


def format_profile(prof: Dict[str, Any], top: int = 10) -> str:
    """Human-readable report: annotated plan tree, top memory
    consumers, fallback summary (docs/observability.md)."""
    lines = ["=== TPU Query Profile ===",
             f"file: {prof.get('_file', '-')}",
             f"query {prof.get('queryId')}: "
             f"{prof.get('wallSeconds', 0):.3f}s wall, "
             f"{prof.get('outputRows', 0)} rows", "",
             "annotated plan (* = on TPU):"]
    _render_node(prof.get("plan", {"op": "?"}), lines, 2)

    mem = prof.get("memory", {})
    pool = mem.get("pool", {})
    ops = mem.get("operators", {})
    lines += ["", "device memory (owner-attributed HBM accounting):",
              f"  pool: peak {_fmt_bytes(pool.get('peakDeviceBytes', 0))}"
              f", live {_fmt_bytes(pool.get('deviceBytes', 0))}, "
              f"{pool.get('spillCount', 0)} spills "
              f"({_fmt_bytes(pool.get('spilledDeviceBytes', 0))} demoted)"]
    ranked = sorted(ops.items(), key=lambda kv: -kv[1].get("peakBytes", 0))
    if ranked:
        lines.append(f"  {'top memory consumers':36s} "
                     f"{'peak':>10s} {'live':>10s}")
        for owner, st in ranked[:top]:
            lines.append(f"  {owner:36s} "
                         f"{_fmt_bytes(st.get('peakBytes', 0)):>10s} "
                         f"{_fmt_bytes(st.get('liveBytes', 0)):>10s}")
    else:
        lines.append("  (no operator registered spillable batches)")

    kern = prof.get("kernels") or {}
    disp = kern.get("dispatches") or {}
    fb = kern.get("fallbacks") or {}
    if disp or fb:
        parts = []
        if disp:
            parts.append("dispatches " + ", ".join(
                f"{k}={v}" for k, v in sorted(disp.items())))
        if fb:
            parts.append("FALLBACKS " + ", ".join(
                f"{k}={v}" for k, v in sorted(fb.items())))
        lines += ["", "kernel tier (docs/kernels.md): "
                  + "; ".join(parts)]
        if fb:
            lines.append("  (fallback calls rode the XLA-op oracle "
                         "composition — check kernel confs / "
                         "tableSlots)")

    ex = prof.get("explain")
    if ex:
        lines += ["", f"explain: {len(ex.get('deviceOps', []))} ops on "
                  f"TPU, {len(ex.get('fallbacks', []))} fallbacks "
                  f"({ex.get('coverage', 1.0):.0%} coverage)"]
        counts = ex.get("reasonCounts", {})
        if counts:
            lines.append("  fallback reasons (by frequency):")
            for r, c in sorted(counts.items(), key=lambda kv: -kv[1])[:top]:
                lines.append(f"    {c:4d}x {r}")
        for fb in ex.get("fallbacks", [])[:top]:
            lines.append(f"  !Exec <{fb['op']}> stayed on CPU")
    return "\n".join(lines)
