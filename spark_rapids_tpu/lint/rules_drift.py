"""Rule family 4 — drift unification (static promotion of the PR 5/6
runtime lints; docs/observability.md).

``metric-key``  — every literal (or metrics-constant) key passed to
                  ``create`` / ``timed`` / ``timed_wall`` must resolve
                  via ``describe_metric`` (exact entry or registered
                  prefix family), and every metric-name constant in
                  metrics.py must be described. Dynamic f-string keys
                  are invisible to the AST — the one remaining runtime
                  smoke in tests/test_profile.py guards those.
``conf-key``    — every whole-string ``spark.rapids.*`` literal in the
                  package must be a registered conf.py key (prefix
                  literals ending in '.' are exempt — they are
                  namespace matches, not keys).
``span-scope``  — every ``trace.span(...)`` open must be the context
                  expression of a ``with`` (an unclosed span corrupts
                  the B/E nesting of the whole lane).
``span-kind``   — every LITERAL span/instant kind recorded in the
                  package (``trace.span``/``trace.instant`` calls, and
                  the ``qt.add``/``qt.mark`` convention over the
                  active trace) must appear in trace.py's
                  ``SPAN_CATALOG``/``INSTANT_CATALOG``, so flight-
                  recorder dumps and trace files can never carry a
                  vocabulary the documentation doesn't (metric-mirror
                  spans are dynamic ``<Exec>.<metric>`` names and are
                  covered by ``metric-key`` instead).
``prom-family`` — every Prometheus family name the telemetry endpoint
                  emits (telemetry/prometheus.py ``_emit_server``
                  sites) must be a key of ``SERVER_FAMILY_HELP`` (the
                  table the observability doc renders) and match the
                  ``srt_[a-z0-9_]+`` naming rule; engine-metric
                  families are derived from registry keys, whose
                  describe_metric coverage the renderer enforces at
                  runtime (srt_undescribed_metric_keys must be 0).
``tuning-action`` — every action the TuningController constructs
                  (literal first argument of a ``_new_action`` call in
                  telemetry/tuning.py) must be an ``ACTION_CATALOG``
                  key, and every ``spark.rapids.*`` knob declared in
                  the catalog must be a registered conf key — the
                  self-tuning loop can only ever actuate the declared,
                  documented vocabulary (docs/tuning.md renders from
                  the same dict).
``docs-drift``  — docs/configs.md, docs/supported_ops.md,
                  docs/observability.md and docs/tuning.md must match
                  `tools docs` regeneration byte-for-byte.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Dict, List, Optional, Set, Tuple

from spark_rapids_tpu.lint import astutil as A
from spark_rapids_tpu.lint.engine import Finding, rule

_METRIC_SINKS = {"create", "timed", "timed_wall"}
_CONF_KEY_RE = re.compile(r"^spark\.rapids\.[A-Za-z0-9_.]*[A-Za-z0-9_]$")


# -- metrics table (parsed from metrics.py, no import) ---------------------

def _module_str_constants(fctx: A.FileCtx) -> Dict[str, str]:
    out: Dict[str, str] = {}
    for stmt in fctx.tree.body:
        if isinstance(stmt, ast.Assign) and isinstance(
                stmt.value, ast.Constant) and isinstance(
                stmt.value.value, str):
            for t in stmt.targets:
                if isinstance(t, ast.Name):
                    out[t.id] = stmt.value.value
    return out


def _dict_keys(fctx: A.FileCtx, name: str,
               consts: Dict[str, str]) -> Optional[Set[str]]:
    for stmt in fctx.tree.body:
        if isinstance(stmt, ast.Assign) or isinstance(stmt,
                                                      ast.AnnAssign):
            targets = stmt.targets if isinstance(stmt, ast.Assign) \
                else [stmt.target]
            if not any(isinstance(t, ast.Name) and t.id == name
                       for t in targets):
                continue
            value = stmt.value
            if not isinstance(value, ast.Dict):
                return None
            keys: Set[str] = set()
            for k in value.keys:
                if isinstance(k, ast.Constant) and isinstance(k.value,
                                                              str):
                    keys.add(k.value)
                elif isinstance(k, ast.Name) and k.id in consts:
                    keys.add(consts[k.id])
            return keys
    return None


class _MetricTable:
    def __init__(self, pctx):
        cfg = pctx.config
        fctx = pctx.file(cfg.metrics_rel)
        self.ok = fctx is not None
        if not self.ok:
            return
        self.consts = _module_str_constants(fctx)
        self.exact = _dict_keys(fctx, "METRIC_DESCRIPTIONS",
                                self.consts) or set()
        self.prefixes = _dict_keys(fctx, "METRIC_PREFIX_DESCRIPTIONS",
                                   self.consts) or set()
        self.metrics_rel = cfg.metrics_rel
        self.metrics_mod = os.path.splitext(
            cfg.metrics_rel.replace("/", "."))[0]

    def describes(self, key: str) -> bool:
        return key in self.exact or any(key.startswith(p)
                                        for p in self.prefixes)

    def resolve_arg(self, fctx: A.FileCtx,
                    arg: ast.AST) -> Optional[str]:
        """Literal, metrics-module attribute (M.OP_TIME) or imported
        constant -> the key string; None when dynamic."""
        if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
            return arg.value
        if isinstance(arg, ast.Attribute) and isinstance(arg.value,
                                                         ast.Name):
            base = fctx.imports.get(arg.value.id, arg.value.id)
            if base == self.metrics_mod and arg.attr in self.consts:
                return self.consts[arg.attr]
        if isinstance(arg, ast.Name):
            target = fctx.imports.get(arg.id)
            if target and target.startswith(self.metrics_mod + "."):
                cname = target[len(self.metrics_mod) + 1:]
                return self.consts.get(cname)
        return None


@rule("metric-key",
      "metric keys must resolve via metrics.describe_metric (exact "
      "entry or prefix family)")
def check_metric_keys(pctx):
    table = _MetricTable(pctx)
    if not table.ok:
        return
    mfctx = pctx.file(table.metrics_rel)
    # direction 1: every metric-name constant in metrics.py described
    for stmt in mfctx.tree.body:
        if isinstance(stmt, ast.Assign) and isinstance(
                stmt.value, ast.Constant) and isinstance(
                stmt.value.value, str):
            for t in stmt.targets:
                if isinstance(t, ast.Name) and t.id.isupper() \
                        and not t.id.startswith("_") \
                        and not table.describes(stmt.value.value):
                    yield Finding(
                        "metric-key", mfctx.rel, stmt.lineno, 1,
                        f"metric constant {t.id} = "
                        f"{stmt.value.value!r} has no entry in "
                        f"METRIC_DESCRIPTIONS")
    # direction 2: every statically-resolvable key at a sink call site
    for fctx in pctx.files:
        if fctx.rel == table.metrics_rel:
            continue
        for call in A.file_calls(fctx):
            if A.call_tail(call) not in _METRIC_SINKS or not call.args:
                continue
            if not isinstance(call.func, ast.Attribute):
                continue
            key = table.resolve_arg(fctx, call.args[0])
            if key is None or table.describes(key):
                continue
            yield Finding(
                "metric-key", fctx.rel, call.lineno,
                call.col_offset + 1,
                f"metric key {key!r} does not resolve via "
                f"describe_metric — add it to METRIC_DESCRIPTIONS (or "
                f"a prefix family) in metrics.py")


@rule("conf-key",
      "spark.rapids.* string literals must be registered conf.py keys")
def check_conf_keys(pctx):
    registered: Set[str] = set()
    reg_nodes: Set[int] = set()
    for fctx in pctx.files:
        for call in A.file_calls(fctx):
            if A.call_tail(call) == "conf" and len(call.args) >= 1 \
                    and isinstance(call.args[0], ast.Constant) \
                    and isinstance(call.args[0].value, str) \
                    and call.args[0].value.startswith("spark.rapids."):
                registered.add(call.args[0].value)
                reg_nodes.add(id(call.args[0]))
    if not registered:
        return  # no registry in this tree (fixture runs)
    for fctx in pctx.files:
        for node in ast.walk(fctx.tree):
            if not (isinstance(node, ast.Constant)
                    and isinstance(node.value, str)):
                continue
            if id(node) in reg_nodes:
                continue
            if not _CONF_KEY_RE.match(node.value):
                continue
            # skip docstrings and f-string fragments
            par = A.parent(node)
            if isinstance(par, ast.Expr) or isinstance(par,
                                                       ast.JoinedStr):
                continue
            if node.value not in registered:
                yield Finding(
                    "conf-key", fctx.rel, node.lineno,
                    node.col_offset + 1,
                    f"conf key literal {node.value!r} is not a "
                    f"registered conf.py entry — register it (or fix "
                    f"the typo); docs/configs.md is generated from "
                    f"the registry")


@rule("span-scope",
      "Tracer span opens must be with-scoped (unclosed spans corrupt "
      "the lane's B/E nesting)")
def check_span_scope(pctx):
    cfg = pctx.config
    trace_mod = os.path.splitext(cfg.trace_rel.replace("/", "."))[0]
    for fctx in pctx.files:
        if fctx.rel == cfg.trace_rel:
            continue
        for call in A.file_calls(fctx):
            if A.call_tail(call) != "span":
                continue
            if not isinstance(call.func, ast.Attribute):
                continue
            base = A.resolve_path(fctx, call.func.value)
            if base != trace_mod:
                continue
            par = A.parent(call)
            if isinstance(par, ast.withitem):
                continue
            yield Finding(
                "span-scope", fctx.rel, call.lineno,
                call.col_offset + 1,
                "trace span opened outside a `with` — every span must "
                "be with-scoped so its B/E pair always closes")


@rule("span-kind",
      "literal span/instant kinds must come from trace.py's "
      "SPAN_CATALOG / INSTANT_CATALOG (docs/observability.md)")
def check_span_kinds(pctx):
    cfg = pctx.config
    trace_mod = os.path.splitext(cfg.trace_rel.replace("/", "."))[0]
    tfctx = pctx.file(cfg.trace_rel)
    if tfctx is None:
        return
    consts = _module_str_constants(tfctx)
    span_kinds = _dict_keys(tfctx, "SPAN_CATALOG", consts)
    instant_kinds = _dict_keys(tfctx, "INSTANT_CATALOG", consts)
    if span_kinds is None or instant_kinds is None:
        return  # no catalogs in this tree (fixture runs)

    def _literal(call) -> Optional[str]:
        if call.args and isinstance(call.args[0], ast.Constant) \
                and isinstance(call.args[0].value, str):
            return call.args[0].value
        return None

    for fctx in pctx.files:
        if fctx.rel == cfg.trace_rel:
            continue
        for call in A.file_calls(fctx):
            tail = A.call_tail(call)
            if tail in ("span", "instant"):
                if not isinstance(call.func, ast.Attribute) or \
                        A.resolve_path(fctx, call.func.value) != trace_mod:
                    continue
                catalog = span_kinds if tail == "span" else instant_kinds
            elif tail in ("add", "mark"):
                # the package convention: `qt = trace._ACTIVE` (or the
                # metrics-module mirror) — literal kinds recorded
                # through it are catalog members too
                f = call.func
                if not (isinstance(f, ast.Attribute)
                        and isinstance(f.value, ast.Name)
                        and f.value.id == "qt"):
                    continue
                catalog = span_kinds if tail == "add" else instant_kinds
            else:
                continue
            kind = _literal(call)
            if kind is None or kind in catalog:
                continue
            which = ("SPAN_CATALOG" if catalog is span_kinds
                     else "INSTANT_CATALOG")
            yield Finding(
                "span-kind", fctx.rel, call.lineno,
                call.col_offset + 1,
                f"span kind {kind!r} is not in trace.py {which} — "
                f"add it (with a description) so dumps can't carry "
                f"undocumented vocabulary")


@rule("prom-family",
      "Prometheus families emitted by the telemetry endpoint must be "
      "SERVER_FAMILY_HELP entries named srt_[a-z0-9_]+")
def check_prom_families(pctx):
    cfg = pctx.config
    pfctx = pctx.file(cfg.prometheus_rel)
    if pfctx is None:
        return
    consts = _module_str_constants(pfctx)
    families = _dict_keys(pfctx, "SERVER_FAMILY_HELP", consts)
    if families is None:
        return
    name_re = re.compile(r"^srt_[a-z0-9_]+$")
    for name in sorted(families):
        if not name_re.match(name):
            yield Finding(
                "prom-family", pfctx.rel, 1, 1,
                f"family {name!r} violates the srt_[a-z0-9_]+ naming "
                f"rule")
    for call in A.walk_calls(pfctx.tree):
        if A.call_tail(call) != "_emit_server" or len(call.args) < 2:
            continue
        arg = call.args[1]
        if not (isinstance(arg, ast.Constant)
                and isinstance(arg.value, str)):
            yield Finding(
                "prom-family", pfctx.rel, call.lineno,
                call.col_offset + 1,
                "emitted family name must be a string literal (the "
                "SERVER_FAMILY_HELP table and the generated doc "
                "cannot cover a dynamic name)")
            continue
        if arg.value not in families:
            yield Finding(
                "prom-family", pfctx.rel, call.lineno,
                call.col_offset + 1,
                f"family {arg.value!r} has no SERVER_FAMILY_HELP "
                f"entry — add it (type + help) so the endpoint and "
                f"docs/observability.md stay in lockstep")


@rule("history-field",
      "query-history record fields must be HISTORY_FIELD_CATALOG "
      "entries (docs/observability.md 'Query history')")
def check_history_fields(pctx):
    cfg = pctx.config
    hfctx = pctx.file(cfg.history_rel)
    if hfctx is None:
        return
    consts = _module_str_constants(hfctx)
    catalog = _dict_keys(hfctx, "HISTORY_FIELD_CATALOG", consts)
    if catalog is None:
        return  # no catalog in this tree (fixture runs)
    name_re = re.compile(r"^[a-z][A-Za-z0-9]*$")
    for name in sorted(catalog):
        if not name_re.match(name):
            yield Finding(
                "history-field", hfctx.rel, 1, 1,
                f"history field {name!r} violates the camelCase "
                f"naming rule")

    def _check_key(node: ast.AST, lineno: int, col: int):
        if isinstance(node, ast.Constant) and isinstance(node.value,
                                                         str) \
                and node.value not in catalog:
            yield Finding(
                "history-field", hfctx.rel, lineno, col + 1,
                f"record field {node.value!r} has no "
                f"HISTORY_FIELD_CATALOG entry — add it (with a "
                f"description) so the on-disk schema and the "
                f"generated doc stay in lockstep")

    # record construction convention: the dict literal assigned to a
    # name `rec`, and every literal subscript store `rec["k"] = ...`
    for node in ast.walk(hfctx.tree):
        if isinstance(node, ast.AnnAssign):
            targets = [node.target]
            value = node.value
        elif isinstance(node, ast.Assign):
            targets = node.targets
            value = node.value
        else:
            continue
        if isinstance(value, ast.Dict) and any(
                isinstance(t, ast.Name) and t.id == "rec"
                for t in targets):
            for k in value.keys:
                if k is not None:
                    yield from _check_key(k, k.lineno, k.col_offset)
        for t in targets:
            if isinstance(t, ast.Subscript) and isinstance(
                    t.value, ast.Name) and t.value.id == "rec":
                yield from _check_key(t.slice, t.lineno, t.col_offset)


def _action_catalog(fctx: A.FileCtx):
    """Parse ``ACTION_CATALOG`` from the tuning module's AST: the set
    of action names, and the knob strings each declares (the ``knob``
    value plus every ``knobs`` list member). Returns (names, knobs,
    lineno) or None when the module has no parseable catalog."""
    for stmt in fctx.tree.body:
        if isinstance(stmt, ast.AnnAssign):
            targets = [stmt.target]
        elif isinstance(stmt, ast.Assign):
            targets = stmt.targets
        else:
            continue
        if not any(isinstance(t, ast.Name) and t.id == "ACTION_CATALOG"
                   for t in targets):
            continue
        value = stmt.value
        if not isinstance(value, ast.Dict):
            return None
        names: Set[str] = set()
        knobs: List[Tuple[str, int]] = []
        for k, v in zip(value.keys, value.values):
            if isinstance(k, ast.Constant) and isinstance(k.value, str):
                names.add(k.value)
            if not isinstance(v, ast.Dict):
                continue
            for fk, fv in zip(v.keys, v.values):
                if not (isinstance(fk, ast.Constant)
                        and fk.value in ("knob", "knobs")):
                    continue
                elts = fv.elts if isinstance(fv, (ast.List,
                                                  ast.Tuple)) else [fv]
                for e in elts:
                    if isinstance(e, ast.Constant) and isinstance(
                            e.value, str):
                        knobs.append((e.value, e.lineno))
        return names, knobs, stmt.lineno
    return None


@rule("tuning-action",
      "TuningController actions must be ACTION_CATALOG entries and "
      "catalog conf knobs must be registered conf keys")
def check_tuning_actions(pctx):
    cfg = pctx.config
    tfctx = pctx.file(cfg.tuning_rel)
    if tfctx is None:
        return
    parsed = _action_catalog(tfctx)
    if parsed is None:
        return  # no catalog in this tree (fixture runs)
    names, knobs, cat_lineno = parsed
    # 1. every spark.rapids.* knob the catalog declares must be a
    # registered conf key (same registry walk as conf-key)
    registered: Set[str] = set()
    for fctx in pctx.files:
        for call in A.file_calls(fctx):
            if A.call_tail(call) == "conf" and len(call.args) >= 1 \
                    and isinstance(call.args[0], ast.Constant) \
                    and isinstance(call.args[0].value, str) \
                    and call.args[0].value.startswith("spark.rapids."):
                registered.add(call.args[0].value)
    if registered:
        for knob, lineno in knobs:
            if knob.startswith("spark.rapids.") \
                    and knob not in registered:
                yield Finding(
                    "tuning-action", tfctx.rel, lineno, 1,
                    f"ACTION_CATALOG knob {knob!r} is not a "
                    f"registered conf.py key — the controller would "
                    f"actuate a conf nothing reads")
    # 2. every action the controller constructs resolves in the
    # catalog, and only through a literal name the table can cover
    for call in A.walk_calls(tfctx.tree):
        if A.call_tail(call) != "_new_action" or not call.args:
            continue
        arg = call.args[0]
        if not (isinstance(arg, ast.Constant)
                and isinstance(arg.value, str)):
            yield Finding(
                "tuning-action", tfctx.rel, call.lineno,
                call.col_offset + 1,
                "action name must be a string literal (the "
                "ACTION_CATALOG table and docs/tuning.md cannot cover "
                "a dynamic name)")
            continue
        if arg.value not in names:
            yield Finding(
                "tuning-action", tfctx.rel, call.lineno,
                call.col_offset + 1,
                f"action {arg.value!r} has no ACTION_CATALOG entry "
                f"(declared at line {cat_lineno}) — add it (verdict, "
                f"knob, bounds, doc) so code, lint and docs/tuning.md "
                f"share one vocabulary")


@rule("docs-drift",
      "generated docs must match `tools docs` regeneration")
def check_docs_drift(pctx):
    cfg = pctx.config
    if not cfg.check_docs:
        return
    # the generators come from the INSTALLED package on sys.path; for a
    # foreign --root tree they would describe the wrong code, so the
    # rule only runs on the tree the interpreter is actually importing
    from spark_rapids_tpu.lint.engine import default_root
    if os.path.realpath(pctx.root) != os.path.realpath(default_root()):
        return
    docs_dir = os.path.join(pctx.root, "docs")
    if not os.path.isdir(docs_dir):
        return
    # the one rule that imports the runtime: the generators ARE the
    # source of truth the docs must match (same order as `tools docs`)
    import spark_rapids_tpu.profile  # noqa: F401 — registers confs
    import spark_rapids_tpu.trace  # noqa: F401 — registers confs
    from spark_rapids_tpu.conf import generate_docs
    from spark_rapids_tpu.tools import (generate_observability_docs,
                                        generate_supported_ops,
                                        generate_tuning_docs)
    for fname, gen in (("configs.md", generate_docs),
                       ("supported_ops.md", generate_supported_ops),
                       ("observability.md",
                        generate_observability_docs),
                       ("tuning.md", generate_tuning_docs)):
        path = os.path.join(docs_dir, fname)
        if not os.path.exists(path):
            continue
        with open(path, "r", encoding="utf-8") as f:
            on_disk = f.read()
        if on_disk != gen():
            yield Finding(
                "docs-drift", f"docs/{fname}", 1, 1,
                f"docs/{fname} is stale — regenerate with "
                f"`python -m spark_rapids_tpu.tools docs`")
