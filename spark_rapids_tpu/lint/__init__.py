"""tpu-lint: AST-based invariant checker for spark_rapids_tpu.

Machine-enforces the correctness invariants the last six PRs fixed by
hand (docs/linting.md):

* ``retry-coverage``   — device allocation/dispatch sites run under the
  PR-4 ``with_retry`` protocol (docs/robustness.md wrapped-site table).
* ``jit-direct`` / ``jit-module-cache`` — all compiles go through the
  bounded single-flight ``JitCache``; no raw ``jax.jit`` or module dict
  caches of compiled programs.
* ``lock-order`` / ``lock-blocking-call`` / ``check-then-act`` — the
  concurrency races PR 7's review pass fixed by hand, checked on the
  lock-acquisition graph of memory/resource/serve/jit_cache.
* ``metric-key`` / ``conf-key`` / ``span-scope`` / ``docs-drift`` — the
  static promotion of the former runtime drift lints: metric keys
  resolve in ``describe_metric``, ``spark.rapids.*`` literals are
  registered confs, spans are with-scoped, generated docs are fresh.
* ``cancel-checkpoint`` — blocking waits in serve/, retry.py and
  jit_cache.py stay cancellable: bounded timeouts or the
  CancelToken-aware lifecycle helpers (docs/serving.md "Query
  lifecycle").
* ``donation-safety`` / ``hidden-sync`` / ``handle-leak`` /
  ``trace-purity`` — the interprocedural data-flow tier
  (``lint/dataflow.py``): no read-after-donate on any forward path, no
  unallowlisted device->host sync in the hot-path scopes, every
  spillable handle deterministically released or escaped, no host
  impurity (clocks/RNG/conf/nonlocal mutation) reachable from a traced
  program builder.

CLI: ``python -m spark_rapids_tpu.tools lint`` (exit 0 clean /
1 findings / 2 internal error). Per-line suppressions must carry a
reason: ``# tpu-lint: disable=rule-name(reason)``.

The package is stdlib-only (``ast`` + ``tokenize``); only the
``docs-drift`` rule imports the runtime doc generators, and only when
enabled.
"""

from spark_rapids_tpu.lint.config import LintConfig, load_config
from spark_rapids_tpu.lint.engine import (Finding, LintResult,
                                          default_root, render_human,
                                          render_json, run_cli, run_lint)

# rule modules self-register on import
from spark_rapids_tpu.lint import rules_retry  # noqa: F401,E402
from spark_rapids_tpu.lint import rules_jit  # noqa: F401,E402
from spark_rapids_tpu.lint import rules_concurrency  # noqa: F401,E402
from spark_rapids_tpu.lint import rules_drift  # noqa: F401,E402
from spark_rapids_tpu.lint import rules_lifecycle  # noqa: F401,E402
from spark_rapids_tpu.lint import rules_dataflow  # noqa: F401,E402

__all__ = ["LintConfig", "load_config", "Finding", "LintResult",
           "run_lint", "run_cli", "render_human", "render_json",
           "default_root"]
