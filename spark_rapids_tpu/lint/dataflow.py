"""Interprocedural data-flow plumbing for tpu-lint's v2 rule tier.

The lexical rules (families 1-5) check properties a single AST node and
its ancestors can prove. The bug classes PR 11 and PR 13 fixed by hand
— a donated buffer read after the donating dispatch, a hidden
device->host sync on the hot path, a spillable handle freed only by GC,
host impurity baked into a traced program — are DATA-FLOW properties:
they need to know where a value came from, where it goes, and what runs
after what. This module provides that substrate, stdlib-`ast` only:

* ``CallGraph`` — whole-package, cross-module call graph with targets
  resolved through import aliases exactly like the jit-rule builder
  closure (``X.fn`` follows the alias to the target module's defs;
  bare names and ``self.method`` match in-file), plus transitive
  reachability for the trace-purity closure.
* Donating-program resolution — which call sites invoke a compiled
  program that donates input buffers: direct
  ``jax.jit(..., donate_argnums=...)(args)`` invocations, names bound
  to donating jits, names bound through ``cache.get_or_build(key,
  builder)`` / ``cache.put(key, builder(...))`` where the builder
  returns a donating jit (the ``build_stage_fn`` shape), and local
  helpers that forward a parameter into a donating call one level deep.
  A conditional ``donate_argnums=(0, 1) if donate else ()`` reads as
  MAY-donate: the safety property must hold on every instantiation.
* Reaching-definitions helpers — ``reads_after_call`` finds loads of a
  name on any forward path from a call (source order after the call,
  plus the back edge of an enclosing loop), with straight-line
  rebindings killing the flag.
* Device-value taint — ``device_taint`` runs a per-function
  fixed point seeding from device-producing calls (``jax.*`` /
  ``jax.numpy.*`` and invocations of names bound from a JitCache
  ``get``/``put``/``get_or_build``) and propagating through
  assignments, so the hidden-sync rule only fires on values that
  actually reach from the device.

Everything here is best-effort static resolution: dynamic dispatch,
attribute tables and cross-instance aliasing are invisible, so the
rules built on top UNDER-approximate (missed findings are possible;
false positives should be rare and carry an allowlist/suppression
path).
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, Iterator, List, Optional, Set, Tuple

from spark_rapids_tpu.lint import astutil as A


# ---------------------------------------------------------------------------
# Whole-package call graph
# ---------------------------------------------------------------------------

class FuncInfo:
    """One function/method definition somewhere in the package."""

    __slots__ = ("fctx", "rel", "node", "qualname")

    def __init__(self, fctx: A.FileCtx, node: ast.AST):
        self.fctx = fctx
        self.rel = fctx.rel
        self.node = node
        self.qualname = A.qualname(node)


class CallGraph:
    """Best-effort package call graph. Defs are indexed per file by
    bare name; a call target resolves to this file's defs (``foo(...)``,
    ``self.method(...)``) or, for ``X.fn(...)`` with ``X`` an import
    alias, to the aliased module's defs."""

    def __init__(self, pctx):
        self.pctx = pctx
        self.defs: Dict[Tuple[str, str], List[FuncInfo]] = {}
        self.infos: Dict[int, FuncInfo] = {}
        for fctx in pctx.files:
            for node in ast.walk(fctx.tree):
                if isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                    info = FuncInfo(fctx, node)
                    self.defs.setdefault((fctx.rel, node.name),
                                         []).append(info)
                    self.infos[id(node)] = info

    def resolve_name(self, fctx: A.FileCtx,
                     name: str) -> List[FuncInfo]:
        """A bare name: a def in this file, or a from-import
        (``from pkg.mod import fn`` maps ``fn`` ->
        ``pkg.mod.fn`` in the alias table) followed to its home."""
        got = self.defs.get((fctx.rel, name))
        if got:
            return got
        dotted = fctx.imports.get(name)
        if dotted and "." in dotted:
            mod, _, attr = dotted.rpartition(".")
            return self.defs.get((A.module_rel(mod), attr), [])
        return []

    def resolve_call(self, fctx: A.FileCtx,
                     call: ast.Call) -> List[FuncInfo]:
        f = call.func
        if isinstance(f, ast.Name):
            return self.resolve_name(fctx, f.id)
        if isinstance(f, ast.Attribute):
            if isinstance(f.value, ast.Name) \
                    and f.value.id in fctx.imports:
                rel = A.module_rel(fctx.imports[f.value.id])
                got = self.defs.get((rel, f.attr))
                if got:
                    return got
            # in-file method resolution ONLY for self/cls receivers: a
            # bare-name match on any `obj.foo()` would collide with
            # unrelated same-named defs and manufacture false donation
            # sites / purity reachability
            if isinstance(f.value, ast.Name) \
                    and f.value.id in ("self", "cls"):
                return self.defs.get((fctx.rel, f.attr), [])
        return []

    def reachable(self, roots: Iterable[Tuple[A.FileCtx, ast.AST]]
                  ) -> Dict[int, FuncInfo]:
        """Transitive closure from ``(fctx, fn-node)`` roots. Lambda
        roots seed their calls but only named defs are returned (a
        lambda's body is lexically part of whatever walks it)."""
        out: Dict[int, FuncInfo] = {}
        seen: Set[int] = set()
        work = list(roots)
        while work:
            fctx, node = work.pop()
            if id(node) in seen:
                continue
            seen.add(id(node))
            info = self.infos.get(id(node))
            if info is not None:
                out[id(node)] = info
            for call in A.walk_calls(node):
                for tgt in self.resolve_call(fctx, call):
                    if id(tgt.node) not in seen:
                        work.append((tgt.fctx, tgt.node))
        return out


# ---------------------------------------------------------------------------
# Position / scope helpers
# ---------------------------------------------------------------------------

def pos_of(node: ast.AST) -> Tuple[int, int]:
    return (getattr(node, "lineno", 0), getattr(node, "col_offset", 0))


def root_name(expr: ast.AST) -> Optional[str]:
    """Base Name of a Name/Attribute/Subscript/Starred chain:
    ``b.columns`` -> ``b``; None for anything rootless (a literal, a
    call result used inline)."""
    cur = expr
    while isinstance(cur, (ast.Attribute, ast.Subscript, ast.Starred)):
        cur = cur.value
    return cur.id if isinstance(cur, ast.Name) else None


def local_names(fn: ast.AST) -> Set[str]:
    """Names BOUND inside a function/lambda: parameters, every Store
    target (assignments, loop/with/except/comprehension targets,
    walrus), nested defs, local imports. A Load of anything outside
    this set reads free state (closure or module)."""
    out: Set[str] = set()
    args = getattr(fn, "args", None)
    if args is not None:
        for a in (list(args.posonlyargs) + list(args.args)
                  + list(args.kwonlyargs)
                  + ([args.vararg] if args.vararg else [])
                  + ([args.kwarg] if args.kwarg else [])):
            out.add(a.arg)
    for node in ast.walk(fn):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)) and node is not fn:
            if not isinstance(node, ast.Lambda):
                out.add(node.name)
            # a nested def's parameters are bound within fn's subtree
            # too (a Pallas kernel's output refs are the inner kern's
            # params — writes to them are not free-state mutation)
            out |= local_names(node)
        elif isinstance(node, ast.Name) \
                and isinstance(node.ctx, ast.Store):
            out.add(node.id)
        elif isinstance(node, (ast.Import, ast.ImportFrom)):
            for a in node.names:
                out.add((a.asname or a.name).split(".")[0])
        elif isinstance(node, ast.ExceptHandler) and node.name:
            out.add(node.name)
    return out


def positional_params(fn: ast.AST) -> List[str]:
    args = getattr(fn, "args", None)
    if args is None:
        return []
    return [a.arg for a in list(args.posonlyargs) + list(args.args)]


def enclosing_function(node: ast.AST) -> Optional[ast.AST]:
    fns = A.enclosing_functions(node)
    return fns[0] if fns else None


def _outermost_loop_within(node: ast.AST,
                           stop: ast.AST) -> Optional[ast.AST]:
    loop = None
    for a in A.ancestors(node):
        if a is stop:
            break
        if isinstance(a, (ast.For, ast.AsyncFor, ast.While)):
            loop = a
    return loop


def _stores_of(scope: ast.AST, name: str) -> List[Tuple[int, int]]:
    return sorted(pos_of(n) for n in ast.walk(scope)
                  if isinstance(n, ast.Name)
                  and isinstance(n.ctx, ast.Store) and n.id == name)


def reads_after_call(fn: ast.AST, call: ast.Call,
                     name: str) -> List[ast.Name]:
    """Loads of ``name`` inside ``fn`` that sit on a forward path from
    ``call``: after it in source order, or anywhere in the call's
    outermost enclosing loop (the back edge runs the read AFTER the
    call on the next iteration). A rebinding of the name between the
    call and the read kills the flag — including the loop's own
    iteration target, which rebinds at the top of every pass."""
    cpos = pos_of(call)
    # the canonical donation idiom rebinds the name to the program's
    # output IN the donating statement (`x = _F(x)`): every later read
    # sees the new value, so nothing downstream can touch the donated
    # storage — the site is clean by construction
    for a in A.ancestors(call):
        if isinstance(a, ast.Assign) and any(
                isinstance(t, ast.Name) and t.id == name
                for t in a.targets):
            return []
        if isinstance(a, ast.stmt):
            break
    kills = _stores_of(fn, name)
    loop = _outermost_loop_within(call, fn)
    loop_ids = {id(n) for n in ast.walk(loop)} if loop is not None \
        else set()
    loop_kills = _stores_of(loop, name) if loop is not None else []
    in_call = {id(n) for n in ast.walk(call)}
    out: List[ast.Name] = []
    for node in ast.walk(fn):
        if not (isinstance(node, ast.Name) and node.id == name
                and isinstance(node.ctx, ast.Load)):
            continue
        if id(node) in in_call:
            continue
        rpos = pos_of(node)
        if rpos > cpos:
            if not any(cpos < k <= rpos for k in kills):
                out.append(node)
        elif id(node) in loop_ids:
            # loop-carried path call -> loop end -> loop head -> read:
            # dead iff the name rebinds after the call (same iteration)
            # or before the read (next iteration)
            if not any(k > cpos for k in loop_kills) \
                    and not any(k < rpos for k in loop_kills):
                out.append(node)
    return sorted(out, key=pos_of)


# ---------------------------------------------------------------------------
# Donating-program resolution
# ---------------------------------------------------------------------------

def donated_positions(fctx: A.FileCtx,
                      call: ast.Call) -> Optional[Set[int]]:
    """Donated argument positions of a ``jax.jit``/``pl.pallas_call``
    EXPRESSION, or None when it does not donate. Every int literal in
    the ``donate_argnums`` expression counts (a conditional
    ``(0, 1) if donate else ()`` MAY donate — the rule must hold for
    the donating instantiation); pallas donation is the keys of an
    ``input_output_aliases`` dict."""
    p = A.resolve_path(fctx, call.func)
    if p == "jax.jit":
        for kw in call.keywords:
            if kw.arg == "donate_argnums":
                ints = {n.value for n in ast.walk(kw.value)
                        if isinstance(n, ast.Constant)
                        and type(n.value) is int}
                return ints or None
    elif p is not None and (p == "pallas_call"
                            or p.endswith(".pallas_call")):
        for kw in call.keywords:
            if kw.arg == "input_output_aliases" \
                    and isinstance(kw.value, ast.Dict):
                ints = {k.value for k in kw.value.keys
                        if isinstance(k, ast.Constant)
                        and type(k.value) is int}
                return ints or None
    return None


def donating_builders(pctx, cg: CallGraph) -> Dict[int, Set[int]]:
    """Function-node id -> donated positions for every def that RETURNS
    a donating jit (directly, or via a local name bound to one) — the
    ``build_stage_fn(steps, donate)`` shape."""
    out: Dict[int, Set[int]] = {}
    for fctx in pctx.files:
        for node in ast.walk(fctx.tree):
            if not isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            bound: Dict[str, Set[int]] = {}
            don_calls: Dict[int, Set[int]] = {}
            for c in A.walk_calls(node):
                ps = donated_positions(fctx, c)
                if ps:
                    don_calls[id(c)] = ps
                    par = A.parent(c)
                    if isinstance(par, ast.Assign):
                        for t in par.targets:
                            if isinstance(t, ast.Name):
                                bound[t.id] = ps
            for r in ast.walk(node):
                if not (isinstance(r, ast.Return) and r.value is not None):
                    continue
                if enclosing_function(r) is not node:
                    continue  # a nested def's return, not this one's
                v = r.value
                if id(v) in don_calls:
                    out.setdefault(id(node), set()).update(
                        don_calls[id(v)])
                elif isinstance(v, ast.Name) and v.id in bound:
                    out.setdefault(id(node), set()).update(bound[v.id])
    return out


def _donating_value(fctx: A.FileCtx, cg: CallGraph,
                    builders: Dict[int, Set[int]],
                    value: ast.AST) -> Optional[Set[int]]:
    """Donated positions of the program a VALUE expression evaluates
    to: a donating jit expression, a call to a donating builder, or a
    JitCache route (``cache.get_or_build(key, builder)`` /
    ``cache.put(key, builder(...))``) whose builder donates."""
    if isinstance(value, ast.Call):
        ps = donated_positions(fctx, value)
        if ps:
            return ps
        tail = A.call_tail(value)
        if tail in ("get_or_build", "put") and len(value.args) >= 2:
            return _donating_value(fctx, cg, builders, value.args[1])
        for tgt in cg.resolve_call(fctx, value):
            if id(tgt.node) in builders:
                return set(builders[id(tgt.node)])
        return None
    if isinstance(value, ast.Lambda):
        return _donating_value(fctx, cg, builders, value.body)
    if isinstance(value, ast.Name):
        for info in cg.resolve_name(fctx, value.id):
            if id(info.node) in builders:
                return set(builders[id(info.node)])
    return None


class DonationSite:
    """One call site that hands buffers to a donating program."""

    __slots__ = ("fctx", "call", "positions", "via")

    def __init__(self, fctx: A.FileCtx, call: ast.Call,
                 positions: Set[int], via: str):
        self.fctx = fctx
        self.call = call
        self.positions = positions
        self.via = via  # what resolved as donating, for the message

    def donated_roots(self) -> List[Tuple[int, Optional[str]]]:
        out = []
        for p in sorted(self.positions):
            if p < len(self.call.args):
                out.append((p, root_name(self.call.args[p])))
        return out


def donation_sites(pctx, cg: CallGraph) -> List[DonationSite]:
    """Every resolvable donating call site in the package, including
    one level of local-helper forwarding: a helper whose body donates
    one of its own positional parameters donates that position at ITS
    call sites too."""
    builders = donating_builders(pctx, cg)
    sites: List[DonationSite] = []
    for fctx in pctx.files:
        # scope-id -> name -> donated positions, for names bound to
        # donating programs (module scope and each function scope)
        bindings: Dict[int, Dict[str, Set[int]]] = {}
        for node in ast.walk(fctx.tree):
            if not isinstance(node, ast.Assign):
                continue
            ps = _donating_value(fctx, cg, builders, node.value)
            if not ps:
                continue
            scope = enclosing_function(node)
            scope_id = id(scope) if scope is not None else id(fctx.tree)
            for t in node.targets:
                name = None
                if isinstance(t, ast.Name):
                    name = t.id
                elif isinstance(t, ast.Tuple) and t.elts \
                        and isinstance(t.elts[0], ast.Name):
                    # fn, was_miss = cache.get_or_build(...)
                    name = t.elts[0].id
                if name:
                    bindings.setdefault(scope_id, {})[name] = ps

        def _lookup(call: ast.Call, fname: str) -> Optional[Set[int]]:
            for scope in A.enclosing_functions(call):
                got = bindings.get(id(scope), {}).get(fname)
                if got:
                    return got
            return bindings.get(id(fctx.tree), {}).get(fname)

        for call in A.file_calls(fctx):
            f = call.func
            if isinstance(f, ast.Call):
                ps = donated_positions(fctx, f)
                if ps:
                    sites.append(DonationSite(fctx, call, ps,
                                              "an inline donating jit"))
            elif isinstance(f, ast.Name):
                ps = _lookup(call, f.id)
                if ps:
                    sites.append(DonationSite(
                        fctx, call, ps,
                        f"`{f.id}` (bound to a donating program)"))
    # one level of helper forwarding: a function that donates its own
    # positional parameter k makes every call site of that function a
    # donation site at position k
    helper_donates: Dict[int, Set[int]] = {}
    for s in sites:
        fn = enclosing_function(s.call)
        if fn is None or id(fn) not in cg.infos:
            continue
        params = positional_params(fn)
        for _p, root in s.donated_roots():
            if root in params:
                helper_donates.setdefault(id(fn), set()).add(
                    params.index(root))
    if helper_donates:
        for fctx in pctx.files:
            for call in A.file_calls(fctx):
                for tgt in cg.resolve_call(fctx, call):
                    ps = helper_donates.get(id(tgt.node))
                    if not ps:
                        continue
                    # bound-method call: `self.helper(x)` binds self
                    # implicitly, so the helper's param index k maps
                    # to call.args[k-1]
                    shift = 1 if (
                        isinstance(call.func, ast.Attribute)
                        and positional_params(tgt.node)[:1]
                        in (["self"], ["cls"])) else 0
                    adj = {p - shift for p in ps if p - shift >= 0}
                    if adj:
                        sites.append(DonationSite(
                            fctx, call, adj,
                            f"helper `{tgt.node.name}` (donates its "
                            f"parameter one call down)"))
    return sites


# ---------------------------------------------------------------------------
# Device-value taint (hidden-sync substrate)
# ---------------------------------------------------------------------------

_JIT_ROUTE_TAILS = ("get", "put", "get_or_build")

# jax calls that return host metadata (topology, backend names), not
# device-resident data — using their results on the host is not a sync
_NON_DATA_JAX = frozenset({
    "jax.device_get", "jax.devices", "jax.local_devices",
    "jax.device_count", "jax.local_device_count",
    "jax.default_backend", "jax.process_index", "jax.process_count"})

# calls that FORCE a device value to the host: their result is a host
# value, so assigning it SANITIZES the target (the sync itself is the
# hidden-sync rule's finding; everything downstream is host-side)
_FORCING_PATHS = frozenset({"numpy.asarray", "numpy.array",
                            "jax.device_get"})


def _jitcache_instance_names(fctx: A.FileCtx) -> Set[str]:
    # memoized per file: device_taint calls this once per FUNCTION,
    # and the whole-tree walk dominated the hidden-sync rule's wall
    # (it is a pure function of the parsed tree)
    cached = getattr(fctx, "_jitcache_names", None)
    if cached is not None:
        return cached
    out: Set[str] = set()
    for node in ast.walk(fctx.tree):
        if isinstance(node, ast.Assign) \
                and isinstance(node.value, ast.Call) \
                and A.call_tail(node.value) == "JitCache":
            for t in node.targets:
                if isinstance(t, ast.Name):
                    out.add(t.id)
    fctx._jitcache_names = out
    return out


def _is_device_producing_call(fctx: A.FileCtx, call: ast.Call,
                              program_names: Set[str]) -> bool:
    p = A.resolve_path(fctx, call.func)
    if p is not None:
        head = p.split(".")[0]
        if head == "jax" and p not in _NON_DATA_JAX:
            return True
    f = call.func
    return isinstance(f, ast.Name) and f.id in program_names


def _is_forcing_call(fctx: A.FileCtx, call: ast.Call) -> bool:
    """A call whose RESULT is a host value pulled off the device:
    np.asarray/np.array/jax.device_get, the float/int/bool builtins,
    and ``x.item()``."""
    p = A.resolve_path(fctx, call.func)
    if p in _FORCING_PATHS:
        return True
    f = call.func
    if isinstance(f, ast.Name) and f.id in ("float", "int", "bool") \
            and len(call.args) == 1:
        return True
    return isinstance(f, ast.Attribute) and f.attr == "item" \
        and not call.args


def device_taint(fctx: A.FileCtx,
                 fn: ast.AST) -> Tuple[Set[str], Set[str]]:
    """Per-function fixed point: ``(tainted, program_names)``.
    ``tainted`` holds names whose value reaches from a device-producing
    call — a ``jax.*``/``jax.numpy.*`` call, or an invocation of a name
    bound from a JitCache ``get``/``put``/``get_or_build`` (a compiled
    program's output lives on the device); ``program_names`` are those
    compiled-program bindings themselves. Taint propagates through
    assignments and tuple unpacking; parameters are NOT tainted
    (callers own that knowledge)."""
    caches = _jitcache_instance_names(fctx)
    program_names: Set[str] = set()
    tainted: Set[str] = set()
    # names assigned from a forcing call are HOST values from then on
    # (flow-insensitively, sanitization wins — prefer a missed finding
    # over flagging host-side arithmetic after the one real sync)
    sanitized: Set[str] = set()

    def expr_tainted(e: ast.AST) -> bool:
        for n in ast.walk(e):
            if isinstance(n, ast.Call) \
                    and _is_device_producing_call(fctx, n,
                                                  program_names):
                return True
            if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load) \
                    and n.id in tainted:
                return True
        return False

    changed = True
    while changed:
        changed = False
        for node in ast.walk(fn):
            if not isinstance(node, ast.Assign):
                continue
            v = node.value
            # program bindings: fn = CACHE.get(...) / .put(...) /
            # fn, miss = CACHE.get_or_build(...)
            if isinstance(v, ast.Call) \
                    and A.call_tail(v) in _JIT_ROUTE_TAILS \
                    and isinstance(v.func, ast.Attribute) \
                    and isinstance(v.func.value, ast.Name) \
                    and (v.func.value.id in caches
                         or "CACHE" in v.func.value.id.upper()):
                for t in node.targets:
                    name = None
                    if isinstance(t, ast.Name):
                        name = t.id
                    elif isinstance(t, ast.Tuple) and t.elts \
                            and isinstance(t.elts[0], ast.Name):
                        name = t.elts[0].id
                    if name and name not in program_names:
                        program_names.add(name)
                        changed = True
                continue
            if isinstance(v, ast.Call) and _is_forcing_call(fctx, v):
                for t in node.targets:
                    for n in ast.walk(t):
                        if isinstance(n, ast.Name) \
                                and isinstance(n.ctx, ast.Store) \
                                and n.id not in sanitized:
                            sanitized.add(n.id)
                            tainted.discard(n.id)
                            changed = True
                continue
            if not expr_tainted(v):
                continue
            for t in node.targets:
                for n in ast.walk(t):
                    if isinstance(n, ast.Name) \
                            and isinstance(n.ctx, ast.Store) \
                            and n.id not in tainted \
                            and n.id not in sanitized:
                        tainted.add(n.id)
                        changed = True
    return tainted, program_names


# ---------------------------------------------------------------------------
# Traced-root collection (trace-purity substrate)
# ---------------------------------------------------------------------------

def traced_roots(pctx, cg: CallGraph
                 ) -> Iterator[Tuple[A.FileCtx, ast.AST, str]]:
    """(fctx, fn-or-lambda node, description) for every function a
    ``jax.jit``/``pl.pallas_call`` builder traces: the first argument,
    resolved through local names — including one ``shard_map(f, ...)``
    wrapper hop — and import aliases."""
    for fctx in pctx.files:
        # name -> value expr for every single-target assignment, so
        # `sm = shard_map(per_shard, ...)` then `jax.jit(sm)` resolves
        assigns: Dict[str, ast.AST] = {}
        for node in ast.walk(fctx.tree):
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                assigns[node.targets[0].id] = node.value
        for call in A.file_calls(fctx):
            p = A.resolve_path(fctx, call.func)
            is_jit = p == "jax.jit"
            is_pallas = p is not None and (p == "pallas_call"
                                           or p.endswith(".pallas_call"))
            if not (is_jit or is_pallas) or not call.args:
                continue
            what = "pl.pallas_call" if is_pallas else "jax.jit"
            for fctx2, node in _resolve_traced_arg(fctx, cg, assigns,
                                                   call.args[0], 0):
                yield fctx2, node, what


def _resolve_traced_arg(fctx: A.FileCtx, cg: CallGraph,
                        assigns: Dict[str, ast.AST], arg: ast.AST,
                        depth: int) -> List[Tuple[A.FileCtx, ast.AST]]:
    if depth > 2:
        return []
    if isinstance(arg, ast.Lambda):
        return [(fctx, arg)]
    if isinstance(arg, ast.Name):
        infos = cg.resolve_name(fctx, arg.id)
        if infos:
            return [(i.fctx, i.node) for i in infos]
        v = assigns.get(arg.id)
        if isinstance(v, ast.Call):
            # one wrapper hop: shard_map(f, ...) / functools.partial(f)
            out = []
            for sub in v.args[:1]:
                out.extend(_resolve_traced_arg(fctx, cg, assigns, sub,
                                               depth + 1))
            return out
        return []
    if isinstance(arg, ast.Call):
        # jax.jit(shard_map(per_shard, ...)) inline
        out = []
        for sub in arg.args[:1]:
            out.extend(_resolve_traced_arg(fctx, cg, assigns, sub,
                                           depth + 1))
        return out
    if isinstance(arg, ast.Attribute):
        infos = cg.resolve_call(
            fctx, ast.Call(func=arg, args=[], keywords=[]))
        return [(i.fctx, i.node) for i in infos]
    return []
