"""Rule family 5 — cancellation discipline over the serving tier's
blocking waits (docs/serving.md "Query lifecycle").

``cancel-checkpoint``: in the lifecycle-critical scope (serve/,
retry.py, jit_cache.py — the modules whose waits the query lifecycle
layer audited by hand), a blocking wait must either pass a BOUNDED
timeout (so the enclosing loop can re-check its CancelToken) or go
through a CancelToken-aware lifecycle helper
(``lifecycle.cancellable_sleep`` / ``lifecycle.cancellable_wait`` —
which are, by construction, not the flagged raw primitives). Flagged
primitives:

- ``<cond-or-event>.wait()`` with no timeout (positional or keyword)
  — an unbounded park no cancel can reach;
- direct ``time.sleep(...)`` — even a bounded backoff sleep ignores
  the token; the lifecycle helper slices and re-checks;
- blocking queue gets with no ``timeout=``: zero-argument ``.get()``
  and explicit ``.get(block=True)`` (``dict.get()`` always takes a
  key and has no ``block`` kwarg, so neither form is a dict lookup;
  ``block=False`` is non-blocking and exempt). The positional form
  ``q.get(True)`` is indistinguishable from ``d.get(True)`` at the
  AST and is out of the rule's reach — spell the kwarg.

This is the machine gate behind the lifecycle tentpole: a NEW wait
site added to the serving tier cannot silently become uncancellable.
"""

from __future__ import annotations

import ast

from spark_rapids_tpu.lint import astutil as A
from spark_rapids_tpu.lint.engine import Finding, rule


def _is_none(node: ast.AST) -> bool:
    return isinstance(node, ast.Constant) and node.value is None


def _bounded_wait(call: ast.Call) -> bool:
    """A ``.wait`` call is bounded when it passes a non-None timeout
    positionally or by keyword."""
    for a in call.args:
        if not _is_none(a):
            return True
    for kw in call.keywords:
        if kw.arg == "timeout" and not _is_none(kw.value):
            return True
    return False


@rule("cancel-checkpoint",
      "blocking waits in the lifecycle-critical scope must pass a "
      "bounded timeout or use a CancelToken-aware lifecycle helper")
def check_cancel_checkpoints(pctx):
    cfg = pctx.config
    for fctx in pctx.files:
        if not pctx.in_scope(fctx.rel, cfg.cancel_scope):
            continue
        for node in ast.walk(fctx.tree):
            if not isinstance(node, ast.Call):
                continue
            tail = A.call_tail(node)
            path = A.resolve_path(fctx, node.func)
            if path == "time.sleep":
                yield Finding(
                    "cancel-checkpoint", fctx.rel, node.lineno,
                    node.col_offset + 1,
                    "direct time.sleep in the lifecycle-critical "
                    "scope — a cancelled/timed-out query sleeps "
                    "through its deadline; use "
                    "lifecycle.cancellable_sleep (docs/serving.md "
                    "'Query lifecycle')")
            elif tail == "wait" and isinstance(node.func,
                                              ast.Attribute):
                if not _bounded_wait(node):
                    yield Finding(
                        "cancel-checkpoint", fctx.rel, node.lineno,
                        node.col_offset + 1,
                        "unbounded .wait() in the lifecycle-critical "
                        "scope — no cancellation can reach a parked "
                        "thread; pass a bounded timeout and re-check "
                        "the CancelToken in the loop, or use "
                        "lifecycle.cancellable_wait")
            elif tail == "get" and isinstance(node.func,
                                              ast.Attribute):
                has_timeout = any(
                    kw.arg == "timeout" and not _is_none(kw.value)
                    for kw in node.keywords)
                block_true = any(
                    kw.arg == "block"
                    and isinstance(kw.value, ast.Constant)
                    and kw.value.value is True
                    for kw in node.keywords)
                block_false = any(
                    kw.arg == "block"
                    and isinstance(kw.value, ast.Constant)
                    and kw.value.value is False
                    for kw in node.keywords)
                blocking_queue_get = (not node.args
                                      and not block_false) or block_true
                if blocking_queue_get and not has_timeout:
                    yield Finding(
                        "cancel-checkpoint", fctx.rel, node.lineno,
                        node.col_offset + 1,
                        "blocking queue .get() without timeout= parks "
                        "forever in the lifecycle-critical scope — "
                        "pass timeout= and checkpoint on Empty "
                        "(docs/serving.md 'Query lifecycle')")
