"""tpu-lint rule engine: file collection, rule registry, suppression
and baseline semantics, JSON/human rendering, CLI entry.

Exit-code contract (wired into `tools lint` and tier-1):
  0 — clean (no unsuppressed, unbaselined findings)
  1 — findings
  2 — internal error (a rule crashed, or the engine itself did)
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import subprocess
import time
import traceback
from typing import Callable, Dict, Iterable, List, Optional, Set

from spark_rapids_tpu.lint.astutil import FileCtx
from spark_rapids_tpu.lint.config import LintConfig, load_config

JSON_SCHEMA_VERSION = 1


@dataclasses.dataclass(frozen=True)
class Finding:
    rule: str
    path: str          # repo-relative, forward slashes
    line: int
    col: int
    message: str

    def fingerprint(self, line_text: str) -> str:
        # line-TEXT based (not line-number based) so unrelated edits
        # above a baselined finding don't churn the baseline file
        h = hashlib.sha256(
            f"{self.rule}|{self.path}|{line_text or self.message}"
            .encode("utf-8"))
        return h.hexdigest()[:16]


@dataclasses.dataclass
class Rule:
    name: str
    doc: str
    func: Callable


RULES: Dict[str, Rule] = {}


def rule(name: str, doc: str):
    """Register a rule. The function receives the PackageContext and
    yields Findings."""
    def deco(func):
        RULES[name] = Rule(name, doc, func)
        return func
    return deco


class PackageContext:
    """Everything a rule needs: every scanned file parsed once, plus
    the config and root."""

    def __init__(self, root: str, config: LintConfig,
                 files: List[FileCtx]):
        self.root = root
        self.config = config
        self.files = files
        self.by_rel: Dict[str, FileCtx] = {f.rel: f for f in files}

    def file(self, rel: str) -> Optional[FileCtx]:
        return self.by_rel.get(rel)

    def in_scope(self, rel: str, scope: Iterable[str]) -> bool:
        return any(rel == s or (s.endswith("/") and rel.startswith(s))
                   for s in scope)


@dataclasses.dataclass
class LintResult:
    root: str
    findings: List[Finding]            # active (reported)
    suppressed: int
    baselined: int
    files: int
    internal_errors: List[str]
    pctx: Optional["PackageContext"] = None
    # findings matched by the baseline file (not reported, but
    # --fix-baseline must re-capture them or accepted debt would be
    # silently dropped from the rewritten file)
    baselined_findings: List[Finding] = dataclasses.field(
        default_factory=list)
    # baseline entries no longer matching ANY current finding: the debt
    # was paid but the entry lingers. Informational (exit stays 0) —
    # reported as `baseline-stale` notes and pruned by --fix-baseline.
    stale_baseline: List[dict] = dataclasses.field(default_factory=list)
    # per-rule wall seconds + the total analysis wall, so the data-flow
    # tier's cost is visible in --json and gated by time_budget_s
    rule_timings: Dict[str, float] = dataclasses.field(
        default_factory=dict)
    wall_s: float = 0.0

    @property
    def clean(self) -> bool:
        return not self.findings and not self.internal_errors


def default_root() -> str:
    """Repo root = parent of the installed package directory."""
    import spark_rapids_tpu
    return os.path.dirname(
        os.path.dirname(os.path.abspath(spark_rapids_tpu.__file__)))


def collect_files(root: str, config: LintConfig) -> List[FileCtx]:
    out: List[FileCtx] = []
    for scan in config.scan_roots:
        base = os.path.join(root, scan)
        if os.path.isfile(base):
            out.append(FileCtx(root, scan))
            continue
        for dirpath, dirnames, filenames in os.walk(base):
            dirnames[:] = sorted(d for d in dirnames
                                 if d != "__pycache__")
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    rel = os.path.relpath(os.path.join(dirpath, fn),
                                          root)
                    out.append(FileCtx(root, rel))
    return out


def _load_baseline(root: str, config: LintConfig) -> Dict[str, dict]:
    path = os.path.join(root, config.baseline)
    if not os.path.exists(path):
        return {}
    with open(path, "r", encoding="utf-8") as f:
        data = json.load(f)
    return {e["fingerprint"]: e for e in data.get("findings", [])}


def write_baseline(root: str, config: LintConfig,
                   findings: List[Finding], pctx: PackageContext) -> str:
    """--fix-baseline: capture current findings as accepted debt.
    Stale entries (not in ``findings``) are pruned by construction.
    Churn guard: when the accepted-debt SET is unchanged — same
    fingerprints, which hash line TEXT, not line numbers — the file is
    left byte-identical, so edits that merely shift lines (or shrink a
    line's suppressed-rule set elsewhere) never rewrite line_hints."""
    path = os.path.join(root, config.baseline)
    entries = []
    for f in sorted(findings, key=lambda f: (f.path, f.line, f.rule)):
        entries.append({
            "fingerprint": f.fingerprint(_line_text(pctx, f)),
            "rule": f.rule, "path": f.path, "line_hint": f.line,
            "message": f.message,
        })
    existing = _load_baseline(root, config)
    if existing and set(existing) == {e["fingerprint"]
                                      for e in entries}:
        return path
    with open(path, "w", encoding="utf-8") as fh:
        json.dump({"version": JSON_SCHEMA_VERSION, "findings": entries},
                  fh, indent=2, sort_keys=True)
        fh.write("\n")
    return path


def _line_text(pctx: PackageContext, f: Finding) -> str:
    fctx = pctx.file(f.path)
    return fctx.line_text(f.line) if fctx is not None else ""


def run_lint(root: Optional[str] = None,
             config: Optional[LintConfig] = None) -> LintResult:
    t_start = time.perf_counter()
    root = root or default_root()
    config = config or load_config(root)
    files = collect_files(root, config)
    pctx = PackageContext(root, config, files)

    raw: List[Finding] = []
    internal: List[str] = []
    timings: Dict[str, float] = {}
    for r in RULES.values():
        t0 = time.perf_counter()
        try:
            raw.extend(r.func(pctx))
        except Exception:
            internal.append(
                f"rule {r.name} crashed:\n{traceback.format_exc()}")
        timings[r.name] = time.perf_counter() - t0
    # suppressions without a reason are findings themselves and are
    # not suppressible (otherwise the grammar could erase its own gate)
    for fctx in files:
        for line, msg in fctx.bad_suppressions:
            raw.append(Finding("bad-suppression", fctx.rel, line, 1,
                               msg))

    suppressed = 0
    unsuppressed: List[Finding] = []
    for f in raw:
        fctx = pctx.file(f.path)
        if f.rule != "bad-suppression" and fctx is not None \
                and fctx.suppressed(f.rule, f.line):
            suppressed += 1
        else:
            unsuppressed.append(f)

    baseline = _load_baseline(root, config)
    baselined: List[Finding] = []
    active: List[Finding] = []
    matched: Set[str] = set()
    for f in unsuppressed:
        fp = f.fingerprint(_line_text(pctx, f))
        if fp in baseline:
            baselined.append(f)
            matched.add(fp)
        else:
            active.append(f)
    # entries whose debt was paid (the finding is gone — fixed, or its
    # suppressed-rule set shrank) linger as dead weight and churn every
    # rewrite: surface them as informational `baseline-stale` notes so
    # --fix-baseline prunes them deliberately, not accidentally
    stale = [e for fp, e in sorted(baseline.items())
             if fp not in matched]
    active.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return LintResult(root=root, findings=active, suppressed=suppressed,
                      baselined=len(baselined), files=len(files),
                      internal_errors=internal, pctx=pctx,
                      baselined_findings=baselined,
                      stale_baseline=stale, rule_timings=timings,
                      wall_s=time.perf_counter() - t_start)


# -- rendering -------------------------------------------------------------

def render_json(result: LintResult,
                pctx: Optional[PackageContext] = None,
                budget: Optional[float] = None) -> str:
    findings = []
    for f in result.findings:
        findings.append({
            "rule": f.rule, "path": f.path, "line": f.line,
            "col": f.col, "message": f.message,
            "fingerprint": f.fingerprint(
                _line_text(pctx, f) if pctx is not None else ""),
        })
    if budget is None:
        # the config default; run_cli passes the effective budget so a
        # --time-budget override and the exit code agree with the JSON
        budget = (result.pctx.config.time_budget_s
                  if result.pctx is not None else None)
    return json.dumps({
        "version": JSON_SCHEMA_VERSION,
        "root": result.root,
        "clean": result.clean,
        "counts": {
            "findings": len(result.findings),
            "suppressed": result.suppressed,
            "baselined": result.baselined,
            "files": result.files,
        },
        "rules": sorted(RULES),
        "findings": findings,
        "staleBaseline": result.stale_baseline,
        "timings": {
            "perRule": {k: round(v, 4)
                        for k, v in sorted(result.rule_timings.items())},
            "totalSeconds": round(result.wall_s, 4),
            "budgetSeconds": budget,
        },
        "internalErrors": result.internal_errors,
    }, indent=2)


def render_human(result: LintResult) -> str:
    lines: List[str] = []
    for f in result.findings:
        lines.append(f"{f.path}:{f.line}:{f.col}: [{f.rule}] "
                     f"{f.message}")
    for e in result.stale_baseline:
        # informational: the debt was paid; exit code is unaffected
        lines.append(f"{e['path']}: note: [baseline-stale] entry "
                     f"`{e['rule']}` no longer matches any finding — "
                     f"run --fix-baseline to prune it")
    lines.append(
        f"tpu-lint: {len(result.findings)} finding(s), "
        f"{result.suppressed} suppressed, {result.baselined} baselined "
        f"({len(result.stale_baseline)} stale) "
        f"across {result.files} files "
        f"({len(RULES)} rules, {result.wall_s:.1f}s)")
    return "\n".join(lines)


def render_github(result: LintResult) -> str:
    """GitHub Actions workflow-command annotations: one ::error per
    finding (file/line/col land as inline PR annotations), ::notice
    for stale baseline entries, ::warning for internal errors."""

    def esc(s: str) -> str:
        # workflow-command data escapes (docs.github.com: % -> %25,
        # CR/LF -> %0D/%0A)
        return (s.replace("%", "%25").replace("\r", "%0D")
                .replace("\n", "%0A"))

    lines: List[str] = []
    for f in result.findings:
        lines.append(f"::error file={esc(f.path)},line={f.line},"
                     f"col={f.col},title=tpu-lint {esc(f.rule)}::"
                     f"{esc(f.message)}")
    for e in result.stale_baseline:
        lines.append(f"::notice file={esc(e['path'])},"
                     f"title=tpu-lint baseline-stale::baseline entry "
                     f"`{esc(e['rule'])}` no longer matches any "
                     f"finding — run --fix-baseline to prune it")
    for err in result.internal_errors:
        lines.append(f"::warning title=tpu-lint internal::{esc(err)}")
    lines.append(f"tpu-lint: {len(result.findings)} finding(s) across "
                 f"{result.files} files")
    return "\n".join(lines)


def changed_files(root: str, base: str) -> Optional[Set[str]]:
    """ROOT-relative paths changed vs ``base`` per
    ``git diff --name-only`` (plus untracked files, so a brand-new
    module is linted pre-commit too); None when git fails. ``git
    diff`` emits toplevel-relative paths, so when the lint root is
    nested inside the worktree they are re-based onto the root —
    otherwise the intersection with finding paths would be empty and
    the incremental mode would silently pass bad code."""
    try:
        # quotepath=off: default git octal-escapes non-ASCII paths
        # ("caf\303\251.py"), which would never match a finding path
        # and silently drop that file from the incremental gate
        out = subprocess.run(
            ["git", "-C", root, "-c", "core.quotepath=off", "diff",
             "--name-only", base],
            capture_output=True, text=True, timeout=30)
        if out.returncode != 0:
            return None
        prefix = ""
        pfx = subprocess.run(
            ["git", "-C", root, "rev-parse", "--show-prefix"],
            capture_output=True, text=True, timeout=30)
        if pfx.returncode == 0:
            prefix = pfx.stdout.strip()
        paths = {p.strip()[len(prefix):] for p in out.stdout.splitlines()
                 if p.strip() and p.strip().startswith(prefix)}
        extra = subprocess.run(
            ["git", "-C", root, "-c", "core.quotepath=off", "ls-files",
             "--others", "--exclude-standard"],
            capture_output=True, text=True, timeout=30)
        if extra.returncode == 0:
            # ls-files paths are already relative to the -C directory
            paths |= {p.strip() for p in extra.stdout.splitlines()
                      if p.strip()}
        return paths
    except Exception:
        return None


def run_cli(root: Optional[str] = None, as_json: bool = False,
            fix_baseline: bool = False, fmt: Optional[str] = None,
            changed_only: Optional[str] = None,
            time_budget: Optional[float] = None) -> int:
    """`tools lint` body. Exit contract: 0 clean / 1 findings /
    2 internal error — including a run whose wall exceeds the time
    budget (the gate must stay affordable, docs/linting.md).

    ``fmt``: "human" (default) / "json" / "github" (workflow-command
    annotations); ``as_json`` is the legacy spelling of fmt="json".
    ``changed_only``: a git base ref — findings are restricted to files
    in ``git diff --name-only <base>`` (+ untracked), while the
    ANALYSIS still covers the whole package so cross-module data-flow
    rules see true call graphs. ``time_budget``: override the
    config's ``time_budget_s``."""
    try:
        root = root or default_root()
        config = load_config(root)
        result = run_lint(root, config)
        if result.files == 0:
            # a wrong --root (or a renamed scan root) must not turn
            # the CI gate green by linting nothing
            print(f"tpu-lint: no files found under {root} "
                  f"(scan roots: {', '.join(config.scan_roots)})")
            return 2
        if result.internal_errors:
            for e in result.internal_errors:
                print(e)
            return 2
        if fix_baseline:
            # active findings PLUS still-live accepted debt: rewriting
            # with only the new findings would un-accept the old ones.
            # Stale entries are pruned by construction (they match no
            # current finding, so they are in neither list).
            keep = result.findings + result.baselined_findings
            path = write_baseline(root, config, keep, result.pctx)
            pruned = len(result.stale_baseline)
            print(f"tpu-lint: baselined {len(keep)} finding(s) into "
                  f"{path}"
                  + (f" ({pruned} stale entr"
                     f"{'y' if pruned == 1 else 'ies'} pruned)"
                     if pruned else ""))
            return 0
        if changed_only is not None:
            changed = changed_files(root, changed_only)
            if changed is None:
                print(f"tpu-lint: --changed-only: git diff "
                      f"--name-only {changed_only} failed under "
                      f"{root}")
                return 2
            result = dataclasses.replace(
                result,
                findings=[f for f in result.findings
                          if f.path in changed],
                stale_baseline=[e for e in result.stale_baseline
                                if e.get("path") in changed])
        budget = (time_budget if time_budget is not None
                  else config.time_budget_s)
        fmt = fmt or ("json" if as_json else "human")
        if fmt == "json":
            print(render_json(result, result.pctx, budget=budget))
        elif fmt == "github":
            print(render_github(result))
        else:
            print(render_human(result))
        if budget and result.wall_s > budget:
            import sys
            # stderr: the budget breach must not corrupt --json stdout
            print(f"tpu-lint: analysis wall {result.wall_s:.1f}s "
                  f"exceeded the {budget:.0f}s budget — the gate must "
                  f"stay affordable; profile the slow rule "
                  f"(--json timings.perRule) or raise time_budget_s "
                  f"in tpu-lint.json", file=sys.stderr)
            return 2
        return 0 if result.clean else 1
    except Exception:
        traceback.print_exc()
        return 2
