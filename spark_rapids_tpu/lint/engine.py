"""tpu-lint rule engine: file collection, rule registry, suppression
and baseline semantics, JSON/human rendering, CLI entry.

Exit-code contract (wired into `tools lint` and tier-1):
  0 — clean (no unsuppressed, unbaselined findings)
  1 — findings
  2 — internal error (a rule crashed, or the engine itself did)
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import traceback
from typing import Callable, Dict, Iterable, List, Optional

from spark_rapids_tpu.lint.astutil import FileCtx
from spark_rapids_tpu.lint.config import LintConfig, load_config

JSON_SCHEMA_VERSION = 1


@dataclasses.dataclass(frozen=True)
class Finding:
    rule: str
    path: str          # repo-relative, forward slashes
    line: int
    col: int
    message: str

    def fingerprint(self, line_text: str) -> str:
        # line-TEXT based (not line-number based) so unrelated edits
        # above a baselined finding don't churn the baseline file
        h = hashlib.sha256(
            f"{self.rule}|{self.path}|{line_text or self.message}"
            .encode("utf-8"))
        return h.hexdigest()[:16]


@dataclasses.dataclass
class Rule:
    name: str
    doc: str
    func: Callable


RULES: Dict[str, Rule] = {}


def rule(name: str, doc: str):
    """Register a rule. The function receives the PackageContext and
    yields Findings."""
    def deco(func):
        RULES[name] = Rule(name, doc, func)
        return func
    return deco


class PackageContext:
    """Everything a rule needs: every scanned file parsed once, plus
    the config and root."""

    def __init__(self, root: str, config: LintConfig,
                 files: List[FileCtx]):
        self.root = root
        self.config = config
        self.files = files
        self.by_rel: Dict[str, FileCtx] = {f.rel: f for f in files}

    def file(self, rel: str) -> Optional[FileCtx]:
        return self.by_rel.get(rel)

    def in_scope(self, rel: str, scope: Iterable[str]) -> bool:
        return any(rel == s or (s.endswith("/") and rel.startswith(s))
                   for s in scope)


@dataclasses.dataclass
class LintResult:
    root: str
    findings: List[Finding]            # active (reported)
    suppressed: int
    baselined: int
    files: int
    internal_errors: List[str]
    pctx: Optional["PackageContext"] = None
    # findings matched by the baseline file (not reported, but
    # --fix-baseline must re-capture them or accepted debt would be
    # silently dropped from the rewritten file)
    baselined_findings: List[Finding] = dataclasses.field(
        default_factory=list)

    @property
    def clean(self) -> bool:
        return not self.findings and not self.internal_errors


def default_root() -> str:
    """Repo root = parent of the installed package directory."""
    import spark_rapids_tpu
    return os.path.dirname(
        os.path.dirname(os.path.abspath(spark_rapids_tpu.__file__)))


def collect_files(root: str, config: LintConfig) -> List[FileCtx]:
    out: List[FileCtx] = []
    for scan in config.scan_roots:
        base = os.path.join(root, scan)
        if os.path.isfile(base):
            out.append(FileCtx(root, scan))
            continue
        for dirpath, dirnames, filenames in os.walk(base):
            dirnames[:] = sorted(d for d in dirnames
                                 if d != "__pycache__")
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    rel = os.path.relpath(os.path.join(dirpath, fn),
                                          root)
                    out.append(FileCtx(root, rel))
    return out


def _load_baseline(root: str, config: LintConfig) -> Dict[str, dict]:
    path = os.path.join(root, config.baseline)
    if not os.path.exists(path):
        return {}
    with open(path, "r", encoding="utf-8") as f:
        data = json.load(f)
    return {e["fingerprint"]: e for e in data.get("findings", [])}


def write_baseline(root: str, config: LintConfig,
                   findings: List[Finding], pctx: PackageContext) -> str:
    """--fix-baseline: capture current findings as accepted debt."""
    path = os.path.join(root, config.baseline)
    entries = []
    for f in sorted(findings, key=lambda f: (f.path, f.line, f.rule)):
        entries.append({
            "fingerprint": f.fingerprint(_line_text(pctx, f)),
            "rule": f.rule, "path": f.path, "line_hint": f.line,
            "message": f.message,
        })
    with open(path, "w", encoding="utf-8") as fh:
        json.dump({"version": JSON_SCHEMA_VERSION, "findings": entries},
                  fh, indent=2, sort_keys=True)
        fh.write("\n")
    return path


def _line_text(pctx: PackageContext, f: Finding) -> str:
    fctx = pctx.file(f.path)
    return fctx.line_text(f.line) if fctx is not None else ""


def run_lint(root: Optional[str] = None,
             config: Optional[LintConfig] = None) -> LintResult:
    root = root or default_root()
    config = config or load_config(root)
    files = collect_files(root, config)
    pctx = PackageContext(root, config, files)

    raw: List[Finding] = []
    internal: List[str] = []
    for r in RULES.values():
        try:
            raw.extend(r.func(pctx))
        except Exception:
            internal.append(
                f"rule {r.name} crashed:\n{traceback.format_exc()}")
    # suppressions without a reason are findings themselves and are
    # not suppressible (otherwise the grammar could erase its own gate)
    for fctx in files:
        for line, msg in fctx.bad_suppressions:
            raw.append(Finding("bad-suppression", fctx.rel, line, 1,
                               msg))

    suppressed = 0
    unsuppressed: List[Finding] = []
    for f in raw:
        fctx = pctx.file(f.path)
        if f.rule != "bad-suppression" and fctx is not None \
                and fctx.suppressed(f.rule, f.line):
            suppressed += 1
        else:
            unsuppressed.append(f)

    baseline = _load_baseline(root, config)
    baselined: List[Finding] = []
    active: List[Finding] = []
    for f in unsuppressed:
        if f.fingerprint(_line_text(pctx, f)) in baseline:
            baselined.append(f)
        else:
            active.append(f)
    active.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return LintResult(root=root, findings=active, suppressed=suppressed,
                      baselined=len(baselined), files=len(files),
                      internal_errors=internal, pctx=pctx,
                      baselined_findings=baselined)


# -- rendering -------------------------------------------------------------

def render_json(result: LintResult,
                pctx: Optional[PackageContext] = None) -> str:
    findings = []
    for f in result.findings:
        findings.append({
            "rule": f.rule, "path": f.path, "line": f.line,
            "col": f.col, "message": f.message,
            "fingerprint": f.fingerprint(
                _line_text(pctx, f) if pctx is not None else ""),
        })
    return json.dumps({
        "version": JSON_SCHEMA_VERSION,
        "root": result.root,
        "clean": result.clean,
        "counts": {
            "findings": len(result.findings),
            "suppressed": result.suppressed,
            "baselined": result.baselined,
            "files": result.files,
        },
        "rules": sorted(RULES),
        "findings": findings,
        "internalErrors": result.internal_errors,
    }, indent=2)


def render_human(result: LintResult) -> str:
    lines: List[str] = []
    for f in result.findings:
        lines.append(f"{f.path}:{f.line}:{f.col}: [{f.rule}] "
                     f"{f.message}")
    lines.append(
        f"tpu-lint: {len(result.findings)} finding(s), "
        f"{result.suppressed} suppressed, {result.baselined} baselined "
        f"across {result.files} files "
        f"({len(RULES)} rules)")
    return "\n".join(lines)


def run_cli(root: Optional[str] = None, as_json: bool = False,
            fix_baseline: bool = False) -> int:
    """`tools lint` body. Exit contract: 0 clean / 1 findings /
    2 internal error."""
    try:
        root = root or default_root()
        config = load_config(root)
        result = run_lint(root, config)
        if result.files == 0:
            # a wrong --root (or a renamed scan root) must not turn
            # the CI gate green by linting nothing
            print(f"tpu-lint: no files found under {root} "
                  f"(scan roots: {', '.join(config.scan_roots)})")
            return 2
        if result.internal_errors:
            for e in result.internal_errors:
                print(e)
            return 2
        if fix_baseline:
            # active findings PLUS still-live accepted debt: rewriting
            # with only the new findings would un-accept the old ones
            keep = result.findings + result.baselined_findings
            path = write_baseline(root, config, keep, result.pctx)
            print(f"tpu-lint: baselined {len(keep)} "
                  f"finding(s) into {path}")
            return 0
        print(render_json(result, result.pctx) if as_json
              else render_human(result))
        return 0 if result.clean else 1
    except Exception:
        traceback.print_exc()
        return 2
