"""Rule family 1 — retry coverage (docs/robustness.md).

Every device-allocation/dispatch call in the retry scope (exec/*,
parallel/*, columnar/transfer.py, columnar/device.py) must run under
the PR-4 OOM protocol: lexically inside a closure handed to
``with_retry`` / ``with_split_retry`` / ``io_with_retry`` (directly or
through the module-local call graph), or in an allowlisted site whose
config entry carries a written reason.

The check is lexical + module-local-transitive on purpose: dynamic
"some caller three modules up wraps me" coverage is exactly the
hand-audit this rule replaces. Sites that are genuinely covered
non-locally are the allowlist (protocol implementation layer) or a
per-line suppression with a reason.
"""

from __future__ import annotations

import ast
from typing import Set

from spark_rapids_tpu.lint import astutil as A
from spark_rapids_tpu.lint.engine import Finding, rule


def _covered_nodes(fctx: A.FileCtx, wrappers) -> Set[int]:
    """ids of function/lambda nodes whose bodies execute under a retry
    combinator: closures passed to a wrapper (positionally or by
    name), closed transitively over module-local calls — with_retry
    re-runs the whole closure, so everything it calls is in scope."""
    covered: Set[int] = set()
    covered_names: Set[str] = set()
    by_name = A.defs_by_name(fctx.tree)
    for call in A.file_calls(fctx):
        if A.call_tail(call) not in wrappers:
            continue
        for arg in A.call_args(call):
            if isinstance(arg, ast.Lambda):
                covered.add(id(arg))
            elif isinstance(arg, ast.Name):
                covered_names.add(arg.id)
    node_of = {}
    for name, nodes in by_name.items():
        for n in nodes:
            node_of[id(n)] = n
            if name in covered_names:
                covered.add(id(n))
    # transitive closure over module-local calls
    work = True
    all_funcs = [n for ns in by_name.values() for n in ns]
    lambdas = [n for n in ast.walk(fctx.tree)
               if isinstance(n, ast.Lambda)]
    while work:
        work = False
        for fn in all_funcs + lambdas:
            if id(fn) not in covered:
                continue
            for call in A.walk_calls(fn):
                t = A.call_tail(call)
                for target in by_name.get(t, ()):
                    if id(target) not in covered:
                        covered.add(id(target))
                        work = True
    return covered


def _inside_wrapper_arg(call: ast.Call, wrappers) -> bool:
    """The call expression itself sits inside an argument of a retry
    combinator call (e.g. ``with_retry(partial(finish_upload, x))``)."""
    for anc in A.ancestors(call):
        if isinstance(anc, ast.Call) and A.call_tail(anc) in wrappers:
            return True
        if isinstance(anc, ast.stmt):
            return False
    return False


@rule("retry-coverage",
      "device allocation/dispatch sites must run under "
      "with_retry/with_split_retry/io_with_retry (PR-4 protocol)")
def check_retry_coverage(pctx):
    cfg = pctx.config
    wrappers = set(cfg.retry_wrappers)
    entry = set(cfg.alloc_entrypoints)
    for fctx in pctx.files:
        if not pctx.in_scope(fctx.rel, cfg.retry_scope):
            continue
        covered = _covered_nodes(fctx, wrappers)
        for call in A.file_calls(fctx):
            tail = A.call_tail(call)
            if tail not in entry:
                continue
            enclosing = A.enclosing_functions(call)
            if any(id(fn) in covered for fn in enclosing):
                continue
            if _inside_wrapper_arg(call, wrappers):
                continue
            allowed = False
            for fn in enclosing:
                if isinstance(fn, ast.Lambda):
                    continue
                key = f"{fctx.rel}::{A.qualname(fn)}"
                if key in cfg.retry_allowlist:
                    allowed = True
                    break
            if allowed:
                continue
            yield Finding(
                "retry-coverage", fctx.rel, call.lineno,
                call.col_offset + 1,
                f"`{tail}` allocates/dispatches on device outside the "
                f"OOM retry protocol — wrap the site in "
                f"with_retry/with_split_retry (docs/robustness.md) or "
                f"allowlist it with a reason")
