"""Rule family 3 — concurrency lint over the lock-acquisition graph.

Scope: memory.py, resource.py, jit_cache.py, serve/* — the shared
mutable core PR 7's review pass hand-audited. Locks are identified by
attribute path (``DeviceStore._lock``, ``AdmissionController._cv``,
``module._NAME``); acquisition = a ``with <lock>:`` statement.

``lock-order``      — nested acquisitions define directed edges; a
                      cycle in the global graph means two code paths
                      take the same locks in opposite orders (ABBA).
                      One level of same-file interprocedural edges is
                      followed (``with A: self.m()`` where ``m``
                      acquires B).
``lock-blocking-call`` — holding a critical lock (DeviceStore /
                      semaphore / scheduler / jit-cache), flag calls
                      that can park the whole process: socket ops,
                      ``time.sleep``, device allocation/dispatch
                      entrypoints, and ``.wait()`` on a DIFFERENT
                      known lock.
``check-then-act``  — ``if k (not) in self.d: self.d[k] = ...`` on a
                      shared dict outside any ``with`` lock block, in
                      a class that owns a lock: the classic racy
                      get-or-create PR 7 fixed by hand in the server.
"""

from __future__ import annotations

import ast
import os
from typing import Dict, List, Optional, Set, Tuple

from spark_rapids_tpu.lint import astutil as A
from spark_rapids_tpu.lint.engine import Finding, rule

_LOCK_CTORS = {"Lock": "lock", "RLock": "rlock", "Condition": "cond"}
_SOCKET_BLOCKING = {"accept", "recv", "recv_into", "connect",
                    "sendall"}


def _mod_name(fctx: A.FileCtx) -> str:
    return os.path.splitext(os.path.basename(fctx.rel))[0]


def _collect_locks(fctx: A.FileCtx) -> Dict[str, str]:
    """lock id -> kind. ``self.X = threading.Lock()`` in class C gives
    ``C.X``; module-global assignments give ``module.NAME``."""
    locks: Dict[str, str] = {}
    mod = _mod_name(fctx)
    for node in ast.walk(fctx.tree):
        if not isinstance(node, ast.Assign) or not isinstance(
                node.value, ast.Call):
            continue
        tail = A.call_tail(node.value)
        if tail not in _LOCK_CTORS:
            continue
        for t in node.targets:
            p = A.attr_path(t)
            if p is None:
                continue
            if p.startswith("self."):
                cls = A.enclosing_class(node)
                if cls is not None:
                    locks[f"{cls.name}.{p[5:]}"] = _LOCK_CTORS[tail]
            elif "." not in p:
                locks[f"{mod}.{p}"] = _LOCK_CTORS[tail]
    return locks


def _lock_id(fctx: A.FileCtx, locks: Dict[str, str],
             expr: ast.AST) -> Optional[str]:
    """Resolve a with-context / receiver expression to a lock id."""
    p = A.attr_path(expr)
    if p is None:
        return None
    if p.startswith("self."):
        cls = A.enclosing_class(expr)
        if cls is not None:
            lid = f"{cls.name}.{p[5:]}"
            if lid in locks:
                return lid
        return None
    lid = f"{_mod_name(fctx)}.{p}"
    return lid if lid in locks else None


def _func_acquires(locks: Dict[str, str], fctx: A.FileCtx,
                   fn: ast.AST) -> Set[str]:
    out: Set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.With):
            for item in node.items:
                lid = _lock_id(fctx, locks, item.context_expr)
                if lid is not None:
                    out.add(lid)
    return out


class _Graph:
    def __init__(self):
        # (from, to) -> first site (rel, line, detail)
        self.edges: Dict[Tuple[str, str], Tuple[str, int, str]] = {}

    def add(self, a: str, b: str, rel: str, line: int,
            detail: str) -> None:
        if a != b and (a, b) not in self.edges:
            self.edges[(a, b)] = (rel, line, detail)

    def cycles(self) -> List[List[str]]:
        """Minimal reporting: find 2-node cycles plus any longer cycle
        via DFS (small graphs — a handful of locks)."""
        adj: Dict[str, Set[str]] = {}
        for (a, b) in self.edges:
            adj.setdefault(a, set()).add(b)
        out: List[List[str]] = []
        seen_pairs = set()
        for (a, b) in self.edges:
            if (b, a) in self.edges and (b, a) not in seen_pairs:
                seen_pairs.add((a, b))
                out.append([a, b, a])
        # longer cycles
        def dfs(start, node, path, visited):
            for nxt in adj.get(node, ()):
                if nxt == start and len(path) > 2:
                    out.append(path + [start])
                    return
                if nxt not in visited and len(path) < 6:
                    dfs(start, nxt, path + [nxt], visited | {nxt})
        for start in adj:
            dfs(start, start, [start], {start})
        # dedup rotations
        uniq, keys = [], set()
        for c in out:
            k = frozenset(c)
            if k not in keys:
                keys.add(k)
                uniq.append(c)
        return uniq


def _scoped(pctx):
    for fctx in pctx.files:
        if pctx.in_scope(fctx.rel, pctx.config.concurrency_scope):
            yield fctx


@rule("lock-order",
      "inconsistent lock acquisition order (potential ABBA deadlock) "
      "across memory/resource/serve/jit_cache")
def check_lock_order(pctx):
    graph = _Graph()
    for fctx in _scoped(pctx):
        locks = _collect_locks(fctx)
        if not locks:
            continue
        by_name = A.defs_by_name(fctx.tree)
        acquires = {}
        for name, nodes in by_name.items():
            for n in nodes:
                acquires[id(n)] = (_func_acquires(locks, fctx, n), name)

        def visit(node, held: List[str]):
            if isinstance(node, ast.With):
                ids = []
                for item in node.items:
                    lid = _lock_id(fctx, locks, item.context_expr)
                    if lid is not None:
                        for h in held:
                            graph.add(h, lid, fctx.rel, node.lineno,
                                      f"with {h} held, acquires {lid}")
                        ids.append(lid)
                for child in node.body:
                    visit(child, held + ids)
                return
            if isinstance(node, ast.Call) and held:
                tail = A.call_tail(node)
                for target in by_name.get(tail, ()):
                    # self.m() / module fn(): one interprocedural level
                    inner, _nm = acquires[id(target)]
                    for lid in inner:
                        for h in held:
                            graph.add(h, lid, fctx.rel, node.lineno,
                                      f"call {tail}() acquires {lid} "
                                      f"while holding {h}")
            for child in ast.iter_child_nodes(node):
                # don't descend into nested defs with the held set —
                # their bodies run later, not under this lock
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef,
                                      ast.Lambda)):
                    visit(child, [])
                else:
                    visit(child, held)

        visit(fctx.tree, [])
    for cyc in graph.cycles():
        edges = list(zip(cyc, cyc[1:]))
        site = graph.edges.get(edges[0])
        rel, line = (site[0], site[1]) if site else ("", 1)
        order = " -> ".join(cyc)
        yield Finding(
            "lock-order", rel or "spark_rapids_tpu", line, 1,
            f"inconsistent lock order: {order} — two paths acquire "
            f"these locks in opposite orders (ABBA deadlock window)")


@rule("lock-blocking-call",
      "blocking call while holding a DeviceStore/scheduler-critical "
      "lock stalls every task in the process")
def check_blocking(pctx):
    cfg = pctx.config
    critical = set(cfg.critical_locks)
    entry = set(cfg.alloc_entrypoints)
    for fctx in _scoped(pctx):
        locks = _collect_locks(fctx)
        if not locks:
            continue

        def visit(node, held: List[str]):
            if isinstance(node, ast.With):
                ids = [lid for item in node.items
                       if (lid := _lock_id(fctx, locks,
                                           item.context_expr))
                       is not None]
                for child in node.body:
                    visit(child, held + ids)
                return
            crit = [h for h in held if h in critical]
            if isinstance(node, ast.Call) and crit:
                tail = A.call_tail(node)
                path = A.resolve_path(fctx, node.func)
                bad = None
                if path == "time.sleep":
                    bad = "time.sleep"
                elif tail in _SOCKET_BLOCKING:
                    bad = f"socket .{tail}()"
                elif tail in entry:
                    bad = f"device dispatch `{tail}`"
                elif tail == "wait" and isinstance(node.func,
                                                  ast.Attribute):
                    rid = _lock_id(fctx, locks, node.func.value)
                    if rid is not None and rid not in held:
                        bad = f"wait on a different lock ({rid})"
                if bad is not None:
                    yield_findings.append(Finding(
                        "lock-blocking-call", fctx.rel, node.lineno,
                        node.col_offset + 1,
                        f"{bad} while holding {', '.join(crit)} — "
                        f"move the blocking work outside the lock "
                        f"(the jit_cache get_or_build pattern)"))
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef,
                                      ast.Lambda)):
                    visit(child, [])
                else:
                    visit(child, held)

        yield_findings: List[Finding] = []
        visit(fctx.tree, [])
        for f in yield_findings:
            yield f


def _dict_attrs(cls: ast.ClassDef) -> Set[str]:
    out: Set[str] = set()
    for node in ast.walk(cls):
        if isinstance(node, ast.Assign):
            val = node.value
            is_dict = isinstance(val, ast.Dict) or (
                isinstance(val, ast.Call)
                and A.call_tail(val) in ("dict", "OrderedDict",
                                         "defaultdict"))
            if not is_dict:
                continue
            for t in node.targets:
                p = A.attr_path(t)
                if p is not None and p.startswith("self."):
                    out.add(p[5:])
    return out


def _mentions_attr(expr: ast.AST, attrs: Set[str]) -> Optional[str]:
    for n in ast.walk(expr):
        p = A.attr_path(n)
        if p is not None and p.startswith("self.") and p[5:] in attrs:
            return p[5:]
    return None


@rule("check-then-act",
      "racy get-or-create on a shared dict outside the owning lock")
def check_then_act(pctx):
    for fctx in _scoped(pctx):
        locks = _collect_locks(fctx)
        for cls in [n for n in ast.walk(fctx.tree)
                    if isinstance(n, ast.ClassDef)]:
            cls_locks = {lid for lid in locks
                         if lid.startswith(cls.name + ".")}
            if not cls_locks:
                continue
            dicts = _dict_attrs(cls)
            if not dicts:
                continue
            for node in ast.walk(cls):
                if not isinstance(node, ast.If):
                    continue
                # test must be a membership check on a shared dict
                tested = None
                for cmp in ast.walk(node.test):
                    if isinstance(cmp, ast.Compare) and any(
                            isinstance(op, (ast.In, ast.NotIn))
                            for op in cmp.ops):
                        tested = _mentions_attr(cmp, dicts)
                if tested is None:
                    continue
                # body (or else) must write the same dict
                writes = False
                for sub in ast.walk(node):
                    if isinstance(sub, (ast.Assign, ast.AugAssign)):
                        tg = sub.targets if isinstance(
                            sub, ast.Assign) else [sub.target]
                        for t in tg:
                            if isinstance(t, ast.Subscript) and \
                                    _mentions_attr(t.value,
                                                   {tested}):
                                writes = True
                if not writes:
                    continue
                # any enclosing with on a class lock?
                guarded = False
                for anc in A.ancestors(node):
                    if isinstance(anc, ast.With):
                        for item in anc.items:
                            if _lock_id(fctx, locks,
                                        item.context_expr) is not None:
                                guarded = True
                if guarded:
                    continue
                yield Finding(
                    "check-then-act", fctx.rel, node.lineno,
                    node.col_offset + 1,
                    f"check-then-act on shared dict `self.{tested}` "
                    f"outside a lock — two threads can both miss and "
                    f"both insert; hold the owning lock (or use "
                    f"setdefault under it)")
