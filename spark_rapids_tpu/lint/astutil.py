"""Shared AST plumbing for tpu-lint (stdlib only).

One parse per file, parent pointers threaded through the tree, import
alias resolution, and the suppression-comment scanner. Rule modules
build on these so every rule sees the same view of a file.
"""

from __future__ import annotations

import ast
import io
import os
import re
import tokenize
from typing import Dict, Iterator, List, Optional, Tuple

_PARENT = "_tpulint_parent"

# suppression grammar (docs/linting.md): a comment containing
#   tpu-lint: disable=rule-a(reason text),rule-b(other reason)
# suppresses the named rules on that physical line; a standalone
# comment line suppresses the NEXT line (for statements too long to
# carry the reason inline). The reason is MANDATORY — a bare
# `disable=rule` is itself reported (bad-suppression) — and may not
# contain parentheses. Parsing is ANCHORED: items must be a strict
# comma-separated list, so prose after the list (or parens inside a
# reason) fails the whole comment cleanly instead of registering
# fragments of it as bogus rules.
SUPPRESS_RE = re.compile(r"tpu-lint:\s*disable=(?P<items>.*)")
ITEM_RE = re.compile(r"([A-Za-z][A-Za-z0-9_-]*)\s*(?:\(([^()]*)\))?\s*")


class FileCtx:
    """One parsed source file: tree with parent links, import alias
    map, and parsed suppressions."""

    def __init__(self, root: str, rel: str):
        self.root = root
        self.rel = rel.replace(os.sep, "/")
        self.path = os.path.join(root, rel)
        with open(self.path, "r", encoding="utf-8") as f:
            self.source = f.read()
        self.lines = self.source.splitlines()
        self.tree = ast.parse(self.source, filename=self.rel)
        for node in ast.walk(self.tree):
            for child in ast.iter_child_nodes(node):
                setattr(child, _PARENT, node)
        # alias -> full dotted target ("jnp" -> "jax.numpy",
        # "R" -> "spark_rapids_tpu.retry",
        # "JitCache" -> "spark_rapids_tpu.jit_cache.JitCache")
        self.imports: Dict[str, str] = {}
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    self.imports[a.asname or a.name.split(".")[0]] = (
                        a.name if a.asname else a.name.split(".")[0])
            elif isinstance(node, ast.ImportFrom) and node.module \
                    and not node.level:
                for a in node.names:
                    self.imports[a.asname or a.name] = \
                        f"{node.module}.{a.name}"
        # line -> [(rule, reason)] and invalid-suppression records
        self.suppressions: Dict[int, List[Tuple[str, str]]] = {}
        self.bad_suppressions: List[Tuple[int, str]] = []
        self._scan_suppressions()

    # -- suppressions ------------------------------------------------------

    def _scan_suppressions(self) -> None:
        try:
            tokens = list(tokenize.generate_tokens(
                io.StringIO(self.source).readline))
        except tokenize.TokenError:
            return
        for tok in tokens:
            if tok.type != tokenize.COMMENT:
                continue
            m = SUPPRESS_RE.search(tok.string)
            if m is None:
                continue
            line = tok.start[0]
            # a comment-only line applies to the next source line
            standalone = tok.line[:tok.start[1]].strip() == ""
            target = line + 1 if standalone else line
            items = m.group("items").strip()
            if not items:
                self.bad_suppressions.append(
                    (line, "empty tpu-lint disable list"))
                continue
            parsed, bad = self._parse_items(items)
            if bad is not None:
                self.bad_suppressions.append((line, bad))
                continue  # malformed list: suppress NOTHING
            for name, reason in parsed:
                if reason is None or not reason.strip():
                    self.bad_suppressions.append(
                        (line, f"suppression of `{name}` carries no "
                               f"reason — write disable={name}(why)"))
                    continue
                self.suppressions.setdefault(target, []).append(
                    (name, reason.strip()))

    @staticmethod
    def _parse_items(items: str):
        """Anchored parse of `rule(reason),rule(reason)`; returns
        (parsed, error). Any trailing prose or parens inside a reason
        is an error for the WHOLE comment — fragments of free text
        must never register as rules."""
        parsed = []
        pos = 0
        while pos < len(items):
            m = ITEM_RE.match(items, pos)
            if m is None or m.end() == pos:
                return [], (f"malformed tpu-lint disable list at "
                            f"{items[pos:][:40]!r} — expected "
                            f"rule-name(reason)[, ...]; reasons may "
                            f"not contain parentheses")
            parsed.append((m.group(1), m.group(2)))
            pos = m.end()
            if pos < len(items):
                if items[pos] != ",":
                    return [], (f"unexpected text after suppression "
                                f"list: {items[pos:][:40]!r}")
                pos += 1
                while pos < len(items) and items[pos].isspace():
                    pos += 1
        return parsed, None

    def suppressed(self, rule: str, line: int) -> bool:
        return any(r == rule for r, _ in self.suppressions.get(line, []))

    def line_text(self, line: int) -> str:
        if 1 <= line <= len(self.lines):
            return self.lines[line - 1].strip()
        return ""


# -- tree helpers ----------------------------------------------------------

def parent(node: ast.AST) -> Optional[ast.AST]:
    return getattr(node, _PARENT, None)


def ancestors(node: ast.AST) -> Iterator[ast.AST]:
    cur = parent(node)
    while cur is not None:
        yield cur
        cur = parent(cur)


def enclosing_functions(node: ast.AST) -> List[ast.AST]:
    """Enclosing FunctionDef/AsyncFunctionDef/Lambda nodes,
    innermost first."""
    return [a for a in ancestors(node)
            if isinstance(a, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda))]


def enclosing_class(node: ast.AST) -> Optional[ast.ClassDef]:
    for a in ancestors(node):
        if isinstance(a, ast.ClassDef):
            return a
    return None


def qualname(node: ast.AST) -> str:
    """Dotted name of a def node within its module
    (``Class.method`` / ``outer.inner``); lambdas render as
    ``<lambda>``."""
    parts: List[str] = []
    for n in [node] + list(ancestors(node)):
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
            parts.append(n.name)
        elif isinstance(n, ast.Lambda):
            parts.append("<lambda>")
        elif isinstance(n, ast.ClassDef):
            parts.append(n.name)
    return ".".join(reversed(parts))


def attr_path(expr: ast.AST) -> Optional[str]:
    """Dotted path of a Name/Attribute chain ("self._lock",
    "R.with_retry"); None for anything more dynamic."""
    parts: List[str] = []
    cur = expr
    while isinstance(cur, ast.Attribute):
        parts.append(cur.attr)
        cur = cur.value
    if not isinstance(cur, ast.Name):
        return None
    parts.append(cur.id)
    return ".".join(reversed(parts))


def call_tail(call: ast.Call) -> Optional[str]:
    """Final name of the called expression (`R.with_retry(...)` ->
    "with_retry", `foo(...)` -> "foo")."""
    f = call.func
    if isinstance(f, ast.Attribute):
        return f.attr
    if isinstance(f, ast.Name):
        return f.id
    return None


def resolve_path(fctx: FileCtx, expr: ast.AST) -> Optional[str]:
    """attr_path with the leading alias resolved through the file's
    imports: ``jnp.stack`` -> ``jax.numpy.stack``."""
    p = attr_path(expr)
    if p is None:
        return None
    head, _, rest = p.partition(".")
    base = fctx.imports.get(head, head)
    return f"{base}.{rest}" if rest else base


def call_args(call: ast.Call) -> List[ast.AST]:
    return list(call.args) + [kw.value for kw in call.keywords]


def walk_calls(node: ast.AST) -> Iterator[ast.Call]:
    for n in ast.walk(node):
        if isinstance(n, ast.Call):
            yield n


def file_calls(fctx: "FileCtx") -> List[ast.Call]:
    """Every Call node in the file, in ``ast.walk`` order, computed
    once per file: ~a dozen rules iterate the whole tree's calls, and
    re-walking 100+ trees per rule dominated the engine wall."""
    cached = getattr(fctx, "_file_calls", None)
    if cached is None:
        cached = fctx._file_calls = list(walk_calls(fctx.tree))
    return cached


def defs_by_name(tree: ast.AST) -> Dict[str, List[ast.AST]]:
    out: Dict[str, List[ast.AST]] = {}
    for n in ast.walk(tree):
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
            out.setdefault(n.name, []).append(n)
    return out


def module_rel(dotted: str) -> str:
    """Dotted module name -> repo-relative path candidate
    (``spark_rapids_tpu.ops.exprs`` -> ``spark_rapids_tpu/ops/exprs.py``)."""
    return dotted.replace(".", "/") + ".py"
