"""Rule family 2 — compile discipline (docs/fusion.md, PR 2/7).

``jit-direct``: every ``jax.jit(...)`` outside ``jit_cache.py`` must be
routed through a bounded single-flight ``JitCache`` — either lexically
inside the value argument of ``<cache>.put(key, ...)``, or inside a
builder reachable from a ``get_or_build`` / ``.put`` call (closed
transitively over the package call graph, across modules via imports:
``_STAGE_CACHE.put(key, X.build_stage_fn(...))`` makes
``ops/exprs.py::build_stage_fn`` a builder).

``pl.pallas_call`` is treated exactly like ``jax.jit`` (a Pallas
kernel pins a compiled program the same way): it must be built inside
the kernels/ registry package (``kernels_home``) — whose builders are
only ever invoked from JitCache-routed programs — or inside a
``JitCache`` builder closure, with reasoned suppressions for anything
else (the capability probes).

``jit-module-cache``: a module-level dict used as a compile cache
(``_FOO_CACHE = {}``) bypasses the LRU bound and the single-flight
build path — compiled programs pin XLA executables, so unbounded dicts
are a leak. Use ``JitCache`` instead.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Set, Tuple

from spark_rapids_tpu.lint import astutil as A
from spark_rapids_tpu.lint.engine import Finding, rule


def _is_jax_jit(fctx: A.FileCtx, call: ast.Call) -> bool:
    return A.resolve_path(fctx, call.func) == "jax.jit"


def _is_pallas_call(fctx: A.FileCtx, call: ast.Call) -> bool:
    p = A.resolve_path(fctx, call.func)
    return p is not None and (p == "pallas_call"
                              or p.endswith(".pallas_call"))


def _jitcache_names(fctx: A.FileCtx) -> Set[str]:
    """Names in this module bound to a JitCache(...) instance."""
    out: Set[str] = set()
    for node in ast.walk(fctx.tree):
        if isinstance(node, ast.Assign) and isinstance(node.value,
                                                       ast.Call):
            if A.call_tail(node.value) == "JitCache":
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        out.add(t.id)
    return out


def _resolve_callable(fctx: A.FileCtx, func: ast.AST
                      ) -> Tuple[str, str]:
    """(rel_path, func_name) a call target resolves to, best effort.
    Local names resolve to this file; ``X.fn`` resolves through the
    import alias map to the target module's path."""
    if isinstance(func, ast.Name):
        return fctx.rel, func.id
    if isinstance(func, ast.Attribute):
        if isinstance(func.value, ast.Name) \
                and func.value.id in fctx.imports:
            return A.module_rel(fctx.imports[func.value.id]), func.attr
        # self.method / other receivers: match by name in this file
        return fctx.rel, func.attr
    return "", ""


def _builder_closure(pctx) -> Dict[str, Set[int]]:
    """Per-file set of function/lambda node ids whose bodies are
    builder code for some JitCache (get_or_build builders, .put value
    expressions, and everything they call, package-wide)."""
    builder_nodes: Dict[str, Set[int]] = {f.rel: set()
                                          for f in pctx.files}
    # (rel, name) pairs still to mark
    work: List[Tuple[str, str]] = []
    seen: Set[Tuple[str, str]] = set()

    def seed_calls_in(fctx: A.FileCtx, node: ast.AST) -> None:
        for c in A.walk_calls(node):
            rel, name = _resolve_callable(fctx, c.func)
            if not name:
                continue
            key = (rel or fctx.rel, name)
            if key not in seen:
                seen.add(key)
                work.append(key)

    for fctx in pctx.files:
        caches = _jitcache_names(fctx)
        for call in A.file_calls(fctx):
            tail = A.call_tail(call)
            if tail == "put" and isinstance(call.func, ast.Attribute) \
                    and isinstance(call.func.value, ast.Name) \
                    and call.func.value.id in caches \
                    and len(call.args) >= 2:
                val = call.args[1]
                for sub in ast.walk(val):
                    if isinstance(sub, (ast.Lambda,)):
                        builder_nodes[fctx.rel].add(id(sub))
                # jits + builder calls inside the put value expression
                builder_nodes[fctx.rel].add(id(val))
                seed_calls_in(fctx, val)
            elif tail == "get_or_build" and len(call.args) >= 2:
                arg = call.args[1]
                if isinstance(arg, ast.Lambda):
                    builder_nodes[fctx.rel].add(id(arg))
                    seed_calls_in(fctx, arg)
                elif isinstance(arg, ast.Name):
                    key = (fctx.rel, arg.id)
                    if key not in seen:
                        seen.add(key)
                        work.append(key)

    defs_cache: Dict[str, Dict[str, List[ast.AST]]] = {
        f.rel: A.defs_by_name(f.tree) for f in pctx.files}
    while work:
        rel, name = work.pop()
        fctx = pctx.by_rel.get(rel)
        if fctx is None:
            continue
        for node in defs_cache[rel].get(name, ()):
            if id(node) in builder_nodes[rel]:
                continue
            builder_nodes[rel].add(id(node))
            seed_calls_in(fctx, node)
    return builder_nodes


@rule("jit-direct",
      "jax.jit / pl.pallas_call must be routed through the bounded "
      "single-flight JitCache (jit_cache.py) or, for pallas, built "
      "inside the kernels/ registry package")
def check_jit_direct(pctx):
    cfg = pctx.config
    kernels_home = getattr(cfg, "kernels_home",
                           "spark_rapids_tpu/kernels")
    builders = _builder_closure(pctx)
    for fctx in pctx.files:
        if fctx.rel == cfg.jit_home:
            continue
        in_kernels = fctx.rel.startswith(kernels_home.rstrip("/") + "/")
        file_builders = builders.get(fctx.rel, set())
        for call in A.file_calls(fctx):
            is_jit = _is_jax_jit(fctx, call)
            is_pallas = not is_jit and _is_pallas_call(fctx, call)
            if not (is_jit or is_pallas):
                continue
            if is_pallas and in_kernels:
                # the kernels/ registry IS the sanctioned home: its
                # builders only run inside JitCache-routed programs
                continue
            # inside a builder function/lambda or a .put value expr?
            ok = any(id(a) in file_builders
                     for a in [call] + list(A.ancestors(call)))
            if ok:
                continue
            what = "pl.pallas_call" if is_pallas else "jax.jit"
            yield Finding(
                "jit-direct", fctx.rel, call.lineno,
                call.col_offset + 1,
                f"direct {what} outside the JitCache path — compile "
                "via a bounded JitCache (get_or_build or "
                "cache.put(key, jax.jit(fn)))"
                + (", or move the kernel into the kernels/ registry "
                   "package" if is_pallas else "")
                + ", or suppress with a reason if the program is "
                "fixed and bounded by construction")


_DICTISH = ("dict", "OrderedDict", "defaultdict")


@rule("jit-module-cache",
      "module-level dict caches of compiled programs bypass the "
      "JitCache LRU bound")
def check_module_cache(pctx):
    cfg = pctx.config
    for fctx in pctx.files:
        if fctx.rel == cfg.jit_home:
            continue
        for stmt in fctx.tree.body:
            targets: List[ast.AST] = []
            value = None
            if isinstance(stmt, ast.Assign):
                targets, value = stmt.targets, stmt.value
            elif isinstance(stmt, ast.AnnAssign) and stmt.value \
                    is not None:
                targets, value = [stmt.target], stmt.value
            if value is None:
                continue
            is_dict = isinstance(value, ast.Dict) or (
                isinstance(value, ast.Call)
                and A.call_tail(value) in _DICTISH)
            if not is_dict:
                continue
            for t in targets:
                if isinstance(t, ast.Name) and "cache" in t.id.lower():
                    yield Finding(
                        "jit-module-cache", fctx.rel, stmt.lineno, 1,
                        f"module-level dict cache `{t.id}` — compiled "
                        f"programs must live in a bounded JitCache "
                        f"(LRU + single-flight + stats); suppress "
                        f"with a reason if it does not hold compiled "
                        f"functions")
