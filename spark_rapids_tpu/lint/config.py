"""tpu-lint configuration.

Defaults below describe the real repo (scopes, allowlisted donating
sites, critical locks). A ``tpu-lint.json`` at the repo root can merge
overrides for the file-based knobs (no runtime conf keys — lint config
is deliberately outside the spark.rapids.* registry)::

    {
      "check_docs": false,
      "retry_allowlist": {"pkg/mod.py::fn": "why this site is exempt"},
      "baseline": "tpu-lint-baseline.json"
    }

Every allowlist entry maps ``<repo-relative-path>::<qualname>`` to a
written reason, mirroring the suppression grammar's
reason-is-mandatory rule.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Dict, Tuple

CONFIG_FILENAME = "tpu-lint.json"


@dataclasses.dataclass
class LintConfig:
    # directories (relative to the lint root) scanned for *.py
    scan_roots: Tuple[str, ...] = ("spark_rapids_tpu",)

    # -- retry-coverage ----------------------------------------------------
    # files whose allocation/dispatch sites must sit inside the PR-4
    # retry protocol (docs/robustness.md wrapped-site table)
    retry_scope: Tuple[str, ...] = (
        "spark_rapids_tpu/exec/",
        "spark_rapids_tpu/parallel/",
        "spark_rapids_tpu/columnar/transfer.py",
        "spark_rapids_tpu/columnar/device.py",
    )
    retry_wrappers: Tuple[str, ...] = (
        "with_retry", "with_split_retry", "io_with_retry")
    # device allocation / dispatch entry points the rule tracks
    alloc_entrypoints: Tuple[str, ...] = (
        "device_put", "finish_upload", "start_upload", "finish_started",
        "upload_batch", "stack_batches")
    # "<rel>::<qualname>" -> reason. These are the protocol's own
    # implementation layer: the wrapped-site table wraps their CALLERS,
    # so the raw calls inside them are the single sanctioned copies.
    retry_allowlist: Dict[str, str] = dataclasses.field(
        default_factory=lambda: {
            "spark_rapids_tpu/columnar/transfer.py::finish_upload":
                "upload protocol implementation — every invoking site "
                "wraps it in with_retry (docs/robustness.md "
                "wrapped-site table)",
            "spark_rapids_tpu/columnar/transfer.py::start_upload":
                "async upload-ahead half: the ring owner handles OOM by "
                "shrinking the ring, then retries via _finish "
                "(docs/scan.md)",
            "spark_rapids_tpu/columnar/transfer.py::upload_batch":
                "composition of the wrapped halves; call sites run it "
                "under with_retry/with_split_retry",
            "spark_rapids_tpu/parallel/ici.py::mesh_exchange":
                "runs under the exchange materializer's with_retry "
                "(exec/exchange.py mesh path, docs/robustness.md)",
        })

    # -- jit discipline ----------------------------------------------------
    jit_home: str = "spark_rapids_tpu/jit_cache.py"
    # the Pallas kernel registry package: pallas_call is sanctioned
    # here (its builders only run inside JitCache-routed programs)
    kernels_home: str = "spark_rapids_tpu/kernels"

    # -- concurrency -------------------------------------------------------
    concurrency_scope: Tuple[str, ...] = (
        "spark_rapids_tpu/memory.py",
        "spark_rapids_tpu/resource.py",
        "spark_rapids_tpu/jit_cache.py",
        "spark_rapids_tpu/serve/",
    )
    # holding one of these, a blocking call is a stall for every task /
    # query in the process (DeviceStore + scheduler/semaphore locks)
    critical_locks: Tuple[str, ...] = (
        "DeviceStore._lock", "TpuSemaphore._cv",
        "AdmissionController._cv", "JitCache._lock")

    # -- cancellation discipline -------------------------------------------
    # files whose blocking waits must be cancellable: bounded timeout
    # (re-checked in a loop) or a lifecycle-aware helper — a new wait
    # site in the serving tier must not silently become uncancellable
    # (docs/serving.md "Query lifecycle")
    cancel_scope: Tuple[str, ...] = (
        "spark_rapids_tpu/serve/",
        "spark_rapids_tpu/retry.py",
        "spark_rapids_tpu/jit_cache.py",
    )

    # -- data-flow tier (tpu-lint v2, docs/linting.md family 6) -----------
    # hot-path scopes where a hidden device->host sync stalls the
    # async dispatch pipeline (the prefetched-device-scalar discipline)
    hot_scope: Tuple[str, ...] = (
        "spark_rapids_tpu/exec/",
        "spark_rapids_tpu/ops/",
        "spark_rapids_tpu/kernels/",
        "spark_rapids_tpu/parallel/",
        "spark_rapids_tpu/columnar/",
    )
    # "<rel>::<qualname>" -> reason: the SANCTIONED drain points —
    # every one is a deliberate, documented sync the pipeline is built
    # around (prefetched scalars resolve here, sizing handshakes, the
    # host half of serde), not an accidental stall
    sync_allowlist: Dict[str, str] = dataclasses.field(
        default_factory=lambda: {
            "spark_rapids_tpu/exec/exchange.py::split_by_pid":
                "the ONE documented counts sync per input batch "
                "(contiguousSplit): partition row counts are attached "
                "so downstream consumers never re-sync",
            "spark_rapids_tpu/ops/join.py::build_key_max_multiplicity":
                "prefetched multiplicity scalar resolved lazily at the "
                "probe's sizing decision — _prefetch_host overlaps the "
                "copy with the stream-side scan (docs/kernels.md)",
            "spark_rapids_tpu/ops/join.py::device_join":
                "the ONE sizing sync per probe: all three scalars ride "
                "one stacked fetch, and the FK fast path skips it "
                "entirely",
            "spark_rapids_tpu/parallel/ici.py::mesh_exchange":
                "the size-exchange handshake: a tiny [n_dev, n_dev] "
                "counts fetch sizes occupancy-proportional send blocks "
                "before the collective (VERDICT r3 weak #6)",
            "spark_rapids_tpu/kernels/autotune.py::_probe_decode_fused":
                "autotune oracle validation, not a query path: runs "
                "once per (kernel, bucket, device) sweep and must "
                "resolve the bit-equality verdict before timing",
            "spark_rapids_tpu/kernels/groupby_hash.py::autotune_probe":
                "autotune oracle validation, not a query path: the "
                "candidate's full output is compared host-side against "
                "a numpy group-by once per sweep",
        })
    # registration entry points whose returned handle/token must reach
    # a close/release_*/finish_* call or escape to a tracked container
    # (plus `<store>.register`, matched by receiver)
    handle_sources: Tuple[str, ...] = (
        "register_spillable", "start_upload")
    # "<rel>::<qualname>" -> reason for trace-purity exemptions
    purity_allowlist: Dict[str, str] = dataclasses.field(
        default_factory=lambda: {})

    # -- drift -------------------------------------------------------------
    metrics_rel: str = "spark_rapids_tpu/metrics.py"
    trace_rel: str = "spark_rapids_tpu/trace.py"
    # the telemetry endpoint module whose SERVER_FAMILY_HELP table the
    # prom-family rule checks emissions against
    prometheus_rel: str = "spark_rapids_tpu/telemetry/prometheus.py"
    # the query-history module whose HISTORY_FIELD_CATALOG the
    # history-field rule checks record construction against
    history_rel: str = "spark_rapids_tpu/telemetry/history.py"
    # the feedback-control module whose ACTION_CATALOG the
    # tuning-action rule checks action construction against
    tuning_rel: str = "spark_rapids_tpu/telemetry/tuning.py"
    # generated docs compared against `tools docs` regeneration
    check_docs: bool = True

    # -- engine ------------------------------------------------------------
    baseline: str = "tpu-lint-baseline.json"
    # total lint wall budget in seconds: `tools lint` exits 2 when a
    # run exceeds it, so the data-flow tier can never quietly make the
    # tier-1 gate unaffordable (per-rule timings ride --json)
    time_budget_s: float = 60.0


def load_config(root: str) -> LintConfig:
    """Defaults, merged with an optional ``tpu-lint.json`` at root."""
    cfg = LintConfig()
    path = os.path.join(root, CONFIG_FILENAME)
    if not os.path.exists(path):
        return cfg
    with open(path, "r", encoding="utf-8") as f:
        data = json.load(f)
    for key in ("check_docs", "baseline", "jit_home", "kernels_home",
                "metrics_rel", "trace_rel", "prometheus_rel",
                "history_rel", "tuning_rel", "time_budget_s"):
        if key in data:
            setattr(cfg, key, data[key])
    for key in ("scan_roots", "retry_scope", "retry_wrappers",
                "alloc_entrypoints", "concurrency_scope",
                "critical_locks", "cancel_scope", "hot_scope",
                "handle_sources"):
        if key in data:
            setattr(cfg, key, tuple(data[key]))
    for key in ("retry_allowlist", "sync_allowlist",
                "purity_allowlist"):
        if key in data:
            merged = dict(getattr(cfg, key))
            merged.update(data[key])
            setattr(cfg, key, merged)
    return cfg
