"""Rule family 6 — interprocedural data-flow invariants
(docs/linting.md "Family 6"; the tpu-lint v2 tier).

Four rules over ``lint/dataflow.py``'s call graph + reaching-defs
substrate, each machine-checking an invariant a previous PR audited by
hand:

``donation-safety`` — a name handed to a donating compiled program
(``jax.jit(..., donate_argnums=...)``, a ``pallas_call`` with
``input_output_aliases``, or anything resolving to one through a
JitCache route or a local helper one call deep) must not be READ on any
forward path after the donating call: the dispatch reuses the buffer's
HBM storage for the outputs, so a later read sees freed/aliased memory
(the PR 11 "kernel path never donates / stage everything before the
donating dispatch" invariant).

``hidden-sync`` — inside the hot-path scopes (``exec/``, ``ops/``,
``kernels/``, ``parallel/``, ``columnar/``), a device->host forcing
operation (``np.asarray``/``np.array``, ``float``/``int``/``bool``,
``.item()``, ``jax.device_get``, ``.block_until_ready()``) applied to
a value that reaches from a device-producing call stalls the async
dispatch pipeline for a flat D2H roundtrip. Sanctioned drain points
(the prefetched-scalar reads q1's pipeline is built around) live in
``sync_allowlist`` with a written reason, same grammar as the retry
allowlist.

``handle-leak`` — the value returned by a spillable registration
(``register_spillable``, ``start_upload``, ``<store>.register``) must
reach a ``close``/``release_*``/``finish_*`` call, a context-manager
scope, or escape into a tracked container/return on SOME path — and
not only on the exception path. A handle whose only release is GC's
weakref finalizer holds HBM until the collector happens to run (the
PR 13 ``release_plan_handles`` class).

``trace-purity`` — function bodies reachable from a ``jax.jit``/
``pl.pallas_call`` builder execute at TRACE time: a ``time.*`` or
``random.*``/``np.random.*`` call, a dynamic ``conf.get`` read, or a
mutation of nonlocal state inside them is baked into the compiled
program once and replayed never — a silent bit-identity break the
moment the impure value would have changed.

Every-path checking is approximated on the syntactic CFG: source order
plus loop back edges for donation reads, exception-path-only release
detection for handles. Dynamic dispatch is invisible, so these rules
under-approximate; anything they DO flag is real enough to need a fix,
an allowlist entry, or a reasoned suppression.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from spark_rapids_tpu.lint import astutil as A
from spark_rapids_tpu.lint import dataflow as DF
from spark_rapids_tpu.lint.engine import Finding, rule


def _callgraph(pctx) -> DF.CallGraph:
    cg = getattr(pctx, "_df_callgraph", None)
    if cg is None:
        cg = DF.CallGraph(pctx)
        pctx._df_callgraph = cg
    return cg


def _allowlisted(fctx: A.FileCtx, node: ast.AST,
                 allowlist: Dict[str, str]) -> bool:
    """True when any enclosing function of ``node`` is an allowlist
    entry (``<rel>::<qualname>`` -> reason)."""
    if not allowlist:
        return False
    for fn in A.enclosing_functions(node):
        if isinstance(fn, ast.Lambda):
            continue
        if f"{fctx.rel}::{A.qualname(fn)}" in allowlist:
            return True
    return False


# ---------------------------------------------------------------------------
# donation-safety
# ---------------------------------------------------------------------------

@rule("donation-safety",
      "a buffer handed to a donating jax.jit / pallas_call program "
      "must not be read on any forward path after the donating call")
def check_donation_safety(pctx):
    cg = _callgraph(pctx)
    seen: Set[Tuple[str, int, int, str]] = set()
    for site in DF.donation_sites(pctx, cg):
        fctx = site.fctx
        scope = DF.enclosing_function(site.call) or fctx.tree
        for _pos, root in site.donated_roots():
            if root is None or root == "self":
                continue
            for read in DF.reads_after_call(scope, site.call, root):
                key = (fctx.rel, read.lineno, read.col_offset, root)
                if key in seen:
                    continue
                seen.add(key)
                yield Finding(
                    "donation-safety", fctx.rel, read.lineno,
                    read.col_offset + 1,
                    f"`{root}` is read after being donated at line "
                    f"{site.call.lineno} via {site.via} — the dispatch "
                    f"reuses donated HBM storage for its outputs, so "
                    f"this read sees freed/aliased memory; stage every "
                    f"post-call use (row counts, placement, tracing) "
                    f"BEFORE the donating dispatch, or drop the "
                    f"donation")


# ---------------------------------------------------------------------------
# hidden-sync
# ---------------------------------------------------------------------------

_FORCING_BUILTINS = ("float", "int", "bool")


def _owning_def(node: ast.AST):
    """Innermost enclosing FunctionDef/AsyncFunctionDef, looking
    through lambdas (a lambda belongs to the def that wrote it)."""
    for a in A.enclosing_functions(node):
        if not isinstance(a, ast.Lambda):
            return a
    return None


def _forcing_kind(fctx: A.FileCtx, call: ast.Call) -> Optional[str]:
    """The device->host forcing shape of a call, if any: 'asarray',
    'builtin', 'item', 'device_get', 'block'."""
    p = A.resolve_path(fctx, call.func)
    if p in ("numpy.asarray", "numpy.array") and call.args:
        return "asarray"
    if p == "jax.device_get":
        return "device_get"
    tail = A.call_tail(call)
    if tail == "block_until_ready" and isinstance(call.func,
                                                 ast.Attribute):
        return "block"
    if tail == "item" and isinstance(call.func, ast.Attribute) \
            and not call.args:
        return "item"
    if isinstance(call.func, ast.Name) \
            and call.func.id in _FORCING_BUILTINS \
            and len(call.args) == 1 and not call.keywords:
        return "builtin"
    return None


@rule("hidden-sync",
      "device->host forcing ops on values reaching from a "
      "device-producing call are findings in the hot-path scopes "
      "unless allowlisted with a reason")
def check_hidden_sync(pctx):
    cfg = pctx.config
    hot = getattr(cfg, "hot_scope", ())
    allow = getattr(cfg, "sync_allowlist", {})
    for fctx in pctx.files:
        if not pctx.in_scope(fctx.rel, hot):
            continue
        flagged: Set[int] = set()
        for fn in ast.walk(fctx.tree):
            if not isinstance(fn, (ast.FunctionDef,
                                   ast.AsyncFunctionDef)):
                continue
            tainted, programs = DF.device_taint(fctx, fn)

            def expr_is_device(e: ast.AST) -> bool:
                for n in ast.walk(e):
                    if isinstance(n, ast.Call) \
                            and DF._is_device_producing_call(
                                fctx, n, programs):
                        return True
                    if isinstance(n, ast.Name) \
                            and isinstance(n.ctx, ast.Load) \
                            and n.id in tainted:
                        return True
                return False

            for call in A.walk_calls(fn):
                if id(call) in flagged:
                    continue
                # a nested def is analyzed as its own unit with its own
                # taint (its parameters are NOT tainted): checking its
                # calls against the OUTER scope's taint would flag a
                # callback whose parameter shadows an outer device
                # name. Lambdas stay with the def that owns them.
                if _owning_def(call) is not fn:
                    continue
                kind = _forcing_kind(fctx, call)
                if kind is None:
                    continue
                if kind in ("asarray", "builtin"):
                    arg = call.args[0]
                    # int(np.asarray(c)): the inner asarray IS the
                    # sync; report once at the inner site
                    if isinstance(arg, ast.Call) \
                            and _forcing_kind(fctx, arg) is not None:
                        continue
                    if not expr_is_device(arg):
                        continue
                    what = ("np.asarray" if kind == "asarray"
                            else f"{call.func.id}()")
                elif kind == "item":
                    if not expr_is_device(call.func.value):
                        continue
                    what = ".item()"
                elif kind == "device_get":
                    what = "jax.device_get"
                else:
                    what = ".block_until_ready()"
                if _allowlisted(fctx, call, allow):
                    continue
                flagged.add(id(call))
                yield Finding(
                    "hidden-sync", fctx.rel, call.lineno,
                    call.col_offset + 1,
                    f"{what} forces a device->host sync on a hot-path "
                    f"value — the async dispatch pipeline stalls for a "
                    f"flat D2H roundtrip here; prefetch the scalar "
                    f"(_prefetch_host) and drain it at a sanctioned "
                    f"point, or add this function to sync_allowlist "
                    f"with a reason (docs/linting.md)")


# ---------------------------------------------------------------------------
# handle-leak
# ---------------------------------------------------------------------------

_RELEASE_TAILS = ("close",)
_RELEASE_PREFIXES = ("release", "finish")
_CONTAINERS = (ast.Tuple, ast.List, ast.Set, ast.Dict, ast.Starred,
               ast.IfExp)


def _is_release_name(tail: Optional[str]) -> bool:
    return tail is not None and (
        tail in _RELEASE_TAILS
        or any(tail.startswith(p + "_") or tail == p
               for p in _RELEASE_PREFIXES))


def _is_handle_source(fctx: A.FileCtx, call: ast.Call,
                      sources: Tuple[str, ...]) -> bool:
    tail = A.call_tail(call)
    if tail in sources:
        return True
    if tail == "register" and isinstance(call.func, ast.Attribute):
        recv = A.attr_path(call.func.value)
        return recv is not None and "store" in recv.lower()
    return False


def _source_binding(call: ast.Call) -> Tuple[str, Optional[str]]:
    """Classify where a registration call's value goes: ('name', n) to
    track, ('ok', None) when it escapes/releases at the source
    (returned, passed on, context-managed, stored), ('dropped', None)
    for a bare expression statement."""
    node: ast.AST = call
    par = A.parent(node)
    while isinstance(par, _CONTAINERS):
        node, par = par, A.parent(par)
    if isinstance(par, ast.Assign):
        # h = src(...)  (also `h = src(...) if c else None`); any
        # tuple/attr/subscript target or wrapped container escapes
        if node is par.value and len(par.targets) == 1 \
                and isinstance(par.targets[0], ast.Name):
            return "name", par.targets[0].id
        return "ok", None
    if isinstance(par, (ast.Return, ast.Yield, ast.Call, ast.withitem)):
        return "ok", None
    if isinstance(par, ast.Expr):
        return "dropped", None
    return "ok", None


def _handle_uses(fn: ast.AST, name: str, source: ast.Call
                 ) -> Tuple[List[ast.AST], List[ast.AST]]:
    """(releases, escapes) — Load uses of ``name`` that release the
    handle (`.close()`, `release_*`/`finish_*` calls, `with h`) or
    move its ownership (returned/yielded, passed to a call, stored
    into an attribute/subscript/alias, put in a container that is
    itself consumed). Plain reads (`h.get()`, `h.rows`, `h is None`)
    are neither."""
    releases: List[ast.AST] = []
    escapes: List[ast.AST] = []
    in_source = {id(n) for n in ast.walk(source)}
    for node in ast.walk(fn):
        if not (isinstance(node, ast.Name) and node.id == name
                and isinstance(node.ctx, ast.Load)):
            continue
        if id(node) in in_source:
            continue
        cur: ast.AST = node
        par = A.parent(cur)
        while isinstance(par, _CONTAINERS):
            cur, par = par, A.parent(par)
        if isinstance(par, ast.Attribute) and par.value is cur:
            gp = A.parent(par)
            if isinstance(gp, ast.Call) and gp.func is par:
                if _is_release_name(par.attr):
                    releases.append(node)
            continue  # attribute read: not a sink
        if isinstance(par, ast.Call):
            if _is_release_name(A.call_tail(par)):
                releases.append(node)
            else:
                escapes.append(node)
        elif isinstance(par, (ast.Return, ast.Yield)):
            escapes.append(node)
        elif isinstance(par, ast.Assign) and par.value is cur:
            escapes.append(node)  # alias / stored: ownership moved
        elif isinstance(par, ast.withitem) and par.context_expr is cur:
            releases.append(node)  # context manager closes it
    return releases, escapes


def _under_except(node: ast.AST) -> bool:
    return any(isinstance(a, ast.ExceptHandler)
               for a in A.ancestors(node))


@rule("handle-leak",
      "a spillable registration's handle must reach a close/release/"
      "finish call or escape to a tracked container — not be freed "
      "only by GC, and not only on the exception path")
def check_handle_leak(pctx):
    cfg = pctx.config
    sources = getattr(cfg, "handle_sources",
                      ("register_spillable", "start_upload"))
    for fctx in pctx.files:
        seen: Set[int] = set()
        for fn in ast.walk(fctx.tree):
            if not isinstance(fn, (ast.FunctionDef,
                                   ast.AsyncFunctionDef)):
                continue
            for call in A.walk_calls(fn):
                if id(call) in seen:
                    continue
                if not _is_handle_source(fctx, call, sources):
                    continue
                if DF.enclosing_function(call) is not fn:
                    continue  # analyzed with its own def
                seen.add(id(call))
                tail = A.call_tail(call)
                role, name = _source_binding(call)
                if role == "ok":
                    continue
                if role == "dropped":
                    yield Finding(
                        "handle-leak", fctx.rel, call.lineno,
                        call.col_offset + 1,
                        f"`{tail}(...)` result dropped — the spillable "
                        f"handle/token it returns can only be freed by "
                        f"GC's weakref finalizer; bind it and close/"
                        f"finish it deterministically "
                        f"(docs/robustness.md)")
                    continue
                releases, escapes = _handle_uses(fn, name, call)
                if not releases and not escapes:
                    yield Finding(
                        "handle-leak", fctx.rel, call.lineno,
                        call.col_offset + 1,
                        f"`{name}` (from `{tail}`) is never closed, "
                        f"finished, released, or handed off — the "
                        f"handle leaks until GC; close it in a "
                        f"finally, or let it escape to the tracked "
                        f"container that owns it")
                elif all(_under_except(s) for s in releases + escapes):
                    yield Finding(
                        "handle-leak", fctx.rel, call.lineno,
                        call.col_offset + 1,
                        f"`{name}` (from `{tail}`) is only released on "
                        f"the exception path — the success path leaks "
                        f"it to GC; close it in normal flow or a "
                        f"finally")


# ---------------------------------------------------------------------------
# trace-purity
# ---------------------------------------------------------------------------

_MUTATORS = frozenset({"append", "extend", "add", "update", "insert",
                       "remove", "discard", "clear", "pop", "popitem",
                       "setdefault", "appendleft", "extendleft"})
_IMPURE_HEADS = ("time.", "random.", "numpy.random.")


def _purity_violations(fctx: A.FileCtx, fn: ast.AST):
    """(node, what) impurities lexically inside ``fn``. Names bound in
    a lexically ENCLOSING function count as local: a closure
    accumulator created fresh per trace (the decode programs' lazy
    ``bytes_all`` memo, the kernel lane planners' ``lanes.append``) is
    deterministic per-trace bookkeeping, not cross-trace state — only
    module/global mutation survives between traces and breaks
    bit-identity."""
    locals_ = DF.local_names(fn)
    for enc in A.enclosing_functions(fn):
        locals_ |= DF.local_names(enc)
    for node in ast.walk(fn):
        if isinstance(node, ast.Global):
            yield node, (f"`global {', '.join(node.names)}` "
                         f"(module-state mutation)")
        elif isinstance(node, ast.Call):
            p = A.resolve_path(fctx, node.func)
            if p is not None and any(p.startswith(h) or p == h[:-1]
                                     for h in _IMPURE_HEADS):
                yield node, f"`{p}(...)` (host clock/RNG)"
                continue
            tail = A.call_tail(node)
            if tail == "get" and isinstance(node.func, ast.Attribute):
                recv = A.attr_path(node.func.value)
                if recv is not None \
                        and "conf" in recv.split(".")[-1].lower():
                    yield node, (f"`{recv}.get(...)` (dynamic conf "
                                 f"read)")
                    continue
            if tail in _MUTATORS and isinstance(node.func,
                                                ast.Attribute):
                root = DF.root_name(node.func.value)
                if root is not None and root not in locals_:
                    yield node, (f"`{root}.{tail}(...)` (mutates "
                                 f"free state)")
        elif isinstance(node, (ast.Assign, ast.AugAssign)):
            targets = (node.targets if isinstance(node, ast.Assign)
                       else [node.target])
            for t in targets:
                if isinstance(t, (ast.Attribute, ast.Subscript)):
                    root = DF.root_name(t)
                    if root is not None and root not in locals_ \
                            and root != "self":
                        yield t, (f"assignment into `{root}` (mutates "
                                  f"free state)")


@rule("trace-purity",
      "function bodies reachable from a jax.jit / pallas_call builder "
      "must not read clocks/RNG/conf or mutate nonlocal state — "
      "impurity is baked in at trace time")
def check_trace_purity(pctx):
    cfg = pctx.config
    allow = getattr(cfg, "purity_allowlist", {})
    cg = _callgraph(pctx)
    roots: List[Tuple[A.FileCtx, ast.AST]] = []
    lambda_roots: List[Tuple[A.FileCtx, ast.AST, str]] = []
    for fctx, node, what in DF.traced_roots(pctx, cg):
        if isinstance(node, ast.Lambda):
            lambda_roots.append((fctx, node, what))
        roots.append((fctx, node))
    reached = cg.reachable(roots)
    seen: Set[Tuple[str, int, int]] = set()

    def emit(fctx, fn_label, node, what):
        key = (fctx.rel, node.lineno, node.col_offset)
        if key in seen:
            return None
        seen.add(key)
        return Finding(
            "trace-purity", fctx.rel, node.lineno,
            node.col_offset + 1,
            f"{what} inside `{fn_label}`, which is traced into a "
            f"compiled program — the impure value is baked in at "
            f"trace time and silently breaks bit-identity; hoist it "
            f"out of the traced body (snapshot before the builder)")

    for info in reached.values():
        if f"{info.rel}::{info.qualname}" in allow:
            continue
        for node, what in _purity_violations(info.fctx, info.node):
            f = emit(info.fctx, info.qualname, node, what)
            if f is not None:
                yield f
    for fctx, lam, _src in lambda_roots:
        if _allowlisted(fctx, lam, allow):
            continue
        for node, what in _purity_violations(fctx, lam):
            f = emit(fctx, "<traced lambda>", node, what)
            if f is not None:
                yield f
