"""Columnar data layer: the cuDF-equivalent (SURVEY.md section 2.4 implication).

- host.py: CPU columns (numpy data + validity) used by the fallback engine,
  file readers, and the comparison baseline — the analogue of
  RapidsHostColumnVector (sql-plugin GpuColumnVector.java neighborhood).
- device.py: HBM-resident columns as JAX arrays with bucketed static
  capacities — the analogue of GpuColumnVector over cudf device memory.
- kernels/: XLA/Pallas programs for the cuDF Table operations the reference
  calls through JNI (Table.concatenate, groupBy, join gather maps, sort,
  filter, contiguousSplit...; SURVEY.md L1).
"""
