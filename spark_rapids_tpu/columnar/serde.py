"""Serialized columnar batch format (the GpuColumnarBatchSerializer /
MetaUtils TableMeta role, GpuColumnarBatchSerializer.scala:50,
MetaUtils.scala): a self-describing binary encoding of a HostBatch used
by the disk spill tier and any future host-staged shuffle leg — pickle
carries arbitrary code-execution risk and no cross-version contract, so
batches on disk use this format instead.

Layout (little-endian):
  magic 'SRTB' | u16 version | u8 codec | u32 n_rows | u32 n_cols
  u32 schema_len | schema bytes (recursive tag encoding, below)
  u64 payload_len | payload (concatenated column blocks, possibly
  compressed)

Each column block: u8 kind | validity bitmap (ceil(n/8) bytes) | data:
  kind 0 fixed-width: u8 dtype-code, raw array bytes
  kind 1 string/binary: u32 total_bytes, offsets (u32[n+1]), utf-8 bytes
  kind 2 decimal128 limbs: two raw int64 arrays (hi, lo)
  kind 3 array<T>: u32 pool_len, lengths u32[n], elem validity bitmap,
         recursively-encoded element pool column

Codec: 0 none, 1 zlib, 2 zstd (spark.rapids.shuffle.compression.codec;
TableCompressionCodec framework analogue).
"""

from __future__ import annotations

import struct
from typing import List, Tuple

import numpy as np

from spark_rapids_tpu.columnar.host import HostBatch, HostColumn
from spark_rapids_tpu.sql import types as T

MAGIC = b"SRTB"
VERSION = 1

_CODECS = {"none": 0, "zlib": 1, "zstd": 2}
_CODEC_NAMES = {v: k for k, v in _CODECS.items()}

_FIXED_DTYPES = [np.dtype(x) for x in
                 ("bool", "int8", "int16", "int32", "int64",
                  "float32", "float64", "uint8")]
_DTYPE_CODE = {dt: i for i, dt in enumerate(_FIXED_DTYPES)}


def _compress(data: bytes, codec: str) -> bytes:
    if codec == "zlib":
        import zlib
        return zlib.compress(data, 1)
    if codec == "zstd":
        try:
            import zstandard
        except ImportError:
            # gate the optional dep: pyarrow ships a zstd codec; its
            # frames don't embed the content size, so prefix it (both
            # ends of a shuffle/spill run the same build, so the
            # fallback is symmetric)
            import struct

            import pyarrow as pa
            comp = pa.Codec("zstd").compress(data, asbytes=True)
            return struct.pack("<Q", len(data)) + comp
        return zstandard.ZstdCompressor(level=1).compress(data)
    return data


def _decompress(data: bytes, codec_id: int) -> bytes:
    codec = _CODEC_NAMES[codec_id]
    if codec == "zlib":
        import zlib
        return zlib.decompress(data)
    if codec == "zstd":
        try:
            import zstandard
        except ImportError:
            import struct

            import pyarrow as pa
            (n,) = struct.unpack("<Q", data[:8])
            buf = pa.Codec("zstd").decompress(data[8:],
                                              decompressed_size=n)
            return buf.to_pybytes() if hasattr(buf, "to_pybytes") \
                else bytes(buf)
        return zstandard.ZstdDecompressor().decompress(data)
    return data


# -- recursive type encoding ------------------------------------------------

_ATOM_TAGS = [T.BooleanT, T.ByteT, T.ShortT, T.IntegerT, T.LongT,
              T.FloatT, T.DoubleT, T.StringT, T.BinaryT, T.DateT,
              T.TimestampT, T.NullT]


def _enc_type(dt: T.DataType, out: bytearray) -> None:
    if isinstance(dt, T.DecimalType):
        out.append(100)
        out.append(dt.precision)
        out.append(dt.scale)
        return
    if isinstance(dt, T.ArrayType):
        out.append(101)
        _enc_type(dt.element_type, out)
        return
    if isinstance(dt, T.StructType):
        out.append(102)
        out += struct.pack("<H", len(dt.fields))
        for f in dt.fields:
            nb = f.name.encode("utf-8")
            out += struct.pack("<H", len(nb))
            out += nb
            _enc_type(f.data_type, out)
        return
    for i, atom in enumerate(_ATOM_TAGS):
        if dt == atom:
            out.append(i)
            return
    raise TypeError(f"unserializable type {dt}")


def _dec_type(buf: bytes, i: int) -> Tuple[T.DataType, int]:
    tag = buf[i]
    if tag == 100:
        return T.DecimalType(buf[i + 1], buf[i + 2]), i + 3
    if tag == 101:
        et, j = _dec_type(buf, i + 1)
        return T.ArrayType(et), j
    if tag == 102:
        (nf,) = struct.unpack_from("<H", buf, i + 1)
        j = i + 3
        fields = []
        for _ in range(nf):
            (ln,) = struct.unpack_from("<H", buf, j)
            j += 2
            name = bytes(buf[j:j + ln]).decode("utf-8")
            j += ln
            ft, j = _dec_type(buf, j)
            fields.append(T.StructField(name, ft))
        return T.StructType(fields), j
    return _ATOM_TAGS[tag], i + 1


def _enc_schema(schema: T.StructType) -> bytes:
    out = bytearray()
    out += struct.pack("<H", len(schema.fields))
    for f in schema.fields:
        nb = f.name.encode("utf-8")
        out += struct.pack("<H", len(nb))
        out += nb
        _enc_type(f.data_type, out)
    return bytes(out)


def _dec_schema(buf: bytes) -> T.StructType:
    (n,) = struct.unpack_from("<H", buf, 0)
    i = 2
    fields = []
    for _ in range(n):
        (ln,) = struct.unpack_from("<H", buf, i)
        i += 2
        name = buf[i:i + ln].decode("utf-8")
        i += ln
        dt, i = _dec_type(buf, i)
        fields.append(T.StructField(name, dt))
    return T.StructType(fields)


# -- column blocks ----------------------------------------------------------

def _enc_column(c: HostColumn, dt: T.DataType, out: List[bytes]) -> None:
    n = len(c)
    vbits = np.packbits(np.asarray(c.validity, dtype=bool),
                        bitorder="little").tobytes()
    if isinstance(dt, T.ArrayType):
        lengths = np.fromiter((len(v) for v in c.data), dtype=np.uint32,
                              count=n)
        pool: List = []
        for v in c.data:
            pool.extend(v)
        elem_valid = [x is not None for x in pool]
        elem_vals = [0 if x is None else x for x in pool]
        child = HostColumn.from_pylist(
            [None if not ok else v
             for ok, v in zip(elem_valid, elem_vals)], dt.element_type) \
            if pool else HostColumn.nulls(0, dt.element_type)
        out.append(struct.pack("<BI", 3, len(pool)))
        out.append(vbits)
        out.append(lengths.tobytes())
        _enc_column(child, dt.element_type, out)
        return
    if isinstance(dt, T.StructType):
        from spark_rapids_tpu.columnar.host import struct_field_values
        from spark_rapids_tpu.columnar.transfer import \
            _col_from_storage_values
        out.append(struct.pack("<B", 4))
        out.append(vbits)
        for fi, f in enumerate(dt.fields):
            _enc_column(_col_from_storage_values(
                struct_field_values(c, fi), f.data_type),
                f.data_type, out)
        return
    if isinstance(dt, (T.StringType, T.BinaryType)):
        is_bin = isinstance(dt, T.BinaryType)
        encoded = [(v if is_bin else v.encode("utf-8")) if ok else b""
                   for v, ok in zip(c.data, np.asarray(c.validity))]
        offsets = np.zeros(n + 1, dtype=np.uint32)
        np.cumsum([len(e) for e in encoded], out=offsets[1:])
        blob = b"".join(encoded)
        out.append(struct.pack("<BI", 1, len(blob)))
        out.append(vbits)
        out.append(offsets.tobytes())
        out.append(blob)
        return
    if T.is_limb_decimal(dt):
        out.append(struct.pack("<B", 2))
        out.append(vbits)
        out.append(np.ascontiguousarray(c.data[:, 0]).tobytes())
        out.append(np.ascontiguousarray(c.data[:, 1]).tobytes())
        return
    data = np.ascontiguousarray(c.data)
    code = _DTYPE_CODE.get(data.dtype)
    if code is None:
        raise TypeError(f"unserializable column dtype {data.dtype}")
    out.append(struct.pack("<BB", 0, code))
    out.append(vbits)
    out.append(data.tobytes())


def _dec_column(buf: memoryview, i: int, n: int, dt: T.DataType
                ) -> Tuple[HostColumn, int]:
    kind = buf[i]
    nvb = (n + 7) // 8
    if kind == 3:
        (pool_len,) = struct.unpack_from("<I", buf, i + 1)
        i += 5
        validity = np.unpackbits(
            np.frombuffer(buf, np.uint8, nvb, i),
            bitorder="little")[:n].astype(bool)
        i += nvb
        lengths = np.frombuffer(buf, np.uint32, n, i)
        i += 4 * n
        child, i = _dec_column(buf, i, pool_len, dt.element_type)
        child_py = child.to_pylist()
        # to_pylist converts to LOGICAL values; re-store them
        from spark_rapids_tpu.columnar.host import _to_storage
        data = np.empty(n, dtype=object)
        off = 0
        for r in range(n):
            ln = int(lengths[r])
            data[r] = tuple(
                None if v is None else _to_storage(v, dt.element_type)
                for v in child_py[off:off + ln]) if validity[r] else ()
            off += ln
        return HostColumn(dt, data, validity), i
    if kind == 4:
        i += 1
        validity = np.unpackbits(
            np.frombuffer(buf, np.uint8, nvb, i),
            bitorder="little")[:n].astype(bool)
        i += nvb
        # decoded field columns are ALREADY storage-form: zip directly
        from spark_rapids_tpu.columnar.host import struct_storage_rows
        fcols = []
        for f in dt.fields:
            fc, i = _dec_column(buf, i, n, f.data_type)
            fcols.append(fc)
        return HostColumn(dt, struct_storage_rows(fcols, validity),
                          validity), i
    if kind == 1:
        (blob_len,) = struct.unpack_from("<I", buf, i + 1)
        i += 5
        validity = np.unpackbits(
            np.frombuffer(buf, np.uint8, nvb, i),
            bitorder="little")[:n].astype(bool)
        i += nvb
        offsets = np.frombuffer(buf, np.uint32, n + 1, i)
        i += 4 * (n + 1)
        blob = bytes(buf[i:i + blob_len])
        i += blob_len
        is_bin = isinstance(dt, T.BinaryType)
        data = np.empty(n, dtype=object)
        for r in range(n):
            raw = blob[offsets[r]:offsets[r + 1]]
            data[r] = (raw if is_bin else raw.decode("utf-8")) \
                if validity[r] else ("" if not is_bin else b"")
        return HostColumn(dt, data, validity), i
    if kind == 2:
        i += 1
        validity = np.unpackbits(
            np.frombuffer(buf, np.uint8, nvb, i),
            bitorder="little")[:n].astype(bool)
        i += nvb
        hi = np.frombuffer(buf, np.int64, n, i).copy()
        i += 8 * n
        lo = np.frombuffer(buf, np.int64, n, i).copy()
        i += 8 * n
        return HostColumn(dt, np.stack([hi, lo], axis=1), validity), i
    # fixed width
    code = buf[i + 1]
    i += 2
    validity = np.unpackbits(
        np.frombuffer(buf, np.uint8, nvb, i),
        bitorder="little")[:n].astype(bool)
    i += nvb
    np_dt = _FIXED_DTYPES[code]
    data = np.frombuffer(buf, np_dt, n, i).copy()
    i += np_dt.itemsize * n
    return HostColumn(dt, data, validity), i


def serialize_batch(b: HostBatch, codec: str = "none") -> bytes:
    assert codec in _CODECS, codec
    blocks: List[bytes] = []
    for f, c in zip(b.schema.fields, b.columns):
        _enc_column(c, f.data_type, blocks)
    payload = _compress(b"".join(blocks), codec)
    schema = _enc_schema(b.schema)
    head = MAGIC + struct.pack("<HBII", VERSION, _CODECS[codec],
                               b.num_rows, b.num_cols)
    return head + struct.pack("<I", len(schema)) + schema \
        + struct.pack("<Q", len(payload)) + payload


def deserialize_batch(data: bytes) -> HostBatch:
    assert data[:4] == MAGIC, "not a serialized batch"
    version, codec_id, n_rows, n_cols = struct.unpack_from("<HBII", data, 4)
    assert version == VERSION, version
    i = 4 + 11
    (slen,) = struct.unpack_from("<I", data, i)
    i += 4
    schema = _dec_schema(data[i:i + slen])
    i += slen
    (plen,) = struct.unpack_from("<Q", data, i)
    i += 8
    payload = memoryview(_decompress(data[i:i + plen], codec_id))
    cols = []
    j = 0
    for f in schema.fields:
        c, j = _dec_column(payload, j, n_rows, f.data_type)
        cols.append(c)
    return HostBatch(schema, cols, n_rows)
