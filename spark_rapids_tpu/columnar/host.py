"""Host-side columnar batches.

The CPU twin of the device format: each column is a numpy data array plus a
boolean validity array (True = valid), Arrow-style. Strings/binary use numpy
object arrays on the host (the device side uses padded byte matrices, see
device.py). This is what the CPU physical operators evaluate over, what file
readers produce, and what `collect()` materializes — playing the role of
Spark's UnsafeRow/ColumnarBatch world plus RapidsHostColumnVector
(GpuColumnVector.java) in the reference.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Iterator, List, Optional, Sequence, Tuple

import numpy as np

from spark_rapids_tpu.sql import types as T


@dataclass
class HostColumn:
    """One column: `data` (numpy array) + `validity` (bool array).

    Invalid slots hold an arbitrary-but-deterministic value (0 / "" / None)
    so vectorized ops never see garbage.
    """

    dtype: T.DataType
    data: np.ndarray
    validity: np.ndarray  # bool, True = valid
    # Optional compact representation for string/binary columns decoded
    # from Arrow: (utf8_bytes uint8[total], lengths int32[n]) where row
    # i's bytes are the next lengths[i] bytes after sum(lengths[:i]).
    # The upload codec ships these raw bytes and rebuilds the padded
    # char matrix ON DEVICE (the reference's copy-compact-bytes pattern,
    # GpuParquetScanBase.scala:82) instead of re-encoding the object
    # array; pure optimization — every consumer falls back to ``data``.
    varbytes: Optional[Tuple[np.ndarray, np.ndarray]] = None

    def __post_init__(self):
        assert len(self.data) == len(self.validity), (
            f"{len(self.data)} != {len(self.validity)}")

    def __len__(self) -> int:
        return len(self.data)

    @property
    def null_count(self) -> int:
        return int((~self.validity).sum())

    def to_pylist(self) -> List[Any]:
        import datetime
        import decimal
        out: List[Any] = []
        is_bool = isinstance(self.dtype, T.BooleanType)
        is_date = isinstance(self.dtype, T.DateType)
        is_ts = isinstance(self.dtype, T.TimestampType)
        dec_scale = (self.dtype.scale
                     if isinstance(self.dtype, T.DecimalType) else None)
        is_array = isinstance(self.dtype, T.ArrayType)
        is_struct = isinstance(self.dtype, T.StructType)
        epoch = datetime.date(1970, 1, 1)
        ts_epoch = datetime.datetime(1970, 1, 1)
        if T.is_limb_decimal(self.dtype):
            from spark_rapids_tpu.ops import int128 as I
            ints = I.to_pyints(self.data[:, 0], self.data[:, 1])
            return [decimal.Decimal(int(u)).scaleb(-dec_scale)
                    if ok else None
                    for u, ok in zip(ints, self.validity)]
        for i in range(len(self.data)):
            if not self.validity[i]:
                out.append(None)
            else:
                v = self.data[i]
                if isinstance(v, np.generic):
                    v = v.item()
                if is_array:
                    out.append([_from_storage(x, self.dtype.element_type)
                                for x in v])
                    continue
                if is_struct:
                    out.append(_from_storage(tuple(v), self.dtype))
                    continue
                if is_bool:
                    v = bool(v)
                elif is_date:
                    # pyspark returns datetime.date for DateType; days
                    # outside datetime's year range stay raw ints
                    try:
                        v = epoch + datetime.timedelta(days=v)
                    except OverflowError:
                        pass
                elif is_ts:
                    try:
                        v = ts_epoch + datetime.timedelta(microseconds=v)
                    except OverflowError:
                        pass
                elif dec_scale is not None:
                    v = decimal.Decimal(v).scaleb(-dec_scale)
                out.append(v)
        return out

    def copy(self) -> "HostColumn":
        return HostColumn(self.dtype, self.data.copy(), self.validity.copy())

    def take(self, indices: np.ndarray) -> "HostColumn":
        return HostColumn(self.dtype, self.data[indices],
                          self.validity[indices])

    def slice(self, start: int, end: int) -> "HostColumn":
        return HostColumn(self.dtype, self.data[start:end],
                          self.validity[start:end])

    @staticmethod
    def from_pylist(values: Sequence[Any], dtype: T.DataType) -> "HostColumn":
        n = len(values)
        validity = np.array([v is not None for v in values], dtype=bool)
        if T.is_limb_decimal(dtype):
            from spark_rapids_tpu.ops import int128 as I
            ints = [0 if v is None else _to_storage(v, dtype)
                    for v in values]
            hi, lo = I.from_pyints(ints)
            return HostColumn(dtype, np.stack([hi, lo], axis=1), validity)
        np_dt = T.numpy_dtype(dtype)
        if isinstance(dtype, T.StructType):
            data = np.empty(n, dtype=object)
            for i, v in enumerate(values):
                data[i] = () if v is None else _to_storage(v, dtype)
            return HostColumn(dtype, data, validity)
        if isinstance(dtype, T.ArrayType):
            # canonical element representation is STORAGE form (date ->
            # days, timestamp -> micros, decimal -> unscaled int), like
            # every other column; to_pylist converts back
            et = dtype.element_type
            data = np.empty(n, dtype=object)
            for i, v in enumerate(values):
                data[i] = () if v is None else tuple(
                    None if x is None else _to_storage(x, et) for x in v)
        elif np_dt == np.dtype(object):
            data = np.empty(n, dtype=object)
            for i, v in enumerate(values):
                data[i] = v if v is not None else ""
        else:
            fill = _zero_for(dtype)
            data = np.array(
                [fill if v is None else _to_storage(v, dtype)
                 for v in values], dtype=np_dt)
        return HostColumn(dtype, data, validity)

    @staticmethod
    def all_valid(data: np.ndarray, dtype: T.DataType) -> "HostColumn":
        return HostColumn(dtype, data, np.ones(len(data), dtype=bool))

    @staticmethod
    def nulls(n: int, dtype: T.DataType) -> "HostColumn":
        if T.is_limb_decimal(dtype):
            return HostColumn(dtype, np.zeros((n, 2), dtype=np.int64),
                              np.zeros(n, dtype=bool))
        np_dt = T.numpy_dtype(dtype)
        if np_dt == np.dtype(object):
            data = np.full(n, "", dtype=object)
        else:
            data = np.zeros(n, dtype=np_dt)
        return HostColumn(dtype, data, np.zeros(n, dtype=bool))

    def normalized(self) -> "HostColumn":
        """Zero out invalid slots for deterministic comparison/hashing."""
        out = self.copy()
        inv = ~out.validity
        if isinstance(self.dtype, (T.ArrayType, T.StructType)):
            for i in np.nonzero(inv)[0]:
                out.data[i] = ()
        elif T.is_limb_decimal(self.dtype):
            out.data[inv] = 0  # broadcasts over both limbs
        elif out.data.dtype == np.dtype(object):
            out.data[inv] = ""
        else:
            out.data[inv] = _zero_for(self.dtype)
        return out


def struct_field_values(c: "HostColumn", fi: int) -> List[Any]:
    """Field ``fi``'s storage values out of a struct HostColumn (None
    for null fields/structs/short tuples) — the single copy of the
    subtle guard shared by serde, transfer staging, and hashing."""
    return [c.data[r][fi]
            if c.validity[r] and len(c.data[r]) > fi else None
            for r in range(len(c.data))]


def struct_storage_rows(field_cols: List["HostColumn"],
                        validity: np.ndarray) -> np.ndarray:
    """Field HostColumns -> object array of struct STORAGE tuples
    (unscaled ints for limb decimals, None for null fields, () for null
    structs). The one implementation shared by the device download,
    CreateNamedStruct, and the arrow conversion."""
    n = len(validity)
    field_vals = []
    for fc in field_cols:
        if T.is_limb_decimal(fc.dtype):
            from spark_rapids_tpu.ops import int128 as I
            ints = I.to_pyints(fc.data[:, 0], fc.data[:, 1])
            field_vals.append([
                int(ints[i]) if fc.validity[i] else None
                for i in range(n)])
        else:
            field_vals.append([
                (fc.data[i].item() if isinstance(fc.data[i], np.generic)
                 else fc.data[i]) if fc.validity[i] else None
                for i in range(n)])
    out = np.empty(n, dtype=object)
    for i in range(n):
        out[i] = (tuple(fv[i] for fv in field_vals)
                  if validity[i] else ())
    return out


def _zero_for(dtype: T.DataType) -> Any:
    if isinstance(dtype, T.BooleanType):
        return False
    if isinstance(dtype, (T.FloatType, T.DoubleType)):
        return 0.0
    if isinstance(dtype, (T.ArrayType, T.StructType)):
        return ()
    return 0


def _to_storage(v: Any, dtype: T.DataType) -> Any:
    import datetime
    import decimal
    if isinstance(dtype, T.StructType):
        # storage form: tuple of field storage values (None = null field)
        if isinstance(v, dict):
            vals = [v.get(f.name) for f in dtype.fields]
        else:
            vals = list(v)
        return tuple(None if x is None else _to_storage(x, f.data_type)
                     for x, f in zip(vals, dtype.fields))
    if isinstance(dtype, T.DateType) and isinstance(v, datetime.date):
        return (v - datetime.date(1970, 1, 1)).days
    if isinstance(dtype, T.TimestampType) and isinstance(v, datetime.datetime):
        epoch = datetime.datetime(1970, 1, 1, tzinfo=datetime.timezone.utc)
        if v.tzinfo is None:
            v = v.replace(tzinfo=datetime.timezone.utc)
        return int((v - epoch).total_seconds() * 1_000_000)
    if isinstance(dtype, T.DecimalType):
        # unscaled int storage: value * 10^scale. A widened local
        # context: the default 28-digit precision rejects 38-digit
        # DECIMAL128 values (InvalidOperation on quantize).
        d = v if isinstance(v, decimal.Decimal) else decimal.Decimal(str(v))
        with decimal.localcontext() as ctx:
            ctx.prec = 80
            q = d.quantize(decimal.Decimal(1).scaleb(-dtype.scale),
                           rounding=decimal.ROUND_HALF_UP)
            return int(q.scaleb(dtype.scale))
    return v


def _from_storage(v: Any, dtype: T.DataType) -> Any:
    """Inverse of _to_storage for collect(): storage ints back to
    python date/datetime/Decimal/bool values (None passes through)."""
    import datetime
    import decimal
    if v is None:
        return None
    if isinstance(dtype, T.StructType):
        return tuple(_from_storage(x, f.data_type)
                     for x, f in zip(v, dtype.fields))
    if isinstance(dtype, T.ArrayType):
        return [_from_storage(x, dtype.element_type) for x in v]
    if isinstance(v, np.generic):
        v = v.item()
    if isinstance(dtype, T.BooleanType):
        return bool(v)
    if isinstance(dtype, T.DateType):
        try:
            return (datetime.date(1970, 1, 1)
                    + datetime.timedelta(days=v))
        except OverflowError:
            return v
    if isinstance(dtype, T.TimestampType):
        try:
            return (datetime.datetime(1970, 1, 1)
                    + datetime.timedelta(microseconds=v))
        except OverflowError:
            return v
    if isinstance(dtype, T.DecimalType):
        return decimal.Decimal(v).scaleb(-dtype.scale)
    return v


@dataclass
class HostBatch:
    """A batch of rows as host columns; the CPU ColumnarBatch."""

    schema: T.StructType
    columns: List[HostColumn]
    num_rows: int

    def __post_init__(self):
        for c in self.columns:
            assert len(c) == self.num_rows

    @property
    def num_cols(self) -> int:
        return len(self.columns)

    def column(self, i: int) -> HostColumn:
        return self.columns[i]

    def to_pydict(self) -> dict:
        return {f.name: c.to_pylist()
                for f, c in zip(self.schema.fields, self.columns)}

    def rows(self) -> Iterator[Tuple]:
        cols = [c.to_pylist() for c in self.columns]
        for i in range(self.num_rows):
            yield tuple(col[i] for col in cols)

    def take(self, indices: np.ndarray) -> "HostBatch":
        return HostBatch(self.schema, [c.take(indices) for c in self.columns],
                         len(indices))

    def slice(self, start: int, end: int) -> "HostBatch":
        end = min(end, self.num_rows)
        return HostBatch(self.schema,
                         [c.slice(start, end) for c in self.columns],
                         max(0, end - start))

    @staticmethod
    def empty(schema: T.StructType) -> "HostBatch":
        return HostBatch(schema,
                         [HostColumn.nulls(0, f.data_type) for f in schema],
                         0)

    @staticmethod
    def from_pydict(data: dict, schema: T.StructType) -> "HostBatch":
        cols = [HostColumn.from_pylist(data[f.name], f.data_type)
                for f in schema.fields]
        n = cols[0].__len__() if cols else 0
        return HostBatch(schema, cols, n)

    @staticmethod
    def concat(batches: Sequence["HostBatch"]) -> "HostBatch":
        """Host-side Table.concatenate."""
        assert batches
        schema = batches[0].schema
        cols = []
        for i, f in enumerate(schema.fields):
            data = np.concatenate([b.columns[i].data for b in batches])
            val = np.concatenate([b.columns[i].validity for b in batches])
            vbs = [b.columns[i].varbytes for b in batches]
            vb = None
            if all(v is not None for v in vbs):
                vb = (np.concatenate([v[0] for v in vbs]),
                      np.concatenate([v[1] for v in vbs]))
            cols.append(HostColumn(f.data_type, data, val, vb))
        return HostBatch(schema, cols, sum(b.num_rows for b in batches))
