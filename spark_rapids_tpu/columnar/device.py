"""Device-resident columnar batches (the GpuColumnVector / cudf Table twin).

TPU-first design, not a translation of the reference's device model:

- Every column is a pair of JAX arrays in HBM: fixed-width ``data`` plus a
  ``validity`` bool mask (Arrow-style; reference keeps the same split in
  GpuColumnVector.java over cudf buffers).
- Strings/binary are padded byte matrices ``uint8[capacity, char_cap]`` with
  a ``lengths`` vector — tensor-shaped so XLA can tile them (the reference
  gets offset+bytes columns from cudf; offsets fight static shapes on TPU).
- **Static shapes everywhere**: a batch has a ``capacity`` bucketed to a
  power of two; the real row count is tracked by an ``active`` row mask and
  a lazily-fetched host count. Filters only flip mask bits (no data
  movement); compaction happens on explicit request with a fixed-shape
  argsort-gather. This is how the build avoids XLA recompilation storms on
  data-dependent row counts (SURVEY.md section 7 "hard parts" (a)).
- A row is *padding* iff ``active[i]`` is False. Padding rows also carry
  validity=False in every column so masked reductions never see them.

Null slots hold deterministic zeros (normalized), mirroring
HostColumn.normalized(), so bitwise comparisons and hashing are stable.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Any, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from spark_rapids_tpu.columnar.host import HostBatch, HostColumn
from spark_rapids_tpu.sql import types as T

# Minimum capacity bucket: small enough for tests, large enough that op
# compile caches stay tiny (log2 buckets between MIN and max batch rows).
MIN_CAPACITY = 64
DEFAULT_CHAR_CAP = 32


def bucket_capacity(n: int) -> int:
    """Round up to the next power of two, floored at MIN_CAPACITY."""
    if n <= MIN_CAPACITY:
        return MIN_CAPACITY
    return 1 << math.ceil(math.log2(n))


def bucket_char_cap(max_len: int) -> int:
    """Byte-matrix width bucket: multiple-of-8 padding, floor 8."""
    if max_len <= 8:
        return 8
    return 8 * math.ceil(max_len / 8)


def is_string_like(dt: T.DataType) -> bool:
    return isinstance(dt, (T.StringType, T.BinaryType))


def storage_jnp_dtype(dt: T.DataType) -> jnp.dtype:
    """Device storage dtype for fixed-width types."""
    return jnp.dtype(T.numpy_dtype(dt))


@dataclass
class DeviceColumn:
    """Fixed-width device column: data[capacity] + validity[capacity]."""

    dtype: T.DataType
    data: jax.Array
    validity: jax.Array  # bool

    @property
    def capacity(self) -> int:
        return self.data.shape[0]

    def arrays(self) -> Tuple[jax.Array, ...]:
        return (self.data, self.validity)

    @staticmethod
    def from_arrays(dtype: T.DataType, arrs: Sequence[jax.Array]
                    ) -> "DeviceColumn":
        data, validity = arrs
        return DeviceColumn(dtype, data, validity)


@dataclass
class DeviceStringColumn:
    """String/binary device column: padded byte matrix + lengths.

    ``chars`` is uint8[capacity, char_cap], zero-padded past ``lengths[i]``;
    zero-padding keeps plain lexicographic comparison of rows equal to
    UTF-8 binary order (shorter string sorts before its extensions), which
    the sort/join kernels rely on.

    Rows longer than char_cap cannot be represented; the host->device
    transfer picks char_cap from the actual max length, and TypeSig gating
    falls back to CPU for columns beyond ``MAX_DEVICE_STRING`` bytes.
    """

    dtype: T.DataType
    chars: jax.Array    # uint8[capacity, char_cap]
    lengths: jax.Array  # int32[capacity]
    validity: jax.Array

    MAX_DEVICE_STRING = 1 << 14

    @property
    def capacity(self) -> int:
        return self.chars.shape[0]

    @property
    def char_cap(self) -> int:
        return self.chars.shape[1]

    def arrays(self) -> Tuple[jax.Array, ...]:
        return (self.chars, self.lengths, self.validity)

    @staticmethod
    def from_arrays(dtype: T.DataType, arrs: Sequence[jax.Array]
                    ) -> "DeviceStringColumn":
        chars, lengths, validity = arrs
        return DeviceStringColumn(dtype, chars, lengths, validity)


AnyDeviceColumn = Union[DeviceColumn, DeviceStringColumn]


def make_column(dtype: T.DataType, arrs: Sequence[jax.Array]
                ) -> AnyDeviceColumn:
    if is_string_like(dtype):
        return DeviceStringColumn.from_arrays(dtype, arrs)
    return DeviceColumn.from_arrays(dtype, arrs)


@dataclass
class DeviceBatch:
    """A columnar batch resident in device HBM.

    ``active`` marks real rows; everything at i >= original row count (and
    everything filtered out since) is False. ``_num_rows`` caches the host
    row count; ``row_count()`` materializes it (one tiny transfer) when a
    sizing decision needs it.
    """

    schema: T.StructType
    columns: List[AnyDeviceColumn]
    active: jax.Array  # bool[capacity]
    _num_rows: Optional[int] = None

    @property
    def capacity(self) -> int:
        return int(self.active.shape[0])

    @property
    def num_cols(self) -> int:
        return len(self.columns)

    def column(self, i: int) -> AnyDeviceColumn:
        return self.columns[i]

    def row_count(self) -> int:
        if self._num_rows is None:
            self._num_rows = int(jnp.sum(self.active))
        return self._num_rows

    def with_columns(self, schema: T.StructType,
                     columns: List[AnyDeviceColumn]) -> "DeviceBatch":
        return DeviceBatch(schema, columns, self.active, self._num_rows)

    def sizeof(self) -> int:
        """Device bytes held by this batch (for HBM accounting)."""
        total = self.active.size * 1
        for c in self.columns:
            for a in c.arrays():
                total += a.size * a.dtype.itemsize
        return total

    # -- transfer ----------------------------------------------------------

    @staticmethod
    def from_host(batch: HostBatch, capacity: Optional[int] = None,
                  device: Optional[jax.Device] = None) -> "DeviceBatch":
        cap = capacity or bucket_capacity(max(1, batch.num_rows))
        assert cap >= batch.num_rows, (cap, batch.num_rows)
        cols: List[AnyDeviceColumn] = []
        for f, c in zip(batch.schema.fields, batch.columns):
            cols.append(_host_col_to_device(c, f.data_type, cap, device))
        active_np = np.zeros(cap, dtype=bool)
        active_np[:batch.num_rows] = True
        active = _put(active_np, device)
        return DeviceBatch(batch.schema, cols, active, batch.num_rows)

    def to_host(self) -> HostBatch:
        """Gather active rows back to a HostBatch (device -> host copy)."""
        active = np.asarray(self.active)
        idx = np.nonzero(active)[0]
        cols: List[HostColumn] = []
        for f, c in zip(self.schema.fields, self.columns):
            cols.append(_device_col_to_host(c, f.data_type, idx))
        b = HostBatch(self.schema, cols, len(idx))
        return b

    @staticmethod
    def empty(schema: T.StructType, capacity: int = MIN_CAPACITY
              ) -> "DeviceBatch":
        return DeviceBatch.from_host(HostBatch.empty(schema), capacity)


def _put(arr: np.ndarray, device: Optional[jax.Device]) -> jax.Array:
    if device is not None:
        return jax.device_put(arr, device)
    return jnp.asarray(arr)


def _host_col_to_device(c: HostColumn, dt: T.DataType, cap: int,
                        device: Optional[jax.Device]) -> AnyDeviceColumn:
    n = len(c)
    validity = np.zeros(cap, dtype=bool)
    validity[:n] = c.validity
    if is_string_like(dt):
        encoded: List[bytes] = []
        max_len = 1
        for i in range(n):
            if c.validity[i]:
                v = c.data[i]
                b = v.encode("utf-8") if isinstance(v, str) else bytes(v)
            else:
                b = b""
            encoded.append(b)
            max_len = max(max_len, len(b))
        char_cap = bucket_char_cap(max_len)
        chars = np.zeros((cap, char_cap), dtype=np.uint8)
        lengths = np.zeros(cap, dtype=np.int32)
        for i, b in enumerate(encoded):
            chars[i, :len(b)] = np.frombuffer(b, dtype=np.uint8)
            lengths[i] = len(b)
        return DeviceStringColumn(dt, _put(chars, device),
                                  _put(lengths, device),
                                  _put(validity, device))
    np_dt = T.numpy_dtype(dt)
    data = np.zeros(cap, dtype=np_dt)
    # normalized() zeroes invalid slots on the host side already
    data[:n] = c.normalized().data
    return DeviceColumn(dt, _put(data, device), _put(validity, device))


def _device_col_to_host(c: AnyDeviceColumn, dt: T.DataType,
                        idx: np.ndarray) -> HostColumn:
    if isinstance(c, DeviceStringColumn):
        chars = np.asarray(c.chars)
        lengths = np.asarray(c.lengths)
        validity = np.asarray(c.validity)[idx]
        data = np.empty(len(idx), dtype=object)
        is_binary = isinstance(dt, T.BinaryType)
        for out_i, i in enumerate(idx):
            raw = chars[i, :lengths[i]].tobytes()
            if is_binary:
                data[out_i] = raw if validity[out_i] else b""
            else:
                data[out_i] = (raw.decode("utf-8", errors="replace")
                               if validity[out_i] else "")
        return HostColumn(dt, data, validity)
    data = np.asarray(c.data)[idx]
    validity = np.asarray(c.validity)[idx]
    return HostColumn(dt, data.copy(), validity.copy()).normalized()


def concat_device(batches: Sequence[DeviceBatch]) -> DeviceBatch:
    """Device-side Table.concatenate: compact all actives into one batch.

    Output capacity = bucket(total active rows); fixed-shape per input
    (gather into slices), so XLA sees only bucketed shapes.
    """
    assert batches
    schema = batches[0].schema
    counts = [b.row_count() for b in batches]
    total = sum(counts)
    cap = bucket_capacity(max(1, total))
    compacted = [compact(b) for b in batches]
    cols: List[AnyDeviceColumn] = []
    for ci, f in enumerate(schema.fields):
        parts = [b.columns[ci] for b in compacted]
        if is_string_like(f.data_type):
            char_cap = max(p.char_cap for p in parts)
            chars = jnp.zeros((cap, char_cap), dtype=jnp.uint8)
            lengths = jnp.zeros(cap, dtype=jnp.int32)
            validity = jnp.zeros(cap, dtype=bool)
            off = 0
            for p, n in zip(parts, counts):
                if n == 0:
                    continue
                pc = p.chars[:n]
                if p.char_cap < char_cap:
                    pc = jnp.pad(pc, ((0, 0), (0, char_cap - p.char_cap)))
                chars = jax.lax.dynamic_update_slice(chars, pc, (off, 0))
                lengths = jax.lax.dynamic_update_slice(
                    lengths, p.lengths[:n], (off,))
                validity = jax.lax.dynamic_update_slice(
                    validity, p.validity[:n], (off,))
                off += n
            cols.append(DeviceStringColumn(f.data_type, chars, lengths,
                                           validity))
        else:
            data = jnp.zeros(cap, dtype=storage_jnp_dtype(f.data_type))
            validity = jnp.zeros(cap, dtype=bool)
            off = 0
            for p, n in zip(parts, counts):
                if n == 0:
                    continue
                data = jax.lax.dynamic_update_slice(data, p.data[:n], (off,))
                validity = jax.lax.dynamic_update_slice(
                    validity, p.validity[:n], (off,))
                off += n
            cols.append(DeviceColumn(f.data_type, data, validity))
    active = jnp.arange(cap) < total
    return DeviceBatch(schema, cols, active, total)


def _compaction_order(active: jax.Array) -> jax.Array:
    """Stable permutation moving active rows to the front."""
    # stable argsort of (!active): False (active) sorts first, order kept
    return jnp.argsort(~active, stable=True)


def take_columns(columns: Sequence[AnyDeviceColumn], idx: jax.Array,
                 valid_at: Optional[jax.Array] = None
                 ) -> List[AnyDeviceColumn]:
    """Gather rows by index; when valid_at is given, rows where it is
    False become null (outer-join style null rows use idx clamped to 0)."""
    out: List[AnyDeviceColumn] = []
    for c in columns:
        if isinstance(c, DeviceStringColumn):
            chars = c.chars[idx]
            lengths = c.lengths[idx]
            validity = c.validity[idx]
            if valid_at is not None:
                validity = validity & valid_at
                lengths = jnp.where(validity, lengths, 0)
                chars = jnp.where(validity[:, None], chars, 0)
            out.append(DeviceStringColumn(c.dtype, chars, lengths, validity))
        else:
            data = c.data[idx]
            validity = c.validity[idx]
            if valid_at is not None:
                validity = validity & valid_at
                data = jnp.where(validity, data,
                                 jnp.zeros((), dtype=data.dtype))
            out.append(DeviceColumn(c.dtype, data, validity))
    return out


@jax.jit
def _compact_arrays(active: jax.Array, *flat: jax.Array):
    order = _compaction_order(active)
    n = jnp.sum(active)
    new_active = jnp.arange(active.shape[0]) < n
    outs = []
    for a in flat:
        g = a[order]
        # zero out the padding tail for determinism
        if a.ndim == 2:
            g = jnp.where(new_active[:, None], g, 0)
        else:
            g = jnp.where(new_active, g, jnp.zeros((), dtype=g.dtype))
        outs.append(g)
    return new_active, tuple(outs)


def flatten_batch(batch: DeviceBatch
                  ) -> Tuple[List[jax.Array], List[Tuple[T.DataType, int]]]:
    """Flatten column arrays + per-column (dtype, arity) spec; inverse is
    rebuild_columns. Shared by compaction and the split/serialize kernels."""
    flat: List[jax.Array] = []
    spec: List[Tuple[T.DataType, int]] = []
    for c in batch.columns:
        arrs = c.arrays()
        spec.append((c.dtype, len(arrs)))
        flat.extend(arrs)
    return flat, spec


def rebuild_columns(spec: Sequence[Tuple[T.DataType, int]],
                    outs: Sequence[jax.Array]) -> List[AnyDeviceColumn]:
    cols: List[AnyDeviceColumn] = []
    i = 0
    for dt, n_arr in spec:
        cols.append(make_column(dt, outs[i:i + n_arr]))
        i += n_arr
    return cols


def compact(batch: DeviceBatch) -> DeviceBatch:
    """Move active rows to the front (fixed-shape compaction)."""
    flat, spec = flatten_batch(batch)
    new_active, outs = _compact_arrays(batch.active, *flat)
    cols = rebuild_columns(spec, outs)
    return DeviceBatch(batch.schema, cols, new_active, batch._num_rows)


def shrink_to_bucket(batch: DeviceBatch) -> DeviceBatch:
    """Compact, then if the active count fits a smaller capacity bucket,
    slice down to it (keeps shuffle payloads tight)."""
    n = batch.row_count()
    cap = bucket_capacity(max(1, n))
    if cap >= batch.capacity:
        return compact(batch)
    c = compact(batch)
    cols: List[AnyDeviceColumn] = []
    for col in c.columns:
        if isinstance(col, DeviceStringColumn):
            cols.append(DeviceStringColumn(
                col.dtype, col.chars[:cap], col.lengths[:cap],
                col.validity[:cap]))
        else:
            cols.append(DeviceColumn(col.dtype, col.data[:cap],
                                     col.validity[:cap]))
    return DeviceBatch(c.schema, cols, c.active[:cap], n)
