"""Device-resident columnar batches (the GpuColumnVector / cudf Table twin).

TPU-first design, not a translation of the reference's device model:

- Every column is a pair of JAX arrays in HBM: fixed-width ``data`` plus a
  ``validity`` bool mask (Arrow-style; reference keeps the same split in
  GpuColumnVector.java over cudf buffers).
- Strings/binary are padded byte matrices ``uint8[capacity, char_cap]`` with
  a ``lengths`` vector — tensor-shaped so XLA can tile them (the reference
  gets offset+bytes columns from cudf; offsets fight static shapes on TPU).
- **Static shapes everywhere**: a batch has a ``capacity`` bucketed to a
  power of two; the real row count is tracked by an ``active`` row mask and
  a lazily-fetched host count. Filters only flip mask bits (no data
  movement); compaction happens on explicit request with a fixed-shape
  argsort-gather. This is how the build avoids XLA recompilation storms on
  data-dependent row counts (SURVEY.md section 7 "hard parts" (a)).
- A row is *padding* iff ``active[i]`` is False. Padding rows also carry
  validity=False in every column so masked reductions never see them.

Null slots hold deterministic zeros (normalized), mirroring
HostColumn.normalized(), so bitwise comparisons and hashing are stable.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Any, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from spark_rapids_tpu.columnar.host import HostBatch, HostColumn
from spark_rapids_tpu.sql import types as T

# Minimum capacity bucket: small enough for tests, large enough that op
# compile caches stay tiny (log2 buckets between MIN and max batch rows).
MIN_CAPACITY = 64
DEFAULT_CHAR_CAP = 32


@jax.jit
def _count_active(active: jax.Array) -> jax.Array:
    return jnp.sum(active)


def bucket_capacity(n: int) -> int:
    """Smallest {1, 1.25, 1.5, 1.75} x 2^k capacity >= n, floored at
    MIN_CAPACITY. Quarter-step buckets bound padding waste at 25% (pure
    powers of two waste up to 100% — the round-2 bench put 1.25M rows in
    a 2M bucket) for 4x the program-cache keys."""
    if n <= MIN_CAPACITY:
        return MIN_CAPACITY
    base = 1 << (n.bit_length() - 1)
    if base == n:
        return n
    for num in (5, 6, 7):
        cap = (base >> 2) * num
        if cap >= n:
            return cap
    return base << 1


def bucket_char_cap(max_len: int) -> int:
    """Byte-matrix width bucket: multiple-of-8 padding, floor 8."""
    if max_len <= 8:
        return 8
    return 8 * math.ceil(max_len / 8)


def is_string_like(dt: T.DataType) -> bool:
    return isinstance(dt, (T.StringType, T.BinaryType))


def storage_jnp_dtype(dt: T.DataType) -> jnp.dtype:
    """Device storage dtype for fixed-width types."""
    return jnp.dtype(T.numpy_dtype(dt))


@dataclass
class DeviceColumn:
    """Fixed-width device column: data[capacity] + validity[capacity]."""

    dtype: T.DataType
    data: jax.Array
    validity: jax.Array  # bool

    @property
    def capacity(self) -> int:
        return self.data.shape[0]

    def arrays(self) -> Tuple[jax.Array, ...]:
        return (self.data, self.validity)

    @staticmethod
    def from_arrays(dtype: T.DataType, arrs: Sequence[jax.Array]
                    ) -> "DeviceColumn":
        data, validity = arrs
        return DeviceColumn(dtype, data, validity)


@dataclass
class DeviceDecimal128Column:
    """DECIMAL128 device column: unscaled value as two int64 limbs
    (``hi`` signed high, ``lo`` holding the uint64 low bit pattern) —
    the ops/int128 representation, resident in HBM. The reference keeps
    these as cudf DECIMAL128 columns (decimalExpressions.scala); two
    plain int64 arrays are the XLA-friendly shape of the same idea."""

    dtype: T.DataType  # DecimalType, precision > 18
    hi: jax.Array      # int64[capacity]
    lo: jax.Array      # int64[capacity] (uint64 bit pattern)
    validity: jax.Array

    @property
    def capacity(self) -> int:
        return self.hi.shape[0]

    @property
    def data(self) -> jax.Array:
        # sort/compact payload convenience: callers that need the limbs
        # use .hi/.lo; generic code paths must go through arrays()
        raise AttributeError("DeviceDecimal128Column has limbs, not data")

    def arrays(self) -> Tuple[jax.Array, ...]:
        return (self.hi, self.lo, self.validity)

    @staticmethod
    def from_arrays(dtype: T.DataType, arrs: Sequence[jax.Array]
                    ) -> "DeviceDecimal128Column":
        hi, lo, validity = arrs
        return DeviceDecimal128Column(dtype, hi, lo, validity)


@dataclass
class DeviceStringColumn:
    """String/binary device column: padded byte matrix + lengths.

    ``chars`` is uint8[capacity, char_cap], zero-padded past ``lengths[i]``;
    zero-padding keeps plain lexicographic comparison of rows equal to
    UTF-8 binary order (shorter string sorts before its extensions), which
    the sort/join kernels rely on.

    Rows longer than char_cap cannot be represented; the host->device
    transfer picks char_cap from the actual max length, and TypeSig gating
    falls back to CPU for columns beyond ``MAX_DEVICE_STRING`` bytes.
    """

    dtype: T.DataType
    chars: jax.Array    # uint8[capacity, char_cap]
    lengths: jax.Array  # int32[capacity]
    validity: jax.Array

    MAX_DEVICE_STRING = 1 << 14

    @property
    def capacity(self) -> int:
        return self.chars.shape[0]

    @property
    def char_cap(self) -> int:
        return self.chars.shape[1]

    def arrays(self) -> Tuple[jax.Array, ...]:
        return (self.chars, self.lengths, self.validity)

    @staticmethod
    def from_arrays(dtype: T.DataType, arrs: Sequence[jax.Array]
                    ) -> "DeviceStringColumn":
        chars, lengths, validity = arrs
        return DeviceStringColumn(dtype, chars, lengths, validity)


@dataclass
class DeviceArrayColumn:
    """Array column: per-row (start, length) views into a shared element
    pool (the offsets+child model of Arrow/cudf list columns, made
    gather-friendly: after a row gather, starts may alias/point anywhere
    in the pool, so no contiguity is assumed).

    ``child`` is the element pool (a device column of its own capacity);
    its validity marks null ELEMENTS. ``validity`` marks null arrays.
    Nested columns are confined to upload -> project/filter ->
    generate/collect paths; exchanges, sorts, joins, and aggregations
    tag nested inputs back to CPU (TpuOverrides).
    """

    dtype: T.ArrayType
    starts: jax.Array   # int32[capacity]
    lengths: jax.Array  # int32[capacity]
    child: "AnyDeviceColumn"
    validity: jax.Array

    @property
    def capacity(self) -> int:
        return self.starts.shape[0]

    def arrays(self) -> Tuple[jax.Array, ...]:
        return (self.starts, self.lengths) + self.child.arrays() \
            + (self.validity,)

    @staticmethod
    def from_arrays(dtype: T.ArrayType, arrs: Sequence[jax.Array]
                    ) -> "DeviceArrayColumn":
        child = make_column(dtype.element_type, arrs[2:-1])
        return DeviceArrayColumn(dtype, arrs[0], arrs[1], child, arrs[-1])


@dataclass
class DeviceStructColumn:
    """Struct column as column-of-columns (the Arrow/cudf struct model,
    GpuColumnVector.java nested handling): each field is its own device
    column at the SAME capacity; ``validity`` marks null structs (null
    structs also null every field slot, kept normalized)."""

    dtype: T.StructType
    fields: List["AnyDeviceColumn"]
    validity: jax.Array

    @property
    def capacity(self) -> int:
        return self.validity.shape[0]

    def arrays(self) -> Tuple[jax.Array, ...]:
        out: Tuple[jax.Array, ...] = ()
        for f in self.fields:
            out = out + f.arrays()
        return out + (self.validity,)

    @staticmethod
    def from_arrays(dtype: T.StructType, arrs: Sequence[jax.Array]
                    ) -> "DeviceStructColumn":
        fields = []
        off = 0
        for f in dtype.fields:
            k = column_arity(f.data_type)
            fields.append(make_column(f.data_type, arrs[off:off + k]))
            off += k
        return DeviceStructColumn(dtype, fields, arrs[off])


AnyDeviceColumn = Union[DeviceColumn, DeviceStringColumn,
                        DeviceDecimal128Column, "DeviceArrayColumn",
                        DeviceStructColumn]


def column_arity(dtype: T.DataType) -> int:
    """Number of flat arrays a device column of `dtype` carries."""
    if isinstance(dtype, T.ArrayType):
        return 3 + column_arity(dtype.element_type)
    if isinstance(dtype, T.StructType):
        return 1 + sum(column_arity(f.data_type) for f in dtype.fields)
    if is_string_like(dtype) or T.is_limb_decimal(dtype):
        return 3  # (chars, lengths, validity) / (hi, lo, validity)
    return 2


def make_column(dtype: T.DataType, arrs: Sequence[jax.Array]
                ) -> AnyDeviceColumn:
    if isinstance(dtype, T.ArrayType):
        return DeviceArrayColumn.from_arrays(dtype, arrs)
    if isinstance(dtype, T.StructType):
        return DeviceStructColumn.from_arrays(dtype, arrs)
    if is_string_like(dtype):
        return DeviceStringColumn.from_arrays(dtype, arrs)
    if T.is_limb_decimal(dtype):
        return DeviceDecimal128Column.from_arrays(dtype, arrs)
    return DeviceColumn.from_arrays(dtype, arrs)


@dataclass
class DeviceBatch:
    """A columnar batch resident in device HBM.

    ``active`` marks real rows; everything at i >= original row count (and
    everything filtered out since) is False. ``_num_rows`` caches the host
    row count; ``row_count()`` materializes it (one tiny transfer) when a
    sizing decision needs it.
    """

    schema: T.StructType
    columns: List[AnyDeviceColumn]
    active: jax.Array  # bool[capacity]
    _num_rows: Optional[int] = None
    # optional device-resident count scalar, attached by producers that
    # compute it anyway (e.g. the FK fast-path join): row_count()
    # resolves it with a prefetched read instead of dispatching a fresh
    # _count_active program + flat roundtrip
    _num_rows_dev: Optional[jax.Array] = None

    @property
    def capacity(self) -> int:
        return int(self.active.shape[0])

    @property
    def num_cols(self) -> int:
        return len(self.columns)

    def column(self, i: int) -> AnyDeviceColumn:
        return self.columns[i]

    def row_count(self) -> int:
        if self._num_rows is None:
            if self._num_rows_dev is not None:
                self._num_rows = int(np.asarray(self._num_rows_dev))
            else:
                # jitted: an EAGER jnp.sum pays a per-op dispatch
                # handshake (~100ms on tunneled TPU backends)
                self._num_rows = int(_count_active(self.active))
        return self._num_rows

    def with_columns(self, schema: T.StructType,
                     columns: List[AnyDeviceColumn]) -> "DeviceBatch":
        return DeviceBatch(schema, columns, self.active, self._num_rows,
                           self._num_rows_dev)

    def sizeof(self) -> int:
        """Device bytes held by this batch (for HBM accounting)."""
        total = self.active.size * 1
        for c in self.columns:
            for a in c.arrays():
                total += a.size * a.dtype.itemsize
        return total

    # -- transfer ----------------------------------------------------------

    @staticmethod
    def from_host(batch: HostBatch, capacity: Optional[int] = None,
                  device: Optional[jax.Device] = None) -> "DeviceBatch":
        cap = capacity or bucket_capacity(max(1, batch.num_rows))
        assert cap >= batch.num_rows, (cap, batch.num_rows)
        # packed codec: narrowed/bit-packed columns ride ONE int32
        # staging buffer + ONE device_put; a single jitted program
        # decodes to full-width padded columns in HBM (transfer.py)
        from spark_rapids_tpu.columnar.transfer import upload_batch
        # NOT retried here: the DeviceStore promote path (memory.py
        # _access) calls this while HOLDING the store lock — a spill +
        # backoff sleep inside it would stall every task in the
        # process. OOM propagates to the caller's own retry scope.
        # tpu-lint: disable=retry-coverage(runs under DeviceStore._lock on the promote path; spilling/sleeping there blocks the whole store — callers own the retry)
        return upload_batch(batch, cap, device)

    def to_host(self) -> HostBatch:
        """Gather active rows back to a HostBatch (device -> host copy).
        Buffers ride per-dtype concatenated transfers: each uncached
        D2H fetch costs ~100ms flat on tunneled backends, so a batch of
        N arrays moves in len(distinct dtypes) fetches, not N."""
        return finish_to_host(self.start_to_host())

    def start_to_host(self):
        """Non-blocking half of to_host: dispatches the pack program and
        the async D2H copies, returns a token for finish_to_host. Lets a
        consumer overlap the ~100ms flat fetch latency of batch k+1 with
        batch k's host-side conversion (TpuColumnarToRowExec lookahead)."""
        flat, spec = flatten_batch(self)
        return (self, spec, start_fetch([self.active] + flat))

    @staticmethod
    def empty(schema: T.StructType, capacity: int = MIN_CAPACITY
              ) -> "DeviceBatch":
        return DeviceBatch.from_host(HostBatch.empty(schema), capacity)


def _prefetch_host(arrays: List[jax.Array]) -> bool:
    """NON-BLOCKING: enqueue async D2H copies so a later np.asarray
    finds the bytes already local. The flat per-fetch latency
    (~100-200ms on tunneled backends) overlaps with whatever runs
    between the prefetch and the blocking read. Returns False when the
    backend has no async copies — callers that replaced a single batched
    fetch with per-item reads must fall back to batching then."""
    for a in arrays:
        try:
            a.copy_to_host_async()
        except Exception:
            return False  # backend without async copies
    return True


def finish_to_host(token) -> HostBatch:
    """Blocking half of DeviceBatch.start_to_host."""
    batch, spec, fetch_tok = token
    np_arrs = finish_fetch(fetch_tok)
    active = np_arrs[0]
    idx = np.nonzero(active)[0]
    cols: List[HostColumn] = []
    i = 1
    for f, (dt, n_arr) in zip(batch.schema.fields, spec):
        cols.append(_np_col_to_host(dt, np_arrs[i:i + n_arr], idx))
        i += n_arr
    return HostBatch(batch.schema, cols, len(idx))


from spark_rapids_tpu.jit_cache import JitCache

_FETCH_PACK_CACHE = JitCache("fetchPack")


def start_fetch(arrays: List[jax.Array]):
    """Non-blocking: dispatch the per-dtype concat program (one
    transfer per distinct dtype instead of one per array) and the async
    copies; returns a token for finish_fetch."""
    key = tuple((a.shape, str(a.dtype)) for a in arrays)
    if len(arrays) <= 2:
        _prefetch_host(list(arrays))
        return ("raw", arrays, None)
    cached = _FETCH_PACK_CACHE.get(key)
    if cached is None:
        groups: dict = {}
        for i, (_shape, dt) in enumerate(key):
            groups.setdefault(dt, []).append(i)
        order = list(groups.items())

        def _fn(*arrs):
            return tuple(
                jnp.concatenate([arrs[i].reshape(-1) for i in idxs])
                if len(idxs) > 1 else arrs[idxs[0]].reshape(-1)
                for _dt, idxs in order)
        cached = _FETCH_PACK_CACHE.put(key, (jax.jit(_fn), order))
    jfn, order = cached
    packed = jfn(*arrays)
    _prefetch_host(list(packed))
    return ("packed", arrays, (order, packed))


def finish_fetch(token) -> List[np.ndarray]:
    kind, arrays, extra = token
    if kind == "raw":
        return [np.asarray(a) for a in arrays]
    order, packed = extra
    out: List[Optional[np.ndarray]] = [None] * len(arrays)
    for (_dt, idxs), buf in zip(order, packed):
        b = np.asarray(buf)
        off = 0
        for i in idxs:
            shape = arrays[i].shape
            size = int(np.prod(shape))
            out[i] = b[off:off + size].reshape(shape)
            off += size
    return out


def _fetch_arrays(arrays: List[jax.Array]) -> List[np.ndarray]:
    return finish_fetch(start_fetch(arrays))


def _np_col_to_host(dt: T.DataType, arrs: List[np.ndarray],
                    idx: np.ndarray) -> HostColumn:
    """Numpy twin of _device_col_to_host over already-fetched arrays."""
    if isinstance(dt, T.StructType):
        from spark_rapids_tpu.columnar.host import struct_storage_rows
        validity = arrs[-1][idx].astype(bool)
        fcols = []
        off = 0
        for f in dt.fields:
            k = column_arity(f.data_type)
            fcols.append(_np_col_to_host(f.data_type, arrs[off:off + k],
                                         idx))
            off += k
        return HostColumn(dt, struct_storage_rows(fcols, validity),
                          validity)
    if isinstance(dt, T.ArrayType):
        starts, lengths, validity = arrs[0], arrs[1], arrs[-1]
        child_arrs = arrs[2:-1]
        pool_n = child_arrs[0].shape[0]
        pc = _np_col_to_host(dt.element_type, list(child_arrs),
                             np.arange(pool_n))
        # storage-form pool values (to_pylist would convert dates etc.,
        # diverging from the CPU engine's canonical element form)
        pool = [pc.data[i].item() if isinstance(pc.data[i], np.generic)
                else pc.data[i]
                for i in range(len(pc.data))]
        pool = [v if ok else None
                for v, ok in zip(pool, pc.validity.tolist())]
        validity = validity[idx]
        data = np.empty(len(idx), dtype=object)
        for out_i, i in enumerate(idx):
            if validity[out_i]:
                s, ln = int(starts[i]), int(lengths[i])
                data[out_i] = tuple(pool[s:s + ln])
            else:
                data[out_i] = ()
        return HostColumn(dt, data, validity)
    if is_string_like(dt):
        chars, lengths, validity = arrs
        validity = validity[idx]
        data = np.empty(len(idx), dtype=object)
        is_binary = isinstance(dt, T.BinaryType)
        for out_i, i in enumerate(idx):
            raw = chars[i, :lengths[i]].tobytes()
            if is_binary:
                data[out_i] = raw if validity[out_i] else b""
            else:
                data[out_i] = (raw.decode("utf-8", errors="replace")
                               if validity[out_i] else "")
        return HostColumn(dt, data, validity)
    if T.is_limb_decimal(dt):
        hi, lo, validity = arrs
        data = np.stack([hi[idx], lo[idx]], axis=1)
        return HostColumn(dt, data, validity[idx].copy()).normalized()
    data, validity = arrs
    return HostColumn(dt, data[idx].copy(),
                      validity[idx].copy()).normalized()


def _put(arr: np.ndarray, device: Optional[jax.Device]) -> jax.Array:
    if device is not None:
        from spark_rapids_tpu import retry as R
        return R.with_retry(lambda: jax.device_put(arr, device))
    return jnp.asarray(arr)


def batch_device(b: DeviceBatch) -> Optional[jax.Device]:
    """The single device this batch's buffers live on, or None when the
    buffers are sharded/replicated across several (e.g. the landed
    output of a mesh exchange). The mesh scan pins each reader stream's
    batches to one chip; residency-aware consumers (exchange slotting,
    broadcast alignment) group by this."""
    try:
        ds = b.active.devices()
    except Exception:  # non-Array stand-ins in unit tests
        return None
    return next(iter(ds)) if len(ds) == 1 else None


def batch_to_device(b: DeviceBatch, device: jax.Device) -> DeviceBatch:
    """Copy a batch's buffers to ``device`` (device-to-device; a cheap
    no-op when already resident there)."""
    from spark_rapids_tpu import retry as R
    flat, spec = flatten_batch(b)
    moved = R.with_retry(lambda: jax.device_put(flat + [b.active],
                                                device))
    return DeviceBatch(b.schema, rebuild_columns(spec, moved[:-1]),
                       moved[-1], b._num_rows)


# One fused program per (input shape-set, output capacity): eager
# op-by-op dispatch costs ~100ms per op on tunneled TPU backends, so the
# whole concatenation must be a single XLA executable.
_CONCAT_CACHE = JitCache("concat")


def concat_device(batches: Sequence[DeviceBatch]) -> DeviceBatch:
    """Device-side Table.concatenate: compact all actives into one batch.

    Output capacity = bucket(total active rows). ONE jitted program
    (cached on input shapes + output capacity): each compacted input is
    written at its traced row offset in FORWARD order, so every write
    repairs the previous input's zero padding — full-capacity updates
    with dynamic offsets, no dynamic shapes. A sum-of-capacities scratch
    guards against XLA's update-slice start clamping, then a static
    slice takes the bucketed prefix.
    """
    assert batches
    if len(batches) == 1:
        return batches[0]
    # inputs spanning chips (a broadcast build or a global merge over
    # the mesh-sharded scan) must land on ONE device first: a jitted
    # program over differently-committed arrays is a placement error.
    # Merge onto the chip holding the most rows (capacity is static —
    # no count sync) so the skewed case moves the small side only
    devs = [batch_device(b) for b in batches]
    if any(d is not None for d in devs):
        load: dict = {}
        for b, d in zip(batches, devs):
            if d is not None:
                load[d] = load.get(d, 0) + b.capacity
        tgt = max(load, key=lambda d: (load[d], -d.id))
        if any(d is not None and d.id != tgt.id for d in devs):
            batches = [b if d is None or d.id == tgt.id
                       else batch_to_device(b, tgt)
                       for b, d in zip(batches, devs)]
    schema = batches[0].schema
    counts = [b.row_count() for b in batches]
    total = sum(counts)
    cap = bucket_capacity(max(1, total))
    compacted = [compact(b) for b in batches]
    flats = []
    specs = []
    for b in compacted:
        flat, spec = flatten_batch(b)
        flats.append(flat)
        specs.append(spec)
    shapes = tuple(tuple((a.shape, str(a.dtype)) for a in flat)
                   for flat in flats)
    key = (shapes, cap)
    fn = _CONCAT_CACHE.get(key)
    if fn is None:
        n_arrays = len(flats[0])
        # scratch must cover BOTH the forward-write extent (sum of input
        # capacities) and the output bucket (which can exceed it when
        # inputs are fully active)
        caps_sum = max(sum(b.capacity for b in compacted), cap)
        # per-FLAT-ARRAY char width: inputs may disagree on a 2-D byte
        # matrix width (incl. string fields nested in structs); writes
        # of a narrower input into the max-width zeros matrix leave the
        # correct zero padding
        arr_widths = [
            max(flats[bi][ai].shape[1] for bi in range(len(flats)))
            if flats[0][ai].ndim == 2 else 0
            for ai in range(n_arrays)]

        def _fn(counts_arr, *all_flat):
            offs = jnp.concatenate([
                jnp.zeros(1, jnp.int64), jnp.cumsum(counts_arr)])
            outs = []
            for ai in range(n_arrays):
                first = all_flat[ai]
                if first.ndim == 2:
                    cc = arr_widths[ai]
                    big = jnp.zeros((caps_sum, cc), dtype=first.dtype)
                    for bi in range(len(flats)):
                        a = all_flat[bi * n_arrays + ai]
                        big = jax.lax.dynamic_update_slice(
                            big, a, (offs[bi], jnp.int64(0)))
                    outs.append(big[:cap])
                else:
                    big = jnp.zeros(caps_sum, dtype=first.dtype)
                    for bi in range(len(flats)):
                        a = all_flat[bi * n_arrays + ai]
                        big = jax.lax.dynamic_update_slice(
                            big, a, (offs[bi],))
                    outs.append(big[:cap])
            total_t = offs[len(flats)]
            active = jnp.arange(cap) < total_t
            return active, tuple(outs)
        fn = _CONCAT_CACHE.put(key, jax.jit(_fn))
    counts_arr = jnp.asarray(np.asarray(counts, dtype=np.int64))
    all_flat = [a for flat in flats for a in flat]
    active, outs = fn(counts_arr, *all_flat)
    cols = rebuild_columns(specs[0], outs)
    return DeviceBatch(schema, cols, active, total)


def mask_col(c: AnyDeviceColumn, keep: jax.Array) -> AnyDeviceColumn:
    """Null out rows outside `keep` (normalized zeros underneath)."""
    if isinstance(c, DeviceStructColumn):
        v = c.validity & keep
        return DeviceStructColumn(c.dtype,
                                  [mask_col(f, v) for f in c.fields], v)
    if isinstance(c, DeviceArrayColumn):
        v = c.validity & keep
        z = jnp.zeros((), c.starts.dtype)
        return DeviceArrayColumn(c.dtype, jnp.where(v, c.starts, z),
                                 jnp.where(v, c.lengths, z), c.child, v)
    if isinstance(c, DeviceStringColumn):
        v = c.validity & keep
        return DeviceStringColumn(
            c.dtype, jnp.where(v[:, None], c.chars, 0),
            jnp.where(v, c.lengths, 0), v)
    if isinstance(c, DeviceDecimal128Column):
        v = c.validity & keep
        z = jnp.zeros((), jnp.int64)
        return DeviceDecimal128Column(c.dtype, jnp.where(v, c.hi, z),
                                      jnp.where(v, c.lo, z), v)
    v = c.validity & keep
    return DeviceColumn(c.dtype, jnp.where(v, c.data,
                                           jnp.zeros((), c.data.dtype)), v)


_SORT_SIGN64 = 0x8000000000000000


def _order_u64(a: jax.Array) -> Optional[jax.Array]:
    """Order-preserving uint64 encoding of one sort-key array, or None
    when the dtype has no such encoding on this backend (f64: 64-bit
    float bitcasts do not lower)."""
    if a.dtype == jnp.bool_:
        return a.astype(jnp.uint64)
    if a.dtype == jnp.uint64:
        return a
    if jnp.issubdtype(a.dtype, jnp.unsignedinteger):
        return a.astype(jnp.uint64)
    if a.dtype == jnp.float64:
        return None
    if a.dtype == jnp.float32:
        u = jax.lax.bitcast_convert_type(a, jnp.int32).view(jnp.uint32)
        u = jnp.where(a < 0, ~u, u | jnp.uint32(0x80000000))
        return u.astype(jnp.uint64)
    return a.astype(jnp.int64).view(jnp.uint64) ^ jnp.uint64(_SORT_SIGN64)


def sort_with_payload(keys: Sequence[jax.Array],
                      payload: Sequence[jax.Array]):
    """Stable lexicographic sort by `keys`; `payload` arrays follow via
    gathers on the resulting order. Returns (sorted_keys, order,
    sorted_payload), `order` total/stable (original index tiebreak).

    XLA's sort compile time on this TPU stack grows superlinearly with
    operand count (measured round 3: a 2-operand sort compiles in ~30s,
    6 operands in ~135s, 8+ operands effectively hangs the compiler).
    So multi-key sorts run as LSD radix passes: each key is encoded as
    an order-preserving uint64 word and a ``lax.scan`` performs one
    STABLE 2-operand sort per key, least-significant first — exactly
    one compiled sort instance regardless of key count. f64 keys (no
    order-preserving 64-bit encoding without a float bitcast) fall back
    to per-key unrolled passes."""
    cap = keys[0].shape[0]
    pos = jnp.arange(cap, dtype=jnp.int32)
    enc = [_order_u64(k) for k in keys]

    def stable_pass(k, order):
        kp = jnp.take(k, order)
        _s, o2 = jax.lax.sort((kp, order), num_keys=1, is_stable=True)
        return o2.astype(jnp.int32)

    if all(e is not None for e in enc):
        if len(enc) == 1:
            order = stable_pass(enc[0], pos)
        else:
            rev = enc[::-1]  # least significant first
            # first pass outside the scan: its output carries the vma
            # (varying-manual-axes) type the scan carry needs when this
            # runs inside a shard_map
            order0 = stable_pass(rev[0], pos)
            stacked = jnp.stack(rev[1:])

            def body(order, k):
                return stable_pass(k, order), None
            order, _ = jax.lax.scan(body, order0, stacked)
    else:
        order = pos
        for k in reversed(keys):
            order = stable_pass(k, order)
    from spark_rapids_tpu.ops.lanes import fused_take
    # ONE lane-matrix gather for keys + payload together (each separate
    # gather costs a flat ~25-40ms on the tunneled backend)
    gathered = fused_take(list(keys) + list(payload), order)
    sorted_keys = tuple(gathered[:len(keys)])
    sorted_payload = gathered[len(keys):]
    return sorted_keys, order, sorted_payload


def _compaction_order(active: jax.Array) -> jax.Array:
    """Stable permutation moving active rows to the front."""
    # stable argsort of (!active): False (active) sorts first, order kept
    return jnp.argsort(~active, stable=True)


def take_columns(columns: Sequence[AnyDeviceColumn], idx: jax.Array,
                 valid_at: Optional[jax.Array] = None
                 ) -> List[AnyDeviceColumn]:
    """Gather rows by index; when valid_at is given, rows where it is
    False become null (outer-join style null rows use idx clamped to 0).
    All columns ride ONE fused lane-matrix gather (ops/lanes.py) — the
    per-gather cost on this backend is a flat ~25-40ms regardless of
    width."""
    from spark_rapids_tpu.ops.lanes import fused_take
    arrays: List[jax.Array] = []
    for c in columns:
        if isinstance(c, DeviceArrayColumn):
            # the element pool is shared, not gathered
            arrays += [c.starts, c.lengths, c.validity]
        else:
            arrays += list(c.arrays())  # structs flatten recursively
    g = fused_take(arrays, idx)
    out: List[AnyDeviceColumn] = []
    off = 0
    for c in columns:
        if isinstance(c, DeviceArrayColumn):
            starts, lengths, validity = g[off:off + 3]
            off += 3
            if valid_at is not None:
                validity = validity & valid_at
            starts = jnp.where(validity, starts, 0)
            lengths = jnp.where(validity, lengths, 0)
            out.append(DeviceArrayColumn(c.dtype, starts, lengths,
                                         c.child, validity))
        elif isinstance(c, DeviceStringColumn):
            chars, lengths, validity = g[off:off + 3]
            off += 3
            if valid_at is not None:
                validity = validity & valid_at
                lengths = jnp.where(validity, lengths, 0)
                chars = jnp.where(validity[:, None], chars, 0)
            out.append(DeviceStringColumn(c.dtype, chars, lengths,
                                          validity))
        elif isinstance(c, DeviceDecimal128Column):
            hi, lo, validity = g[off:off + 3]
            off += 3
            if valid_at is not None:
                validity = validity & valid_at
                z = jnp.zeros((), jnp.int64)
                hi = jnp.where(validity, hi, z)
                lo = jnp.where(validity, lo, z)
            out.append(DeviceDecimal128Column(c.dtype, hi, lo, validity))
        elif isinstance(c, DeviceStructColumn):
            k = column_arity(c.dtype)
            sc = DeviceStructColumn.from_arrays(c.dtype, g[off:off + k])
            off += k
            if valid_at is not None:
                sc = mask_col(sc, valid_at)
            out.append(sc)
        else:
            data, validity = g[off:off + 2]
            off += 2
            if valid_at is not None:
                validity = validity & valid_at
                data = jnp.where(validity, data,
                                 jnp.zeros((), dtype=data.dtype))
            out.append(DeviceColumn(c.dtype, data, validity))
    return out


def _compact_body(active: jax.Array, flat):
    """Stable compaction (active rows to the front): ONE 2-operand sort
    pass for the permutation + ONE fused lane-matrix gather for all
    arrays. (A searchsorted-based variant was tried in round 5: XLA
    lowers searchsorted to ~log2(cap) gather iterations on this backend,
    costing more than the sort pass it saved.)"""
    from spark_rapids_tpu.ops.lanes import fused_take
    cap = active.shape[0]
    pos = jnp.arange(cap, dtype=jnp.int32)
    _k, idx = jax.lax.sort((~active, pos), num_keys=1, is_stable=True)
    new_active = pos < jnp.sum(active)
    outs = []
    for g in fused_take(list(flat), idx):
        # zero out the padding tail for determinism
        if g.ndim == 2:
            g = jnp.where(new_active[:, None], g, 0)
        else:
            g = jnp.where(new_active, g, jnp.zeros((), dtype=g.dtype))
        outs.append(g)
    return new_active, tuple(outs)


@jax.jit
def _compact_arrays(active: jax.Array, *flat: jax.Array):
    return _compact_body(active, flat)


def flatten_columns(columns: Sequence[AnyDeviceColumn]
                    ) -> Tuple[List[jax.Array], List[Tuple[T.DataType, int]]]:
    """Flatten column arrays + per-column (dtype, arity) spec; inverse is
    rebuild_columns."""
    flat: List[jax.Array] = []
    spec: List[Tuple[T.DataType, int]] = []
    for c in columns:
        arrs = c.arrays()
        spec.append((c.dtype, len(arrs)))
        flat.extend(arrs)
    return flat, spec


def flatten_batch(batch: DeviceBatch
                  ) -> Tuple[List[jax.Array], List[Tuple[T.DataType, int]]]:
    """Flatten a batch's column arrays (see flatten_columns). Shared by
    compaction and the split/serialize kernels."""
    return flatten_columns(batch.columns)


def rebuild_columns(spec: Sequence[Tuple[T.DataType, int]],
                    outs: Sequence[jax.Array]) -> List[AnyDeviceColumn]:
    cols: List[AnyDeviceColumn] = []
    i = 0
    for dt, n_arr in spec:
        cols.append(make_column(dt, outs[i:i + n_arr]))
        i += n_arr
    return cols


def compact(batch: DeviceBatch) -> DeviceBatch:
    """Move active rows to the front (fixed-shape compaction)."""
    flat, spec = flatten_batch(batch)
    new_active, outs = _compact_arrays(batch.active, *flat)
    cols = rebuild_columns(spec, outs)
    return DeviceBatch(batch.schema, cols, new_active, batch._num_rows)


_SHRINK_CACHE = JitCache("shrink")


def _shrink_impl(batch: DeviceBatch, n: int, compact_first: bool
                 ) -> DeviceBatch:
    """Slice down to n's capacity bucket as ONE jitted program per
    (shape-set, target capacity, compact?), compacting first unless the
    caller guarantees active rows already form a prefix."""
    cap = bucket_capacity(max(1, n))
    if cap >= batch.capacity:
        return compact(batch) if compact_first else batch
    flat, spec = flatten_batch(batch)
    key = (tuple((a.shape, str(a.dtype)) for a in flat), cap,
           compact_first)
    fn = _SHRINK_CACHE.get(key)
    if fn is None:
        def _fn(active, *arrs):
            if compact_first:
                active, arrs = _compact_body(active, arrs)
            return active[:cap], tuple(
                (a[:cap] if a.ndim == 1 else a[:cap, :]) for a in arrs)
        fn = _SHRINK_CACHE.put(key, jax.jit(_fn))
    new_active, outs = fn(batch.active, *flat)
    return DeviceBatch(batch.schema, rebuild_columns(spec, outs),
                       new_active, n)


def shrink_to_bucket(batch: DeviceBatch) -> DeviceBatch:
    """Compact, then if the active count fits a smaller capacity bucket,
    slice down to it (keeps shuffle payloads tight)."""
    n = batch.row_count()  # the one necessary host sync (sizes the bucket)
    return _shrink_impl(batch, n, compact_first=True)


def slice_compacted_to_bucket(batch: DeviceBatch) -> DeviceBatch:
    """Slice an ALREADY-COMPACTED batch (active rows form a prefix,
    ``_num_rows`` known) down to its capacity bucket — a pure static
    slice, no sort and no host sync (unlike shrink_to_bucket)."""
    n = batch.row_count()  # cached: caller set _num_rows
    return _shrink_impl(batch, n, compact_first=False)
