"""Spark-compatible Murmur3 (x86_32) hashing, vectorized in numpy.

The reference relies on cuDF's spark-murmur3 mode so that GPU hash
partitioning places rows in the same shuffle partitions CPU Spark would
(GpuHashPartitioning.scala; SURVEY.md 2.5 'murmur3-compatible GPU hash').
This module is the host/reference implementation; the device twin (jnp) is
columnar/kernels/hashing.py and must match bit-for-bit.

Algorithm: Spark's Murmur3_x86_32 (hashInt/hashLong/hashUnsafeBytes with
trailing bytes processed one-at-a-time as signed ints), seed 42, columns
folded left-to-right with the running hash as seed; null slots leave the
running hash unchanged.
"""

from __future__ import annotations

import numpy as np

DEFAULT_SEED = np.int32(42)

_C1 = np.uint32(0xCC9E2D51)
_C2 = np.uint32(0x1B873593)
_M5 = np.uint32(0xE6546B64)


def _rotl(x: np.ndarray, r: int) -> np.ndarray:
    x = x.astype(np.uint32)
    return ((x << np.uint32(r)) | (x >> np.uint32(32 - r))).astype(np.uint32)


def _mix_k1(k1: np.ndarray) -> np.ndarray:
    k1 = (k1.astype(np.uint32) * _C1).astype(np.uint32)
    k1 = _rotl(k1, 15)
    return (k1 * _C2).astype(np.uint32)


def _mix_h1(h1: np.ndarray, k1: np.ndarray) -> np.ndarray:
    h1 = (h1.astype(np.uint32) ^ k1).astype(np.uint32)
    h1 = _rotl(h1, 13)
    return (h1 * np.uint32(5) + _M5).astype(np.uint32)


def _fmix(h1: np.ndarray, length: np.ndarray) -> np.ndarray:
    h1 = (h1.astype(np.uint32) ^ np.asarray(length).astype(np.uint32))
    h1 = h1 ^ (h1 >> np.uint32(16))
    h1 = (h1 * np.uint32(0x85EBCA6B)).astype(np.uint32)
    h1 = h1 ^ (h1 >> np.uint32(13))
    h1 = (h1 * np.uint32(0xC2B2AE35)).astype(np.uint32)
    h1 = h1 ^ (h1 >> np.uint32(16))
    return h1


def hash_int(values: np.ndarray, seed: np.ndarray) -> np.ndarray:
    """hashInt: one 4-byte round + fmix(4). values int32, seed int32/uint32
    array or scalar; returns int32."""
    k1 = _mix_k1(values.astype(np.int32).view(np.uint32))
    h1 = _mix_h1(np.asarray(seed, dtype=np.int32).view(np.uint32)
                 * np.ones(len(values), dtype=np.uint32), k1)
    return _fmix(h1, np.uint32(4)).view(np.int32)


def hash_long(values: np.ndarray, seed: np.ndarray) -> np.ndarray:
    """hashLong: low int32 word then high, + fmix(8)."""
    v = values.astype(np.int64).view(np.uint64)
    low = (v & np.uint64(0xFFFFFFFF)).astype(np.uint32)
    high = (v >> np.uint64(32)).astype(np.uint32)
    h1 = np.asarray(seed, dtype=np.int32).view(np.uint32) \
        * np.ones(len(values), dtype=np.uint32)
    h1 = _mix_h1(h1, _mix_k1(low))
    h1 = _mix_h1(h1, _mix_k1(high))
    return _fmix(h1, np.uint32(8)).view(np.int32)


def hash_bytes_one(data: bytes, seed: int) -> int:
    """Scalar hashUnsafeBytes for strings/binary (per-row host loop).
    1-element arrays throughout: integer wraparound is intended and
    numpy only warns on scalar overflow."""
    h1 = np.array([seed], dtype=np.int32).view(np.uint32)
    n = len(data)
    aligned = n - n % 4
    for i in range(0, aligned, 4):
        word = np.frombuffer(data[i:i + 4], dtype="<u4").copy()
        h1 = _mix_h1(h1, _mix_k1(word))
    for i in range(aligned, n):
        b = (np.array([data[i]], dtype=np.uint8).astype(np.int8)
             .astype(np.int32).view(np.uint32))
        h1 = _mix_h1(h1, _mix_k1(b))
    res = _fmix(h1, np.uint32(n))
    return int(res.view(np.int32)[0])


def hash_float(values: np.ndarray, seed) -> np.ndarray:
    """Float: -0.0 normalized to 0.0, then bits hashed as int32
    (Spark Murmur3Hash HashExpression for FloatType)."""
    v = values.astype(np.float32).copy()
    v[v == np.float32(0.0)] = np.float32(0.0)  # folds -0.0 into +0.0
    return hash_int(v.view(np.int32), seed)


def hash_double(values: np.ndarray, seed) -> np.ndarray:
    v = values.astype(np.float64).copy()
    v[v == 0.0] = 0.0
    return hash_long(v.view(np.int64), seed)
