"""Packed host->device transfer codec (the bytes-on-the-wire discipline).

Measured on the tunneled TPU backend (round-3 probe): H2D moves at
~45MB/s for a list of buffers, ~64MB/s for one int64 buffer, but
~160MB/s for one int32 buffer — a fixed per-buffer cost plus a strong
container-dtype effect; the tunnel does not compress. So the upload path

  (a) narrows integer columns to the smallest int dtype that holds their
      value range (Parquet-style bit-width reduction), shipping each as
      its own buffer — the decode is then a pure elementwise astype.
      (Weaving them into the staging words would decode via (n,2)
      reshapes, whose TPU tiling pads the minor dim 2 -> 128: a 64x HBM
      blowup that OOMs wide batches.)
  (b) bit-packs booleans and validity masks into the int32 staging
      words (skipping all-valid masks entirely), alongside the string
      byte matrices,
  (c) ships only the real rows (no capacity padding on the wire), and
  (d) moves the staging words + raw buffers in ONE device_put, with a
      single jitted program rebuilding full-width, capacity-padded
      columns in HBM.

The reference's scan path uses the same idea at the file level: copy the
compact encoded bytes to the device once, decode there
(GpuParquetScanBase.scala:82 row-group copy + cudf decode). Here it is
applied to every row->columnar upload.

float64 columns bypass the packed buffer (their reconstruction would
need a 64-bit float bitcast, which this TPU lowering stack rejects) and
ride the same device_put as extra raw buffers.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Callable, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from spark_rapids_tpu.sql import types as T

# layout entry kinds
_INT_KINDS = ("i8", "i16", "i32", "i64")


def _narrow_kind(mn: int, mx: int) -> str:
    if -128 <= mn and mx <= 127:
        return "i8"
    if -32768 <= mn and mx <= 32767:
        return "i16"
    if -(1 << 31) <= mn and mx <= (1 << 31) - 1:
        return "i32"
    return "i64"


_KIND_WIDTH = {"i8": 1, "i16": 2, "i32": 4, "i64": 8}


class _Packer:
    """Accumulates 4-byte-aligned byte regions into one staging buffer."""

    def __init__(self):
        self.parts: List[np.ndarray] = []
        self.off = 0

    def add(self, arr: np.ndarray) -> int:
        b = np.ascontiguousarray(arr).view(np.uint8).reshape(-1)
        start = self.off
        self.parts.append(b)
        self.off += b.nbytes
        pad = (-self.off) % 4
        if pad:
            self.parts.append(np.zeros(pad, np.uint8))
            self.off += pad
        return start

    def words(self) -> np.ndarray:
        if not self.parts:
            return np.zeros(1, dtype=np.int32)
        return np.concatenate(self.parts).view(np.int32)


def _encode_strings(data: np.ndarray, validity: np.ndarray, n: int,
                    is_binary: bool) -> Tuple[np.ndarray, np.ndarray]:
    """Object array of str/bytes -> (uint8[n, char_cap], int32 lengths).
    ASCII string columns take a vectorized numpy path (codepoints via a
    U-dtype view); anything else falls back to per-row encoding."""
    from spark_rapids_tpu.columnar.device import bucket_char_cap
    if n == 0:
        return np.zeros((0, 8), np.uint8), np.zeros(0, np.int32)
    if not is_binary:
        try:
            u = data.astype(np.str_)
        except (TypeError, ValueError):
            u = None
        if u is not None and u.dtype.itemsize == 0:
            return np.zeros((n, 8), np.uint8), np.zeros(n, np.int32)
        if u is not None:
            k = u.dtype.itemsize // 4
            u32 = np.ascontiguousarray(u).view(np.uint32).reshape(n, k)
            if (u32 < 128).all():
                # pure-ASCII fast path: UTF-32 codepoints ARE the bytes
                lengths = np.char.str_len(u).astype(np.int32)
                char_cap = bucket_char_cap(int(lengths.max(initial=1)))
                chars = np.zeros((n, char_cap), np.uint8)
                w = min(k, char_cap)
                chars[:, :w] = u32[:, :w].astype(np.uint8)
                lengths = np.where(validity, lengths, 0)
                chars[~validity] = 0
                return chars, lengths
    encoded: List[bytes] = []
    max_len = 1
    for i in range(n):
        if validity[i]:
            v = data[i]
            b = v.encode("utf-8") if isinstance(v, str) else bytes(v)
        else:
            b = b""
        encoded.append(b)
        max_len = max(max_len, len(b))
    char_cap = bucket_char_cap(max_len)
    chars = np.zeros((n, char_cap), np.uint8)
    lengths = np.zeros(n, np.int32)
    for i, b in enumerate(encoded):
        chars[i, :len(b)] = np.frombuffer(b, dtype=np.uint8)
        lengths[i] = len(b)
    return chars, lengths


def pack_batch(batch) -> Tuple[np.ndarray, List[np.ndarray], Tuple]:
    """Stage a HostBatch: returns (int32 staging words, extra raw buffers,
    static layout descriptor). Layout is hashable and, with (n, cap),
    fully determines the decode program."""
    from spark_rapids_tpu.columnar.device import is_string_like
    n = batch.num_rows
    pk = _Packer()
    extras: List[np.ndarray] = []
    layout: List[Tuple] = []
    for f, c in zip(batch.schema.fields, batch.columns):
        dt = f.data_type
        validity = np.ascontiguousarray(c.validity[:n])
        if validity.all():
            vdesc: Tuple = ("av",)
        else:
            vdesc = ("vb", pk.add(np.packbits(validity, bitorder="little")))
        if is_string_like(dt):
            vb = getattr(c, "varbytes", None)
            if vb is not None and len(vb[1]) == n and len(vb[0]) > 0:
                # compact Arrow bytes ride the wire as-is; the decode
                # program rebuilds the padded char matrix on device
                # (cumsum starts + gather) — no host re-encode, no
                # char_cap padding on the wire. The byte payload is
                # padded to a bucketed size so the layout tuple (and
                # with it every later column's c_off) repeats across
                # batches — an exact len(bts) would compile a fresh
                # decode program per batch.
                from spark_rapids_tpu.columnar.device import (
                    bucket_capacity, bucket_char_cap)
                bts, raw_lengths = vb
                masked_max = int(raw_lengths[validity].max()) \
                    if validity.any() else 1
                char_cap = bucket_char_cap(max(1, masked_max))
                nb = bucket_capacity(len(bts))
                if nb > len(bts):
                    bts = np.concatenate(
                        [bts, np.zeros(nb - len(bts), np.uint8)])
                c_off = pk.add(bts)
                raw_max = int(raw_lengths.max(initial=0))
                lk = ("i8" if raw_max <= 127 else
                      "i16" if raw_max <= 32767 else "i32")
                l_idx = len(extras)
                extras.append(raw_lengths.astype(
                    {"i8": np.int8, "i16": np.int16,
                     "i32": np.int32}[lk]))
                layout.append(("vstr", char_cap, c_off, nb,
                               lk, l_idx, vdesc))
                continue
            chars, lengths = _encode_strings(
                c.data, validity, n, isinstance(dt, T.BinaryType))
            # invalid slots already zeroed by _encode_strings
            char_cap = chars.shape[1] if n else 8
            c_off = pk.add(chars)
            lk = ("i8" if char_cap <= 127 else
                  "i16" if char_cap <= 32767 else "i32")
            l_idx = len(extras)
            extras.append(lengths.astype(
                {"i8": np.int8, "i16": np.int16, "i32": np.int32}[lk]))
            layout.append(("str", char_cap, c_off, lk, l_idx, vdesc))
            continue
        if T.is_limb_decimal(dt):
            limbs = c.data[:n]
            if not validity.all():
                limbs = limbs.copy()
                limbs[~validity] = 0
            ent = ["dec128"]
            for li in range(2):  # hi then lo, each narrowed like an int
                ld = np.ascontiguousarray(limbs[:, li])
                mn, mx = (int(ld.min()), int(ld.max())) if n else (0, 0)
                kind = _narrow_kind(mn, mx)
                ent.append(len(extras))
                extras.append(ld.astype(
                    np.dtype(kind.replace("i", "int"))))
            ent.append(vdesc)
            layout.append(tuple(ent))
            continue
        np_dt = T.numpy_dtype(dt)
        data = np.ascontiguousarray(c.data[:n])
        if not validity.all():
            # normalized zeros at invalid slots (narrowing + determinism)
            data = data.copy()
            data[~validity] = (False if np_dt == np.dtype(bool) else
                               np_dt.type(0))
        if np_dt == np.dtype(bool):
            layout.append(("bool", pk.add(np.packbits(
                data.astype(bool), bitorder="little")), vdesc))
        elif np_dt == np.dtype(np.float64):
            layout.append(("f64", len(extras), vdesc))
            # asarray: already-f64 contiguous data ships without a copy
            extras.append(np.asarray(data, np.float64))
        elif np_dt == np.dtype(np.float32):
            layout.append(("f32", pk.add(np.asarray(data, np.float32)),
                           vdesc))
        else:
            if n:
                mn, mx = int(data.min()), int(data.max())
            else:
                mn = mx = 0
            kind = _narrow_kind(mn, mx)
            # don't widen on the wire (e.g. int8 storage stays int8)
            kind = kind if _KIND_WIDTH[kind] <= np_dt.itemsize else \
                {1: "i8", 2: "i16", 4: "i32", 8: "i64"}[np_dt.itemsize]
            narrow = data.astype(np.dtype(kind.replace("i", "int")))
            # narrowed ints ride as their OWN buffers: widening back is
            # a pure elementwise astype. Weaving them through the int32
            # staging words would decode via (n,2)-shaped reshapes whose
            # TPU tiling pads the minor dim 2 -> 128 (a 64x HBM blowup
            # that OOMs multi-column batches).
            layout.append(("int", str(np_dt), len(extras), vdesc))
            extras.append(narrow)
    return pk.words(), extras, tuple(layout)


# -- device-side decode ----------------------------------------------------

# Bounded LRU: every distinct (layout, n, cap, nbytes) compiles its own
# decode program; long sessions with varying batch sizes must not retain
# them all.
from spark_rapids_tpu.jit_cache import JitCache

_DECODE_CACHE = JitCache("uploadDecode", capacity=64)


def _pad_cap(x: jax.Array, n: int, cap: int) -> jax.Array:
    if cap == n:
        return x
    pad = [(0, cap - n)] + [(0, 0)] * (x.ndim - 1)
    return jnp.pad(x, pad)


def _build_decode(layout: Tuple, n: int, cap: int) -> Callable:
    """One XLA program: staging words -> per-column (data, validity)
    arrays at full capacity, plus the active mask."""

    def fn(words, *extras):
        bytes_all = None

        def get_bytes():
            nonlocal bytes_all
            if bytes_all is None:
                shifts = jnp.arange(4, dtype=jnp.int32) * 8
                bytes_all = ((words[:, None] >> shifts) & 0xFF).reshape(-1)
            return bytes_all

        def decode_bits(off: int, count: int) -> jax.Array:
            nbytes = (count + 7) // 8
            b = jax.lax.slice(get_bytes(), (off,), (off + nbytes,))
            bits = ((b[:, None] >> jnp.arange(8, dtype=jnp.int32)) & 1)
            return bits.reshape(-1)[:count].astype(jnp.bool_)

        active = jnp.arange(cap) < n
        outs: List[jax.Array] = []
        for ent in layout:
            vdesc = ent[-1]
            if vdesc[0] == "av":
                validity = active
            else:
                validity = _pad_cap(decode_bits(vdesc[1], n), n, cap)
            kind = ent[0]
            if kind == "vstr":
                # compact bytes -> (cap, char_cap) matrix on device:
                # starts are the cumsum of the raw lengths, each row
                # gathers its window, nulls/tails mask to 0
                _, char_cap, c_off, nbytes, _lk, l_idx, _v = ent
                raw_len = extras[l_idx].astype(jnp.int32)
                starts = jnp.cumsum(raw_len) - raw_len
                src = jax.lax.slice(get_bytes(), (c_off,),
                                    (c_off + max(1, nbytes),))
                idx = starts[:, None] + jnp.arange(char_cap,
                                                   dtype=jnp.int32)
                out_len = jnp.where(validity[:n], raw_len, 0)
                mask = jnp.arange(char_cap, dtype=jnp.int32) \
                    < out_len[:, None]
                gathered = src[jnp.clip(idx, 0, max(0, nbytes - 1))]
                chars = jnp.where(mask, gathered, 0).astype(jnp.uint8)
                outs.extend([_pad_cap(chars, n, cap),
                             _pad_cap(out_len, n, cap), validity])
            elif kind == "str":
                _, char_cap, c_off, lk, l_idx, _ = ent
                chars = _pad_cap(
                    jax.lax.slice(get_bytes(), (c_off,),
                                  (c_off + n * char_cap,))
                    .reshape(n, char_cap).astype(jnp.uint8), n, cap)
                lengths = _pad_cap(
                    extras[l_idx].astype(jnp.int32), n, cap)
                outs.extend([chars, lengths, validity])
            elif kind == "dec128":
                _, i_hi, i_lo, _v = ent
                hi = extras[i_hi].astype(jnp.int64)
                lo = extras[i_lo].astype(jnp.int64)
                outs.extend([_pad_cap(hi, n, cap), _pad_cap(lo, n, cap),
                             validity])
            elif kind == "bool":
                outs.extend([_pad_cap(decode_bits(ent[1], n), n, cap),
                             validity])
            elif kind == "f64":
                outs.extend([_pad_cap(extras[ent[1]], n, cap), validity])
            elif kind == "f32":
                w = ent[1] // 4
                raw = jax.lax.slice(words, (w,), (w + n,))
                outs.extend([_pad_cap(jax.lax.bitcast_convert_type(
                    raw, jnp.float32), n, cap), validity])
            else:  # "int": own narrowed buffer, widen elementwise
                _, np_dt, idx, _v = ent
                data = extras[idx].astype(jnp.dtype(np_dt))
                outs.extend([_pad_cap(data, n, cap), validity])
        return active, tuple(outs)

    return jax.jit(fn)


# Below this row count the packed codec's per-(layout, n, cap) decode
# compile outweighs the wire savings; small batches ride a plain padded
# device_put (no program at all).
PACKED_MIN_ROWS = 1 << 16


def _col_from_storage_values(vals, dt: T.DataType):
    """Storage-form python values (None = null) -> HostColumn, without
    the from_pylist value conversion (dates/decimals already sit in
    storage ints inside struct tuples)."""
    from spark_rapids_tpu.columnar.host import HostColumn
    n = len(vals)
    validity = np.array([v is not None for v in vals], dtype=bool)
    if T.is_limb_decimal(dt):
        from spark_rapids_tpu.ops import int128 as I
        hi, lo = I.from_pyints([0 if v is None else int(v) for v in vals])
        return HostColumn(dt, np.stack([hi, lo], axis=1), validity)
    np_dt = T.numpy_dtype(dt)
    if np_dt == np.dtype(object):
        data = np.empty(n, dtype=object)
        for i, v in enumerate(vals):
            data[i] = v if v is not None else ""
        return HostColumn(dt, data, validity)
    fill = False if np_dt == np.dtype(bool) else np_dt.type(0)
    data = np.array([fill if v is None else v for v in vals],
                    dtype=np_dt)
    return HostColumn(dt, data, validity)


def _stage_column(c, dt: T.DataType, cap: int) -> List[np.ndarray]:
    """Full-width staging buffers for one column, matching the device
    column's arrays() layout; recurses into array element pools."""
    from spark_rapids_tpu.columnar import device as D
    from spark_rapids_tpu.columnar.host import HostColumn
    n = len(c)
    validity = np.zeros(cap, dtype=bool)
    validity[:n] = c.validity
    if isinstance(dt, T.ArrayType):
        starts = np.zeros(cap, dtype=np.int32)
        lengths = np.zeros(cap, dtype=np.int32)
        elems: List = []
        off = 0
        for i in range(n):
            if c.validity[i]:
                row = c.data[i]
                starts[i] = off
                lengths[i] = len(row)
                elems.extend(row)
                off += len(row)
        child_cap = D.bucket_capacity(max(1, off))
        child_col = HostColumn.from_pylist(elems, dt.element_type)
        return [starts, lengths] + \
            _stage_column(child_col, dt.element_type, child_cap) + \
            [validity]
    if isinstance(dt, T.StructType):
        validity = np.zeros(cap, dtype=bool)
        validity[:n] = c.validity
        parts: List[np.ndarray] = []
        from spark_rapids_tpu.columnar.host import struct_field_values
        for fi, f in enumerate(dt.fields):
            # field values are ALREADY storage-form (struct tuples hold
            # storage ints); build the host column without re-converting
            parts.extend(_stage_column(
                _col_from_storage_values(
                    struct_field_values(c, fi)[:n], f.data_type),
                f.data_type, cap))
        return parts + [validity]
    if D.is_string_like(dt):
        ch, ln = _encode_strings(c.data, c.validity, n,
                                 isinstance(dt, T.BinaryType))
        char_cap = ch.shape[1] if n else 8
        chars = np.zeros((cap, char_cap), dtype=np.uint8)
        chars[:n] = ch
        lengths = np.zeros(cap, dtype=np.int32)
        lengths[:n] = ln
        return [chars, lengths, validity]
    if T.is_limb_decimal(dt):
        limbs = np.zeros((cap, 2), dtype=np.int64)
        limbs[:n] = c.normalized().data
        return [np.ascontiguousarray(limbs[:, 0]),
                np.ascontiguousarray(limbs[:, 1]), validity]
    np_dt = T.numpy_dtype(dt)
    data = np.zeros(cap, dtype=np_dt)
    data[:n] = c.normalized().data
    return [data, validity]


def _stage_direct(batch, cap: int):
    """Host staging for the small-batch / nested-column path."""
    n = batch.num_rows
    np_arrays: List[np.ndarray] = []
    spec: List[Tuple[T.DataType, int]] = []
    for f, c in zip(batch.schema.fields, batch.columns):
        parts = _stage_column(c, f.data_type, cap)
        spec.append((f.data_type, len(parts)))
        np_arrays.extend(parts)
    active_np = np.zeros(cap, dtype=bool)
    active_np[:n] = True
    np_arrays.append(active_np)
    return ("direct", batch.schema, n, spec, np_arrays)


def prepare_upload(batch, cap: int, conf=None, metrics=None):
    """Host-side half of an upload (pack/stage, NO device touch): the
    returned opaque token feeds finish_upload. Splitting the phases lets
    a producer thread pack batch k+1 while batch k's bytes move.
    ``conf``/``metrics`` (scan path) gate the fused-decode kernel and
    receive its dispatch/fallback counters; without them the encoded
    path runs the stock XLA chain uncounted."""
    from spark_rapids_tpu.io.device_decode import EncodedBatch
    if isinstance(batch, EncodedBatch):
        return prepare_encoded_upload(batch, cap, conf=conf,
                                      metrics=metrics)
    n = batch.num_rows
    if n < PACKED_MIN_ROWS or any(
            isinstance(f.data_type, (T.ArrayType, T.StructType))
            for f in batch.schema.fields):
        return _stage_direct(batch, cap)
    words, extras, layout = pack_batch(batch)
    return ("packed", batch.schema, n, cap, words, extras, layout)


def finish_upload(staged, device: Optional[jax.Device] = None):
    """Device-side half: one device_put (+ one decode program on the
    packed and encoded paths). Traced per staging mode with the target
    chip, nested inside the R2C transition's copyToDeviceTime span."""
    from spark_rapids_tpu import trace as _trace
    with _trace.span("finishUpload", mode=staged[0],
                     chip=(device.id if device is not None else None)):
        return finish_started(start_upload(staged, device))


def start_upload(staged, device: Optional[jax.Device] = None):
    """Issue a staged token's host->device copies ASYNCHRONOUSLY (jax
    device_put returns once the transfers are enqueued) and return an
    upload token for :func:`finish_started`. The split is the scan
    pipeline's upload-ahead hook (docs/scan.md): batch k+1's raw-chunk
    bytes move while batch k's decode program / downstream compute
    runs, bounded by deviceDecode.maxInFlight tokens in flight."""
    def put(bufs):
        return (jax.device_put(bufs, device) if device is not None
                else jax.device_put(bufs))

    if staged[0] == "direct":
        _tag, schema, n, spec, np_arrays = staged
        return ("direct", schema, n, spec, put(np_arrays))
    if staged[0] == "encoded":
        (_tag, schema, n, cap, words, extras, layout, spec,
         fuse) = staged
        dev = put([words, np.asarray(n, dtype=np.int64)] + list(extras))
        return ("encoded", schema, n, cap, words.nbytes, layout, spec,
                dev, fuse)
    _tag, schema, n, cap, words, extras, layout = staged
    return ("packed", schema, n, cap, words.nbytes, layout,
            put([words] + extras))


def finish_started(token):
    """Complete a :func:`start_upload` token: run the decode program
    (packed/encoded paths) and assemble the DeviceBatch. Safe to
    re-invoke after an OOM retry — the device buffers are still
    resident, only the program dispatch repeats."""
    from spark_rapids_tpu.columnar import device as D
    if token[0] == "direct":
        _tag, schema, n, spec, dev = token
        return D.DeviceBatch(schema, D.rebuild_columns(spec, dev[:-1]),
                             dev[-1], n)
    if token[0] == "encoded":
        return _finish_encoded_upload(token)
    _tag, schema, n, cap, nbytes, layout, dev = token
    key = (layout, n, cap, nbytes)
    fn = _DECODE_CACHE.get(key)
    if fn is None:
        fn = _DECODE_CACHE.put(key, _build_decode(layout, n, cap))
    active, outs = fn(dev[0], *dev[1:])
    spec = [(f.data_type,
             3 if (D.is_string_like(f.data_type)
                   or T.is_limb_decimal(f.data_type)) else 2)
            for f in schema.fields]
    return D.DeviceBatch(schema, D.rebuild_columns(spec, outs),
                         active, n)


def upload_batch(batch, cap: int, device: Optional[jax.Device] = None):
    """HostBatch -> DeviceBatch via the packed codec (one device_put,
    one decode program); small batches skip the codec."""
    return finish_upload(prepare_upload(batch, cap), device)


# -- device parquet decode (EncodedBatch path) -----------------------------
#
# The scan's raw-page staging: the wire carries the *still-encoded*
# page bytes (dict indices at their bit width, packed validity runs,
# PLAIN fixed-width bytes) plus small host-parsed plan tables; one XLA
# program per (layout, n, cap) expands everything into device columns
# (the reference's copy-compact-bytes-then-cudf-decode shape,
# GpuParquetScanBase.scala:82, applied to the scan itself).

def _pad_pow2(n: int, floor: int = 8) -> int:
    if n <= floor:
        return floor
    return 1 << (n - 1).bit_length()


def prepare_encoded_upload(enc, cap: int, conf=None, metrics=None):
    """EncodedBatch -> staged token: pads plan tables to pow2 buckets so
    the decode-program cache keys repeat across row groups (the row
    count itself rides as a device scalar, so row groups of any size
    share one program per layout/capacity bucket)."""
    n = enc.num_rows
    extras: List[np.ndarray] = []
    layout: List[Tuple] = []
    spec: List[Tuple[T.DataType, int]] = []
    for fi, f in enumerate(enc.schema.fields):
        dt = f.data_type
        plan = enc.plans.get(fi)
        if plan is None:
            parts = _stage_column(enc.host_cols[fi], dt, cap)
            layout.append(("host", len(parts)))
            spec.append((dt, len(parts)))
            extras.extend(parts)
            continue
        n_pages = len(plan.pg_enc)
        npg = _pad_pow2(n_pages)
        dense_start = np.full(npg + 1, 1 << 62, dtype=np.int64)
        dense_start[:n_pages + 1] = plan.pg_dense_start
        plain_byte = np.zeros(npg, dtype=np.int64)
        plain_byte[:n_pages] = plan.pg_plain_byte
        pg_enc = np.zeros(npg, dtype=np.int32)
        pg_enc[:n_pages] = plan.pg_enc
        extras.extend([dense_start, plain_byte, pg_enc])
        if plan.has_delta:
            pg_first = np.zeros(npg, dtype=np.int64)
            pg_first[:n_pages] = plan.pg_first
            extras.append(pg_first)
        ndl = _pad_pow2(len(plan.dl)) if plan.dl is not None else 0
        if plan.dl is not None:
            extras.extend(plan.dl.arrays(ndl))
        nvr = _pad_pow2(len(plan.vr)) if plan.vr is not None else 0
        if plan.vr is not None:
            extras.extend(plan.vr.arrays(nvr))
        ndr = _pad_pow2(len(plan.dr)) if plan.dr is not None else 0
        if plan.dr is not None:
            extras.extend(plan.dr.arrays(ndr))
        has_slen = plan.str_lens is not None
        if has_slen:
            slen = np.zeros(cap, dtype=np.int32)
            slen[:plan.str_lens.shape[0]] = plan.str_lens
            extras.append(slen)
        dict_shapes: List[Tuple] = []
        for da in plan.dict_arrays:
            pad = _pad_pow2(da.shape[0], floor=1)
            if pad > da.shape[0]:
                padded = np.zeros((pad,) + da.shape[1:], dtype=da.dtype)
                padded[:da.shape[0]] = da
                da = padded
            dict_shapes.append((da.shape, str(da.dtype)))
            extras.append(da)
        layout.append(("dev", plan.kind, plan.np_dtype, plan.elem_bytes,
                       plan.char_cap, npg, ndl, nvr, ndr,
                       tuple(dict_shapes), plan.has_plain,
                       plan.has_delta, plan.has_bss, has_slen))
        arity = 3 if plan.kind in ("str", "dec128") else 2
        spec.append((dt, arity))
    # bucket the page buffer so same-shaped row groups share one
    # decode program (exact sizes would compile per unit)
    from spark_rapids_tpu.columnar.device import bucket_capacity
    words = enc.words
    nw = bucket_capacity(len(words))
    if nw > len(words):
        words = np.concatenate([words,
                                np.zeros(nw - len(words), np.int32)])
    # fuse context: resolved HERE (the host-side half, where the conf
    # lives) so the device-side finish never touches conf objects; the
    # params come from the autotuner's warm table (defaults untuned)
    fuse = None
    if conf is not None or metrics is not None:
        fuse = {"enabled": False, "metrics": metrics, "params": {},
                "tuned": False}
        from spark_rapids_tpu import kernels as KR
        if conf is not None and KR.kernel_enabled(conf, "decodeFused"):
            from spark_rapids_tpu.kernels import autotune as AT
            params, tuned = AT.params_for(conf, "decodeFused", cap)
            fuse.update(enabled=True, params=params, tuned=tuned)
    return ("encoded", enc.schema, n, cap, words, extras,
            tuple(layout), tuple(spec), fuse)


def _encoded_decode_body(layout: Tuple, cap: int, words, n_arr, extras,
                         char_chunk: int = 0):
    """The encoded-decode arithmetic, shared verbatim by the XLA chain
    (``_build_encoded_decode`` jits it directly) and the fused Pallas
    kernel (``kernels/decode_fused.py`` executes it inside one
    ``pallas_call``) — bit-identity between the two paths is
    structural, not tested-into (the murmur3 kernel's model).
    ``char_chunk`` bounds the string char-gather's live index matrix
    (autotunable; 0 = unchunked) without changing a byte."""
    from spark_rapids_tpu.io.device_decode import (PGE_BSS, PGE_DELTA,
                                                   PGE_DICT, PGE_DL_STR,
                                                   PGE_PLAIN_STR)
    from spark_rapids_tpu.ops import rle as R
    bytes_all = None

    def get_bytes():
        nonlocal bytes_all
        if bytes_all is None:
            bytes_all = R.bytes_of_words(words)
        return bytes_all

    active = jnp.arange(cap) < n_arr
    pos = jnp.arange(cap, dtype=jnp.int64)
    outs: List[jax.Array] = []
    cur = 0
    for ent in layout:
        if ent[0] == "host":
            _tag, n_parts = ent
            outs.extend(extras[cur:cur + n_parts])
            cur += n_parts
            continue
        (_tag, kind, np_dt, elem_bytes, char_cap, npg, ndl, nvr,
         ndr, dict_shapes, has_plain, has_delta, has_bss,
         has_slen) = ent
        dense_start = extras[cur]
        plain_byte = extras[cur + 1]
        pg_enc = extras[cur + 2]
        cur += 3
        pg_first = None
        if has_delta:
            pg_first = extras[cur]
            cur += 1
        if ndl:
            dl = extras[cur:cur + 5]
            cur += 5
            dl_v = R.hybrid_lookup(get_bytes(), pos, *dl)
            validity = (dl_v == 1) & active
        else:
            validity = active
        vr = None
        if nvr:
            vr = extras[cur:cur + 5]
            cur += 5
        dr = None
        if ndr:
            dr = extras[cur:cur + 5]
            cur += 5
        slen = None
        if has_slen:
            slen = extras[cur]
            cur += 1
        dicts = [extras[cur + i] for i in range(len(dict_shapes))]
        cur += len(dict_shapes)

        j = jnp.clip(R.dense_ranks(validity), 0, cap - 1) \
            .astype(jnp.int64)
        if kind == "bool":
            v = R.hybrid_lookup(get_bytes(), j, *vr)
            data = jnp.where(validity, v != 0, False)
            outs.extend([data, validity])
            continue
        pg = jnp.clip(
            jnp.searchsorted(dense_start, j, side="right") - 1,
            0, npg - 1)
        local = j - dense_start[pg]
        enc_pg = pg_enc[pg]
        didx = None
        if vr is not None and dict_shapes:
            didx = jnp.clip(R.hybrid_lookup(get_bytes(), j, *vr),
                            0, dict_shapes[0][0][0] - 1)
        if kind == "str":
            if has_slen:
                # offset+bytes model (SURVEY.md §7 c), computed in
                # DENSE coordinates (pos) — each stored value's
                # footprint counts exactly once even when null rows
                # repeat a dense index through j: offsets are a
                # per-page segmented prefix-sum over the byte
                # footprints (PLAIN values add their 4-byte length
                # prefix), then one gather builds the char matrix
                pgd = jnp.clip(
                    jnp.searchsorted(dense_start, pos,
                                     side="right") - 1, 0, npg - 1)
                encd = pg_enc[pgd]
                sl_d = slen.astype(jnp.int64)
                lp_d = jnp.where(encd == PGE_PLAIN_STR, 4, 0) \
                    .astype(jnp.int64)
                is_str_d = (encd == PGE_PLAIN_STR) \
                    | (encd == PGE_DL_STR)
                contrib = jnp.where(is_str_d, sl_d + lp_d, 0)
                based = jnp.clip(dense_start[pgd], 0, cap - 1)
                rel_d = R.seg_excl_cumsum(contrib, based)
                start_d = plain_byte[pgd] + rel_d + lp_d
                jj = jnp.clip(j, 0, cap - 1)
                pchars = R.gather_chars_chunked(get_bytes(), start_d[jj],
                                                sl_d[jj].astype(jnp.int32),
                                                char_cap, char_chunk)
                plens = sl_d[jj].astype(jnp.int32)
            else:
                pchars = jnp.zeros((cap, char_cap), dtype=jnp.uint8)
                plens = jnp.zeros(cap, dtype=jnp.int32)
            if didx is not None:
                is_dict_pg = enc_pg == PGE_DICT
                chars = jnp.where(is_dict_pg[:, None],
                                  dicts[0][didx], pchars)
                lengths = jnp.where(is_dict_pg,
                                    dicts[1][didx].astype(jnp.int32),
                                    plens)
            else:
                chars, lengths = pchars, plens
            chars = jnp.where(validity[:, None], chars, 0)
            lengths = jnp.where(validity, lengths, 0)
            outs.extend([chars, lengths, validity])
            continue
        if kind == "dec128":
            if has_plain:
                off = plain_byte[pg] + local * elem_bytes
                p_hi, p_lo = R.read_be_limbs(get_bytes(), off,
                                             elem_bytes)
            else:
                p_hi = p_lo = jnp.zeros(cap, dtype=jnp.int64)
            if didx is not None:
                is_dict_pg = enc_pg == PGE_DICT
                hi = jnp.where(is_dict_pg, dicts[0][didx], p_hi)
                lo = jnp.where(is_dict_pg, dicts[1][didx], p_lo)
            else:
                hi, lo = p_hi, p_lo
            hi = jnp.where(validity, hi, 0)
            lo = jnp.where(validity, lo, 0)
            outs.extend([hi, lo, validity])
            continue
        # fixed-width scalar kinds: select in the int64 bit domain
        if has_plain:
            off = plain_byte[pg] + local * elem_bytes
            if kind == "dec64":
                v = R.read_be_signed(get_bytes(), off, elem_bytes)
            else:
                v = R.read_le(get_bytes(), off, elem_bytes)
        else:
            v = jnp.zeros(cap, dtype=jnp.int64)
        if has_bss:
            # BYTE_STREAM_SPLIT: byte j of value i lives at
            # page_base + j*values_in_page + i
            stride = jnp.clip(dense_start[pg + 1] - dense_start[pg],
                              0, cap)
            b_v = R.read_bss(get_bytes(), plain_byte[pg], stride,
                             local, elem_bytes)
            v = jnp.where(enc_pg == PGE_BSS, b_v, v)
        if has_delta:
            # DELTA_BINARY_PACKED, in DENSE coordinates (each delta
            # counts once even when null rows repeat a dense index):
            # per-value deltas from the miniblock run table,
            # reconstructed by a per-page segmented prefix-sum off
            # the page's first_value, then gathered per row
            pgd = jnp.clip(
                jnp.searchsorted(dense_start, pos,
                                 side="right") - 1, 0, npg - 1)
            encd = pg_enc[pgd]
            d_raw = R.delta_lookup(get_bytes(), pos, *dr)
            d_contrib = jnp.where(
                (encd == PGE_DELTA) & (pos > dense_start[pgd]),
                d_raw, 0)
            c = jnp.cumsum(d_contrib)
            based = jnp.clip(dense_start[pgd], 0, cap - 1)
            val_d = pg_first[pgd] + (c - c[based])
            d_v = val_d[jnp.clip(j, 0, cap - 1)]
            v = jnp.where(enc_pg == PGE_DELTA, d_v, v)
        if didx is not None:
            v = jnp.where(enc_pg == PGE_DICT, dicts[0][didx], v)
        if kind == "f32":
            data = jax.lax.bitcast_convert_type(
                v.astype(jnp.int32), jnp.float32)
            data = jnp.where(validity, data, jnp.float32(0))
        elif kind == "f64":
            data = jax.lax.bitcast_convert_type(v, jnp.float64)
            data = jnp.where(validity, data, jnp.float64(0))
        else:  # int / dec64: reinterpret low bits into the storage
            data = v.astype(jnp.dtype(np_dt)) if np_dt != "int64" \
                else v
            if np_dt == "int64" and elem_bytes == 4 \
                    and kind != "dec64":
                data = v.astype(jnp.int32).astype(jnp.int64)
            data = jnp.where(validity, data, 0)
        outs.extend([data, validity])
    return active, tuple(outs)


def _build_encoded_decode(layout: Tuple, cap: int) -> Callable:
    """One XLA program: packed page words + plan tables -> per-column
    (data, validity) arrays at full capacity, plus the active mask.
    The page-encoding class array (pg_enc) selects the decode lane per
    page, so dict / PLAIN / DELTA / BYTE_STREAM_SPLIT / string pages
    can mix freely inside one chunk (dictionary overflow)."""

    def fn(words, n_arr, *extras):
        return _encoded_decode_body(layout, cap, words, n_arr, extras)

    return jax.jit(fn)


def _chain_fn(layout, cap: int, nbytes: int):
    # the row count is a DEVICE SCALAR input, not a static shape: row
    # groups of any size share one compiled program per (layout, cap,
    # bucketed-words) key
    key = ("enc", layout, cap, nbytes)
    fn = _DECODE_CACHE.get(key)
    if fn is None:
        fn = _DECODE_CACHE.put(key, _build_encoded_decode(layout, cap))
    return fn


def _finish_encoded_upload(token):
    from spark_rapids_tpu.columnar import device as D
    _tag, schema, n, cap, nbytes, layout, spec, dev, fuse = token
    from spark_rapids_tpu import kernels as KR
    from spark_rapids_tpu.kernels import decode_fused as DF
    metrics = fuse.get("metrics") if fuse else None
    fused = bool(fuse and fuse["enabled"]) \
        and not KR.is_poisoned("decodeFused", (layout, cap))
    active = outs = None
    if fused:
        params = fuse.get("params") or {}
        char_chunk = int(params.get("charChunk", 0))
        key = ("encF", layout, cap, nbytes, char_chunk)
        try:
            KR.check_injected_failure("decodeFused")
            fn = _DECODE_CACHE.get(key)
            if fn is None:
                fn = _DECODE_CACHE.put(key, DF.build_fused_decode(
                    layout, cap, interpret=KR.interpret(),
                    char_chunk=char_chunk))
            KR.count_dispatch(metrics, "decodeFused")
            with KR.dispatch_span("decodeFused", bucket=cap,
                                  tuned=bool(fuse.get("tuned"))):
                active, outs = fn(dev[0], dev[1], *dev[2:])
        except Exception as e:
            if not KR.is_oracle_fallback_error(e):
                raise
            # lowering/compile/dispatch failure: poison this (layout,
            # cap) and decode THIS batch (and every later one of the
            # shape) on the stock XLA chain — bit-identical either way
            KR.poison("decodeFused", (layout, cap))
            KR.count_fallback(metrics, "decodeFused")
            fused = False
            active = outs = None
    if outs is None:
        fn = _chain_fn(layout, cap, nbytes)
        active, outs = fn(dev[0], dev[1], *dev[2:])
    if metrics is not None:
        # programs-per-batch attribution for the fused A/B: the chain
        # bills its static per-layout logical stage count, the fused
        # kernel bills 1 (bench divides by deviceDecodedBatches)
        metrics.create("deviceDecodePrograms").add(
            1 if fused else DF.chain_programs(layout))
    return D.DeviceBatch(schema, D.rebuild_columns(list(spec), outs),
                         active, n)
