"""Spark-compatible XXH64 hashing, vectorized in numpy.

Spark's XxHash64 expression (catalyst XXH64, seed 42L) — the second hash
family the reference accelerates (GpuXxHash64, HashFunctions.scala). The
host implementation here is the oracle; the device twin lives in
ops/hashing.py (xx_* functions) and must match bit-for-bit.

Per-type dispatch mirrors Spark's HashExpression: bool/byte/short/int/
date hash as 4-byte ints, long/timestamp/decimal(<=18) as 8-byte longs,
float/double as their IEEE bits (-0.0 folded to +0.0), strings/binary as
UTF-8 bytes via the full XXH64 byte algorithm (32-byte stripes + tail).
All arithmetic is uint64 with wraparound.
"""

from __future__ import annotations

import numpy as np

DEFAULT_SEED = np.int64(42)

P1 = np.uint64(0x9E3779B185EBCA87)
P2 = np.uint64(0xC2B2AE3D27D4EB4F)
P3 = np.uint64(0x165667B19E3779F9)
P4 = np.uint64(0x85EBCA77C2B2AE63)
P5 = np.uint64(0x27D4EB2F165667C5)


def _rotl(x: np.ndarray, r: int) -> np.ndarray:
    x = x.astype(np.uint64)
    return (x << np.uint64(r)) | (x >> np.uint64(64 - r))


def _fmix(h: np.ndarray) -> np.ndarray:
    h = h ^ (h >> np.uint64(33))
    h = h * P2
    h = h ^ (h >> np.uint64(29))
    h = h * P3
    h = h ^ (h >> np.uint64(32))
    return h


def hash_int(values: np.ndarray, seed: np.ndarray) -> np.ndarray:
    """XXH64.hashInt: value zero-extended to a 4-byte block."""
    v = values.astype(np.int32).view(np.uint32).astype(np.uint64)
    h = seed.astype(np.int64).view(np.uint64) + P5 + np.uint64(4)
    h = h ^ (v * P1)
    h = _rotl(h, 23) * P2 + P3
    return _fmix(h).view(np.int64)


def hash_long(values: np.ndarray, seed: np.ndarray) -> np.ndarray:
    v = values.astype(np.int64).view(np.uint64)
    h = seed.astype(np.int64).view(np.uint64) + P5 + np.uint64(8)
    h = h ^ (_rotl(v * P2, 31) * P1)
    h = _rotl(h, 27) * P1 + P4
    return _fmix(h).view(np.int64)


def hash_float(values: np.ndarray, seed) -> np.ndarray:
    v = values.astype(np.float32).copy()
    v[v == np.float32(0.0)] = np.float32(0.0)  # fold -0.0
    return hash_int(v.view(np.int32), seed)


def hash_double(values: np.ndarray, seed) -> np.ndarray:
    v = values.astype(np.float64).copy()
    v[v == 0.0] = 0.0
    return hash_long(v.view(np.int64), seed)


def hash_bytes_one(data: bytes, seed: int) -> int:
    """Scalar XXH64 over a byte string (per-row host loop). 1-element
    arrays throughout: wraparound is intended, and numpy only warns on
    scalar overflow."""
    def u(x) -> np.ndarray:
        return np.array([x], dtype=np.uint64)

    n = len(data)
    seed_u = np.array([seed], dtype=np.int64).view(np.uint64)
    i = 0
    if n >= 32:
        acc = [seed_u + P1 + P2, seed_u + P2, seed_u.copy(), seed_u - P1]
        while i + 32 <= n:
            for k in range(4):
                lane = np.frombuffer(
                    data[i + 8 * k:i + 8 * k + 8], dtype="<u8").copy()
                acc[k] = _rotl(acc[k] + lane * P2, 31) * P1
            i += 32
        h = (_rotl(acc[0], 1) + _rotl(acc[1], 7) + _rotl(acc[2], 12)
             + _rotl(acc[3], 18))
        for v in acc:
            h = (h ^ (_rotl(v * P2, 31) * P1)) * P1 + P4
    else:
        h = seed_u + P5
    h = h + u(n)
    while i + 8 <= n:
        lane = np.frombuffer(data[i:i + 8], dtype="<u8").copy()
        h = _rotl(h ^ (_rotl(lane * P2, 31) * P1), 27) * P1 + P4
        i += 8
    if i + 4 <= n:
        lane = np.frombuffer(data[i:i + 4], dtype="<u4").astype(np.uint64)
        h = _rotl(h ^ (lane * P1), 23) * P2 + P3
        i += 4
    while i < n:
        h = _rotl(h ^ (u(data[i]) * P5), 11) * P1
        i += 1
    return int(_fmix(h).view(np.int64)[0])
