"""File scans: DataFrameReader + CpuFileScanExec.

Mirrors the reference's scan architecture (GpuParquetScanBase.scala:82):
the host side lists files, parses footers, and plans partition units
(row-group granularity for Parquet, like the reference's copy-filtered
row-group blocks), then each partition decodes with one of three reader
strategies selected by ``spark.rapids.sql.format.parquet.reader.type``
(RapidsConf.scala:719-733):

- PERFILE       — decode units one by one (reference ParquetPartitionReader)
- MULTITHREADED — prefetch units with a thread pool, overlap IO with
                  downstream compute (MultiFileCloudParquetPartitionReader)
- COALESCING    — stitch all units of the partition into one decode
                  (MultiFileParquetPartitionReader)

Decode is Arrow on the host; device residency begins at the coalesced
upload in TpuRowToColumnarExec (HostColumnarToGpu's role).
"""

from __future__ import annotations

import glob
import os
import re
import threading
from collections import OrderedDict
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass
from typing import Any, Dict, Iterator, List, Optional, Sequence

from spark_rapids_tpu.columnar.host import HostBatch
from spark_rapids_tpu.conf import (MAX_READER_BATCH_SIZE_ROWS,
                                   MULTITHREADED_READ_NUM_THREADS,
                                   PARQUET_DEVICE_DECODE,
                                   PARQUET_READER_TYPE, TASK_PARALLELISM,
                                   TpuConf)
from spark_rapids_tpu.io.arrow_convert import (arrow_schema_to_sql,
                                               arrow_to_host_batch,
                                               sql_type_to_arrow)
from spark_rapids_tpu.sql import logical as L
from spark_rapids_tpu.sql import physical as P
from spark_rapids_tpu.sql import types as T

DEFAULT_MAX_PARTITION_BYTES = 128 << 20

HIVE_DEFAULT_PARTITION = "__HIVE_DEFAULT_PARTITION__"


def list_files(paths: Sequence[str]) -> List[tuple]:
    """Directory/glob expansion with Hive partition-directory discovery
    (PartitioningAwareFileIndex role): returns ``(file, part_values)``
    pairs where part_values maps partition column -> raw string value
    parsed from ``k=v`` path components under a directory input."""
    out: List[tuple] = []
    for p in paths:
        if os.path.isdir(p):
            base = os.path.abspath(p)
            for root, dirs, names in os.walk(base):
                dirs.sort()
                dirs[:] = [d for d in dirs if not d.startswith((".", "_"))]
                pv: Dict[str, str] = {}
                rel = os.path.relpath(root, base)
                if rel != ".":
                    from urllib.parse import unquote
                    for comp in rel.split(os.sep):
                        if "=" in comp:
                            k, v = comp.split("=", 1)
                            pv[k] = (v if v == HIVE_DEFAULT_PARTITION
                                     else unquote(v))
                for n in sorted(names):
                    if n.startswith(("_", ".")):
                        continue
                    out.append((os.path.join(root, n), pv))
        elif any(ch in p for ch in "*?["):
            out.extend((f, {}) for f in sorted(glob.glob(p)))
        elif os.path.exists(p):
            out.append((p, {}))
        else:
            raise FileNotFoundError(p)
    if not out:
        raise FileNotFoundError(f"no input files in {list(paths)}")
    return out


def file_fingerprints(files: Sequence[str]):
    """``(path, size, mtime_ns)`` per input file — the invalidation
    currency of the serve-tier caches (docs/caching.md). ``None`` when
    any file cannot be statted (vanished between listing and here): an
    unfingerprintable input set is simply uncacheable, never stale."""
    try:
        return tuple(
            (f, st.st_size, st.st_mtime_ns)
            for f, st in ((f, os.stat(f)) for f in files))
    except OSError:
        return None


def discovered_partition_fields(files: List[tuple]) -> List[T.StructField]:
    """Partition columns + value-inferred types (Spark's
    PartitioningUtils.inferPartitionColumnValue: int -> long -> double ->
    string, null for the Hive default marker)."""
    names: List[str] = []
    values: Dict[str, List[str]] = {}
    for _f, pv in files:
        for k, v in pv.items():
            if k not in values:
                names.append(k)
                values[k] = []
            values[k].append(v)
    fields = []
    for n in names:
        fields.append(T.StructField(n, _infer_part_type(values[n])))
    return fields


_INT_RE = re.compile(r"-?\d+\Z")
_FLOAT_RE = re.compile(r"-?(\d+\.\d*|\.\d+|\d+)([eE][-+]?\d+)?\Z")


def _infer_part_type(raw: List[str]) -> T.DataType:
    """Strict numeric parse (Long.parseLong/parseDouble shape): values
    Python's int()/float() accept but Arrow's cast rejects ('1_0', '+5',
    ' 7') must stay strings or the scan crashes casting later."""
    vals = [v for v in raw if v != HIVE_DEFAULT_PARTITION]
    if not vals:
        return T.StringT
    if all(_INT_RE.match(v) for v in vals):
        ints = [int(v) for v in vals]
        if all(-(1 << 31) <= i < (1 << 31) for i in ints):
            return T.IntegerT
        if all(-(1 << 63) <= i < (1 << 63) for i in ints):
            return T.LongT
        # beyond int64: Spark widens numerically rather than to string
        return T.DoubleT
    if all(_FLOAT_RE.match(v) for v in vals):
        return T.DoubleT
    return T.StringT


@dataclass
class ScanUnit:
    """One decode unit: a file, or a row-group range of a parquet file
    (the reference's filtered-block unit, GpuParquetScanBase.scala:1363).

    ``stats`` holds per-column footer statistics for predicate pushdown:
    {name: (min, max, null_count, num_rows)} with None min/max when the
    footer has none (the reference prunes on the same footer stats,
    GpuParquetScanBase filterBlocks)."""

    path: str
    size_bytes: int
    row_groups: Optional[List[int]] = None  # parquet only; None = whole file
    part_values: Optional[Dict[str, str]] = None  # Hive dir values
    stats: Optional[Dict[str, tuple]] = None


# Footer-parse results memoized per (fmt, file set), invalidated by the
# files' stat signature, so re-planning the same DataFrame (every
# collect()) doesn't re-read every parquet footer — the reference caches
# its file index per relation. Bounded LRU so sessions reading many
# distinct/growing datasets don't accumulate stale listings.
# tpu-lint: disable=jit-module-cache(holds unit-assignment tuples, not compiled programs; hand-bounded at _UNITS_CACHE_MAX below)
_UNITS_CACHE: "OrderedDict[tuple, tuple]" = OrderedDict()
_UNITS_CACHE_MAX = 64


def plan_scan_units(fmt: str, files: List[tuple]) -> List[ScanUnit]:
    key = (fmt, tuple(f for f, _ in files))
    sig = tuple((tuple(sorted(pv.items())),
                 os.path.getmtime(f), os.path.getsize(f))
                for f, pv in files)
    cached = _UNITS_CACHE.get(key)
    if cached is not None and cached[0] == sig:
        _UNITS_CACHE.move_to_end(key)
        return cached[1]
    units: List[ScanUnit] = []
    if fmt == "parquet":
        import pyarrow.parquet as pq
        for f, pv in files:
            try:
                meta = pq.ParquetFile(f).metadata
            except Exception:
                units.append(ScanUnit(f, os.path.getsize(f),
                                      part_values=pv))
                continue
            for rg in range(meta.num_row_groups):
                rgm = meta.row_group(rg)
                stats: Dict[str, tuple] = {}
                for ci in range(rgm.num_columns):
                    col = rgm.column(ci)
                    name = col.path_in_schema.split(".")[0]
                    try:
                        st = col.statistics
                        if st is None:
                            stats[name] = (None, None, None,
                                           rgm.num_rows)
                        else:
                            stats[name] = (
                                st.min if st.has_min_max else None,
                                st.max if st.has_min_max else None,
                                st.null_count if st.has_null_count
                                else None,
                                rgm.num_rows)
                    except Exception:
                        # some physical/logical combos (e.g. decimal
                        # stored as integer) cannot extract stats —
                        # pruning is optional, the scan is not
                        stats[name] = (None, None, None, rgm.num_rows)
                units.append(ScanUnit(
                    f, rgm.total_byte_size, [rg], pv, stats))
            if meta.num_row_groups == 0:
                units.append(ScanUnit(f, 0, [], pv))
    elif fmt == "orc":
        # stripe-granularity units (GpuOrcScanBase.scala:66 stripe-copy
        # role): each stripe decodes independently, so a multi-stripe
        # file fans out across the task pool like parquet row groups
        import pyarrow.orc as po
        for f, pv in files:
            try:
                of = po.ORCFile(f)
                ns = of.nstripes
            except Exception:
                units.append(ScanUnit(f, os.path.getsize(f),
                                      part_values=pv))
                continue
            if ns <= 1:
                units.append(ScanUnit(f, os.path.getsize(f),
                                      part_values=pv))
                continue
            per = max(1, os.path.getsize(f) // ns)
            for st in range(ns):
                units.append(ScanUnit(f, per, [st], pv))
    else:
        for f, pv in files:
            units.append(ScanUnit(f, os.path.getsize(f), part_values=pv))
    _UNITS_CACHE[key] = (sig, units)
    if len(_UNITS_CACHE) > _UNITS_CACHE_MAX:
        _UNITS_CACHE.popitem(last=False)
    return units


def shard_units_by_bytes(units: List[ScanUnit], n: int
                         ) -> List[List[ScanUnit]]:
    """Round-robin-by-bytes unit scheduler for the mesh scan: each unit
    goes to the stream with the least accumulated bytes (ties resolve
    by lowest stream index, so equal-sized units round-robin), which
    keeps skewed row-group sizes balanced across chips — the task->
    executor placement Spark's scheduler gives the reference for free.
    Streams may come back empty (fewer units than chips); callers keep
    them so per-chip structure is stable."""
    streams: List[List[ScanUnit]] = [[] for _ in range(n)]
    loads = [0] * n
    for u in units:
        i = min(range(n), key=lambda d: (loads[d], d))
        streams[i].append(u)
        # +1 so zero-byte units (empty row groups) still spread instead
        # of all landing on stream 0
        loads[i] += u.size_bytes + 1
    return streams


def pack_partitions(units: List[ScanUnit], max_bytes: int,
                    open_cost: int = 0) -> List[List[ScanUnit]]:
    """Bin-pack units into partitions (FilePartition.getFilePartitions;
    each unit weighs its bytes PLUS openCostInBytes, like Spark)."""
    parts: List[List[ScanUnit]] = []
    cur: List[ScanUnit] = []
    cur_bytes = 0
    for u in units:
        w = u.size_bytes + open_cost
        if cur and cur_bytes + w > max_bytes:
            parts.append(cur)
            cur, cur_bytes = [], 0
        cur.append(u)
        cur_bytes += w
    if cur:
        parts.append(cur)
    return parts


# ---------------------------------------------------------------------------
# Decoders (host side)
# ---------------------------------------------------------------------------

def _read_unit(fmt: str, unit: ScanUnit, schema: T.StructType,
               options: Dict[str, Any]):
    """Decode one unit to a pyarrow Table with `schema`'s columns."""
    import pyarrow as pa
    names = [f.name for f in schema.fields]
    if fmt == "parquet":
        import pyarrow.parquet as pq
        pf = pq.ParquetFile(unit.path)
        if unit.row_groups is not None:
            if not unit.row_groups:
                return pa.table(
                    {n: pa.array([], type=sql_type_to_arrow(f.data_type))
                     for n, f in zip(names, schema.fields)})
            return pf.read_row_groups(unit.row_groups, columns=names)
        return pf.read(columns=names)
    if fmt == "orc":
        import pyarrow.orc as po
        of = po.ORCFile(unit.path)
        if unit.row_groups:  # stripe indices
            batches = [of.read_stripe(st, columns=names)
                       for st in unit.row_groups]
            return pa.Table.from_batches(
                batches) if batches else of.read(columns=names)
        return of.read(columns=names)
    if fmt == "csv":
        return _read_csv(unit.path, schema, options)
    if fmt == "json":
        import pyarrow.json as pj
        tbl = pj.read_json(unit.path)
        return _conform(tbl, schema)
    if fmt == "text":
        import pyarrow.csv as pc
        tbl = pc.read_csv(unit.path, parse_options=pc.ParseOptions(
            delimiter="\x01", quote_char=False, escape_char=False),
            read_options=pc.ReadOptions(column_names=[names[0]]))
        return tbl
    raise NotImplementedError(f"format {fmt}")


def _read_csv(path: str, schema: T.StructType, options: Dict[str, Any]):
    import pyarrow as pa
    import pyarrow.csv as pc
    header = str(options.get("header", "false")).lower() == "true"
    sep = options.get("sep", options.get("delimiter", ","))
    null_value = options.get("nullValue", "")
    names = [f.name for f in schema.fields]
    null_values = [null_value] if null_value else [""]
    parse_opts = pc.ParseOptions(delimiter=sep)
    convert_opts = pc.ConvertOptions(
        column_types={f.name: sql_type_to_arrow(f.data_type)
                      for f in schema.fields},
        null_values=null_values,
        strings_can_be_null=True,
        timestamp_parsers=[pc.ISO8601, "%Y-%m-%d %H:%M:%S"])
    try:
        tbl = pc.read_csv(
            path,
            read_options=pc.ReadOptions(
                column_names=None if header else names, skip_rows=0),
            parse_options=parse_opts,
            convert_options=convert_opts)
    except pa.lib.ArrowInvalid:
        # PERMISSIVE-mode tolerance: the file's column count differs from
        # the schema — re-read with positional names (same null semantics,
        # types conformed by cast below)
        tbl = pc.read_csv(
            path,
            read_options=pc.ReadOptions(autogenerate_column_names=True,
                                        skip_rows=1 if header else 0),
            parse_options=parse_opts,
            convert_options=pc.ConvertOptions(
                null_values=null_values, strings_can_be_null=True,
                timestamp_parsers=[pc.ISO8601, "%Y-%m-%d %H:%M:%S"]))
    # align by position when file header names/column count differ from
    # the schema; extra columns are dropped, missing ones become null
    n = min(len(names), tbl.num_columns)
    tbl = tbl.select(list(range(n))).rename_columns(names[:n])
    return _conform(tbl, schema)


def _partition_value_array(f: T.StructField, raw: Optional[str], n: int):
    """One partition field's constant column: parse the raw directory
    value ONCE, then broadcast the scalar
    (PartitioningUtils.castPartValueToDesiredType role)."""
    import pyarrow as pa
    at = sql_type_to_arrow(f.data_type)
    if raw is None or raw == HIVE_DEFAULT_PARTITION:
        return pa.nulls(n, type=at)
    return pa.repeat(pa.scalar(raw, type=pa.string()).cast(at), n)


def _append_partition_columns(tbl, part_fields: List[T.StructField],
                              part_values: Dict[str, str]):
    """Attach directory-derived partition values as constant columns."""
    for f in part_fields:
        tbl = tbl.append_column(f.name, _partition_value_array(
            f, part_values.get(f.name), tbl.num_rows))
    return tbl


def _conform(tbl, schema: T.StructType):
    """Reorder/cast a table to the requested schema (schema evolution)."""
    import pyarrow as pa
    cols = []
    for f in schema.fields:
        if f.name in tbl.column_names:
            cols.append(tbl.column(f.name).cast(
                sql_type_to_arrow(f.data_type)))
        else:
            cols.append(pa.nulls(tbl.num_rows,
                                 type=sql_type_to_arrow(f.data_type)))
    return pa.Table.from_arrays(cols, names=[f.name for f in schema.fields])


def _extend_with_partition_cols(enc, schema: T.StructType,
                                part_fields: List[T.StructField],
                                part_values: Dict[str, str]):
    """Remap an EncodedBatch built against the data schema onto the full
    scan schema, adding directory-derived partition values as constant
    host columns (they never touch the file bytes)."""
    from spark_rapids_tpu.io.arrow_convert import arrow_column_to_host
    data_idx = {f.name: i for i, f in enumerate(enc.schema.fields)}
    plans = {}
    host_cols = {}
    n = enc.num_rows
    for fi, f in enumerate(schema.fields):
        di = data_idx.get(f.name)
        if di is not None:
            if di in enc.plans:
                plans[fi] = enc.plans[di]
            else:
                host_cols[fi] = enc.host_cols[di]
            continue
        host_cols[fi] = arrow_column_to_host(
            _partition_value_array(f, part_values.get(f.name), n),
            f.data_type)
    enc.schema = schema
    enc.plans = plans
    enc.host_cols = host_cols
    return enc


# ---------------------------------------------------------------------------
# Physical scan
# ---------------------------------------------------------------------------

_READ_POOL: Optional[ThreadPoolExecutor] = None
_POOL_SIZE: int = 0
_POOL_LOCK = threading.Lock()


def _shared_pool(n_threads: int) -> ThreadPoolExecutor:
    global _READ_POOL, _POOL_SIZE
    with _POOL_LOCK:
        if _READ_POOL is None or _POOL_SIZE != n_threads:
            if _READ_POOL is not None:
                _READ_POOL.shutdown(wait=False)
            _READ_POOL = ThreadPoolExecutor(
                max_workers=n_threads, thread_name_prefix="srt-multifile")
            _POOL_SIZE = n_threads
        return _READ_POOL


def _stat_storage(v, dt: T.DataType):
    """Footer stat value -> the engine's storage form (days/micros/
    unscaled int); None when not convertible (disables pruning)."""
    from spark_rapids_tpu.columnar.host import _to_storage
    try:
        out = _to_storage(v, dt)
        if isinstance(out, (int, float, str)):
            return out
        return None
    except Exception:
        return None


def unit_can_match(u: ScanUnit, preds: List[tuple],
                   fields: Dict[str, T.DataType]) -> bool:
    """False when this row-group's footer stats PRECLUDE any row
    matching every pushed conjunct (GpuParquetScanBase filterBlocks /
    parquet-mr StatisticsFilter shape). Conservative: missing stats or
    unconvertible values keep the unit."""
    if u.stats is None:
        return True
    for name, op, val in preds:
        st = u.stats.get(name)
        if st is None:
            continue
        mn, mx, nulls, n_rows = st
        dt = fields.get(name)
        if op == "notnull":
            if nulls is not None and n_rows and nulls == n_rows:
                return False
            continue
        if op == "isnull":
            if nulls is not None and nulls == 0 and n_rows:
                return False
            continue
        if mn is None or mx is None or dt is None:
            continue
        lo, hi = _stat_storage(mn, dt), _stat_storage(mx, dt)
        if lo is None or hi is None:
            continue
        try:
            if op == "eq" and (val < lo or val > hi):
                return False
            if op == "lt" and lo >= val:
                return False
            if op == "le" and lo > val:
                return False
            if op == "gt" and hi <= val:
                return False
            if op == "ge" and hi < val:
                return False
        except TypeError:
            continue  # cross-type compare: keep the unit
    return True


class CpuFileScanExec(P.PhysicalPlan):
    """File source scan; feeds the device through the transparent R2C
    transition (GpuFileSourceScanExec's role, host-decode variant)."""

    def __init__(self, output, fmt: str, paths: List[str],
                 options: Dict[str, Any], conf: TpuConf):
        from spark_rapids_tpu import metrics as M
        from spark_rapids_tpu.conf import METRICS_LEVEL
        self.children = []
        self._output = output
        self.fmt = fmt
        self.paths = paths
        self.options = options or {}
        self.conf = conf
        # decodeTime/convertTime surface in the bench stage breakdown —
        # round-4 verdict: the dominant cost must never be invisible
        self.metrics = M.MetricRegistry(str(conf.get(METRICS_LEVEL)),
                                        owner="FileScan")
        listed = list_files(paths)
        self.files = [f for f, _ in listed]
        # input-file fingerprints (path, size, mtime_ns) captured at
        # scan planning: the serve-tier caches (docs/caching.md) key on
        # these and re-stat before every reuse, so ANY change to the
        # inputs — append, rewrite, touch, delete — invalidates instead
        # of serving stale bytes
        self.fingerprints = file_fingerprints(self.files)
        part_names = {k for _f, pv in listed for k in pv}
        self._part_fields = [f for f in self.schema.fields
                             if f.name in part_names]
        max_bytes = int(
            conf.get_key("spark.sql.files.maxPartitionBytes",
                         DEFAULT_MAX_PARTITION_BYTES))
        open_cost = int(
            conf.get_key("spark.sql.files.openCostInBytes", 4 << 20))
        self._units = plan_scan_units(fmt, listed)
        # Spark's FilePartition.maxSplitBytes: size splits so the scan
        # fans out across the configured task parallelism instead of
        # packing one giant partition — bytesPerCore floored by
        # openCostInBytes, capped by maxPartitionBytes. Without this a
        # 60MB dataset became ONE partition and serialized the whole
        # decode/upload/compute pipeline on a single task thread.
        parallelism = max(1, int(conf.get(TASK_PARALLELISM)))
        total = sum(u.size_bytes for u in self._units) \
            + open_cost * len(self._units)
        self._max_bytes = min(max_bytes,
                              max(open_cost, total // parallelism))
        self._pushed: List[tuple] = []  # (col, op, storage value)
        self.pruned_units = 0  # observability (tools/tests)
        self._open_cost = open_cost
        self._parts = pack_partitions(self._units, self._max_bytes,
                                      open_cost)
        # set by the planner when input_file_name() sits above this scan
        self.force_perfile = False
        # set (at execution time) by TpuRowToColumnarExec when IT is the
        # direct consumer: only then may partitions() emit EncodedBatch
        # staging objects instead of HostBatches — CPU consumers always
        # see decoded rows
        self.emit_encoded = False
        # mesh scan (docs/multichip.md): set by TpuRowToColumnarExec at
        # execution time to the active mesh's devices; partitions() then
        # returns ONE reader stream per chip (units assigned round-
        # robin-by-bytes) and publishes the per-stream target device in
        # partition_devices so the upload lands each stream on its chip
        self._mesh_devices: List = []
        self.partition_devices: List = []

    def set_scan_mesh(self, devices: List) -> None:
        self._mesh_devices = list(devices or [])

    def set_pushdown(self, preds: List[tuple]) -> None:
        """Install pushed-down predicates (name, op, storage-value) and
        prune row-group units whose footer stats preclude matches. The
        enclosing Filter node still runs, so pruning is free to be
        conservative."""
        self._pushed = preds
        if not preds or self.fmt != "parquet":
            return
        fields = {f.name: f.data_type for f in self.schema.fields}
        kept = [u for u in self._units
                if unit_can_match(u, preds, fields)]
        self.pruned_units = len(self._units) - len(kept)
        # always at least one (possibly empty) partition so global
        # aggregates still see a partition to produce their one row in
        self._parts = pack_partitions(kept, self._max_bytes,
                              self._open_cost) \
            if kept else [[]]

    @property
    def output(self):
        return self._output

    def simple_string(self):
        s = (f"FileScan {self.fmt} [{len(self.files)} files, "
             f"{len(self._parts)} partitions")
        if self._pushed:
            s += (f", pushed {len(self._pushed)} filters, "
                  f"pruned {self.pruned_units} units")
        return s + "]"

    def partitions(self):
        reader_type = str(self.conf.get(PARQUET_READER_TYPE)).upper()
        if self.force_perfile:
            reader_type = "PERFILE"
        max_rows = int(self.conf.get(MAX_READER_BATCH_SIZE_ROWS))
        schema = self.schema
        part_fields = self._part_fields
        part_names = {f.name for f in part_fields}
        data_schema = T.StructType(
            [f for f in schema.fields if f.name not in part_names])
        # the device-decode path stitches no tables, so COALESCING keeps
        # the host decode (its whole point is the one-table stitch)
        device_decode = (self.fmt == "parquet"
                         and reader_type != "COALESCING"
                         and self.emit_encoded
                         and bool(self.conf.get(PARQUET_DEVICE_DECODE)))

        metrics = self.metrics

        def decode(u: ScanUnit):
            from spark_rapids_tpu import retry as R
            with metrics.timed_wall("decodeTime", path=u.path,
                                    bytes=u.size_bytes):
                # transient IO errors retry with bounded exponential
                # backoff (spark.rapids.sql.reader.maxRetries /
                # retryBackoffMs), re-raising the original after
                # exhaustion; covers PERFILE, MULTITHREADED (pool
                # threads), COALESCING, and the mesh-sharded streams,
                # which all decode through here
                tbl = R.io_with_retry(
                    lambda: _read_unit(self.fmt, u, data_schema,
                                       self.options),
                    self.conf, metrics, path=u.path)
                if part_fields:
                    tbl = _append_partition_columns(tbl, part_fields,
                                                    u.part_values or {})
                    tbl = tbl.select([f.name for f in schema.fields])
            return tbl

        def emit(tbl) -> Iterator[HostBatch]:
            for lo in range(0, max(1, tbl.num_rows), max_rows):
                with metrics.timed_wall("convertTime"):
                    hb = arrow_to_host_batch(tbl.slice(lo, max_rows),
                                             schema)
                yield hb

        def plan_device(u: ScanUnit):
            """ScanUnit -> EncodedBatch (host does IO/decompress/header
            parse only), or None when the unit must host-decode."""
            from spark_rapids_tpu.io import device_decode as DD
            if u.row_groups is None or len(u.row_groups) != 1:
                # whole-file / multi-row-group units host-decode; count
                # them so the bench attribution can't mistake an
                # all-fallback run for "nothing to decode"
                metrics.create("deviceFallbackUnits").add(1)
                return None
            from spark_rapids_tpu import retry as R
            with metrics.timed_wall("deviceDecodeTime", path=u.path):
                try:
                    # the device plan's file reads ride the same
                    # transient-IO retry protocol (and fault-injection
                    # checkpoints) as the host decode; a genuine IO
                    # failure after retries fails the query either way
                    enc = R.io_with_retry(
                        lambda: DD.plan_unit_encoded(
                            u, data_schema, self.conf),
                        self.conf, metrics, path=u.path)
                except OSError:
                    raise  # exhausted retries: a real reader failure
                except Exception:
                    enc = None  # corrupt chunk: the host decode decides
            if enc is None or enc.num_rows > max_rows:
                metrics.create("deviceFallbackUnits").add(1)
                return None
            if part_fields:
                enc = _extend_with_partition_cols(
                    enc, schema, part_fields, u.part_values or {})
            # OOM recovery: the upload can fall back to the pyarrow
            # host decode of this unit for just this batch
            enc.host_fallback = lambda u=u: list(emit(decode(u)))
            metrics.create("deviceDecodedBatches").add(1)
            for name, _reason in enc.fallbacks:
                metrics.create("deviceFallbackColumns").add(1)
            for ename, nvals in enc.fallback_encodings.items():
                metrics.create(f"hostDecodedValues.{ename}").add(nvals)
            for plan in enc.plans.values():
                for ename, nvals in plan.encoding_values.items():
                    metrics.create(
                        f"deviceDecodedValues.{ename}").add(nvals)
            return enc

        def decode_unit(u: ScanUnit) -> List:
            """One unit -> MATERIALIZED batches (EncodedBatch or
            HostBatches) for the prefetch pool: the arrow->HostBatch
            conversion (string object arrays, casts) runs IN the pool
            thread so the consumer thread only packs/uploads
            (MultiFileCloudParquetPartitionReader keeps its host-side
            decode off the task thread the same way). The PERFILE path
            streams instead (one batch in flight, not a whole file)."""
            if device_decode:
                enc = plan_device(u)
                if enc is not None:
                    return [enc]
            return list(emit(decode(u)))

        from spark_rapids_tpu.sql import expressions as E

        def _set_file(path: str) -> None:
            # input_file_name() context: valid for scan-adjacent
            # projects on this thread (InputFileBlockRule role)
            E._PART_CTX.input_file = path

        def make(units: List[ScanUnit]):
            def run() -> Iterator[HostBatch]:
                if reader_type == "COALESCING" and len(units) > 1:
                    import pyarrow as pa
                    tbl = pa.concat_tables([decode(u) for u in units])
                    _set_file("")  # batches span files after the stitch
                    yield from emit(tbl)
                elif reader_type == "MULTITHREADED" and len(units) > 1:
                    n_threads = int(
                        self.conf.get(MULTITHREADED_READ_NUM_THREADS))
                    pool = _shared_pool(n_threads)
                    # sliding prefetch window: decoded-and-converted
                    # HostBatches are several times their arrow size, so
                    # bound in-flight units instead of materializing the
                    # whole partition's decode output at once
                    from collections import deque
                    from itertools import islice
                    it = iter(units)
                    futures = deque(pool.submit(decode_unit, u)
                                    for u in islice(it, n_threads + 2))
                    done = iter(units)
                    try:
                        while futures:
                            f = futures.popleft()
                            nxt = next(it, None)
                            if nxt is not None:
                                futures.append(
                                    pool.submit(decode_unit, nxt))
                            _set_file(next(done).path)
                            for hb in f.result():
                                yield hb
                    finally:
                        # a decode error (or a closed consumer) must not
                        # leak pool work: unstarted prefetches are
                        # cancelled so the shared pool drains promptly
                        # and later queries see a clean queue
                        for f in futures:
                            f.cancel()
                else:  # PERFILE: streamed, one host batch in flight
                    for u in units:
                        if device_decode:
                            enc = plan_device(u)
                            if enc is not None:
                                _set_file(u.path)
                                yield enc
                                continue
                        tbl = decode(u)
                        _set_file(u.path)
                        yield from emit(tbl)
            return run

        if len(self._mesh_devices) >= 2:
            # mesh scan: one reader stream per chip over the (pruned)
            # unit list, round-robin-by-bytes; empty streams are kept so
            # a chip with zero units still yields an (empty) partition
            # and the per-chip pipeline structure stays stable
            units = [u for part in self._parts for u in part]
            streams = shard_units_by_bytes(units, len(self._mesh_devices))
            self.partition_devices = list(self._mesh_devices)

            def chip_stream(st: List[ScanUnit]):
                # a chip's share still honors the max_bytes bin packing
                # (COALESCING concatenates one TABLE per sub-partition,
                # not the chip's whole share; MULTITHREADED windows per
                # sub-partition) — the stream just chains them
                subs = pack_partitions(st, self._max_bytes,
                                       self._open_cost) if st else [[]]
                runs = [make(us) for us in subs]

                def run():
                    for r in runs:
                        yield from r()
                return run

            for d, st in zip(self._mesh_devices, streams):
                metrics.create(f"meshScanUnits.chip{d.id}").add(len(st))
            return [chip_stream(st) for st in streams]
        self.partition_devices = []
        return [make(us) for us in self._parts]


# ---------------------------------------------------------------------------
# DataFrameReader
# ---------------------------------------------------------------------------

class DataFrameReader:
    """spark.read facade (pyspark DataFrameReader shape)."""

    def __init__(self, session):
        self._session = session
        self._format = "parquet"
        self._schema: Optional[T.StructType] = None
        self._options: Dict[str, Any] = {}

    def format(self, fmt: str) -> "DataFrameReader":
        self._format = fmt.lower()
        return self

    def schema(self, schema) -> "DataFrameReader":
        if isinstance(schema, str):
            from spark_rapids_tpu.sql.session import _parse_ddl_schema
            schema = _parse_ddl_schema(schema)
        self._schema = schema
        return self

    def option(self, key: str, value: Any) -> "DataFrameReader":
        self._options[key] = value
        return self

    def options(self, **opts) -> "DataFrameReader":
        self._options.update(opts)
        return self

    def load(self, path=None):
        from spark_rapids_tpu.sql.dataframe import DataFrame
        paths = [path] if isinstance(path, str) else list(path)
        listed = list_files(paths)  # one walk for infer + discovery
        schema = self._schema or self._infer_schema_from(listed)
        # append Hive-style partition columns discovered from k=v dirs
        have = {f.name for f in schema.fields}
        extra = [f for f in discovered_partition_fields(listed)
                 if f.name not in have]
        if extra:
            schema = T.StructType(list(schema.fields) + extra)
        plan = L.FileScan(self._format, paths, schema, dict(self._options))
        return DataFrame(plan, self._session)

    def parquet(self, *paths: str):
        return self.format("parquet").load(list(paths))

    def orc(self, *paths: str):
        return self.format("orc").load(list(paths))

    def csv(self, path, schema=None, header=None, sep=None,
            inferSchema=None, nullValue=None):
        if schema is not None:
            self.schema(schema)
        if header is not None:
            self.option("header", str(header).lower())
        if sep is not None:
            self.option("sep", sep)
        if inferSchema is not None:
            self.option("inferSchema", str(inferSchema).lower())
        if nullValue is not None:
            self.option("nullValue", nullValue)
        return self.format("csv").load(path)

    def json(self, path, schema=None):
        if schema is not None:
            self.schema(schema)
        return self.format("json").load(path)

    def text(self, path):
        self._schema = T.StructType([T.StructField("value", T.StringT)])
        return self.format("text").load(path)

    def table(self, name: str):
        return self._session.table(name)

    # -- schema inference --------------------------------------------------

    def _infer_schema_from(self, listed: List[tuple]) -> T.StructType:
        first = listed[0][0]
        fmt = self._format
        if fmt == "parquet":
            import pyarrow.parquet as pq
            return arrow_schema_to_sql(
                pq.ParquetFile(first).schema_arrow)
        if fmt == "orc":
            import pyarrow.orc as po
            return arrow_schema_to_sql(po.ORCFile(first).schema)
        if fmt == "json":
            import pyarrow.json as pj
            return arrow_schema_to_sql(pj.read_json(first).schema)
        if fmt == "csv":
            return self._infer_csv_schema(first)
        raise ValueError(
            f"cannot infer schema for format {fmt}; pass .schema(...)")

    def _infer_csv_schema(self, path: str) -> T.StructType:
        import pyarrow.csv as pc
        header = str(self._options.get("header", "false")).lower() == "true"
        sep = self._options.get("sep", self._options.get("delimiter", ","))
        infer = str(self._options.get("inferSchema",
                                      "false")).lower() == "true"
        tbl = pc.read_csv(
            path,
            read_options=pc.ReadOptions(),
            parse_options=pc.ParseOptions(delimiter=sep))
        names = (tbl.column_names if header
                 else [f"_c{i}" for i in range(tbl.num_columns)])
        if not header:
            # first row was data; re-read without consuming it as header
            tbl = pc.read_csv(
                path,
                read_options=pc.ReadOptions(column_names=names),
                parse_options=pc.ParseOptions(delimiter=sep))
        if infer:
            fields = []
            for n, col in zip(names, tbl.columns):
                try:
                    dt = arrow_type_to_sql_for_csv(col.type)
                except TypeError:
                    dt = T.StringT
                fields.append(T.StructField(n, dt))
            return T.StructType(fields)
        return T.StructType([T.StructField(n, T.StringT) for n in names])


def arrow_type_to_sql_for_csv(at) -> T.DataType:
    """CSV inference maps ints to LONG and floats to DOUBLE (Spark's
    CSVInferSchema tightest types)."""
    import pyarrow as pa
    if pa.types.is_boolean(at):
        return T.BooleanT
    if pa.types.is_integer(at):
        return T.LongT
    if pa.types.is_floating(at):
        return T.DoubleT
    if pa.types.is_timestamp(at):
        return T.TimestampT
    if pa.types.is_date(at):
        return T.DateT
    return T.StringT
