"""df.cache(): Parquet-compressed in-memory cache.

Twin of the reference's ParquetCachedBatchSerializer
(sql-plugin/src/main/311+-all/.../ParquetCachedBatchSerializer.scala):
`df.cache()` stores each partition's batches as compressed Parquet bytes in
host memory, decoded back on demand. Materialization is lazy and happens at
most once per cached plan.
"""

from __future__ import annotations

import io
import threading
from typing import List, Optional

from spark_rapids_tpu.columnar.host import HostBatch
from spark_rapids_tpu.io.arrow_convert import (arrow_to_host_batch,
                                               host_batch_to_arrow)
from spark_rapids_tpu.sql import logical as L
from spark_rapids_tpu.sql import physical as P


class CachedRelation(L.LogicalPlan):
    """InMemoryRelation: holds parquet-compressed partition payloads."""

    def __init__(self, child: L.LogicalPlan, session):
        self.children = []  # leaf once materialized; child kept for lazy run
        self.child_plan = child
        self.session = session
        self._output = list(child.output)
        self._lock = threading.Lock()
        self._payloads: Optional[List[List[bytes]]] = None
        self.cached_bytes = 0

    @property
    def output(self):
        return self._output

    def simple_string(self):
        state = "materialized" if self._payloads is not None else "lazy"
        return f"InMemoryRelation [parquet-cached, {state}]"

    def materialize(self) -> List[List[bytes]]:
        with self._lock:
            if self._payloads is None:
                # nested planning must not clobber the OUTER query's
                # rewrite report / plan capture (materialize runs lazily
                # inside the outer collect)
                saved = self.session.last_rewrite_report
                physical = self.session.plan_physical(self.child_plan)
                self.session.last_rewrite_report = saved
                payloads: List[List[bytes]] = []
                for thunk in physical.partitions():
                    part: List[bytes] = []
                    for batch in thunk():
                        part.append(_encode(batch))
                    payloads.append(part)
                self._payloads = payloads
                self.cached_bytes = sum(
                    len(b) for p in payloads for b in p)
            return self._payloads


def _encode(batch: HostBatch) -> bytes:
    import pyarrow.parquet as pq
    buf = io.BytesIO()
    pq.write_table(host_batch_to_arrow(batch), buf, compression="snappy")
    return buf.getvalue()


def _decode(payload: bytes, schema) -> HostBatch:
    import pyarrow.parquet as pq
    tbl = pq.read_table(io.BytesIO(payload))
    return arrow_to_host_batch(tbl, schema)


class CpuCachedScanExec(P.PhysicalPlan):
    def __init__(self, rel: CachedRelation):
        self.children = []
        self.rel = rel

    @property
    def output(self):
        return self.rel.output

    def simple_string(self):
        return f"CachedScan [{len(self.rel._payloads or [])} partitions]"

    def partitions(self):
        payloads = self.rel.materialize()
        schema = self.schema

        def make(part: List[bytes]):
            def run():
                for payload in part:
                    yield _decode(payload, schema)
            return run
        return [make(p) for p in payloads]


def cache_plan(df) -> CachedRelation:
    plan = df.plan
    if isinstance(plan, CachedRelation):
        return plan
    return CachedRelation(plan, df.session)
