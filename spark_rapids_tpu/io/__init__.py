"""File IO: readers, writers, cache serializer.

The reference reimplements Parquet/ORC/CSV scans with a CPU-fetch /
GPU-decode split (GpuParquetScanBase.scala:82) and writes columnar data
back with device encoders (GpuParquetFileFormat). On TPU the decode stays
host-side (Arrow decoders; a Pallas page decoder is not yet profitable) and
the device boundary is the coalesced upload in TpuRowToColumnarExec —
mirroring the reference's HostColumnarToGpu path for host-columnar sources.
"""
