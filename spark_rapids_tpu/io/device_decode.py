"""Device-side Parquet decode: host plans, device decodes.

The reference's scan decodes Parquet pages on the GPU inside cuDF
(GpuParquetScanBase.scala:82 copies the filtered row-group bytes to the
device and calls Table.readParquet); this module is the TPU equivalent.
The host does only the cheap, sequential work:

1. read the raw column-chunk bytes (one contiguous read per chunk),
2. decompress page bodies (snappy/zstd/gzip — host codecs, as the
   issue scopes; the wire then carries the *uncompressed but still
   encoded* pages, typically far smaller than decoded columns),
3. parse page headers (Thrift compact protocol, a few dozen bytes per
   page) and RLE/bit-packed *run headers* (a varint per run),

and builds a ``ColumnDevicePlan``: run tables + page tables + decoded
dictionaries. Every per-value operation — bit-unpacking the packed
runs, dictionary-index gather, PLAIN fixed-width reinterpret,
definition-level expansion into validity masks — happens on device in
one XLA program (ops/rle.py kernels, wired by columnar/transfer.py).

Unsupported encodings/types (DELTA_*, BYTE_STREAM_SPLIT, nested,
PLAIN byte arrays, INT96, ...) fall back PER COLUMN to the pyarrow
host decode, so results stay bit-for-bit identical to the host path.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from spark_rapids_tpu.sql import types as T

# Parquet enums (format/parquet.thrift)
PAGE_DATA = 0
PAGE_INDEX = 1
PAGE_DICTIONARY = 2
PAGE_DATA_V2 = 3

ENC_PLAIN = 0
ENC_PLAIN_DICTIONARY = 2
ENC_RLE = 3
ENC_BIT_PACKED = 4
ENC_DELTA_BINARY_PACKED = 5
ENC_DELTA_LENGTH_BYTE_ARRAY = 6
ENC_DELTA_BYTE_ARRAY = 7
ENC_RLE_DICTIONARY = 8
ENC_BYTE_STREAM_SPLIT = 9

_ENC_NAMES = {ENC_PLAIN: "PLAIN", ENC_PLAIN_DICTIONARY: "PLAIN_DICTIONARY",
              ENC_RLE: "RLE", ENC_RLE_DICTIONARY: "RLE_DICTIONARY",
              ENC_DELTA_BINARY_PACKED: "DELTA_BINARY_PACKED",
              ENC_DELTA_LENGTH_BYTE_ARRAY: "DELTA_LENGTH_BYTE_ARRAY",
              ENC_DELTA_BYTE_ARRAY: "DELTA_BYTE_ARRAY",
              ENC_BYTE_STREAM_SPLIT: "BYTE_STREAM_SPLIT"}

# per-page value-section encoding classes shipped to the device
# (columnar/transfer.py selects the decode lane per page by these)
PGE_DICT = 0     # RLE/bit-packed hybrid stream (dict indices, bool bits)
PGE_PLAIN = 1    # PLAIN fixed-width at pg_plain_byte
PGE_DELTA = 2    # DELTA_BINARY_PACKED (miniblock runs + seg-cumsum)
PGE_BSS = 3      # BYTE_STREAM_SPLIT at pg_plain_byte
PGE_PLAIN_STR = 4  # PLAIN byte array (4-byte length prefixes)
PGE_DL_STR = 5   # DELTA_LENGTH byte array (concatenated bytes)

# searchsorted sentinel for padded run/page tables
_SENTINEL = 1 << 62


def dev_entry_stages(ndl: int, n_dicts: int, has_slen: bool,
                     has_delta: bool, has_bss: bool) -> int:
    """Logical decode-stage count one device-decoded column runs
    through on the stock XLA chain: the base page-select/value read,
    plus definition-level validity expansion, dictionary gather, the
    string offsets-from-lengths segmented cumsum and its char gather,
    DELTA reconstruction and the BSS reinterleave when the plan uses
    them. The fused Pallas kernel (kernels/decode_fused.py) replaces
    ALL of them with one program — the ``deviceDecodePrograms`` metric
    bills this count on the chain and 1 on the fused path."""
    return (1 + (1 if ndl else 0) + (1 if n_dicts else 0)
            + (2 if has_slen else 0) + (1 if has_delta else 0)
            + (1 if has_bss else 0))

_HOST_CODECS = {"UNCOMPRESSED": None, "SNAPPY": "snappy", "ZSTD": "zstd",
                "GZIP": "gzip", "BROTLI": "brotli"}


class UnsupportedColumn(Exception):
    """Per-column fallback trigger; the message is the reason string."""


# ---------------------------------------------------------------------------
# Thrift compact protocol (just enough for PageHeader)
# ---------------------------------------------------------------------------

_CT_TRUE, _CT_FALSE, _CT_BYTE = 1, 2, 3
_CT_I16, _CT_I32, _CT_I64, _CT_DOUBLE = 4, 5, 6, 7
_CT_BINARY, _CT_LIST, _CT_SET, _CT_MAP, _CT_STRUCT = 8, 9, 10, 11, 12


def _varint(buf: bytes, pos: int) -> Tuple[int, int]:
    out = shift = 0
    while True:
        b = buf[pos]
        pos += 1
        out |= (b & 0x7F) << shift
        if not b & 0x80:
            return out, pos
        shift += 7


def _zigzag(buf: bytes, pos: int) -> Tuple[int, int]:
    v, pos = _varint(buf, pos)
    return (v >> 1) ^ -(v & 1), pos


def _skip(buf: bytes, pos: int, ftype: int) -> int:
    if ftype in (_CT_TRUE, _CT_FALSE):
        return pos
    if ftype == _CT_BYTE:
        return pos + 1
    if ftype in (_CT_I16, _CT_I32, _CT_I64):
        _, pos = _varint(buf, pos)
        return pos
    if ftype == _CT_DOUBLE:
        return pos + 8
    if ftype == _CT_BINARY:
        n, pos = _varint(buf, pos)
        return pos + n
    if ftype == _CT_STRUCT:
        _, pos = _thrift_struct(buf, pos)
        return pos
    if ftype in (_CT_LIST, _CT_SET):
        h = buf[pos]
        pos += 1
        n, et = h >> 4, h & 0x0F
        if n == 15:
            n, pos = _varint(buf, pos)
        for _ in range(n):
            pos = _skip(buf, pos, et)
        return pos
    if ftype == _CT_MAP:
        n, pos = _varint(buf, pos)
        if n:
            h = buf[pos]
            pos += 1
            for _ in range(n):
                pos = _skip(buf, pos, h >> 4)
                pos = _skip(buf, pos, h & 0x0F)
        return pos
    raise UnsupportedColumn(f"thrift type {ftype} in page header")


def _thrift_struct(buf: bytes, pos: int) -> Tuple[Dict[int, Any], int]:
    """Generic compact-protocol struct -> {field_id: value}; nested
    structs recurse, unknown field types are skipped."""
    out: Dict[int, Any] = {}
    fid = 0
    while True:
        b = buf[pos]
        pos += 1
        if b == 0:
            return out, pos
        delta, ftype = b >> 4, b & 0x0F
        if delta:
            fid += delta
        else:
            fid, pos = _zigzag(buf, pos)
        if ftype in (_CT_TRUE, _CT_FALSE):
            out[fid] = ftype == _CT_TRUE
        elif ftype == _CT_BYTE:
            out[fid] = buf[pos]
            pos += 1
        elif ftype in (_CT_I16, _CT_I32, _CT_I64):
            out[fid], pos = _zigzag(buf, pos)
        elif ftype == _CT_STRUCT:
            out[fid], pos = _thrift_struct(buf, pos)
        else:
            pos = _skip(buf, pos, ftype)


def parse_page_header(buf: bytes, pos: int) -> Tuple[Dict[int, Any], int]:
    """PageHeader at ``pos`` -> (fields, body_offset). Field ids follow
    parquet.thrift: 1 type, 2 uncompressed_page_size,
    3 compressed_page_size, 5 data_page_header {1 num_values,
    2 encoding, 3 definition_level_encoding}, 7 dictionary_page_header
    {1 num_values, 2 encoding}, 8 data_page_header_v2 {1 num_values,
    2 num_nulls, 3 num_rows, 4 encoding, 5 definition_levels_byte_length,
    6 repetition_levels_byte_length, 7 is_compressed}."""
    return _thrift_struct(buf, pos)


# ---------------------------------------------------------------------------
# Plan structures
# ---------------------------------------------------------------------------

@dataclass
class RunTable:
    """RLE/bit-packed hybrid runs, host-parsed headers only: where each
    run's output starts, whether it is bit-packed, the RLE value,
    the absolute payload bit offset into the packed buffer, and the
    per-run bit width (dictionary index width varies across pages)."""

    out_start: List[int] = field(default_factory=list)
    packed: List[bool] = field(default_factory=list)
    value: List[int] = field(default_factory=list)
    bit_start: List[int] = field(default_factory=list)
    width: List[int] = field(default_factory=list)

    def add(self, out_start: int, packed: bool, value: int,
            bit_start: int, width: int) -> None:
        self.out_start.append(out_start)
        self.packed.append(packed)
        self.value.append(value)
        self.bit_start.append(bit_start)
        self.width.append(width)

    def __len__(self) -> int:
        return len(self.out_start)

    def arrays(self, pad_to: int) -> List[np.ndarray]:
        nr = len(self.out_start)
        os = np.full(pad_to, _SENTINEL, dtype=np.int64)
        os[:nr] = self.out_start
        pk = np.zeros(pad_to, dtype=bool)
        pk[:nr] = self.packed
        va = np.zeros(pad_to, dtype=np.int64)
        va[:nr] = self.value
        bs = np.zeros(pad_to, dtype=np.int64)
        bs[:nr] = self.bit_start
        wd = np.ones(pad_to, dtype=np.int64)
        wd[:nr] = self.width
        return [os, pk, va, bs, wd]


@dataclass
class ColumnDevicePlan:
    """One column chunk's device-decode plan (see module docstring)."""

    dtype: T.DataType
    kind: str             # int | f32 | f64 | dec64 | dec128 | bool | str
    np_dtype: str         # output numpy dtype name for 'int' kinds
    elem_bytes: int       # PLAIN element width (FLBA length for decimals)
    dl: Optional[RunTable]         # definition levels (None = no nulls)
    pg_dense_start: List[int] = field(default_factory=list)
    pg_plain_byte: List[int] = field(default_factory=list)  # -1 = dict page
    pg_enc: List[int] = field(default_factory=list)         # PGE_* class
    pg_first: List[int] = field(default_factory=list)  # delta first_value
    vr: Optional[RunTable] = None  # dict-index / bool-bit runs
    dr: Optional[RunTable] = None  # delta miniblock runs (value=min_delta)
    str_lens: Optional[np.ndarray] = None  # dense byte lengths (plain/DL)
    dict_arrays: List[np.ndarray] = field(default_factory=list)
    char_cap: int = 0
    n_dense: int = 0               # non-null value count
    has_plain: bool = False
    has_delta: bool = False
    has_bss: bool = False
    encoding_values: Dict[str, int] = field(default_factory=dict)


@dataclass
class EncodedBatch:
    """A scan unit staged for device decode: the packed page buffer plus
    per-column plans; columns that fell back carry a HostColumn
    instead. Consumed by transfer.prepare_upload (tag 'encoded')."""

    schema: T.StructType
    num_rows: int
    words: np.ndarray                      # int32 staging words
    plans: Dict[int, ColumnDevicePlan]     # field index -> device plan
    host_cols: Dict[int, Any]              # field index -> HostColumn
    fallbacks: List[Tuple[str, str]]       # (column, reason)
    path: str = ""
    # host-decoded value counts per Parquet data encoding for the
    # fallback columns (bench detail.decode's device-vs-host split)
    fallback_encodings: Dict[str, int] = field(default_factory=dict)
    # OOM recovery hook (docs/robustness.md): () -> List[HostBatch] via
    # the pyarrow per-column host decode of the SAME scan unit; set by
    # the reader so a device-decode upload that cannot fit falls back
    # for just that batch instead of failing the query
    host_fallback: Any = None


# ---------------------------------------------------------------------------
# Host-side planner
# ---------------------------------------------------------------------------

def _check_supported(dt: T.DataType, leaf) -> None:
    """Raise UnsupportedColumn unless the file's physical/logical type
    decodes losslessly into ``dt``'s device storage on this backend."""
    if leaf.max_repetition_level > 0:
        raise UnsupportedColumn("nested (repeated) column")
    if leaf.max_definition_level > 1:
        raise UnsupportedColumn("nested optional column")
    phys = leaf.physical_type
    lt = str(leaf.logical_type)
    if isinstance(dt, T.BooleanType):
        if phys != "BOOLEAN":
            raise UnsupportedColumn(f"physical {phys} for boolean")
        return
    if isinstance(dt, T.ByteType):
        if phys != "INT32" or "bitWidth=8" not in lt:
            raise UnsupportedColumn(f"physical {phys}/{lt} for tinyint")
        return
    if isinstance(dt, T.ShortType):
        if phys != "INT32" or "bitWidth=16" not in lt:
            raise UnsupportedColumn(f"physical {phys}/{lt} for smallint")
        return
    if isinstance(dt, T.IntegerType):
        if phys != "INT32" or not (lt == "None" or "bitWidth=32" in lt):
            raise UnsupportedColumn(f"physical {phys}/{lt} for int")
        return
    if isinstance(dt, T.LongType):
        if phys != "INT64" or not (lt == "None" or "bitWidth=64" in lt):
            raise UnsupportedColumn(f"physical {phys}/{lt} for bigint")
        return
    if isinstance(dt, T.FloatType):
        if phys != "FLOAT":
            raise UnsupportedColumn(f"physical {phys} for float")
        return
    if isinstance(dt, T.DoubleType):
        if phys != "DOUBLE":
            raise UnsupportedColumn(f"physical {phys} for double")
        from spark_rapids_tpu.device_caps import f64_bitcast_exact
        if not f64_bitcast_exact():
            raise UnsupportedColumn(
                "f64 bitcast unsupported on this backend")
        return
    if isinstance(dt, T.DateType):
        if phys != "INT32" or lt != "Date":
            raise UnsupportedColumn(f"physical {phys}/{lt} for date")
        return
    if isinstance(dt, T.TimestampType):
        if phys != "INT64" or not lt.startswith("Timestamp") \
                or "micro" not in lt:
            raise UnsupportedColumn(f"physical {phys}/{lt} for timestamp")
        return
    if isinstance(dt, T.DecimalType):
        if f"precision={dt.precision}, scale={dt.scale}" not in lt:
            raise UnsupportedColumn(f"logical {lt} != {dt.simple_string}")
        if phys == "FIXED_LEN_BYTE_ARRAY":
            w = leaf.length
            if T.is_limb_decimal(dt):
                if not 8 < w <= 16:
                    raise UnsupportedColumn(f"FLBA width {w} for dec128")
            elif not 0 < w <= 8:
                raise UnsupportedColumn(f"FLBA width {w} for dec64")
            return
        if phys == "INT64" and not T.is_limb_decimal(dt):
            return
        if phys == "INT32" and not T.is_limb_decimal(dt):
            return
        raise UnsupportedColumn(f"physical {phys} for {dt.simple_string}")
    if isinstance(dt, (T.StringType, T.BinaryType)):
        if phys != "BYTE_ARRAY":
            raise UnsupportedColumn(f"physical {phys} for string/binary")
        return  # per-page dictionary-only check happens during the walk
    raise UnsupportedColumn(f"type {dt.simple_string} not device-decodable")


def _kind_for(dt: T.DataType, leaf) -> Tuple[str, str, int]:
    """(kind, np_dtype_name, plain_elem_bytes) for a supported column."""
    if isinstance(dt, T.BooleanType):
        return "bool", "bool", 0
    if isinstance(dt, T.ByteType):
        return "int", "int8", 4
    if isinstance(dt, T.ShortType):
        return "int", "int16", 4
    if isinstance(dt, (T.IntegerType, T.DateType)):
        return "int", "int32", 4
    if isinstance(dt, (T.LongType, T.TimestampType)):
        return "int", "int64", 8
    if isinstance(dt, T.FloatType):
        return "f32", "float32", 4
    if isinstance(dt, T.DoubleType):
        return "f64", "float64", 8
    if isinstance(dt, T.DecimalType):
        phys = leaf.physical_type
        if phys == "INT32":
            return "int", "int64", 4
        if phys == "INT64":
            return "int", "int64", 8
        if T.is_limb_decimal(dt):
            return "dec128", "int64", leaf.length
        return "dec64", "int64", leaf.length
    return "str", "uint8", 0


def _parse_hybrid_runs(page: bytes, pos: int, end: int, width: int,
                       n_values: int, out_base: int, page_buf_off: int,
                       runs: RunTable) -> Tuple[int, List[Tuple[int, int]]]:
    """Parse run HEADERS of an RLE/bit-packed hybrid stream (payload
    stays in the page bytes for the device). Returns (stream_end_pos,
    packed_regions) where packed_regions are (page_pos, n_vals) of
    bit-packed payloads (the host popcounts these for validity
    bookkeeping when parsing definition levels)."""
    if width == 0:
        # zero-width stream: every value is 0, no bytes consumed
        runs.add(out_base, False, 0, 0, 1)
        return pos, []
    count = 0
    vbytes = (width + 7) // 8
    packed_regions: List[Tuple[int, int]] = []
    while count < n_values:
        if pos >= end:
            raise UnsupportedColumn("truncated RLE/bit-packed stream")
        header, pos = _varint(page, pos)
        if header & 1:  # bit-packed: groups of 8 values
            groups = header >> 1
            nv = min(groups * 8, n_values - count)
            runs.add(out_base + count, True, 0,
                     (page_buf_off + pos) * 8, width)
            packed_regions.append((pos, nv))
            pos += groups * width
            count += nv
        else:  # RLE run
            run_len = header >> 1
            if run_len == 0:
                raise UnsupportedColumn("zero-length RLE run")
            v = int.from_bytes(page[pos:pos + vbytes], "little")
            pos += vbytes
            runs.add(out_base + count, False, v, 0, width)
            count += min(run_len, n_values - count)
    return pos, packed_regions


def _popcount_regions(page: bytes, regions: List[Tuple[int, int]]) -> int:
    """Non-null count contribution of bit-packed def-level regions
    (width-1 streams): vectorized popcount over the payload bytes."""
    total = 0
    for pos, nv in regions:
        nbytes = (nv + 7) // 8
        bits = np.unpackbits(
            np.frombuffer(page, dtype=np.uint8, offset=pos, count=nbytes),
            bitorder="little")[:nv]
        total += int(bits.sum())
    return total


def _plain_str_lengths(body: bytes, pos: int, end: int,
                       nn: int) -> np.ndarray:
    """Per-value byte lengths of a PLAIN byte-array page (4-byte LE
    length prefixes interleaved with the bytes). The value starts form
    a sequential chain (start[i+1] = start[i] + 4 + len[i]); resolved
    with vectorized pointer doubling over a byte-position jump table —
    O(page_bytes * log n) numpy work, no per-value Python loop."""
    if nn <= 0:
        return np.zeros(0, dtype=np.int64)
    buf = np.frombuffer(body, dtype=np.uint8, offset=pos,
                        count=end - pos).astype(np.int64)
    B = buf.shape[0]
    if B < 4:
        raise UnsupportedColumn("truncated PLAIN byte-array page")
    le = (buf[:-3] | (buf[1:-2] << 8) | (buf[2:-1] << 16)
          | (buf[3:] << 24))        # u32 length at every byte position
    limit = B - 3
    nxt = np.arange(limit, dtype=np.int64) + 4 + le
    np.clip(nxt, 0, limit - 1, out=nxt)   # keep the table in-domain
    starts = np.empty(nn, dtype=np.int64)
    starts[0] = 0
    filled = 1
    jump = nxt                            # jumps exactly `filled` values
    while filled < nn:
        take = min(filled, nn - filled)
        starts[filled:filled + take] = jump[starts[:take]]
        filled += take
        if filled < nn:
            jump = jump[jump]
    lengths = le[starts]
    if nn >= 2 and not (np.diff(starts) > 0).all():
        raise UnsupportedColumn("corrupt PLAIN byte-array chain")
    if int(starts[-1]) + 4 + int(lengths[-1]) > B:
        raise UnsupportedColumn("PLAIN byte-array page overruns body")
    return lengths


def _parse_delta_header(page: bytes, pos: int) -> Tuple[int, int, int,
                                                        int, int]:
    """DELTA_BINARY_PACKED stream header ->
    (values_per_miniblock, miniblocks_per_block, total_count,
    first_value, pos_after_header)."""
    block_size, pos = _varint(page, pos)
    mbpb, pos = _varint(page, pos)
    total, pos = _varint(page, pos)
    first, pos = _zigzag(page, pos)
    if mbpb <= 0 or block_size <= 0 or block_size % mbpb:
        raise UnsupportedColumn("malformed delta header")
    vpm = block_size // mbpb
    if vpm % 8:
        raise UnsupportedColumn(f"delta miniblock size {vpm}")
    return vpm, mbpb, total, first, pos


def _parse_delta_runs(page: bytes, pos: int, end: int, out_base: int,
                      page_buf_off: int, runs: RunTable
                      ) -> Tuple[int, int, int]:
    """Parse DELTA_BINARY_PACKED block/miniblock HEADERS (the payload
    stays in the page bytes for the device): appends one run per
    miniblock with out_start in dense-lane coordinates (the lane of the
    miniblock's FIRST delta = out_base + 1 + delta_index), value =
    the block's min_delta, and the payload's absolute bit offset.
    Returns (first_value, total_count, stream_end_pos)."""
    vpm, mbpb, total, first, pos = _parse_delta_header(page, pos)
    remaining = total - 1
    di = 0
    while remaining > 0:
        if pos >= end:
            raise UnsupportedColumn("truncated delta stream")
        md, pos = _zigzag(page, pos)
        widths = page[pos:pos + mbpb]
        pos += mbpb
        for w in widths:
            if remaining <= 0:
                break
            if w > 64:
                raise UnsupportedColumn(f"delta bit width {w}")
            nv = min(vpm, remaining)
            runs.add(out_base + 1 + di, True, md,
                     (page_buf_off + pos) * 8, w)
            pos += vpm * w // 8
            di += nv
            remaining -= nv
    if pos > end:
        # a truncated last miniblock would otherwise point the device
        # kernel past this page into neighbor bytes — fall back instead
        raise UnsupportedColumn("delta stream overruns page")
    return first, total, pos


def _delta_decode_host(page: bytes, pos: int, end: int
                       ) -> Tuple[np.ndarray, int]:
    """Full host decode of one DELTA_BINARY_PACKED stream (used for
    DELTA_LENGTH_BYTE_ARRAY *lengths*, which the host needs anyway to
    size the static char matrix): vectorized per miniblock via
    unpackbits, wrap-around arithmetic in uint64. Returns
    (int64 values, stream_end_pos)."""
    vpm, mbpb, total, first, pos = _parse_delta_header(page, pos)
    first_u = np.uint64(first & 0xFFFFFFFFFFFFFFFF)
    if total <= 0:
        return np.zeros(0, dtype=np.int64), pos
    deltas = np.zeros(max(0, total - 1), dtype=np.uint64)
    remaining = total - 1
    di = 0
    shifts = {}
    while remaining > 0:
        if pos >= end:
            raise UnsupportedColumn("truncated delta stream")
        md, pos = _zigzag(page, pos)
        md_u = np.uint64(md & 0xFFFFFFFFFFFFFFFF)
        widths = page[pos:pos + mbpb]
        pos += mbpb
        for w in widths:
            if remaining <= 0:
                break
            if w > 64:
                raise UnsupportedColumn(f"delta bit width {w}")
            nv = min(vpm, remaining)
            nb = vpm * w // 8
            if w:
                bits = np.unpackbits(
                    np.frombuffer(page, dtype=np.uint8, offset=pos,
                                  count=nb), bitorder="little")
                if w not in shifts:
                    shifts[w] = np.arange(w, dtype=np.uint64)
                vals = (bits.reshape(vpm, w).astype(np.uint64)
                        << shifts[w]).sum(axis=1, dtype=np.uint64)
                deltas[di:di + nv] = vals[:nv] + md_u
            else:
                deltas[di:di + nv] = md_u
            pos += nb
            di += nv
            remaining -= nv
    out = np.empty(total, dtype=np.uint64)
    out[0] = first_u
    if total > 1:
        np.cumsum(deltas, out=out[1:])
        out[1:] += first_u
    return out.view(np.int64), pos


def _decode_dict_page(body: bytes, nvals: int, dt: T.DataType,
                      kind: str, leaf) -> Tuple[List[np.ndarray], int]:
    """PLAIN dictionary page -> host-decoded lookup arrays (dictionaries
    are bounded by the writer's dict-page limit, ~1MB, so host decode
    here is footer-scale work, not row-scale)."""
    if kind == "int":
        phys = leaf.physical_type
        np_in = np.int32 if phys == "INT32" else np.int64
        vals = np.frombuffer(body, dtype=np_in, count=nvals)
        return [vals.astype(np.int64)], 0
    if kind == "f32":
        raw = np.frombuffer(body, dtype=np.int32, count=nvals)
        return [raw.astype(np.int64)], 0
    if kind == "f64":
        return [np.frombuffer(body, dtype=np.int64, count=nvals).copy()], 0
    if kind in ("dec64", "dec128"):
        w = leaf.length
        b = np.frombuffer(body, dtype=np.uint8,
                          count=nvals * w).reshape(nvals, w)
        if kind == "dec64":
            acc = np.zeros(nvals, dtype=np.int64)
            for k in range(w):
                acc = (acc << 8) | b[:, k].astype(np.int64)
            if w < 8:
                acc -= (acc >> (8 * w - 1)) << (8 * w)
            return [acc], 0
        hi_w = w - 8
        hi = np.zeros(nvals, dtype=np.int64)
        for k in range(hi_w):
            hi = (hi << 8) | b[:, k].astype(np.int64)
        if hi_w < 8:
            hi -= (hi >> (8 * hi_w - 1)) << (8 * hi_w)
        lo = np.zeros(nvals, dtype=np.uint64)
        for k in range(hi_w, w):
            lo = (lo << np.uint64(8)) | b[:, k].astype(np.uint64)
        return [hi, lo.view(np.int64)], 0
    if kind == "str":
        from spark_rapids_tpu.columnar.device import bucket_char_cap
        vals: List[bytes] = []
        pos = 0
        max_len = 1
        for _ in range(nvals):
            ln = int.from_bytes(body[pos:pos + 4], "little")
            pos += 4
            vals.append(body[pos:pos + ln])
            pos += ln
            max_len = max(max_len, ln)
        char_cap = bucket_char_cap(max_len)
        chars = np.zeros((max(nvals, 1), char_cap), dtype=np.uint8)
        lengths = np.zeros(max(nvals, 1), dtype=np.int32)
        for i, v in enumerate(vals):
            chars[i, :len(v)] = np.frombuffer(v, dtype=np.uint8)
            lengths[i] = len(v)
        return [chars, lengths], char_cap
    raise UnsupportedColumn(f"dictionary for kind {kind}")


_ALL_FEATS = (True, True, True)  # (byteArray, delta, byteStreamSplit)


def _plan_column(raw: bytes, chunk, leaf, dt: T.DataType, n_rows: int,
                 packer, feats: Tuple[bool, bool, bool] = _ALL_FEATS
                 ) -> ColumnDevicePlan:
    """Walk one column chunk's pages, appending decompressed page bytes
    to ``packer`` and building the device plan. ``feats`` are the
    per-encoding enables (deviceDecode.byteArray/delta/byteStreamSplit
    confs) — a disabled encoding falls back per column."""
    _check_supported(dt, leaf)
    codec_name = _HOST_CODECS.get(chunk.compression, "?")
    if codec_name == "?":
        raise UnsupportedColumn(f"codec {chunk.compression}")
    kind, np_dt, elem_bytes = _kind_for(dt, leaf)
    max_def = leaf.max_definition_level
    feat_bytearray, feat_delta, feat_bss = feats

    start, end = 0, len(raw)  # raw is exactly the chunk's byte range

    plan = ColumnDevicePlan(dt, kind, np_dt, elem_bytes,
                            dl=RunTable(), vr=RunTable(), dr=RunTable())
    import pyarrow as pa
    codec = pa.Codec(codec_name) if codec_name else None

    rows = 0       # rows consumed (levels)
    dense = 0      # non-null values consumed
    n_dict = 0
    all_valid_runs = True
    str_parts: List[Tuple[int, np.ndarray]] = []  # (dense_off, lengths)
    pos = start
    while pos < end:
        hdr, body_off = parse_page_header(raw, pos)
        ptype = hdr.get(1)
        usize, csize = hdr.get(2, 0), hdr.get(3, 0)
        body = raw[body_off:body_off + csize]
        pos = body_off + csize
        if ptype == PAGE_INDEX:
            continue
        if ptype == PAGE_DICTIONARY:
            dph = hdr.get(7, {})
            if dph.get(2, ENC_PLAIN) not in (ENC_PLAIN,
                                             ENC_PLAIN_DICTIONARY):
                raise UnsupportedColumn("non-PLAIN dictionary page")
            if codec is not None:
                body = codec.decompress(body, usize).to_pybytes()
            n_dict = dph.get(1, 0)
            plan.dict_arrays, plan.char_cap = _decode_dict_page(
                body, n_dict, dt, kind, leaf)
            continue
        if ptype == PAGE_DATA:
            dph = hdr.get(5)
            if dph is None:
                raise UnsupportedColumn("data page without header")
            nv = dph.get(1, 0)
            enc = dph.get(2, ENC_PLAIN)
            if max_def and dph.get(3, ENC_RLE) != ENC_RLE:
                raise UnsupportedColumn("non-RLE definition levels")
            if codec is not None:
                body = codec.decompress(body, usize).to_pybytes()
            val_off = 0
            def_section = None
            if max_def:
                dl_len = int.from_bytes(body[0:4], "little")
                def_section = (4, 4 + dl_len)
                val_off = 4 + dl_len
        elif ptype == PAGE_DATA_V2:
            dph = hdr.get(8)
            if dph is None:
                raise UnsupportedColumn("v2 page without header")
            nv = dph.get(1, 0)
            enc = dph.get(4, ENC_PLAIN)
            rep_len = dph.get(6, 0)
            dl_len = dph.get(5, 0)
            if rep_len:
                raise UnsupportedColumn("v2 repetition levels")
            levels = body[:dl_len]
            values = body[dl_len:]
            if dph.get(7, True) and codec is not None:
                values = codec.decompress(
                    values, usize - dl_len).to_pybytes()
            body = levels + values
            def_section = (0, dl_len) if max_def else None
            val_off = dl_len
        else:
            raise UnsupportedColumn(f"page type {ptype}")

        if nv == 0:
            continue
        page_off = packer.add(np.frombuffer(body, dtype=np.uint8))

        # definition levels -> validity runs (+ per-page non-null count)
        nn = nv
        if def_section is not None:
            width = max_def.bit_length()
            dl_runs = RunTable()
            _, regions = _parse_hybrid_runs(
                body, def_section[0], def_section[1], width, nv,
                rows, page_off, dl_runs)
            nn = _popcount_regions(body, regions)
            for i in range(len(dl_runs)):
                plan.dl.add(dl_runs.out_start[i], dl_runs.packed[i],
                            dl_runs.value[i], dl_runs.bit_start[i],
                            dl_runs.width[i])
                if dl_runs.packed[i]:
                    all_valid_runs = False
                elif dl_runs.value[i] != max_def:
                    all_valid_runs = False
                else:
                    nxt = (dl_runs.out_start[i + 1]
                           if i + 1 < len(dl_runs) else rows + nv)
                    nn += nxt - dl_runs.out_start[i]

        # value section
        plan.pg_dense_start.append(dense)
        ename = _ENC_NAMES.get(enc, str(enc))
        plan.encoding_values[ename] = \
            plan.encoding_values.get(ename, 0) + nn
        plan.pg_first.append(0)
        if enc in (ENC_PLAIN_DICTIONARY, ENC_RLE_DICTIONARY):
            if not plan.dict_arrays:
                raise UnsupportedColumn("dictionary page missing")
            vw = body[val_off]
            if vw > 32:
                raise UnsupportedColumn(f"dict index width {vw}")
            _parse_hybrid_runs(body, val_off + 1, len(body), vw, nn,
                               dense, page_off, plan.vr)
            plan.pg_enc.append(PGE_DICT)
            plan.pg_plain_byte.append(-1)
        elif enc == ENC_PLAIN and kind == "str":
            if not feat_bytearray:
                raise UnsupportedColumn(
                    "PLAIN byte array (deviceDecode.byteArray disabled)")
            lens = _plain_str_lengths(body, val_off, len(body), nn)
            str_parts.append((dense, lens))
            plan.pg_enc.append(PGE_PLAIN_STR)
            plan.pg_plain_byte.append(page_off + val_off)
        elif enc == ENC_PLAIN and kind == "bool":
            # raw bit-packed values == one packed run of width 1
            plan.vr.add(dense, True, 0, (page_off + val_off) * 8, 1)
            plan.pg_enc.append(PGE_DICT)  # value comes from vr
            plan.pg_plain_byte.append(-1)
        elif enc == ENC_PLAIN:
            plan.has_plain = True
            plan.pg_enc.append(PGE_PLAIN)
            plan.pg_plain_byte.append(page_off + val_off)
        elif enc == ENC_RLE and kind == "bool":
            # v2 boolean pages: 4-byte length prefix then a hybrid
            # stream of width 1 — same device lane as PLAIN booleans
            _parse_hybrid_runs(body, val_off + 4, len(body), 1, nn,
                               dense, page_off, plan.vr)
            plan.pg_enc.append(PGE_DICT)
            plan.pg_plain_byte.append(-1)
        elif enc == ENC_DELTA_BINARY_PACKED and kind in ("int", "dec64") \
                and leaf.physical_type in ("INT32", "INT64"):
            if not feat_delta:
                raise UnsupportedColumn(
                    "DELTA_BINARY_PACKED (deviceDecode.delta disabled)")
            first, total, _ = _parse_delta_runs(
                body, val_off, len(body), dense, page_off, plan.dr)
            if total != nn:
                raise UnsupportedColumn(
                    f"delta count {total} != page values {nn}")
            plan.pg_first[-1] = first
            plan.has_delta = True
            plan.pg_enc.append(PGE_DELTA)
            plan.pg_plain_byte.append(-1)
        elif enc == ENC_DELTA_LENGTH_BYTE_ARRAY and kind == "str":
            if not (feat_bytearray and feat_delta):
                raise UnsupportedColumn(
                    "DELTA_LENGTH_BYTE_ARRAY (deviceDecode disabled)")
            lens, bytes_pos = _delta_decode_host(body, val_off,
                                                 len(body))
            if lens.shape[0] != nn:
                raise UnsupportedColumn(
                    f"delta-length count {lens.shape[0]} != {nn}")
            if lens.shape[0] and (int(lens.min()) < 0 or
                                  bytes_pos + int(lens.sum())
                                  > len(body)):
                raise UnsupportedColumn("delta-length bytes overrun")
            str_parts.append((dense, lens))
            plan.pg_enc.append(PGE_DL_STR)
            plan.pg_plain_byte.append(page_off + bytes_pos)
        elif enc == ENC_BYTE_STREAM_SPLIT and (
                kind in ("f32", "f64")
                or (kind == "int" and leaf.physical_type
                    in ("INT32", "INT64"))):
            if not feat_bss:
                raise UnsupportedColumn(
                    "BYTE_STREAM_SPLIT (deviceDecode.byteStreamSplit "
                    "disabled)")
            if val_off + nn * elem_bytes > len(body):
                raise UnsupportedColumn("BYTE_STREAM_SPLIT page overrun")
            plan.has_bss = True
            plan.pg_enc.append(PGE_BSS)
            plan.pg_plain_byte.append(page_off + val_off)
        else:
            raise UnsupportedColumn(
                f"encoding {_ENC_NAMES.get(enc, enc)} for {kind}")
        rows += nv
        dense += nn

    if rows != n_rows:
        raise UnsupportedColumn(
            f"page rows {rows} != row-group rows {n_rows}")
    plan.n_dense = dense
    plan.pg_dense_start.append(dense)
    if all_valid_runs or max_def == 0:
        plan.dl = None  # no nulls: validity is just the active mask
    if len(plan.vr) == 0:
        plan.vr = None
    if len(plan.dr) == 0:
        plan.dr = None
    if str_parts:
        # dense-lane byte lengths for the non-dict string pages; the
        # device builds offsets from these with a per-page (segmented)
        # prefix-sum and gathers the bytes column (SURVEY.md §7 c)
        lens = np.zeros(max(1, dense), dtype=np.int32)
        max_len = 1
        for off, part in str_parts:
            lens[off:off + part.shape[0]] = part
            if part.shape[0]:
                max_len = max(max_len, int(part.max()))
        plan.str_lens = lens
        from spark_rapids_tpu.columnar.device import bucket_char_cap
        plain_cap = bucket_char_cap(max_len)
        if plan.dict_arrays:
            if plain_cap > plan.char_cap:
                # unify the char matrix width across dict + plain pages
                ch = plan.dict_arrays[0]
                wide = np.zeros((ch.shape[0], plain_cap), dtype=ch.dtype)
                wide[:, :ch.shape[1]] = ch
                plan.dict_arrays[0] = wide
                plan.char_cap = plain_cap
        else:
            plan.char_cap = plain_cap
    if kind == "str" and plan.vr is None and plan.str_lens is None:
        raise UnsupportedColumn("string column with no value pages")
    return plan


def _feats_from_conf(conf) -> Tuple[bool, bool, bool]:
    if conf is None:
        return _ALL_FEATS
    from spark_rapids_tpu.conf import (PARQUET_DEVICE_DECODE_BYTE_ARRAY,
                                       PARQUET_DEVICE_DECODE_BSS,
                                       PARQUET_DEVICE_DECODE_DELTA)
    return (bool(conf.get(PARQUET_DEVICE_DECODE_BYTE_ARRAY)),
            bool(conf.get(PARQUET_DEVICE_DECODE_DELTA)),
            bool(conf.get(PARQUET_DEVICE_DECODE_BSS)))


def plan_unit_encoded(unit, data_schema: T.StructType, conf=None
                      ) -> Optional[EncodedBatch]:
    """Build the device-decode staging for one parquet ScanUnit (one
    row group). Columns whose chunk cannot be device-decoded fall back
    to the pyarrow host decode individually; returns None when nothing
    can be device-decoded (caller uses the plain host path)."""
    import pyarrow.parquet as pq
    from spark_rapids_tpu.columnar.transfer import _Packer
    from spark_rapids_tpu.io.arrow_convert import arrow_column_to_host
    feats = _feats_from_conf(conf)

    if not unit.row_groups or len(unit.row_groups) != 1:
        return None
    pf = pq.ParquetFile(unit.path)
    meta = pf.metadata
    rg = unit.row_groups[0]
    rgm = meta.row_group(rg)
    n_rows = rgm.num_rows
    if n_rows == 0:
        return None
    sch = pf.schema
    leaf_by_name = {}
    for i in range(len(sch)):
        c = sch.column(i)
        leaf_by_name.setdefault(c.path.split(".")[0], (i, c))
    chunk_by_leaf = {}
    for ci in range(rgm.num_columns):
        col = rgm.column(ci)
        chunk_by_leaf[col.path_in_schema.split(".")[0]] = col

    with open(unit.path, "rb") as f:

        def chunk_bytes(chunk) -> bytes:
            start = chunk.data_page_offset
            if chunk.dictionary_page_offset is not None:
                start = min(start, chunk.dictionary_page_offset)
            f.seek(start)
            return f.read(chunk.total_compressed_size)

        packer = _Packer()
        plans: Dict[int, ColumnDevicePlan] = {}
        host_cols: Dict[int, Any] = {}
        fallbacks: List[Tuple[str, str]] = []
        for fi, fld in enumerate(data_schema.fields):
            entry = leaf_by_name.get(fld.name)
            chunk = chunk_by_leaf.get(fld.name)
            if entry is None or chunk is None:
                fallbacks.append((fld.name, "column missing in file"))
                continue
            _li, leaf = entry
            try:
                raw = chunk_bytes(chunk)
                # per-column staging: a mid-chunk UnsupportedColumn
                # (e.g. dictionary overflow into PLAIN byte arrays)
                # must not leave this column's already-appended pages
                # as dead bytes in every uploaded batch
                sub = _Packer()
                plan = _plan_column(raw, chunk, leaf,
                                    fld.data_type, n_rows, sub, feats)
                _rebase_plan(plan, packer.off)
                packer.parts.extend(sub.parts)
                packer.off += sub.off
                plans[fi] = plan
            except UnsupportedColumn as e:
                fallbacks.append((fld.name, str(e)))
            except Exception as e:  # defensive: never fail the scan
                fallbacks.append((fld.name, f"decode-plan error: {e}"))

    if not plans:
        return None
    # host-decoded value counts per data encoding for the fallback
    # columns (regression visibility: a new fallback shows up in the
    # bench's hostDecodedValues split, not just a unit count)
    fallback_encodings: Dict[str, int] = {}
    for name, _reason in fallbacks:
        chunk = chunk_by_leaf.get(name)
        if chunk is None:
            continue
        # count each column's rows ONCE, under its dominant DATA
        # encoding: chunk.encodings also lists level encodings and the
        # dictionary page's own PLAIN, which would multi-count
        data_encs = [e for e in chunk.encodings
                     if e not in ("RLE", "BIT_PACKED")]
        dict_encs = [e for e in data_encs if "DICTIONARY" in e]
        ename = (dict_encs or data_encs or ["UNKNOWN"])[0]
        fallback_encodings[ename] = \
            fallback_encodings.get(ename, 0) + n_rows
    if fallbacks:
        names = [n for n, _r in fallbacks]
        present = [n for n in names if n in leaf_by_name]
        tbl = pf.read_row_groups([rg], columns=present) if present \
            else None
        for fi, fld in enumerate(data_schema.fields):
            if fi in plans:
                continue
            if tbl is not None and fld.name in tbl.column_names:
                host_cols[fi] = arrow_column_to_host(
                    tbl.column(fld.name), fld.data_type)
            else:
                from spark_rapids_tpu.columnar.host import HostColumn
                host_cols[fi] = _null_host_column(fld.data_type, n_rows)
    return EncodedBatch(data_schema, n_rows, packer.words(), plans,
                        host_cols, fallbacks, unit.path,
                        fallback_encodings=fallback_encodings)


def _rebase_plan(plan: ColumnDevicePlan, base: int) -> None:
    """Shift a plan built against a column-local buffer to its final
    byte offset in the shared packed buffer (base is 4-byte aligned:
    _Packer pads every add)."""
    for rt in (plan.dl, plan.vr, plan.dr):
        if rt is None:
            continue
        for i in range(len(rt)):
            if rt.packed[i]:
                rt.bit_start[i] += base * 8
    plan.pg_plain_byte = [b + base if b >= 0 else b
                          for b in plan.pg_plain_byte]


def _null_host_column(dt: T.DataType, n: int):
    from spark_rapids_tpu.columnar.host import HostColumn
    validity = np.zeros(n, dtype=bool)
    if T.is_limb_decimal(dt):
        return HostColumn(dt, np.zeros((n, 2), dtype=np.int64), validity)
    np_dt = T.numpy_dtype(dt)
    if np_dt == np.dtype(object):
        data = np.empty(n, dtype=object)
        data[:] = ""
        return HostColumn(dt, data, validity)
    return HostColumn(dt, np.zeros(n, dtype=np_dt), validity)
