"""DataFrameWriter: parquet/orc/csv/json writes with modes + partitionBy.

The reference writes columnar data with device encoders behind
GpuParquetFileFormat (411 LoC) / GpuOrcFileFormat and drives dynamic
partitioning sort-side (GpuFileFormatDataWriter, GpuDynamicPartitionDataWriter).
Here encode is Arrow on the host; the dynamic-partition write groups rows by
partition values before emitting one file per (task, partition-dir), matching
the reference's output layout (part-<task>-... files under k=v dirs).
"""

from __future__ import annotations

import os
import shutil
import uuid
from typing import Any, Dict, List, Optional

import numpy as np

from spark_rapids_tpu.columnar.host import HostBatch
from spark_rapids_tpu.io.arrow_convert import host_batch_to_arrow


class DataFrameWriter:
    def __init__(self, df):
        self._df = df
        self._format = "parquet"
        self._mode = "errorifexists"
        self._options: Dict[str, Any] = {}
        self._partition_by: List[str] = []

    def format(self, fmt: str) -> "DataFrameWriter":
        self._format = fmt.lower()
        return self

    def mode(self, m: str) -> "DataFrameWriter":
        m = m.lower()
        if m not in ("overwrite", "append", "ignore", "error",
                     "errorifexists"):
            raise ValueError(f"unknown save mode {m}")
        self._mode = m
        return self

    def option(self, key: str, value: Any) -> "DataFrameWriter":
        self._options[key] = value
        return self

    def options(self, **opts) -> "DataFrameWriter":
        self._options.update(opts)
        return self

    def partitionBy(self, *cols: str) -> "DataFrameWriter":
        self._partition_by = list(cols)
        return self

    def parquet(self, path: str) -> None:
        self.format("parquet").save(path)

    def orc(self, path: str) -> None:
        self.format("orc").save(path)

    def csv(self, path: str, header=None, sep=None) -> None:
        if header is not None:
            self.option("header", str(header).lower())
        if sep is not None:
            self.option("sep", sep)
        self.format("csv").save(path)

    def json(self, path: str) -> None:
        self.format("json").save(path)

    def save(self, path: str) -> None:
        if os.path.exists(path):
            if self._mode in ("error", "errorifexists"):
                raise FileExistsError(
                    f"path {path} already exists (mode=errorIfExists)")
            if self._mode == "ignore":
                return
            if self._mode == "overwrite":
                shutil.rmtree(path)
        os.makedirs(path, exist_ok=True)

        physical = self._df.session.plan_physical(self._df.plan)
        task_id = 0
        for thunk in physical.partitions():
            for batch in thunk():
                if batch.num_rows == 0:
                    continue
                self._write_batch(batch, path, task_id)
                task_id += 1
        # commit marker, Hadoop-committer style
        open(os.path.join(path, "_SUCCESS"), "w").close()

    # -- helpers -----------------------------------------------------------

    def _write_batch(self, batch: HostBatch, root: str, task_id: int) -> None:
        if not self._partition_by:
            self._write_file(batch, root, task_id)
            return
        # dynamic partitioning: group rows by partition tuple
        schema = batch.schema
        part_idx = [schema.field_index(c) for c in self._partition_by]
        data_fields = [i for i in range(batch.num_cols)
                       if i not in part_idx]
        keys = list(zip(*[batch.columns[i].to_pylist() for i in part_idx]))
        order: Dict[tuple, List[int]] = {}
        for row, k in enumerate(keys):
            order.setdefault(k, []).append(row)
        for k, rows in order.items():
            sub = batch.take(np.asarray(rows, dtype=np.int64))
            from spark_rapids_tpu.sql import types as T
            dschema = T.StructType([schema.fields[i] for i in data_fields])
            dcols = [sub.columns[i] for i in data_fields]
            dbatch = HostBatch(dschema, dcols, sub.num_rows)
            # partition values are URL-escaped like Spark's
            # PartitioningUtils.escapePathName so separators/specials
            # round-trip through the directory name
            from urllib.parse import quote
            subdir = os.path.join(root, *[
                f"{c}={'__HIVE_DEFAULT_PARTITION__' if v is None else quote(str(v), safe='')}"
                for c, v in zip(self._partition_by, k)])
            os.makedirs(subdir, exist_ok=True)
            self._write_file(dbatch, subdir, task_id)

    def _write_file(self, batch: HostBatch, directory: str,
                    task_id: int) -> None:
        ext = {"parquet": "parquet", "orc": "orc", "csv": "csv",
               "json": "json"}[self._format]
        name = f"part-{task_id:05d}-{uuid.uuid4().hex[:12]}.{ext}"
        fpath = os.path.join(directory, name)
        tbl = host_batch_to_arrow(batch)
        if self._format == "parquet":
            import pyarrow.parquet as pq
            codec = str(self._options.get("compression", "snappy"))
            pq.write_table(tbl, fpath, compression=codec)
        elif self._format == "orc":
            import pyarrow.orc as po
            po.write_table(tbl, fpath)
        elif self._format == "csv":
            import pyarrow.csv as pc
            header = str(self._options.get("header",
                                           "false")).lower() == "true"
            sep = str(self._options.get("sep", ","))
            pc.write_csv(tbl, fpath, write_options=pc.WriteOptions(
                include_header=header, delimiter=sep))
        elif self._format == "json":
            import json as _json
            with open(fpath, "w") as f:
                for row in tbl.to_pylist():
                    f.write(_json.dumps(row, default=str) + "\n")
        else:
            raise NotImplementedError(self._format)
