"""Arrow <-> HostBatch conversion and type mapping.

The JVM<->device interchange format of the reference is Arrow-shaped
(GpuColumnVector.java wraps Arrow-layout cuDF buffers;
AccessibleArrowColumnVector reads Spark's Arrow cache). Here Arrow is the
host interchange for file formats and the pandas-UDF path.
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np
import pyarrow as pa

from spark_rapids_tpu.columnar.host import HostBatch, HostColumn
from spark_rapids_tpu.sql import types as T


def arrow_type_to_sql(at: pa.DataType) -> T.DataType:
    if pa.types.is_boolean(at):
        return T.BooleanT
    if pa.types.is_int8(at):
        return T.ByteT
    if pa.types.is_int16(at):
        return T.ShortT
    if pa.types.is_int32(at):
        return T.IntegerT
    if pa.types.is_int64(at):
        return T.LongT
    if pa.types.is_float32(at):
        return T.FloatT
    if pa.types.is_float64(at):
        return T.DoubleT
    if pa.types.is_string(at) or pa.types.is_large_string(at):
        return T.StringT
    if pa.types.is_binary(at) or pa.types.is_large_binary(at):
        return T.BinaryT
    if pa.types.is_date32(at):
        return T.DateT
    if pa.types.is_timestamp(at):
        return T.TimestampT
    if pa.types.is_decimal(at):
        return T.DecimalType(at.precision, at.scale)
    # unsigned ints land in the next-wider signed type (Spark has none)
    if pa.types.is_uint8(at):
        return T.ShortT
    if pa.types.is_uint16(at):
        return T.IntegerT
    if pa.types.is_uint32(at) or pa.types.is_uint64(at):
        return T.LongT
    if pa.types.is_list(at) or pa.types.is_large_list(at):
        return T.ArrayType(arrow_type_to_sql(at.value_type))
    if pa.types.is_struct(at):
        return T.StructType([
            T.StructField(at.field(i).name,
                          arrow_type_to_sql(at.field(i).type),
                          at.field(i).nullable)
            for i in range(at.num_fields)])
    raise TypeError(f"unsupported arrow type {at}")


def sql_type_to_arrow(dt: T.DataType) -> pa.DataType:
    if isinstance(dt, T.BooleanType):
        return pa.bool_()
    if isinstance(dt, T.ByteType):
        return pa.int8()
    if isinstance(dt, T.ShortType):
        return pa.int16()
    if isinstance(dt, T.IntegerType):
        return pa.int32()
    if isinstance(dt, T.LongType):
        return pa.int64()
    if isinstance(dt, T.FloatType):
        return pa.float32()
    if isinstance(dt, T.DoubleType):
        return pa.float64()
    if isinstance(dt, T.StringType):
        return pa.string()
    if isinstance(dt, T.BinaryType):
        return pa.binary()
    if isinstance(dt, T.DateType):
        return pa.date32()
    if isinstance(dt, T.TimestampType):
        return pa.timestamp("us", tz="UTC")
    if isinstance(dt, T.DecimalType):
        return pa.decimal128(dt.precision, dt.scale)
    if isinstance(dt, T.ArrayType):
        return pa.list_(sql_type_to_arrow(dt.element_type))
    if isinstance(dt, T.StructType):
        return pa.struct([
            pa.field(f.name, sql_type_to_arrow(f.data_type), f.nullable)
            for f in dt.fields])
    raise TypeError(f"unsupported sql type {dt}")


def arrow_schema_to_sql(schema: pa.Schema) -> T.StructType:
    return T.StructType([
        T.StructField(f.name, arrow_type_to_sql(f.type), f.nullable)
        for f in schema])


def sql_schema_to_arrow(schema: T.StructType) -> pa.Schema:
    return pa.schema([
        pa.field(f.name, sql_type_to_arrow(f.data_type), f.nullable)
        for f in schema.fields])


def _fill_for(dt: T.DataType):
    if isinstance(dt, T.BooleanType):
        return False
    if isinstance(dt, (T.FloatType, T.DoubleType)):
        return 0.0
    return 0


def _string_varbytes(arr: pa.Array):
    """Compact (utf8_bytes, raw_lengths) view of an Arrow string/binary
    array for the upload codec (HostColumn.varbytes). ``raw_lengths``
    are the unmasked offset deltas — their cumsum reproduces the byte
    starts exactly (null slots may own bytes; the decode program masks
    OUTPUT lengths with validity, not the starts)."""
    try:
        if not (pa.types.is_string(arr.type) or pa.types.is_binary(arr.type)
                or pa.types.is_large_string(arr.type)
                or pa.types.is_large_binary(arr.type)):
            return None
        n = len(arr)
        if n == 0:
            return None
        wide = (pa.types.is_large_string(arr.type)
                or pa.types.is_large_binary(arr.type))
        obuf = arr.buffers()[1]
        offs = np.frombuffer(obuf, dtype=np.int64 if wide else np.int32,
                             count=arr.offset + n + 1)[arr.offset:]
        dbuf = arr.buffers()[2]
        if dbuf is None:
            return None
        lengths = np.diff(offs).astype(np.int32)
        raw = np.frombuffer(dbuf, dtype=np.uint8, count=int(offs[-1]))
        return np.ascontiguousarray(raw[int(offs[0]):]), lengths
    except Exception:
        return None


def arrow_column_to_host(arr: pa.ChunkedArray | pa.Array,
                         dt: T.DataType) -> HostColumn:
    if isinstance(arr, pa.ChunkedArray):
        arr = arr.combine_chunks()
    n = len(arr)
    if arr.null_count:
        validity = np.asarray(arr.is_valid())
    else:
        validity = np.ones(n, dtype=bool)
    if isinstance(dt, T.DecimalType):
        # vectorized: decimal128 buffers ARE 16-byte little-endian
        # two's-complement ints — view them as (lo, hi) int64 limb
        # pairs (the engine's unscaled storage) with no per-row loop
        a = arr
        want = pa.decimal128(dt.precision, dt.scale)
        if a.type != want:
            a = a.cast(want)
        buf = a.buffers()[1]
        raw = np.frombuffer(buf, dtype=np.int64,
                            count=2 * (a.offset + n))[2 * a.offset:]
        lo = raw[0::2].copy()
        hi = raw[1::2].copy()
        if arr.null_count:
            lo[~validity] = 0
            hi[~validity] = 0
        if T.is_limb_decimal(dt):
            return HostColumn(dt, np.stack([hi, lo], axis=1), validity)
        return HostColumn(dt, lo, validity)
    np_dt = T.numpy_dtype(dt)
    if isinstance(dt, T.StructType):
        # recurse per field, then zip into storage tuples
        from spark_rapids_tpu.columnar.host import struct_storage_rows
        fields = [arrow_column_to_host(arr.field(i), f.data_type)
                  for i, f in enumerate(dt.fields)]
        return HostColumn(dt, struct_storage_rows(fields, validity),
                          validity)
    if isinstance(dt, T.ArrayType):
        la = arr
        if pa.types.is_large_list(la.type):
            la = la.cast(pa.list_(la.type.value_type))
        offsets = np.asarray(la.offsets, dtype=np.int64)
        child = arrow_column_to_host(la.values, dt.element_type)
        child_py = [None if not child.validity[i]
                    else (child.data[i].item()
                          if isinstance(child.data[i], np.generic)
                          else child.data[i])
                    for i in range(len(child.data))]
        data = np.empty(n, dtype=object)
        for i in range(n):
            if validity[i]:
                data[i] = tuple(child_py[offsets[i]:offsets[i + 1]])
            else:
                data[i] = ()
        return HostColumn(dt, data, validity)
    if np_dt == np.dtype(object):
        # to_numpy is ~70x faster than a to_pylist loop at SF1 scale
        data = arr.to_numpy(zero_copy_only=False)
        if arr.null_count:
            data = data.copy()
            data[~validity] = ""
        return HostColumn(dt, data, validity,
                          _string_varbytes(arr))
    if isinstance(dt, T.TimestampType):
        arr = arr.cast(pa.timestamp("us"))
        data = np.asarray(arr.cast(pa.int64()).fill_null(0),
                          dtype=np.int64)
        return HostColumn(dt, data, validity)
    if isinstance(dt, T.DateType):
        data = np.asarray(arr.cast(pa.int32()).fill_null(0), dtype=np.int32)
        return HostColumn(dt, data, validity)
    arr = arr.cast(sql_type_to_arrow(dt))
    if arr.null_count:
        arr = arr.fill_null(_fill_for(dt))
    data = np.ascontiguousarray(np.asarray(arr), dtype=np_dt)
    return HostColumn(dt, data, validity)


def arrow_to_host_batch(table: pa.Table,
                        schema: Optional[T.StructType] = None) -> HostBatch:
    if schema is None:
        schema = arrow_schema_to_sql(table.schema)
    cols: List[HostColumn] = []
    for i, f in enumerate(schema.fields):
        cols.append(arrow_column_to_host(table.column(i), f.data_type))
    return HostBatch(schema, cols, table.num_rows)


def host_column_to_arrow(c: HostColumn) -> pa.Array:
    dt = c.dtype
    at = sql_type_to_arrow(dt)
    mask = None if c.validity.all() else ~c.validity
    if isinstance(dt, (T.StringType, T.BinaryType)):
        vals = [v if ok else None
                for v, ok in zip(c.data.tolist(), c.validity.tolist())]
        return pa.array(vals, type=at)
    if isinstance(dt, T.ArrayType):
        # elements are storage-form; build the child through the scalar
        # path and assemble a ListArray from offsets
        et = dt.element_type
        offsets = np.zeros(len(c.data) + 1, dtype=np.int32)
        elems: list = []
        for i, (v, ok) in enumerate(zip(c.data.tolist(),
                                        c.validity.tolist())):
            if ok:
                elems.extend(v)
            offsets[i + 1] = len(elems)
        ev = np.array([x is not None for x in elems], dtype=bool)
        np_et = T.numpy_dtype(et)
        if np_et == np.dtype(object):
            ed = np.empty(len(elems), dtype=object)
            for i, x in enumerate(elems):
                ed[i] = x if x is not None else ""
        else:
            ed = np.array([0 if x is None else x for x in elems],
                          dtype=np_et)
        child = host_column_to_arrow(HostColumn(et, ed, ev))
        mask = None if c.validity.all() else ~c.validity
        return pa.ListArray.from_arrays(
            pa.array(offsets, type=pa.int32()), child,
            mask=pa.array(mask) if mask is not None else None)
    if isinstance(dt, T.DecimalType):
        # limbs/int64 -> raw 16-byte decimal128 buffer, no per-row loop
        if T.is_limb_decimal(dt):
            hi = np.ascontiguousarray(c.data[:, 0])
            lo = np.ascontiguousarray(c.data[:, 1])
        else:
            lo = c.data.astype(np.int64)
            hi = lo >> np.int64(63)  # sign extension
        pairs = np.empty((len(lo), 2), dtype=np.int64)
        pairs[:, 0] = lo
        pairs[:, 1] = hi
        buf = pa.py_buffer(np.ascontiguousarray(pairs).tobytes())
        if mask is not None:
            vbits = pa.array(~np.asarray(mask), type=pa.bool_()) \
                .buffers()[1]
            return pa.Array.from_buffers(at, len(lo), [vbits, buf],
                                         null_count=int(mask.sum()))
        return pa.Array.from_buffers(at, len(lo), [None, buf])
    if isinstance(dt, T.StructType):
        from spark_rapids_tpu.columnar.host import struct_field_values
        from spark_rapids_tpu.columnar.transfer import \
            _col_from_storage_values
        fields = [host_column_to_arrow(_col_from_storage_values(
            struct_field_values(c, fi), f.data_type))
            for fi, f in enumerate(dt.fields)]
        if mask is not None:
            return pa.StructArray.from_arrays(
                fields, names=[f.name for f in dt.fields],
                mask=pa.array(mask))
        return pa.StructArray.from_arrays(
            fields, names=[f.name for f in dt.fields])
    if isinstance(dt, T.TimestampType):
        a = pa.array(c.data.astype(np.int64), type=pa.int64(), mask=mask)
        return a.cast(at)
    if isinstance(dt, T.DateType):
        a = pa.array(c.data.astype(np.int32), type=pa.int32(), mask=mask)
        return a.cast(at)
    return pa.array(c.data, type=at, mask=mask)


def host_batch_to_arrow(b: HostBatch) -> pa.Table:
    arrays = [host_column_to_arrow(c) for c in b.columns]
    return pa.Table.from_arrays(
        arrays, schema=sql_schema_to_arrow(b.schema))
