"""Qualification + profiling tools (the reference's `tools` module:
qualification — "how much of this workload would accelerate" — and
profiling — per-operator metrics after a run; user-facing-tools/
spark-qualification-tool.md is the shape being mirrored).

API:
  qualify(session, df)       -> QualificationReport
  qualify_sql(session, sql)  -> QualificationReport
  profile(session, df)       -> ProfileReport (runs the query)

CLI:
  python -m spark_rapids_tpu.tools qualify "SELECT ..." --view name=path
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Tuple


@dataclass
class QualificationReport:
    """Per-operator device placement + fallback reasons."""

    device_ops: List[str] = field(default_factory=list)
    cpu_ops: List[Tuple[str, List[str]]] = field(default_factory=list)
    plan_string: str = ""

    @property
    def op_coverage(self) -> float:
        total = len(self.device_ops) + len(self.cpu_ops)
        return (len(self.device_ops) / total) if total else 1.0

    def format(self) -> str:
        lines = ["=== TPU Qualification Report ===",
                 f"operator coverage: {self.op_coverage:.0%} "
                 f"({len(self.device_ops)} on TPU, "
                 f"{len(self.cpu_ops)} on CPU)", ""]
        if self.device_ops:
            lines.append("runs on TPU:")
            lines += [f"  + {o}" for o in self.device_ops]
        if self.cpu_ops:
            lines.append("stays on CPU:")
            for name, reasons in self.cpu_ops:
                lines.append(f"  - {name}")
                lines += [f"      because {r}" for r in reasons]
        lines += ["", "physical plan:", self.plan_string]
        return "\n".join(lines)


def qualify(session, df) -> QualificationReport:
    """Rewrite the plan (without executing) and report placement —
    the qualification tool's core signal."""
    from spark_rapids_tpu.exec.base import TpuExec
    physical = session.plan_physical(df.plan)
    report = QualificationReport(
        plan_string=session.explain_string(df.plan, physical=physical))
    rewrite = session.last_rewrite_report
    if rewrite is not None:
        for name, reasons in rewrite.fallbacks:
            report.cpu_ops.append((name, list(reasons)))

    def walk(p):
        if isinstance(p, TpuExec):
            report.device_ops.append(p.simple_string().split()[0])
        # constituents of a fused stage, SHALLOW (their child links
        # point back into the chain)
        for op in getattr(p, "fused_ops", []):
            report.device_ops.append(op.simple_string().split()[0])
        for c in p.children:
            walk(c)
    walk(physical)
    return report


def qualify_sql(session, sql: str) -> QualificationReport:
    return qualify(session, session.sql(sql))


@dataclass
class ProfileReport:
    """Executed-query metrics per operator (profiling tool)."""

    rows: int = 0
    operators: List[Tuple[str, Dict[str, int]]] = field(
        default_factory=list)

    def format(self) -> str:
        lines = ["=== TPU Profile Report ===", f"output rows: {self.rows}"]
        for name, metrics in self.operators:
            lines.append(f"  {name}")
            for k, v in sorted(metrics.items()):
                lines.append(f"      {k}: {v}")
        return "\n".join(lines)


def profile(session, df) -> ProfileReport:
    """Execute the query and collect every device operator's metric
    registry (the write-only metrics VERDICT round 1 flagged — this is
    where they surface)."""
    from spark_rapids_tpu.exec.base import TpuExec
    physical = session.plan_physical(df.plan)
    result = physical.execute_collect()
    out = ProfileReport(rows=result.num_rows)

    def visit(p):
        vals = {name: m.value
                for name, m in p.metrics.metrics.items() if m.value}
        out.operators.append((p.simple_string().split()[0], vals))

    def walk(p):
        if isinstance(p, TpuExec):
            visit(p)
        # constituents of a fused stage keep their own metric
        # registries (the fan-back contract, docs/fusion.md) — visited
        # SHALLOW, their child links point back into the chain
        for op in getattr(p, "fused_ops", []):
            visit(op)
        for c in p.children:
            walk(c)
    walk(physical)
    return out


# -- offline (event-log) tools ---------------------------------------------
# (Qualification.scala:34 / Profiler.scala:31 roles: score and profile a
# PAST workload from its logs, no live session required)

def qualify_log(log_path: str) -> str:
    """Score logged queries for device suitability: per-query operator
    coverage + a histogram of fallback reasons."""
    from spark_rapids_tpu.event_log import read_events
    lines = ["=== TPU Qualification Report (offline) ===",
             f"log: {log_path}", ""]
    reason_counts: Dict[str, int] = {}
    n_q = 0
    covs: List[float] = []
    for ev in read_events(log_path):
        if ev.get("event") != "queryCompleted":
            continue
        n_q += 1
        ops = ev.get("ops", [])
        rated = [o for o in ops
                 if not o["op"].startswith(("TpuRowToColumnar",
                                            "TpuColumnarToRow"))]
        dev = sum(1 for o in rated if o.get("device"))
        total = len(rated) or 1
        cov = dev / total
        covs.append(cov)
        lines.append(f"query {ev.get('queryId')}: "
                     f"{cov:.0%} of operators on TPU, "
                     f"{ev.get('wallSeconds', 0):.3f}s, "
                     f"{ev.get('outputRows', 0)} rows")
        for fb in ev.get("fallbacks", []):
            for r in fb.get("reasons", []):
                reason_counts[r] = reason_counts.get(r, 0) + 1
    if not n_q:
        lines.append("no queryCompleted events found")
        return "\n".join(lines)
    score = sum(covs) / len(covs)
    lines += ["", f"queries: {n_q}",
              f"mean operator coverage: {score:.0%}",
              ("recommendation: ACCELERATE" if score >= 0.5 else
               "recommendation: investigate fallbacks first")]
    if reason_counts:
        lines += ["", "fallback reasons (by frequency):"]
        for r, c in sorted(reason_counts.items(), key=lambda kv: -kv[1]):
            lines.append(f"  {c:4d}x {r}")
    return "\n".join(lines)


def profile_log(log_path: str) -> str:
    """Aggregate per-operator metrics + a text timeline across logged
    queries (GenerateTimeline.scala's role, in text)."""
    from spark_rapids_tpu.event_log import read_events
    lines = ["=== TPU Profile Report (offline) ===",
             f"log: {log_path}", ""]
    op_metrics: Dict[str, Dict[str, int]] = {}
    events = [ev for ev in read_events(log_path)
              if ev.get("event") == "queryCompleted"]
    if not events:
        lines.append("no queryCompleted events found")
        return "\n".join(lines)
    t0 = min(ev["ts"] - ev.get("wallSeconds", 0) for ev in events)
    span = max(max(ev["ts"] for ev in events) - t0, 1e-9)
    lines.append("timeline (each bar spans the query's wall time):")
    width = 50
    for ev in events:
        start = ev["ts"] - ev.get("wallSeconds", 0) - t0
        dur = ev.get("wallSeconds", 0)
        a = int(start / span * width)
        b = max(a + 1, int((start + dur) / span * width))
        bar = " " * a + "#" * (b - a)
        lines.append(f"  q{ev.get('queryId'):>3} |{bar:<{width}}| "
                     f"{dur:.3f}s")
        for o in ev.get("ops", []):
            for k, v in o.get("metrics", {}).items():
                d = op_metrics.setdefault(o["op"], {})
                d[k] = d.get(k, 0) + v
        st = ev.get("storeStats")
        if st and st.get("spillCount"):
            lines.append(f"       spills: {st['spillCount']} "
                         f"({st.get('spilledDeviceBytes', 0)} bytes)")
    lines += ["", "aggregate operator metrics:"]
    for op, ms in sorted(op_metrics.items()):
        lines.append(f"  {op}")
        for k, v in sorted(ms.items()):
            lines.append(f"      {k}: {v}")
    return "\n".join(lines)


# -- offline trace analysis -------------------------------------------------
# (the span-trace half of the profiling tool: critical path, exclusive
# self-time, per-chip occupancy over one query's Chrome-trace file —
# docs/observability.md explains how to read each section)

def _trace_bounds(spans: List[dict]) -> Tuple[float, float]:
    t0 = min(s["t0"] for s in spans)
    t1 = max(s["t1"] for s in spans)
    return t0, max(t1, t0 + 1e-9)


def critical_path(spans: List[dict]) -> Tuple[Dict[str, float], float]:
    """Backward walk from the last span end to the first span start: at
    each point the *most immediate* covering span (the one with the
    latest start) owns the segment; where nothing covers, the gap is
    idle. Returns (microseconds attributed per span name, idle us) —
    the chain of work that determined the query wall, so shrinking
    anything NOT on it cannot speed the query up."""
    if not spans:
        return {}, 0.0
    import heapq
    t_begin, t_end = _trace_bounds(spans)
    desc = sorted(spans, key=lambda s: -s["t1"])
    attr: Dict[str, float] = {}
    idle = 0.0
    heap: List[Tuple[float, int]] = []  # (-t0, index into desc)
    i = 0
    cur = t_end
    while cur > t_begin + 1e-9:
        while i < len(desc) and desc[i]["t1"] >= cur - 1e-9:
            heapq.heappush(heap, (-desc[i]["t0"], i))
            i += 1
        # a span whose t0 >= cur can never cover this or any smaller cur
        while heap and -heap[0][0] >= cur - 1e-9:
            heapq.heappop(heap)
        if heap:
            neg_t0, idx = heap[0]
            s = desc[idx]
            seg_start = max(-neg_t0, t_begin)
            attr[s["name"]] = attr.get(s["name"], 0.0) + (cur - seg_start)
            cur = seg_start
        elif i < len(desc):
            nxt = min(cur, max(desc[i]["t1"], t_begin))
            idle += cur - nxt
            cur = nxt
        else:
            idle += cur - t_begin
            cur = t_begin
    return attr, idle


def exclusive_times(spans: List[dict]) -> Dict[str, Dict[str, float]]:
    """Per span name: count, total us, and EXCLUSIVE us (total minus
    directly nested child spans on the same lane). This undoes
    double counting at the reporting layer — e.g. the ``retryBlock``
    span nested inside an operator's timer span is subtracted from the
    operator's self-time, fixing the documented retryBlockTime-inside-
    opTime overlap (docs/robustness.md)."""
    out: Dict[str, Dict[str, float]] = {}
    by_tid: Dict[int, List[dict]] = {}
    for s in spans:
        by_tid.setdefault(s["tid"], []).append(s)
    for ss in by_tid.values():
        ss.sort(key=lambda s: (s["t0"], -(s["t1"] - s["t0"])))
        stack: List[dict] = []
        for s in ss:
            s["_child"] = 0.0
            while stack and stack[-1]["t1"] <= s["t0"] + 1e-9:
                stack.pop()
            if stack:
                stack[-1]["_child"] += s["t1"] - s["t0"]
            stack.append(s)
        for s in ss:
            d = out.setdefault(s["name"],
                               {"count": 0, "total": 0.0,
                                "exclusive": 0.0})
            d["count"] += 1
            dur = s["t1"] - s["t0"]
            d["total"] += dur
            d["exclusive"] += max(0.0, dur - s.pop("_child"))
    return out


def chip_occupancy(spans: List[dict]) -> Dict[int, Dict]:
    """Busy/idle per chip from chip-attributed spans (uploads,
    dispatches): merged busy intervals, occupancy over the trace
    window, and the top idle gaps (mesh skew shows up here)."""
    t_begin, t_end = _trace_bounds(spans) if spans else (0.0, 1.0)
    per: Dict[int, List[Tuple[float, float]]] = {}
    for s in spans:
        chip = s.get("args", {}).get("chip")
        if chip is not None:
            per.setdefault(int(chip), []).append((s["t0"], s["t1"]))
    out: Dict[int, Dict] = {}
    for chip, ivs in sorted(per.items()):
        ivs.sort()
        merged: List[List[float]] = []
        for a, b in ivs:
            if merged and a <= merged[-1][1]:
                merged[-1][1] = max(merged[-1][1], b)
            else:
                merged.append([a, b])
        busy = sum(b - a for a, b in merged)
        gaps = []
        prev = t_begin
        for a, b in merged:
            if a > prev:
                gaps.append((prev, a - prev))
            prev = max(prev, b)
        if t_end > prev:
            gaps.append((prev, t_end - prev))
        gaps.sort(key=lambda g: -g[1])
        out[chip] = {
            "busy_us": round(busy, 1),
            "occupancy": round(busy / (t_end - t_begin), 4),
            "dispatches": len(ivs),
            "topIdleGaps_us": [round(g[1], 1) for g in gaps[:3]],
        }
    return out


def top_spans(spans: List[dict], n: int = 10) -> List[dict]:
    ranked = sorted(spans, key=lambda s: -(s["t1"] - s["t0"]))[:n]
    return [{"name": s["name"], "dur_us": round(s["t1"] - s["t0"], 1),
             "t0_us": round(s["t0"], 1), "tid": s["tid"],
             "args": s.get("args", {})} for s in ranked]


def analyze_trace(path: str) -> Dict:
    """Machine-readable analysis of one trace file (bench detail.trace
    consumes this)."""
    from spark_rapids_tpu.trace import load_trace
    tr = load_trace(path)
    spans = tr["spans"]
    out: Dict = {"file": path, "meta": tr["meta"],
                 "spanCount": len(spans),
                 "instantCount": len(tr["instants"])}
    if not spans:
        return out
    cp, idle = critical_path(spans)
    total = sum(cp.values()) + idle
    out["criticalPath_s"] = {
        k: round(v / 1e6, 4)
        for k, v in sorted(cp.items(), key=lambda kv: -kv[1])}
    out["criticalPathIdle_s"] = round(idle / 1e6, 4)
    out["criticalPathSpan_s"] = round(total / 1e6, 4)
    out["occupancy"] = chip_occupancy(spans)
    out["topSpans"] = top_spans(spans, 5)
    return out


def format_trace_report(path: str, top: int = 10) -> str:
    """Human-readable trace report (the `tools trace` CLI output)."""
    from spark_rapids_tpu.trace import load_trace
    tr = load_trace(path)
    spans, instants, meta = tr["spans"], tr["instants"], tr["meta"]
    lines = ["=== TPU Trace Report ===", f"trace: {path}",
             f"query {meta.get('queryId')}: "
             f"{meta.get('wallSeconds', 0):.3f}s wall, "
             f"{meta.get('outputRows', 0)} rows, "
             f"{len(spans)} spans, {len(instants)} markers", ""]
    if not spans:
        lines.append("no spans recorded")
        return "\n".join(lines)
    t_begin, t_end = _trace_bounds(spans)
    window = t_end - t_begin
    cp, idle = critical_path(spans)
    lines.append(f"critical path ({window / 1e6:.3f}s traced window):")
    for name, us in sorted(cp.items(), key=lambda kv: -kv[1]):
        lines.append(f"  {us / 1e6:8.3f}s  {us / window:5.1%}  {name}")
    lines.append(f"  {idle / 1e6:8.3f}s  {idle / window:5.1%}  (idle)")
    lines += ["", "exclusive self-time per operator (retry/compile "
              "blocks subtracted from their enclosing spans):"]
    excl = exclusive_times(spans)
    ranked = sorted(excl.items(), key=lambda kv: -kv[1]["exclusive"])
    lines.append(f"  {'span':44s} {'count':>6s} {'total_s':>9s} "
                 f"{'self_s':>9s}")
    for name, d in ranked[:top]:
        lines.append(f"  {name:44s} {d['count']:6d} "
                     f"{d['total'] / 1e6:9.3f} "
                     f"{d['exclusive'] / 1e6:9.3f}")
    occ = chip_occupancy(spans)
    lines += ["", "per-chip occupancy (chip-attributed spans over the "
              "traced window):"]
    if occ:
        for chip, d in occ.items():
            gaps = ", ".join(f"{g / 1e3:.1f}ms"
                             for g in d["topIdleGaps_us"]) or "-"
            lines.append(f"  chip {chip}: {d['occupancy']:6.1%} busy, "
                         f"{d['dispatches']} dispatches, "
                         f"top idle gaps: {gaps}")
    else:
        lines.append("  (no chip-attributed spans)")
    lines += ["", f"top {top} slowest spans:"]
    for s in top_spans(spans, top):
        extra = ""
        if s["args"]:
            extra = "  " + ", ".join(
                f"{k}={v}" for k, v in sorted(s["args"].items()))
        lines.append(f"  {s['dur_us'] / 1e3:9.1f}ms  {s['name']}{extra}")
    if instants:
        counts: Dict[str, int] = {}
        for ins in instants:
            counts[ins["name"]] = counts.get(ins["name"], 0) + 1
        lines += ["", "instant markers:"]
        for name, c in sorted(counts.items(), key=lambda kv: -kv[1]):
            lines.append(f"  {c:5d}x {name}")
    return "\n".join(lines)


def hotspots_report(paths: List[str], top: int = 20) -> str:
    """Rank EXCLUSIVE self-time per span name across a whole trace
    directory (the `tools hotspots` CLI): the picker for the NEXT
    Pallas kernel target (docs/kernels.md) — a span family's summed
    self-time across queries is the ceiling on what hand-writing that
    loop can save. Kernel dispatches are split out per (kernel, shape
    bucket) (`kernelDispatch[<name>@<bucket>]`) so kernel vs oracle
    time is attributable per capacity class, and dispatches that ran
    on default parameters are flagged `(untuned)` — the autotuner's
    remaining targets."""
    from spark_rapids_tpu.trace import load_trace
    agg: Dict[str, Dict[str, float]] = {}
    window = 0.0
    for fp in paths:
        tr = load_trace(fp)
        spans = tr["spans"]
        if not spans:
            continue
        t0, t1 = _trace_bounds(spans)
        window += t1 - t0

        def _name(s) -> str:
            a = s.get("args", {})
            k = a.get("kernel")
            if k and s["name"] in ("kernelDispatch",
                                   "TpuHashAggregateExec.dispatch"):
                b = a.get("bucket")
                bucket = f"@{b}" if b is not None else ""
                flag = (" (untuned)"
                        if "tuned" in a and not a["tuned"] else "")
                return f"{s['name']}[{k}{bucket}]{flag}"
            return s["name"]

        for name, d in exclusive_times(
                [dict(s, name=_name(s)) for s in spans]).items():
            e = agg.setdefault(name, {"count": 0, "total": 0.0,
                                      "exclusive": 0.0})
            e["count"] += d["count"]
            e["total"] += d["total"]
            e["exclusive"] += d["exclusive"]
    lines = ["=== TPU Hotspot Report ===",
             f"{len(paths)} trace file(s), "
             f"{window / 1e6:.3f}s summed traced window", "",
             "exclusive self-time per span family (the next kernel "
             "targets — docs/kernels.md):", ""]
    if not agg:
        lines.append("no spans recorded")
        return "\n".join(lines)
    ranked = sorted(agg.items(), key=lambda kv: -kv[1]["exclusive"])
    lines.append(f"  {'span':44s} {'count':>7s} {'total_s':>9s} "
                 f"{'self_s':>9s} {'self%':>6s}")
    for name, d in ranked[:top]:
        pct = d["exclusive"] / window if window else 0.0
        lines.append(f"  {name:44s} {d['count']:7d} "
                     f"{d['total'] / 1e6:9.3f} "
                     f"{d['exclusive'] / 1e6:9.3f} {pct:6.1%}")
    return "\n".join(lines)


def _main(argv: List[str]) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        prog="spark_rapids_tpu.tools",
        description="TPU qualification/profiling tools")
    ap.add_argument("command",
                    choices=["qualify", "profile", "docs", "trace",
                             "hotspots", "serve", "serve-client",
                             "lint", "top", "bench-diff", "soak",
                             "history", "doctor", "tuning"])
    ap.add_argument("sql", nargs="?", help="SQL text to analyze (live "
                    "mode; omit when using --log), the trace "
                    "file/directory for the trace/hotspots commands, "
                    "a profile-*.json file/directory for the "
                    "profile command (spark.rapids.sql.profile.dir "
                    "output), the server port for `top`, the "
                    "BASELINE bench JSON for `bench-diff`, the "
                    "history directory for `history`, or the "
                    "queryId/signature selector for `doctor`")
    ap.add_argument("paths", nargs="*",
                    help="bench-diff: the CANDIDATE bench JSON, or a "
                    "directory holding BENCH_r*.json files (the "
                    "newest round is the candidate)")
    ap.add_argument("--view", action="append", default=[],
                    help="name=path parquet view registrations")
    ap.add_argument("--log", help="offline mode: event-log file or "
                    "directory (spark.rapids.sql.eventLog.dir output)")
    ap.add_argument("--out", default="docs",
                    help="docs: output directory for generated markdown")
    ap.add_argument("--top", type=int, default=10,
                    help="trace: rows per report section")
    ap.add_argument("--conf", action="append", default=[],
                    help="serve: key=value spark.rapids confs")
    ap.add_argument("--host", default=None, help="serve/serve-client: "
                    "bind/connect host (default 127.0.0.1)")
    ap.add_argument("--port", type=int, default=None,
                    help="serve: bind port (0/unset = ephemeral); "
                    "serve-client: server port (required)")
    ap.add_argument("--tenant", default=None,
                    help="serve-client: tenant id for the request "
                    "(default 'default'); history: restrict the "
                    "report to one tenant")
    ap.add_argument("--since", default=None,
                    help="history: only records newer than this — a "
                    "number of seconds ago (e.g. 3600) or an ISO "
                    "timestamp (2026-08-04T12:00)")
    ap.add_argument("--history", default=None,
                    help="doctor/tuning: the query-history directory "
                    "(spark.rapids.sql.telemetry.history.dir)")
    ap.add_argument("--signature", default=None,
                    help="history: restrict the report to one "
                    "signature digest (full 40-hex or a prefix)")
    ap.add_argument("--all", action="store_true",
                    help="doctor: batch mode — diagnose every "
                    "signature's newest record and rank regressions "
                    "worst-first (--top rows)")
    ap.add_argument("--pin", type=int, default=None, metavar="EPOCH",
                    help="tuning: pin the action (exempt from the "
                    "guardrail's auto-revert)")
    ap.add_argument("--unpin", type=int, default=None, metavar="EPOCH",
                    help="tuning: clear the pin")
    ap.add_argument("--revert", type=int, default=None, metavar="EPOCH",
                    help="tuning: request a rollback — the controller "
                    "honors it at its next tick (or skips the action "
                    "at the next server start)")
    ap.add_argument("--stats", action="store_true",
                    help="serve-client: print server stats instead of "
                    "running SQL")
    ap.add_argument("--json", action="store_true",
                    help="lint: machine-readable JSON output "
                    "(same as --format=json)")
    ap.add_argument("--format", default=None, dest="lint_format",
                    choices=["human", "json", "github"],
                    help="lint: output format; `github` emits "
                    "workflow-command annotations (::error ...) for "
                    "inline PR comments in Actions")
    ap.add_argument("--changed-only", nargs="?", const="HEAD",
                    default=None, metavar="BASE",
                    help="lint: restrict findings to files in `git "
                    "diff --name-only BASE` (default HEAD) plus "
                    "untracked files — the incremental pre-commit "
                    "mode; the analysis still covers the whole "
                    "package so cross-module rules stay sound")
    ap.add_argument("--time-budget", type=float, default=None,
                    help="lint: fail (exit 2) when the analysis wall "
                    "exceeds this many seconds (default: "
                    "time_budget_s in tpu-lint.json, 60s)")
    ap.add_argument("--fix-baseline", action="store_true",
                    help="lint: capture current findings into the "
                    "baseline file as accepted debt (stale entries "
                    "are pruned)")
    ap.add_argument("--root", default=None,
                    help="lint: repo root to analyze (default: the "
                    "installed package's parent directory)")
    ap.add_argument("--metrics-port", type=int, default=None,
                    help="serve: also serve GET /metrics (Prometheus "
                    "text) over HTTP on this port (0 = ephemeral)")
    ap.add_argument("--interval", type=float, default=2.0,
                    help="top: seconds between stats polls")
    ap.add_argument("--iterations", type=int, default=0,
                    help="top: frames to render before exiting "
                    "(0 = until interrupted)")
    ap.add_argument("--once", action="store_true",
                    help="top: render exactly one frame and exit "
                    "(scripting mode)")
    ap.add_argument("--rounds", type=int, default=5,
                    help="soak: chaos rounds (fault schedules rotate "
                    "per round)")
    ap.add_argument("--concurrency", type=int, default=8,
                    help="soak: concurrent tenants")
    ap.add_argument("--queries", type=int, default=3,
                    help="soak: queries per tenant per round")
    ap.add_argument("--seed", type=int, default=7,
                    help="soak: deterministic action/schedule seed")
    ap.add_argument("--data", default=None,
                    help="soak: existing data directory (default: "
                    "generate into a temp dir)")
    ap.add_argument("--threshold", type=float, default=None,
                    help="bench-diff: relative regression threshold "
                    "for gating checks (default 0.10)")
    # intermixed: `serve-client --port N "SELECT ..."` must parse (the
    # plain parser cannot allocate a positional after optionals)
    args = ap.parse_intermixed_args(argv)

    if args.command == "lint":
        # exit contract (docs/linting.md): 0 clean / 1 findings /
        # 2 internal error
        from spark_rapids_tpu.lint import run_cli
        return run_cli(root=args.root, as_json=args.json,
                       fix_baseline=args.fix_baseline,
                       fmt=args.lint_format,
                       changed_only=args.changed_only,
                       time_budget=args.time_budget)

    if args.command == "serve":
        return _serve_main(args)
    if args.command == "serve-client":
        return _serve_client_main(args, ap)

    if args.command == "top":
        from spark_rapids_tpu.telemetry.top import run_top
        target = args.sql or (str(args.port) if args.port else None)
        if not target:
            ap.error("top requires the server port (or host:port)")
        host, _, port_s = target.rpartition(":")
        try:
            port = int(port_s)
        except ValueError:
            ap.error(f"top: not a port: {target!r}")
        return run_top(port, host=host or args.host or "127.0.0.1",
                       interval=args.interval,
                       iterations=args.iterations, once=args.once)

    if args.command == "bench-diff":
        return _bench_diff_main(args, ap)

    if args.command == "history":
        return _history_main(args, ap)
    if args.command == "doctor":
        return _doctor_main(args, ap)
    if args.command == "tuning":
        return _tuning_main(args, ap)

    if args.command == "soak":
        # chaos soak harness (docs/serving.md "Query lifecycle"):
        # exit 0 when every round completed with zero hangs, diverged
        # survivors, or post-drain leaks; 1 otherwise
        import json as _json

        from spark_rapids_tpu.soak import run_soak
        report = run_soak(rounds=args.rounds,
                          concurrency=args.concurrency,
                          queries_per_tenant=args.queries,
                          seed=args.seed, data_dir=args.data)
        print(_json.dumps(report, indent=2, default=str))
        return 0 if report["ok"] else 1

    if args.command == "profile":
        # offline renderer: a path argument means "render the written
        # profile artifacts" (spark.rapids.sql.profile.dir output);
        # SQL text keeps the live run-and-profile behavior below
        import os
        # an argument that LOOKS like a path but does not exist must
        # error like the trace command does, not fall through and run
        # "/tmp/.../profile-1.json" as SQL text
        looks_like_path = bool(args.sql) and (
            os.path.exists(args.sql) or args.sql.endswith(".json")
            or (os.sep in args.sql and " " not in args.sql))
        if looks_like_path and not os.path.exists(args.sql):
            print(f"no such profile file or directory: {args.sql}")
            return 1
        path = args.sql if looks_like_path else None
        if path is not None:
            from spark_rapids_tpu.profile import (format_profile,
                                                  read_profiles)
            n = 0
            for prof in read_profiles(path):
                if n:
                    print()
                print(format_profile(prof, top=args.top))
                n += 1
            if not n:
                print(f"no profile-*.json files in {path}")
                return 1
            return 0

    if args.command in ("trace", "hotspots"):
        import os
        path = args.sql or args.log
        if not path:
            ap.error("provide a trace file or directory "
                     "(spark.rapids.sql.trace.dir output)")
        # a path that does not exist is an ERROR (clean message, exit
        # 1, never a stack trace); an existing-but-empty trace dir is
        # a normal answer ("no spans found", exit 0) — an untraced or
        # idle-ring deployment must not fail automation that tails it
        if not os.path.exists(path):
            print(f"no such trace file or directory: {path}")
            return 1
        if os.path.isdir(path):
            files = sorted(
                os.path.join(path, f) for f in os.listdir(path)
                if f.startswith("trace-") and f.endswith(".json"))
            if not files:
                print(f"no spans found (no trace-*.json files in "
                      f"{path})")
                return 0
        else:
            files = [path]
        try:
            if args.command == "hotspots":
                print(hotspots_report(files, top=args.top))
                return 0
            for i, fp in enumerate(files):
                if i:
                    print()
                print(format_trace_report(fp, top=args.top))
        except (ValueError, KeyError) as e:  # incl. JSONDecodeError
            print(f"not a readable Chrome-trace file: {e}")
            return 1
        return 0

    if args.command == "docs":
        import os

        import spark_rapids_tpu.profile  # noqa: F401 - registers the
        #   spark.rapids.sql.profile.* conf entries before generate_docs
        import spark_rapids_tpu.trace  # noqa: F401 - registers the
        #   spark.rapids.sql.trace.* conf entries before generate_docs
        from spark_rapids_tpu.conf import generate_docs
        os.makedirs(args.out, exist_ok=True)
        with open(os.path.join(args.out, "configs.md"), "w") as f:
            f.write(generate_docs())
        with open(os.path.join(args.out, "supported_ops.md"), "w") as f:
            f.write(generate_supported_ops())
        with open(os.path.join(args.out, "observability.md"), "w") as f:
            f.write(generate_observability_docs())
        with open(os.path.join(args.out, "tuning.md"), "w") as f:
            f.write(generate_tuning_docs())
        print(f"wrote {args.out}/configs.md, {args.out}/supported_ops.md, "
              f"{args.out}/observability.md and {args.out}/tuning.md")
        return 0

    if args.log:
        print(qualify_log(args.log) if args.command == "qualify"
              else profile_log(args.log))
        return 0
    if not args.sql:
        ap.error("provide SQL text or --log <path>")

    from spark_rapids_tpu.sql.session import TpuSparkSession
    spark = TpuSparkSession({"spark.rapids.sql.enabled": "true"})
    try:
        for v in args.view:
            name, _, path = v.partition("=")
            spark.read.parquet(path).createOrReplaceTempView(name)
        df = spark.sql(args.sql)
        if args.command == "qualify":
            print(qualify(spark, df).format())
        else:
            print(profile(spark, df).format())
    finally:
        spark.stop()
    return 0




def _parse_since(raw, ap) -> float:
    """`--since` value -> unix-seconds lower bound: a number means
    that many seconds ago, anything else must parse as an ISO
    timestamp."""
    import datetime
    import time as _t
    try:
        return _t.time() - float(raw)
    except (TypeError, ValueError):
        pass
    try:
        return datetime.datetime.fromisoformat(str(raw)).timestamp()
    except ValueError:
        ap.error(f"--since: not seconds-ago or an ISO timestamp: "
                 f"{raw!r}")


def _history_main(args, ap) -> int:
    """`tools history <dir>`: per-signature/per-tenant table over the
    persistent query-history store, with trends
    (docs/observability.md 'Query history'). Exit 0 on a rendered
    report (an EMPTY store is a normal answer), 1 on a missing
    path."""
    import json as _json
    import os

    from spark_rapids_tpu.telemetry.history import (format_history,
                                                    read_records,
                                                    signature_aggregates)
    path = args.sql or args.history
    if not path:
        ap.error("history requires the history directory "
                 "(spark.rapids.sql.telemetry.history.dir output)")
    if not os.path.exists(path):
        print(f"no such history file or directory: {path}")
        return 1
    since = _parse_since(args.since, ap) if args.since else None
    sig = getattr(args, "signature", None)
    if sig and len(sig) == 40:
        # full digest: push the filter into the reader
        records = read_records(path, since=since, tenant=args.tenant,
                               signature=sig)
    else:
        records = read_records(path, since=since, tenant=args.tenant)
        if sig:
            # display prefix (tools print 12-hex): prefix-match here
            records = [r for r in records
                       if str(r.get("signature", "")).startswith(sig)]
    if args.json:
        print(_json.dumps({
            "records": len(records),
            "signatures": signature_aggregates(records),
        }, indent=2, default=str))
        return 0
    print(format_history(records, top=max(args.top, 10)))
    return 0


def _doctor_main(args, ap) -> int:
    """`tools doctor <queryId|signature> --history <dir>`: automated
    slow-query diagnosis against the signature's historical baseline
    (docs/observability.md 'tools doctor'). Exit 0 with a verdict, 1
    when the selector or the directory does not resolve."""
    import json as _json
    import os

    from spark_rapids_tpu.telemetry.doctor import (diagnose,
                                                   format_diagnosis)
    if not args.sql and not args.all:
        ap.error("doctor requires a queryId or signature selector "
                 "(or --all for the batch scan)")
    if not args.history:
        ap.error("doctor requires --history <dir> "
                 "(spark.rapids.sql.telemetry.history.dir output)")
    if not os.path.exists(args.history):
        print(f"no such history file or directory: {args.history}")
        return 1
    if args.all:
        # batch mode: every signature's newest record diagnosed
        # against its own baseline, worst regression first
        from spark_rapids_tpu.telemetry.doctor import (format_scan,
                                                       scan_signatures)
        scans = scan_signatures(args.history, top=max(args.top, 1))
        print(_json.dumps(scans, indent=2, default=str) if args.json
              else format_scan(scans))
        return 0
    d = diagnose(args.history, args.sql)
    print(_json.dumps(d, indent=2, default=str) if args.json
          else format_diagnosis(d))
    return 1 if d.get("error") else 0


def _tuning_main(args, ap) -> int:
    """`tools tuning --history <dir>`: inspect the TuningController's
    action ledger; --pin/--unpin/--revert write control flags into the
    state file, which the controller honors at its next tick (or at
    the next server start) — the CLI never races the live server's
    knob writes (docs/tuning.md). Exit 0 on a rendered report, 1 when
    the directory or the epoch does not resolve."""
    import json as _json
    import os

    from spark_rapids_tpu.telemetry.tuning import (format_tuning,
                                                   load_state,
                                                   save_state)
    path = args.sql or args.history
    if not path:
        ap.error("tuning requires the history directory "
                 "(spark.rapids.sql.telemetry.history.dir output)")
    if not os.path.isdir(path):
        print(f"no such history directory: {path}")
        return 1
    state = load_state(path)
    edits = [(args.pin, "pinned", True), (args.unpin, "pinned", False),
             (args.revert, "revertRequested", True)]
    for epoch, field, value in edits:
        if epoch is None:
            continue
        hit = next((a for a in state.get("actions", [])
                    if int(a.get("epoch", -1)) == epoch), None)
        if hit is None:
            print(f"no tuning action with epoch {epoch}")
            return 1
        hit[field] = value
        save_state(path, state)
        print(f"epoch {epoch}: {field} = {value}")
    if args.json:
        print(_json.dumps(state, indent=2, default=str))
        return 0
    print(format_tuning(state))
    return 0


def _bench_diff_main(args, ap) -> int:
    """`tools bench-diff <a> <b|dir>`: exit 0 when no gating check
    regressed, 1 on regression, 2 on unusable inputs
    (docs/observability.md 'Live telemetry')."""
    import json as _json
    import os

    from spark_rapids_tpu.telemetry.bench_diff import (
        DEFAULT_THRESHOLD, bench_diff, format_diff, latest_bench_file)
    if not args.sql or not args.paths:
        ap.error("bench-diff requires <baseline.json> "
                 "<candidate.json | dir>")
    a, b = args.sql, args.paths[0]
    if os.path.isdir(b):
        picked = latest_bench_file(b, exclude=a)
        if picked is None:
            print(f"no BENCH_r*.json files in {b}")
            return 2
        b = picked
    for p in (a, b):
        if not os.path.exists(p):
            print(f"no such bench file: {p}")
            return 2
    try:
        report = bench_diff(
            a, b, threshold=(args.threshold if args.threshold is not None
                             else DEFAULT_THRESHOLD))
    except ValueError as e:
        print(f"bench-diff: {e}")
        return 2
    print(_json.dumps(report, indent=2) if args.json
          else format_diff(report))
    return 1 if report["verdict"] == "regression" else 0


def _serve_main(args) -> int:
    """`tools serve`: run the query server until interrupted
    (docs/serving.md). Views from --view name=path, confs from
    --conf key=value; --metrics-port adds the Prometheus HTTP twin."""
    import json as _json
    import signal
    import threading

    from spark_rapids_tpu.serve import QueryServer
    conf = {"spark.rapids.sql.enabled": "true"}
    for kv in args.conf:
        k, _, v = kv.partition("=")
        conf[k.strip()] = v.strip()
    srv = QueryServer(conf, host=args.host, port=args.port)
    srv.start()
    metrics_port = None
    if args.metrics_port is not None:
        metrics_port = srv.start_metrics_http(args.metrics_port)
    for v in args.view:
        name, _, path = v.partition("=")
        srv.register_view(name, path)
    print(_json.dumps({"event": "serving", "host": srv.host,
                       "port": srv.port,
                       "metricsPort": metrics_port,
                       "views": sorted(v.partition("=")[0]
                                       for v in args.view)}),
          flush=True)
    stop = threading.Event()
    signal.signal(signal.SIGINT, lambda *_: stop.set())
    signal.signal(signal.SIGTERM, lambda *_: stop.set())
    while not stop.is_set() and not srv._stopping.is_set():
        stop.wait(0.2)
    # graceful drain (docs/serving.md "Query lifecycle"): in-flight
    # queries finish inside serve.drainTimeoutMs, stragglers are
    # cooperatively cancelled, the process exits with the store empty
    from spark_rapids_tpu.conf import SERVE_DRAIN_TIMEOUT_MS, TpuConf
    drain_s = max(1.0, int(TpuConf(conf).get(
        SERVE_DRAIN_TIMEOUT_MS)) / 1000.0)
    drained = srv.shutdown(timeout=drain_s)
    print(_json.dumps({"event": "stopped", "drained": drained,
                       **srv.stats()}), flush=True)
    return 0


def _serve_client_main(args, ap) -> int:
    """`tools serve-client`: the client smoke command — one SQL round
    trip (or --stats) against a running server."""
    import json as _json

    from spark_rapids_tpu.serve import ServeClient
    if args.port is None:
        ap.error("serve-client requires --port")
    with ServeClient(args.port, host=args.host or "127.0.0.1",
                     tenant=args.tenant or "default") as c:
        if args.stats:
            print(_json.dumps(c.stats(), indent=2))
            return 0
        if not args.sql:
            ap.error("provide SQL text (or --stats)")
        batch, header = c.sql(args.sql)
        names = [f.name for f in batch.schema.fields]
        print("\t".join(names))
        for row in batch.rows():
            print("\t".join(str(v) for v in row))
        print(_json.dumps({k: header[k] for k in
                           ("rows", "queueWaitMs", "execMs",
                            "planCacheHit") if k in header}))
    return 0


def generate_supported_ops() -> str:
    """docs/supported_ops.md generator (the reference builds the same
    table from its rule registries, SupportedOpsDocs via
    TypeChecks.scala): one row per exec and per expression rule with
    its conf key, type signature, and compatibility notes. Everything
    is derived FROM the live registries, so the doc cannot drift from
    the code."""
    from spark_rapids_tpu import overrides as O
    from spark_rapids_tpu import typesig as TS

    def sig_str(sig) -> str:
        tags = sorted(sig.tags)
        s = ", ".join(tags)
        if "decimal" in sig.tags and sig.max_decimal_precision:
            s += f" (precision <= {sig.max_decimal_precision})"
        return s or "none"

    lines = [
        "# Supported operators and expressions",
        "",
        "Generated from the rule registries "
        "(`python -m spark_rapids_tpu.tools docs`); the per-op conf "
        "keys disable individual replacements, exactly like the "
        "reference's `spark.rapids.sql.exec.*` / "
        "`spark.rapids.sql.expression.*` keys.",
        "",
        "## Execs",
        "",
        "| Exec | Description | Conf key | Supported types |",
        "|---|---|---|---|",
    ]
    for cls, rule in sorted(O._EXEC_RULES.items(),
                            key=lambda kv: kv[1].name):
        lines.append(f"| {rule.name} | {rule.desc} | `{rule.conf_key}` "
                     f"| {sig_str(rule.checks.sig)} |")
    lines += [
        "",
        "## Expressions",
        "",
        "| Expression | Conf key | Output types | Input types | Notes |",
        "|---|---|---|---|---|",
    ]
    for cls, rule in sorted(O._EXPR_RULES.items(),
                            key=lambda kv: kv[1].name):
        note = rule.incompat or ""
        lines.append(
            f"| {rule.name} | `{rule.conf_key}` "
            f"| {sig_str(rule.checks.output)} "
            f"| {sig_str(rule.checks.inputs)} | {note} |")
    lines += [
        "",
        "## Parquet device decode (encoding matrix)",
        "",
        "Device decode is the DEFAULT scan path "
        "(`spark.rapids.sql.format.parquet.deviceDecode.enabled`, on "
        "by default): the scan uploads still-encoded page bytes and "
        "decodes them in one XLA program per batch "
        "(io/device_decode.py + ops/rle.py), pipelined ahead of the "
        "consuming stage (docs/scan.md). Unsupported cells fall back "
        "PER COLUMN to the pyarrow host decode — results are "
        "bit-identical either way, and fallbacks are visible as "
        "`deviceFallbackColumns` / `hostDecodedValues.<ENC>` metrics. "
        "The `PERFILE`/`MULTITHREADED` reader types feed the device "
        "path; `COALESCING` keeps the host decode (its point is the "
        "one-table stitch). Compression is handled on the host: "
        "uncompressed, snappy, zstd, gzip, brotli (lz4 falls back). "
        "Per-encoding enables: `deviceDecode.byteArray.enabled`, "
        "`deviceDecode.delta.enabled`, "
        "`deviceDecode.byteStreamSplit.enabled`.",
        "",
        "| Type | PLAIN | PLAIN_DICTIONARY / RLE_DICTIONARY | "
        "DELTA_BINARY_PACKED / DELTA_LENGTH_BYTE_ARRAY | "
        "BYTE_STREAM_SPLIT | DELTA_BYTE_ARRAY |",
        "|---|---|---|---|---|---|",
        "| BOOLEAN | device (bit-unpack; v2 RLE pages too) | n/a | "
        "n/a | n/a | n/a |",
        "| INT32 (byte/short/int/date/decimal) | device | device | "
        "device (miniblock runs + seg prefix-sum) | device | n/a |",
        "| INT64 (long/timestamp-micros/decimal) | device | device | "
        "device (miniblock runs + seg prefix-sum) | device | n/a |",
        "| INT96 (legacy timestamp) | fallback | fallback | fallback "
        "| fallback | n/a |",
        "| FLOAT | device | device | n/a | device | n/a |",
        "| DOUBLE | device (backends with exact f64 bitcast; TPU "
        "falls back) | same | n/a | same | n/a |",
        "| FIXED_LEN_BYTE_ARRAY (decimal64/decimal128) | device "
        "(big-endian limb build) | device | fallback | fallback "
        "| n/a |",
        "| BYTE_ARRAY (string/binary) | device (offsets = segmented "
        "prefix-sum over lengths, bytes gather) | device (dictionary "
        "gather) | device (DELTA_LENGTH: host decodes lengths, device "
        "builds offsets + gathers bytes) | n/a | fallback |",
        "| nested (LIST/MAP/STRUCT, repeated) | fallback | fallback "
        "| fallback | fallback | fallback |",
    ]
    return "\n".join(lines) + "\n"


def metric_name_constants() -> List[Tuple[str, str]]:
    """Every metric-name constant defined in metrics.py (the drift
    guard's source of truth: a new metric constant MUST appear in the
    generated observability doc or tier-1 fails)."""
    from spark_rapids_tpu import metrics as M
    return sorted(
        (n, v) for n, v in vars(M).items()
        if n.isupper() and not n.startswith("_") and isinstance(v, str))


def generate_observability_docs() -> str:
    """docs/observability.md generator (`python -m spark_rapids_tpu.tools
    docs`): span model, trace configuration, how to open traces in
    Perfetto, how to read the offline reports, and the full metric-name
    reference derived from the LIVE metrics module so the doc cannot
    drift from the code."""
    from spark_rapids_tpu import conf as C
    from spark_rapids_tpu import profile as _profile  # registers confs
    from spark_rapids_tpu import trace as _trace  # registers trace confs

    assert _trace is not None and _profile is not None
    lines = [
        "# Observability: span tracing, metrics, event logs",
        "",
        "Generated by `python -m spark_rapids_tpu.tools docs`.",
        "",
        "## Span model",
        "",
        "With `spark.rapids.sql.trace.enabled` the engine records a",
        "Dapper-style span stream `(query_id, batch_id, chip, thread,",
        "kind, t0, t1, attrs)` at its existing choke points and writes",
        "ONE Chrome-trace JSON file per query (`trace-<pid>-q<n>.json`)",
        "under `spark.rapids.sql.trace.dir`:",
        "",
        "- every `MetricRegistry.timed`/`timed_wall` scope mirrors its",
        "  interval into a span named `<Exec>.<metric>` (reader",
        "  `FileScan.decodeTime`, upload",
        "  `TpuRowToColumnar.copyToDeviceTime` with the target chip,",
        "  exchange `TpuShuffleExchangeExec.partitionTime`, sort/join/",
        "  agg timers, `pipelineDrainTime`, ...) — the trace, the event",
        "  log, and the profiler read the SAME measurement;",
        "- device dispatches are explicit spans with the executing chip:",
        "  `TpuFusedStageExec.dispatch` (stage label, batch sequence) and",
        "  `TpuHashAggregateExec.dispatch` (mode);",
        "- the scan pipeline (docs/scan.md) adds `scanPrefetch` (the",
        "  producer thread's read+pack of one staged batch, mirrored",
        "  into the interval-union `scanPrefetchTime` metric) and",
        "  `uploadAhead` (the async raw-chunk device_put issued ahead",
        "  of the consuming stage, with the target chip);",
        "- JIT compiles are `compile` spans (attr `cache` = which LRU",
        "  missed); a thread that blocks on ANOTHER thread's",
        "  in-progress compile of the same key (single-flight) emits a",
        "  `compileCacheContention` instant and counts in the cache's",
        "  `contention` stat; semaphore waits are `semaphoreWait` spans;",
        "  store",
        "  tier movement is `spillToHost`/`spillToDisk`/",
        "  `promoteFromDisk`/`promoteToDevice`; the ICI exchange adds",
        "  `meshStack`/`meshSizeExchange`/`meshExchange` and",
        "  `exchangeMaterialize`;",
        "- retry machinery emits INSTANT markers (`retryOOM`,",
        "  `splitRetry`, `ioRetry`, `chipFailure`) plus a nested",
        "  `retryBlock` span covering the spill+backoff wall — the same",
        "  interval the `retryBlockTime` metric reads.",
        "",
        "A span that crosses a generator yield can resume on another",
        "thread; the exporter assigns such partially-overlapping spans",
        "to overflow lanes (`<thread>!k`) so every lane's B/E stream is",
        "strictly nested — the schema tests assert this invariant.",
        "",
        "## Configuration",
        "",
        "| Key | Default | Description |",
        "|---|---|---|",
    ]
    for e in sorted(C.registered_entries(), key=lambda e: e.key):
        if e.key.startswith(("spark.rapids.sql.trace.",
                             "spark.rapids.sql.profile.",
                             "spark.rapids.sql.telemetry.")) \
                or e.key == "spark.rapids.sql.explain":
            lines.append(f"| {e.key} | {e.default} | {e.doc} |")
    lines += [
        "",
        "Sampling: with `sampleRate < 1.0` the Nth traced-candidate",
        "query of the process is traced iff the Nth draw of the",
        "`sampleSeed`-seeded stream falls below the rate — a fixed seed",
        "gives a deterministic, reproducible sample (production traces",
        "a stable subset at bounded overhead; the bench measures the",
        "overhead in `detail.trace`).",
        "",
        "## Opening traces in Perfetto",
        "",
        "1. run a query with `spark.rapids.sql.trace.enabled=true`;",
        "2. open https://ui.perfetto.dev (or chrome://tracing) and drag",
        "   the `trace-<pid>-q<n>.json` file in;",
        "3. lanes are the engine's real threads (`srt-task-*` task",
        "   threads, `srt-multifile-*` reader pool, `srt-pack` upload",
        "   stagers); click a span for its attrs (chip, batch, rows,",
        "   path, cache); instant markers show retries/splits.",
        "",
        "## Reading the offline reports",
        "",
        "`python -m spark_rapids_tpu.tools trace <file-or-dir>` prints:",
        "",
        "- **critical path** — backward walk from the last span end:",
        "  at every instant the most-recently-started covering span",
        "  owns the segment, uncovered gaps are idle. Only work ON this",
        "  chain bounds the query wall; optimize it first.",
        "- **exclusive self-time** — per span name, total minus",
        "  directly nested spans (same lane). This undoes the",
        "  documented double counts at the reporting layer: e.g.",
        "  `retryBlock` (spill+backoff) nests inside operator timers,",
        "  so operators' self-time no longer absorbs retry stalls.",
        "- **per-chip occupancy** — busy fraction + top idle gaps per",
        "  chip from chip-attributed spans; mesh skew and a degraded",
        "  chip show up as occupancy imbalance.",
        "- **top slowest spans** and **instant marker counts** (retry",
        "  storms surface here).",
        "",
        "`bench.py` runs a traced q1 leg (`detail.trace`): occupancy,",
        "critical-path breakdown, and measured tracing overhead vs the",
        "untraced wall (the overhead budget is <= 15%, asserted by",
        "tests/test_trace.py on the smoke input).",
        "",
        "## Reading a query profile",
        "",
        "With `spark.rapids.sql.profile.enabled` every executed query",
        "writes ONE artifact (`profile-<pid>-q<n>.json` under",
        "`spark.rapids.sql.profile.dir`) unifying the annotated plan,",
        "the HBM accounting, and the rewrite explain. Render it with",
        "`python -m spark_rapids_tpu.tools profile <file-or-dir>`:",
        "",
        "- **annotated plan tree** — the final physical plan (fused",
        "  stages with their constituents), each node with its full",
        "  metric registry: rows/batches, operator timers, jit-cache",
        "  hits/misses, retry/split/spill counters. A `*` marks device",
        "  operators.",
        "- **top memory consumers** — the owner-attributed HBM ledger:",
        "  every `SpillableBatch` is tagged with the registering",
        "  operator (`TpuExec.register_spillable`), so the store keeps",
        "  live/peak bytes PER OPERATOR next to the pool watermarks.",
        "  The per-op live bytes always sum to the pool's live bytes;",
        "  the pool peak never exceeds the sum of per-op peaks. Spills",
        "  are billed to the owning operator (`spillBytes`), and each",
        "  op's `peakDeviceMemory` metric mirrors its ledger peak.",
        "- **fallback summary** — operator coverage plus the explain",
        "  reasons aggregated by frequency (see below).",
        "",
        "With tracing ALSO enabled, the store emits Chrome-trace",
        "counter events (`deviceStoreBytes`/`hostStoreBytes`), so",
        "Perfetto shows the HBM/host pool occupancy timeline in a",
        "`counters` lane next to the query's spans.",
        "",
        "`bench.py` runs a profiled q1+q3 leg (`detail.profile`):",
        "per-op peak HBM, explain coverage counts, and the measured",
        "profiling overhead vs the clean wall (budget <= 15%).",
        "",
        "## Explain / fallback reasons",
        "",
        "`spark.rapids.sql.explain=NOT_ON_TPU` prints one line per",
        "operator/expression that stayed on CPU:",
        "",
        "    !Exec <CpuProjectExec> cannot run on TPU because",
        "    expression PythonUDF <...> is not supported on TPU",
        "",
        "`ALL` additionally lists `*Exec <...> will run on TPU` for",
        "every placed operator (`NOT_ON_GPU` is accepted as an alias).",
        "Expression-level reasons name the OFFENDING SUBTREE, so a",
        "failure deep inside a projection is attributable without",
        "replaying the rewrite. The same report aggregates per query",
        "into the profile artifact's `explain` section (device ops,",
        "coverage, reason histogram) and the event log's",
        "`fallbackSummary` field; `tools qualify` scores whole",
        "workloads with it.",
        "",
        "## Event log (v2)",
        "",
        "Event lines (`spark.rapids.sql.eventLog.dir`) carry",
        "`version: 2`: per-op metrics now INCLUDE zero values (an op",
        "that saw 0 rows is distinguishable from one whose metric never",
        "existed), plus a compact snapshot of the session's explicit",
        "conf settings and the fault-injector summary when injection is",
        "active; each line also carries the per-query `fallbackSummary`",
        "(coverage + reason histogram) and `memoryByOperator` (the",
        "per-op peak/live HBM ledger). `read_events` still reads v1",
        "lines (version normalized to 1). Queries executed through the",
        "query server additionally carry `tenant` (docs/serving.md) —",
        "the same id appears in the profile artifact and the trace",
        "file's `otherData.tenant`, and admission waits show up as",
        "`serveQueueWait` spans.",
        "",
        "## Live telemetry",
        "",
        "The serving tier's always-on observability layer",
        "(spark_rapids_tpu/telemetry/): file traces and profile",
        "artifacts are opt-in *per query*, but on a long-lived",
        "multi-tenant server the interesting query is the one you",
        "didn't pre-instrument — the p99 outlier, the retry storm, the",
        "tenant whose ledger tripped an over-share spill.",
        "",
        "### Flight recorder (`spark.rapids.sql.trace.mode=ring`)",
        "",
        "The existing Tracer grows a second sink: a fixed-size,",
        "lock-free ring buffer keeping the last",
        "`spark.rapids.sql.trace.ringSpans` spans/instants/counter",
        "samples PER THREAD, always on (query server sessions default",
        "to it), bounded memory, near-zero overhead (the bench's",
        "`detail.telemetry` leg measures the q1 ring-on/off ratio",
        "against a <= 1.05x budget). `telemetry.dump_ring(dir)` — or a",
        "trigger firing — writes the rings as a standard Chrome-trace",
        "file (`trace-ring-<pid>-<n>.json`), so Perfetto,",
        "`tools trace` and `tools hotspots` work unchanged on dumps.",
        "`tools trace`/`tools hotspots` on an empty or span-free trace",
        "directory print `no spans found` and exit 0 (an idle recorder",
        "is a normal answer, not an error); a nonexistent path errors",
        "with exit 1.",
        "",
        "### Triggers and slow-query bundles",
        "",
        "Declarative conditions evaluated where they become true, each",
        "emitting one *bundle* (`bundle-<pid>-<n>-<trigger>.json`",
        "under `spark.rapids.sql.telemetry.dir`) that ties together",
        "the ring dump, the query's profile-artifact path (when",
        "profiling is on), a server stats snapshot (when a QueryServer",
        "is up), the device-store stats, and the triggering condition:",
        "",
        "| Trigger | Condition | Evaluated at |",
        "|---|---|---|",
        "| slowQuery | query wall > telemetry.slowQueryMs | query "
        "close |",
        "| retryCount | per-query retry+split deltas > telemetry."
        "retryCountThreshold | query close |",
        "| kernelFallbacks | per-query kernelFallbacks.* delta > "
        "telemetry.kernelFallbackThreshold | query close |",
        "| retryStorm | > telemetry.retryStormThreshold OOM retries "
        "in a 60 s window | retry time |",
        "| hbmWatermark | store live bytes > telemetry.hbmWatermark x "
        "pool budget | every store transition |",
        "| queueSaturation | admission depth > telemetry."
        "queueWatermark x serve.maxQueued | every enqueue |",
        "| stuckQuery | elapsed wall > serve.watchdogFactor x the "
        "plan-cache signature's observed p99 | the lifecycle "
        "watchdog's periodic scan (docs/serving.md 'Query "
        "lifecycle'; with serve.watchdogCancel the query is also "
        "cancelled) |",
        "| sloBurn | a tenant's observed p99 over the history window "
        "> its serve.slo.p99Ms objective | query close on the server "
        "(see 'SLO tracking' below) |",
        "",
        "Per-trigger rate limiting (`telemetry.triggerMinIntervalS`)",
        "bounds disk pressure under a storm (suppressed firings count",
        "in the engine stats and on the endpoint); bundle IO runs on a",
        "dedicated daemon thread so no query, store or admission path",
        "blocks on a file write. The store/admission/retry triggers",
        "arm when any session sets a `spark.rapids.sql.telemetry.*`",
        "conf. Artifact sprawl is bounded: bundles and ring dumps in",
        "`telemetry.dir` beyond `telemetry.maxBundles` (or",
        "`telemetry.maxBundleBytes` total) are pruned OLDEST-FIRST by",
        "the bundle-worker thread after each write — never under a",
        "hot-path lock; pruned counts show in the engine stats, the",
        "server stats `telemetry` section, and",
        "`srt_telemetry_bundles_pruned_total`.",
        "",
        "### Prometheus endpoint",
        "",
        "The QueryServer's `metrics` protocol verb (alias",
        "`stats-stream`; `ServeClient.metrics()`), and the",
        "`tools serve --metrics-port N` HTTP twin (`GET /metrics`),",
        "export one text exposition per scrape: every registry metric",
        "as `srt_<snake_case>[_seconds]_total` (prefix families like",
        "`kernelFallbacks.groupbyHash` become one family with a",
        "`key` label; `*Time` metrics convert ns to seconds; `peak*`",
        "metrics are gauges folded by MAX across registries, not",
        "summed — a high-watermark, never a sum of dead plans' peaks),",
        "HELP text from `describe_metric` — an",
        "undescribed key is NOT exported and counts in",
        "`srt_undescribed_metric_keys`, which tier-1 asserts is 0.",
        "Scrapes run through a registry-delta aggregator: per-registry",
        "snapshots are cached against metric mutation counters (a",
        "scrape re-reads only registries that changed) and registries",
        "garbage-collected with their plans fold into a retired base,",
        "so counters stay MONOTONE across plan lifetimes. Server-level",
        "families:",
        "",
        "| Family | Type | Help |",
        "|---|---|---|",
    ]
    from spark_rapids_tpu.telemetry.prometheus import SERVER_FAMILY_HELP
    for name, (ftype, help_text) in sorted(SERVER_FAMILY_HELP.items()):
        lines.append(f"| `{name}` | {ftype} | {help_text} |")
    lines += [
        "",
        "`tools top <port>` renders a refreshing terminal table over",
        "the same stats (tenants x QPS / p50 / p99 / queue wait / live",
        "HBM / in-flight / rejections; `--interval`, `--iterations`,",
        "`--once` for scripting). A server that goes away mid-poll is",
        "a clean exit (message + code 0); a failed initial connect",
        "exits 1.",
        "",
        "### Query history",
        "",
        "`spark.rapids.sql.telemetry.history.dir` turns on the",
        "persistent query-history store: ONE compact JSONL record per",
        "finished query, appended at query close by",
        "`session.execute_plan` (every terminal status it sees) and by",
        "the query server (outcomes the session never starts, e.g.",
        "cancelled while queued). Storage is crash-safe and bounded:",
        "records are single JSON lines in rotated segments",
        "(`history-<ms>-<pid>-<seq>.jsonl`), compacted",
        "whole-segment-at-a-time by `telemetry.history.maxBytes` and",
        "`telemetry.history.maxAgeDays` (a torn tail line from a crash",
        "is skipped by the reader, never propagated). The record",
        "schema (`HISTORY_FIELD_CATALOG`; the tpu-lint `history-field`",
        "rule pins record construction to it):",
        "",
        "| Field | Meaning |",
        "|---|---|",
    ]
    from spark_rapids_tpu.telemetry.history import HISTORY_FIELD_CATALOG
    for fname, fdesc in sorted(HISTORY_FIELD_CATALOG.items()):
        lines.append(f"| `{fname}` | {fdesc} |")
    lines += [
        "",
        "**Warm-start** (`telemetry.history.warmStart`, on by default",
        "when the dir is set): at server start the history replays",
        "into the lifecycle layer — finished records seed the",
        "stuck-query watchdog's per-signature p99 reservoirs and clear",
        "failure streaks, failed records replay the quarantine",
        "streaks — so a restarted server can tell \"stuck\" from",
        "\"first time\" from query one, and a poison signature stays",
        "fail-fast across restarts. Cancelled/timed-out/quarantined",
        "records never count, the same rules as the live paths.",
        "",
        "### SLO tracking",
        "",
        "`spark.rapids.sql.serve.slo.p99Ms` (per-tenant override",
        "`serve.slo.p99Ms.<tenant>`) sets a latency objective: the",
        "tenant's observed p99 wall over the last `serve.slo.window`",
        "seconds of query history must stay under it. The server",
        "evaluates objectives over the history store (cached ~1 s),",
        "exposes them in its stats (`slo` section) and as the",
        "`srt_slo_*` Prometheus families (objective, observed p99,",
        "window queries, violations, burn ratio — gauges, because the",
        "window slides), and fires a rate-limited `sloBurn` bundle",
        "through the trigger engine when the observed p99 exceeds the",
        "objective.",
        "",
        "### `tools history`",
        "",
        "`tools history <dir> [--since N|ISO] [--tenant T]",
        "[--signature D] [--json]` renders the store as a",
        "per-signature table (count, wall p50/p99, trend slope in",
        "seconds-of-wall per hour-of-history, retry/fallback rates,",
        "status histogram, tenants) plus a per-tenant rollup.",
        "`--signature` restricts the report to one signature digest —",
        "the full 40-hex form is pushed into the reader's",
        "`read_records(signature=)` filter, a shorter prefix (the",
        "12-hex display form the tools print) matches by prefix. An",
        "empty store is a normal answer (exit 0); a missing path",
        "exits 1.",
        "",
        "### `tools doctor`",
        "",
        "`tools doctor <queryId|signature> --history <dir> [--json]`",
        "answers \"why was this query slow\" automatically: it joins",
        "the query's history record, profile artifact, and trace",
        "against the signature's historical baseline (the other",
        "finished records of the same shape), diffs per-stage",
        "self-times stage by stage (profile time metrics aggregated by",
        "stage key — `retryBlockTime` -> `retryBlock`), and emits a",
        "ranked verdict with evidence lines. `tools doctor --all",
        "--history <dir> [--top N]` is the batch mode: every",
        "signature's NEWEST finished record is diagnosed against its",
        "own baseline in one store read, ranked regressed-first then",
        "by slowdown — the triage view after a bad deploy (the",
        "TuningController's scan tick runs the same walk,",
        "docs/tuning.md). The verdict taxonomy:",
        "",
        "| Verdict | Meaning |",
        "|---|---|",
    ]
    from spark_rapids_tpu.telemetry.doctor import VERDICT_CLASSES
    for vname, vdesc in sorted(VERDICT_CLASSES.items()):
        lines.append(f"| `{vname}` | {vdesc} |")
    lines += [
        "",
        "### Regression tracking (`tools bench-diff`)",
        "",
        "`tools bench-diff <baseline.json> <candidate.json|dir>` diffs",
        "two bench outputs — headline rows/s, device walls, decode",
        "overlap, kernel A/B, serving QPS, tracing/profiling/ring",
        "overheads — against a relative `--threshold` (default 10%),",
        "prints a verdict table (`--json` for machines), and exits 1",
        "when a gating check regressed; bench.py runs it against the",
        "previous BENCH_r0*.json every round (`detail.telemetry.",
        "benchDiff`). Informational checks (CPU-engine wall, retry",
        "counters, the `detail.tuning.*` feedback-control legs) report",
        "but never gate.",
        "",
        "### Self-tuning (`tools tuning`)",
        "",
        "`spark.rapids.sql.serve.tuning.enabled` closes the",
        "observe-diagnose-act loop: the server embeds a",
        "TuningController that scores the query history through the",
        "aggregate + doctor pipeline at start and on a periodic tick",
        "and applies bounded, logged, reversible actions from the",
        "declared ACTION_CATALOG — see docs/tuning.md for the action",
        "table, the guardrail/rollback state machine, and the",
        "pin/revert workflow. Every action lands in the history store",
        "as a `tuning` record (rollbacks as `revert`); both statuses",
        "are control-plane records EXCLUDED from signature aggregates,",
        "SLO windows, doctor baselines, and warm-start replay, so the",
        "controller's own audit trail never moves the statistics it",
        "steers by. Controller state exports as the `srt_tuning_*`",
        "families above; `tools tuning --history <dir>` renders the",
        "action ledger, `--pin/--unpin/--revert <epoch>` write control",
        "flags the controller honors at its next tick.",
        "",
        "### Span catalog",
        "",
        "Every explicit span/instant kind the engine records (the",
        "tpu-lint `span-kind` rule pins literal recording sites to",
        "these tables; metric-mirror spans are the dynamic",
        "`<Exec>.<metric>` family covered by `metric-key`):",
        "",
        "| Span kind | Meaning |",
        "|---|---|",
    ]
    from spark_rapids_tpu.trace import INSTANT_CATALOG, SPAN_CATALOG
    for kind, desc in sorted(SPAN_CATALOG.items()):
        lines.append(f"| `{kind}` | {desc} |")
    lines += ["", "| Instant kind | Meaning |", "|---|---|"]
    for kind, desc in sorted(INSTANT_CATALOG.items()):
        lines.append(f"| `{kind}` | {desc} |")
    lines += [
        "",
        "## Metric-name reference",
        "",
        "Derived from the central description table",
        "(`spark_rapids_tpu.metrics.METRIC_DESCRIPTIONS`); tier-1",
        "asserts every metric-name constant appears here AND that every",
        "metric a `Tpu*Exec` registers at runtime resolves in the table",
        "(the \"new metric, stale docs\" drift guard, now a lint over",
        "the live registries).",
        "",
        "| Metric key | Description |",
        "|---|---|",
    ]
    from spark_rapids_tpu.metrics import (METRIC_DESCRIPTIONS,
                                          METRIC_PREFIX_DESCRIPTIONS)
    for name, desc in sorted(METRIC_DESCRIPTIONS.items()):
        lines.append(f"| `{name}` | {desc} |")
    for prefix, desc in sorted(METRIC_PREFIX_DESCRIPTIONS.items()):
        lines.append(f"| `{prefix}*` | {desc} |")
    # the constants table keeps the original drift guard anchored: a
    # new metrics.py constant must surface here (and therefore in
    # METRIC_DESCRIPTIONS, which the lint test cross-checks)
    lines += ["", "| Constant | Metric key |", "|---|---|"]
    for const, name in metric_name_constants():
        lines.append(f"| {const} | `{name}` |")
    return "\n".join(lines) + "\n"


def generate_tuning_docs() -> str:
    """docs/tuning.md generator (`python -m spark_rapids_tpu.tools
    docs`): the feedback-control loop, the action catalog rendered
    LIVE from ACTION_CATALOG (so docs cannot drift from the declared
    vocabulary), the guardrail state machine, and the operator
    pin/revert workflow."""
    from spark_rapids_tpu import conf as C
    from spark_rapids_tpu.telemetry.tuning import ACTION_CATALOG
    lines = [
        "# Self-tuning: history-driven feedback control",
        "",
        "Generated by `python -m spark_rapids_tpu.tools docs`.",
        "",
        "`spark.rapids.sql.serve.tuning.enabled` (requires",
        "`spark.rapids.sql.telemetry.history.dir`) embeds a",
        "**TuningController** in the query server. At server start and",
        "every `serve.tuning.intervalS` seconds it scores the",
        "persistent query history through the `signature_aggregates` +",
        "doctor-verdict pipeline (the same walk `tools doctor --all`",
        "runs) and applies per-signature actions from the declared",
        "catalog below. Tuning never changes what a query COMPUTES —",
        "only admission shaping, cache residency, and kernel-tier",
        "routing, all bit-identity-preserving by their own contracts",
        "(tier-1 asserts results are identical with tuning on vs",
        "off).",
        "",
        "Every action is:",
        "",
        "- **bounded** — per-knob min/max clamps declared in the",
        "  catalog; at most `serve.tuning.maxActionsPerTick` new",
        "  actions per tick;",
        "- **logged** — a `tuning` history record (action, scope,",
        "  knob, old->new value, evidence, epoch) in the same store as",
        "  query records; rollbacks log a `revert` record. Both are",
        "  control-plane statuses EXCLUDED from aggregates, SLO",
        "  windows, doctor baselines, and warm-start replay;",
        "- **exported** — the `srt_tuning_*` Prometheus families",
        "  (ticks, actions by name, reverts, active/pinned counts,",
        "  pre-warmed signatures);",
        "- **inspectable and reversible** — `tools tuning` below;",
        "- **guarded** — the post-action baseline is watched and the",
        "  action auto-reverts on regression (state machine below).",
        "",
        "## Action catalog",
        "",
        "Rendered from `telemetry.tuning.ACTION_CATALOG` — the",
        "tpu-lint `tuning-action` rule pins every action the",
        "controller constructs to this table, and every",
        "`spark.rapids.*` knob in it to a registered conf key.",
        "Internal knobs (`signatureConcurrency`, `tenantWeight`,",
        "`prewarm`) actuate the admission controller and the pre-warm",
        "ledger directly.",
        "",
        "| Action | Trigger verdict | Knob | Bounds | What it does |",
        "|---|---|---|---|---|",
    ]
    for name, cat in sorted(ACTION_CATALOG.items()):
        knobs = cat.get("knobs", [cat["knob"]])
        knob_s = " / ".join(f"`{k}`" for k in knobs)
        lines.append(
            f"| `{name}` | {cat['verdict']} | {knob_s} | "
            f"[{cat['min']}, {cat['max']}] | {cat['doc']} |")
    lines += [
        "",
        "## Guardrail / rollback state machine",
        "",
        "Each applied action captures the pre-action p50/p99 baseline",
        "of its scope (a signature digest, or `tenant:<id>`) in its",
        "evidence. States:",
        "",
        "```",
        "            apply                      window fills, no",
        " (decided) -------> applied ---------> regression: accepted",
        "                      |  \\",
        "                      |   \\ tools tuning --revert",
        "                      |    \\ (honored at next tick)",
        "   guardrail:         |     v",
        "   p50/p99 regressed  +--> reverted  (a `revert` record",
        "   past threshold            logs old value restored)",
        "```",
        "",
        "- once `serve.tuning.guardWindowQueries` post-action",
        "  finished records exist for the scope (cache-served and",
        "  control-plane records excluded), the controller computes",
        "  `change = (baseline - observed) / baseline` for p50 and",
        "  p99 — the same relative-change discipline `tools",
        "  bench-diff` gates on;",
        "- `change < -serve.tuning.revertThreshold` on either",
        "  percentile auto-reverts: the knob's old value is restored",
        "  and a `revert` record lands with the observed window as",
        "  evidence;",
        "- otherwise the action graduates to **accepted** (still",
        "  manually revertible);",
        "- **pinned** actions are exempt from auto-revert;",
        "- `kernelFallback` is accepted at birth: the conf flip",
        "  changes the plan signature (kernel.*.enabled is",
        "  signature-relevant), so the new shape RE-BASELINES under",
        "  its own history and the old scope's window can never fill",
        "  — manual revert only.",
        "",
        "Applied/accepted actions persist in",
        "`<history.dir>/tuning-state.json` and re-actuate at the next",
        "server start: a retry-storm shape admitted narrowly today is",
        "admitted narrowly tomorrow, and the pre-warm ledger's",
        "recorded SQL replays through the planning path before the",
        "first client request.",
        "",
        "## Fault injection (`site:tuning:N`)",
        "",
        "`spark.rapids.sql.test.injectOOM=site:tuning:N` makes the Nth",
        "controller tick apply a deliberately HARMFUL synthetic action",
        "(a concurrency clamp recorded against an epsilon baseline),",
        "so the observe-and-revert loop is deterministically testable",
        "end to end — the injected action must auto-revert within the",
        "guard window, visible in `tools tuning`, the history store,",
        "and the `srt_tuning_*` families.",
        "",
        "## Operator workflow (`tools tuning`)",
        "",
        "```",
        "tools tuning --history <dir>            # the action ledger",
        "tools tuning --history <dir> --json     # machine-readable",
        "tools tuning --history <dir> --pin 7    # exempt from revert",
        "tools tuning --history <dir> --unpin 7",
        "tools tuning --history <dir> --revert 7 # request rollback",
        "```",
        "",
        "Pin/revert write control flags into the STATE FILE, not the",
        "live server: the controller merges them at its next tick (a",
        "revert request on a stopped server simply skips the action at",
        "the next start), so the CLI never races the controller's own",
        "knob writes.",
        "",
        "## Configuration",
        "",
        "| Key | Default | Description |",
        "|---|---|---|",
    ]
    for e in sorted(C.registered_entries(), key=lambda e: e.key):
        if e.key.startswith("spark.rapids.sql.serve.tuning."):
            lines.append(f"| {e.key} | {e.default} | {e.doc} |")
    return "\n".join(lines) + "\n"


if __name__ == "__main__":
    import sys
    raise SystemExit(_main(sys.argv[1:]))
