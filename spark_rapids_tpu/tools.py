"""Qualification + profiling tools (the reference's `tools` module:
qualification — "how much of this workload would accelerate" — and
profiling — per-operator metrics after a run; user-facing-tools/
spark-qualification-tool.md is the shape being mirrored).

API:
  qualify(session, df)       -> QualificationReport
  qualify_sql(session, sql)  -> QualificationReport
  profile(session, df)       -> ProfileReport (runs the query)

CLI:
  python -m spark_rapids_tpu.tools qualify "SELECT ..." --view name=path
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Tuple


@dataclass
class QualificationReport:
    """Per-operator device placement + fallback reasons."""

    device_ops: List[str] = field(default_factory=list)
    cpu_ops: List[Tuple[str, List[str]]] = field(default_factory=list)
    plan_string: str = ""

    @property
    def op_coverage(self) -> float:
        total = len(self.device_ops) + len(self.cpu_ops)
        return (len(self.device_ops) / total) if total else 1.0

    def format(self) -> str:
        lines = ["=== TPU Qualification Report ===",
                 f"operator coverage: {self.op_coverage:.0%} "
                 f"({len(self.device_ops)} on TPU, "
                 f"{len(self.cpu_ops)} on CPU)", ""]
        if self.device_ops:
            lines.append("runs on TPU:")
            lines += [f"  + {o}" for o in self.device_ops]
        if self.cpu_ops:
            lines.append("stays on CPU:")
            for name, reasons in self.cpu_ops:
                lines.append(f"  - {name}")
                lines += [f"      because {r}" for r in reasons]
        lines += ["", "physical plan:", self.plan_string]
        return "\n".join(lines)


def qualify(session, df) -> QualificationReport:
    """Rewrite the plan (without executing) and report placement —
    the qualification tool's core signal."""
    from spark_rapids_tpu.exec.base import TpuExec
    physical = session.plan_physical(df.plan)
    report = QualificationReport(
        plan_string=session.explain_string(df.plan, physical=physical))
    rewrite = session.last_rewrite_report
    if rewrite is not None:
        for name, reasons in rewrite.fallbacks:
            report.cpu_ops.append((name, list(reasons)))

    def walk(p):
        if isinstance(p, TpuExec):
            report.device_ops.append(p.simple_string().split()[0])
        for c in p.children:
            walk(c)
    walk(physical)
    return report


def qualify_sql(session, sql: str) -> QualificationReport:
    return qualify(session, session.sql(sql))


@dataclass
class ProfileReport:
    """Executed-query metrics per operator (profiling tool)."""

    rows: int = 0
    operators: List[Tuple[str, Dict[str, int]]] = field(
        default_factory=list)

    def format(self) -> str:
        lines = ["=== TPU Profile Report ===", f"output rows: {self.rows}"]
        for name, metrics in self.operators:
            lines.append(f"  {name}")
            for k, v in sorted(metrics.items()):
                lines.append(f"      {k}: {v}")
        return "\n".join(lines)


def profile(session, df) -> ProfileReport:
    """Execute the query and collect every device operator's metric
    registry (the write-only metrics VERDICT round 1 flagged — this is
    where they surface)."""
    from spark_rapids_tpu.exec.base import TpuExec
    physical = session.plan_physical(df.plan)
    result = physical.execute_collect()
    out = ProfileReport(rows=result.num_rows)

    def walk(p):
        if isinstance(p, TpuExec):
            vals = {name: m.value
                    for name, m in p.metrics.metrics.items() if m.value}
            out.operators.append((p.simple_string().split()[0], vals))
        for c in p.children:
            walk(c)
    walk(physical)
    return out


def _main(argv: List[str]) -> int:
    import argparse

    from spark_rapids_tpu.sql.session import TpuSparkSession

    ap = argparse.ArgumentParser(
        prog="spark_rapids_tpu.tools",
        description="TPU qualification/profiling tools")
    ap.add_argument("command", choices=["qualify", "profile"])
    ap.add_argument("sql", help="SQL text to analyze")
    ap.add_argument("--view", action="append", default=[],
                    help="name=path parquet view registrations")
    args = ap.parse_args(argv)

    spark = TpuSparkSession({"spark.rapids.sql.enabled": "true"})
    try:
        for v in args.view:
            name, _, path = v.partition("=")
            spark.read.parquet(path).createOrReplaceTempView(name)
        df = spark.sql(args.sql)
        if args.command == "qualify":
            print(qualify(spark, df).format())
        else:
            print(profile(spark, df).format())
    finally:
        spark.stop()
    return 0


if __name__ == "__main__":
    import sys
    raise SystemExit(_main(sys.argv[1:]))
